"""Slot-based continuous-batching serving engine — the `update_slots` analog.

Reference: llama.cpp's server loop (task queue + slots, wired to gRPC at
/root/reference/backend/cpp/llama-cpp/grpc-server.cpp:69-97; stream path
:571-995) and the MLX backend's stream_generate
(/root/reference/backend/python/mlx/backend.py:193-231).

TPU-first design — everything the XLA compiler sees is fixed-shape:
- ONE decode computation over the full slot array [B] every step, compiled
  once; inactive slots compute masked garbage (cheaper than recompiling).
- prompt prefill is padded to a small set of length buckets (one compile per
  bucket, reused forever).
- per-slot sampler knobs are device arrays (ops/sampling.SamplerState), so any
  mix of temperatures/top-k/penalties shares the same compiled step.
- KV caches + sampler state are DONATED through the jitted step: no
  per-token reallocation, the cache lives in HBM across the whole session.
- host↔device traffic per step is [B] tokens + [B] logprobs out and [B]
  bools in — a few hundred bytes.

The host side owns: admission queue, stop sequences (with holdback so a
half-matched stop string is never emitted), EOS/max-token termination,
incremental UTF-8-safe detokenization, per-request output queues, and
tokens/sec + TTFT metrics (GetMetrics parity —
/root/reference/backend/backend.proto:40-46).
"""
from __future__ import annotations

import contextlib
import dataclasses
import queue
import os
import threading
import time
from functools import partial
from typing import Any, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.models.llama import (
    LlamaConfig,
    cache_shift,
    decode_step,
    extend,
    init_kv_cache,
    prefill,
)
from localai_tpu.ops.rope import rope_table
from localai_tpu.ops.sampling import (
    SamplerState,
    SamplingParams,
    sample,
    sampler_row,
)
from localai_tpu.parallel.mesh import activate_mesh
from localai_tpu.testing import faults
from localai_tpu.testing.lockdep import lockdep_lock


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Engine shape knobs (reference: n_parallel / n_ctx in ModelOptions,
    /root/reference/backend/backend.proto:185-187,199)."""
    max_slots: int = 4            # n_parallel — concurrent sequences
    max_context: int = 1024       # n_ctx per slot
    prefill_buckets: tuple[int, ...] = (64, 256, 1024)
    prefill_chunk: int = 256      # chunked-prefill window (tokens/engine tick)
    pipeline: bool = True         # keep one decode step in flight
    decode_block: int = 16        # decode steps fused per device dispatch
                                  # (amortizes host↔device latency; falls back
                                  # to single steps around grammar masks,
                                  # pending admissions, and context limits)
    decode_loop: int = 64         # single-dispatch decode loop: up to this
                                  # many sample→decode steps fused into ONE
                                  # on-device lax.while_loop with per-slot
                                  # stop conditions (EOS set, max_tokens
                                  # budget, context margin) evaluated on
                                  # device and early exit when every live
                                  # slot finished. 0/1 disables — the engine
                                  # then serves on the decode_block scan
                                  # ladder. Grammar and stop-string slots
                                  # always keep the host-verified block path.
    dtype: str | None = None      # default: model dtype
    cache_type: str = ""          # ""|bf16 dense; int8|q8_0 quantized KV
                                  # (reference CacheTypeKey/Value,
                                  # backend.proto:257-258)
    mesh: Any | None = None       # jax.sharding.Mesh for TP/DP sharding
    shift_keep: int = 4           # context-shift: sink tokens always kept
    replicator: Any | None = None  # multi-host: rank-0 step broadcaster
                                   # (parallel/distributed.Replicator)
    gamma: int = 4                # speculative: draft tokens per step
                                  # (reference NDraft, backend.proto:150)
    prompt_cache: bool = True     # reuse a freed slot's KV prefix when a new
                                  # prompt shares it (llama.cpp prompt/slot
                                  # cache role, backend.proto:136-142)
    prompt_cache_min: int = 16    # minimum shared prefix worth reusing
    sampling_topk_width: int = 64  # sort-free decode sampling when every
                                   # active slot's top_k fits this width
                                   # (0 disables; see ops/sampling.sample)
    admit_per_tick: int = 4       # admission/prefill units per engine tick
                                  # while decodes are running (burst TTFT vs
                                  # decode-cadence trade; unbounded when the
                                  # engine is idle)
    kv_pages: int = 0             # paged KV: physical 128-token blocks in the
                                  # shared pool, incl. the reserved trash
                                  # block 0 (0 = dense per-slot cache). Slots
                                  # reserve ceil((prompt+max_tokens)/128)
                                  # blocks at admission, so the pool
                                  # oversubscribes max_context, not requests.
    ragged_token_budget: int = 0  # ragged continuous batching (paged KV
                                  # only): token rows packed per mixed tick.
                                  # When > 0, ticks with prefill work pack
                                  # ALL live decode slots (one row each) plus
                                  # chunked-prefill windows into ONE flat
                                  # stream and run a single ragged-attention
                                  # dispatch (ops/pallas/ragged_attention.py)
                                  # — no per-bucket padding, no separate
                                  # prefill+decode programs on mixed ticks.
                                  # Admission becomes host-only bookkeeping
                                  # (never stalls on a device prefill); pure-
                                  # decode ticks keep the fused while-loop
                                  # path. 0 disables (the default serving
                                  # paths are untouched). Rounded up to a
                                  # QBLK (8-row) multiple. Grammar slots ride
                                  # the pack (fresh host masks each tick) and
                                  # multimodal prompt chunks pack their
                                  # feature rows via per-row embedding
                                  # injection — neither forces a dense
                                  # fallback dispatch.
    ragged_loop_steps: int = 16   # fused multi-step ragged ticks (ragged
                                  # engines only): up to this many decode
                                  # iterations per ragged dispatch in ONE
                                  # on-device lax.while_loop
                                  # (models/llama.build_ragged_loop).
                                  # Iteration 0 is the mixed ragged pack;
                                  # follow-on iterations re-derive the
                                  # decode metadata on device and run the
                                  # dense decode body, early-exiting when
                                  # any slot finishes (the host admits into
                                  # the freed slot immediately), when the
                                  # host-set prefill-pending flag is up
                                  # (TTFT stays at ragged levels), or at
                                  # this step cap. Pure-decode ticks on a
                                  # ragged engine ride the same program
                                  # (pack-free variant) instead of the
                                  # decode_loop path, gaining the
                                  # first-finish exit. 0/1 disables — the
                                  # engine keeps the single-step ragged +
                                  # decode_loop split (the escape hatch).
                                  # Speculative (draft) engines ignore it:
                                  # spec-as-ragged verify windows stay
                                  # single-step per tick.
    grammar_table_states: int = 256  # device grammar tables: shared capacity
                                  # (automaton states across live grammars)
                                  # for the precompiled [S, ceil(V/32)] u32
                                  # mask rows + [S, V] transition table that
                                  # let constrained slots ride the fused
                                  # while-loop and the spec verify window
                                  # with the mask gathered ON DEVICE.
                                  # Grammars whose reachable state set
                                  # exceeds the cap (unbounded nesting) fall
                                  # back to per-token host masks. 0 disables
                                  # (every grammar slot is host-masked).
    kv_policy: str = "full"       # KV lifecycle tier (engine/kvtier.py):
                                  # "full" keeps every block hot (identical
                                  # to the untiered engine), "sink_window(
                                  # sinks=N, window=W[, quantize_cold=true])"
                                  # switches the paged table to COMPACT ring
                                  # geometry — O(sinks+window) resident
                                  # blocks per slot for ANY context length.
                                  # Requires kv_pages; per-request policies
                                  # (GenRequest.kv_policy) may only shrink
                                  # the engine geometry.
    kv_cold_pages: int = 0        # quantize_cold: physical 128-token blocks
                                  # in the int8 cold pool (incl. reserved
                                  # index 0 = "not demoted"). Blocks whose
                                  # tokens exit the window are copied here
                                  # with sub-channel per-token scales instead
                                  # of being dropped; a full cold pool falls
                                  # back to eviction (kv_evictions metric).
    kv_host_bytes: int = 0        # host-RAM KV spill tier (engine/kvhost.py):
                                  # byte budget for blocks the device pool
                                  # evicts (slot reclaim, prefix-cache
                                  # rewrite, kvtier eviction), held int8
                                  # sub-channel and keyed by the prefix
                                  # cache's chain hashes. Admission consults
                                  # the tier after _match_prefix_blocks and
                                  # re-admits hits H2D, overlapped with the
                                  # uncovered suffix's prefill. 0 disables.
    max_restarts: int = 2         # fatal step() errors survived per engine
                                  # lifetime: in-flight streams fail, device
                                  # state is rebuilt, new requests serve
                                  # (reference analog: the manager reaping +
                                  # respawning a dead backend — this recovers
                                  # WITHOUT losing the loaded weights)


@dataclasses.dataclass
class GenRequest:
    """One generation request (the PredictOptions surface that matters to the
    engine; prompt templating/grammar happen upstream)."""
    prompt_ids: list[int]
    params: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    max_tokens: int = 128
    stop: tuple[str, ...] = ()
    ignore_eos: bool = False
    logprobs: bool = False
    grammar: str = ""             # GBNF; enforced via native matcher masks
    context_shift: bool = False   # evict-and-continue past max_context
                                  # (reference ctx_shift, backend.proto:22)
    prompt_cache_path: str = ""   # persist/reuse this prompt's KV on disk
                                  # (reference PromptCachePath,
                                  # backend.proto:136-142)
    prompt_cache_ro: bool = False  # reuse only; never rewrite the file
    trace_id: str = ""            # request id propagated from the HTTP layer
                                  # (telemetry span correlation; "" = untraced)
    trace_parent: int = 0         # parent span id (the gRPC handler's span)
    deadline: float = 0.0         # absolute time.monotonic() the request's
                                  # budget expires (PredictOptions.deadline_ms
                                  # via the HTTP middleware); the engine
                                  # evicts the slot with finish "timeout"
                                  # instead of decoding past it. 0 = none.
    kv_policy: str = ""           # per-request KV retention policy ("" =
                                  # inherit the engine's). "full" or
                                  # "sink_window(sinks=N, window=W)"; a
                                  # windowed request needs a windowed engine
                                  # and may only shrink its geometry
                                  # (engine/kvtier.resolve_policy)
    # multimodal (models/llava.py): projected image features [K, H] f32 and
    # the prompt positions they occupy (the expanded image-token slots) —
    # injected into prefill instead of token embeddings
    mm_embeds: Any = None          # np.ndarray [K, H] | None
    mm_positions: Any = None       # np.ndarray [K] i64 | None
    queued_t: float = 0.0          # time.monotonic() at submit() — the
                                   # arrival instant the SLO layer measures
                                   # queue wait and TTFT from (0 = direct
                                   # construction, falls back to admission)
    resume: dict | None = None     # preemption resume payload (ISSUE 19,
                                   # engine/resume.ResumeToken.payload()):
                                   # prompt_ids is prompt+emitted; "emitted"
                                   # counts the trailing checkpoint tokens,
                                   # "key" restores the slot's RNG chain,
                                   # "sent_chars" suppresses re-emission of
                                   # text the client already received


@dataclasses.dataclass
class StepOutput:
    """One streamed chunk."""
    request_id: int
    text: str                 # newly-stable text (may be "")
    token_id: int
    logprob: float
    finished: bool
    finish_reason: str | None = None   # stop | length | eos
    generated_tokens: int = 0
    prompt_tokens: int = 0
    timings: dict | None = None        # per-request phase timeline, attached
                                       # to the FINAL chunk only (ISSUE 11;
                                       # None mid-stream or with the SLO
                                       # layer disabled)
    resume: dict | None = None         # ResumeToken.to_dict() riding the
                                       # terminal "preempted" chunk — the
                                       # spill-drain's checkpoint of this
                                       # request (ISSUE 19); None otherwise


@dataclasses.dataclass
class _Slot:
    request_id: int
    req: GenRequest
    out: queue.Queue
    detok: Any                       # _IncrementalDecoder | None
    pending_text: str = ""           # holdback buffer for stop-string scan
    sent_chars: int = 0              # detok chars released downstream since
                                     # the ORIGINAL prompt boundary (global
                                     # across resume segments — the preempt
                                     # checkpoint's dedup cursor; excludes
                                     # pending_text, which a resume replays)
    resume_base: int = 0             # emitted-chain tokens replayed into
                                     # this slot at resume admission; a
                                     # second preempt folds them back into
                                     # the checkpoint's emitted list so
                                     # resumes compose exactly
    matcher: Any = None              # grammar MatcherState | None
    generated: int = 0
    gen_ids: list[int] = dataclasses.field(default_factory=list)
    start_time: float = 0.0
    first_token_time: float | None = None
    prompt_len: int = 0
    prefilled: bool = True           # False while chunked prefill in progress
    prefill_pos: int = 0             # prompt tokens already written to KV
    row: Any = None                  # sampler row (installed at final chunk)
    counts_row: Any = None
    shifted: int = 0                 # tokens evicted by context shifts
    disk_prefix: int = 0             # prefix length loaded from the disk
                                     # prompt cache (skip the re-save)
    fast_w: int | None = None        # narrowest sort-free top-k width that
                                     # covers this slot's sampling (None =
                                     # needs the full-sort path)
    span: Any = None                 # open telemetry span for this request
                                     # (None when tracing is disabled)
    inflight: int = 0                # tokens reserved by in-flight (not yet
                                     # consumed) decode dispatches — the
                                     # pipelined loop path budgets the NEXT
                                     # dispatch's per-slot `remaining` net of
                                     # this, so a slot can never overshoot
                                     # max_tokens however dispatches overlap
    # SLO phase timeline (ISSUE 11) — maintained only when the registry is
    # enabled (engine._slo is not None); all zeros/None otherwise
    prefill_done_t: float | None = None  # last prompt chunk committed
    last_token_t: float | None = None    # host arrival of the latest token
                                         # batch (TPOT reference point)
    obs_tokens: int = 0              # generated count at last_token_t — the
                                     # fused loop delivers token BURSTS, so
                                     # TPOT is the amortized gap over the
                                     # burst, weighted by its token count
    path: str = ""                   # decode path that served the latest
                                     # token (loop/dense/ragged/spec)
    dispatches: int = 0              # device dispatches this request rode
                                     # (Kernel Looping's per-request number)
    timeline: dict | None = None     # finished-request record handed to the
                                     # flight recorder at release
    gbase: int | None = None         # base row of this slot's grammar in the
                                     # shared device mask/transition tables;
                                     # None = host-masked (matcher walks the
                                     # mask) because the automaton overflowed
                                     # grammar_table_states or tables are off
    path_counts: dict = dataclasses.field(default_factory=dict)
                                     # per-path token counts for this request
                                     # (exported via req_path_counts when
                                     # engine.record_paths is set — bench
                                     # soup's per-tenant dispatch attribution)


class _AsyncFetch:
    """Async, double-buffered device→host result streaming (PRESERVE-style
    overlap): the D2H copy of a dispatch's small outputs (tokens, logprobs,
    per-slot counters) STARTS the moment the dispatch is enqueued —
    `copy_to_host_async` — so block N's tokens land in host memory while
    block N+1 computes. `wait()` then completes through `jax.device_get`
    (the sanctioned explicit transfer); on the pipelined hot path the data
    has already arrived and the call returns without a device stall."""

    __slots__ = ("_arrays",)

    def __init__(self, arrays):
        self._arrays = tuple(arrays)
        for a in self._arrays:
            try:
                a.copy_to_host_async()
            except Exception:
                # layouts without an async path (some sharded/committed
                # arrays): wait() still fetches correctly, just later
                pass

    def wait(self):
        """Finish the copies; returns host numpy arrays in input order."""
        return tuple(np.asarray(jax.device_get(a)) for a in self._arrays)


class Engine:
    """Continuous-batching engine over one loaded model."""

    def __init__(
        self,
        cfg: LlamaConfig,
        params,
        tokenizer=None,
        econfig: EngineConfig | None = None,
        draft: tuple | None = None,
        kvhost=None,
    ):
        """`draft=(draft_cfg, draft_params)` enables speculative decoding:
        the engine proposes ec.gamma tokens per step with the draft model and
        verifies them in one target forward (engine/spec.py).

        `kvhost`: an existing engine/kvhost.HostKVPool to adopt instead of
        building one from ec.kv_host_bytes — host RAM outlives device state,
        so a restarted/rerouted worker re-admits the previous process's
        spilled blocks (the bench --mode session restart leg)."""
        self.cfg = cfg
        self.params = params
        self.tok = tokenizer
        self.ec = econfig or EngineConfig()
        self._draft = draft
        if self.ec.max_context > cfg.max_position:
            raise ValueError("max_context exceeds model max_position")
        for b in self.ec.prefill_buckets:
            if b > self.ec.max_context:
                raise ValueError("prefill bucket larger than max_context")

        B, T, V = self.ec.max_slots, self.ec.max_context, cfg.vocab_size
        dtype = jnp.dtype(self.ec.dtype) if self.ec.dtype else cfg.jdtype
        self.mesh = self.ec.mesh
        # single-process meshes (one host driving all chips) keep every
        # shard addressable: the disk prompt cache can slice/inject KV
        # host-side. Multi-host meshes can't (rank 0 host code isn't
        # replayed on followers), so the cache stays off there.
        self._cache_addressable = (self.mesh is None
                                   or jax.process_count() == 1)

        if (jax.default_backend() == "tpu" and self.mesh is None
                and os.environ.get("LOCALAI_NO_PALLAS") != "1"
                and os.environ.get("LOCALAI_FORCE_PALLAS") != "1"):
            # decide the attention tier NOW, eagerly — the in-trace probe
            # path exists as a fallback but a load-time probe gives a clean
            # log line and never races a jit trace. Prefill always asks for
            # the kv_quant=False key (llama._attn_impls default), so warm
            # both variants when the KV cache is quantized.
            from localai_tpu.ops.kvcache import is_quant_kind
            from localai_tpu.ops.pallas import pallas_works

            pallas_works(cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
                         cfg.sliding_window, cfg.jdtype, kv_quant=False)
            if is_quant_kind(self.ec.cache_type):
                pallas_works(cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
                             cfg.sliding_window, cfg.jdtype, kv_quant=True)

        # paged KV (ops/paged.py): block pool + per-slot tables instead of a
        # dense [B, T] product. Host owns allocation; the device sees a
        # [B, MAXB] table per dispatch. Under a mesh the pool rides the XLA
        # gather path — block axis replicated, KV heads sharded on 'model'.
        # Incompatible (v1) with the disk prompt cache; context-shift runs
        # block-granular (cache_shift_paged); speculative decoding pages
        # the TARGET cache (the small draft keeps a dense one).
        self._paged = self.ec.kv_pages > 0
        if self._paged:
            if self.ec.kv_pages < 2:
                raise ValueError("kv_pages must be >= 2 (block 0 is trash)")
        # ragged continuous batching: one flat-stream dispatch for mixed
        # prefill+decode ticks (models/llama.ragged_forward). Paged-pool
        # only — the flat KV writes resolve through block tables.
        self._ragged = self.ec.ragged_token_budget > 0
        if self._ragged:
            if not self._paged:
                raise ValueError(
                    "ragged_token_budget requires paged KV (set kv_pages)")
            from localai_tpu.ops.pallas import QBLK

            rows = max(self.ec.ragged_token_budget, 2 * QBLK)
            if self._draft is not None:
                # spec-as-ragged: each verifying slot needs gamma+1 window
                # rows (QBLK-aligned) in the flat stream — make sure a full
                # slot population plus one prefill block always fits
                winb = -(-(self.ec.gamma + 1) // QBLK)
                rows = max(rows,
                           (self.ec.max_slots * winb + 1) * QBLK)
            self._ragged_rows = -(-rows // QBLK) * QBLK
        # KV lifecycle tier (engine/kvtier.py): a windowed engine policy
        # switches the paged table to COMPACT geometry — the per-slot table
        # row holds only sink_blocks identity columns plus a reused ring, so
        # decode gathers O(sinks + window) rows however long the sequence
        # runs. kv_policy="full" (the default) keeps kvt=None on every
        # dispatch path — byte-identical programs to the untiered engine.
        from localai_tpu.engine import kvtier

        self._kv_policy = kvtier.parse_policy(self.ec.kv_policy)
        self._tiered = self._kv_policy.windowed
        self._cold = self._tiered and self._kv_policy.quantize_cold
        if self._tiered:
            if not self._paged:
                raise ValueError(
                    "kv_policy sink_window requires paged KV (set kv_pages)")
            if self._draft is not None:
                raise ValueError(
                    "kv_policy sink_window is incompatible with a draft "
                    "model (the dense draft cache has no ring geometry)")
            if self.ec.replicator is not None:
                raise ValueError(
                    "kv_policy sink_window does not support multi-host "
                    "replication (per-slot ring geometry is host state)")
            if self._ragged and self._cold:
                raise ValueError(
                    "quantize_cold is incompatible with ragged continuous "
                    "batching (the flat-stream program has no cold-tier "
                    "lane); drop quantize_cold or ragged_token_budget")
            self._kv_margin = kvtier.engine_margin_tokens(self.ec)
            self._kv_ring = kvtier.ring_blocks(self._kv_policy.window,
                                               self._kv_margin)
            self._kv_resident = kvtier.resident_blocks(self._kv_policy,
                                                       self._kv_margin)
            if self._kv_resident > self.ec.kv_pages - 1:
                raise ValueError(
                    f"kv_policy {self._kv_policy.describe()} needs "
                    f"{self._kv_resident} resident blocks per slot but the "
                    f"pool has {self.ec.kv_pages - 1}; raise kv_pages or "
                    f"shrink sinks/window")
            if self._cold:
                if self.ec.kv_cold_pages < 2:
                    raise ValueError(
                        "quantize_cold needs kv_cold_pages >= 2 (cold "
                        "block 0 is the not-demoted sentinel)")
                from localai_tpu.ops.kvcache import is_quant_kind

                if is_quant_kind(self.ec.cache_type):
                    raise ValueError(
                        "quantize_cold requires a dense hot cache "
                        "(cache_type=''): the cold tier is already int8")
        elif self.ec.kv_cold_pages:
            raise ValueError(
                "kv_cold_pages needs kv_policy sink_window(..., "
                "quantize_cold=true)")
        # host-RAM KV spill tier (engine/kvhost.py, ISSUE 17): catches
        # blocks the device pool evicts, keyed by the prefix cache's chain
        # hashes. The pool may be injected (worker restart adopts the old
        # process's host RAM); ec.kv_host_bytes=0 with no injected pool
        # keeps self._kvhost None — every hook below is one branch.
        self._kvhost = None
        self._host_pending: list = []
        self._spill_group: bytes | None = None
        if kvhost is not None or self.ec.kv_host_bytes > 0:
            if not self._paged:
                raise ValueError(
                    "kv_host_bytes requires paged KV (set kv_pages)")
            if self._draft is not None:
                raise ValueError(
                    "kv_host_bytes is incompatible with a draft model "
                    "(draft engines never consult the prefix cache)")
            if self.ec.replicator is not None:
                raise ValueError(
                    "kv_host_bytes does not support multi-host replication "
                    "(the spill/readmit transfers are host-rank state)")
            from localai_tpu.engine.kvhost import HostKVPool

            self._kvhost = (kvhost if kvhost is not None
                            else HostKVPool(self.ec.kv_host_bytes))
        if self._draft is not None and self._draft[0].vocab_size != V:
            raise ValueError("draft vocab differs from target")
        self._kv_dtype = dtype
        self._init_device_state()
        # window the verify extend writes ahead of `lengths`; reserve it so
        # a spec step can never write past the cache end
        self._ctx_reserve = (self.ec.gamma + 1) if self._draft else 0
        # chunked prefill: chunk window + the buckets small enough to prefill
        # single-shot without stalling running decodes longer than one chunk
        if self.ec.prefill_chunk < 8:
            raise ValueError("prefill_chunk must be >= 8")
        self._chunk = min(self.ec.prefill_chunk, self.ec.max_context)
        small = tuple(b for b in self.ec.prefill_buckets if b <= self._chunk)
        dropped = tuple(b for b in self.ec.prefill_buckets if b > self._chunk)
        if dropped:
            import warnings

            warnings.warn(
                f"prefill buckets {dropped} exceed prefill_chunk="
                f"{self._chunk}; prompts longer than "
                f"{max(small) if small else self._chunk} tokens will prefill "
                f"in {self._chunk}-token chunks instead of single-shot",
                stacklevel=3)
        self._small_buckets = small or (self._chunk,)
        self._small_max = max(self._small_buckets)
        self._prefillq: list[int] = []   # slot indices mid-prefill, FIFO
        self._pending = None             # in-flight decode (pipeline depth 1)
        self._inflight_steps = 0         # step count of the pending dispatch
        self._queue: "queue.Queue[tuple[int, GenRequest, queue.Queue]]" = queue.Queue()
        self._next_id = 0
        # request ids marked for eviction by cancel() (client disconnect /
        # gRPC termination). Written from handler threads under _lock; the
        # loop thread reads bare — set membership is atomic under the GIL,
        # and a one-tick-late observation only costs one extra token.
        self._cancelled: set[int] = set()
        self._live: set[int] = set()   # rids submitted but not yet terminal
        self._lock = lockdep_lock("engine.submit")
        self._grammar_lock = lockdep_lock("engine.grammar")
        self._wake = threading.Event()
        self._running = False
        self._dead = False
        self._thread: threading.Thread | None = None
        # preemption spill-drain handshake (ISSUE 19): preempt() arms the
        # request + grace deadline from any thread; the engine thread runs
        # _spill_drain at a tick boundary and signals done
        self._preempt_req = threading.Event()
        self._preempt_done = threading.Event()
        self._preempt_t = 0.0
        self._preempt_manifest: list[dict] = []

        # metrics (reference MetricsResponse: backend.proto:40-46)
        self.metrics = {
            "requests_completed": 0,
            "tokens_generated": 0,
            "prompt_tokens_processed": 0,
            "prompt_tokens_reused": 0,
            "prompt_cache_hits": 0,
            "ttft_ms_last": 0.0,
            "tokens_per_second_last": 0.0,
            # dispatch-fusing telemetry: on a tunneled chip each dispatch
            # pays the link RTT, so decode_steps_dispatched /
            # decode_dispatches is the number that explains serve throughput
            "decode_dispatches": 0,
            "decode_steps_dispatched": 0,
            "admit_dispatches": 0,
            # cumulative ms the engine thread spent BLOCKED waiting for a
            # dispatch's results to land on the host (the async-fetch wait,
            # not the detok/stream fan-out) — per token this is the number
            # the decode-loop + copy_to_host_async work is driving to zero
            "host_sync_wait_ms": 0.0,
            # per-path token attribution (ISSUE 13): always-on so live
            # servers can compute constrained_over_plain-style ratios from
            # GetMetrics, not just bench.py --mode soup
            "tokens_by_path__loop": 0,
            "tokens_by_path__rloop": 0,
            "tokens_by_path__ragged": 0,
            "tokens_by_path__spec": 0,
            "tokens_by_path__dense": 0,
            # preemption-safe serving (ISSUE 19): spill-drains run, blocks
            # force-spilled, and resume admissions by coverage outcome
            "preempts": 0,
            "preempt_spilled_blocks": 0,
            "resume_readmits": 0,
            "resume_reprefills": 0,
        }
        if self._draft is not None:
            self.metrics["draft_proposed"] = 0
            self.metrics["draft_accepted"] = 0
        if self._ragged:
            # token-budget utilization = ragged_tokens_packed /
            # (ragged_dispatches * ragged rows) — how full the flat stream
            # runs; always-on (ISSUE 13), maintained incrementally at each
            # ragged dispatch so GetMetrics needs no recompute
            self.metrics["ragged_dispatches"] = 0
            self.metrics["ragged_tokens_packed"] = 0
            self.metrics["budget_utilization"] = 0.0
            # dispatch-budget bookkeeping (ISSUE 16): prefill tokens that
            # rode ragged packs (they earn budget credit alongside generated
            # tokens) and spec-as-ragged dispatches (still exempt — see
            # testing/tripwires.dispatch_budget)
            self.metrics["ragged_prefill_tokens"] = 0
            self.metrics["spec_ragged_dispatches"] = 0
        # per-request path attribution (bench.py --mode soup): opt-in so the
        # dict can't grow unbounded under a long-lived server
        self.record_paths = False
        self.req_path_counts: dict[int, dict] = {}
        if self._tiered:
            # KV lifecycle telemetry: cold demotions, evictions (window-
            # exited blocks dropped — ring overwrite, or a full cold pool),
            # prefix-cache blocks re-prefilled because ring columns can't be
            # borrowed, admission-time full→window demotions, and pool
            # occupancy (peak proves the O(sinks+window) residency bound)
            self.metrics.update(
                kv_cold_blocks=0, kv_evictions=0, kv_recomputes=0,
                kv_policy_demotions=0, kv_blocks_in_use=0, kv_blocks_peak=0)
        if self._kvhost is not None:
            # host-tier telemetry (ISSUE 17): occupancy is refreshed from
            # the pool at each _host_drain; hits/spills/evictions are the
            # pool's cumulative counters (shared across engines adopting
            # the same pool — restart legs keep their history)
            self.metrics.update(
                kv_host_blocks=0, kv_host_bytes=0, kv_host_bytes_peak=0,
                kv_host_hits=0, kv_host_spills=0, kv_host_evictions=0)

        # telemetry (localai_tpu/telemetry): both gates resolve to None/False
        # here so the per-dispatch cost of a disabled build is one attribute
        # load + branch (see _obs) — the hot path stays fence-free
        from localai_tpu import telemetry

        self._prof = telemetry.engine_profiler(cfg, mesh=self.mesh)
        self._tracer = telemetry.maybe_tracer()
        # serving SLO layer (ISSUE 11): streaming histograms + the flight
        # recorder, same one-attribute-load-and-branch contract as _obs when
        # disabled (LOCALAI_METRICS=0 → both None)
        self._slo = telemetry.maybe_slo()
        self._flightrec = (telemetry.flightrec()
                           if self._slo is not None else None)
        self._tick_n = 0
        # scheduler X-ray (ISSUE 13): the per-tick pack ledger — None when
        # disabled (LOCALAI_SCHED=0 / LOCALAI_METRICS=0), keeping step() on
        # the one-branch contract. Per-engine instance: bench runs several
        # engines in one process and their streams must not mix.
        self._sched = telemetry.maybe_ledger()
        self._set_tick = telemetry.set_current_tick
        # per-variant (jit fn, abstract arg shapes) captured at first
        # dispatch — rooflines() AOT-lowers the SAME traced programs later
        self._variant_avals: dict = {}
        self._rooflines: dict | None = None

        # runtime tripwire (localai_tpu/testing/tripwires): with
        # LOCALAI_TRANSFER_GUARD set, every decode dispatch runs under
        # jax.transfer_guard(level) — an implicit host transfer inside the
        # fused block raises instead of silently stalling the pipeline
        from localai_tpu.testing.tripwires import decode_guard_level

        self._xfer_guard = decode_guard_level()

        self._build_jit()

    def _init_device_state(self):
        """(Re)create all device-held serving state: KV caches, sampler,
        logits, lengths, paged tables, grammar masks, host slot table.
        Called at construction and again by the loop's self-restart path —
        params are never donated, so a fresh state block is all a recovery
        needs after a fatal device error."""
        cfg, B, T = self.cfg, self.ec.max_slots, self.ec.max_context
        V, dtype = cfg.vocab_size, self._kv_dtype
        if self._paged:
            from localai_tpu.ops.paged import BLOCK

            # tiered engines run the COMPACT table: resident columns per
            # slot (sinks + ring), not ceil(max_context/128) — the whole
            # point of the lifecycle tier (decode gathers O(resident) rows)
            self._maxb = (self._kv_resident if self._tiered
                          else -(-T // BLOCK))
            self._table = np.zeros((B, self._maxb), np.int32)
            self._kv_free: list[int] = list(range(1, self.ec.kv_pages))
            self._slot_blocks: list[list[int]] = [[] for _ in range(B)]
            self._released_lru: list[int] = []
            # block-level prefix cache: refcounted shared pages. A block's
            # refcount is the number of slot block-lists (live or released-
            # retained) holding it; the chain-hash index maps a full
            # 128-token content prefix to the physical block still storing
            # its K/V, letting a new admission map another tenant's pages
            # straight into its table (copy-on-write: borrowed pages are
            # never written — see _alloc_slot).
            self._block_ref = np.zeros(self.ec.kv_pages, np.int64)
            self._block_ref[0] = 1          # trash block: pinned forever
            self._hash_index: dict[bytes, int] = {}
            self._block_hash_of: dict[int, bytes] = {}
        if self._tiered:
            from localai_tpu.ops.paged import BLOCK

            # per-slot ring geometry, shipped with every dispatch (_kvt).
            # Full-policy sentinels: sb = table width makes the ring map the
            # identity and every column resident; window/sinks sentinels at
            # max_context keep the retention mask all-true for any length.
            self._kv_sb = np.full((B,), self._maxb, np.int32)
            self._kv_rw = np.ones((B,), np.int32)
            self._kv_sinks = np.full((B,), T, np.int32)
            self._kv_window = np.full((B,), T, np.int32)
            self._slot_policy: list = [None] * B
            # next raw (virtual) block index eligible for demotion/eviction
            # per slot — advanced by _kv_tick as tokens exit the window
            self._demote_next = np.zeros((B,), np.int64)
            if self._cold:
                self._cold_maxb = -(-T // BLOCK)
                self._cold_table = np.zeros((B, self._cold_maxb), np.int32)
                self._cold_free: list[int] = list(
                    range(1, self.ec.kv_cold_pages))
                self._slot_cold: list[list[int]] = [[] for _ in range(B)]
        self._deferred: tuple | None = None   # admission waiting on blocks
        self._admitting: tuple | None = None  # admission mid-device-call
        self._blocks_freed = False
        # in-flight D2H spills (hash, group, _AsyncFetch) — dropped on a
        # device-state rebuild: their source buffers died with the error
        # (the pool claims opened by begin_spill must be abandoned too, or
        # the chain pins they hold would leak forever)
        if getattr(self, "_host_pending", None) and self._kvhost is not None:
            for h, _group, _fetch in self._host_pending:
                self._kvhost.end_spill(h, None)
        self._host_pending = []
        self._ragged_rr = 0   # ragged decode-row round-robin offset (fair
                              # rotation when the token budget can't hold
                              # every live slot in one tick)

        with activate_mesh(self.mesh):
            cos, sin = rope_table(cfg.rope, T)
            self._cos, self._sin = cos, sin
            if self._paged:
                from localai_tpu.ops.paged import init_paged

                self._kc, self._vc = init_paged(
                    cfg.num_layers, self.ec.kv_pages, cfg.num_kv_heads,
                    cfg.head_dim, dtype, cache_type=self.ec.cache_type)
                if self._cold:
                    # parallel int8 cold pool (sub-channel per-token scales,
                    # Transformer-Lite): window-exited blocks are copied
                    # here by _dev_demote and read back through cold_tab
                    self._ck, self._cv = init_paged(
                        cfg.num_layers, self.ec.kv_cold_pages,
                        cfg.num_kv_heads, cfg.head_dim, dtype,
                        cache_type="int8")
            else:
                self._kc, self._vc = init_kv_cache(
                    cfg, B, T, dtype, cache_type=self.ec.cache_type)
            if self.mesh is not None and jax.process_count() == 1:
                # pre-place the KV state under its serving sharding (slots
                # on 'data', KV heads on 'model'; paged pool: block axis
                # replicated) so the first donated dispatch doesn't pay a
                # layout move and GSPMD never defaults the pool to
                # replicated. safe_sharding degrades non-dividing axes to
                # replicated instead of refusing to serve.
                from localai_tpu.models.llama import (
                    kv_cache_spec, paged_pool_spec,
                )
                from localai_tpu.parallel.mesh import safe_sharding

                kv_spec = paged_pool_spec() if self._paged \
                    else kv_cache_spec()
                place = lambda t: jax.tree_util.tree_map(  # noqa: E731
                    lambda a: jax.device_put(
                        a, safe_sharding(self.mesh, kv_spec, a.shape)), t)
                self._kc, self._vc = place(self._kc), place(self._vc)
            self._sampler = SamplerState.init(B, V)
            self._last_logits = jnp.zeros((B, V), jnp.float32)
            self._lengths = jnp.zeros((B,), jnp.int32)
            # device-resident EOS id set for the fused decode loop's on-device
            # stop condition (padded with -1 when the model has no tokenizer —
            # no sampled token matches, the budget/margin conditions still
            # bound the loop). Uploaded once, never per dispatch.
            eos = sorted(self.tok.eos_ids) if (
                self.tok is not None and getattr(self.tok, "eos_ids", None)
            ) else []
            self._eos_dev = jnp.asarray(
                np.asarray(eos or [-1], np.int32))
            if self._draft is not None:
                dcfg = self._draft[0]
                self._cos_d, self._sin_d = rope_table(dcfg.rope, T)
                self._kcd, self._vcd = init_kv_cache(dcfg, B, T, dtype)
                self._next_tokens = jnp.zeros((B,), jnp.int32)

        # grammar masks: one bitmask row per slot, all-ones = unconstrained
        self._mask_nbytes = (V + 7) // 8
        self._mask_host = np.full((B, self._mask_nbytes), 0xFF, np.uint8)
        self._grammar_slots = 0
        self._grammar_hostonly = 0   # grammar slots WITHOUT device tables
                                     # (automaton overflowed the cap): these
                                     # keep the per-token host-mask paths and
                                     # bar the fused while-loop
        self._grammar_cache = None
        # device grammar tables (grammar_table_states > 0): ONE shared pair
        # of arrays for every live grammar — masks [cap, ceil(V/32)] u32
        # (LSB-first packed allowed-token rows) and trans [cap, V] i32
        # (absolute next-state per token). Row 0 is the IDENTITY state every
        # unconstrained slot sits in: all-ones mask (where(True, x, -inf) is
        # x exactly, so constrained and unconstrained slots share one
        # compiled program bit-identically) and a self-loop transition.
        # Grammars get base offsets in _grammar_table_entry; the np mirrors
        # are authoritative (host _emit advances _gstate through _gtrans_np)
        # and the device copies refresh lazily on new installs (_gtab —
        # same shapes, so no recompile).
        self._mask_nwords = (V + 31) // 32
        self._gtab_cap = max(int(self.ec.grammar_table_states), 0)
        self._gstate = np.zeros((B,), np.int32)
        if self._gtab_cap:
            self._gmasks_np = np.zeros((self._gtab_cap, self._mask_nwords),
                                       np.uint32)
            self._gmasks_np[0] = 0xFFFFFFFF
            self._gtrans_np = np.zeros((self._gtab_cap, V), np.int32)
            self._gtab_used = 1
            self._gtab_base: dict[str, int | None] = {}
            self._gtab_dirty = True
            self._gmasks_dev = None
            self._gtrans_dev = None

        # host-side slot table
        self._slots: list[_Slot | None] = [None] * B
        self._free: list[int] = list(range(B))
        # prompt cache: per slot, the token ids whose K/V rows are still
        # valid in that slot's cache region (recorded at release)
        self._slot_kv_tokens: list[list[int]] = [[] for _ in range(B)]

    # ------------------------------------------------------------ jit builds

    def _build_jit(self):
        cfg = self.cfg

        def _install_row(sampler, slot, row, counts_row):
            # single-row install == the K=1 batched case (one body to keep
            # in sync with SamplerState's fields)
            return _install_rows(
                sampler, slot[None], {k: v[None] for k, v in row.items()},
                None if counts_row is None else counts_row[None])

        def _install_rows(sampler, slots, rows, counts_rows):
            """Install K sampler rows at `slots` [K]; rows' fields are
            stacked [K, ...]. counts_rows is [K, V] or None. "Light" rows
            (no penalties, no bias — the common case) omit the [V]-sized
            logit_bias and counts so an admission ships a few scalars instead
            of ~1 MB over a (possibly tunneled) link; absent fields are
            zeroed on device. None/missing keys are static → each variant
            compiles once."""
            new_fields = {}
            for f in dataclasses.fields(SamplerState):
                cur = getattr(sampler, f.name)
                if f.name == "token_counts":
                    if counts_rows is None:
                        new_fields[f.name] = cur.at[slots].set(0)
                    else:
                        new_fields[f.name] = cur.at[slots].set(counts_rows)
                elif f.name == "logit_bias" and "logit_bias" not in rows:
                    new_fields[f.name] = cur.at[slots].set(0.0)
                else:
                    new_fields[f.name] = cur.at[slots].set(rows[f.name])
            return SamplerState(**new_fields)

        def _admit_many(params, cos, sin, kc, vc, sampler, last_logits,
                        lengths, tokens, lens, slots, rows, counts_rows,
                        table=None, inject=None, kvt=None):
            """Admission burst: prefill K same-bucket requests in ONE pass.

            The single-request _admit streams the full weight set per call —
            a 16-slot burst pays 16 weight streams + 16 tunnel round trips,
            which is what put p50 TTFT at 1.6 s on the real chip. Batching
            the burst reads the weights once and rides one round trip (the
            reference can't do this — llama.cpp prefills slots one ubatch at
            a time, grpc-server.cpp update_slots)."""
            logits, kc, vc = prefill(
                params, cfg, tokens, lens, cos, sin, kc, vc, slots, table,
                inject, kvt
            )
            last_logits = last_logits.at[slots].set(logits)
            lengths = lengths.at[slots].set(lens)
            sampler = _install_rows(sampler, slots, rows, counts_rows)
            return kc, vc, sampler, last_logits, lengths

        def _extend_mid(params, cos, sin, kc, vc, tokens, start, slot,
                        table=None, inject=None, kvt=None):
            """One non-final prefill chunk: KV writes only. Mid chunks are
            always full (the final chunk takes _extend_final), so every
            position sits inside the slot's allocation → full_window keeps
            the paged scatter on the asserted-unique in-place path."""
            _, kc, vc = extend(params, cfg, tokens, start[None], cos, sin,
                               kc, vc, slot_map=slot[None], with_logits=False,
                               table=table, inject=inject, full_window=True,
                               kvt=kvt)
            return kc, vc

        def _extend_final(params, cos, sin, kc, vc, sampler, last_logits,
                          lengths, tokens, start, nvalid, slot, row,
                          counts_row, table=None, inject=None, kvt=None):
            """Final prefill chunk: KV writes + last-token logits + sampler
            row install (deferred to here so the request's RNG stream is
            independent of how many engine ticks the prefill spanned)."""
            logits, kc, vc = extend(
                params, cfg, tokens, start[None], cos, sin, kc, vc,
                slot_map=slot[None],
                last_pos=jnp.maximum(nvalid - 1, 0)[None], table=table,
                inject=inject, kvt=kvt)
            last_logits = last_logits.at[slot].set(logits[0])
            lengths = lengths.at[slot].set(start + nvalid)
            sampler = _install_row(sampler, slot, row, counts_row)
            return kc, vc, sampler, last_logits, lengths

        def _decode(params, cos, sin, kc, vc, sampler, last_logits, lengths,
                    active, mask_bits, fast_width=None, table=None, kvt=None):
            """sample(prev logits) → decode → next logits, for all slots."""
            tokens, keys, logprobs = sample(last_logits, sampler, mask_bits,
                                            topk_width=fast_width)
            logits, kc, vc = decode_step(
                params, cfg, tokens, lengths, cos, sin, kc, vc, active, table,
                kvt
            )
            act = active.astype(jnp.int32)
            counts = sampler.token_counts.at[
                jnp.arange(tokens.shape[0]), tokens
            ].add(act)
            sampler = dataclasses.replace(
                sampler, key=keys, token_counts=counts
            )
            lengths = lengths + act
            return tokens, logprobs, kc, vc, sampler, logits, lengths

        # multi-host: the engine's host decisions (tokens to write, slot
        # indices, masks) must be readable on rank 0 even when slots shard
        # over hosts — replicate the tiny per-step outputs
        from localai_tpu.parallel.mesh import constrain
        from jax.sharding import PartitionSpec as P

        _decode_raw = _decode

        def _decode(*a, **kw):
            tokens, logprobs, kc, vc, sampler, logits, lengths = _decode_raw(
                *a, **kw)
            return (constrain(tokens, P(None)), constrain(logprobs, P(None)),
                    kc, vc, sampler, logits, lengths)

        # donate the big carried buffers: cache stays in place in HBM.
        # mask_bits=None compiles a no-grammar variant with zero extra
        # host→device traffic on the common path.
        self._admit_many_fn = jax.jit(_admit_many,
                                      donate_argnums=(3, 4, 5, 6, 7))
        self._extend_mid_fn = jax.jit(_extend_mid, donate_argnums=(3, 4))
        self._extend_final_fn = jax.jit(_extend_final,
                                        donate_argnums=(3, 4, 5, 6, 7))
        # context shift: keep/discard are static → one compiled program
        if self._paged:
            # block-granular (models/llama.py cache_shift_paged): keep the
            # sink block(s), drop a half-context worth of whole blocks; the
            # slide itself is a host-side table permutation
            from localai_tpu.ops.paged import BLOCK

            from localai_tpu.models.llama import cache_shift_paged

            self._shift_keepb = max(1, -(-self.ec.shift_keep // BLOCK))
            self._shift_discb = max(1, (self._maxb - self._shift_keepb) // 2)
            self._shift_discard = self._shift_discb * BLOCK
            # a shift must leave at least one tail block to slide: tiny
            # contexts (maxb <= keepb+discb) cannot evict block-granularly —
            # submit() rejects context_shift there instead of driving
            # lengths negative
            self._shift_ok = self._maxb > (self._shift_keepb
                                           + self._shift_discb)

            def _shift_paged(kc, lengths, row_table, slot):
                kc = cache_shift_paged(
                    cfg, kc, row_table, keep_blocks=self._shift_keepb,
                    discard_blocks=self._shift_discb)
                return kc, lengths.at[slot].add(-self._shift_discard)

            self._shift_fn = jax.jit(_shift_paged, donate_argnums=(0, 1))
        else:
            self._shift_discard = max(
                1, (self.ec.max_context - self.ec.shift_keep) // 2)
            self._shift_fn = jax.jit(
                partial(cache_shift, cfg, keep=self.ec.shift_keep,
                        discard=self._shift_discard),
                donate_argnums=(0, 1, 2))

        if self._draft is not None:
            from localai_tpu.engine.spec import (
                build_draft_ingest, build_spec_admit_tail, build_spec_decode,
            )

            if self._paged:
                from localai_tpu.ops.paged import BLOCK

                if self.ec.max_slots * (self.ec.gamma + 1) > BLOCK:
                    import logging

                    logging.getLogger("localai_tpu").warning(
                        "paged spec verify: %d slots x (gamma+1)=%d trash "
                        "offsets exceed one %d-token block, so the verify "
                        "scatter cannot assert uniqueness — expect reduced "
                        "paged throughput; lower max_slots or gamma to "
                        "restore the in-place path",
                        self.ec.max_slots, self.ec.gamma + 1, BLOCK)

            dcfg = self._draft[0]
            _spec_raw = build_spec_decode(cfg, dcfg, self.ec.gamma)

            def _spec(*a):
                # host (rank 0) reads the small per-step outputs each spec
                # step — replicate them, as with _decode above
                (tokens_out, n_out, logprobs_out, next_tokens, kct, vct,
                 kcd, vcd, sampler, lengths, n_extra) = _spec_raw(*a)
                return (constrain(tokens_out, P(None)),
                        constrain(n_out, P(None)),
                        constrain(logprobs_out, P(None)),
                        constrain(next_tokens, P(None)),
                        kct, vct, kcd, vcd, sampler, lengths,
                        constrain(n_extra, P(None)))

            self._spec_fn = jax.jit(
                _spec, donate_argnums=(6, 7, 8, 9, 10, 11, 12))
            self._spec_admit_tail_fn = jax.jit(
                build_spec_admit_tail(cfg), donate_argnums=(0,))
            self._draft_ingest_fn = jax.jit(
                build_draft_ingest(dcfg), donate_argnums=(3, 4))
            # spec-as-ragged: the verify pass as a ragged pack variant —
            # draft windows are just extra qlen rows in the flat stream,
            # packed alongside other tenants' prefill chunks (and their
            # multimodal inject rows) in ONE program (engine/spec.py
            # build_spec_ragged). Replaces the per-mode dense verify on
            # ragged engines; the extend-based _spec_fn stays for dense ones.
            self._spec_ragged_fn = None
            if self._ragged:
                from localai_tpu.engine.spec import build_spec_ragged

                _specr_raw = build_spec_ragged(cfg, dcfg, self.ec.gamma)

                def _specr(*a, **kw):
                    (tokens_out, n_out, logprobs_out, next_tokens, kct, vct,
                     kcd, vcd, sampler, last_logits, lengths,
                     n_extra) = _specr_raw(*a, **kw)
                    return (constrain(tokens_out, P(None, None)),
                            constrain(n_out, P(None)),
                            constrain(logprobs_out, P(None, None)),
                            constrain(next_tokens, P(None)),
                            kct, vct, kcd, vcd, sampler, last_logits,
                            lengths, constrain(n_extra, P(None)))

                self._spec_ragged_fn = jax.jit(
                    _specr, donate_argnums=(6, 7, 8, 9, 10, 11, 12, 13))
        self._decode_fn = jax.jit(_decode, donate_argnums=(3, 4, 5, 6, 7),
                                  static_argnames=())
        self._decode_nomask_fn = jax.jit(
            partial(_decode, mask_bits=None), donate_argnums=(3, 4, 5, 6, 7))
        # fast_width static → one compiled variant per width (the base
        # width plus the 8x escalation tier: one wide-top_k tenant no
        # longer de-optimizes the whole batch to the full-sort path)
        self._decode_fast_fn = jax.jit(
            partial(_decode, mask_bits=None),
            donate_argnums=(3, 4, 5, 6, 7),
            static_argnames=("fast_width",))

        def _decode_block(params, cos, sin, kc, vc, sampler, last_logits,
                          lengths, active, mask_bits=None, table=None,
                          kvt=None, *, steps: int, fast_width=None):
            """`steps` fused sample→decode iterations in ONE device program.

            One dispatch + one result fetch per `steps` tokens: on a remote
            (tunneled) TPU the per-call host↔device round trip is tens of ms —
            more than the decode step itself — so fusing the loop is worth
            ~steps× decode throughput. Grammar slots ride the block with
            their block-START mask held fixed; the host verifies each sampled
            token against the PDA afterwards and rolls the slot back at the
            first stale-mask miss (engine._repair) — free slots keep full
            block speed either way."""
            def body(carry, _):
                kc, vc, sampler, last_logits, lengths = carry
                tokens, logprobs, kc, vc, sampler, last_logits, lengths = (
                    _decode(params, cos, sin, kc, vc, sampler, last_logits,
                            lengths, active, mask_bits, fast_width, table,
                            kvt))
                return (kc, vc, sampler, last_logits, lengths), (tokens,
                                                                 logprobs)
            carry = (kc, vc, sampler, last_logits, lengths)
            carry, (toks, lps) = jax.lax.scan(body, carry, None, length=steps)
            kc, vc, sampler, last_logits, lengths = carry
            return toks, lps, kc, vc, sampler, last_logits, lengths

        self._decode_block_fn = jax.jit(
            partial(_decode_block, mask_bits=None),
            donate_argnums=(3, 4, 5, 6, 7),
            static_argnames=("steps", "fast_width"))
        self._decode_block_mask_fn = jax.jit(
            _decode_block, donate_argnums=(3, 4, 5, 6, 7),
            static_argnames=("steps", "fast_width"))

        # single-dispatch decode loop (Kernel Looping): the while-loop
        # variant of the scan block, with stop conditions ON DEVICE and
        # early exit — one dispatch per decode_loop-token block instead of
        # the scan ladder's 4-8 (models/llama.build_decode_loop). The raw
        # (un-constrained) _decode is the body so the per-step RNG/count
        # semantics are bit-identical to the other paths; the tiny outputs
        # are replicated for the rank-0 host read like _decode's.
        self._decode_loop_fn = None
        if self.ec.decode_loop > 1:
            from localai_tpu.models.llama import build_decode_loop

            _loop_raw = build_decode_loop(
                _decode_raw,
                max_steps=self.ec.decode_loop,
                limit=self.ec.max_context - 2 - self._ctx_reserve)

            def _loop(*a, **kw):
                (toks, lps, n_out, steps, kc, vc, sampler, last_logits,
                 lengths) = _loop_raw(*a, **kw)
                return (constrain(toks, P(None, None)),
                        constrain(lps, P(None, None)),
                        constrain(n_out, P(None)), steps,
                        kc, vc, sampler, last_logits, lengths)

            self._decode_loop_fn = jax.jit(
                _loop, donate_argnums=(3, 4, 5, 6, 7),
                static_argnames=("fast_width",))

        # standalone sampler-row install: the ragged path defers a final
        # chunk's row to its own small dispatch (the ragged program's
        # signature stays row-structure-free, so it compiles exactly once)
        self._install_fn = jax.jit(_install_row, donate_argnums=(0,))

        # ragged mixed-tick program: sample all slots from last_logits,
        # splice the sampled tokens into the packed flat stream at the
        # decode rows, then ONE ragged forward covers every decode slot and
        # prefill chunk (models/llama.ragged_forward). Per-slot RNG/count
        # semantics mirror _decode exactly — topk_width=None draws the same
        # tokens as any fast-width tier (ops/sampling._draw is width-
        # independent), so ragged and dense serving emit identical streams.
        self._ragged_fn = None
        self._ragged_loop_fn = None
        if self._ragged:
            from localai_tpu.models.llama import ragged_forward

            def _ragged_step(params, cos, sin, kc, vc, sampler, last_logits,
                             lengths, tokens_flat, decode_slot, is_decode,
                             set_len, logit_set, logit_rows, block_seq,
                             qstart, qlen, kvlen, table, kvt=None,
                             mask_bits=None, inject=None):
                # mask_bits [B, ceil(V/8)] u8 rides ticks with grammar slots
                # (the pack is consumed synchronously, so host masks are
                # always fresh — this covers table AND overflow grammars);
                # inject (extra [T, H] f32, is_embed [T] bool) carries
                # multimodal feature rows for packed prompt chunks. Both are
                # None on the common path — jit specializes each variant.
                sampled, keys, logprobs = sample(last_logits, sampler,
                                                 mask_bits, topk_width=None)
                toks = jnp.where(decode_slot >= 0,
                                 sampled[jnp.maximum(decode_slot, 0)],
                                 tokens_flat)
                logits, kc, vc = ragged_forward(
                    params, cfg, toks, cos, sin, kc, vc, block_seq, qstart,
                    qlen, kvlen, table, logit_rows, kvt, inject)
                act = is_decode.astype(jnp.int32)
                counts = sampler.token_counts.at[
                    jnp.arange(sampled.shape[0]), sampled].add(act)
                sampler = dataclasses.replace(sampler, key=keys,
                                              token_counts=counts)
                # decode slots and final prefill chunks pick up their new
                # last-token logits; mid-chunk and idle slots hold theirs
                last_logits = jnp.where(logit_set[:, None], logits,
                                        last_logits)
                lengths = jnp.where(set_len >= 0, set_len, lengths + act)
                return (constrain(sampled, P(None)),
                        constrain(logprobs, P(None)),
                        kc, vc, sampler, last_logits, lengths)

            self._ragged_fn = jax.jit(_ragged_step,
                                      donate_argnums=(3, 4, 5, 6, 7))

            # fused multi-step ragged tick (ISSUE 16): iteration 0 is the
            # mixed ragged body above, follow-on iterations re-derive the
            # decode metadata on device and run the raw dense body — one
            # dispatch covers up to ragged_loop_steps decode steps with
            # first-finish / prefill-pending early exit
            # (models/llama.build_ragged_loop). Draft engines keep the
            # spec-as-ragged single-step tick: verify windows are whole
            # rows of the pack and must return to the host every tick.
            if self.ec.ragged_loop_steps > 1 and self._draft is None:
                from localai_tpu.models.llama import build_ragged_loop

                _rloop_raw = build_ragged_loop(
                    _ragged_step, _decode_raw,
                    max_steps=self.ec.ragged_loop_steps,
                    limit=self.ec.max_context - 2 - self._ctx_reserve)

                def _rloop(*a, **kw):
                    (toks, lps, n_out, steps, code, kc, vc, sampler,
                     last_logits, lengths) = _rloop_raw(*a, **kw)
                    return (constrain(toks, P(None, None)),
                            constrain(lps, P(None, None)),
                            constrain(n_out, P(None)), steps, code,
                            kc, vc, sampler, last_logits, lengths)

                self._ragged_loop_fn = jax.jit(
                    _rloop, donate_argnums=(3, 4, 5, 6, 7),
                    static_argnames=("fast_width", "has_pack"))

        # cold demotion: copy ONE hot physical block into a cold-pool index
        # with sub-channel (per-token over head_dim) int8 quantization.
        # pb/ci are traced scalars → one compiled program however many
        # blocks ever demote (the compile-count tripwire stays green).
        self._demote_fn = None
        if self._cold:
            from localai_tpu.ops.kvcache import QuantKV, quantize_tokens

            def _demote(kc, vc, ck, cv, pb, ci):
                def one(hot, cold):
                    blk = hot[:, pb]                      # [L, KVH, BS, D]
                    q, scale = quantize_tokens(blk)       # scale [L,KVH,BS]
                    return QuantKV(
                        cold.q.at[:, ci].set(q),
                        cold.s.at[:, ci].set(
                            scale[:, :, None, :].astype(cold.s.dtype)))
                return one(kc, ck), one(vc, cv)

            self._demote_fn = jax.jit(_demote, donate_argnums=(2, 3))

        # host-RAM spill tier (ISSUE 17): slice ONE physical block out of
        # the hot pool in int8 sub-channel form (spill), and write one host
        # block back into fresh physical pages (readmit). pb is a traced
        # scalar → one compiled program each however many blocks move (the
        # compile-count tripwire pins decode_step; these are admission-side
        # programs like _demote_fn). A quantized hot pool spills its q/s
        # bytes verbatim — the round trip is byte-exact, which is what the
        # --mode session greedy-parity gate measures; a dense pool pays the
        # same quantize_tokens error the kvtier cold read path accepts.
        self._spill_fn = None
        self._readmit_fn = None
        if self._kvhost is not None:
            from localai_tpu.ops.kvcache import (
                QuantKV, is_quant_kind, quantize_tokens,
            )

            if is_quant_kind(self.ec.cache_type):
                def _spill(kc, vc, pb):
                    return (kc.q[:, pb], kc.s[:, pb],
                            vc.q[:, pb], vc.s[:, pb])

                def _readmit(kc, vc, kq, ks, vq, vs, pb):
                    return (QuantKV(kc.q.at[:, pb].set(kq),
                                    kc.s.at[:, pb].set(ks)),
                            QuantKV(vc.q.at[:, pb].set(vq),
                                    vc.s.at[:, pb].set(vs)))
            else:
                def _spill(kc, vc, pb):
                    def one(hot):
                        q, scale = quantize_tokens(hot[:, pb])
                        # scale [L,KVH,BS] → the stored [L,KVH,1,BS] tile
                        return q, scale[:, :, None, :]
                    (kq, ks), (vq, vs) = one(kc), one(vc)
                    return kq, ks, vq, vs

                def _readmit(kc, vc, kq, ks, vq, vs, pb):
                    def one(hot, q, s):
                        blk = (q.astype(jnp.float32)
                               * s[:, :, 0, :, None]).astype(hot.dtype)
                        return hot.at[:, pb].set(blk)
                    return one(kc, kq, ks), one(vc, vq, vs)

            self._spill_fn = jax.jit(_spill)
            self._readmit_fn = jax.jit(_readmit, donate_argnums=(0, 1))

    # ------------------------------------------------------ device dispatch
    # Every device call goes through one of these. On a multi-host mesh the
    # rank-0 engine broadcasts (op, args) over the Replicator side channel
    # first; follower ranks replay the identical sequence via follow() so the
    # SPMD programs stay in lockstep (parallel/distributed.py).

    def _bcast(self, op: str, **kw):
        rep = self.ec.replicator
        if rep is not None:
            rep.broadcast(op, {
                k: (np.asarray(v) if hasattr(v, "shape") or isinstance(
                    v, (list, tuple)) else v)
                for k, v in kw.items()})

    def _tab(self):
        """Device copy of the block table for this dispatch (paged KV only).
        Tiny ([B, MAXB] i32) — shipping it per call keeps the host allocator
        the single source of truth with no donation bookkeeping."""
        return jnp.asarray(self._table) if self._paged else None

    def _kvt(self):
        """Per-slot KV-tier geometry for this dispatch (None on untiered
        engines — every jitted program then traces WITHOUT the tier branch,
        byte-identical to the pre-tier engine). Like _tab(), the tiny [B]
        arrays ship per call as runtime data: any mix of full and windowed
        slots (and any demotion state) reuses one compiled program."""
        if not self._tiered:
            return None
        d = {"sb": jnp.asarray(self._kv_sb), "rw": jnp.asarray(self._kv_rw),
             "sinks": jnp.asarray(self._kv_sinks),
             "window": jnp.asarray(self._kv_window)}
        if self._cold:
            d["cold_k"], d["cold_v"] = self._ck, self._cv
            d["cold_tab"] = jnp.asarray(self._cold_table)
        return d

    def _gtab(self):
        """Device copies of the shared grammar tables (masks u32, trans
        i32). Re-uploaded only after a new grammar install marked them
        dirty — same shapes every time, so every consumer program compiles
        exactly once and the upload is off the per-token hot path."""
        if self._gtab_dirty:
            with activate_mesh(self.mesh):
                self._gmasks_dev = jnp.asarray(self._gmasks_np)
                self._gtrans_dev = jnp.asarray(self._gtrans_np)
            self._gtab_dirty = False
        return self._gmasks_dev, self._gtrans_dev

    def _dev_gtable(self, base: int, masks, trans):
        """Install one grammar's precompiled rows at `base` in the shared
        table mirrors (device copies refresh lazily via _gtab). Broadcast so
        follower ranks hold identical tables for the loop/spec replays."""
        self._bcast("gtable", base=base, masks=masks, trans=trans)
        n = masks.shape[0]
        self._gmasks_np[base:base + n] = masks
        self._gtrans_np[base:base + n] = trans
        self._gtab_dirty = True

    def _grammar_table_entry(self, grammar: str) -> int | None:
        """Base offset of this grammar's rows in the shared device tables,
        building + installing them (off the hot path) on first use. None =
        the automaton doesn't fit (table overflow, or tables disabled) — the
        slot then keeps the per-token host-mask paths."""
        if not self._gtab_cap:
            return None
        if grammar in self._gtab_base:
            return self._gtab_base[grammar]
        cg = self._compile_grammar(grammar)
        tbl = cg.table(self._gtab_cap)
        base = None
        if tbl is not None and self._gtab_used + tbl.n_states <= self._gtab_cap:
            base = self._gtab_used
            masks = tbl.masks.copy()
            # local -1 (token masked off — never sampled) → absolute 0; the
            # identity row is harmless if ever gathered. Live states remap
            # to base-relative absolute indices.
            trans = np.where(tbl.trans < 0, 0,
                             tbl.trans + base).astype(np.int32)
            # EOS policy is per-tokenizer, injected here (the raw table has
            # no EOS bits — matcher.mask_bits parity): accepting states
            # allow EOS and self-loop on it, mirroring the host matcher
            # which never advances past EOS.
            V = self.cfg.vocab_size
            eos = [e for e in (self.tok.eos_ids if self.tok else ())
                   if 0 <= e < V]
            for s in range(tbl.n_states):
                if tbl.accepting[s]:
                    for e in eos:
                        masks[s, e >> 5] |= np.uint32(1) << np.uint32(e & 31)
                        trans[s, e] = base + s
            self._dev_gtable(base, masks, trans)
            self._gtab_used = base + tbl.n_states
            self.metrics["grammar_table_states"] = self._gtab_used
        else:
            self.metrics["grammar_table_overflows"] = (
                self.metrics.get("grammar_table_overflows", 0) + 1)
            if self._sched is not None:
                self._sched.reason("grammar_table_overflow",
                                   states=(0 if tbl is None
                                           else int(tbl.n_states)))
        self._gtab_base[grammar] = base
        return base

    def _note_pool(self):
        """Refresh the pool-occupancy gauges (tiered engines only — the
        peak is the bench's O(sinks+window) residency proof)."""
        if not self._tiered:
            return
        used = self.ec.kv_pages - 1 - len(self._kv_free)
        self.metrics["kv_blocks_in_use"] = used
        if used > self.metrics["kv_blocks_peak"]:
            self.metrics["kv_blocks_peak"] = used

    def _decode_guard(self):
        """Transfer-guard context for the decode dispatch (nullcontext unless
        LOCALAI_TRANSFER_GUARD is set — see testing/tripwires)."""
        if self._xfer_guard:
            return jax.transfer_guard(self._xfer_guard)
        return contextlib.nullcontext()

    def _obs(self, stage: str, t0: float, tokens: int = 0, fence=None,
             **args):
        """Record one device-dispatch observation (telemetry subsystem).

        With LOCALAI_PROFILE the profiler fences (`block_until_ready`) before
        reading the clock, so the sample is the stage's real host+device cost
        — opt-in because the fence defeats the decode pipeline. With
        LOCALAI_TRACE a span lands in the ring buffer (un-fenced samples
        measure enqueue time only and say so via the `fenced` arg). Disabled
        (the default) this is two attribute loads and a branch."""
        prof, tr = self._prof, self._tracer
        if prof is None and tr is None:
            return
        dur = None
        if prof is not None:
            dur = prof.record(stage, t0, tokens=tokens, fence=fence)
        if tr is not None:
            tr.add_complete("engine." + stage, t0, dur_s=dur, cat="engine",
                            args=dict(args, tokens=tokens,
                                      fenced=prof is not None))

    def _sched_pack(self, variant: str, fn, fargs, fkw, **comp):
        """Tick-ledger dispatch record (ISSUE 13): the pack composition of
        one dispatch under its compiled-program variant name, plus a one-
        time capture of the program's abstract arg shapes
        (jax.ShapeDtypeStruct — no buffer refs, so donation can't dangle)
        for the lazy AOT cost-analysis pass in rooflines(). One None-check
        when the ledger is disabled."""
        sched = self._sched
        if sched is None:
            return
        if variant not in self._variant_avals:
            try:
                def _aval(x):
                    if hasattr(x, "shape") and hasattr(x, "dtype"):
                        return jax.ShapeDtypeStruct(x.shape, x.dtype)
                    return x
                self._variant_avals[variant] = (
                    fn, jax.tree_util.tree_map(_aval, fargs),
                    jax.tree_util.tree_map(_aval, fkw))
            except Exception:
                self._variant_avals[variant] = None
        sched.pack(variant, **comp)

    def _dev_admit(self, ids, n, slot, row, counts_row, inject=None):
        # single admission == the K=1 batched case (the delegate broadcasts
        # "admit_many"; the "admit" follower op is kept for replay compat)
        self._dev_admit_many(
            np.asarray(ids, np.int32), np.asarray([n], np.int32),
            np.asarray([slot], np.int32),
            {k: np.asarray(v)[None] for k, v in row.items()},
            None if counts_row is None else np.asarray(counts_row)[None],
            inject)

    def _dev_admit_many(self, ids, lens, slots, rows, counts_rows,
                        inject=None):
        self.metrics["admit_dispatches"] += 1
        t0 = time.perf_counter()
        self._bcast("admit_many", ids=ids, lens=lens, slots=slots,
                    rows={k: np.asarray(v) for k, v in rows.items()},
                    counts_rows=counts_rows, inject=self._inj_msg(inject))
        with activate_mesh(self.mesh):
            (self._kc, self._vc, self._sampler, self._last_logits,
             self._lengths) = self._admit_many_fn(
                self.params, self._cos, self._sin,
                self._kc, self._vc, self._sampler, self._last_logits,
                self._lengths,
                jnp.asarray(ids), jnp.asarray(lens), jnp.asarray(slots),
                {k: jnp.asarray(v) for k, v in rows.items()},
                None if counts_rows is None else jnp.asarray(counts_rows),
                self._tab(), self._inj(inject), self._kvt())
        self._obs("admit", t0, tokens=int(np.sum(lens)),
                  fence=self._lengths, requests=len(slots))

    @staticmethod
    def _inj(inject):
        """Host inject pair (extra [B,S,H] f32, is_embed [B,S] bool) → device
        arrays (None passes through; jit specializes the text-only variant)."""
        if inject is None:
            return None
        extra, is_embed = inject
        return (jnp.asarray(extra), jnp.asarray(is_embed))

    @staticmethod
    def _inj_msg(inject):
        """inject pair → broadcast-safe dict (the _bcast serializer would
        np.asarray a tuple, which fails on mismatched member shapes)."""
        if inject is None:
            return None
        return {"extra": np.asarray(inject[0]), "mask": np.asarray(inject[1])}

    @staticmethod
    def _inj_of(msg):
        """_inj_msg's inverse, for follower replay."""
        if msg is None:
            return None
        return (msg["extra"], msg["mask"])

    def _dev_extend_mid(self, buf, pos, idx, inject=None):
        t0 = time.perf_counter()
        self._bcast("extend_mid", buf=buf, pos=pos, idx=idx,
                    inject=self._inj_msg(inject))
        with activate_mesh(self.mesh):
            self._kc, self._vc = self._extend_mid_fn(
                self.params, self._cos, self._sin, self._kc, self._vc,
                jnp.asarray(buf), jnp.int32(pos), jnp.int32(idx), self._tab(),
                self._inj(inject), self._kvt())
        self._obs("prefill", t0, tokens=int(buf.shape[1]), fence=self._kc,
                  slot=int(idx), final=False)

    def _dev_extend_final(self, buf, pos, nvalid, idx, row, counts_row,
                          inject=None):
        t0 = time.perf_counter()
        self._bcast("extend_final", buf=buf, pos=pos, nvalid=nvalid, idx=idx,
                    row={k: np.asarray(v) for k, v in row.items()},
                    counts_row=counts_row, inject=self._inj_msg(inject))
        with activate_mesh(self.mesh):
            (self._kc, self._vc, self._sampler, self._last_logits,
             self._lengths) = self._extend_final_fn(
                self.params, self._cos, self._sin,
                self._kc, self._vc, self._sampler, self._last_logits,
                self._lengths, jnp.asarray(buf), jnp.int32(pos),
                jnp.int32(nvalid), jnp.int32(idx),
                {k: jnp.asarray(v) for k, v in row.items()},
                None if counts_row is None else jnp.asarray(counts_row),
                self._tab(), self._inj(inject), self._kvt())
        self._obs("prefill", t0, tokens=int(nvalid), fence=self._lengths,
                  slot=int(idx), final=True)

    def _dev_decode(self, active, mask_host=None, fast_width=None):
        self.metrics["decode_dispatches"] += 1
        self.metrics["decode_steps_dispatched"] += 1
        t0 = time.perf_counter()
        self._bcast("decode", active=active,
                    mask=None if mask_host is None else mask_host,
                    fast_width=fast_width)
        with activate_mesh(self.mesh), self._decode_guard():
            args = (self.params, self._cos, self._sin,
                    self._kc, self._vc, self._sampler, self._last_logits,
                    self._lengths, jnp.asarray(active))
            if mask_host is not None:
                variant, fn = "decode_masked", self._decode_fn
                fargs = (*args, jnp.asarray(mask_host))
                fkw = dict(table=self._tab(), kvt=self._kvt())
            elif fast_width:
                variant, fn = f"decode_fast{fast_width}", self._decode_fast_fn
                fargs = args
                fkw = dict(table=self._tab(), kvt=self._kvt(),
                           fast_width=fast_width)
            else:
                variant, fn = "decode", self._decode_nomask_fn
                fargs = args
                fkw = dict(table=self._tab(), kvt=self._kvt())
            n_act = int(np.sum(active))
            B = self.ec.max_slots
            self._sched_pack(variant, fn, fargs, fkw, decode_rows=n_act,
                             rows_used=B, pad_rows=B - n_act, packed=n_act)
            (tokens, logprobs, self._kc, self._vc, self._sampler,
             self._last_logits, self._lengths) = fn(*fargs, **fkw)
        self._obs("decode", t0, tokens=n_act, fence=tokens,
                  fast_width=fast_width or 0,
                  grammar=mask_host is not None)
        return _AsyncFetch((tokens, logprobs))

    def _dev_decode_block(self, active, steps: int, fast_width=None,
                          mask_host=None):
        self.metrics["decode_dispatches"] += 1
        self.metrics["decode_steps_dispatched"] += steps
        t0 = time.perf_counter()
        self._bcast("decode_block", active=active, steps=steps,
                    fast_width=fast_width,
                    mask=None if mask_host is None else mask_host)
        with activate_mesh(self.mesh), self._decode_guard():
            args = (self.params, self._cos, self._sin,
                    self._kc, self._vc, self._sampler, self._last_logits,
                    self._lengths, jnp.asarray(active))
            if mask_host is not None:
                variant = f"decode_block{steps}_masked"
                fn = self._decode_block_mask_fn
                fargs = (*args, jnp.asarray(mask_host))
                fkw = dict(table=self._tab(), kvt=self._kvt(), steps=steps,
                           fast_width=None)
            else:
                variant, fn = f"decode_block{steps}", self._decode_block_fn
                fargs = args
                fkw = dict(table=self._tab(), kvt=self._kvt(), steps=steps,
                           fast_width=fast_width)
            n_act = int(np.sum(active))
            B = self.ec.max_slots
            self._sched_pack(variant, fn, fargs, fkw, decode_rows=n_act,
                             rows_used=B, pad_rows=B - n_act,
                             packed=steps * n_act)
            (tokens, logprobs, self._kc, self._vc, self._sampler,
             self._last_logits, self._lengths) = fn(*fargs, **fkw)
        self._obs("decode_block", t0, tokens=steps * int(np.sum(active)),
                  fence=tokens, steps=steps, fast_width=fast_width or 0,
                  grammar=mask_host is not None)
        return _AsyncFetch((tokens, logprobs))

    def _dev_decode_loop(self, active, remaining, check_eos, fast_width=None,
                         gstate=None):
        """ONE while-loop dispatch covering up to ec.decode_loop decode steps
        with per-slot stop conditions on device (models/llama.py
        build_decode_loop). `remaining` [B] i32 is each slot's token budget
        for THIS dispatch (max_tokens net of in-flight reservations);
        `check_eos` [B] bool gates the EOS-set stop. `gstate` [B] i32 (or
        None) selects the grammar variant: each iteration gathers the
        per-slot mask row from the shared device tables and advances the
        automaton state on device, so table-backed grammar slots ride the
        full loop with NO per-token host round trip (unconstrained slots sit
        in identity row 0 — bit-identical sampling). Steps actually run come
        back with the async fetch — the dispatch-step metric is credited at
        consume time, when the early-exit count is known."""
        self.metrics["decode_dispatches"] += 1
        t0 = time.perf_counter()
        self._bcast("decode_loop", active=active, remaining=remaining,
                    check_eos=check_eos, fast_width=fast_width,
                    gstate=gstate)
        with activate_mesh(self.mesh), self._decode_guard():
            gkw = {}
            if gstate is not None:
                gmasks, gtrans = self._gtab()
                gkw = dict(gstate=jnp.asarray(np.asarray(gstate, np.int32)),
                           gmasks=gmasks, gtrans=gtrans)
            variant = ("loop" + (f"_fast{fast_width}" if fast_width else "")
                       + ("_grammar" if gstate is not None else ""))
            fargs = (self.params, self._cos, self._sin, self._kc, self._vc,
                     self._sampler, self._last_logits, self._lengths,
                     jnp.asarray(active), jnp.asarray(remaining),
                     jnp.asarray(check_eos), self._eos_dev, self._tab())
            fkw = dict(fast_width=fast_width, kvt=self._kvt(), **gkw)
            n_act = int(np.sum(active))
            B = self.ec.max_slots
            self._sched_pack(variant, self._decode_loop_fn, fargs, fkw,
                             decode_rows=n_act, rows_used=B,
                             pad_rows=B - n_act, packed=n_act)
            (toks, lps, n_out, steps, self._kc, self._vc, self._sampler,
             self._last_logits, self._lengths) = self._decode_loop_fn(
                *fargs, **fkw)
        # tokens here is the RESERVED upper bound (actual count rides the
        # fetch); the consume-side "sample" stage records the exact number
        self._obs("decode_loop", t0,
                  tokens=int(np.minimum(np.maximum(remaining, 0),
                                        self.ec.decode_loop).sum()),
                  fence=toks, fast_width=fast_width or 0,
                  grammar=gstate is not None)
        return _AsyncFetch((toks, lps, n_out, steps))

    def _dev_ragged(self, pack):
        """ONE flat-stream dispatch for a mixed tick: every live decode slot
        (one sampled token each) plus packed chunked-prefill windows run a
        single ragged-attention forward. `pack` is the host-built metadata
        (see _ragged_tick); `packed` counts the live token rows for the
        budget-utilization metric."""
        self.metrics["decode_dispatches"] += 1
        self.metrics["decode_steps_dispatched"] += 1
        self.metrics["ragged_dispatches"] = (
            self.metrics.get("ragged_dispatches", 0) + 1)
        self.metrics["ragged_tokens_packed"] = (
            self.metrics.get("ragged_tokens_packed", 0)
            + int(pack["packed"]))
        # non-decode rows actually packed (prefill-chunk tokens): the
        # dispatch-budget tripwire credits these against the per-token
        # budget, so mixed consolidation stays exempt-by-math while
        # decode-heavy single-step ragged streams count at full price
        self.metrics["ragged_prefill_tokens"] = (
            self.metrics.get("ragged_prefill_tokens", 0)
            + int(pack["packed"]) - int(np.sum(pack["is_decode"])))
        self.metrics["budget_utilization"] = (
            self.metrics["ragged_tokens_packed"]
            / max(self.metrics["ragged_dispatches"] * self._ragged_rows, 1))
        t0 = time.perf_counter()
        self._bcast("ragged", **dict(
            pack, inject=self._inj_msg(pack.get("inject"))))
        with activate_mesh(self.mesh), self._decode_guard():
            mask = pack.get("mask")
            variant = ("ragged" + ("_mask" if mask is not None else "")
                       + ("_inj" if pack.get("inject") is not None else ""))
            fargs = (self.params, self._cos, self._sin, self._kc, self._vc,
                     self._sampler, self._last_logits, self._lengths,
                     jnp.asarray(pack["tokens"]),
                     jnp.asarray(pack["decode_slot"]),
                     jnp.asarray(pack["is_decode"]),
                     jnp.asarray(pack["set_len"]),
                     jnp.asarray(pack["logit_set"]),
                     jnp.asarray(pack["logit_rows"]),
                     jnp.asarray(pack["block_seq"]),
                     jnp.asarray(pack["qstart"]), jnp.asarray(pack["qlen"]),
                     jnp.asarray(pack["kvlen"]), self._tab(), self._kvt(),
                     None if mask is None else jnp.asarray(mask),
                     self._inj(pack.get("inject")))
            n_dec = int(np.sum(pack["is_decode"]))
            rows = int(pack.get("rows_used", 0))
            inj = pack.get("inject")
            self._sched_pack(
                variant, self._ragged_fn, fargs, {},
                decode_rows=n_dec,
                prefill_tokens=int(pack["packed"]) - n_dec,
                mm_rows=0 if inj is None else int(np.sum(inj[1])),
                pad_rows=max(rows - int(pack["packed"]), 0),
                rows_used=rows, budget_rows=self._ragged_rows,
                packed=int(pack["packed"]))
            (tokens, logprobs, self._kc, self._vc, self._sampler,
             self._last_logits, self._lengths) = self._ragged_fn(*fargs)
        self._obs("ragged", t0, tokens=int(pack["packed"]), fence=tokens,
                  grammar=pack.get("mask") is not None)
        return _AsyncFetch((tokens, logprobs))

    def _dev_ragged_loop(self, pack, remaining, check_eos, prefill_pending,
                         gstate=None):
        """ONE fused multi-step ragged dispatch (ISSUE 16): the mixed pack
        runs as iteration 0, then up to ragged_loop_steps-1 dense decode
        iterations continue every live decode slot on device
        (models/llama.build_ragged_loop). `remaining`/`check_eos` [B] are
        the PR 6 per-slot stop inputs; `prefill_pending` (traced bool) makes
        the loop collapse to a single iteration when the host has prefill or
        admission work, so TTFT stays at single-step ragged levels. Steps
        actually run and the exit code ride the async fetch — step and
        exit-reason metrics are credited at consume time."""
        self.metrics["decode_dispatches"] += 1
        self.metrics["ragged_dispatches"] = (
            self.metrics.get("ragged_dispatches", 0) + 1)
        self.metrics["ragged_tokens_packed"] = (
            self.metrics.get("ragged_tokens_packed", 0)
            + int(pack["packed"]))
        n_dec = int(np.sum(pack["is_decode"]))
        self.metrics["ragged_prefill_tokens"] = (
            self.metrics.get("ragged_prefill_tokens", 0)
            + int(pack["packed"]) - n_dec)
        self.metrics["budget_utilization"] = (
            self.metrics["ragged_tokens_packed"]
            / max(self.metrics["ragged_dispatches"] * self._ragged_rows, 1))
        t0 = time.perf_counter()
        self._bcast("ragged_loop", remaining=remaining, check_eos=check_eos,
                    prefill_pending=bool(prefill_pending), gstate=gstate,
                    **pack)
        with activate_mesh(self.mesh), self._decode_guard():
            gkw = {}
            if gstate is not None:
                gmasks, gtrans = self._gtab()
                gkw = dict(gstate=jnp.asarray(np.asarray(gstate, np.int32)),
                           gmasks=gmasks, gtrans=gtrans)
            variant = ("rloop_pack"
                       + ("_grammar" if gstate is not None else ""))
            dev_pack = dict(
                tokens=jnp.asarray(pack["tokens"]),
                decode_slot=jnp.asarray(pack["decode_slot"]),
                set_len=jnp.asarray(pack["set_len"]),
                logit_set=jnp.asarray(pack["logit_set"]),
                logit_rows=jnp.asarray(pack["logit_rows"]),
                block_seq=jnp.asarray(pack["block_seq"]),
                qstart=jnp.asarray(pack["qstart"]),
                qlen=jnp.asarray(pack["qlen"]),
                kvlen=jnp.asarray(pack["kvlen"]))
            fargs = (self.params, self._cos, self._sin, self._kc, self._vc,
                     self._sampler, self._last_logits, self._lengths,
                     jnp.asarray(pack["is_decode"]),
                     jnp.asarray(remaining), jnp.asarray(check_eos),
                     self._eos_dev, jnp.asarray(bool(prefill_pending)))
            fkw = dict(pack=dev_pack, table=self._tab(), kvt=self._kvt(),
                       fast_width=None, has_pack=True, **gkw)
            rows = int(pack.get("rows_used", 0))
            self._sched_pack(
                variant, self._ragged_loop_fn, fargs, fkw,
                decode_rows=n_dec,
                prefill_tokens=int(pack["packed"]) - n_dec,
                pad_rows=max(rows - int(pack["packed"]), 0),
                rows_used=rows, budget_rows=self._ragged_rows,
                packed=int(pack["packed"]))
            (toks, lps, n_out, steps, code, self._kc, self._vc,
             self._sampler, self._last_logits,
             self._lengths) = self._ragged_loop_fn(*fargs, **fkw)
        self._obs("ragged_loop", t0, tokens=int(pack["packed"]), fence=toks,
                  grammar=gstate is not None)
        return _AsyncFetch((toks, lps, n_out, steps, code))

    def _dev_rloop_decode(self, active, remaining, check_eos,
                          fast_width=None, gstate=None):
        """The fused ragged loop's pack-free variant: a pure-decode tick on
        a ragged engine. Same stop conditions and grammar-table handling as
        _dev_decode_loop, plus the first-finish early exit — one finished
        slot returns control to the host so the freed slot admits
        immediately instead of waiting out the remaining steps."""
        self.metrics["decode_dispatches"] += 1
        t0 = time.perf_counter()
        self._bcast("rloop_decode", active=active, remaining=remaining,
                    check_eos=check_eos, fast_width=fast_width,
                    gstate=gstate)
        with activate_mesh(self.mesh), self._decode_guard():
            gkw = {}
            if gstate is not None:
                gmasks, gtrans = self._gtab()
                gkw = dict(gstate=jnp.asarray(np.asarray(gstate, np.int32)),
                           gmasks=gmasks, gtrans=gtrans)
            variant = ("rloop" + (f"_fast{fast_width}" if fast_width else "")
                       + ("_grammar" if gstate is not None else ""))
            fargs = (self.params, self._cos, self._sin, self._kc, self._vc,
                     self._sampler, self._last_logits, self._lengths,
                     jnp.asarray(active), jnp.asarray(remaining),
                     jnp.asarray(check_eos), self._eos_dev,
                     jnp.asarray(False))
            fkw = dict(pack=None, table=self._tab(), kvt=self._kvt(),
                       fast_width=fast_width, has_pack=False, **gkw)
            n_act = int(np.sum(active))
            B = self.ec.max_slots
            self._sched_pack(variant, self._ragged_loop_fn, fargs, fkw,
                             decode_rows=n_act, rows_used=B,
                             pad_rows=B - n_act, packed=n_act)
            (toks, lps, n_out, steps, code, self._kc, self._vc,
             self._sampler, self._last_logits,
             self._lengths) = self._ragged_loop_fn(*fargs, **fkw)
        self._obs("rloop_decode", t0,
                  tokens=int(np.minimum(np.maximum(remaining, 0),
                                        self.ec.ragged_loop_steps).sum()),
                  fence=toks, fast_width=fast_width or 0,
                  grammar=gstate is not None)
        return _AsyncFetch((toks, lps, n_out, steps, code))

    def _dev_spec_ragged(self, pack):
        """ONE spec-as-ragged dispatch: gamma draft steps + a ragged target
        verify covering every verifying slot's (gamma+1)-row window PLUS any
        packed prefill chunks (and their multimodal inject rows) — the
        one-program-for-every-tenant tick of a draft+ragged engine. Counted
        as a ragged dispatch (exempt from the per-token dispatch budget the
        same way, and for the same reason: it replaces N programs with 1)."""
        self.metrics["decode_dispatches"] += 1
        self.metrics["decode_steps_dispatched"] += self.ec.gamma + 1
        self.metrics["ragged_dispatches"] = (
            self.metrics.get("ragged_dispatches", 0) + 1)
        # spec dispatches keep the dispatch-budget exemption (gamma-fused by
        # construction; acceptance is gated separately) — the tripwire
        # subtracts this counter, not ragged_dispatches
        self.metrics["spec_ragged_dispatches"] = (
            self.metrics.get("spec_ragged_dispatches", 0) + 1)
        self.metrics["ragged_tokens_packed"] = (
            self.metrics.get("ragged_tokens_packed", 0)
            + int(pack["packed"]))
        self.metrics["budget_utilization"] = (
            self.metrics["ragged_tokens_packed"]
            / max(self.metrics["ragged_dispatches"] * self._ragged_rows, 1))
        t0 = time.perf_counter()
        self._bcast("spec_ragged", **dict(
            pack, inject=self._inj_msg(pack.get("inject"))))
        with activate_mesh(self.mesh), self._decode_guard():
            gkw = {}
            gstate = pack.get("gstate")
            if gstate is not None:
                gmasks, gtrans = self._gtab()
                gkw = dict(gstate=jnp.asarray(np.asarray(gstate, np.int32)),
                           gmasks=gmasks, gtrans=gtrans)
            variant = ("spec_ragged"
                       + ("_grammar" if gstate is not None else "")
                       + ("_inj" if pack.get("inject") is not None else ""))
            fargs = (self.params, self._draft[1], self._cos, self._sin,
                     self._cos_d, self._sin_d, self._kc, self._vc,
                     self._kcd, self._vcd, self._sampler, self._last_logits,
                     self._lengths, self._next_tokens,
                     jnp.asarray(pack["verify"]),
                     jnp.asarray(pack["tokens"]),
                     jnp.asarray(pack["spec_rows"]),
                     jnp.asarray(pack["set_len"]),
                     jnp.asarray(pack["logit_set"]),
                     jnp.asarray(pack["logit_rows"]),
                     jnp.asarray(pack["block_seq"]),
                     jnp.asarray(pack["qstart"]), jnp.asarray(pack["qlen"]),
                     jnp.asarray(pack["kvlen"]), self._tab())
            fkw = dict(kvt=self._kvt(),
                       inject=self._inj(pack.get("inject")), **gkw)
            n_win = int(np.sum(pack["verify"]))
            win_toks = n_win * (self.ec.gamma + 1)
            rows = int(pack.get("rows_used", 0))
            inj = pack.get("inject")
            self._sched_pack(
                variant, self._spec_ragged_fn, fargs, fkw,
                spec_windows=n_win,
                prefill_tokens=int(pack["packed"]) - win_toks,
                mm_rows=0 if inj is None else int(np.sum(inj[1])),
                pad_rows=max(rows - int(pack["packed"]), 0),
                rows_used=rows, budget_rows=self._ragged_rows,
                packed=int(pack["packed"]))
            (tokens_out, n_out, logprobs_out, self._next_tokens,
             self._kc, self._vc, self._kcd, self._vcd, self._sampler,
             self._last_logits, self._lengths,
             n_extra) = self._spec_ragged_fn(*fargs, **fkw)
        self._obs("spec_ragged", t0, tokens=int(pack["packed"]),
                  fence=tokens_out, grammar=pack.get("gstate") is not None)
        return _AsyncFetch((tokens_out, n_out, logprobs_out, n_extra))

    def _dev_demote(self, pb: int, ci: int):
        """Copy hot physical block `pb` into cold-pool index `ci` (int8,
        sub-channel scales). Enqueued AFTER any in-flight decode dispatch on
        the same stream, so the copy reads the block's final hot content."""
        t0 = time.perf_counter()
        self._bcast("demote", pb=pb, ci=ci)
        with activate_mesh(self.mesh):
            self._ck, self._cv = self._demote_fn(
                self._kc, self._vc, self._ck, self._cv,
                jnp.int32(pb), jnp.int32(ci))
        self._obs("demote", t0, tokens=128, block=int(pb))

    # ------------------------------------------------- host KV tier (ISSUE 17)

    def _spill_block(self, pb: int, h: bytes | None = None,
                     group: bytes | None = None):
        """Spill physical block `pb` to the host tier before its content
        dies (free, rewrite, or ring overwrite). The D2H copy starts NOW
        (copy_to_host_async) and is enqueued on the device stream before
        any later dispatch can rewrite the block, so finalizing it lazily
        in _host_drain is race-free — the same ordering argument as
        _dev_demote and the kvtier ring's slack blocks."""
        if self._kvhost is None:
            return
        if h is None:
            h = self._block_hash_of.get(pb)
        gkey = group if group is not None else self._spill_group
        # begin_spill claims the hash AND pins the group's resident chain
        # until _host_drain lands it — an LRU eviction racing the async
        # copy can no longer free the chain head under its in-flight tail
        if h is None or not self._kvhost.begin_spill(h, group=gkey):
            return
        t0 = time.perf_counter()
        with activate_mesh(self.mesh):
            arrs = self._spill_fn(self._kc, self._vc, jnp.int32(pb))
        self._host_pending.append((h, gkey, _AsyncFetch(arrs)))
        self.metrics["kv_host_spills"] += 1
        if self._sched is not None:
            self._sched.reason("kv_host_spill", block=int(pb))
        self._obs("host_spill", t0, tokens=128, block=int(pb))

    def _host_drain(self):
        """Land every in-flight spill in the HostKVPool. The copies were
        started at spill time, so wait() here is normally a no-op fetch of
        already-arrived host buffers — not a device stall."""
        if not self._host_pending:
            return
        from localai_tpu.engine.kvhost import HostKVBlock

        pending, self._host_pending = self._host_pending, []
        evicted = 0
        for h, group, fetch in pending:
            kq, ks, vq, vs = fetch.wait()
            evicted += self._kvhost.end_spill(
                h, HostKVBlock(kq=kq, ks=ks, vq=vq, vs=vs))
        if evicted:
            if self._sched is not None:
                self._sched.reason("kv_host_evict_budget", blocks=evicted)
            if self._flightrec is not None:
                self._flightrec.record_event("kv_host_evict_budget",
                                             blocks=evicted)
        self._host_note()

    def _host_note(self):
        """Refresh the kv_host_* GetMetrics keys from the pool (the pool
        may be shared across engines — restart legs keep its history)."""
        st = self._kvhost.stats()
        self.metrics["kv_host_blocks"] = st["blocks"]
        self.metrics["kv_host_bytes"] = st["bytes"]
        self.metrics["kv_host_bytes_peak"] = st["peak_bytes"]
        self.metrics["kv_host_spills"] = st["spills"]
        self.metrics["kv_host_hits"] = st["hits"]
        self.metrics["kv_host_evictions"] = st["evictions"]

    def _readmit_block(self, pb: int, blk):
        """Write one host-tier block into physical page `pb` (H2D). The
        jnp.asarray uploads are explicit sanctioned transfers on the
        admission path — the decode transfer guard wraps decode dispatches
        only, and the uploads overlap the uncovered suffix's prefill
        chunks (they are enqueued first on the same stream)."""
        t0 = time.perf_counter()
        with activate_mesh(self.mesh):
            self._kc, self._vc = self._readmit_fn(
                self._kc, self._vc,
                jnp.asarray(blk.kq), jnp.asarray(blk.ks),
                jnp.asarray(blk.vq), jnp.asarray(blk.vs), jnp.int32(pb))
        self._obs("host_readmit", t0, tokens=128, block=int(pb))

    def _host_extend(self, slot: int, req: GenRequest, shared, shtok: int):
        """Extend a device prefix-cache match with host-tier blocks.

        Called from _admit_one right after _match_prefix_blocks: for each
        chain hash past the device hit, a host hit re-admits into a fresh
        physical page (registered in the hash index, so the NEXT tenant
        finds it on device); the first miss on both tiers ends the run —
        everything after it re-prefills. Returns the updated
        (shared, shtok); readmitted blocks are ref'd like matched ones."""
        if self._kvhost is None:
            return shared, shtok
        self._host_drain()   # a block spilled this tick is admissible now
        from localai_tpu.ops.paged import BLOCK

        limit = self.ec.max_context - 2 - self._ctx_reserve
        nfull = min(len(req.prompt_ids) - 1, limit - 1) // BLOCK
        base = len(shared) if shared is not None else 0
        if nfull <= base:
            return shared, shtok
        chain = self._chain_hashes(req.prompt_ids[:nfull * BLOCK])
        added: list[int] = []
        for vb in range(base, nfull):
            blk = self._kvhost.get(chain[vb])
            if blk is None:
                break
            got = self._take_blocks(1, keep_slot=slot)
            if got is None:
                break
            pb = got[0]
            self._readmit_block(pb, blk)
            # register: this page now holds the chain's content on device
            self._drop_hash(pb)
            self._hash_index[chain[vb]] = pb
            self._block_hash_of[pb] = chain[vb]
            added.append(pb)
            if self._sched is not None:
                self._sched.reason("kv_host_readmit", slot=int(slot),
                                   block=int(pb))
        if added:
            shared = (list(shared) if shared is not None else []) + added
            shtok = len(shared) * BLOCK
            if self._flightrec is not None:
                self._flightrec.record_event(
                    "kv_host_readmit", slot=int(slot),
                    blocks=len(added), covered_tokens=int(shtok))
        elif nfull > base and self._sched is not None:
            # both tiers missed at least one full prefix block: the
            # uncovered prefix pays full re-prefill
            self._sched.reason("kv_host_miss_reprefill",
                               blocks=int(nfull - base))
        self._host_note()
        return shared, shtok

    def kvhost_snapshot(self) -> dict:
        """Host-tier stats for GetTrace/debug surfaces ({} when off)."""
        if self._kvhost is None:
            return {}
        st = self._kvhost.stats()
        st["pending"] = len(self._host_pending)
        return st

    def _dev_install(self, idx, row, counts_row):
        """Sampler-row install for a ragged final prefill chunk (the dense
        path installs inside _extend_final; the ragged program defers it
        here so its own signature stays row-structure-free)."""
        t0 = time.perf_counter()
        self._bcast("install", idx=idx,
                    row={k: np.asarray(v) for k, v in row.items()},
                    counts_row=counts_row)
        with activate_mesh(self.mesh):
            self._sampler = self._install_fn(
                self._sampler, jnp.int32(idx),
                {k: jnp.asarray(v) for k, v in row.items()},
                None if counts_row is None else jnp.asarray(counts_row))
        self._obs("install", t0, slot=int(idx))

    def _dev_shift(self, idx):
        t0 = time.perf_counter()
        self._bcast("shift", idx=idx)
        with activate_mesh(self.mesh):
            if self._paged:
                # rotate K's tail blocks in place, then permute the table
                # row host-side: sink blocks stay, discarded blocks
                # re-append as fresh tail capacity (reservation unchanged)
                self._kc, self._lengths = self._shift_fn(
                    self._kc, self._lengths,
                    jnp.asarray(self._table[idx]), jnp.int32(idx))
                blocks = self._slot_blocks[idx]
                kb, db = self._shift_keepb, self._shift_discb
                if len(blocks) > kb + db:   # shift only fires at the cap,
                    # where the reservation spans the full context — the
                    # guard covers degenerate tiny-context configs
                    newb = (blocks[:kb] + blocks[kb + db:]
                            + blocks[kb:kb + db])
                    self._slot_blocks[idx] = newb
                    self._table[idx, :len(newb)] = newb
            else:
                self._kc, self._vc, self._lengths = self._shift_fn(
                    self._kc, self._vc, self._lengths, jnp.int32(idx))
        self._obs("shift", t0, fence=self._lengths, slot=int(idx))

    def _dev_draft_ingest(self, buf, pos, idx):
        self._bcast("draft_ingest", buf=buf, pos=pos, idx=idx)
        with activate_mesh(self.mesh):
            self._kcd, self._vcd = self._draft_ingest_fn(
                self._draft[1], self._cos_d, self._sin_d, self._kcd,
                self._vcd, jnp.asarray(buf), jnp.int32(pos), jnp.int32(idx))

    def _dev_spec_admit_tail(self, idx, mask=None):
        if mask is None:
            s = self._slots[idx]
            if s is not None and s.matcher is not None:
                # grammar slot: the admission token samples under the start
                # (or resumed) state's mask, same as every decode token
                mask = self._mask_host[idx:idx + 1].copy()
        self._bcast("spec_admit_tail", idx=idx, mask=mask)
        with activate_mesh(self.mesh):
            if mask is not None:
                tok, lp, self._sampler = self._spec_admit_tail_fn(
                    self._sampler, self._last_logits, jnp.int32(idx),
                    jnp.asarray(mask))
            else:
                tok, lp, self._sampler = self._spec_admit_tail_fn(
                    self._sampler, self._last_logits, jnp.int32(idx))
            self._next_tokens = self._next_tokens.at[idx].set(tok)
        # lint: allow(host-sync-cast) — spec invariant: the admission-sampled
        # first token must be emitted NOW (one sync per request, not per step)
        return int(tok), float(lp)

    def _dev_spec_decode(self, active):
        self.metrics["decode_dispatches"] += 1
        # one spec dispatch fuses gamma draft steps + the verify pass
        self.metrics["decode_steps_dispatched"] += self.ec.gamma + 1
        t0 = time.perf_counter()
        self._bcast("spec", active=active)
        with activate_mesh(self.mesh):
            fargs = (self.params, self._draft[1], self._cos, self._sin,
                     self._cos_d, self._sin_d, self._kc, self._vc,
                     self._kcd, self._vcd, self._sampler, self._lengths,
                     self._next_tokens, jnp.asarray(active), self._tab())
            n_act = int(np.sum(active))
            B = self.ec.max_slots
            if self._sched is not None:
                # dense spec is a non-ragged decode dispatch: it needs its
                # dispatch-category code for the fallback-sum invariant
                self._sched.reason("spec_dense")
            self._sched_pack("spec", self._spec_fn, fargs, {},
                             spec_windows=n_act, rows_used=B,
                             pad_rows=B - n_act,
                             packed=n_act * (self.ec.gamma + 1))
            (tokens_out, n_out, logprobs_out, self._next_tokens,
             self._kc, self._vc, self._kcd, self._vcd, self._sampler,
             self._lengths, n_extra) = self._spec_fn(*fargs)
        self._obs("spec_decode", t0,
                  tokens=(self.ec.gamma + 1) * int(np.sum(active)),
                  fence=tokens_out)
        return _AsyncFetch((tokens_out, n_out, logprobs_out, n_extra))

    def follow(self, channel) -> None:
        """Follower-rank loop (multi-host, process_index > 0): replay the
        rank-0 engine's device dispatches against this process's shards of
        the same global arrays. Blocks until rank 0 sends `stop` or the
        channel drops."""
        while True:
            try:
                op, kw = channel.recv()
            except (ConnectionError, EOFError):
                return
            if op == "stop":
                return
            try:
                self._follow_op(op, kw)
            except Exception:
                # the same fatal device error rank 0 just hit: survive it so
                # the upcoming 'reset' replay can rebuild this rank's state —
                # dying here would leave rank 0's restart hanging on
                # collectives this rank never joins
                import traceback

                traceback.print_exc()

    def _follow_op(self, op: str, kw: dict) -> None:
        if op == "admit":
            self._dev_admit(kw["ids"], kw["n"], kw["slot"], kw["row"],
                            kw["counts_row"])
        elif op == "admit_many":
            self._dev_admit_many(kw["ids"], kw["lens"], kw["slots"],
                                 kw["rows"], kw["counts_rows"],
                                 self._inj_of(kw.get("inject")))
        elif op == "extend_mid":
            self._dev_extend_mid(kw["buf"], kw["pos"], kw["idx"],
                                 self._inj_of(kw.get("inject")))
        elif op == "extend_final":
            self._dev_extend_final(kw["buf"], kw["pos"], kw["nvalid"],
                                   kw["idx"], kw["row"], kw["counts_row"],
                                   self._inj_of(kw.get("inject")))
        elif op == "decode":
            self._dev_decode(kw["active"], kw["mask"],
                             kw.get("fast_width"))
        elif op == "decode_block":
            self._dev_decode_block(kw["active"], int(kw["steps"]),
                                   kw.get("fast_width"), kw.get("mask"))
        elif op == "decode_loop":
            self._dev_decode_loop(kw["active"], kw["remaining"],
                                  kw["check_eos"], kw.get("fast_width"),
                                  kw.get("gstate"))
        elif op == "ragged":
            self._dev_ragged(dict(kw, inject=self._inj_of(kw.get("inject"))))
        elif op == "ragged_loop":
            kw = dict(kw)
            self._dev_ragged_loop(kw, kw.pop("remaining"),
                                  kw.pop("check_eos"),
                                  kw.pop("prefill_pending"),
                                  gstate=kw.pop("gstate"))
        elif op == "rloop_decode":
            self._dev_rloop_decode(kw["active"], kw["remaining"],
                                   kw["check_eos"], kw.get("fast_width"),
                                   kw.get("gstate"))
        elif op == "spec_ragged":
            self._dev_spec_ragged(
                dict(kw, inject=self._inj_of(kw.get("inject"))))
        elif op == "gtable":
            self._dev_gtable(int(kw["base"]), kw["masks"], kw["trans"])
        elif op == "install":
            self._dev_install(kw["idx"], kw["row"], kw["counts_row"])
        elif op == "demote":
            self._dev_demote(kw["pb"], kw["ci"])
        elif op == "shift":
            self._dev_shift(kw["idx"])
        elif op == "draft_ingest":
            self._dev_draft_ingest(kw["buf"], kw["pos"], kw["idx"])
        elif op == "spec_admit_tail":
            self._dev_spec_admit_tail(kw["idx"], kw.get("mask"))
        elif op == "spec":
            self._dev_spec_decode(kw["active"])
        elif op == "reset":
            # rank 0 is self-restarting after a fatal step error
            self._init_device_state()

    # ------------------------------------------------------------ submission

    def submit(self, req: GenRequest) -> tuple[int, queue.Queue]:
        """Enqueue a request; returns (request_id, output queue of StepOutput)."""
        if self._dead:
            raise RuntimeError("engine loop has terminated; no new requests")
        if len(req.prompt_ids) == 0:
            raise ValueError("empty prompt")
        limit = self.ec.max_context - 2 - self._ctx_reserve
        if len(req.prompt_ids) > limit:
            raise ValueError(
                f"prompt length {len(req.prompt_ids)} exceeds {limit} "
                f"(max_context minus the decode margin); longer prompts "
                f"need a larger context window"
            )
        if req.grammar and self._draft is not None:
            if not self._ragged:
                raise ValueError(
                    "grammar-constrained decoding with a draft model needs "
                    "ragged continuous batching (the spec-as-ragged verify "
                    "threads the device grammar tables; the dense spec "
                    "program has no grammar lane)")
            # the verify window masks come from the DEVICE tables (the host
            # cannot resync inside the fused draft+verify program), so the
            # grammar must compile to a bounded automaton that fits the cap
            if not self._gtab_cap or self._compile_grammar(
                    req.grammar).table(self._gtab_cap) is None:
                raise ValueError(
                    "grammar automaton exceeds grammar_table_states; "
                    "speculative verify needs the precompiled device "
                    "grammar table (raise grammar_table_states or drop "
                    "the draft model for this grammar)")
        if req.mm_embeds is not None:
            if self._draft is not None and not self._ragged:
                raise ValueError(
                    "multimodal prompts with a draft model need ragged "
                    "continuous batching (feature rows pack into the flat "
                    "stream; the bucketed dense prefill has no draft-side "
                    "path). The draft itself ingests token ids only.")
            emb = np.asarray(req.mm_embeds, np.float32)
            pos = np.asarray(req.mm_positions, np.int64)
            if emb.ndim != 2 or emb.shape[1] != self.cfg.hidden_size:
                raise ValueError(
                    f"mm_embeds must be [K, {self.cfg.hidden_size}], got "
                    f"{emb.shape}")
            if pos.shape != (emb.shape[0],):
                raise ValueError("mm_positions must match mm_embeds rows")
            if len(pos) and (pos.min() < 0
                             or pos.max() >= len(req.prompt_ids)):
                raise ValueError("mm_positions outside the prompt")
            if len(pos) > 1 and (np.diff(pos) <= 0).any():
                raise ValueError("mm_positions must be strictly increasing")
            req.mm_embeds, req.mm_positions = emb, pos
        if req.context_shift and self._draft is not None:
            raise ValueError(
                "context_shift is not supported with a draft model "
                "(the draft cache would need shifting too)")
        if req.context_shift and self._paged and not self._shift_ok:
            raise ValueError(
                "context_shift with paged KV needs max_context spanning "
                "more than keep+discard blocks (128-token granularity); "
                "raise max_context or use a dense cache")
        if req.context_shift and self._tiered:
            raise ValueError(
                "context_shift is not supported under a sink_window "
                "kv_policy (the ring geometry already bounds residency; "
                "long sequences decode in place up to max_context)")
        if req.kv_policy:
            # reject malformed/oversized policies NOW (gRPC
            # INVALID_ARGUMENT) instead of failing in-band at admission
            from localai_tpu.engine import kvtier

            kvtier.resolve_policy(req.kv_policy, self._kv_policy)
        if self._paged and self._blocks_for(req) > self.ec.kv_pages - 1:
            raise ValueError(
                f"request needs {self._blocks_for(req)} KV blocks under "
                f"kv_policy {self._req_policy(req).describe()} "
                f"(prompt {len(req.prompt_ids)} + max_tokens "
                f"{req.max_tokens}) but the pool has {self.ec.kv_pages - 1}; "
                f"raise kv_pages or lower max_tokens")
        V = self.cfg.vocab_size
        if any(not (0 <= t < V) for t in req.prompt_ids):
            raise ValueError(f"prompt token id outside [0, {V})")
        if req.grammar:
            # compile now (cached) so a malformed GBNF rejects THIS call with
            # ValueError → gRPC INVALID_ARGUMENT, instead of surfacing later
            # as an in-band admission error
            self._compile_grammar(req.grammar)
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._live.add(rid)
        out: queue.Queue = queue.Queue()
        req.queued_t = time.monotonic()
        self._queue.put((rid, req, out))
        self._wake.set()
        return rid, out

    def cancel(self, rid: int):
        """Mark a submitted request for eviction: its slot finishes with
        reason "cancelled" at the next token (queued requests terminate at
        admission). Safe from any thread; unknown/finished rids are no-ops —
        gRPC termination callbacks fire on NORMAL completion too."""
        with self._lock:
            if rid in self._live:
                self._cancelled.add(rid)
        self._wake.set()

    def _finish_rid(self, rid: int):
        """A terminal StepOutput went out for `rid` — drop its bookkeeping."""
        with self._lock:
            self._live.discard(rid)
            self._cancelled.discard(rid)

    # ------------------------------------------------------------ the loop

    def _bucket(self, n: int) -> int:
        for b in self._small_buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt too long for single-shot prefill: {n}")

    def _compile_grammar(self, grammar: str):
        """Compile (or fetch cached) GBNF → CompiledGrammar. Called from gRPC
        handler threads (submit-time validation) AND the engine loop thread.
        Only the lazy GrammarCache INIT is held under _grammar_lock (it walks
        the whole vocab once); the compile itself — which may include a slow
        device-table precompilation — runs outside any engine lock. The
        cache is internally thread-safe (functions/matcher.GrammarCache:
        double-checked insert), so a slow grammar compile never blocks other
        handler threads' cache hits or the engine loop."""
        cache = self._grammar_cache
        if cache is None:
            with self._grammar_lock:
                if self._grammar_cache is None:
                    if self.tok is None:
                        raise ValueError(
                            "grammar constraint requires a tokenizer")
                    from localai_tpu.functions.matcher import GrammarCache

                    self._grammar_cache = GrammarCache(self.tok)
                cache = self._grammar_cache
        return cache.get(grammar)

    def _matcher_for(self, grammar: str):
        return self._compile_grammar(grammar).state()

    def _admit_one(self, rid: int, req: GenRequest, out: queue.Queue,
                   batch: list | None = None) -> bool:
        # Host-side per-request failures (bad GBNF, missing tokenizer) must
        # reject THIS request only — never kill the loop, which would strand
        # every other in-flight stream (the reference rejects a bad grammar
        # per-request in the sampler). Device failures below are engine-fatal
        # on purpose: donation makes the state unrecoverable.
        try:
            matcher = self._matcher_for(req.grammar) if req.grammar else None
            # device grammar tables: installed once per grammar (BFS +
            # upload happen off the decode hot path); gbase None = overflow
            # → the slot keeps per-token host masks (and bars the loop)
            gbase = (self._grammar_table_entry(req.grammar)
                     if req.grammar else None)
            if req.grammar and gbase is None and self._draft is not None:
                # shared-capacity overflow after submit's buildability check
                # (other grammars filled the table): reject per-request —
                # spec verify cannot host-resync
                raise ValueError("grammar table capacity exhausted")
            n = len(req.prompt_ids)
            chunked = n > self._small_max
            bucket = None if chunked else self._bucket(n)
            pol = self._req_policy(req) if self._tiered else None
        except Exception:
            self._finish_rid(rid)
            out.put(StepOutput(
                request_id=rid, text="", token_id=-1,
                logprob=0.0, finished=True, finish_reason="error",
                prompt_tokens=len(req.prompt_ids),
            ))
            return False
        mm = req.mm_embeds is not None
        if self._ragged:
            # ragged admissions are always chunked: admission itself becomes
            # host-only slot bookkeeping, and the prompt is packed unpadded
            # into mixed ragged ticks — no bucket padding, no admission-time
            # device dispatch. Multimodal prompts pack too: their feature
            # rows ride the flat stream as per-row embedding overrides
            # (ragged_forward's inject), so mm prompts no longer force the
            # bucketed dense prefill
            chunked, bucket = True, None
        if self._tiered and not pol.windowed:
            # admission-time policy demotion: a full-policy request that
            # cannot fit the compact table (its identity mapping would write
            # past the resident columns), or that lands while the free pool
            # runs low (windowed slots return ALL their blocks at release
            # instead of retaining a warm prefix), rides the engine's window
            # instead of being rejected
            from localai_tpu.ops.paged import blocks_needed

            margin = 2 * self.ec.decode_block + 1
            base = blocks_needed(min(n + max(req.max_tokens, 0) + margin,
                                     self.ec.max_context))
            if base > self._maxb or base > len(self._kv_free):
                pol = self._kv_policy
                self.metrics["kv_policy_demotions"] += 1
                if self._sched is not None:
                    self._sched.reason("kv_policy_demotion", rid=rid,
                                       blocks_needed=int(base))
        # multimodal: id-level prefix reuse would match the repeated image
        # token while the injected features differ — no slot or disk reuse
        slot, lcp = self._pick_slot([] if mm else req.prompt_ids)
        if self._paged:
            shared = None
            if req.context_shift:
                # a shift rotates this slot's pages IN PLACE — never run it
                # over pages other tenants read: no borrowed pages, and
                # lcp=0 makes _alloc_slot's copy-on-write pass swap every
                # externally-shared retained block before the cold prefill
                lcp = 0
            elif self.ec.prompt_cache and self._draft is None and not mm:
                # block-level prefix cache: another tenant's pages beat the
                # slot-retained token match when they cover more prefix
                shared, shtok = self._match_prefix_blocks(req.prompt_ids)
                if self._kvhost is not None:
                    # device miss → host tier: re-admit spilled blocks H2D
                    # before falling back to re-prefill (ISSUE 17). The
                    # uploads enqueue ahead of the suffix's prefill chunks,
                    # so the DMA hides under prefill compute
                    shared, shtok = self._host_extend(
                        slot, req, shared, shtok)
                if shtok > lcp:
                    lcp = shtok
                else:
                    self._unref_blocks(shared)
                    shared = None
            if pol is not None and pol.windowed and lcp:
                # a windowed slot may borrow/retain prefix pages ONLY for
                # whole sink blocks: everything past the sinks lives in
                # ring columns whose position mapping is per-tenant, so
                # those cached blocks are re-prefilled (block-granular
                # recompute — the prefix-cache-shared case)
                from localai_tpu.ops.paged import BLOCK

                keep = min(lcp // BLOCK, self._kv_policy.sink_blocks)
                self.metrics["kv_recomputes"] += max(
                    0, lcp // BLOCK - keep)
                if shared is not None:
                    if keep < len(shared):
                        self._unref_blocks(shared[keep:])
                        shared = shared[:keep]
                    if not shared:
                        shared = None
                lcp = keep * BLOCK
            eff = self._alloc_slot(slot, req, shared=shared, lcp=lcp)
            if eff is None:
                # pool exhausted even after reclaim: defer (FIFO) until
                # blocks free — the caller re-attempts on later ticks
                self._free.append(slot)
                self._deferred = (rid, req, out)
                if self._sched is not None:
                    self._sched.reason("kv_pool_exhausted", rid=rid)
                return None
            lcp = eff
            if self._tiered:
                # per-slot tier geometry: the RESIDENCY (sb/rw) always uses
                # the ENGINE window (the ring was sized for it); the request
                # policy narrows only the attention masks (sinks/window
                # token counts), so shrunken per-request windows share the
                # same table layout and compiled program
                if pol.windowed:
                    self._kv_sb[slot] = self._kv_policy.sink_blocks
                    self._kv_rw[slot] = self._kv_ring
                    self._kv_sinks[slot] = pol.sinks
                    self._kv_window[slot] = pol.window
                else:
                    self._kv_sb[slot] = self._maxb
                    self._kv_rw[slot] = 1
                    self._kv_sinks[slot] = self.ec.max_context
                    self._kv_window[slot] = self.ec.max_context
                self._slot_policy[slot] = pol
                self._demote_next[slot] = self._kv_policy.sink_blocks
                if self._cold:
                    for ci in self._slot_cold[slot]:
                        self._cold_free.append(ci)
                    self._slot_cold[slot] = []
                    self._cold_table[slot, :] = 0
                self._note_pool()
        self._slot_kv_tokens[slot] = []
        disk_prefix = 0
        if not lcp and req.prompt_cache_path and not mm:
            lcp = disk_prefix = self._load_prompt_cache(slot, req)
        if lcp:
            # shared prefix already in this slot's cache: prefill only the
            # suffix via the chunked-extend path (start offset = lcp)
            chunked = True
            self.metrics["prompt_cache_hits"] += 1
            self.metrics["prompt_tokens_reused"] += lcp
        if req.resume is not None:
            # resume outcome attribution (ISSUE 19): every full prefix
            # block covered by the device/host caches = fast resume; any
            # uncovered full block pays re-prefill of prompt+emitted
            if self._paged:
                from localai_tpu.ops.paged import BLOCK

                full = (min(n - 1, self.ec.max_context - 2
                            - self._ctx_reserve - 1) // BLOCK) * BLOCK
                fast = full > 0 and lcp >= full
            else:
                fast = lcp > 0
            self.metrics["resume_readmits" if fast
                         else "resume_reprefills"] += 1
            if self._sched is not None:
                self._sched.reason(
                    "resume_readmit" if fast else "resume_reprefill",
                    rid=rid, covered=int(lcp), prompt=int(n))
            if self._flightrec is not None:
                self._flightrec.record_event(
                    "resume", rid=int(rid), covered_tokens=int(lcp),
                    reprefill_tokens=int(n - lcp),
                    emitted=int(req.resume.get("emitted", 0)),
                    outcome="readmit" if fast else "reprefill")
        # token_counts/logit_bias only influence sampling when penalties or a
        # bias are actually set — the common case skips both [V]-sized
        # transfers (~1 MB/admission on a tunneled link)
        p = req.params.normalized()
        heavy = bool(p.logit_bias) or p.repeat_penalty != 1.0 \
            or p.presence_penalty != 0.0 or p.frequency_penalty != 0.0
        row = sampler_row(req.params, self.cfg.vocab_size,
                          fallback_seed=rid + 1, include_bias=heavy)
        if req.resume is not None and req.resume.get("key") is not None:
            # restore the preempted slot's RNG carry chain: the device key
            # read back at spill-drain continues the exact split sequence,
            # so sampled resumes are byte-identical (greedy ignores it)
            row = dict(row, key=np.asarray(req.resume["key"], np.uint32))
        if heavy:
            counts_row = np.zeros((self.cfg.vocab_size,), np.int32)
            pid, pcnt = np.unique(np.asarray(req.prompt_ids, np.int64),
                                  return_counts=True)
            counts_row[pid] = pcnt
        else:
            counts_row = None

        if not chunked:
            if batch is not None and self._draft is None and not mm:
                # defer the device call: _flush_admits batches same-bucket
                # admissions from this tick into one prefill pass
                batch.append(dict(slot=slot, n=n, bucket=bucket,
                                  prompt_ids=req.prompt_ids, row=row,
                                  counts_row=counts_row, heavy=heavy))
            else:
                ids = self._pad_ids([dict(n=n, prompt_ids=req.prompt_ids)],
                                    bucket)
                inject = self._mm_inject(req, 0, bucket) if mm else None
                self._dev_admit(ids, n, slot, row, counts_row, inject)
                if self._draft is not None:
                    self._dev_draft_ingest(ids, 0, slot)

        W = self.ec.sampling_topk_width
        # `p` is the normalized params from the heavy-row check above
        fast_w = None
        if W and not req.grammar and (p.typical_p is None
                                      or p.typical_p >= 1.0):
            V = self.cfg.vocab_size
            tk = min(p.top_k or 0, V)   # sampler_row clamps the row the same
            if p.greedy:
                # greedy is argmax — rank 0 of ANY top-k window is exact, so
                # a plain temperature=0 request (the most common of all)
                # always rides the sort-free path
                fast_w = min(W, V)
            elif 0 < tk <= min(W, V):
                fast_w = min(W, V)
            elif 0 < tk <= min(8 * W, V):
                # escalation tier: a wide-top_k request rides an 8x-wider
                # (vocab-capped) sort-free window instead of dragging the
                # whole batch onto the full [B, V] sort path
                fast_w = min(8 * W, V)
        slot_obj = _Slot(
            request_id=rid, req=req, out=out,
            detok=self.tok.stream_decoder() if self.tok else None,
            matcher=matcher,
            start_time=time.monotonic(), prompt_len=n,
            prefilled=not chunked, row=row, counts_row=counts_row,
            prefill_pos=lcp, disk_prefix=disk_prefix, fast_w=fast_w,
        )
        slo = self._slo
        if slo is not None:
            if req.queued_t:
                slo.observe("queue_wait", "all",
                            slot_obj.start_time - req.queued_t)
            if not chunked:
                # single-shot prefill: committed within this admission (the
                # dispatch itself is async — host-side prefill time is the
                # admission work, real chunked time lands in _prefill_drain)
                slot_obj.prefill_done_t = time.monotonic()
                slo.observe("prefill", "all",
                            slot_obj.prefill_done_t - slot_obj.start_time)
        if self._tracer is not None:
            # one span per request, admission → release; request_id ties it
            # to the HTTP/gRPC spans of the same request, trace_parent nests
            # it under the gRPC handler's span in the merged trace
            slot_obj.span = self._tracer.begin(
                "engine.request", cat="engine",
                parent_id=req.trace_parent or None,
                args={"request_id": req.trace_id or f"rid-{rid}",
                      "slot": slot, "prompt_tokens": n})
        self._slots[slot] = slot_obj
        if chunked:
            self._prefillq.append(slot)
        if matcher is not None:
            eos = self.tok.eos_ids if self.tok else ()
            self._grammar_slots += 1
            slot_obj.gbase = gbase
            if gbase is not None:
                # table-backed slot: start in the grammar's initial state;
                # the host mask row materializes from the table mirror (u32
                # LSB-first words view as the same LSB-first u8 bytes), so
                # the per-token V-trial matcher mask walk is skipped for
                # the whole life of the request
                self._gstate[slot] = gbase
                self._mask_host[slot] = self._gmasks_np[gbase].view(
                    np.uint8)[:self._mask_nbytes]
            else:
                self._grammar_hostonly += 1
                self._mask_host[slot] = matcher.mask_bits(eos)
            if req.resume is not None:
                # replay the emitted tokens through the automaton so the
                # PDA (and the device table mirror) resumes mid-grammar
                # exactly where the preempted slot stopped
                for t in req.prompt_ids[n - int(req.resume.get(
                        "emitted", 0)):]:
                    if not matcher.accept(t):
                        break
                    if gbase is not None:
                        st = int(self._gtrans_np[self._gstate[slot], t])
                        self._gstate[slot] = st
                        self._mask_host[slot] = self._gmasks_np[st].view(
                            np.uint8)[:self._mask_nbytes]
                    else:
                        self._mask_host[slot] = matcher.mask_bits(eos)
        if req.resume is not None:
            # detokenizer replay: push the emitted chain through the fresh
            # incremental decoder (identical stream to the preempted run),
            # suppress the chars the client already received, and hand any
            # remainder — text the dead backend produced but never
            # released (stop-string holdback, or chars past the last
            # flushed chunk) — straight to the stream / holdback buffer
            cut = n - int(req.resume.get("emitted", 0))
            slot_obj.resume_base = n - cut
            replay = ""
            if slot_obj.detok is not None:
                for t in req.prompt_ids[cut:]:
                    replay += slot_obj.detok.push(t)
            sent = max(0, int(req.resume.get("sent_chars", 0)))
            leftover = replay[sent:]
            slot_obj.sent_chars = sent
            if req.stop:
                slot_obj.pending_text = leftover
            elif leftover:
                slot_obj.sent_chars += len(leftover)
                out.put(StepOutput(
                    request_id=rid, text=leftover, token_id=-1,
                    logprob=0.0, finished=False,
                    generated_tokens=0, prompt_tokens=n))
        self.metrics["prompt_tokens_processed"] += n - lcp
        if not chunked and self._draft is not None:
            # spec invariant: the first token is sampled (and emitted) at
            # admission; it becomes the carried next_token
            tok, lp = self._dev_spec_admit_tail(slot)
            self._emit(slot, slot_obj, tok, lp, time.monotonic(),
                       path="spec")
        return True

    def _prefill_tick(self):
        """Admission work for one engine tick: continue in-progress chunked
        prefills (oldest first) and admit queued requests, up to
        `admit_per_tick` units while decodes are running — bounding the work
        keeps running decodes at a steady cadence instead of stalling behind
        whole long prompts (the reference's update_slots interleaving,
        grpc-server.cpp:69-97). An idle engine (nothing decoding) has no
        cadence to protect, so it drains freely — burst TTFT at high slot
        counts is set by this path."""
        budget = max(1, self.ec.admit_per_tick)
        if not any(s is not None and s.prefilled for s in self._slots):
            budget = max(budget, self.ec.max_slots)
        pending: list = []
        try:
            self._prefill_drain(budget, pending)
        finally:
            self._flush_admits(pending)

    def _prefill_drain(self, budget: int, pending: list):
        for _ in range(budget):
            pq = self._prefillq
            if self._ragged_now():
                # ragged mode packs ALL token-level prefill — multimodal
                # included, via the flat-stream injection lane — into mixed
                # ragged ticks (_ragged_tick / _spec_ragged_tick); nothing
                # takes the dense chunked path here
                pq = []
            if pq:
                idx = pq[0]
                slot = self._slots[idx]
                ids = slot.req.prompt_ids
                pos = slot.prefill_pos
                nvalid = min(len(ids) - pos, self._chunk)
                buf = np.zeros((1, self._chunk), np.int32)
                buf[0, :nvalid] = ids[pos:pos + nvalid]
                final = pos + nvalid == len(ids)
                inject = (self._mm_inject(slot.req, pos, self._chunk)
                          if slot.req.mm_embeds is not None else None)
                if final:
                    self._dev_extend_final(buf, pos, nvalid, idx, slot.row,
                                           slot.counts_row, inject)
                else:
                    self._dev_extend_mid(buf, pos, idx, inject)
                if self._draft is not None:
                    self._dev_draft_ingest(buf, pos, idx)
                slot.prefill_pos = pos + nvalid
                if final:
                    slot.prefilled = True
                    self._prefillq.remove(idx)
                    if self._slo is not None:
                        slot.prefill_done_t = time.monotonic()
                        self._slo.observe(
                            "prefill", "all",
                            slot.prefill_done_t - slot.start_time)
                    if self._draft is not None:
                        tok, lp = self._dev_spec_admit_tail(idx)
                        self._emit(idx, slot, tok, lp, time.monotonic(),
                                   path="spec")
                continue
            if not self._free:
                return
            if self._deferred is not None:
                # a paged admission waiting on KV blocks retries only after
                # something released (head-of-line, preserving FIFO)
                if not self._blocks_freed:
                    return
                self._blocks_freed = False
                rid, req, out = self._deferred
                self._deferred = None
            else:
                try:
                    rid, req, out = self._queue.get_nowait()
                except queue.Empty:
                    return
            # dead-on-arrival requests (deadline spent waiting in the queue,
            # or cancelled before admission) terminate here — never paying
            # a prefill whose output nobody will read
            if (rid in self._cancelled
                    or (req.deadline and time.monotonic() > req.deadline)):
                reason = "cancelled" if rid in self._cancelled else "timeout"
                self._finish_rid(rid)
                out.put(StepOutput(
                    request_id=rid, text="", token_id=-1, logprob=0.0,
                    finished=True, finish_reason=reason,
                    prompt_tokens=len(req.prompt_ids)))
                continue
            # keep the popped triple reachable while the device call runs:
            # if admission dies mid-flight, _fail_active must still
            # terminate this stream (it is in neither _queue nor _slots)
            self._admitting = (rid, req, out)
            ok = self._admit_one(rid, req, out, batch=pending)
            self._admitting = None
            if ok is None:
                return

    _ADMIT_GROUP_SIZES = (2, 4, 8)

    @staticmethod
    def _mm_inject(req: GenRequest, start: int, width: int):
        """(extra [1, width, H] f32, mask [1, width] bool) for the prompt
        window [start, start+width): image-feature rows from req.mm_embeds
        land at their expanded positions, everything else stays a token."""
        pos, emb = req.mm_positions, req.mm_embeds
        lo = int(np.searchsorted(pos, start))
        hi = int(np.searchsorted(pos, start + width))
        extra = np.zeros((1, width, emb.shape[1]), np.float32)
        mask = np.zeros((1, width), bool)
        sel = (pos[lo:hi] - start).astype(np.int64)
        extra[0, sel] = emb[lo:hi]
        mask[0, sel] = True
        return (extra, mask)

    @staticmethod
    def _pad_ids(plans: list, bucket: int) -> np.ndarray:
        """[K, bucket] zero-padded prompt buffer from admission plans.
        (Chunked prefill pads its per-chunk window separately in
        _prefill_drain — different shape contract.)"""
        ids = np.zeros((len(plans), bucket), np.int32)
        for i, p in enumerate(plans):
            ids[i, :p["n"]] = p["prompt_ids"]
        return ids

    def _flush_admits(self, pending: list):
        """Execute this tick's deferred admissions: group by (bucket, heavy)
        and prefill each group in one batched device call. Group size is
        padded up to the next of _ADMIT_GROUP_SIZES by REPEATING the last
        plan — duplicate scatter rows write identical values, so the padding
        is a no-op on device state while keeping the set of compiled program
        shapes small. Singles take the existing single-request path."""
        groups: dict = {}
        for plan in pending:
            groups.setdefault((plan["bucket"], plan["heavy"]),
                              []).append(plan)
        for (bucket, heavy), g in groups.items():
            while g:
                if len(g) == 1:
                    p = g.pop()
                    self._dev_admit(self._pad_ids([p], bucket), p["n"],
                                    p["slot"], p["row"], p["counts_row"])
                    continue
                k = min(len(g), self._ADMIT_GROUP_SIZES[-1])
                size = next(s for s in self._ADMIT_GROUP_SIZES if s >= k)
                batch, g = g[:k], g[k:]
                batch = batch + [batch[-1]] * (size - k)
                ids = self._pad_ids(batch, bucket)
                lens = np.asarray([p["n"] for p in batch], np.int32)
                slots = np.asarray([p["slot"] for p in batch], np.int32)
                rows = {f: np.stack([np.asarray(p["row"][f]) for p in batch])
                        for f in batch[0]["row"]}
                counts = (np.stack([p["counts_row"] for p in batch])
                          if heavy else None)
                self._dev_admit_many(ids, lens, slots, rows, counts)

    def _active_mask(self) -> np.ndarray:
        return np.array([s is not None and s.prefilled for s in self._slots],
                        bool)

    def _block_steps(self) -> int:
        """How many decode steps the next dispatch may fuse. 1 whenever a
        per-token host decision is live: pending admissions or chunked
        prefills (so new requests don't wait a whole block) or a slot near
        its context limit / shift boundary. A slot approaching max_tokens
        steps the batch DOWN a power-of-two ladder (16→8→4→2→1) instead of
        collapsing it to single steps — on a tunneled chip each dispatch
        pays the link RTT, and the old cliff single-stepped the last
        2*G tokens of EVERY request (a quarter of a 128-token stream).
        Grammar slots DO ride blocks — sampled under their block-start
        mask, host-verified against the PDA, rolled back at the first
        stale-mask miss — so one constrained request no longer serializes
        every other tenant."""
        G = self.ec.decode_block
        if (G <= 1 or not self.ec.pipeline or self._prefillq
                or (self._free and not self._queue.empty())):
            # a non-empty queue only matters if a slot is free to admit into —
            # a saturated engine keeps full block fusion
            return 1
        limit = self.ec.max_context - 2 - self._ctx_reserve
        steps = G
        for s in self._slots:
            if s is None or not s.prefilled:
                continue
            # 2G margin: with one block pipelined in flight, host-side
            # `generated` is stale by up to a full block when this guard runs
            if s.prompt_len + s.generated - s.shifted + 2 * G >= limit:
                if self._sched is not None:
                    self._sched.reason("context_margin")
                return 1
            # remaining tokens, discounted by the ACTUAL in-flight
            # dispatch's staleness (not the max block size — the tail then
            # rides 4/2-step dispatches to the end); overshooting a slot's
            # max_tokens only wastes its lanes (emission stops at the bound
            # and the slot is released), so the ladder trades a little tail
            # compute for RTT
            stale = self._inflight_steps if self._pending is not None else 0
            rem = s.req.max_tokens - s.generated - stale
            while steps > 1 and steps * 2 > max(rem, 1):
                steps //= 2
            if steps == 1:
                if self._sched is not None:
                    self._sched.reason("max_tokens_ladder")
                return 1
        if steps < G and self._sched is not None:
            self._sched.reason("max_tokens_ladder")
        return steps

    def _loop_block_reason(self, entries) -> str | None:
        """None when this dispatch can go loop-native (ONE while_loop
        dispatch, stop conditions on device); otherwise the registered
        reason code (telemetry.sched.REASON_CODES, "dispatch" category) for
        why the block/ladder path runs instead. Host-verified decisions
        keep the dense path: grammar masks and stop strings need per-token
        host checks, speculative decoding has its own fused program, and
        pending admissions/chunked prefills must not wait out a whole loop
        (the device cannot see the host queue mid-dispatch)."""
        if self._decode_loop_fn is None:
            return "loop_disabled"
        if self._draft is not None:
            return "draft_engine"
        # table-backed grammar slots ride the loop (the device gathers each
        # step's mask row and advances the automaton state); only automata
        # that OVERFLOWED the table still need per-token host masks
        if self._grammar_hostonly > 0:
            return "grammar_hostonly"
        if self._prefillq:
            return "pending_prefill"
        if self._free and not self._queue.empty():
            return "pending_admission"
        if any(self._slots[i].req.stop for i, _ in entries):
            return "stop_string"
        return None

    def _loop_eligible(self, entries) -> bool:
        return self._loop_block_reason(entries) is None

    def _dispatch_loop(self, active, entries, fast):
        """Dispatch the fused while-loop block. Per-slot `remaining` budgets
        are max_tokens net of the PENDING dispatch's reservation, so two
        loop blocks can pipeline without ever overshooting a budget; a slot
        whose whole budget is already in flight sits this dispatch out (the
        device would run it zero steps anyway)."""
        G = (self.ec.ragged_loop_steps if self._ragged_loop_fn is not None
             else self.ec.decode_loop)
        B = self.ec.max_slots
        remaining = np.zeros((B,), np.int32)
        check_eos = np.zeros((B,), bool)
        live = []
        for i, rid in entries:
            s = self._slots[i]
            rem = s.req.max_tokens - s.generated - s.inflight
            if rem <= 0:
                active[i] = False
                continue
            remaining[i] = rem
            check_eos[i] = self.tok is not None and not s.req.ignore_eos
            live.append((i, rid))
        if not live:
            return None
        res = {}
        for i, _ in live:
            res[i] = int(min(G, remaining[i]))
            self._slots[i].inflight += res[i]
        self._inflight_steps = G
        if self._sched is not None:
            # the fast path is recorded too, so the dispatch-category codes
            # stay exhaustive over dense dispatches (the fallback-sum
            # invariant bench.py's dense_fallback_reasons relies on)
            self._sched.reason("loop_native")
        gstate = self._gstate.copy() if self._grammar_slots > 0 else None
        if self._ragged_loop_fn is not None:
            # ragged engines with the fused loop: pure-decode dispatches
            # ride the pack-free ragged-loop variant — same stop semantics
            # as the decode_loop program plus the first-finish early exit
            # (a freed slot admits immediately instead of waiting out the
            # loop; G above already capped reservations at its step budget)
            fetch = self._dev_rloop_decode(active, remaining, check_eos,
                                           fast, gstate=gstate)
            return ("rloop", fetch, live, res)
        fetch = self._dev_decode_loop(active, remaining, check_eos, fast,
                                      gstate=gstate)
        return ("loop", fetch, live, res)

    def _dispatch(self):
        """Dispatch one decode step, a fused scan block, or a single-dispatch
        while loop for the currently-active slots; returns a tagged pend
        ("loop"|"block", async fetch, [(slot_idx, request_id)], ...) without
        waiting for the device — or None if nothing can run."""
        active = self._active_mask()
        if not active.any():
            return None
        entries = [(int(i), self._slots[i].request_id)
                   for i in np.where(active)[0]]
        # sort-free sampling only when EVERY active slot's knobs fit SOME
        # top-k window (and no grammar masks are live); the dispatch width
        # is the widest any active slot needs — one wide-top_k tenant costs
        # the batch a wider window, not the full-sort path
        fast = None
        if self._grammar_slots == 0:
            ws = [self._slots[i].fast_w if self._slots[i] is not None
                  else None for i, _ in entries]
            if all(w is not None for w in ws):
                fast = max(ws)
        loop_block = self._loop_block_reason(entries)
        if loop_block is None:
            return self._dispatch_loop(active, entries, fast)
        if self._sched is not None:
            # exactly ONE dispatch-category code per dense dispatch — this
            # is what lets bench.py explain dense_fallback_dispatches as a
            # sum of reason-code counts
            self._sched.reason(loop_block)
        steps = self._block_steps()
        # snapshot the dispatch-time masks: _consume compares each slot's
        # refreshed mask against what the device sampled under, to catch the
        # allowed-set GROWING mid-block (see _consume)
        gmask = self._mask_host.copy() if self._grammar_slots > 0 else None
        self._inflight_steps = steps
        res = {}
        for i, _ in entries:
            res[i] = steps
            self._slots[i].inflight += steps
        if steps > 1:
            fetch = self._dev_decode_block(active, steps, fast, gmask)
        else:
            fetch = self._dev_decode(active, gmask, fast)
        return ("block", fetch, entries, gmask, res)

    def _release_reservations(self, entries, res):
        """Return a consumed dispatch's per-slot token reservations (see
        _Slot.inflight) before emitting — emission moves the budget from
        `inflight` into `generated`."""
        for i, rid in entries:
            s = self._slots[i]
            if s is not None and s.request_id == rid:
                s.inflight = max(0, s.inflight - res.get(i, 0))

    def _dispatch_gauges(self):
        """Refresh the profiler's dispatch-fusing gauges (prof_* GetMetrics
        keys → scoreboard/Prometheus). Profiling-mode only — the disabled
        hot path stays a None-check."""
        if self._prof is None:
            return
        m = self.metrics
        d = max(m["decode_dispatches"], 1)
        self._prof.set_gauges(
            decode_dispatches_count=m["decode_dispatches"],
            steps_per_dispatch=m["decode_steps_dispatched"] / d,
            host_sync_wait_ms_per_token=(
                m["host_sync_wait_ms"] / max(m["tokens_generated"], 1)))

    # device exit codes of the fused ragged loop (models/llama.py
    # RLOOP_EXIT_*) → telemetry.sched pack reason codes. host_arbitration is
    # recorded host-side at decline time (_ragged_tick), never by the device.
    _RLOOP_EXIT_REASON = {
        0: "loop_early_exit_steps_cap",
        1: "loop_early_exit_finish",
        2: "loop_early_exit_prefill",
    }

    def _rloop_exit(self, code: int, reason: str | None = None) -> None:
        """Record one fused-ragged-loop exit: the sched pack reason code
        (per-tick attribution) plus a flat metrics counter
        (`rloop_exit_<cause>`) the bench JSON reports as
        loop_exit_reasons."""
        reason = reason or self._RLOOP_EXIT_REASON.get(
            code, "loop_early_exit_steps_cap")
        if self._sched is not None:
            self._sched.reason(reason)
        key = "rloop_exit_" + reason[len("loop_early_exit_"):]
        self.metrics[key] = self.metrics.get(key, 0) + 1

    def _consume_loop(self, pend):
        """Consume a fused while-loop dispatch: finish the async token fetch,
        credit the ACTUAL step count (early exit makes it <= decode_loop),
        and commit slot b's n_out[b] tokens in device order. The host still
        re-derives every finish decision in _emit — cancel/deadline can
        terminate a slot mid-buffer, and the rest of its tokens are dropped
        by the request-id check exactly as on the block path."""
        tag, fetch, entries, res = pend
        t0 = time.perf_counter()
        out = fetch.wait()
        self.metrics["host_sync_wait_ms"] += (time.perf_counter() - t0) * 1e3
        if tag == "rloop":
            # fused ragged loop (pack-free variant): the fetch carries the
            # device's exit code — map it onto the pack reason taxonomy and
            # the flat loop-exit counters the bench scoreboard reads
            tokens, logprobs, n_out, steps, code = out
            self._rloop_exit(int(code))
        else:
            tokens, logprobs, n_out, steps = out
        steps = int(steps)
        self.metrics["decode_steps_dispatched"] += steps
        self._release_reservations(entries, res)
        now = time.monotonic()
        if self._slo is not None:
            for i, rid in entries:
                s = self._slots[i]
                if s is not None and s.request_id == rid:
                    s.dispatches += 1
        emitted = 0
        for g in range(steps):
            for i, rid in entries:
                if g >= int(n_out[i]):
                    continue
                slot = self._slots[i]
                if slot is None or slot.request_id != rid:
                    continue  # finished earlier (cancel/deadline/shift race)
                self._emit(i, slot, int(tokens[g, i]),
                           float(logprobs[g, i]), now,
                           path="rloop" if tag == "rloop" else "loop")
                emitted += 1
        self._obs("sample", t0, tokens=emitted, steps=steps, rollbacks=0)
        self._dispatch_gauges()

    def _consume(self, pend):
        """Block on a dispatched step's results and run the host-side token
        handling for every slot that was active at dispatch time and is still
        serving the same request. Grammar slots in a fused block sampled under
        their block-START mask: the first token a slot's (live) PDA rejects
        marks that slot for rollback — its accepted prefix stands, the rest of
        its block is discarded, and _repair restores the device state."""
        if pend[0] in ("loop", "rloop"):
            self._consume_loop(pend)
            return
        _, fetch, entries, gmask, res = pend
        t0 = time.perf_counter()
        tokens, logprobs = fetch.wait()
        self.metrics["host_sync_wait_ms"] += (time.perf_counter() - t0) * 1e3
        self._release_reservations(entries, res)
        now = time.monotonic()
        if tokens.ndim == 1:
            tokens, logprobs = tokens[None], logprobs[None]
        steps = tokens.shape[0]
        if self._slo is not None:
            for i, rid in entries:
                s = self._slots[i]
                if s is not None and s.request_id == rid:
                    s.dispatches += 1
        rolled: list[int] = []
        for g in range(steps):
            for i, rid in entries:
                slot = self._slots[i]
                if slot is None or slot.request_id != rid or i in rolled:
                    continue  # finished earlier in this block (EOS/stop/len)
                if not self._emit(i, slot, int(tokens[g, i]),
                                  float(logprobs[g, i]), now,
                                  fresh_mask=(g == 0)):
                    rolled.append(i)
                    continue
                # mask-growth check: PDA-reject rollback makes in-block
                # grammar sampling exact REJECTION sampling while the
                # allowed set only shrinks — but if this token's acceptance
                # OPENED tokens the dispatch mask forbade, the rest of the
                # block was drawn from a wrongly-restricted distribution
                # and must be discarded even though the PDA might accept it.
                if (gmask is not None and g + 1 < steps
                        and self._slots[i] is slot
                        and slot.matcher is not None
                        and np.any(self._mask_host[i] & ~gmask[i])):
                    rolled.append(i)
        for i in rolled:
            slot = self._slots[i]
            if slot is not None:
                self._repair(i, slot)
        # "sample" = the host side of sampling: async-fetch completion (the
        # copy started at dispatch — on the pipelined path it has usually
        # already landed) plus token commit (grammar advance, detok, stop
        # scan, stream fan-out)
        self._obs("sample", t0, tokens=steps * len(entries),
                  steps=steps, rollbacks=len(rolled))
        self._dispatch_gauges()

    def _repair(self, idx: int, slot: _Slot):
        """Roll a grammar slot back to its last PDA-accepted token after a
        fused block sampled past a stale mask (see _consume): re-run the model
        on that token through the extend path — rewriting the same KV row with
        identical values, restoring last_logits and lengths[slot] to the
        accepted position — and re-install the sampler row with a fresh
        deterministic RNG key (re-using the admission key would replay the
        block's draws). The rows the block wrote past the accepted position
        are garbage but unreadable: attention masks by lengths, and future
        decode steps overwrite them in order."""
        self.metrics["grammar_rollbacks"] = (
            self.metrics.get("grammar_rollbacks", 0) + 1)
        n = slot.prompt_len + slot.generated - slot.shifted  # valid rows
        seq = list(slot.req.prompt_ids) + slot.gen_ids
        buf = np.zeros((1, self._chunk), np.int32)
        buf[0, 0] = seq[-1]
        seed = (slot.request_id * 1000003 + slot.generated) & 0x7FFFFFFF
        key = jax.device_get(jax.random.key_data(
            jax.random.PRNGKey(seed))).astype(np.uint32)
        row = dict(slot.row, key=key)
        slot.row = row
        counts = slot.counts_row
        if counts is not None:
            counts = counts.copy()
            for t in slot.gen_ids:
                counts[t] += 1
        self._dev_extend_final(buf, n - 1, 1, idx, row, counts)

    def _step_spec(self) -> bool:
        """Spec-mode iteration: one batched draft+verify step for all active
        slots (engine/spec.py), emitting 1..gamma+1 tokens per slot."""
        active = self._active_mask()
        if active.any():
            entries = [(int(i), self._slots[i].request_id)
                       for i in np.where(active)[0]]
            pend = self._dev_spec_decode(active)
            self._prefill_tick()   # admission overlaps the device step
            t0 = time.perf_counter()
            tokens_out, n_out, logprobs_out, n_extra = pend.wait()
            self.metrics["host_sync_wait_ms"] += (
                time.perf_counter() - t0) * 1e3
            now = time.monotonic()
            G = self.ec.gamma
            for i, rid in entries:
                slot = self._slots[i]
                if slot is None or slot.request_id != rid:
                    continue
                self.metrics["draft_proposed"] += G
                self.metrics["draft_accepted"] += int(n_extra[i])
                if self._slo is not None:
                    slot.dispatches += 1
                for j in range(int(n_out[i])):
                    slot = self._slots[i]
                    if slot is None or slot.request_id != rid:
                        break  # finished mid-window (EOS/length/stop)
                    self._emit(i, slot, int(tokens_out[i, j]),
                               float(logprobs_out[i, j]), now, path="spec")
        else:
            self._prefill_tick()
        return (any(s is not None for s in self._slots)
                or not self._queue.empty() or self._deferred is not None)

    def _step_spec_ragged(self) -> bool:
        """Draft+ragged iteration: ONE spec-as-ragged dispatch per tick —
        gamma draft steps plus a ragged target verify whose flat stream
        holds every verifying slot's (gamma+1)-row window AND any packed
        prefill chunks (multimodal inject rows included). This is the path
        a mixed tenant soup rides: spec, grammar, mm and plain traffic all
        share the one program (engine/spec.py build_spec_ragged)."""
        self._prefill_tick()   # ragged admissions are host-only bookkeeping,
        # so new arrivals can pack into THIS tick's stream
        active = self._active_mask()
        if active.any() or self._ragged_chunkable():
            self._spec_ragged_tick(active, self._ragged_chunkable())
        return (any(s is not None for s in self._slots)
                or not self._queue.empty() or self._deferred is not None)

    def _spec_ragged_tick(self, active, chunkable: list[int]):
        """Pack verify windows + prefill chunks into one flat [T] stream and
        dispatch a single spec-as-ragged program. Layout contract matches
        _ragged_tick (QBLK-aligned per-seq q blocks, seq index == slot
        index), except a verifying slot spans ceil((gamma+1)/QBLK) blocks —
        the draft window is spliced into its rows ON DEVICE (the window
        tokens live in device state; the host ships zeros)."""
        from localai_tpu.ops.pallas import QBLK
        B = self.ec.max_slots
        T = self._ragged_rows
        G = self.ec.gamma
        winb = -(-(G + 1) // QBLK)
        block_seq = np.full((T // QBLK,), -1, np.int32)
        tokens = np.zeros((T,), np.int32)
        verify = np.zeros((B,), bool)
        spec_rows = np.zeros((B,), np.int32)
        qstart = np.zeros((B,), np.int32)
        qlen = np.zeros((B,), np.int32)
        kvlen = np.zeros((B,), np.int32)
        set_len = np.full((B,), -1, np.int32)
        logit_set = np.zeros((B,), bool)
        logit_rows = np.zeros((B, G + 1), np.int32)
        row = 0
        cap = T - QBLK   # one q-block always reserved for prefill
        entries = []
        order = [(self._ragged_rr + j) % B for j in range(B)]
        self._ragged_rr = (self._ragged_rr + 1) % max(B, 1)
        for i in order:
            if not active[i]:
                continue
            s = self._slots[i]
            if row + winb * QBLK > cap:
                if self._sched is not None:
                    self._sched.reason("budget_cap", kind="verify_windows")
                break
            n = s.prompt_len + s.generated - s.shifted
            qstart[i], qlen[i], kvlen[i] = row, G + 1, n + G + 1
            block_seq[row // QBLK: row // QBLK + winb] = i
            spec_rows[i] = row
            verify[i] = True
            logit_rows[i] = row + np.arange(G + 1)
            entries.append((i, s.request_id))
            row += winb * QBLK
        packed = len(entries) * (G + 1)
        chunks = []
        inj_extra = inj_mask = None
        for idx in chunkable:
            if T - row < QBLK:
                if self._sched is not None:
                    self._sched.reason("budget_cap", kind="prefill_chunks")
                break
            s = self._slots[idx]
            ids = s.req.prompt_ids
            pos = s.prefill_pos
            nvalid = min(len(ids) - pos, T - row, self._chunk)
            tokens[row:row + nvalid] = ids[pos:pos + nvalid]
            nb = -(-nvalid // QBLK)
            block_seq[row // QBLK:row // QBLK + nb] = idx
            final = pos + nvalid == len(ids)
            qstart[idx], qlen[idx] = row, nvalid
            kvlen[idx] = pos + nvalid
            if final:
                set_len[idx] = pos + nvalid
                logit_set[idx] = True
                # all G+1 logit rows point at the final prompt row, so the
                # kernel's last_logits merge picks up the admission logits
                logit_rows[idx, :] = row + nvalid - 1
            if s.req.mm_embeds is not None:
                mpos, emb = s.req.mm_positions, s.req.mm_embeds
                lo = int(np.searchsorted(mpos, pos))
                hi = int(np.searchsorted(mpos, pos + nvalid))
                if hi > lo:
                    if inj_extra is None:
                        inj_extra = np.zeros(
                            (T, self.cfg.hidden_size), np.float32)
                        inj_mask = np.zeros((T,), bool)
                    sel = (mpos[lo:hi] - pos).astype(np.int64) + row
                    inj_extra[sel] = emb[lo:hi]
                    inj_mask[sel] = True
            chunks.append((idx, pos, nvalid, final))
            packed += nvalid
            row += nb * QBLK
        pack = dict(verify=verify, tokens=tokens, spec_rows=spec_rows,
                    set_len=set_len, logit_set=logit_set,
                    logit_rows=logit_rows, block_seq=block_seq,
                    qstart=qstart, qlen=qlen, kvlen=kvlen, packed=packed,
                    rows_used=row,
                    # grammar verify masks come from the DEVICE tables
                    # (submit() rejects draft+grammar automata that
                    # overflow them), keyed by each slot's automaton state
                    gstate=(self._gstate.copy()
                            if self._grammar_slots > 0 else None),
                    inject=(None if inj_extra is None
                            else (inj_extra, inj_mask)))
        fetch = self._dev_spec_ragged(pack)
        # chunk bookkeeping overlaps the device step; the draft ingests each
        # chunk's token ids through its own (tiny) prefill program
        for idx, pos, nvalid, final in chunks:
            s = self._slots[idx]
            s.prefill_pos = pos + nvalid
            buf = np.zeros((1, self._chunk), np.int32)
            buf[0, :nvalid] = s.req.prompt_ids[pos:pos + nvalid]
            self._dev_draft_ingest(buf, pos, idx)
            if final:
                self._dev_install(idx, s.row, s.counts_row)
                s.prefilled = True
                self._prefillq.remove(idx)
                if self._slo is not None:
                    s.prefill_done_t = time.monotonic()
                    self._slo.observe("prefill", "all",
                                      s.prefill_done_t - s.start_time)
                    s.dispatches += 1
                    s.path = "ragged"
                tok, lp = self._dev_spec_admit_tail(idx)
                self._emit(idx, s, tok, lp, time.monotonic(), path="spec")
            elif self._slo is not None:
                s.dispatches += 1
                s.path = "ragged"
        t0 = time.perf_counter()
        tokens_out, n_out, logprobs_out, n_extra = fetch.wait()
        self.metrics["host_sync_wait_ms"] += (time.perf_counter() - t0) * 1e3
        now = time.monotonic()
        emitted = 0
        for i, rid in entries:
            slot = self._slots[i]
            if slot is None or slot.request_id != rid:
                continue
            self.metrics["draft_proposed"] += G
            self.metrics["draft_accepted"] += int(n_extra[i])
            if self._slo is not None:
                slot.dispatches += 1
            for j in range(int(n_out[i])):
                slot = self._slots[i]
                if slot is None or slot.request_id != rid:
                    break  # finished mid-window (EOS/length/stop)
                self._emit(i, slot, int(tokens_out[i, j]),
                           float(logprobs_out[i, j]), now, path="spec")
                emitted += 1
        self._obs("sample", t0, tokens=emitted, steps=G + 1, rollbacks=0)
        self._dispatch_gauges()

    # ------------------------------------------------------ ragged scheduling

    def _ragged_now(self) -> bool:
        """True when this tick may run the ragged mixed-dispatch path.
        Grammar slots ride it too: the tick is consumed synchronously, so
        the per-slot mask rows shipped with the pack are never stale — the
        PDA (or its table mirror) advances before the next dispatch."""
        return self._ragged

    def _ragged_chunkable(self) -> list[int]:
        """Prefill-queue slots whose next chunk can ride the flat stream.
        Multimodal prompts pack too — their embedding chunks ride the
        per-row injection lane (see the `inject` pack field)."""
        return [i for i in self._prefillq if self._slots[i] is not None]

    def _step_ragged(self) -> bool:
        """Run one mixed ragged tick if there is prefill work to pack with
        the running decodes. Returns False to fall through to the dense
        tick — pure decode keeps the single-dispatch while-loop, which a
        mixed program cannot beat when there is nothing to mix."""
        admissible = ((not self._queue.empty() and bool(self._free))
                      or (self._deferred is not None and self._blocks_freed))
        if not self._ragged_chunkable() and not admissible:
            return False
        # host lengths must be exact before packing (loop dispatches have
        # data-dependent step counts): consume the in-flight dispatch first.
        # The ragged dispatch below is consumed synchronously in-tick, so
        # the pipeline resumes cleanly on the next pure-decode tick.
        if self._pending is not None:
            self._consume(self._pending)
            self._pending = None
        self._prefill_tick()   # ragged admissions land chunked (host-only)
        chunkable = self._ragged_chunkable()
        if not chunkable:
            return False       # only mm prompts queued: dense tick serves
        self._ragged_tick(chunkable)
        return True

    def _ragged_tick(self, chunkable: list[int]):
        """Pack every live decode slot plus as many prefill-chunk tokens as
        fit into ONE flat [T] token stream and dispatch a single ragged
        forward. Layout contract (ops/pallas/ragged_attention): each
        QBLK-row q block belongs to exactly one sequence; a decode slot
        occupies one live row + QBLK-1 dead pad rows; a prefill chunk spans
        ceil(n/QBLK) blocks. Seq index == engine slot index, so the device
        derives every per-row position and page target from the engine's
        own block table — no remapping, no bucket padding."""
        from localai_tpu.ops.pallas import QBLK
        B = self.ec.max_slots
        T = self._ragged_rows
        block_seq = np.full((T // QBLK,), -1, np.int32)
        tokens = np.zeros((T,), np.int32)
        decode_slot = np.full((T,), -1, np.int32)
        qstart = np.zeros((B,), np.int32)
        qlen = np.zeros((B,), np.int32)
        kvlen = np.zeros((B,), np.int32)
        set_len = np.full((B,), -1, np.int32)
        logit_set = np.zeros((B,), bool)
        is_decode = np.zeros((B,), bool)
        logit_rows = np.zeros((B,), np.int32)
        row = 0
        entries = []
        # Decode packing: one QBLK-aligned row per prefilled slot. One QBLK
        # is always reserved for prefill so admission can't be starved by a
        # full decode population; when the budget can't hold every slot the
        # rotating offset keeps the overflow fair across ticks.
        cap = T - QBLK
        order = [(self._ragged_rr + j) % B for j in range(B)]
        self._ragged_rr = (self._ragged_rr + 1) % max(B, 1)
        for i in order:
            s = self._slots[i]
            if s is None or not s.prefilled:
                continue
            if row + QBLK > cap:
                if self._sched is not None:
                    self._sched.reason("budget_cap", kind="decode_rows")
                break
            n = s.prompt_len + s.generated - s.shifted
            qstart[i], qlen[i], kvlen[i] = row, 1, n + 1
            block_seq[row // QBLK] = i
            decode_slot[row] = i
            is_decode[i] = True
            logit_set[i] = True
            logit_rows[i] = row
            entries.append((i, s.request_id))
            row += QBLK
        packed = len(entries)
        chunks = []
        inj_extra = inj_mask = None
        for idx in chunkable:
            if T - row < QBLK:
                if self._sched is not None:
                    self._sched.reason("budget_cap", kind="prefill_chunks")
                break
            s = self._slots[idx]
            ids = s.req.prompt_ids
            pos = s.prefill_pos
            nvalid = min(len(ids) - pos, T - row, self._chunk)
            tokens[row:row + nvalid] = ids[pos:pos + nvalid]
            nb = -(-nvalid // QBLK)
            block_seq[row // QBLK:row // QBLK + nb] = idx
            final = pos + nvalid == len(ids)
            qstart[idx], qlen[idx] = row, nvalid
            kvlen[idx] = pos + nvalid
            if final:
                # device length is set only at the final chunk (mid chunks
                # mirror extend_mid: host tracks prefill_pos, device length
                # stays 0 so the slot can't be decoded early)
                set_len[idx] = pos + nvalid
                logit_set[idx] = True
                logit_rows[idx] = row + nvalid - 1
            if s.req.mm_embeds is not None:
                # multimodal packing: this chunk's image-feature rows land
                # at their flat-stream rows via the per-row injection lane
                # (lazily allocated — text-only ticks skip the [T, H] cost)
                mpos, emb = s.req.mm_positions, s.req.mm_embeds
                lo = int(np.searchsorted(mpos, pos))
                hi = int(np.searchsorted(mpos, pos + nvalid))
                if hi > lo:
                    if inj_extra is None:
                        inj_extra = np.zeros(
                            (T, self.cfg.hidden_size), np.float32)
                        inj_mask = np.zeros((T,), bool)
                    sel = (mpos[lo:hi] - pos).astype(np.int64) + row
                    inj_extra[sel] = emb[lo:hi]
                    inj_mask[sel] = True
            chunks.append((idx, pos, nvalid, final))
            packed += nvalid
            row += nb * QBLK
        pack = dict(tokens=tokens, decode_slot=decode_slot,
                    is_decode=is_decode, set_len=set_len,
                    logit_set=logit_set, logit_rows=logit_rows,
                    block_seq=block_seq, qstart=qstart, qlen=qlen,
                    kvlen=kvlen, packed=packed, rows_used=row,
                    # grammar decode slots sample under their CURRENT mask
                    # rows — consumed synchronously below, so never stale
                    mask=(self._mask_host.copy()
                          if self._grammar_slots > 0 else None),
                    inject=(None if inj_extra is None
                            else (inj_extra, inj_mask)))
        # fused multi-step tick (ISSUE 16): run the pack as iteration 0 of
        # the ragged loop and let every decode slot keep advancing on device
        # until a slot finishes, host work appears, or the step cap. Host
        # arbitration declines the loop: host-only grammar overflows and
        # stop-string slots need per-token host decisions, and mm inject
        # rows only occur mid-prefill where the loop would cap at one step
        # anyway — all three keep the single-step dispatch (exact current
        # behavior, fresh host masks).
        res: dict[int, int] = {}
        arbitration = (self._grammar_hostonly > 0
                       or any(self._slots[i] is not None
                              and self._slots[i].req.stop
                              for i, _ in entries))
        use_loop = (self._ragged_loop_fn is not None and bool(entries)
                    and inj_extra is None and not arbitration)
        if use_loop:
            remaining = np.zeros((B,), np.int32)
            check_eos = np.zeros((B,), bool)
            for i, rid in entries:
                s = self._slots[i]
                remaining[i] = max(1, s.req.max_tokens - s.generated
                                   - s.inflight)
                check_eos[i] = self.tok is not None and not s.req.ignore_eos
                # pipelined-style budget reservation (PR 6): released at
                # consume below, before emission moves tokens to `generated`
                res[i] = int(min(self.ec.ragged_loop_steps, remaining[i]))
                s.inflight += res[i]
            # prefill-pending flag, computed at dispatch time: chunk work
            # left after this pack (mid chunks, budget-capped slots),
            # queued/deferred admissions — any of these collapses the loop
            # to a single iteration so TTFT stays at ragged levels
            left = set(self._prefillq) - {
                idx for idx, _pos, _nv, fin in chunks if fin}
            prefill_pending = (bool(left) or self._deferred is not None
                               or not self._queue.empty())
            fetch = self._dev_ragged_loop(
                pack, remaining, check_eos, prefill_pending,
                gstate=(self._gstate.copy()
                        if self._grammar_slots > 0 else None))
        else:
            if (self._ragged_loop_fn is not None and entries
                    and arbitration):
                self._rloop_exit(-1,
                                 reason="loop_early_exit_host_arbitration")
            fetch = self._dev_ragged(pack)
        for idx, pos, nvalid, final in chunks:
            s = self._slots[idx]
            s.prefill_pos = pos + nvalid
            if final:
                # sampler row rides a separate tiny dispatch so the ragged
                # program's signature stays row-structure-free
                self._dev_install(idx, s.row, s.counts_row)
                s.prefilled = True
                self._prefillq.remove(idx)
                if self._slo is not None:
                    s.prefill_done_t = time.monotonic()
                    self._slo.observe("prefill", "all",
                                      s.prefill_done_t - s.start_time)
        t0 = time.perf_counter()
        steps = 1
        if use_loop:
            tokens_out, logprobs, n_out, steps, code = fetch.wait()
            steps = int(steps)
            self.metrics["decode_steps_dispatched"] += steps
            self._rloop_exit(int(code))
            self._release_reservations(entries, res)
        else:
            tokens_out, logprobs = fetch.wait()
        self.metrics["host_sync_wait_ms"] += (time.perf_counter() - t0) * 1e3
        now = time.monotonic()
        if self._slo is not None:
            # dispatch attribution: every slot packed into this ragged tick
            # (decode rows AND prefill chunks) rode one device dispatch
            for i, rid in entries:
                s = self._slots[i]
                if s is not None and s.request_id == rid:
                    s.dispatches += 1
            for idx, _pos, _nv, _fin in chunks:
                s = self._slots[idx]
                if s is not None:
                    s.dispatches += 1
                    s.path = "ragged"
        emitted = 0
        if use_loop:
            # drain the [steps, B] device token ring in device order — the
            # host re-derives every finish decision in _emit exactly as on
            # the loop path (cancel/deadline can drop a slot mid-ring)
            for g in range(steps):
                for i, rid in entries:
                    if g >= int(n_out[i]):
                        continue
                    s = self._slots[i]
                    if s is None or s.request_id != rid:
                        continue
                    self._emit(i, s, int(tokens_out[g, i]),
                               float(logprobs[g, i]), now, path="ragged")
                    emitted += 1
        else:
            for i, rid in entries:
                s = self._slots[i]
                if s is None or s.request_id != rid:
                    continue
                self._emit(i, s, int(tokens_out[i]), float(logprobs[i]),
                           now, path="ragged")
                emitted += 1
        self._obs("sample", t0, tokens=emitted, steps=steps, rollbacks=0)
        self._dispatch_gauges()

    def _kv_tick(self):
        """Advance the hot→cold→evicted lifecycle for windowed slots.

        A raw block is eligible the moment its LAST token exits the window
        of the oldest position any in-flight or future query can hold (the
        host length only LAGS the device, so eligibility here is
        conservative). quantize_cold copies the block into the int8 cold
        pool — the dispatch is enqueued behind any in-flight decode on the
        same stream, and the ring's +2 slack blocks (kvtier.ring_blocks)
        guarantee the copy lands before the ring wraps over the block. A
        full cold pool, or a drop-policy slot, counts the block evicted
        (the ring overwrite IS the eviction — SnapStream semantics)."""
        if not self._tiered:
            return
        from localai_tpu.ops.paged import BLOCK

        for i, s in enumerate(self._slots):
            if s is None:
                continue
            pol = self._slot_policy[i]
            if pol is None or not pol.windowed:
                continue
            n = (s.prompt_len + s.generated - s.shifted if s.prefilled
                 else s.prefill_pos)
            sb = int(self._kv_sb[i])
            lim = n - int(self._kv_window[i])
            while True:
                raw = int(self._demote_next[i])
                if raw < sb or (raw + 1) * BLOCK > lim:
                    break
                self._demote_next[i] = raw + 1
                if not self._cold or not self._cold_free:
                    self.metrics["kv_evictions"] += 1
                    if self._sched is not None:
                        self._sched.reason("kv_eviction", slot=i, block=raw)
                    if (self._kvhost is not None and s.shifted == 0
                            and s.req.mm_embeds is None
                            and (raw + 1) * BLOCK
                            <= int(self._kv_window[i])):
                        # the ring will overwrite this block — spill a copy
                        # first. Ring content sits at TRUE positions (only
                        # the column mapping rotates), and every token in a
                        # block ending inside the first window span was
                        # computed with its FULL history still attendable —
                        # byte-equivalent to full-policy prefill, so it is
                        # valid prefix-cache content for any future tenant.
                        # Later blocks saw truncated attention and must not
                        # be served cross-tenant. The ring's +2 slack
                        # blocks order the async D2H before the wrap,
                        # exactly as for _dev_demote
                        ids = (list(s.req.prompt_ids) + s.gen_ids)
                        if len(ids) >= (raw + 1) * BLOCK:
                            chain = self._chain_hashes(
                                ids[:(raw + 1) * BLOCK])
                            col = sb + (raw - sb) % max(
                                int(self._kv_rw[i]), 1)
                            self._spill_block(
                                int(self._table[i, col]), h=chain[raw],
                                group=chain[0])
                    continue
                ci = self._cold_free.pop()
                col = sb + (raw - sb) % max(int(self._kv_rw[i]), 1)
                pb = int(self._table[i, col])
                self._cold_table[i, raw] = ci
                self._slot_cold[i].append(ci)
                self.metrics["kv_cold_blocks"] += 1
                if self._sched is not None:
                    self._sched.reason("kv_cold_demotion", slot=i, block=raw)
                self._dev_demote(pb, ci)

    def step(self) -> bool:
        """One engine iteration. In pipelined mode (the default, grammar-free)
        one decode step stays in flight: step N+1 is dispatched before step
        N's tokens are pulled to the host, hiding the device→host sync +
        Python bookkeeping behind the next step's compute. Grammar-constrained
        batches run synchronously (the sampled token must update the PDA mask
        before the next sample). Returns True while work remains.

        With the tick ledger live (ISSUE 13) each iteration runs bracketed
        by begin()/commit(): the committed record — pack composition +
        reason codes — feeds both /debug/sched's ring and the flight
        recorder's tick ring, so a post-mortem shows the last N scheduling
        DECISIONS, not just dispatch counts. Disabled, the overhead is the
        two attribute loads + branch below."""
        if faults.fire("engine_crash") is not None:
            # chaos hook (LOCALAI_FAULT=engine_crash): a deterministic fatal
            # step — drives the _loop restart + flight-recorder post-mortem
            # path in tests; one env dict miss when disarmed
            raise RuntimeError("injected engine_crash (LOCALAI_FAULT)")
        if self._preempt_req.is_set() and (
                time.monotonic() >= self._preempt_t
                or not any(s is not None for s in self._slots)):
            # grace expired (or nothing left decoding): freeze and spill
            # every live slot, manifest the queue, keep serving — the
            # caller owns what happens to the process next
            self._spill_drain()
        sched = self._sched
        if sched is None and self._flightrec is None:
            return self._step_inner()
        self._tick_n += 1
        self._set_tick(self._tick_n)
        if sched is None:
            # flight recorder without the ledger: keep the coarse summary
            # every 64 ticks (the pre-ledger ring contents)
            if (self._tick_n & 63) == 0:
                self._flightrec.record_tick({
                    "tick": self._tick_n,
                    "t_wall": time.time(),
                    "active_slots": sum(s is not None for s in self._slots),
                    "queued": self._queue.qsize(),
                    "deferred": self._deferred is not None,
                    "tokens_generated": self.metrics["tokens_generated"],
                    "decode_dispatches": self.metrics["decode_dispatches"],
                })
            return self._step_inner()
        sched.begin(self._tick_n)
        busy = self._step_inner()
        rec = sched.commit(
            active_slots=sum(s is not None for s in self._slots),
            queued=self._queue.qsize(),
            deferred=self._deferred is not None,
            tokens_generated=self.metrics["tokens_generated"],
            decode_dispatches=self.metrics["decode_dispatches"])
        if self._flightrec is not None:
            self._flightrec.record_tick(rec)
        return busy

    def _step_inner(self) -> bool:
        if self._draft is not None:
            # draft + ragged = spec-as-ragged: every tick is ONE dispatch
            # covering verify windows + prefill chunks (mm rows included)
            return (self._step_spec_ragged() if self._ragged
                    else self._step_spec())
        if self._tiered:
            self._kv_tick()
        if self._host_pending:
            # land last tick's spills (their D2H copies have arrived by
            # now) so the pool's occupancy metrics stay current even on
            # admission-free ticks
            self._host_drain()
        if self._ragged_now() and self._step_ragged():
            # mixed tick: decode + prefill ran as one ragged dispatch,
            # consumed synchronously (no pending survives a ragged tick)
            return (any(s is not None for s in self._slots)
                    or not self._queue.empty() or self._pending is not None
                    or self._deferred is not None)
        sync = self._grammar_slots > 0 or not self.ec.pipeline
        if sync and self._pending is not None:
            self._consume(self._pending)
            self._pending = None
        cur = self._dispatch()
        self._prefill_tick()
        if cur is None:
            if self._pending is not None:
                self._consume(self._pending)
                self._pending = None
        elif sync:
            self._consume(cur)
        else:
            prev, self._pending = self._pending, cur
            if prev is not None:
                self._consume(prev)
        return (any(s is not None for s in self._slots)
                or not self._queue.empty() or self._pending is not None
                or self._deferred is not None)

    def _emit(self, idx: int, slot: _Slot, token_id: int, logprob: float,
              now: float, fresh_mask: bool = True,
              path: str = "dense") -> bool:
        """Commit one sampled token to `slot` (grammar advance, detok, stop
        scan, stream, maybe finish). Returns False — with NO state mutated —
        when the slot's grammar rejects a token sampled under a STALE fused-
        block mask (fresh_mask=False); the caller then rolls the device back
        (_repair). A rejection under a FRESH mask means mask and matcher
        disagree (should not happen): finish the request defensively instead
        of livelocking on an identical resample."""
        finish = None
        shift = False
        cache_len = slot.prompt_len + slot.generated + 1 - slot.shifted
        is_eos = self.tok is not None and token_id in self.tok.eos_ids
        if is_eos and not slot.req.ignore_eos:
            finish = "eos"
        elif slot.generated + 1 >= slot.req.max_tokens:
            finish = "length"
        elif cache_len >= self.ec.max_context - 2 - self._ctx_reserve:
            if slot.req.context_shift:
                # evict-and-continue (reference ctx_shift): slide the cache
                # left, re-rotating K; the in-flight pipelined step wrote at a
                # pre-shift position and is already part of the device state
                # (spec mode rejected context_shift at submit)
                shift = True
            else:
                finish = "length"
        # eviction (ISSUE 4): a cancelled request (client gone — gRPC
        # termination callback) or an expired deadline stops consuming decode
        # lanes at the next emitted token instead of running to max_tokens
        if finish is None and slot.request_id in self._cancelled:
            finish = "cancelled"
        elif finish is None and slot.req.deadline \
                and now > slot.req.deadline:
            finish = "timeout"

        # grammar: validate + advance the PDA BEFORE mutating anything, so a
        # stale-mask rejection leaves the slot exactly at its accepted prefix
        if slot.matcher is not None:
            eos = self.tok.eos_ids if self.tok else ()
            if is_eos:
                # EOS never advances the PDA; it is legal exactly when the
                # grammar is complete (mask_bits sets the EOS bits then). A
                # stale block mask can propose EOS mid-grammar — roll back.
                if not slot.matcher.done:
                    if not fresh_mask:
                        return False
                    if finish is None:
                        finish = "stop"  # mask/matcher disagreement
                elif finish is None:
                    # ignore_eos + completed grammar: the model stopped and
                    # rolling back would just re-sample the same EOS forever
                    finish = "stop"
            elif finish is None:
                if slot.matcher.accept(token_id):
                    if slot.gbase is not None:
                        # table-backed slot: advance the host mirror of the
                        # device automaton and take the mask row straight
                        # from the table (u32 LSB-first words view as the
                        # same LSB-first u8 bytes) — skips the V-trial
                        # matcher mask walk; matcher.accept above stays the
                        # arbiter for done/can_continue/rollback
                        st = int(self._gtrans_np[self._gstate[idx], token_id])
                        self._gstate[idx] = st
                        self._mask_host[idx] = self._gmasks_np[st].view(
                            np.uint8)[:self._mask_nbytes]
                    else:
                        self._mask_host[idx] = slot.matcher.mask_bits(eos)
                    if (slot.matcher.done and not slot.matcher.can_continue
                            and not eos):
                        finish = "stop"  # complete and nothing can follow
                elif not fresh_mask:
                    return False
                else:
                    finish = "stop"  # mask/matcher disagreement (defensive)

        if slot.first_token_time is None:
            slot.first_token_time = now
            # TTFT from ARRIVAL (queued_t) — the user-perceived number,
            # queue wait included; falls back to admission time for requests
            # submitted without a queue timestamp
            self.metrics["ttft_ms_last"] = \
                (now - (slot.req.queued_t or slot.start_time)) * 1e3
        slot.generated += 1
        slot.gen_ids.append(token_id)
        slot.path_counts[path] = slot.path_counts.get(path, 0) + 1
        self.metrics["tokens_generated"] += 1
        self.metrics["tokens_by_path__" + path] += 1
        slo = self._slo
        if slo is not None:
            slot.path = path
            if slot.last_token_t is None:
                # TTFT from ARRIVAL (queued_t), matching ttft_ms_last above
                slo.observe("ttft", path,
                            now - (slot.req.queued_t or slot.start_time))
                slot.last_token_t = now
                slot.obs_tokens = slot.generated
            elif now > slot.last_token_t:
                # amortized inter-token gap: a fused-loop dispatch delivers a
                # burst sharing one host arrival — weight the gap over the
                # burst instead of recording zeros inside it
                k = slot.generated - slot.obs_tokens
                if k > 0:
                    slo.observe("tpot", path,
                                (now - slot.last_token_t) / k, n=k)
                slot.last_token_t = now
                slot.obs_tokens = slot.generated
        if shift:
            self._dev_shift(idx)
            slot.shifted += self._shift_discard

        text = ""
        if slot.detok is not None:
            if finish != "eos":
                text = slot.detok.push(token_id)
            if finish is not None:
                text += slot.detok.flush()

        # stop-string scan with holdback
        emit_text = text
        if slot.req.stop:
            slot.pending_text += text
            hold = max(len(s) for s in slot.req.stop) - 1
            matched = None
            for s in slot.req.stop:
                j = slot.pending_text.find(s)
                if j != -1 and (matched is None or j < matched[0]):
                    matched = (j, s)
            if matched is not None:
                emit_text = slot.pending_text[: matched[0]]
                slot.pending_text = ""
                finish = "stop"
            elif finish is not None:
                emit_text = slot.pending_text
                slot.pending_text = ""
            else:
                stable = len(slot.pending_text) - hold
                emit_text = slot.pending_text[:stable] if stable > 0 else ""
                slot.pending_text = slot.pending_text[max(stable, 0):]

        timings = None
        if finish is not None and slo is not None:
            timings = self._timeline(slot, finish, now)
            slot.timeline = timings   # _release_slot → flight recorder
            slo.observe("e2e", slot.path or path,
                        now - (slot.req.queued_t or slot.start_time))
        slot.sent_chars += len(emit_text)
        slot.out.put(StepOutput(
            request_id=slot.request_id, text=emit_text, token_id=token_id,
            logprob=logprob, finished=finish is not None, finish_reason=finish,
            generated_tokens=slot.generated, prompt_tokens=slot.prompt_len,
            timings=timings,
        ))
        if finish is not None:
            dur = now - slot.start_time
            if dur > 0:
                self.metrics["tokens_per_second_last"] = slot.generated / dur
            self.metrics["requests_completed"] += 1
            self._release_slot(idx, slot)
        return True

    def _timeline(self, slot: _Slot, reason: str, now: float) -> dict:
        """The request's phase timeline (ms, arrival-relative) — the final
        StepOutput's `timings` payload and the flight-recorder record."""
        qt = slot.req.queued_t or slot.start_time
        return {
            "request_id": slot.req.trace_id or f"rid-{slot.request_id}",
            "path": slot.path or "dense",
            "finish_reason": reason,
            "prompt_tokens": slot.prompt_len,
            "generated_tokens": slot.generated,
            "dispatches": slot.dispatches,
            "kv_policy": slot.req.kv_policy or self.ec.kv_policy or "full",
            "queue_wait_ms": (slot.start_time - qt) * 1e3,
            "prefill_ms": ((slot.prefill_done_t - slot.start_time) * 1e3
                           if slot.prefill_done_t is not None else None),
            "ttft_ms": ((slot.first_token_time - qt) * 1e3
                        if slot.first_token_time is not None else None),
            "e2e_ms": (now - qt) * 1e3,
            "t_wall_finished": time.time(),
        }

    # --------------------------------------------- paged-KV block allocator
    # Host-side, reservation-based: a request reserves every block it could
    # ever write (prompt + max_tokens + in-flight margin) at admission, so
    # generation can never exhaust the pool mid-flight — oversubscription
    # comes from max_tokens being much smaller than max_context. Released
    # slots RETAIN their blocks (the warm prefix cache) until the pool runs
    # short, then the least-recently-released slot is reclaimed. On top of
    # that, full 128-token blocks are content-hash-indexed at release, so a
    # NEW admission sharing the prompt prefix maps the same physical pages
    # into its own table (refcounted, copy-on-write: a borrower only ever
    # writes positions past the shared prefix, which live in fresh blocks).

    def _req_policy(self, req: GenRequest):
        """Effective retention policy for `req` (before pressure demotion).
        Falls back to the engine policy on a malformed request policy —
        submit() already rejected those; this keeps _blocks_for total."""
        from localai_tpu.engine import kvtier

        try:
            return kvtier.resolve_policy(req.kv_policy, self._kv_policy)
        except ValueError:
            return self._kv_policy

    def _blocks_for(self, req: GenRequest) -> int:
        from localai_tpu.ops.paged import blocks_needed

        margin = 2 * self.ec.decode_block + 1   # in-flight pipelined writes
        if self._draft is not None:
            # the spec-verify window writes up to gamma+1 positions past the
            # sampled length — the reservation must cover the overshoot or
            # the tail of the window silently lands in the trash block
            margin = max(margin, self.ec.gamma + 1)
        tokens = min(len(req.prompt_ids) + max(req.max_tokens, 0) + margin,
                     self.ec.max_context)
        need = blocks_needed(tokens)
        if self._tiered:
            # retention bounds residency: the compact table holds at most
            # sink+ring columns per slot however long the sequence runs
            # (the ring reuses its blocks in place), and a full-policy
            # request larger than the table demotes to the engine window at
            # admission — so a ctx-64k request under sink_window is NOT
            # rejected for blocks it will never hold resident
            need = min(need, self._maxb)
        return need

    def _ref_blocks(self, blocks):
        for pb in blocks:
            self._block_ref[pb] += 1

    def _unref_blocks(self, blocks):
        """Drop one reference from each block; blocks reaching zero return
        to the free pool (their content is dead — any hash entry with it)."""
        freed = False
        for pb in blocks:
            self._block_ref[pb] -= 1
            if self._block_ref[pb] <= 0:
                self._block_ref[pb] = 0
                if self._kvhost is not None:
                    # last reference on registered content: catch it in the
                    # host tier before the page returns to the free pool
                    self._spill_block(pb)
                self._drop_hash(pb)
                self._kv_free.append(pb)
                freed = True
        if freed:
            self._blocks_freed = True

    def _drop_hash(self, pb: int):
        """Forget a block's registered content (freed or about to be
        rewritten) so the prefix index can never serve stale pages."""
        h = self._block_hash_of.pop(pb, None)
        if h is not None and self._hash_index.get(h) == pb:
            del self._hash_index[h]

    @staticmethod
    def _chain_hashes(ids) -> list[bytes]:
        """Chain content hashes of consecutive full 128-token blocks: the
        hash of block v commits to every token before it, so equal hash ⇒
        equal whole prefix AND equal absolute positions (K rows are stored
        post-RoPE — position-dependent — which a flat per-block hash would
        get wrong)."""
        import hashlib

        from localai_tpu.ops.paged import BLOCK

        h = b""
        out = []
        for vb in range(len(ids) // BLOCK):
            blk = np.asarray(ids[vb * BLOCK:(vb + 1) * BLOCK], np.int64)
            h = hashlib.blake2b(h + blk.tobytes(), digest_size=16).digest()
            out.append(h)
        return out

    def _match_prefix_blocks(self, prompt_ids) -> tuple[list[int], int]:
        """Block-level prefix cache lookup: the longest run of leading full
        128-token blocks whose chain hash is registered. Matched blocks are
        ref'd for the caller — commit them via _alloc_slot(shared=...) or
        return them with _unref_blocks on any bail-out.
        Returns (physical blocks, tokens covered)."""
        from localai_tpu.ops.paged import BLOCK

        limit = self.ec.max_context - 2 - self._ctx_reserve
        nfull = min(len(prompt_ids) - 1, limit - 1) // BLOCK
        blocks: list[int] = []
        for h in self._chain_hashes(prompt_ids[:nfull * BLOCK]):
            pb = self._hash_index.get(h)
            if pb is None:
                break
            blocks.append(pb)
        self._ref_blocks(blocks)
        return blocks, len(blocks) * BLOCK

    def _take_blocks(self, k: int, keep_slot: int):
        """Pop k free blocks (ref'd for the caller), reclaiming released
        slots' retained blocks (oldest first, never `keep_slot` — its prefix
        is being reused). A victim's pages that other tenants still share
        stay alive (refcount) — only its last reference frees a block.
        Returns None when the pool genuinely cannot satisfy k."""
        while len(self._kv_free) < k:
            victim = next((s for s in self._released_lru if s != keep_slot),
                          None)
            if victim is None:
                return None
            self._released_lru.remove(victim)
            if self._kvhost is not None and self._slot_blocks[victim]:
                # the victim's retained chain dies as one session: group
                # its spills under the chain-head hash so host-tier LRU
                # evicts whole conversations, tail-first
                self._spill_group = self._block_hash_of.get(
                    self._slot_blocks[victim][0])
            self._unref_blocks(self._slot_blocks[victim])
            self._spill_group = None
            self._slot_blocks[victim] = []
            self._slot_kv_tokens[victim] = []
            self._table[victim, :] = 0
        out = self._kv_free[:k]
        del self._kv_free[:k]
        self._ref_blocks(out)
        return out

    def _alloc_slot(self, slot: int, req: GenRequest, shared=None,
                    lcp: int = 0):
        """Size `slot`'s block list for `req`; update the table row.

        `shared`: already-ref'd physical blocks from _match_prefix_blocks —
        they become the slot's head (the borrowed prefix pages). `lcp`: the
        token prefix the request will NOT rewrite (slot-retained or shared
        reuse). Returns the EFFECTIVE reusable prefix length (may shrink —
        see the copy-on-write pass), or None when the pool is exhausted
        (defer; `shared` refs are returned here on that path)."""
        from localai_tpu.ops.paged import BLOCK

        need = self._blocks_for(req)
        have = self._slot_blocks[slot]
        if shared is not None:
            fresh = self._take_blocks(need - len(shared), keep_slot=slot) \
                if need > len(shared) else []
            if fresh is None:
                self._unref_blocks(shared)
                return None
            self._unref_blocks(have)
            have = list(shared) + fresh
            self._slot_blocks[slot] = have
        else:
            old_len = len(have)
            if len(have) < need:
                got = self._take_blocks(need - len(have), keep_slot=slot)
                if got is None:
                    return None
                have.extend(got)
            elif len(have) > need:
                self._unref_blocks(have[need:])
                del have[need:]
            # copy-on-write: every block from the first written one onward
            # gets rewritten by this request. A page another tenant still
            # reads (ref > 1) must not be written in place — swap in a
            # fresh block. Context-shift requests rotate even their prefix
            # blocks, so for them EVERY shared page swaps (lcp arrives 0).
            j0 = lcp // BLOCK
            swap = [j for j in range(j0, len(have))
                    if self._block_ref[have[j]] > 1]
            if swap:
                got = self._take_blocks(len(swap), keep_slot=slot)
                if got is None:
                    # roll the extension back: a deferred slot must not sit
                    # on fresh blocks the retry (or another request) needs
                    if len(have) > old_len:
                        self._unref_blocks(have[old_len:])
                        del have[old_len:]
                    return None
                for j, nb in zip(swap, got):
                    self._unref_blocks([have[j]])
                    have[j] = nb
                if swap[0] == j0:
                    # the partially-reused block itself was swapped: the
                    # rows [j0*BLOCK, lcp) went with it
                    lcp = j0 * BLOCK
        # the to-be-written blocks' old content is dead the moment the
        # first new row lands — their hash entries must go now, or the
        # index would hand out pages mid-rewrite. The host tier catches
        # each registered block on the way out (the spill's async D2H is
        # enqueued before this request's first prefill dispatch can
        # rewrite the page — same-stream ordering)
        for j in range(lcp // BLOCK, len(have)):
            if self._kvhost is not None:
                self._spill_block(
                    have[j], group=self._block_hash_of.get(have[0]))
            self._drop_hash(have[j])
        self._table[slot, :] = 0
        self._table[slot, :len(have)] = have
        if slot in self._released_lru:
            self._released_lru.remove(slot)
        return lcp

    def _pick_slot(self, prompt_ids: list[int]) -> tuple[int, int]:
        """Choose a free slot, preferring one whose cached tokens share the
        longest prefix with the new prompt (llama.cpp's slot prompt cache).
        Returns (slot, reusable_prefix_len); 0 = cold prefill."""
        limit = self.ec.max_context - 2 - self._ctx_reserve

        def common(cached: list[int]) -> int:
            m = min(len(cached), len(prompt_ids) - 1, limit - 1)
            i = 0
            while i < m and cached[i] == prompt_ids[i]:
                i += 1
            return i

        best_slot, best_lcp = None, 0
        if self.ec.prompt_cache and self._draft is None:
            for s in self._free:
                lcp = common(self._slot_kv_tokens[s])
                if lcp > best_lcp:
                    best_slot, best_lcp = s, lcp
        if best_slot is not None and best_lcp >= self.ec.prompt_cache_min:
            self._free.remove(best_slot)
            return best_slot, best_lcp
        # cold admission: take the free slot with the LEAST useful cached
        # record, so other tenants' warm prefixes survive (llama.cpp picks
        # the slot without a usable cache the same way)
        cold = min(self._free,
                   key=lambda s: len(self._slot_kv_tokens[s]))
        self._free.remove(cold)
        return cold, 0

    # --------------------------------------------- disk prompt cache
    # (reference PromptCachePath/PromptCacheAll/PromptCacheRO — llama.cpp
    # persists a prompt's KV to a file and restores it across restarts)

    def _load_prompt_cache(self, slot: int, req: GenRequest) -> int:
        """Restore a saved KV prefix into `slot` if the file's tokens prefix
        this prompt. Returns the reusable length (0 = cold)."""
        if (not self._cache_addressable or self._draft is not None
                or self._paged):
            return 0
        try:
            with np.load(req.prompt_cache_path, allow_pickle=False) as z:
                tokens = z["tokens"].tolist()
                leaves = {k: z[k] for k in z.files if k != "tokens"}
        except Exception:
            # corrupt/truncated/foreign files raise a zoo (BadZipFile,
            # zlib.error, ValueError...) — all of them mean cold prefill,
            # never a dead engine
            return 0
        limit = self.ec.max_context - 2 - self._ctx_reserve
        m = min(len(tokens), len(req.prompt_ids) - 1, limit - 1)
        lcp = 0
        while lcp < m and tokens[lcp] == req.prompt_ids[lcp]:
            lcp += 1
        if lcp < self.ec.prompt_cache_min:
            return 0
        try:
            self._kc, self._vc = self._cache_inject(
                self._kc, self._vc, slot, leaves, lcp)
        except Exception:
            return 0
        return lcp

    def _cache_inject(self, kc, vc, slot: int, leaves: dict, n: int):
        """Write saved KV rows [L, KVH, n, D] into slot's cache region."""
        from localai_tpu.ops.kvcache import QuantKV

        if isinstance(kc, QuantKV):
            kc = QuantKV(kc.q.at[:, slot, :, :n].set(leaves["kq"][:, :, :n]),
                         kc.s.at[:, slot].set(leaves["ks"]))
            vc = QuantKV(vc.q.at[:, slot, :, :n].set(leaves["vq"][:, :, :n]),
                         vc.s.at[:, slot].set(leaves["vs"]))
            return kc, vc
        kc = kc.at[:, slot, :, :n].set(
            jnp.asarray(leaves["k"][:, :, :n], kc.dtype))
        vc = vc.at[:, slot, :, :n].set(
            jnp.asarray(leaves["v"][:, :, :n], vc.dtype))
        return kc, vc

    def _save_prompt_cache(self, idx: int, slot: _Slot):
        """Persist the slot's prompt-KV rows + token ids to the request's
        cache file (skipped for RO requests, meshes, shifted slots)."""
        if (not slot.req.prompt_cache_path or slot.req.prompt_cache_ro
                or not self._cache_addressable or self._draft is not None
                or self._paged or slot.shifted or not slot.prefilled
                or slot.req.mm_embeds is not None):
            # (mm: no reuse path can load it, and the repeated image-token
            # ids could positionally match a text prompt — see _release_slot)
            return
        n = min(slot.prompt_len, self.ec.max_context - 2)
        if slot.disk_prefix >= n - 1:
            return   # the file already covers this prompt — skip the
                     # device→host transfer + rewrite (hot shared prefix)
        try:
            from localai_tpu.ops.kvcache import QuantKV

            if isinstance(self._kc, QuantKV):
                leaves = {
                    "kq": np.asarray(self._kc.q[:, idx, :, :n]),
                    "ks": np.asarray(self._kc.s[:, idx]),
                    "vq": np.asarray(self._vc.q[:, idx, :, :n]),
                    "vs": np.asarray(self._vc.s[:, idx]),
                }
            else:
                # f32 on disk: npz round-trips bfloat16 as raw void bytes
                # that cannot cast back — upcast once here instead
                leaves = {
                    "k": np.asarray(self._kc[:, idx, :, :n]).astype(
                        np.float32),
                    "v": np.asarray(self._vc[:, idx, :, :n]).astype(
                        np.float32),
                }
            tmp = slot.req.prompt_cache_path + ".tmp"
            with open(tmp, "wb") as f:   # file handle: savez must not
                np.savez(f, tokens=np.asarray(   # append its own .npz
                    slot.req.prompt_ids[:n], np.int64), **leaves)
            os.replace(tmp, slot.req.prompt_cache_path)
        except Exception:   # best-effort: a faulted device or full disk
                            # must not break _fail_active's cleanup loop
            import logging

            logging.getLogger("localai_tpu").warning(
                "failed to write prompt cache %s",
                slot.req.prompt_cache_path, exc_info=True)

    def _release_slot(self, idx: int, slot: _Slot):
        self._finish_rid(slot.request_id)
        if self._flightrec is not None and slot.timeline is not None:
            self._flightrec.record_request(slot.timeline)
        if slot.span is not None and self._tracer is not None:
            ttft_ms = ((slot.first_token_time - slot.start_time) * 1e3
                       if slot.first_token_time is not None else None)
            self._tracer.finish(slot.span, generated=slot.generated,
                                ttft_ms=ttft_ms)
            slot.span = None
        self._save_prompt_cache(idx, slot)
        if slot.matcher is not None:
            self._mask_host[idx] = 0xFF
            self._grammar_slots -= 1
            self._gstate[idx] = 0  # row 0 = identity (all-ones, self-loop)
            if slot.gbase is None:
                self._grammar_hostonly -= 1
        if self.record_paths:
            self.req_path_counts[slot.request_id] = dict(slot.path_counts)
        windowed = False
        if self._tiered:
            pol = self._slot_policy[idx]
            windowed = pol is not None and pol.windowed
        if self._paged:
            if (self.ec.prompt_cache and slot.shifted == 0
                    and self._draft is None and not windowed):
                # retain ONLY the blocks holding cached rows as the warm
                # prefix cache (reclaimable oldest-first, _take_blocks); the
                # unused tail of the reservation returns to the pool now.
                # Safe against the in-flight pipelined step: it writes
                # through the table captured at ITS dispatch, and device
                # ordering runs it before any later admission's prefill.
                from localai_tpu.ops.paged import blocks_needed

                kept = min(slot.prompt_len + slot.generated,
                           self.ec.max_context - 2)
                keep = blocks_needed(kept)
                blocks = self._slot_blocks[idx]
                if len(blocks) > keep:
                    self._unref_blocks(blocks[keep:])
                    del blocks[keep:]
                    self._table[idx, keep:] = 0
                # register every FULL block in the content-hash index: a
                # future admission sharing the prefix maps these pages into
                # its own table (block-level prefix cache). Multimodal rows
                # are excluded for the same reason as the token record
                # below — identical image-token ids, different KV.
                if slot.req.mm_embeds is None:
                    ids = (list(slot.req.prompt_ids) + slot.gen_ids)[:kept]
                    for vb, h in enumerate(self._chain_hashes(ids)):
                        pb = blocks[vb]
                        if h not in self._hash_index:
                            self._drop_hash(pb)
                            self._hash_index[h] = pb
                            self._block_hash_of[pb] = h
                self._released_lru.append(idx)
            else:
                # windowed slots land here too: ring columns hold position-
                # rotated content no other tenant can address, so nothing is
                # retained or hash-registered — every block returns NOW
                self._unref_blocks(self._slot_blocks[idx])
                self._slot_blocks[idx] = []
                self._table[idx, :] = 0
            self._blocks_freed = True
        if self._tiered:
            # reset the slot's geometry to the full-policy sentinels (the
            # in-flight pipelined dispatch captured ITS OWN copy at
            # dispatch time — _kvt materializes per call)
            self._kv_sb[idx] = self._maxb
            self._kv_rw[idx] = 1
            self._kv_sinks[idx] = self.ec.max_context
            self._kv_window[idx] = self.ec.max_context
            self._slot_policy[idx] = None
            self._demote_next[idx] = 0
            if self._cold:
                for ci in self._slot_cold[idx]:
                    self._cold_free.append(ci)
                self._slot_cold[idx] = []
                self._cold_table[idx, :] = 0
            self._note_pool()
        # record what this slot's cache still holds (valid rows 0..len-1) so
        # a future prompt sharing the prefix skips that part of its prefill.
        # Shifted slots moved rows — their mapping is no longer positional.
        # (multimodal prompts excluded: their image-token ids all look alike
        # while the injected embeddings differ per image, so positional
        # prefix-matching on ids would reuse the WRONG image's KV)
        if (self.ec.prompt_cache and self._draft is None
                and slot.shifted == 0 and slot.req.mm_embeds is None
                and not windowed):
            kept = (list(slot.req.prompt_ids) + slot.gen_ids)[
                : self.ec.max_context - 2]
            self._slot_kv_tokens[idx] = kept
        else:
            self._slot_kv_tokens[idx] = []
        self._slots[idx] = None
        self._free.append(idx)

    # ------------------------------------------------------------ run modes

    def warmup(self):
        """Pre-compile the decode hot-path programs — the while-loop decode
        variants (every sort-free sampling tier) plus the remaining scan
        ladder widths the grammar/stop-string fallback still rides — so the
        first requests (and bench window 0) never pay an XLA compile
        mid-stream. Dispatches run with an all-inactive slot mask: every
        cache write redirects to the trash row/block and no slot state is
        consumed, but it MUST run before any request is admitted. Dispatch
        metrics are snapshotted so warmup doesn't pollute the fusing
        telemetry."""
        if any(s is not None for s in self._slots):
            raise RuntimeError("warmup() requires an idle engine")
        B, V = self.ec.max_slots, self.cfg.vocab_size
        snap = {k: self.metrics[k] for k in (
            "decode_dispatches", "decode_steps_dispatched",
            "host_sync_wait_ms") + (
            ("ragged_dispatches", "ragged_tokens_packed",
             "budget_utilization", "ragged_prefill_tokens",
             "spec_ragged_dispatches")
            if self._ragged else ())}
        idle = np.zeros((B,), bool)
        ones_mask = np.full((B, self._mask_nbytes), 0xFF, np.uint8)
        idle_gstate = (np.zeros((B,), np.int32)
                       if self._gtab_cap > 0 else None)
        try:
            if self._draft is not None:
                if self._spec_ragged_fn is not None:
                    # spec-as-ragged: warm every variant a mixed tenant soup
                    # can reach (grammar tables x multimodal inject) so the
                    # one-program tick never compiles mid-stream
                    T = self._ragged_rows
                    from localai_tpu.ops.pallas import QBLK
                    G = self.ec.gamma
                    base = dict(
                        verify=idle,
                        tokens=np.zeros((T,), np.int32),
                        spec_rows=np.zeros((B,), np.int32),
                        set_len=np.full((B,), -1, np.int32),
                        logit_set=np.zeros((B,), bool),
                        logit_rows=np.zeros((B, G + 1), np.int32),
                        block_seq=np.full((T // QBLK,), -1, np.int32),
                        qstart=np.zeros((B,), np.int32),
                        qlen=np.zeros((B,), np.int32),
                        kvlen=np.zeros((B,), np.int32),
                        packed=0, gstate=None, inject=None)
                    inj = (np.zeros((T, self.cfg.hidden_size), np.float32),
                           np.zeros((T,), bool))
                    variants = [dict(base)]
                    if idle_gstate is not None:
                        variants.append(dict(base, gstate=idle_gstate))
                    variants.append(dict(base, inject=inj))
                    if idle_gstate is not None:
                        variants.append(dict(base, gstate=idle_gstate,
                                             inject=inj))
                    for pk in variants:
                        self._dev_spec_ragged(pk).wait()
                else:
                    self._dev_spec_decode(idle).wait()
                return
            if self._ragged:
                # all-dead packs compile the ragged program's variant set
                # (shapes are fixed — [T] stream + [B] metadata — so one
                # trace per mask/inject presence combination covers every
                # future mix of decode rows, grammar slots and mm chunks)
                T = self._ragged_rows
                from localai_tpu.ops.pallas import QBLK
                base = dict(
                    tokens=np.zeros((T,), np.int32),
                    decode_slot=np.full((T,), -1, np.int32),
                    is_decode=np.zeros((B,), bool),
                    set_len=np.full((B,), -1, np.int32),
                    logit_set=np.zeros((B,), bool),
                    logit_rows=np.zeros((B,), np.int32),
                    block_seq=np.full((T // QBLK,), -1, np.int32),
                    qstart=np.zeros((B,), np.int32),
                    qlen=np.zeros((B,), np.int32),
                    kvlen=np.zeros((B,), np.int32),
                    packed=0, mask=None, inject=None)
                inj = (np.zeros((T, self.cfg.hidden_size), np.float32),
                       np.zeros((T,), bool))
                for pk in (dict(base), dict(base, mask=ones_mask),
                           dict(base, inject=inj),
                           dict(base, mask=ones_mask, inject=inj)):
                    self._dev_ragged(pk).wait()
                if self._ragged_loop_fn is not None:
                    # fused multi-step pack variants (ISSUE 16): the loop
                    # program is one trace per grammar-table presence —
                    # prefill_pending/remaining are traced runtime values,
                    # so one all-dead dispatch covers every future mix
                    lp = {k: v for k, v in base.items()
                          if k not in ("mask", "inject")}
                    self._dev_ragged_loop(
                        dict(lp), np.zeros((B,), np.int32),
                        np.zeros((B,), bool), False).wait()
                    if idle_gstate is not None:
                        self._dev_ragged_loop(
                            dict(lp), np.zeros((B,), np.int32),
                            np.zeros((B,), bool), False,
                            gstate=idle_gstate).wait()
            widths = [None]
            W = self.ec.sampling_topk_width
            if W:
                widths.append(min(W, V))
                if min(8 * W, V) != min(W, V):
                    widths.append(min(8 * W, V))   # the escalation tier
            for w in widths:
                if self._ragged_loop_fn is not None:
                    # fused-ragged engines dispatch the loop's pack-free
                    # variant for pure-decode ticks; _dev_decode_loop never
                    # runs there, so warming it would be a wasted compile
                    self._dev_rloop_decode(
                        idle, np.zeros((B,), np.int32),
                        np.zeros((B,), bool), w).wait()
                elif self._decode_loop_fn is not None:
                    self._dev_decode_loop(
                        idle, np.zeros((B,), np.int32),
                        np.zeros((B,), bool), w).wait()
                self._dev_decode(idle, None, w).wait()
            if self._ragged_loop_fn is not None and idle_gstate is not None:
                self._dev_rloop_decode(idle, np.zeros((B,), np.int32),
                                       np.zeros((B,), bool), None,
                                       gstate=idle_gstate).wait()
            elif (self._decode_loop_fn is not None
                    and idle_gstate is not None):
                # the grammar-table loop variant (full-sort sampling only —
                # masked slots never ride a fast_width tier)
                self._dev_decode_loop(idle, np.zeros((B,), np.int32),
                                      np.zeros((B,), bool), None,
                                      gstate=idle_gstate).wait()
            # the dense masked step: the path every grammar config can
            # still fall back to (host-only automata, decode_loop=0)
            self._dev_decode(idle, ones_mask, None).wait()
            steps = self.ec.decode_block
            while steps > 1:
                self._dev_decode_block(idle, steps, None, None).wait()
                steps //= 2
        finally:
            self.metrics.update(snap)
            if self._sched is not None:
                # keep the captured variant avals (rooflines needs them) but
                # drop the warmup dispatches from the ledger stream — the
                # serving/bench counters start clean, same as `snap` above
                self._sched.reset()

    def rooflines(self, force: bool = False) -> dict:
        """Per-variant XLA cost analysis → roofline attribution (ISSUE 13).

        AOT-lowers each captured decode/ragged/spec/loop variant with its
        abstract arg shapes (jax.ShapeDtypeStruct — see _sched_pack) and
        reads `compile().cost_analysis()` for FLOPs + bytes accessed. The
        AOT compile does NOT populate the jit call cache, so the
        compile-count tripwire (decode_compile_count) is unaffected — but
        it IS a real XLA compile per variant, visible to jax.log_compiles:
        call this off the measured path (bench: after the windows; server:
        first /debug/sched or GetTrace). Results are cached on the engine
        and mirrored into the tick ledger for GetMetrics `sched_roofline_*`
        keys and the profiler's cost-backed per-stage MFU."""
        if self._rooflines is not None and not force:
            return self._rooflines
        from localai_tpu import telemetry

        kind = ""
        try:
            d = jax.devices()[0]
            kind = getattr(d, "device_kind", d.platform)
        except Exception:
            pass
        peak = telemetry.peak_flops(kind)
        bw = telemetry.peak_bandwidth(kind)
        out: dict[str, dict] = {}
        for name, spec in list(self._variant_avals.items()):
            if spec is None:
                continue
            fn, fargs, fkw = spec
            try:
                with activate_mesh(self.mesh):
                    ca = fn.lower(*fargs, **fkw).compile().cost_analysis()
            except Exception:
                continue
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if not ca:
                continue
            flops = float(ca.get("flops", 0.0))
            bytes_ = float(ca.get("bytes accessed", 0.0))
            if flops <= 0 and bytes_ <= 0:
                continue
            out[name] = telemetry.roofline_entry(flops, bytes_, peak, bw)
        self._rooflines = out
        if self._sched is not None:
            self._sched.rooflines = out
        if self._prof is not None and out:
            # fold per-variant costs onto the profiler's stage names (the
            # first matching variant stands for the stage — stages share
            # one program modulo static knobs)
            stage_of = (("spec_ragged", "spec_ragged"),
                        ("decode_block", "decode_block"),
                        ("loop", "decode_loop"), ("ragged", "ragged"),
                        ("decode", "decode"), ("spec", "spec_decode"))
            costs: dict[str, dict] = {}
            for name, e in out.items():
                for prefix, stage in stage_of:
                    if name.startswith(prefix) and stage not in costs:
                        costs[stage] = {"flops": e["cost_flops"],
                                        "bytes": e["cost_bytes"]}
                        break
            self._prof.set_costs(costs)
        return out

    def sched_snapshot(self, ticks: int = 64,
                       with_rooflines: bool = True) -> dict:
        """Structured tick-ledger export for /debug/sched and GetTrace —
        {} when the ledger is disabled. Computes (and caches) the roofline
        pass on first call unless `with_rooflines` is False."""
        if self._sched is None:
            return {}
        if with_rooflines:
            try:
                self.rooflines()
            except Exception:
                pass
        snap = self._sched.snapshot(ticks)
        kh = self.kvhost_snapshot()
        if kh:
            snap["kv_host"] = kh
        return snap

    def start(self):
        """Run the engine loop in a background thread (serving mode)."""
        if self._running:
            return
        self._running = True
        self._dead = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        was_serving = self._thread is not None
        self._running = False
        self._dead = True
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=30)
            if self._thread.is_alive():
                # thread stuck (e.g. mid-compile): do NOT reclaim slots it may
                # still touch — consumers see the engine as dead via submit()
                return
            self._thread = None
        if was_serving:
            self._fail_active("cancelled")

    def preempt(self, grace: float = 0.0) -> list[dict]:
        """Preemption notice (ISSUE 19): freeze every in-flight request,
        force-spill their KV chains to the host tier, and return a resume
        manifest (one ResumeToken dict per live/queued request).

        For up to ``grace`` seconds the engine keeps decoding — slots that
        finish naturally stream their normal terminal chunk — then the
        spill-drain runs at a tick boundary: each surviving slot gets a
        terminal StepOutput with finish_reason "preempted" carrying its
        checkpoint.  Unlike drain_model (wait for idle) or a kill (lose
        everything), nothing is waited to completion and nothing is lost.

        Safe from any thread; with no loop thread running (generate()/test
        mode) the drain runs inline.  The engine stays serviceable — a
        resume may be submitted right back into it."""
        if self._dead:
            return []
        self._preempt_manifest = []
        self._preempt_done.clear()
        self._preempt_t = time.monotonic() + max(float(grace), 0.0)
        if self._thread is not None and self._thread.is_alive():
            self._preempt_req.set()
            self._wake.set()
            self._preempt_done.wait(timeout=max(float(grace), 0.0) + 60.0)
        else:
            self._preempt_req.set()
            while (self._preempt_req.is_set()
                   and time.monotonic() < self._preempt_t
                   and any(s is not None for s in self._slots)):
                self.step()
            if self._preempt_req.is_set():
                self._spill_drain()
        return list(self._preempt_manifest)

    def _spill_drain(self):
        """Engine-thread half of preempt(): consume the in-flight pipelined
        dispatch, checkpoint + spill + release every live slot, manifest
        queued/deferred work, land the spills in the host pool."""
        from localai_tpu.engine.resume import ResumeToken

        self._preempt_req.clear()
        t0 = time.perf_counter()
        if self._pending is not None:
            self._consume(self._pending)
            self._pending = None
        self._prefillq.clear()
        manifest: list[dict] = []
        live = [i for i, s in enumerate(self._slots) if s is not None]
        keys = None
        if live:
            try:
                # explicit sanctioned D2H read (same class as _AsyncFetch
                # .wait): the per-slot RNG carry keys advance on device per
                # dispatch, so byte-exact sampled resume needs the real
                # device values, not a host-side replay from the seed
                keys = np.asarray(jax.device_get(self._sampler.key))
            except Exception:
                keys = None    # greedy-only resume still works
        now = time.monotonic()
        spilled_total = 0
        frozen_rids: set[int] = set()
        for idx in live:
            slot = self._slots[idx]
            if slot is None:
                continue
            frozen_rids.add(slot.request_id)
            tok, spilled = self._freeze_slot(idx, slot, keys, now)
            spilled_total += spilled
            manifest.append(tok.to_dict())
            timings = None
            if self._slo is not None:
                timings = self._timeline(slot, "preempted", now)
                slot.timeline = timings
            slot.out.put(StepOutput(
                request_id=slot.request_id, text="", token_id=-1,
                logprob=0.0, finished=True, finish_reason="preempted",
                generated_tokens=slot.generated,
                prompt_tokens=slot.prompt_len,
                timings=timings, resume=tok.to_dict(),
            ))
            if not slot.prefilled:
                # mid-prefill slot: its block list is only partially
                # written — take _release_slot's no-retention path (the
                # shifted branch) so garbage blocks are never registered
                # in the prefix-cache hash index
                slot.shifted = max(slot.shifted, 1)
            self._release_slot(idx, slot)
        # queued / deferred / mid-admission requests have no device state:
        # their manifest entries are plain resubmits (emitted=[])
        waiting = []
        if self._deferred is not None:
            waiting.append(self._deferred)
            self._deferred = None
        if self._admitting is not None:
            rid, req, out = self._admitting
            self._admitting = None
            if rid not in frozen_rids:   # died before reaching a slot
                waiting.append((rid, req, out))
        while True:
            try:
                waiting.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for rid, req, out in waiting:
            tok = ResumeToken(
                prompt_ids=list(req.prompt_ids), emitted=[],
                deadline_left=(max(req.deadline - now, 0.0)
                               if req.deadline else 0.0),
                request_id=req.trace_id or f"rid-{rid}")
            manifest.append(tok.to_dict())
            self._finish_rid(rid)
            out.put(StepOutput(
                request_id=rid, text="", token_id=-1, logprob=0.0,
                finished=True, finish_reason="preempted",
                prompt_tokens=len(req.prompt_ids),
                resume=tok.to_dict(),
            ))
        self._host_drain()
        self.metrics["preempts"] += 1
        self.metrics["preempt_spilled_blocks"] += spilled_total
        if self._flightrec is not None:
            self._flightrec.record_event(
                "preempt", slots=len(live), queued=len(waiting),
                spilled_blocks=spilled_total,
                drain_ms=(time.perf_counter() - t0) * 1e3)
        self._preempt_manifest = manifest
        self._preempt_done.set()

    def _freeze_slot(self, idx: int, slot: _Slot, keys, now: float):
        """Checkpoint one live slot into a ResumeToken, force-spilling its
        full KV chain blocks to the host tier (same eligibility rules as
        _release_slot's retention: no mm, no shift, no draft, no window)."""
        from localai_tpu.engine.resume import ResumeToken

        req = slot.req
        spilled = 0
        chain_hex: list[str] = []
        windowed = False
        if self._tiered:
            pol = self._slot_policy[idx]
            windowed = pol is not None and pol.windowed
        if (self._paged and self.ec.prompt_cache and self._kvhost is not None
                and slot.prefilled and slot.shifted == 0
                and req.mm_embeds is None and self._draft is None
                and not windowed):
            from localai_tpu.ops.paged import BLOCK

            kept = min(slot.prompt_len + slot.generated,
                       self.ec.max_context - 2)
            ids = (list(req.prompt_ids) + slot.gen_ids)[:kept]
            chain = self._chain_hashes(ids)
            blocks = self._slot_blocks[idx]
            group = chain[0] if chain else None
            for vb, h in enumerate(chain):
                if vb >= len(blocks):
                    break
                self._spill_block(blocks[vb], h=h, group=group)
                spilled += 1
                chain_hex.append(h.hex())
            if spilled and self._sched is not None:
                self._sched.reason("preempt_spill", slot=int(idx),
                                   blocks=int(spilled))
        key = None
        if keys is not None and not req.params.normalized().greedy:
            key = [int(k) for k in np.asarray(keys[idx], np.uint32)]
        # a slot that is itself a resume carries replayed emitted-chain
        # tokens inside its prompt (resume_base); fold them back into the
        # checkpoint's emitted list so the ORIGINAL prompt boundary — and
        # with it detok replay and sent_chars dedup — stays fixed across
        # any number of preempt/resume rounds
        cut = slot.prompt_len - slot.resume_base
        return ResumeToken(
            prompt_ids=list(req.prompt_ids[:cut]),
            emitted=list(req.prompt_ids[cut:]) + list(slot.gen_ids),
            key=key,
            sent_chars=int(slot.sent_chars),
            chain=chain_hex,
            deadline_left=(max(req.deadline - now, 0.0)
                           if req.deadline else 0.0),
            request_id=req.trace_id or f"rid-{slot.request_id}",
        ), spilled

    def _fail_active(self, reason: str):
        """Send a terminal StepOutput to every in-flight slot + queued request
        so no consumer blocks forever on its output queue."""
        self._pending = None
        self._prefillq.clear()
        failed_rids = set()
        for slot in self._slots:
            if slot is not None:
                failed_rids.add(slot.request_id)
        if self._deferred is not None:
            rid, req, out = self._deferred
            self._deferred = None
            self._finish_rid(rid)
            out.put(StepOutput(request_id=rid, text="", token_id=-1,
                               logprob=0.0, finished=True,
                               finish_reason=reason))
        if self._admitting is not None:
            rid, req, out = self._admitting
            self._admitting = None
            if rid not in failed_rids:  # died before reaching a slot
                self._finish_rid(rid)
                out.put(StepOutput(request_id=rid, text="", token_id=-1,
                                   logprob=0.0, finished=True,
                                   finish_reason=reason))
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            timings = None
            if self._slo is not None:
                # the dying request's timeline reaches the flight recorder
                # (via _release_slot) and its terminal chunk — the black-box
                # record the post-mortem dump is for
                timings = self._timeline(slot, reason, time.monotonic())
                slot.timeline = timings
            slot.out.put(StepOutput(
                request_id=slot.request_id, text="", token_id=-1, logprob=0.0,
                finished=True, finish_reason=reason,
                generated_tokens=slot.generated, prompt_tokens=slot.prompt_len,
                timings=timings,
            ))
            self._release_slot(i, slot)
        while True:
            try:
                rid, req, out = self._queue.get_nowait()
            except queue.Empty:
                break
            self._finish_rid(rid)
            out.put(StepOutput(request_id=rid, text="", token_id=-1,
                               logprob=0.0, finished=True,
                               finish_reason=reason))

    def _loop(self):
        restarts = 0
        while self._running:
            try:
                busy = self.step()
            except Exception as e:  # device OOM, compile failure, ...
                import traceback

                traceback.print_exc()
                self._fail_active("error")
                # black box first (rare path — always recorded, dump capped):
                # the ring now holds every failed request's timeline
                from localai_tpu.telemetry import flightrec

                rec = flightrec()
                rec.record_event("engine_fatal",
                                 error=f"{type(e).__name__}: {e}",
                                 restarts=restarts)
                rec.auto_dump("engine_fatal")
                if restarts >= self.ec.max_restarts:
                    self._running = False
                    self._dead = True
                    return
                restarts += 1
                # donation may have invalidated the carried device buffers —
                # rebuild state from scratch (weights are never donated) and
                # keep serving new requests
                try:
                    self._bcast("reset")
                    self._init_device_state()
                except Exception:
                    traceback.print_exc()
                    self._running = False
                    self._dead = True
                    self._fail_active("error")
                    return
                continue
            if not busy:
                self._wake.clear()
                self._wake.wait(timeout=0.05)

    def generate(self, req: GenRequest) -> Iterator[StepOutput]:
        """Synchronous convenience: submit + drive the loop until finished.
        Only valid when the background thread is NOT running."""
        if self._running:
            raise RuntimeError("use submit() while the engine loop is running")
        rid, out = self.submit(req)
        done = False
        while not done:
            self.step()
            while True:
                try:
                    o = out.get_nowait()
                except queue.Empty:
                    break
                yield o
                if o.finished:
                    done = True

    def generate_text(self, req: GenRequest) -> str:
        return "".join(o.text for o in self.generate(req))
