"""Batched speculative decoding inside the serving engine.

Reference parity: llama.cpp's DraftModel/NDraft serving knobs
(/root/reference/backend/backend.proto:218,150) — a small draft model
proposes gamma tokens, the target verifies them in one forward, and the
Leviathan et al. accept/residual rule preserves the target's sampling
distribution exactly.

TPU-first shape discipline: ONE jitted step serves ALL slots — the draft
loop is a lax.scan of gamma draft decode steps, verification is a single
target `extend` over the [next_token, d_1..d_gamma] window, and the accept
loop is a vectorized cumprod over the window (no per-token host round
trips — the round-3 standalone decoder's weakness). Per step each slot
emits 1..gamma+1 tokens.

Invariant (differs from the non-spec engine): instead of carrying
`last_logits` and sampling at the top of the next step, the spec engine
carries `next_tokens` [B] — the already-sampled, already-emitted token
whose KV is not yet written. The verify `extend` writes its KV along with
the drafts'; rejected draft KV beyond the new length is dead and is
overwritten by the next window.

The target distribution uses the slot's FULL sampling pipeline
(ops/sampling.sampling_probs): temperature, top-k/p, min-p, typical-p,
penalties — with token counts frozen at window start (the same
approximation llama.cpp's spec sampler makes). The draft proposes from a
temperature-only distribution; any proposal is distribution-safe under the
accept/residual rule.

Fused multi-step ragged ticks (ISSUE 16) and spec: verify windows stay
SINGLE-step. A spec tick already amortizes the dispatch boundary over
gamma+1 tokens per slot, and the accept/rollback arbitration after each
window is inherently host-side (acceptance counts feed gamma autotuning and
per-request rollback bookkeeping), so draft engines never build
`_ragged_loop_fn` — the engine gates the fused loop on `self._draft is
None` in `_build_jit`. Were a future PR to fold verify windows into the
device loop, acceptance would have to become a loop-carried reduction and
any rejection would force the `loop_early_exit_host_arbitration` exit; the
spec-as-ragged pack layout (gamma+1 rows per verifying slot) already fits
the loop's ragged iteration, so only the arbitration move is open.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from localai_tpu.models.llama import (
    LlamaConfig, decode_step, extend, ragged_forward,
)
from localai_tpu.ops.sampling import (
    SamplerState, pipeline_logits, sample, sampling_probs,
)

TINY = 1e-30


def _draft_state(sampler: SamplerState) -> SamplerState:
    """Temperature-only proposal settings (greedy follows the slot)."""
    ones = jnp.ones_like(sampler.top_p)
    zeros = jnp.zeros_like(sampler.min_p)
    return dataclasses.replace(
        sampler,
        top_k=jnp.zeros_like(sampler.top_k),
        top_p=ones,
        min_p=zeros,
        typical_p=ones,
        repeat_penalty=jnp.ones_like(sampler.repeat_penalty),
        presence_penalty=zeros,
        frequency_penalty=zeros,
        token_counts=jnp.zeros_like(sampler.token_counts),
        logit_bias=jnp.zeros_like(sampler.logit_bias),
    )


def _slot_keys(key_data):
    return jax.vmap(jax.random.wrap_key_data)(key_data)


def build_spec_decode(cfg_t: LlamaConfig, cfg_d: LlamaConfig, gamma: int):
    """Returns the jittable all-slots speculative step.

    (params_t, params_d, cos_t, sin_t, cos_d, sin_d, kct, vct, kcd, vcd,
     sampler, lengths, next_tokens, active) →
    (tokens_out [B, gamma+1], n_out [B], logprobs_out [B, gamma+1],
     next_tokens', kct', vct', kcd', vcd', sampler', lengths')
    """

    def spec_decode(params_t, params_d, cos_t, sin_t, cos_d, sin_d,
                    kct, vct, kcd, vcd, sampler, lengths, next_tokens,
                    active, table=None):
        B = next_tokens.shape[0]
        G = gamma
        act_i = active.astype(jnp.int32)

        # one key split per step; all draws derive via fold_in
        new_keys = jax.vmap(
            lambda kk: jax.random.split(jax.random.wrap_key_data(kk), 2)
        )(sampler.key)
        carry_keys = jax.vmap(jax.random.key_data)(new_keys[:, 0]).astype(
            jnp.uint32)
        step_keys = new_keys[:, 1]          # [B] typed keys

        dstate = _draft_state(sampler)

        # ---- draft phase: scan gamma draft decode steps
        def draft_iter(carry, i):
            kcd, vcd, tok = carry
            logits_d, kcd, vcd = decode_step(
                params_d, cfg_d, tok, lengths + i, cos_d, sin_d, kcd, vcd,
                active)
            p_d = sampling_probs(logits_d, dstate)               # [B, V]
            # disjoint fold_in domains: drafts 100+i, uniforms 1, correction 2
            sub = jax.vmap(lambda k: jax.random.fold_in(k, 100 + i))(
                step_keys)
            d = jax.vmap(
                lambda k, p: jax.random.categorical(k, jnp.log(p + TINY))
            )(sub, p_d).astype(jnp.int32)
            return (kcd, vcd, d), (d, p_d)

        (kcd, vcd, d_last), (drafts, p_ds) = jax.lax.scan(
            draft_iter, (kcd, vcd, next_tokens), jnp.arange(G))
        # the loop wrote KV for next_token..d_{G-1}; ingest d_G too — on full
        # acceptance its position is committed, and a hole there would poison
        # every later draft proposal (junk attended forever)
        _, kcd, vcd = decode_step(params_d, cfg_d, d_last, lengths + G,
                                  cos_d, sin_d, kcd, vcd, active)
        d_tok = drafts.T                                         # [B, G]
        p_d_stack = jnp.moveaxis(p_ds, 0, 1)                     # [B, G, V]

        # ---- target verify: one extend over [next_token, d_1..d_gamma]
        window = jnp.concatenate([next_tokens[:, None], d_tok], axis=1)
        if table is None:
            # dense inactive redirect: start T-1 puts the first garbage row
            # at the never-readable last position; the rest fall out of
            # bounds and the scatter drops them
            T = kct.shape[3]
            start = jnp.where(active, lengths, T - 1)
            tlogits, kct, vct = extend(params_t, cfg_t, window, start,
                                       cos_t, sin_t, kct, vct)   # [B,G+1,V]
        else:
            # paged: out-of-bounds positions would CLAMP through the table
            # gather into a real block, so inactive rows route their whole
            # window to the trash block instead (models/llama.py extend
            # redirect)
            tlogits, kct, vct = extend(params_t, cfg_t, window, lengths,
                                       cos_t, sin_t, kct, vct, table=table,
                                       redirect=~active)         # [B,G+1,V]
        (tokens_out, n_out, logprobs_out, c, n_extra,
         sampler) = _verify_outputs(sampler, active, step_keys, carry_keys,
                                    d_tok, p_d_stack, tlogits, G)
        lengths = lengths + act_i * (1 + n_extra)
        next_tokens = jnp.where(active, c, next_tokens)
        n_out = n_out * act_i
        return (tokens_out, n_out, logprobs_out, next_tokens,
                kct, vct, kcd, vcd, sampler, lengths, n_extra * act_i)

    return spec_decode


def _verify_outputs(sampler, active, step_keys, carry_keys, d_tok,
                    p_d_stack, tlogits, G, mask_rows=None):
    """Shared verify tail of both spec programs (extend-based and ragged):
    target distributions per window position, vectorized Leviathan accept,
    residual correction token, output assembly, sampler commit.

    mask_rows: optional [B, G+1, W32] u32 grammar mask per window position
    (the automaton state AFTER each draft prefix) — masked target probs
    reject grammar-invalid drafts through the ordinary accept test (p_t = 0
    → u < 0 never accepts) and the residual renormalizes over the allowed
    set, so the correction token is grammar-valid by construction. The
    draft proposes unmasked; any proposal is distribution-safe under the
    accept/residual rule.

    Returns (tokens_out [B, G+1], n_out [B] UNGATED (= n_extra+1),
    logprobs_out, c [B] correction token, n_extra [B], sampler')."""
    B = d_tok.shape[0]

    def _m(i):
        return None if mask_rows is None else mask_rows[:, i]

    ps_t = jnp.stack(
        [sampling_probs(tlogits[:, i], sampler, _m(i))
         for i in range(G + 1)],
        axis=1)                                              # [B,G+1,V]
    # logprobs use the PRE-truncation distribution — sample()'s contract
    lp_pre = jnp.stack(
        [jax.nn.log_softmax(pipeline_logits(tlogits[:, i], sampler, _m(i)),
                            axis=-1) for i in range(G + 1)],
        axis=1)                                              # [B,G+1,V]

    # ---- vectorized accept (Leviathan): u_i < p_t(d_i) / p_d(d_i)
    bidx = jnp.arange(B)[:, None]
    pt_d = ps_t[:, :G][bidx, jnp.arange(G)[None, :], d_tok]  # [B, G]
    pd_d = p_d_stack[bidx, jnp.arange(G)[None, :], d_tok]
    u_keys = jax.vmap(lambda k: jax.random.fold_in(k, 1))(step_keys)
    us = jax.vmap(lambda k: jax.random.uniform(k, (G,)))(u_keys)
    accept = us < pt_d / jnp.maximum(pd_d, TINY)
    acc_prefix = jnp.cumprod(accept.astype(jnp.int32), axis=1)
    n_extra = acc_prefix.sum(axis=1)                         # [B] 0..G

    # ---- correction/bonus token from the residual distribution
    p_t_corr = jnp.take_along_axis(
        ps_t, n_extra[:, None, None], axis=1)[:, 0]          # [B, V]
    p_d_corr = jnp.take_along_axis(
        p_d_stack, jnp.minimum(n_extra, G - 1)[:, None, None],
        axis=1)[:, 0]
    p_d_corr = jnp.where((n_extra < G)[:, None], p_d_corr, 0.0)
    residual = jnp.maximum(p_t_corr - p_d_corr, 0.0)
    z = residual.sum(axis=-1, keepdims=True)
    resid = jnp.where(z > TINY, residual / jnp.maximum(z, TINY),
                      p_t_corr)
    c_keys = jax.vmap(lambda k: jax.random.fold_in(k, 2))(step_keys)
    c = jax.vmap(
        lambda k, p: jax.random.categorical(k, jnp.log(p + TINY))
    )(c_keys, resid).astype(jnp.int32)

    # ---- assemble outputs: accepted drafts then the correction token
    cols = jnp.arange(G + 1)[None, :]
    d_pad = jnp.concatenate(
        [d_tok, jnp.zeros((B, 1), jnp.int32)], axis=1)
    tokens_out = jnp.where(
        cols < n_extra[:, None], d_pad,
        jnp.where(cols == n_extra[:, None], c[:, None], 0))
    n_out = n_extra + 1
    lp_d = lp_pre[:, :G][bidx, jnp.arange(G)[None, :], d_tok]
    lp_d = jnp.concatenate([lp_d, jnp.zeros((B, 1), jnp.float32)], axis=1)
    lp_c = jnp.take_along_axis(
        lp_pre, n_extra[:, None, None], axis=1)[:, 0][jnp.arange(B), c]
    logprobs_out = jnp.where(
        cols < n_extra[:, None], lp_d,
        jnp.where(cols == n_extra[:, None], lp_c[:, None], 0.0))

    # ---- sampler commit (inactive slots' counts unchanged)
    valid = (cols < n_out[:, None]) & active[:, None]
    counts = sampler.token_counts.at[
        jnp.arange(B)[:, None], tokens_out
    ].add(valid.astype(jnp.int32))
    sampler = dataclasses.replace(sampler, key=carry_keys,
                                  token_counts=counts)
    return tokens_out, n_out, logprobs_out, c, n_extra, sampler


def build_spec_ragged(cfg_t: LlamaConfig, cfg_d: LlamaConfig, gamma: int):
    """Speculative decode as a RAGGED PACK VARIANT (one program for every
    tenant): the draft scan is unchanged, but the target verify runs through
    ragged_forward — each verifying slot's [next_token, d_1..d_gamma] window
    is just gamma+1 extra qlen rows in the flat token stream, packed
    alongside chunked-prefill windows (and their multimodal inject rows) of
    OTHER slots in the same dispatch. Draft tokens are spliced into the
    stream on device (they are sampled inside this program), and
    logit_rows [B, gamma+1] gathers the target distribution at every window
    row. Grammar-constrained slots thread the device automaton tables: the
    state chain along the draft path is unrolled (gamma is static), each
    window position's target probs are masked by its state's row, and
    grammar-invalid drafts die in the ordinary accept test.

    (params_t, params_d, cos_t, sin_t, cos_d, sin_d, kct, vct, kcd, vcd,
     sampler, last_logits, lengths, next_tokens, active, tokens [T],
     spec_rows [B], set_len [B], logit_set [B], logit_rows [B, gamma+1],
     block_seq, qstart, qlen, kvlen, table, kvt, inject, gstate, gmasks,
     gtrans) →
    (tokens_out [B, gamma+1], n_out [B], logprobs_out, next_tokens',
     kct', vct', kcd', vcd', sampler', last_logits', lengths', n_extra)

    `active` marks slots verifying a window this tick (prefilled, live);
    `spec_rows[b]` is slot b's window start row in the stream (its rows are
    host-zeroed and device-filled); set_len/logit_set carry the packed
    prefill chunks' length commits and final-chunk last_logits updates,
    exactly like the plain ragged program."""

    def spec_ragged(params_t, params_d, cos_t, sin_t, cos_d, sin_d,
                    kct, vct, kcd, vcd, sampler, last_logits, lengths,
                    next_tokens, active, tokens, spec_rows, set_len,
                    logit_set, logit_rows, block_seq, qstart, qlen, kvlen,
                    table, kvt=None, inject=None, gstate=None, gmasks=None,
                    gtrans=None):
        B = next_tokens.shape[0]
        G = gamma
        T = tokens.shape[0]
        act_i = active.astype(jnp.int32)

        # one key split per step; all draws derive via fold_in (identical
        # stream discipline to build_spec_decode so token parity holds)
        new_keys = jax.vmap(
            lambda kk: jax.random.split(jax.random.wrap_key_data(kk), 2)
        )(sampler.key)
        carry_keys = jax.vmap(jax.random.key_data)(new_keys[:, 0]).astype(
            jnp.uint32)
        step_keys = new_keys[:, 1]          # [B] typed keys

        dstate = _draft_state(sampler)

        # ---- draft phase: scan gamma draft decode steps (dense draft KV).
        # Grammar slots thread their automaton state through the scan and
        # mask each PROPOSAL by its state's row: a blind draft would be
        # rejected by the masked verify almost every time (p_t = 0), which
        # collapses speculative efficiency for constrained tenants. Any
        # proposal distribution is safe under the accept/residual rule, so
        # masking the draft changes throughput, never the output law.
        gst0 = gstate if gmasks is not None else jnp.zeros(
            (B,), jnp.int32)

        def draft_iter(carry, i):
            kcd, vcd, tok, gst = carry
            logits_d, kcd, vcd = decode_step(
                params_d, cfg_d, tok, lengths + i, cos_d, sin_d, kcd, vcd,
                active)
            dmask = gmasks[gst] if gmasks is not None else None
            p_d = sampling_probs(logits_d, dstate, dmask)        # [B, V]
            sub = jax.vmap(lambda k: jax.random.fold_in(k, 100 + i))(
                step_keys)
            d = jax.vmap(
                lambda k, p: jax.random.categorical(k, jnp.log(p + TINY))
            )(sub, p_d).astype(jnp.int32)
            if gmasks is not None:
                gst = gtrans[gst, d]
            return (kcd, vcd, d, gst), (d, p_d)

        (kcd, vcd, d_last, _), (drafts, p_ds) = jax.lax.scan(
            draft_iter, (kcd, vcd, next_tokens, gst0), jnp.arange(G))
        _, kcd, vcd = decode_step(params_d, cfg_d, d_last, lengths + G,
                                  cos_d, sin_d, kcd, vcd, active)
        d_tok = drafts.T                                         # [B, G]
        p_d_stack = jnp.moveaxis(p_ds, 0, 1)                     # [B, G, V]

        # ---- splice the verify windows into the flat stream on device:
        # inactive slots' rows redirect past the end and the scatter drops
        # them (their q blocks are dead padding in block_seq anyway)
        window = jnp.concatenate([next_tokens[:, None], d_tok], axis=1)
        rows = jnp.where(active[:, None],
                         spec_rows[:, None] + jnp.arange(G + 1)[None, :],
                         T)
        toks = tokens.at[rows.reshape(-1)].set(window.reshape(-1),
                                               mode="drop")

        # ---- target verify: ONE ragged forward over spec windows AND any
        # packed prefill chunks; [B, G+1] logit_rows → [B, G+1, V]
        tlogits, kct, vct = ragged_forward(
            params_t, cfg_t, toks, cos_t, sin_t, kct, vct, block_seq,
            qstart, qlen, kvlen, table, logit_rows, kvt=kvt, inject=inject)

        # packed final prefill chunks refresh last_logits (their G+1 gather
        # rows all point at the chunk's last token, so any index works)
        last_logits = jnp.where(logit_set[:, None], tlogits[:, -1],
                                last_logits)

        mask_rows = None
        if gmasks is not None:
            # automaton states along the draft path: window[0] is the
            # already-emitted next_token (gstate is PAST it), so position j
            # masks what may follow window[..j]. Unconstrained slots sit in
            # identity row 0 (all-ones masks, self-loop) — bit-identical.
            sts = [gstate]
            for j in range(1, G + 1):
                sts.append(gtrans[sts[-1], window[:, j]])
            mask_rows = gmasks[jnp.stack(sts, axis=1)]       # [B,G+1,W32]

        (tokens_out, n_out, logprobs_out, c, n_extra,
         sampler) = _verify_outputs(sampler, active, step_keys, carry_keys,
                                    d_tok, p_d_stack, tlogits, G,
                                    mask_rows=mask_rows)
        # prefill chunk slots commit their packed length; verify slots
        # advance by the accepted run (disjoint sets — a slot mid-prefill
        # is never active for verify)
        lengths = jnp.where(set_len >= 0, set_len,
                            lengths + act_i * (1 + n_extra))
        next_tokens = jnp.where(active, c, next_tokens)
        n_out = n_out * act_i
        return (tokens_out, n_out, logprobs_out, next_tokens,
                kct, vct, kcd, vcd, sampler, last_logits, lengths,
                n_extra * act_i)

    return spec_ragged


def build_spec_admit_tail(cfg_t: LlamaConfig):
    """Sample the FIRST token of a freshly-admitted slot from last_logits
    (full pipeline, that slot's key stream only) and count it. mask is the
    slot's grammar bitmask [1, ceil(V/8)] u8 (None for unconstrained) — a
    grammar slot's first token must respect the start state like every
    later one. Returns (token, logprob, sampler')."""

    def admit_tail(sampler, last_logits, slot, mask=None):
        row = jax.tree_util.tree_map(lambda a: a[slot][None], sampler)
        tok, keys, lp = sample(last_logits[slot][None], row, mask)
        counts = sampler.token_counts.at[slot, tok[0]].add(1)
        sampler = dataclasses.replace(
            sampler,
            key=sampler.key.at[slot].set(keys[0]),
            token_counts=counts)
        return tok[0], lp[0], sampler

    return admit_tail


def build_draft_ingest(cfg_d: LlamaConfig):
    """Write a prompt window into the DRAFT cache (KV only) — mirrors the
    target admission/chunk writes so the draft never needs host catch-up."""

    def ingest(params_d, cos_d, sin_d, kcd, vcd, tokens, start, slot):
        _, kcd, vcd = extend(params_d, cfg_d, tokens, start[None],
                             cos_d, sin_d, kcd, vcd, slot_map=slot[None],
                             with_logits=False)
        return kcd, vcd

    return ingest
