from localai_tpu.engine.loader import load_config, load_params, load_model  # noqa: F401
from localai_tpu.engine.tokenizer import Tokenizer  # noqa: F401
from localai_tpu.engine.engine import (  # noqa: F401
    Engine,
    EngineConfig,
    GenRequest,
    StepOutput,
)
