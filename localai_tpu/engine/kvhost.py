"""Host-RAM KV spill tier (ISSUE 17).

The device block pool (ops/paged.py) is the only place KV lives today:
when a retained slot is reclaimed, a prefix-cache block rewritten, or a
windowed block evicted past the kvtier cold pool, the content is gone and
the next turn of that conversation re-prefills from token zero.  This
module adds the missing storage tier between the device pool and
re-prefill:

    device pool  --spill (async D2H, int8 sub-channel)-->  HostKVPool
    HostKVPool   --re-admit (H2D, overlapped w/ prefill)-->  device pool

Blocks are keyed by the same chained content hashes the prefix cache
uses (engine._chain_hashes), so a host hit is exactly a prefix-cache hit
that happens to live one tier further away.  Storage is int8 sub-channel
(ops/kvcache.quantize_tokens layout): a spilled block from a quantized
pool round-trips byte-exact (greedy parity 1.00); from a dense pool it
pays the same quantization error the kvtier cold read path already
accepts.

The pool itself is pure host-side bookkeeping (numpy + dicts) so it can
be unit-tested in milliseconds and handed to a fresh Engine to model a
worker restart (``Engine(..., kvhost=survivor_pool)``).

Also here: the federation-layer prefix digest.  The reverse proxy cannot
tokenize, so cluster KV affinity is keyed on *text-chunk* chain hashes
(``text_chain_ids``) computed identically by the proxy and every worker
from the request body — a worker's digest covers a follow-up turn's hint
iff it served the conversation's earlier turns.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from localai_tpu.testing.lockdep import lockdep_lock

__all__ = [
    "HostKVBlock", "HostKVPool", "PrefixDigest",
    "text_chain_ids", "body_prompt_text",
]


# --------------------------------------------------------------------------
# spilled block payload
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class HostKVBlock:
    """One 128-token KV block in int8 sub-channel form.

    kq/vq: int8  [L, KVH, BLOCK, D]
    ks/vs: f32   [L, KVH, 1, BLOCK]   (quantize_tokens scale tile layout)
    """

    kq: np.ndarray
    ks: np.ndarray
    vq: np.ndarray
    vs: np.ndarray

    @property
    def nbytes(self) -> int:
        return (self.kq.nbytes + self.ks.nbytes
                + self.vq.nbytes + self.vs.nbytes)


@dataclass
class _Entry:
    block: HostKVBlock
    group: bytes
    pins: int = 0


@dataclass
class _Group:
    # chain-ordered hashes; tail blocks are useless without their head, so
    # budget eviction inside a group strips from the tail first
    hashes: list = field(default_factory=list)


@dataclass
class _SpillBatch:
    # one in-flight async spill of a chain group: hashes claimed via
    # begin_spill but not yet landed/abandoned, plus every hash this batch
    # pinned (residents at claim time + blocks landed while the batch was
    # open).  Pins release only when the last claim of the batch ends, so
    # an LRU eviction racing the spill can never free a chain head out
    # from under its still-in-flight tail.
    claims: set = field(default_factory=set)
    pinned: list = field(default_factory=list)


# --------------------------------------------------------------------------
# the pool
# --------------------------------------------------------------------------

class HostKVPool:
    """Refcounted, byte-budgeted host store of spilled KV blocks.

    Keys are the engine's chained content hashes (16-byte blake2b).
    Blocks belong to a *group* (the chain-head hash of the session that
    spilled them); eviction is LRU over groups — drop the
    least-recently-touched session first, and within it tail blocks
    before head blocks, since a chain is only usable as a leading run.

    ``budget_bytes <= 0`` disables admission entirely (every ``put`` is
    dropped), which lets callers keep one unconditional code path.

    Thread-safe: the engine thread spills/readmits while the gRPC thread
    reads ``stats()``/``digest()`` for metrics and health gossip.
    """

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self._lock = lockdep_lock("kvhost.pool")
        self._entries: dict[bytes, _Entry] = {}
        # insertion/touch order == LRU order (oldest first)
        self._groups: "OrderedDict[bytes, _Group]" = OrderedDict()
        self.used_bytes = 0
        # counters (cumulative; exported via engine.metrics kv_host_*)
        self.spills = 0          # blocks admitted
        self.hits = 0            # blocks re-admitted via get()
        self.misses = 0          # probes that found nothing
        self.evictions = 0       # blocks dropped to respect the budget
        self.rejects = 0         # puts refused (dup / zero budget / pinned)
        self.peak_bytes = 0
        # in-flight async spills (begin_spill/end_spill): hash -> group key
        self._pending_h: dict[bytes, bytes] = {}
        self._spilling: dict[bytes, _SpillBatch] = {}

    # -- admission ---------------------------------------------------------

    def accepts(self, h: bytes) -> bool:
        """Cheap pre-flight: would ``put`` store this hash?  Lets the
        engine skip the device->host copy for dups and zero budgets."""
        if self.budget_bytes <= 0:
            return False
        with self._lock:
            return h not in self._entries and h not in self._pending_h

    def put(self, h: bytes, block: HostKVBlock,
            group: Optional[bytes] = None) -> int:
        """Admit one block; returns number of blocks evicted for budget.

        A duplicate hash is refused (first copy wins — content-addressed,
        so the bytes are identical anyway).  A block larger than the
        whole budget is refused rather than flushing the pool for it.
        """
        if self.budget_bytes <= 0 or block.nbytes > self.budget_bytes:
            self.rejects += 1
            return 0
        gkey = group if group is not None else h
        with self._lock:
            if h in self._entries or h in self._pending_h:
                self.rejects += 1
                return 0
            self._land_locked(h, block, gkey)
            return self._evict_to_budget_locked()

    def _land_locked(self, h: bytes, block: HostKVBlock,
                     gkey: bytes) -> None:
        self._entries[h] = _Entry(block=block, group=gkey)
        g = self._groups.get(gkey)
        if g is None:
            g = self._groups[gkey] = _Group()
        g.hashes.append(h)
        self._groups.move_to_end(gkey)     # MRU
        self.used_bytes += block.nbytes
        self.spills += 1
        self.peak_bytes = max(self.peak_bytes, self.used_bytes)

    # -- in-flight spill claims --------------------------------------------

    def begin_spill(self, h: bytes, group: Optional[bytes] = None) -> bool:
        """Claim ``h`` for an async D2H spill that will land later via
        ``end_spill``.  Returns False (and counts a reject) when the pool
        would refuse the block anyway (zero budget, duplicate, or an
        identical spill already in flight) so the caller can skip the
        device->host copy.

        A successful claim opens (or joins) the group's spill batch and
        pins every block of the group already resident; blocks landed
        while the batch is open are born pinned too.  All of it unpins
        when the batch's last claim ends — without this, an LRU eviction
        between enqueue and drain can free the chain head whose in-flight
        tail is useless without it.
        """
        if self.budget_bytes <= 0:
            self.rejects += 1
            return False
        gkey = group if group is not None else h
        with self._lock:
            if h in self._entries or h in self._pending_h:
                self.rejects += 1
                return False
            batch = self._spilling.get(gkey)
            if batch is None:
                batch = self._spilling[gkey] = _SpillBatch()
                g = self._groups.get(gkey)
                if g is not None:
                    for rh in g.hashes:
                        self._entries[rh].pins += 1
                        batch.pinned.append(rh)
            batch.claims.add(h)
            self._pending_h[h] = gkey
            return True

    def end_spill(self, h: bytes,
                  block: Optional[HostKVBlock] = None) -> int:
        """Land (``block`` given) or abandon (``block=None``) a claim made
        by ``begin_spill``; returns blocks evicted for budget.  Ending a
        hash that was never claimed degrades to a plain ``put``/no-op so
        callers keep one unconditional drain path."""
        with self._lock:
            gkey = self._pending_h.pop(h, None)
            if gkey is None:
                if block is None:
                    return 0
                if (self.budget_bytes <= 0
                        or block.nbytes > self.budget_bytes
                        or h in self._entries):
                    self.rejects += 1
                    return 0
                self._land_locked(h, block, h)
                return self._evict_to_budget_locked()
            batch = self._spilling[gkey]
            batch.claims.discard(h)
            evicted = 0
            if block is not None:
                if block.nbytes > self.budget_bytes:
                    self.rejects += 1
                else:
                    self._land_locked(h, block, gkey)
                    self._entries[h].pins += 1     # born pinned
                    batch.pinned.append(h)
                    evicted = self._evict_to_budget_locked()
            if not batch.claims:
                del self._spilling[gkey]
                for ph in batch.pinned:
                    e = self._entries.get(ph)
                    if e is not None and e.pins > 0:
                        e.pins -= 1
                # pins may have deferred evictions the budget needs
                evicted += self._evict_to_budget_locked()
            return evicted

    def _evict_to_budget_locked(self) -> int:
        evicted = 0
        while self.used_bytes > self.budget_bytes:
            victim = None
            for gkey in self._groups:          # oldest group first
                g = self._groups[gkey]
                # tail-first inside the group; skip pinned blocks
                for h in reversed(g.hashes):
                    if self._entries[h].pins == 0:
                        victim = (gkey, h)
                        break
                if victim:
                    break
            if victim is None:                 # everything pinned
                break
            gkey, h = victim
            e = self._entries.pop(h)
            self._groups[gkey].hashes.remove(h)
            if not self._groups[gkey].hashes:
                del self._groups[gkey]
            self.used_bytes -= e.block.nbytes
            self.evictions += 1
            evicted += 1
        return evicted

    # -- lookup ------------------------------------------------------------

    def get(self, h: bytes) -> Optional[HostKVBlock]:
        """Non-destructive lookup; a hit touches the block's group (MRU)
        so live sessions outlast idle ones."""
        with self._lock:
            e = self._entries.get(h)
            if e is None:
                self.misses += 1
                return None
            self.hits += 1
            self._groups.move_to_end(e.group)
            return e.block

    def contains(self, h: bytes) -> bool:
        with self._lock:
            return h in self._entries

    def pin(self, h: bytes) -> bool:
        with self._lock:
            e = self._entries.get(h)
            if e is None:
                return False
            e.pins += 1
            return True

    def unpin(self, h: bytes) -> None:
        with self._lock:
            e = self._entries.get(h)
            if e is not None and e.pins > 0:
                e.pins -= 1

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "blocks": len(self._entries),
                "groups": len(self._groups),
                "bytes": self.used_bytes,
                "peak_bytes": self.peak_bytes,
                "budget_bytes": self.budget_bytes,
                "spills": self.spills,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "rejects": self.rejects,
                "pending_spills": len(self._pending_h),
            }

    def digest(self, k: int = 128) -> list:
        """Top-k most-recent block ids (hex) for health-poll gossip —
        MRU groups first, chain order inside a group."""
        out: list = []
        with self._lock:
            for gkey in reversed(self._groups):      # MRU first
                for h in self._groups[gkey].hashes:
                    out.append(h.hex())
                    if len(out) >= k:
                        return out
        return out


# --------------------------------------------------------------------------
# federation prefix digest (text-chunk chain hashes)
# --------------------------------------------------------------------------

# one chunk ~= one prefill block's worth of text; the exact figure only
# needs to be identical on the proxy and the workers, not token-accurate
TEXT_CHUNK = 512


def text_chain_ids(text: str, chunk: int = TEXT_CHUNK,
                   limit: int = 64) -> list:
    """Chained blake2b ids over fixed-size chunks of ``text``.

    Chaining makes each id commit to the whole preceding conversation,
    mirroring engine._chain_hashes over token blocks: a worker's digest
    covers a follow-up turn's leading ids iff it served the same
    conversation prefix.  Trailing partial chunks are dropped (they will
    re-hash identically once the conversation grows past them).
    """
    data = text.encode("utf-8", errors="replace")
    ids: list = []
    prev = b""
    for i in range(0, min(len(data) // chunk, limit)):
        hh = hashlib.blake2b(digest_size=16)
        hh.update(prev)
        hh.update(data[i * chunk:(i + 1) * chunk])
        prev = hh.digest()
        ids.append(prev.hex())
    return ids


def body_prompt_text(body: dict) -> str:
    """Canonical conversation text of an OpenAI-style request body.

    Both the federation proxy and the workers run this over the same
    JSON body, so their chain ids agree by construction.  Only fields
    that are stable across turns of one conversation participate.
    """
    if not isinstance(body, dict):
        return ""
    msgs = body.get("messages")
    if isinstance(msgs, list):
        parts = []
        for m in msgs:
            if not isinstance(m, dict):
                continue
            content = m.get("content")
            if isinstance(content, list):     # multimodal content parts
                content = "".join(
                    p.get("text", "") for p in content
                    if isinstance(p, dict) and p.get("type") == "text")
            if isinstance(content, str):
                parts.append(f"{m.get('role', '')}\x1f{content}\x1e")
        return "".join(parts)
    prompt = body.get("prompt")
    if isinstance(prompt, list):
        prompt = "".join(p for p in prompt if isinstance(p, str))
    return prompt if isinstance(prompt, str) else ""


class PrefixDigest:
    """Bounded MRU set of text-chain ids a worker has served.

    Workers feed it from their chat/completions handlers; its ``to_list``
    rides the /healthz response so the federation picker can score
    KV affinity without an extra RPC.  Thread-safe (aiohttp handlers +
    health responses share it).
    """

    def __init__(self, cap: int = 1024):
        self.cap = int(cap)
        self._lock = lockdep_lock("kvhost.digest")
        self._ids: "OrderedDict[str, None]" = OrderedDict()

    def add(self, ids: list) -> None:
        if not ids:
            return
        with self._lock:
            for i in ids:
                if i in self._ids:
                    self._ids.move_to_end(i)
                else:
                    self._ids[i] = None
            while len(self._ids) > self.cap:
                self._ids.popitem(last=False)

    def to_list(self, k: int = 128) -> list:
        with self._lock:
            # most recent last in OrderedDict; gossip MRU first
            return list(reversed(self._ids))[:k]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ids)


def coverage(digest, hint) -> int:
    """Length of the leading run of ``hint`` ids present in ``digest``.

    Chain ids commit to their whole prefix, so only a *leading* run is
    re-usable KV — a mid-conversation match without its head is noise.
    """
    if not hint:
        return 0
    have = digest if isinstance(digest, (set, frozenset)) else set(digest)
    n = 0
    for i in hint:
        if i not in have:
            break
        n += 1
    return n


def request_hint(raw_body: bytes, limit: int = 64) -> list:
    """Best-effort text-chain hint from a raw (possibly non-JSON) proxy
    request body.  Returns [] rather than raising — affinity is an
    optimization, never a correctness gate."""
    try:
        body = json.loads(raw_body)
    except Exception:
        return []
    text = body_prompt_text(body)
    if not text:
        return []
    return text_chain_ids(text, limit=limit)
