"""Checkpoint loading: HF safetensors → stacked JAX param pytree.

Reference analog: `LoadModel` in the llama.cpp backend reads GGUF
(/root/reference/backend/cpp/llama-cpp/grpc-server.cpp:505) and vLLM loads HF
checkpoints (/root/reference/backend/python/vllm/backend.py:92-122). Here the
on-disk format is HF safetensors (the TPU-ecosystem standard); tensors are
read lazily per-shard, transposed into our [in, out] matmul layout, stacked
on a leading layer axis (the lax.scan layout), and — when a mesh is given —
placed directly as sharded jax.Arrays so a TP-sharded load never materializes
the full model on one chip.
"""
from __future__ import annotations

import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from localai_tpu.models.llama import LlamaConfig, param_specs

# HF architectures the Llama-family decoder covers (SURVEY §2.2 row 1 scope).
LLAMA_FAMILY = {
    "LlamaForCausalLM": {},
    "MistralForCausalLM": {},
    "MixtralForCausalLM": {"moe": True},
    "Qwen2ForCausalLM": {"qkv_bias": True},
    "TinyLlamaForCausalLM": {},
}


def load_config(model_dir: str, dtype: str | None = None) -> LlamaConfig:
    """Parse HF config.json into a LlamaConfig. `dtype` overrides the compute
    dtype (activations follow params; bf16 is the TPU default)."""
    with open(os.path.join(model_dir, "config.json")) as f:
        hf: dict[str, Any] = json.load(f)

    arch = (hf.get("architectures") or ["LlamaForCausalLM"])[0]
    if hf.get("model_type") == "llava" or arch.startswith("Llava"):
        # vision-language checkpoint: the language side is a plain
        # Llama-family config nested under text_config (the vision side
        # loads separately — models/llava.py)
        hf = dict(hf["text_config"])
        arch = (hf.get("architectures")
                or [{"llama": "LlamaForCausalLM",
                     "mistral": "MistralForCausalLM",
                     "qwen2": "Qwen2ForCausalLM"}.get(
                        hf.get("model_type", "llama"), "LlamaForCausalLM")])[0]
    if arch not in LLAMA_FAMILY:
        raise ValueError(f"unsupported architecture {arch!r}")
    extra = LLAMA_FAMILY[arch]

    num_heads = hf["num_attention_heads"]
    head_dim = hf.get("head_dim") or hf["hidden_size"] // num_heads

    kw: dict[str, Any] = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=num_heads,
        num_kv_heads=hf.get("num_key_value_heads", num_heads),
        head_dim=head_dim,
        max_position=hf.get("max_position_embeddings", 8192),
        rms_eps=hf.get("rms_norm_eps", 1e-5),
        rope_base=hf.get("rope_theta", 10000.0),
        tie_embeddings=hf.get("tie_word_embeddings", False),
        sliding_window=hf.get("sliding_window"),
        qkv_bias=hf.get("attention_bias", extra.get("qkv_bias", False)),
    )
    if extra.get("moe") or hf.get("num_local_experts"):
        kw["num_experts"] = hf.get("num_local_experts", 8)
        kw["experts_per_tok"] = hf.get("num_experts_per_tok", 2)
    if dtype is not None:
        # int8 = weight quantization; activations/KV stay bf16
        kw["dtype"] = ("bfloat16" if dtype in ("int8", "q8", "int4", "q4")
                       else dtype)

    rs = hf.get("rope_scaling") or hf.get("rope_parameters") or None
    if rs and isinstance(rs, dict) and rs.get("rope_type", rs.get("type")) not in (None, "default"):
        rope_type = rs.get("rope_type", rs.get("type"))
        kw["rope_scaling"] = rope_type
        kw["rope_scale_factor"] = rs.get("factor", 1.0)
        kw["rope_original_max_position"] = rs.get(
            "original_max_position_embeddings", kw["max_position"]
        )
        if rope_type == "llama3":
            kw["rope_low_freq_factor"] = rs.get("low_freq_factor", 1.0)
            kw["rope_high_freq_factor"] = rs.get("high_freq_factor", 4.0)
        if rope_type == "yarn":
            kw["rope_beta_fast"] = rs.get("beta_fast", 32.0)
            kw["rope_beta_slow"] = rs.get("beta_slow", 1.0)
            kw["rope_attn_factor"] = rs.get("attention_factor")
    return LlamaConfig(**kw)


class _SafetensorsFile:
    """Minimal host-side safetensors reader: 8-byte header length, JSON header
    {name: {dtype, shape, data_offsets}}, then raw little-endian tensor data.
    mmap + np.frombuffer keeps every tensor on HOST memory (bf16 via ml_dtypes)
    so a TP-sharded load never materializes the full model on one chip —
    unlike framework-mode safe_open, which commits to the default device.
    """

    _DTYPES = {
        "F64": np.float64, "F32": np.float32, "F16": np.float16,
        "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
        "U8": np.uint8, "BOOL": np.bool_,
    }

    def __init__(self, path: str):
        import mmap

        import ml_dtypes

        self._DTYPES = dict(self._DTYPES)
        self._DTYPES["BF16"] = ml_dtypes.bfloat16
        self._f = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        (hlen,) = np.frombuffer(self._mm[:8], np.uint64)
        self._header: dict[str, Any] = json.loads(self._mm[8 : 8 + int(hlen)])
        self._header.pop("__metadata__", None)
        self._base = 8 + int(hlen)

    def keys(self):
        return self._header.keys()

    def get(self, name: str) -> np.ndarray:
        meta = self._header[name]
        lo, hi = meta["data_offsets"]
        arr = np.frombuffer(
            self._mm[self._base + lo : self._base + hi],
            self._DTYPES[meta["dtype"]],
        )
        return arr.reshape(meta["shape"])

    def close(self):
        self._mm.close()
        self._f.close()


class _TensorReader:
    """Lazy per-tensor host reads across safetensors shards."""

    def __init__(self, model_dir: str):
        self.dir = model_dir
        self.index = self._shard_index(model_dir)
        self._open: dict[str, _SafetensorsFile] = {}

    @staticmethod
    def _shard_index(model_dir: str) -> dict[str, str]:
        """tensor name → safetensors filename (single-file or index.json)."""
        idx = os.path.join(model_dir, "model.safetensors.index.json")
        if os.path.exists(idx):
            with open(idx) as f:
                return json.load(f)["weight_map"]
        name = "model.safetensors"
        if os.path.exists(os.path.join(model_dir, name)):
            f = _SafetensorsFile(os.path.join(model_dir, name))
            try:
                return {k: name for k in f.keys()}
            finally:
                f.close()
        raise FileNotFoundError(f"no safetensors checkpoint in {model_dir}")

    @staticmethod
    def _variants(name: str):
        """Key spellings across HF save layouts: plain Llama, classic LLaVA
        (language_model.model.* + language_model.lm_head.*), and the 4.52+
        LLaVA relayout (model.language_model.* + top-level lm_head.*)."""
        yield name
        yield "language_model." + name
        if name.startswith("model."):
            yield "model.language_model." + name[len("model."):]

    def _resolve(self, name: str) -> str | None:
        for v in self._variants(name):
            if v in self.index:
                return v
        return None

    def __contains__(self, name: str) -> bool:
        return self._resolve(name) is not None

    def get(self, name: str) -> np.ndarray:
        key = self._resolve(name)
        if key is None:
            raise KeyError(name)
        fname = self.index[key]
        if fname not in self._open:
            self._open[fname] = _SafetensorsFile(os.path.join(self.dir, fname))
        return self._open[fname].get(key)

    def close(self):
        for f in self._open.values():
            f.close()
        self._open.clear()


def load_params(
    model_dir: str,
    cfg: LlamaConfig,
    *,
    dtype=None,
    mesh=None,
    specs=None,
):
    """Load + restructure a HF Llama-family checkpoint.

    HF stores projection weights as [out, in]; our matmuls are x @ W so every
    projection is transposed once here, at load time. Per-layer tensors are
    stacked on a leading [L, ...] axis to match the lax.scan execution layout
    (models/llama.py init_params). With `mesh`, each stacked param is placed
    as a NamedSharding'ed jax.Array per param_specs (Megatron-style TP).

    dtype="int8"/"int4" loads bf16 then quantizes projections per output
    channel (the GGUF-quant analog, int4 being the exllama2/Q4 role). On a
    single chip that happens on device (ops/quant.quantize_params); under a
    `mesh` each projection quantizes PER HOST-READ SHARD (numpy, right after
    the safetensors read) and only the int8 payload + f32 scales are
    device_put under param_specs(cfg, qbits=...) — the full bf16 stack is
    never materialized on one host buffer or one chip, which is what lets
    an 8B int8 recipe board a 16GB-per-chip v5e-8.
    """
    qbits = {"int8": 8, "q8": 8, "int4": 4, "q4": 4}.get(dtype)
    quantize = qbits is not None
    host_quant = quantize and mesh is not None
    if quantize:
        dtype = "bfloat16"
    dtype = jnp.dtype(dtype) if dtype is not None else cfg.jdtype

    if _is_synthetic(model_dir):
        # benchmark checkpoints: config.json declares the geometry, weights
        # are deterministic random init on device — lets the serving path be
        # measured at flagship scale without writing tens of GB to disk
        return _synthetic_params(cfg, dtype=dtype, mesh=mesh,
                                 qbits=qbits, specs=specs)

    r = _TensorReader(model_dir)
    if mesh is not None and specs is None:
        specs = param_specs(cfg, qbits=qbits if host_quant else None)

    def put(x, spec):
        # host numpy → cast on host → single device_put (sharded when meshed)
        if isinstance(x, dict):
            # host-quantized {"q", "s"} (mesh path): spec is the matching
            # {"q", "s"} dict from param_specs(qbits=...). int4 ships in an
            # int8 container and casts AFTER the sharded placement (the
            # elementwise astype runs distributed, never regathering)
            q = jax.device_put(x["q"], NamedSharding(mesh, spec["q"]))
            if qbits == 4:
                q = q.astype(jnp.int4)
            return {"q": q,
                    "s": jax.device_put(x["s"], NamedSharding(mesh, spec["s"]))}
        x = x if x.dtype == dtype else x.astype(dtype)
        if mesh is not None:
            return jax.device_put(x, NamedSharding(mesh, spec))
        return jnp.asarray(x)

    def hq(t: np.ndarray):
        # mirror the device path bit for bit: checkpoint dtype → bf16 (the
        # load cast) → f32 quantization (quantize_np == ops.quant.quantize)
        from localai_tpu.ops.quant import quantize_np

        return quantize_np(np.asarray(t).astype(dtype), qbits)

    def stack(fmt: str, transpose: bool, quant: bool = False):
        if quant and host_quant:
            qs, ss = [], []
            for i in range(cfg.num_layers):
                t = r.get(fmt.format(i=i))
                d = hq(t.T if transpose else t)
                qs.append(d["q"])
                ss.append(d["s"])
            return {"q": np.stack(qs), "s": np.stack(ss)}
        ts = []
        for i in range(cfg.num_layers):
            t = r.get(fmt.format(i=i))
            ts.append(t.T if transpose else t)
        return np.stack(ts)

    L = "model.layers.{i}."
    layers = {
        "attn_norm": stack(L + "input_layernorm.weight", False),
        "wq": stack(L + "self_attn.q_proj.weight", True, quant=True),
        "wk": stack(L + "self_attn.k_proj.weight", True, quant=True),
        "wv": stack(L + "self_attn.v_proj.weight", True, quant=True),
        "wo": stack(L + "self_attn.o_proj.weight", True, quant=True),
        "mlp_norm": stack(L + "post_attention_layernorm.weight", False),
    }
    if cfg.num_experts:
        # Mixtral MoE: experts stacked [L, E, in, out]
        # (block_sparse_moe.gate + experts.N.w{1,2,3})
        def stack_experts(which: str):
            if host_quant:
                qs, ss = [], []
                for i in range(cfg.num_layers):
                    row = [hq(r.get(f"model.layers.{i}.block_sparse_moe."
                                    f"experts.{e}.{which}.weight").T)
                           for e in range(cfg.num_experts)]
                    qs.append(np.stack([d["q"] for d in row]))
                    ss.append(np.stack([d["s"] for d in row]))
                return {"q": np.stack(qs), "s": np.stack(ss)}
            out = []
            for i in range(cfg.num_layers):
                row = [r.get(f"model.layers.{i}.block_sparse_moe."
                             f"experts.{e}.{which}.weight").T
                       for e in range(cfg.num_experts)]
                out.append(np.stack(row))
            return np.stack(out)

        layers["moe_gate"] = stack(
            L + "block_sparse_moe.gate.weight", True)
        layers["moe_w1"] = stack_experts("w1")
        layers["moe_w2"] = stack_experts("w2")
        layers["moe_w3"] = stack_experts("w3")
    else:
        layers.update({
            "w_gate": stack(L + "mlp.gate_proj.weight", True, quant=True),
            "w_up": stack(L + "mlp.up_proj.weight", True, quant=True),
            "w_down": stack(L + "mlp.down_proj.weight", True, quant=True),
        })
    if cfg.qkv_bias:
        layers["bq"] = stack(L + "self_attn.q_proj.bias", False)
        layers["bk"] = stack(L + "self_attn.k_proj.bias", False)
        layers["bv"] = stack(L + "self_attn.v_proj.bias", False)

    lspecs = specs["layers"] if specs else {k: None for k in layers}
    layers = {k: put(v, lspecs[k]) for k, v in layers.items()}

    params = {
        "embed": put(
            r.get("model.embed_tokens.weight"), specs["embed"] if specs else None
        ),
        "layers": layers,
        "final_norm": put(
            r.get("model.norm.weight"), specs["final_norm"] if specs else None
        ),
    }
    if not cfg.tie_embeddings:
        name = "lm_head.weight"
        if name not in r:
            raise ValueError(
                "config says untied embeddings but lm_head.weight is missing"
            )
        head = hq(r.get(name).T) if host_quant else r.get(name).T
        params["lm_head"] = put(head, specs["lm_head"] if specs else None)
    r.close()
    if quantize and not host_quant:
        from localai_tpu.ops.quant import quantize_params

        params = quantize_params(params, bits=qbits)
    return params


def _synthetic_params(cfg: LlamaConfig, *, dtype, mesh=None, qbits=None,
                      specs=None):
    """Deterministic random params at any scale. The quantized case generates
    the {q, s} leaves DIRECTLY — an 8B bf16 intermediate would not fit
    next to itself on a 16GB chip — and, under a mesh, shards them per
    param_specs(qbits=...) like the safetensors path."""
    from localai_tpu.models.llama import init_params
    from localai_tpu.parallel.mesh import shard_params

    if qbits is None:
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
        if mesh is not None:
            params = shard_params(params, specs or param_specs(cfg), mesh)
        return params

    h, hd = cfg.hidden_size, cfg.head_dim
    nh, nkv, L, inter = (cfg.num_heads, cfg.num_kv_heads, cfg.num_layers,
                         cfg.intermediate_size)
    key = jax.random.PRNGKey(0)
    qmax = 7 if qbits == 4 else 127
    qdtype = jnp.int4 if qbits == 4 else jnp.int8

    def qrand(k, shape, fan_in):
        # int body + per-output-channel scale sized so dequantized weights
        # have ~1/sqrt(fan_in) std, matching init_params' distribution
        q = jax.random.randint(k, shape, -qmax, qmax + 1).astype(qdtype)
        s = jnp.full(shape[:-2] + (1, shape[-1]),
                     (fan_in ** -0.5) * (1.73 / qmax), jnp.float32)
        return {"q": q, "s": s}

    ks = jax.random.split(key, 12)
    layers = {
        "attn_norm": jnp.ones((L, h), dtype),
        "wq": qrand(ks[0], (L, h, nh * hd), h),
        "wk": qrand(ks[1], (L, h, nkv * hd), h),
        "wv": qrand(ks[2], (L, h, nkv * hd), h),
        "wo": qrand(ks[3], (L, nh * hd, h), nh * hd),
        "mlp_norm": jnp.ones((L, h), dtype),
    }
    if cfg.num_experts:
        E = cfg.num_experts
        layers["moe_gate"] = (
            jax.random.normal(ks[9], (L, h, E), jnp.float32) * (h ** -0.5))
        layers["moe_w1"] = qrand(ks[4], (L, E, h, inter), h)
        layers["moe_w2"] = qrand(ks[5], (L, E, inter, h), inter)
        layers["moe_w3"] = qrand(ks[6], (L, E, h, inter), h)
    else:
        layers.update({
            "w_gate": qrand(ks[4], (L, h, inter), h),
            "w_up": qrand(ks[5], (L, h, inter), h),
            "w_down": qrand(ks[6], (L, inter, h), inter),
        })
    if cfg.qkv_bias:
        layers["bq"] = jnp.zeros((L, nh * hd), dtype)
        layers["bk"] = jnp.zeros((L, nkv * hd), dtype)
        layers["bv"] = jnp.zeros((L, nkv * hd), dtype)
    params = {
        "embed": (jax.random.normal(ks[7], (cfg.vocab_size, h), jnp.float32)
                  * (h ** -0.5)).astype(dtype),
        "layers": layers,
        "final_norm": jnp.ones((h,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = qrand(ks[8], (h, cfg.vocab_size), h)
    if mesh is not None:
        params = shard_params(params, specs or param_specs(cfg, qbits=qbits),
                              mesh)
    return params


def _is_synthetic(model_dir: str) -> bool:
    """True for benchmark checkpoints: config.json with
    "localai_synthetic": true AND the LOCALAI_ALLOW_SYNTHETIC=1 env opt-in.
    Without the opt-in a stray config key can never make a production server
    silently serve random weights — the missing-safetensors error stands."""
    if os.environ.get("LOCALAI_ALLOW_SYNTHETIC") != "1":
        return False
    try:
        with open(os.path.join(model_dir, "config.json")) as fh:
            return bool(json.load(fh).get("localai_synthetic"))
    except (OSError, ValueError):
        return False


def load_tokenizer(model_dir: str):
    """Tokenizer for a model dir; None for synthetic benchmark checkpoints
    (callers drive the engine with prompt_ids)."""
    from localai_tpu.engine.tokenizer import Tokenizer

    try:
        return Tokenizer.from_dir(model_dir)
    except FileNotFoundError:
        if not _is_synthetic(model_dir):
            raise
        return None


def load_model(model_dir: str, *, dtype=None, mesh=None):
    """config.json + safetensors + tokenizer in one call → (cfg, params, tok)."""
    cfg = load_config(model_dir, dtype=dtype)
    params = load_params(model_dir, cfg, dtype=dtype, mesh=mesh)
    return cfg, params, load_tokenizer(model_dir)
