"""Speculative decoding: draft model proposes, target verifies in one pass.

Reference surface: llama.cpp draft-model speculation
(/root/reference/backend/backend.proto:218 DraftModel, :150 NDraft). TPU-first
shape: the draft runs gamma cheap decode steps; the target scores all gamma+1
positions in ONE `extend` forward (a [gamma+1]-token matmul batch that keeps
the MXU busy), then canonical rejection sampling (Leviathan et al. 2023)
accepts a prefix and resamples once — output distribution provably equals the
target model's.

Temperature sampling uses the full softmax for both models (rejection
sampling needs a common support; truncation knobs apply to the non-speculative
path). temperature=0 degenerates to exact greedy-match acceptance.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.models.llama import (
    LlamaConfig, decode_step, extend, init_kv_cache, prefill,
)
from localai_tpu.ops.rope import rope_table


@dataclasses.dataclass
class SpecStats:
    proposed: int = 0
    accepted: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0


class SpeculativeDecoder:
    """Single-stream speculative generation over (target, draft) models."""

    def __init__(self, cfg_t: LlamaConfig, params_t, cfg_d: LlamaConfig,
                 params_d, *, gamma: int = 4, max_context: int = 1024):
        if cfg_t.vocab_size != cfg_d.vocab_size:
            raise ValueError("draft/target vocabularies differ")
        self.cfg_t, self.params_t = cfg_t, params_t
        self.cfg_d, self.params_d = cfg_d, params_d
        self.gamma = gamma
        self.T = min(max_context, cfg_t.max_position, cfg_d.max_position)
        self.stats = SpecStats()

        self._cos_t, self._sin_t = rope_table(cfg_t.rope, self.T)
        self._cos_d, self._sin_d = rope_table(cfg_d.rope, self.T)
        self._prefill_t = jax.jit(partial(prefill, cfg=cfg_t))
        self._prefill_d = jax.jit(partial(prefill, cfg=cfg_d))
        self._decode_d = jax.jit(partial(decode_step, cfg=cfg_d))
        self._extend_t = jax.jit(partial(extend, cfg=cfg_t))
        self._extend_d = jax.jit(partial(extend, cfg=cfg_d))

    def _softmax(self, logits, temperature):
        if temperature <= 0:
            return None  # greedy
        return jax.nn.softmax(logits / temperature, axis=-1)

    def generate(self, prompt_ids: list[int], max_tokens: int,
                 temperature: float = 0.0, seed: int = 0,
                 eos_ids: set[int] | None = None) -> list[int]:
        eos_ids = eos_ids or set()
        rng = np.random.default_rng(seed)
        n = len(prompt_ids)
        if n + max_tokens + self.gamma + 1 > self.T:
            raise ValueError("prompt + max_tokens exceeds speculative context")

        kc_t, vc_t = init_kv_cache(self.cfg_t, 1, self.T)
        kc_d, vc_d = init_kv_cache(self.cfg_d, 1, self.T)
        ids = np.zeros((1, self.T), np.int32)
        ids[0, :n] = prompt_ids
        lengths = jnp.array([n], jnp.int32)
        slot = jnp.array([0], jnp.int32)

        logits_t, kc_t, vc_t = self._prefill_t(
            self.params_t, tokens=jnp.asarray(ids[:, :n]), lengths=lengths,
            cos=self._cos_t, sin=self._sin_t, k_cache=kc_t, v_cache=vc_t,
            slot_map=slot)
        _, kc_d, vc_d = self._prefill_d(
            self.params_d, tokens=jnp.asarray(ids[:, :n]), lengths=lengths,
            cos=self._cos_d, sin=self._sin_d, k_cache=kc_d, v_cache=vc_d,
            slot_map=slot)

        out: list[int] = []
        all_ids = list(prompt_ids)       # every committed token, by position
        # logits (target) for the next token after the committed sequence
        last_logits_t = logits_t[0]
        pos = n                          # committed length
        draft_done = n                   # committed positions in draft cache

        def sample_from(logits):
            # this class IS the host-driven reference decoder (per-token
            # syncs by design); the production fused path is engine/spec.py
            if temperature <= 0:
                # lint: allow(host-sync-cast)
                return int(jnp.argmax(logits))
            # lint: allow(host-sync-asarray)
            p = np.asarray(jax.nn.softmax(logits / temperature))
            return int(rng.choice(len(p), p=p / p.sum()))

        while len(out) < max_tokens:
            gamma = min(self.gamma, max_tokens - len(out))
            prev = sample_from(last_logits_t)
            out.append(prev)
            all_ids.append(prev)
            if prev in eos_ids or len(out) >= max_tokens:
                break

            # --- draft: catch up on committed tokens it hasn't seen (incl.
            # prev), then propose gamma tokens sequentially
            catch_up = all_ids[draft_done: pos + 1]   # positions draft_done..pos
            dl, kc_d, vc_d = self._extend_d(
                self.params_d,
                tokens=jnp.asarray(catch_up, jnp.int32)[None, :],
                start=jnp.array([draft_done], jnp.int32),
                cos=self._cos_d, sin=self._sin_d, k_cache=kc_d, v_cache=vc_d)
            draft_done = pos + 1
            dlogits_all = [dl[0, -1]]
            draft_tokens = [sample_from(dl[0, -1])]
            for g in range(1, gamma):
                dstep, kc_d, vc_d = self._decode_d(
                    self.params_d,
                    tokens=jnp.array([draft_tokens[-1]], jnp.int32),
                    lengths=jnp.array([pos + g], jnp.int32),
                    cos=self._cos_d, sin=self._sin_d, k_cache=kc_d,
                    v_cache=vc_d)
                dlogits_all.append(dstep[0])
                draft_tokens.append(sample_from(dstep[0]))

            # --- target scores the whole window in one extend pass
            window = [prev] + draft_tokens
            tl, kc_t, vc_t = self._extend_t(
                self.params_t, tokens=jnp.asarray(window, jnp.int32)[None, :],
                start=jnp.array([pos], jnp.int32),
                cos=self._cos_t, sin=self._sin_t, k_cache=kc_t, v_cache=vc_t)
            tlogits = tl[0]  # row g scores the token after window[g]

            # --- accept / reject (Leviathan-style)
            n_accept = 0
            resampled = None
            for g, d_tok in enumerate(draft_tokens):
                if len(out) >= max_tokens or out[-1] in eos_ids:
                    break
                self.stats.proposed += 1
                if temperature <= 0:
                    # lint: allow(host-sync-cast) — host-driven reference
                    # accept loop (see sample_from); fused path: engine/spec
                    t_tok = int(jnp.argmax(tlogits[g]))
                    if t_tok == d_tok:
                        out.append(d_tok)
                        all_ids.append(d_tok)
                        n_accept += 1
                        continue
                    resampled = t_tok
                    break
                # lint: allow(host-sync-asarray) — Leviathan accept test
                # needs both densities on host; reference path by design
                pt = np.asarray(jax.nn.softmax(tlogits[g] / temperature))
                # lint: allow(host-sync-asarray)
                pd = np.asarray(jax.nn.softmax(dlogits_all[g] / temperature))
                if rng.random() < min(1.0, pt[d_tok] / max(pd[d_tok], 1e-20)):
                    out.append(d_tok)
                    all_ids.append(d_tok)
                    n_accept += 1
                    continue
                resid = np.maximum(pt - pd, 0.0)
                s = resid.sum()
                resampled = (int(rng.choice(len(resid), p=resid / s))
                             if s > 0 else int(np.argmax(pt)))
                break
            self.stats.accepted += n_accept

            old_pos = pos
            pos += 1 + n_accept           # prev + accepted draft tokens
            # draft cache now holds prev (old_pos) + d_1..d_{gamma-1}; of
            # those, only positions < pos are committed — the rest are stale
            # and get overwritten by the next catch-up pass
            draft_done = min(old_pos + gamma, pos)
            if resampled is not None and len(out) < max_tokens:
                # commit `resampled` as next iteration's forced `prev`
                one_hot = jnp.full((self.cfg_t.vocab_size,), -1e9, jnp.float32)
                last_logits_t = one_hot.at[resampled].set(0.0)
            else:
                last_logits_t = tlogits[n_accept]
            if out[-1] in eos_ids:
                break

        return out[:max_tokens]
