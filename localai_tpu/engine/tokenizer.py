"""Tokenizer: HF tokenizer.json + chat template + incremental detokenization.

The reference delegates tokenization to each backend (llama.cpp's vocab;
vLLM's HF tokenizer with chat template —
/root/reference/backend/python/vllm/backend.py:242-243). We standardize on the
`tokenizers` runtime (no transformers import in the serving path) with the
chat template rendered by jinja2 from tokenizer_config.json.

Incremental detokenization: byte-level BPE emits partial UTF-8 sequences at
token boundaries; `StreamDecoder` holds bytes back until they form complete
characters — the role of the rune-reassembly loop in the reference's Go core
(/root/reference/core/backend/llm.go:114-144).
"""
from __future__ import annotations

import json
import os
from typing import Any

from tokenizers import Tokenizer as _HFTokenizer

# Fallback when tokenizer_config.json carries no chat template: the ubiquitous
# [INST]-style template (functionally the reference's hardcoded llama2 default).
_FALLBACK_TEMPLATE = (
    "{% for message in messages %}"
    "{% if message['role'] == 'system' %}<<SYS>>{{ message['content'] }}<</SYS>>\n"
    "{% elif message['role'] == 'user' %}[INST] {{ message['content'] }} [/INST]"
    "{% else %}{{ message['content'] }}{% endif %}"
    "{% endfor %}"
)


class Tokenizer:
    """Thin wrapper: encode/decode, special ids, chat template."""

    def __init__(
        self,
        tok: _HFTokenizer,
        *,
        bos_id: int | None = None,
        eos_ids: set[int] | None = None,
        add_bos: bool = True,
        chat_template: str | None = None,
        eos_token: str | None = None,
    ):
        self._tok = tok
        self.bos_id = bos_id
        self.eos_ids = eos_ids or set()
        self.eos_token = eos_token
        self.add_bos = add_bos
        self.chat_template = chat_template or _FALLBACK_TEMPLATE
        self._jinja = None

    # ------------------------------------------------------------ loading

    @classmethod
    def from_dir(cls, model_dir: str) -> "Tokenizer":
        path = os.path.join(model_dir, "tokenizer.json")
        if not os.path.isfile(path):
            # the rust tokenizers lib raises a bare Exception for a missing
            # file; callers need a catchable FileNotFoundError
            raise FileNotFoundError(path)
        tok = _HFTokenizer.from_file(path)
        cfg: dict[str, Any] = {}
        cfg_path = os.path.join(model_dir, "tokenizer_config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)

        def _tok_str(v):
            if isinstance(v, dict):
                return v.get("content")
            return v

        bos = _tok_str(cfg.get("bos_token"))
        eos = _tok_str(cfg.get("eos_token"))
        bos_id = tok.token_to_id(bos) if bos else None
        eos_ids = set()
        if eos and tok.token_to_id(eos) is not None:
            eos_ids.add(tok.token_to_id(eos))
        # generation_config.json may add extra stop ids (llama3 <|eot_id|>)
        gen_path = os.path.join(model_dir, "generation_config.json")
        if os.path.exists(gen_path):
            with open(gen_path) as f:
                g = json.load(f)
            e = g.get("eos_token_id")
            for i in e if isinstance(e, list) else ([e] if e is not None else []):
                eos_ids.add(int(i))
        return cls(
            tok,
            bos_id=bos_id,
            eos_ids=eos_ids,
            add_bos=bool(cfg.get("add_bos_token", bos_id is not None)),
            chat_template=cfg.get("chat_template"),
            eos_token=eos,
        )

    # ------------------------------------------------------------ encode/decode

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    def encode(self, text: str, *, add_bos: bool | None = None) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        add_bos = self.add_bos if add_bos is None else add_bos
        if add_bos and self.bos_id is not None:
            if not ids or ids[0] != self.bos_id:
                ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: list[int], *, skip_special: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special)

    def id_to_token(self, i: int) -> str | None:
        return self._tok.id_to_token(i)

    # ------------------------------------------------------------ chat template

    def apply_chat_template(
        self,
        messages: list[dict[str, Any]],
        *,
        add_generation_prompt: bool = True,
        tools: list | None = None,
    ) -> str:
        if self._jinja is None:
            import jinja2

            env = jinja2.Environment(
                trim_blocks=True, lstrip_blocks=True,
                extensions=["jinja2.ext.loopcontrols"],
            )
            env.globals["raise_exception"] = _raise_exception
            env.filters["tojson"] = json.dumps
            self._jinja = env.from_string(self.chat_template)
        bos = self.id_to_token(self.bos_id) if self.bos_id is not None else ""
        return self._jinja.render(
            messages=messages,
            tools=tools,
            add_generation_prompt=add_generation_prompt,
            bos_token=bos or "",
            eos_token=self.eos_token or "",
        )

    def encode_chat(self, messages, **kw) -> list[int]:
        text = self.apply_chat_template(messages, **kw)
        # chat templates typically embed the BOS token themselves
        explicit_bos = self.bos_id is not None and text.startswith(
            self.id_to_token(self.bos_id) or "\x00"
        )
        return self.encode(text, add_bos=not explicit_bos)

    def stream_decoder(self) -> "_IncrementalDecoder":
        return _IncrementalDecoder(self)


class _IncrementalDecoder:
    """Stateful decode: emits only newly-completed text per pushed token.

    Sliding two-offset window (the vLLM detokenize_incrementally scheme): the
    delta is `decode(ids[prefix:]) - decode(ids[prefix:read])`, so tokenizers
    whose decoders strip a leading word-boundary space per call (SentencePiece
    Metaspace — Llama-2/Mistral) still produce correct inter-word spaces; a
    suffix ending in an incomplete UTF-8 sequence is held back until complete.
    """

    def __init__(self, tok: Tokenizer):
        self._tok = tok
        self._ids: list[int] = []
        self._prefix = 0      # token index where the decode window starts
        self._read = 0        # tokens fully represented in _text
        self._text = ""

    def _window(self) -> tuple[str, str]:
        prefix_text = self._tok.decode(self._ids[self._prefix:self._read])
        full_text = self._tok.decode(self._ids[self._prefix:])
        return prefix_text, full_text

    def push(self, token_id: int) -> str:
        self._ids.append(token_id)
        prefix_text, full_text = self._window()
        if full_text.endswith("�"):
            return ""  # incomplete multi-byte char; wait for more tokens
        delta = full_text[len(prefix_text):]
        self._prefix = self._read
        self._read = len(self._ids)
        self._text += delta
        return delta

    def flush(self) -> str:
        """Emit whatever is still held back (incomplete sequences included) —
        called when a request finishes so no trailing text is lost."""
        if self._read == len(self._ids):
            return ""
        prefix_text, full_text = self._window()
        delta = full_text[len(prefix_text):]
        self._prefix = self._read = len(self._ids)
        self._text += delta
        return delta

    @property
    def text(self) -> str:
        return self._text

    @property
    def ids(self) -> list[int]:
        return list(self._ids)


def _raise_exception(msg):
    raise ValueError(msg)
