"""KV lifecycle tier — per-request retention policy over the paged pool.

The paged pool (ops/paged.py) made KV *placement* free, but every request
still held O(ctx) blocks resident for its whole lifetime: a 32k-context slot
reserves 256 blocks even though decode only ever reads the attention sinks
plus a sliding window of recent tokens. SnapStream (arXiv:2511.03092) shows
attention-sink + sliding-window KV compression preserves long-sequence decode
quality on dataflow accelerators; Transformer-Lite (arXiv:2403.20041) shows
sub-channel (per-token-over-head-dim) quantization keeps low-bit KV accurate.
This module is the policy/geometry layer of that design; the device-side ring
arithmetic lives in ops/paged.py (ring_block_map / resident_block_positions)
so the model layer never imports the engine package.

Lifecycle of a block under `sink_window(sinks=N, window=W)`:

  hot      — resident in the bf16/int8 hot pool. Sink blocks ([0, N) tokens)
             are identity-mapped and stay hot forever; window blocks live in
             a RING of ceil(W/128)+margin physical blocks that the write path
             reuses in place as the sequence grows.
  cold     — (quantize_cold only) a block whose tokens fully left the window
             is copied into a parallel int8 cold pool (sub-channel scales)
             before the ring wraps over it; attention keeps reading it at
             int8 precision through the cold table.
  evicted  — without quantize_cold the ring overwrite IS the eviction: the
             block's tokens leave the attention set entirely (SnapStream
             semantics). With quantize_cold, eviction only happens when the
             cold pool itself is full (counted in kv_evictions).

A slot's residency is therefore O(sinks + window) blocks, fixed at admission
— the reservation invariant (generation can never run out of pool mid-flight)
carries over unchanged, the table row never mutates mid-decode, and one
compiled program serves any mix of full/windowed slots because the per-slot
geometry (sink blocks, ring width, sinks, window) ships as runtime [B] arrays
with full-policy sentinels.
"""
from __future__ import annotations

import dataclasses
import re

from localai_tpu.ops.paged import BLOCK, blocks_needed


@dataclasses.dataclass(frozen=True)
class KVPolicy:
    """Retention policy for one request's KV blocks.

    kind: "full" (keep everything hot — the default, byte-identical to the
    pre-tier engine) or "sink_window" (attention sinks + sliding window).
    sinks/window are token counts; quantize_cold keeps exited-window blocks
    readable at int8 instead of dropping them."""
    kind: str = "full"
    sinks: int = 0
    window: int = 0
    quantize_cold: bool = False

    @property
    def windowed(self) -> bool:
        return self.kind == "sink_window"

    @property
    def sink_blocks(self) -> int:
        return blocks_needed(self.sinks) if self.sinks > 0 else 0

    def describe(self) -> str:
        if not self.windowed:
            return "full"
        s = f"sink_window(sinks={self.sinks}, window={self.window}"
        if self.quantize_cold:
            s += ", quantize_cold=true"
        return s + ")"


_POLICY_RE = re.compile(r"^\s*sink_window\s*\((?P<args>[^)]*)\)\s*$")


def parse_policy(text: str) -> KVPolicy:
    """Parse a policy string: "full" | "sink_window(sinks=N, window=W[,
    quantize_cold=true])". Raises ValueError on anything else."""
    t = (text or "").strip()
    if t in ("", "full"):
        return KVPolicy()
    m = _POLICY_RE.match(t)
    if not m:
        raise ValueError(
            f"unknown kv_policy {text!r}: expected 'full' or "
            f"'sink_window(sinks=N, window=W[, quantize_cold=true])'")
    kw: dict[str, int | bool] = {}
    for part in m.group("args").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"kv_policy argument {part!r} is not k=v")
        k, v = (x.strip() for x in part.split("=", 1))
        if k in ("sinks", "window"):
            kw[k] = int(v)
        elif k == "quantize_cold":
            kw[k] = v.lower() in ("1", "true", "yes", "on")
        else:
            raise ValueError(f"unknown kv_policy argument {k!r}")
    if "window" not in kw or int(kw["window"]) <= 0:
        raise ValueError("sink_window needs window=W > 0")
    pol = KVPolicy(kind="sink_window", sinks=int(kw.get("sinks", 0)),
                   window=int(kw["window"]),
                   quantize_cold=bool(kw.get("quantize_cold", False)))
    if pol.sinks < 0:
        raise ValueError("sink_window sinks must be >= 0")
    return pol


def ring_blocks(window: int, margin_tokens: int) -> int:
    """Physical blocks in the sliding-window ring.

    blocks_needed(window) covers the window span itself; the margin covers
    tokens written ahead of the host's confirmed length (chunked-prefill
    windows, fused decode-loop steps, pipelined in-flight writes); +2 keeps
    (a) a partially-filled current block and (b) one block of slack between
    "tokens exited the window" (demotion eligibility) and "the ring wraps
    over their block" so the quantize_cold copy always runs first."""
    return blocks_needed(window) + blocks_needed(max(margin_tokens, 1)) + 2


def resident_blocks(pol: KVPolicy, margin_tokens: int) -> int:
    """Total table columns a windowed slot holds resident: identity-mapped
    sink blocks + the ring."""
    return pol.sink_blocks + ring_blocks(pol.window, margin_tokens)


def engine_margin_tokens(ec) -> int:
    """Tokens the serving paths may write past the host's confirmed length:
    a full prefill chunk, a full fused decode-loop dispatch, or the pipelined
    scan-ladder block (2*decode_block+1, the _blocks_for margin)."""
    return max(ec.prefill_chunk, ec.decode_loop, 2 * ec.decode_block + 1)


def resolve_policy(req_policy: str, engine_policy: KVPolicy) -> KVPolicy:
    """Resolve a request's effective policy at admission.

    The engine policy fixes the compiled geometry (table width, cold pool),
    so a request may only pick "full" (identity residency, capped at the
    engine's resident width) or a sink_window no LARGER than the engine's —
    a wider window would not fit the ring."""
    if not req_policy:
        return engine_policy
    pol = parse_policy(req_policy)
    if not pol.windowed:
        return pol
    if not engine_policy.windowed:
        raise ValueError(
            "request kv_policy sink_window needs an engine configured with "
            "a windowed kv_policy (the table geometry is fixed at load)")
    if (pol.sink_blocks > engine_policy.sink_blocks
            or blocks_needed(pol.window) > blocks_needed(
                engine_policy.window)):
        raise ValueError(
            f"request kv_policy {pol.describe()} exceeds the engine policy "
            f"{engine_policy.describe()} (per-request windows may only "
            f"shrink the resident geometry)")
    # quantize_cold is an engine-level capability (the cold pool either
    # exists or it doesn't); a windowed request on a cold engine rides it
    return dataclasses.replace(
        pol, quantize_cold=engine_policy.quantize_cold)
