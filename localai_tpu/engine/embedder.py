"""Embeddings runner: bucketed, jitted masked-mean pooling over the decoder.

Reference analog: the transformers backend's Embedding RPC with mean_pooling
(/root/reference/backend/python/transformers/backend.py:323,37). TPU-first:
prompts are padded to a small set of length buckets so each shape compiles
once; batch requests share one compiled call.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.models.llama import LlamaConfig, encode_pooled
from localai_tpu.parallel.mesh import activate_mesh


class Embedder:
    def __init__(self, cfg: LlamaConfig, params, *,
                 buckets: tuple[int, ...] = (64, 256, 1024), mesh=None):
        self.cfg = cfg
        self.params = params
        self.buckets = tuple(sorted(b for b in buckets
                                    if b <= cfg.max_position)) or (64,)
        self.mesh = mesh
        # normalize is a Python `if` inside the trace — keep it static so a
        # live-bool caller can't hit a TracerBoolConversionError
        self._fn = jax.jit(partial(encode_pooled, cfg=cfg),
                           static_argnames=("normalize",))

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"input length {n} exceeds max embedding bucket {self.buckets[-1]}"
        )

    def embed(self, ids_batch: list[list[int]]) -> np.ndarray:
        """[N] token-id lists → [N, H] f32 L2-normalized embeddings."""
        if not ids_batch:
            return np.zeros((0, self.cfg.hidden_size), np.float32)
        n = len(ids_batch)
        longest = max(len(ids) for ids in ids_batch)
        bucket = self._bucket(max(longest, 1))
        # pad the BATCH dim to a power of two as well: arbitrary client batch
        # sizes must not each compile a fresh XLA program
        nb = 1
        while nb < n:
            nb *= 2
        toks = np.zeros((nb, bucket), np.int32)
        lens = np.zeros((nb,), np.int32)
        for i, ids in enumerate(ids_batch):
            toks[i, : len(ids)] = ids
            lens[i] = len(ids)
        with activate_mesh(self.mesh):
            out = self._fn(self.params, tokens=jnp.asarray(toks),
                           lengths=jnp.asarray(lens))
        return np.asarray(jax.device_get(out))[:n]


def _doc_logprob(params, cfg, tokens, lengths, q_len):
    """Mean conditional log-prob of the document tokens given the query
    prefix. tokens [B, S]; lengths [B] total (query+doc); q_len [B]."""
    from localai_tpu.models.llama import forward_train

    logits = forward_train(params, cfg, tokens)            # [B, S, V]
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    b, s = tokens.shape
    # position i's logits predict token i+1
    tok_lp = jnp.take_along_axis(
        lp[:, :-1], tokens[:, 1:, None], axis=-1)[..., 0]  # [B, S-1]
    pos = jnp.arange(s - 1)[None, :]
    mask = (pos + 1 >= q_len[:, None]) & (pos + 1 < lengths[:, None])
    n_doc = jnp.maximum(mask.sum(axis=1), 1)
    return (tok_lp * mask).sum(axis=1) / n_doc


class CrossScorer:
    """Cross-encoder-style reranker over the causal LM: each document is
    scored by the model's mean log-likelihood of the document tokens
    CONDITIONED on the query — query and document attend jointly, which is
    what makes it a cross-encoder rather than a bi-encoder cosine
    (reference role: the rerankers backend,
    /root/reference/backend/python/rerankers/backend.py)."""

    def __init__(self, cfg: LlamaConfig, params, *,
                 buckets: tuple[int, ...] = (64, 256, 1024), mesh=None):
        self.cfg = cfg
        self.params = params
        self.buckets = tuple(sorted(b for b in buckets
                                    if b <= cfg.max_position)) or (64,)
        self.mesh = mesh
        self._fn = jax.jit(partial(_doc_logprob, cfg=cfg))

    def score(self, query_ids: list[int],
              docs_ids: list[list[int]]) -> np.ndarray:
        """[N] relevance scores (higher = more relevant)."""
        if not docs_ids:
            return np.zeros((0,), np.float32)
        pairs = [list(query_ids) + list(d) for d in docs_ids]
        longest = max(len(p) for p in pairs)
        bucket = next((b for b in self.buckets if longest <= b), None)
        if bucket is None:
            raise ValueError(
                f"query+document length {longest} exceeds max bucket "
                f"{self.buckets[-1]}")
        n = len(pairs)
        nb = 1
        while nb < n:
            nb *= 2
        toks = np.zeros((nb, bucket), np.int32)
        lens = np.zeros((nb,), np.int32)
        for i, p in enumerate(pairs):
            toks[i, :len(p)] = p
            lens[i] = len(p)
        qlen = np.full((nb,), len(query_ids), np.int32)
        with activate_mesh(self.mesh):
            out = self._fn(self.params, tokens=jnp.asarray(toks),
                           lengths=jnp.asarray(lens), q_len=jnp.asarray(qlen))
        return np.asarray(jax.device_get(out))[:n]
