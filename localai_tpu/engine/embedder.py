"""Embeddings runner: bucketed, jitted masked-mean pooling over the decoder.

Reference analog: the transformers backend's Embedding RPC with mean_pooling
(/root/reference/backend/python/transformers/backend.py:323,37). TPU-first:
prompts are padded to a small set of length buckets so each shape compiles
once; batch requests share one compiled call.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.models.llama import LlamaConfig, encode_pooled
from localai_tpu.parallel.mesh import activate_mesh


class Embedder:
    def __init__(self, cfg: LlamaConfig, params, *,
                 buckets: tuple[int, ...] = (64, 256, 1024), mesh=None):
        self.cfg = cfg
        self.params = params
        self.buckets = tuple(sorted(b for b in buckets
                                    if b <= cfg.max_position)) or (64,)
        self.mesh = mesh
        self._fn = jax.jit(partial(encode_pooled, cfg=cfg))

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"input length {n} exceeds max embedding bucket {self.buckets[-1]}"
        )

    def embed(self, ids_batch: list[list[int]]) -> np.ndarray:
        """[N] token-id lists → [N, H] f32 L2-normalized embeddings."""
        if not ids_batch:
            return np.zeros((0, self.cfg.hidden_size), np.float32)
        n = len(ids_batch)
        longest = max(len(ids) for ids in ids_batch)
        bucket = self._bucket(max(longest, 1))
        # pad the BATCH dim to a power of two as well: arbitrary client batch
        # sizes must not each compile a fresh XLA program
        nb = 1
        while nb < n:
            nb *= 2
        toks = np.zeros((nb, bucket), np.int32)
        lens = np.zeros((nb,), np.int32)
        for i, ids in enumerate(ids_batch):
            toks[i, : len(ids)] = ids
            lens[i] = len(ids)
        with activate_mesh(self.mesh):
            out = self._fn(self.params, tokens=jnp.asarray(toks),
                           lengths=jnp.asarray(lens))
        return np.asarray(jax.device_get(out))[:n]
