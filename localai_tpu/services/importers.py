"""Importers: guess a ModelConfig from a bare checkpoint directory/URI.

Reference: /root/reference/core/gallery/importers (per-backend-family config
guessers) + core/config/guesser.go:11-46 (fill missing knobs from model
metadata). Here the metadata source is HF config.json instead of GGUF headers.
"""
from __future__ import annotations

import json
import os
from typing import Any

# architectures the TPU llm engine serves (engine/loader.py LLAMA_FAMILY)
_LLM_ARCHS = {
    "LlamaForCausalLM", "MistralForCausalLM", "Qwen2ForCausalLM",
    "TinyLlamaForCausalLM",
}
_WHISPER_ARCHS = {"WhisperForConditionalGeneration"}


def guess_model_config(model_dir: str, name: str | None = None) -> dict[str, Any]:
    """Inspect a checkpoint dir → ModelConfig dict (ready for YAML dump)."""
    cfg_path = os.path.join(model_dir, "config.json")
    if not os.path.exists(cfg_path):
        raise FileNotFoundError(f"no config.json in {model_dir}")
    with open(cfg_path) as f:
        hf = json.load(f)
    arch = (hf.get("architectures") or [""])[0]
    name = name or os.path.basename(os.path.normpath(model_dir))

    out: dict[str, Any] = {
        "name": name,
        "parameters": {"model": model_dir},
    }
    if arch in _WHISPER_ARCHS:
        out["backend"] = "whisper"
        return out
    if arch in _LLM_ARCHS or "hidden_size" in hf:
        out["backend"] = "llm"
        maxpos = hf.get("max_position_embeddings")
        if maxpos:
            out["context_size"] = min(int(maxpos), 8192)
        # small models → likely used for embeddings too
        if hf.get("hidden_size", 4096) <= 1024:
            out["embeddings"] = True
        out["template"] = {"use_tokenizer_template": True}
        return out
    raise ValueError(f"unsupported architecture {arch!r} in {model_dir}")
