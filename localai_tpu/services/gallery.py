"""Model gallery: remote/local YAML index → download artifacts + write model
YAML into the models dir.

Reference: /root/reference/core/gallery/models.go:75-285 (resolve from index,
download files with sha256+progress, write per-model config),
core/services/gallery.go:116-166 (serialized job queue with status map).
Galleries are YAML lists of entries:

  - name: tinyllama-chat
    description: ...
    files:
      - filename: model/config.json
        uri: file:///path/or/https://...
        sha256: ...
    config:            # ModelConfig overrides written to <name>.yaml
      backend: llm
      parameters: {model: tinyllama-chat/model}
"""
from __future__ import annotations

import dataclasses
import os
import queue
import threading
import uuid
from typing import Any

import yaml

from localai_tpu.downloader import download_file


@dataclasses.dataclass
class GalleryModel:
    name: str
    description: str = ""
    license: str = ""
    urls: list[str] = dataclasses.field(default_factory=list)
    tags: list[str] = dataclasses.field(default_factory=list)
    files: list[dict] = dataclasses.field(default_factory=list)
    config: dict = dataclasses.field(default_factory=dict)
    gallery: str = ""


class Gallery:
    """One or more gallery indexes (local path or URL of a YAML list)."""

    def __init__(self, sources: list[str]):
        self.sources = sources
        self._models: dict[str, GalleryModel] | None = None

    def _fetch_index(self, src: str) -> list[dict]:
        if "://" in src and not src.startswith("file://"):
            import tempfile

            with tempfile.NamedTemporaryFile(suffix=".yaml") as t:
                download_file(src, t.name)
                with open(t.name) as f:
                    return yaml.safe_load(f) or []
        path = src.removeprefix("file://")
        with open(path) as f:
            return yaml.safe_load(f) or []

    def models(self) -> dict[str, GalleryModel]:
        if self._models is None:
            out: dict[str, GalleryModel] = {}
            for src in self.sources:
                for entry in self._fetch_index(src):
                    known = {f.name for f in dataclasses.fields(GalleryModel)}
                    gm = GalleryModel(**{k: v for k, v in entry.items()
                                         if k in known})
                    gm.gallery = src
                    out[gm.name] = gm
            self._models = out
        return self._models

    def get(self, name: str) -> GalleryModel | None:
        return self.models().get(name)


def _confine(root: str, relpath: str) -> str:
    """Resolve `relpath` under `root` and refuse any escape — gallery indexes
    are untrusted input (reference verifyPath, core/gallery/models.go; this
    was a CVE class upstream). Rejects absolute paths, `..`, and symlink
    escapes alike by comparing realpaths."""
    dest = os.path.realpath(os.path.join(root, relpath))
    if dest == root or os.path.commonpath([root, dest]) != root:
        raise ValueError(f"path traversal in gallery path {relpath!r}")
    return dest


def install_model(gallery: Gallery, name: str, models_path: str,
                  progress=None, overrides: dict | None = None) -> str:
    """Download a gallery model's files and write its ModelConfig YAML.
    Returns the YAML path (models.go:159-285 semantics)."""
    gm = gallery.get(name)
    if gm is None:
        raise KeyError(f"model {name!r} not in galleries")
    os.makedirs(models_path, exist_ok=True)
    root = os.path.realpath(models_path)
    # confine every destination (including the YAML) BEFORE fetching anything:
    # a malicious name must not cost bandwidth first
    ypath = _confine(root, f"{name}.yaml")
    dests = [_confine(root, f["filename"]) for f in gm.files]
    for f, dest in zip(gm.files, dests):
        download_file(f["uri"], dest, sha256=f.get("sha256"),
                      progress=progress)
    cfg: dict[str, Any] = {"name": name,
                           "description": gm.description}
    cfg.update(gm.config or {})
    cfg.update(overrides or {})
    cfg.setdefault("name", name)
    with open(ypath, "w") as f:
        yaml.safe_dump(cfg, f, sort_keys=False)
    return ypath


class GalleryService:
    """Serialized install job queue with UUID status map
    (services/gallery.go:116-166)."""

    def __init__(self, gallery: Gallery, models_path: str):
        self.gallery = gallery
        self.models_path = models_path
        self._jobs: "queue.Queue[tuple[str, str, dict | None]]" = queue.Queue()
        self.status: dict[str, dict] = {}
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def start(self):
        if self._thread:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._jobs.put(("", "", None))  # wake
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def submit(self, model_name: str, overrides: dict | None = None) -> str:
        job_id = uuid.uuid4().hex
        self.status[job_id] = {"state": "queued", "model": model_name,
                               "progress": 0.0, "error": ""}
        self._jobs.put((job_id, model_name, overrides))
        return job_id

    def _loop(self):
        while not self._stop.is_set():
            job_id, name, overrides = self._jobs.get()
            if not job_id:
                continue
            st = self.status[job_id]
            st["state"] = "processing"

            def progress(done, total, st=st):
                st["progress"] = done / total if total else 0.0

            try:
                path = install_model(self.gallery, name, self.models_path,
                                     progress=progress, overrides=overrides)
                st.update(state="done", progress=1.0, config=path)
            except Exception as e:
                st.update(state="error", error=f"{type(e).__name__}: {e}")


def cli_models(args) -> int:
    """`localai-tpu models list|install` (reference core/cli models cmd)."""
    from localai_tpu.config import ModelConfigLoader

    sources = []
    if getattr(args, "galleries", None):
        sources = [s.strip() for s in args.galleries.split(",") if s.strip()]
    gallery = Gallery(sources) if sources else None

    if args.action == "list":
        loader = ModelConfigLoader(args.models_path)
        for n in loader.names():
            print(f"{n} (installed)")
        if gallery:
            for n in sorted(gallery.models()):
                print(n)
        return 0
    if args.action == "install":
        if not args.name:
            print("usage: models install <name>")
            return 1
        if gallery is None:
            print("no galleries configured (--galleries)")
            return 1
        path = install_model(gallery, args.name, args.models_path,
                             progress=lambda d, t: None)
        print(f"installed → {path}")
        return 0
    return 1
