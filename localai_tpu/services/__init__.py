from localai_tpu.services.gallery import (  # noqa: F401
    Gallery,
    GalleryService,
    install_model,
)
