"""GGUF ingestion — parse, dequantize, and convert GGUF checkpoints to the
HF-layout (config.json + model.safetensors + tokenizer.json) this engine
loads.

Role: the reference is GGUF-first — its gallery/config guesser reads GGUF
metadata (/root/reference/core/config/gguf.go, guesser.go:11-46) and its
flagship backend serves GGUF directly via llama.cpp. Here GGUF is an IMPORT
format: quantized blocks are decoded once to f32/f16 tensors (the engine
re-quantizes to int8 on device at load, ops/quant.py), metadata synthesizes
config.json (the guesser role), and the embedded tokenizer becomes a HF
tokenizer.json. Clean-room implementation from the public GGUF/GGML layout.

Supported tensor types: F32, F16, BF16, Q8_0, Q4_0, Q4_1, Q5_0, Q5_1, Q6_K.
"""
from __future__ import annotations

import json
import os
import struct

import numpy as np

GGUF_MAGIC = b"GGUF"

# metadata value types
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32, _T_F32, _T_BOOL, _T_STR, \
    _T_ARR, _T_U64, _T_I64, _T_F64 = range(13)

_SCALAR = {
    _T_U8: ("<B", 1), _T_I8: ("<b", 1), _T_U16: ("<H", 2), _T_I16: ("<h", 2),
    _T_U32: ("<I", 4), _T_I32: ("<i", 4), _T_F32: ("<f", 4),
    _T_BOOL: ("<?", 1), _T_U64: ("<Q", 8), _T_I64: ("<q", 8),
    _T_F64: ("<d", 8),
}

# ggml tensor types → (block_elems, block_bytes)
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q4_1 = 2, 3
GGML_Q5_0, GGML_Q5_1 = 6, 7
GGML_Q8_0 = 8
GGML_Q6_K = 14
GGML_BF16 = 30

_BLOCK = {
    GGML_F32: (1, 4), GGML_F16: (1, 2), GGML_BF16: (1, 2),
    GGML_Q4_0: (32, 18), GGML_Q4_1: (32, 20),
    GGML_Q5_0: (32, 22), GGML_Q5_1: (32, 24),
    GGML_Q8_0: (32, 34), GGML_Q6_K: (256, 210),
}


class _Reader:
    def __init__(self, buf: memoryview):
        self.buf = buf
        self.pos = 0

    def scalar(self, t):
        fmt, n = _SCALAR[t]
        v = struct.unpack_from(fmt, self.buf, self.pos)[0]
        self.pos += n
        return v

    def string(self) -> str:
        n = self.scalar(_T_U64)
        s = bytes(self.buf[self.pos:self.pos + n]).decode("utf-8",
                                                          errors="replace")
        self.pos += n
        return s

    def value(self, t):
        if t == _T_STR:
            return self.string()
        if t == _T_ARR:
            et = self.scalar(_T_U32)
            n = self.scalar(_T_U64)
            if et in _SCALAR and et != _T_BOOL:
                fmt, sz = _SCALAR[et]
                out = np.frombuffer(self.buf, dtype=np.dtype(fmt[1:]).newbyteorder("<"),
                                    count=n, offset=self.pos)
                self.pos += n * sz
                return out.tolist()
            return [self.value(et) for _ in range(n)]
        return self.scalar(t)


def parse_gguf(path: str):
    """Parse header + metadata + tensor directory. Returns
    (metadata: dict, tensors: {name: (shape, ggml_type, abs_offset)}, mmap).
    Shapes are numpy order (GGUF stores dims reversed)."""
    mm = np.memmap(path, dtype=np.uint8, mode="r")
    buf = memoryview(mm)
    if bytes(buf[:4]) != GGUF_MAGIC:
        raise ValueError(f"{path}: not a GGUF file")
    r = _Reader(buf)
    r.pos = 4
    version = r.scalar(_T_U32)
    if version not in (2, 3):
        raise ValueError(f"unsupported GGUF version {version}")
    n_tensors = r.scalar(_T_U64)
    n_kv = r.scalar(_T_U64)
    meta = {}
    for _ in range(n_kv):
        key = r.string()
        t = r.scalar(_T_U32)
        meta[key] = r.value(t)
    infos = []
    for _ in range(n_tensors):
        name = r.string()
        nd = r.scalar(_T_U32)
        dims = [r.scalar(_T_U64) for _ in range(nd)]
        ttype = r.scalar(_T_U32)
        off = r.scalar(_T_U64)
        infos.append((name, tuple(reversed(dims)), ttype, off))
    align = int(meta.get("general.alignment", 32))
    data_start = (r.pos + align - 1) // align * align
    tensors = {n: (s, t, data_start + o) for n, s, t, o in infos}
    return meta, tensors, mm


# ------------------------------------------------------------- dequantize

def _f16(b):
    return b.view(np.float16).astype(np.float32)


def dequantize(raw: np.ndarray, ggml_type: int, shape) -> np.ndarray:
    """Decode one tensor's raw bytes to f32 (f16 kept as f16 to halve disk)."""
    n = int(np.prod(shape))
    if ggml_type == GGML_F32:
        return raw.view(np.float32)[:n].reshape(shape)
    if ggml_type == GGML_F16:
        return raw.view(np.float16)[:n].reshape(shape)
    if ggml_type == GGML_BF16:
        out = np.zeros((n,), np.float32)
        out.view(np.uint32)[:] = raw.view(np.uint16)[:n].astype(np.uint32) << 16
        return out.reshape(shape)
    be, bb = _BLOCK[ggml_type]
    nb = n // be
    blocks = raw[: nb * bb].reshape(nb, bb)
    if ggml_type == GGML_Q8_0:
        d = _f16(blocks[:, :2].copy())[:, 0]
        q = blocks[:, 2:].view(np.int8).astype(np.float32)
        out = q * d[:, None]
    elif ggml_type in (GGML_Q4_0, GGML_Q4_1):
        if ggml_type == GGML_Q4_0:
            d = _f16(blocks[:, :2].copy())[:, 0][:, None]
            m = -8.0 * d
            qs = blocks[:, 2:]
        else:
            d = _f16(blocks[:, :2].copy())[:, 0][:, None]
            m = _f16(blocks[:, 2:4].copy())[:, 0][:, None]
            qs = blocks[:, 4:]
        lo = (qs & 0x0F).astype(np.float32)
        hi = (qs >> 4).astype(np.float32)
        out = np.concatenate([lo, hi], axis=1) * d + m
    elif ggml_type in (GGML_Q5_0, GGML_Q5_1):
        d = _f16(blocks[:, :2].copy())[:, 0][:, None]
        if ggml_type == GGML_Q5_1:
            m = _f16(blocks[:, 2:4].copy())[:, 0][:, None]
            qh = blocks[:, 4:8].copy().view(np.uint32)[:, 0]
            qs = blocks[:, 8:]
        else:
            m = -16.0 * d
            qh = blocks[:, 2:6].copy().view(np.uint32)[:, 0]
            qs = blocks[:, 6:]
        lo = (qs & 0x0F).astype(np.uint8)
        hi = (qs >> 4).astype(np.uint8)
        q = np.concatenate([lo, hi], axis=1).astype(np.float32)
        bits = ((qh[:, None] >> np.arange(32)[None, :]) & 1).astype(np.float32)
        out = (q + bits * 16.0) * d + m
    elif ggml_type == GGML_Q6_K:
        # block 256: ql[128] qh[64] scales[16] d(f16)
        ql = blocks[:, :128]
        qh = blocks[:, 128:192]
        sc = blocks[:, 192:208].view(np.int8).astype(np.float32)
        d = _f16(blocks[:, 208:210].copy())[:, 0]
        out = np.zeros((nb, 256), np.float32)
        for g in range(2):                      # two 128-elem halves
            qlh = ql[:, g * 64:(g + 1) * 64]
            qhh = qh[:, g * 32:(g + 1) * 32]
            base = g * 128
            for j in range(4):                  # 4 32-elem quarters
                if j < 2:
                    lowq = (qlh[:, j * 32:(j + 1) * 32] & 0x0F)
                else:
                    lowq = (qlh[:, (j - 2) * 32:(j - 1) * 32] >> 4)
                high = ((qhh >> (2 * j)) & 3).astype(np.uint8)
                q = (lowq | (high << 4)).astype(np.float32) - 32.0
                s = sc[:, g * 8 + j * 2:g * 8 + j * 2 + 2]
                # scales apply per 16 elems
                q[:, :16] *= s[:, 0:1]
                q[:, 16:] *= s[:, 1:2]
                out[:, base + j * 32: base + (j + 1) * 32] = q * d[:, None]
    else:
        raise ValueError(f"unsupported ggml tensor type {ggml_type}")
    return out.reshape(shape)


# ------------------------------------------------------------- name mapping

def _unpermute(w: np.ndarray, n_head: int) -> np.ndarray:
    """Invert llama.cpp's q/k row permutation (convert_hf_to_gguf permute):
    GGUF stores wq/wk with rows reordered for GGML's interleaved rope; the
    HF layout this engine expects needs them back."""
    out_dim = w.shape[0]
    return (w.reshape(n_head, out_dim // n_head // 2, 2, *w.shape[1:])
             .swapaxes(1, 2)
             .reshape(w.shape))


def map_tensors(tensors: dict, meta: dict) -> dict:
    """GGUF tensor names → HF llama names (+ the q/k unpermute marker).
    Returns {hf_name: (gguf_name, unpermute_heads | None)}."""
    arch = meta.get("general.architecture", "llama")
    nh = int(meta.get(f"{arch}.attention.head_count", 32))
    nkv = int(meta.get(f"{arch}.attention.head_count_kv", nh))
    out = {
        "model.embed_tokens.weight": ("token_embd.weight", None),
        "model.norm.weight": ("output_norm.weight", None),
    }
    if "output.weight" in tensors:
        out["lm_head.weight"] = ("output.weight", None)
    i = 0
    while f"blk.{i}.attn_q.weight" in tensors:
        L = f"model.layers.{i}."
        B = f"blk.{i}."
        out[L + "input_layernorm.weight"] = (B + "attn_norm.weight", None)
        out[L + "self_attn.q_proj.weight"] = (B + "attn_q.weight", nh)
        out[L + "self_attn.k_proj.weight"] = (B + "attn_k.weight", nkv)
        out[L + "self_attn.v_proj.weight"] = (B + "attn_v.weight", None)
        out[L + "self_attn.o_proj.weight"] = (B + "attn_output.weight", None)
        out[L + "post_attention_layernorm.weight"] = (B + "ffn_norm.weight",
                                                      None)
        out[L + "mlp.gate_proj.weight"] = (B + "ffn_gate.weight", None)
        out[L + "mlp.up_proj.weight"] = (B + "ffn_up.weight", None)
        out[L + "mlp.down_proj.weight"] = (B + "ffn_down.weight", None)
        for bias in ("q", "k", "v"):
            if B + f"attn_{bias}.bias" in tensors:
                out[L + f"self_attn.{bias}_proj.bias"] = (
                    B + f"attn_{bias}.bias",
                    (nh if bias == "q" else nkv))
        i += 1
    return out


def synth_config(meta: dict, tensors: dict) -> dict:
    """GGUF metadata → HF config.json (the reference guesser.go role)."""
    arch = meta.get("general.architecture", "llama")
    nh = int(meta.get(f"{arch}.attention.head_count", 32))
    vocab = len(meta.get("tokenizer.ggml.tokens", [])) or int(
        meta.get(f"{arch}.vocab_size", 32000))
    cfg = {
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": vocab,
        "hidden_size": int(meta.get(f"{arch}.embedding_length", 4096)),
        "intermediate_size": int(meta.get(f"{arch}.feed_forward_length",
                                          11008)),
        "num_hidden_layers": int(meta.get(f"{arch}.block_count", 32)),
        "num_attention_heads": nh,
        "num_key_value_heads": int(meta.get(
            f"{arch}.attention.head_count_kv", nh)),
        "max_position_embeddings": int(meta.get(f"{arch}.context_length",
                                                8192)),
        "rms_norm_eps": float(meta.get(
            f"{arch}.attention.layer_norm_rms_epsilon", 1e-5)),
        "rope_theta": float(meta.get(f"{arch}.rope.freq_base", 10000.0)),
        "tie_word_embeddings": "output.weight" not in tensors,
        "model_type": "llama",
        "localai_gguf_import": True,
    }
    if f"{arch}.attention.key_length" in meta:
        cfg["head_dim"] = int(meta[f"{arch}.attention.key_length"])
    if f"{arch}.rope.scaling.factor" in meta:
        cfg["rope_scaling"] = {
            "rope_type": meta.get(f"{arch}.rope.scaling.type", "linear"),
            "factor": float(meta[f"{arch}.rope.scaling.factor"]),
        }
    eos = meta.get("tokenizer.ggml.eos_token_id")
    bos = meta.get("tokenizer.ggml.bos_token_id")
    if eos is not None:
        cfg["eos_token_id"] = int(eos)
    if bos is not None:
        cfg["bos_token_id"] = int(bos)
    return cfg


def synth_tokenizer(meta: dict) -> dict | None:
    """Embedded GGUF vocab → HF tokenizer.json dict.

    tokenizer.ggml.model: "gpt2" → byte-level BPE (tokens + merges);
    "llama" → sentencepiece-style Unigram (tokens + scores, byte fallback).
    """
    tokens = meta.get("tokenizer.ggml.tokens")
    if not tokens:
        return None
    model = meta.get("tokenizer.ggml.model", "llama")
    ttypes = meta.get("tokenizer.ggml.token_type") or [1] * len(tokens)
    added = [
        {"id": i, "content": t, "special": True}
        for i, (t, tt) in enumerate(zip(tokens, ttypes))
        if tt in (3, 4)    # CONTROL=3, USER_DEFINED=4
    ]
    if model == "gpt2":
        merges = meta.get("tokenizer.ggml.merges") or []
        return {
            "version": "1.0",
            "added_tokens": added,
            "pre_tokenizer": {"type": "ByteLevel", "add_prefix_space": False,
                              "trim_offsets": True, "use_regex": True},
            "decoder": {"type": "ByteLevel", "add_prefix_space": True,
                        "trim_offsets": True, "use_regex": True},
            "model": {
                "type": "BPE",
                "vocab": {t: i for i, t in enumerate(tokens)},
                "merges": merges,
                "byte_fallback": False,
            },
        }
    scores = meta.get("tokenizer.ggml.scores") or [0.0] * len(tokens)
    return {
        "version": "1.0",
        "added_tokens": added,
        "normalizer": {"type": "Sequence", "normalizers": [
            {"type": "Prepend", "prepend": "▁"},
            {"type": "Replace", "pattern": {"String": " "}, "content": "▁"},
        ]},
        "decoder": {"type": "Sequence", "decoders": [
            {"type": "Replace", "pattern": {"String": "▁"}, "content": " "},
            {"type": "ByteFallback"},
            {"type": "Fuse"},
            {"type": "Strip", "content": " ", "start": 1, "stop": 0},
        ]},
        "model": {
            "type": "Unigram",
            "unk_id": int(meta.get("tokenizer.ggml.unknown_token_id", 0)),
            "vocab": [[t, float(s)] for t, s in zip(tokens, scores)],
            "byte_fallback": True,
        },
    }


# ------------------------------------------------------------- conversion

def convert_gguf(path: str, out_dir: str) -> str:
    """GGUF file → HF checkpoint dir (config.json + model.safetensors +
    tokenizer.json). Returns out_dir. Dequantizes once; f16/f32 preserved,
    quantized types decoded to f16 (the engine re-quantizes on device)."""
    from safetensors.numpy import save_file

    meta, tensors, mm = parse_gguf(path)
    mapping = map_tensors(tensors, meta)
    missing = [h for h, (g, _) in mapping.items() if g not in tensors]
    if missing:
        raise ValueError(f"GGUF missing tensors for {missing[:4]}...")
    os.makedirs(out_dir, exist_ok=True)
    out = {}
    for hf_name, (gguf_name, unperm) in mapping.items():
        shape, ttype, off = tensors[gguf_name]
        be, bb = _BLOCK[ttype]
        nbytes = int(np.prod(shape)) // be * bb
        raw = np.asarray(mm[off:off + nbytes])
        w = dequantize(raw, ttype, shape)
        if unperm is not None:
            w = _unpermute(w, unperm)   # 1-D q/k biases are permuted too
        if w.dtype == np.float32 and ttype not in (GGML_F32,):
            w = w.astype(np.float16)   # quantized sources → f16 on disk
        out[hf_name] = np.ascontiguousarray(w)
    save_file(out, os.path.join(out_dir, "model.safetensors"))
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(synth_config(meta, tensors), f, indent=1)
    tok = synth_tokenizer(meta)
    if tok is not None:
        with open(os.path.join(out_dir, "tokenizer.json"), "w") as f:
            json.dump(tok, f)
    chat = meta.get("tokenizer.chat_template")
    if chat:
        with open(os.path.join(out_dir, "tokenizer_config.json"), "w") as f:
            json.dump({"chat_template": chat}, f)
    return out_dir


def resolve_gguf(path: str) -> str:
    """Serving hook: a `.gguf` model path converts (once, cached next to the
    file as <name>.hf/) and loads as the converted dir."""
    out_dir = path + ".hf"
    marker = os.path.join(out_dir, "config.json")
    src_mtime = os.path.getmtime(path)
    if os.path.exists(marker) and os.path.getmtime(marker) >= src_mtime:
        return out_dir
    return convert_gguf(path, out_dir)
