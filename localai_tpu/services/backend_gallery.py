"""Backend gallery: install/list/delete serving backends, with meta-backends
resolved by detected hardware capability.

Reference: /root/reference/core/gallery/backends.go:73-439 + the registry
index format /root/reference/backend/index.yaml — entries carry a
`capabilities` map (capability key → concrete backend name); installing the
meta entry picks the concrete backend for the detected system (here
`tpu-v5e|tpu-v6e|...|cpu`, system/capabilities.py) and records an alias so
model configs can keep naming the meta backend.

An installed backend is a directory under `backends_path` with a
`metadata.json` and a `run.sh` (the spawn contract — the ModelManager execs
`run.sh --addr 127.0.0.1:<port>` for external backends; in-tree roles keep
spawning `python -m localai_tpu.backend`). Payloads arrive as directories,
tarballs, or OCI images (`oci://`, via localai_tpu/oci).
"""
from __future__ import annotations

import dataclasses
import json
import os
import queue
import shutil
import tarfile
import threading
import uuid
from typing import Any

import yaml

from localai_tpu.backend.server import ROLES
from localai_tpu.downloader.uri import download_file, resolve_uri
from localai_tpu.system.capabilities import detect_capability

METADATA = "metadata.json"


@dataclasses.dataclass
class GalleryBackend:
    name: str
    uri: str = ""
    alias: str = ""
    description: str = ""
    mirrors: list[str] = dataclasses.field(default_factory=list)
    capabilities: dict[str, str] = dataclasses.field(default_factory=dict)
    license: str = ""
    tags: list[str] = dataclasses.field(default_factory=list)

    @property
    def is_meta(self) -> bool:
        return bool(self.capabilities)


class BackendGallery:
    """Registry index (YAML list) from one or more sources (file/http).
    The parsed index is cached for `cache_ttl` seconds so a long-running
    server keeps seeing registry updates without re-fetching per request."""

    def __init__(self, sources: list[str], cache_ttl: float = 60.0):
        self.sources = sources
        self.cache_ttl = cache_ttl
        self._cache: dict[str, GalleryBackend] | None = None
        self._cached_at = 0.0

    def _fetch(self, src: str) -> list[dict]:
        import tempfile

        src = resolve_uri(src)
        if src.startswith(("http://", "https://")):
            with tempfile.NamedTemporaryFile(suffix=".yaml") as tmp:
                download_file(src, tmp.name)
                with open(tmp.name) as f:
                    return yaml.safe_load(f) or []
        path = src[len("file://"):] if src.startswith("file://") else src
        with open(path) as f:
            return yaml.safe_load(f) or []

    def backends(self) -> dict[str, GalleryBackend]:
        import time

        if self._cache is None or (time.monotonic() - self._cached_at
                                   > self.cache_ttl):
            out: dict[str, GalleryBackend] = {}
            known = {f.name for f in dataclasses.fields(GalleryBackend)}
            for src in self.sources:
                for entry in self._fetch(src):
                    gb = GalleryBackend(**{k: v for k, v in entry.items()
                                           if k in known})
                    out[gb.name] = gb
            self._cache = out
            self._cached_at = time.monotonic()
        return self._cache

    def get(self, name: str) -> GalleryBackend | None:
        return self.backends().get(name)


def resolve_meta(gallery: BackendGallery, gb: GalleryBackend,
                 capability: str | None = None) -> GalleryBackend:
    """Meta entry → concrete entry for this system's capability (backends.go
    FindBestBackendFromMeta). Falls back to the `default` key."""
    if not gb.is_meta:
        return gb
    cap = capability or detect_capability()
    target = gb.capabilities.get(cap) or gb.capabilities.get("default")
    if not target:
        raise KeyError(
            f"meta backend {gb.name!r} has no candidate for capability "
            f"{cap!r} (and no default)")
    concrete = gallery.get(target)
    if concrete is None:
        raise KeyError(f"meta backend {gb.name!r} points to unknown "
                       f"backend {target!r}")
    return concrete


def _write_metadata(path: str, meta: dict):
    with open(os.path.join(path, METADATA), "w") as f:
        json.dump(meta, f, indent=1)


def install_backend(gallery: BackendGallery, name: str, backends_path: str,
                    progress=None, capability: str | None = None,
                    force: bool = False) -> str:
    """Install `name` (meta or concrete) into backends_path; returns the
    installed directory. Idempotent unless force."""
    os.makedirs(backends_path, exist_ok=True)
    existing = list_system_backends(backends_path)
    if not force and any(b["name"] == name and not b.get("system")
                         for b in existing):
        return os.path.join(backends_path, name)
    gb = gallery.get(name)
    if gb is None:
        raise KeyError(f"backend {name!r} not in galleries")
    concrete = resolve_meta(gallery, gb, capability)

    dest = os.path.join(backends_path, concrete.name)
    if os.path.realpath(dest) != os.path.join(
            os.path.realpath(backends_path), concrete.name):
        raise ValueError(f"backend name escapes backends path: {name!r}")
    os.makedirs(dest, exist_ok=True)

    uri = concrete.uri
    for candidate in [uri] + concrete.mirrors:
        try:
            _fetch_payload(candidate, dest, progress)
            break
        except Exception:
            if candidate == (concrete.mirrors or [uri])[-1]:
                raise
    meta: dict[str, Any] = {"name": concrete.name, "uri": uri}
    if concrete.alias:
        meta["alias"] = concrete.alias
    _write_metadata(dest, meta)

    if concrete.name != gb.name:
        # meta alias dir so configs can keep naming the meta backend
        mdir = os.path.join(backends_path, gb.name)
        os.makedirs(mdir, exist_ok=True)
        _write_metadata(mdir, {"name": gb.name,
                               "meta_backend_for": concrete.name})
    return dest


def _fetch_payload(uri: str, dest: str, progress=None):
    resolved = resolve_uri(uri)
    if resolved.startswith("oci://") or resolved.startswith("ocifile://"):
        download_file(resolved, dest, progress=progress)
        return
    path = resolved[len("file://"):] if resolved.startswith("file://") \
        else resolved
    if os.path.isdir(path):
        shutil.copytree(path, dest, dirs_exist_ok=True)
        return
    # tarball (local or http)
    local = path
    if resolved.startswith(("http://", "https://")):
        local = os.path.join(dest, ".payload.tar")
        download_file(resolved, local, progress=progress)
    with tarfile.open(local) as tf:
        root = os.path.realpath(dest)
        for m in tf.getmembers():
            target = os.path.realpath(os.path.join(dest, m.name))
            if not (target == root or target.startswith(root + os.sep)):
                raise ValueError(f"tar member escapes backend dir: {m.name!r}")
        tf.extractall(dest, filter="data")
    if local.endswith(".payload.tar"):
        os.unlink(local)


def list_system_backends(backends_path: str) -> list[dict]:
    """Installed external backends + in-tree system roles (backends.go
    ListSystemBackends)."""
    out = [{"name": role, "system": True} for role in sorted(ROLES)]
    if backends_path and os.path.isdir(backends_path):
        for entry in sorted(os.listdir(backends_path)):
            mpath = os.path.join(backends_path, entry, METADATA)
            if os.path.isfile(mpath):
                with open(mpath) as f:
                    meta = json.load(f)
                meta.setdefault("name", entry)
                meta["system"] = False
                out.append(meta)
    return out


def resolve_backend_dir(backends_path: str, name: str) -> str | None:
    """name/alias/meta → runnable backend dir (one with run.sh), or None for
    in-tree roles."""
    if not backends_path:
        return None
    direct = os.path.join(backends_path, name)
    meta_file = os.path.join(direct, METADATA)
    if os.path.isfile(meta_file):
        with open(meta_file) as f:
            meta = json.load(f)
        target = meta.get("meta_backend_for")
        if target:
            return resolve_backend_dir(backends_path, target)
        if os.path.isfile(os.path.join(direct, "run.sh")):
            return direct
    # alias scan
    if os.path.isdir(backends_path):
        for entry in os.listdir(backends_path):
            mpath = os.path.join(backends_path, entry, METADATA)
            if os.path.isfile(mpath):
                with open(mpath) as f:
                    meta = json.load(f)
                if meta.get("alias") == name and os.path.isfile(
                        os.path.join(backends_path, entry, "run.sh")):
                    return os.path.join(backends_path, entry)
    return None


def delete_backend(backends_path: str, name: str):
    """Remove an installed backend (and a meta alias dir pointing at it)."""
    target = os.path.join(backends_path, name)
    if not os.path.isdir(target):
        raise KeyError(f"backend {name!r} is not installed")
    if not os.path.isfile(os.path.join(target, METADATA)):
        raise KeyError(f"{name!r} has no metadata — refusing to delete")
    with open(os.path.join(target, METADATA)) as f:
        meta = json.load(f)
    shutil.rmtree(target)
    concrete = meta.get("meta_backend_for")
    if concrete and os.path.isdir(os.path.join(backends_path, concrete)):
        shutil.rmtree(os.path.join(backends_path, concrete))


class BackendGalleryService:
    """Serialized backend-install job queue with UUID status map (mirrors
    services/gallery.go's model job queue)."""

    def __init__(self, gallery: BackendGallery, backends_path: str):
        self.gallery = gallery
        self.backends_path = backends_path
        self._jobs: "queue.Queue[tuple[str, str]]" = queue.Queue()
        self.status: dict[str, dict] = {}
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def start(self):
        if self._thread:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._jobs.put(("", ""))
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def submit(self, name: str) -> str:
        job_id = uuid.uuid4().hex
        self.status[job_id] = {"state": "queued", "backend": name,
                               "progress": 0.0, "error": ""}
        self._jobs.put((job_id, name))
        return job_id

    def _loop(self):
        while not self._stop.is_set():
            job_id, name = self._jobs.get()
            if not job_id:
                continue
            st = self.status[job_id]
            st["state"] = "processing"

            def progress(done, total, st=st):
                st["progress"] = done / total if total else 0.0

            try:
                path = install_backend(self.gallery, name,
                                       self.backends_path, progress=progress)
                st.update(state="done", progress=1.0, path=path)
            except Exception as e:
                st.update(state="error", error=f"{type(e).__name__}: {e}")
