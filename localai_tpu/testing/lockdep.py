"""Runtime lock-dependency tripwire + schedule-perturbing race harness.

The dynamic half of ``tools/lockdep`` (the static whole-program lock-order
analyzer).  Three pieces:

- ``lockdep_lock(name, lock=None)``: the registration point.  Every
  serving-critical lock in the tree is created through it with a stable
  hierarchy name (``"manager.map"``, ``"kvhost.pool"``, ...) — the same
  names ``tools/lockdep/hierarchy.py`` ranks.  With ``LOCALAI_LOCKDEP``
  unset this returns the raw ``threading.Lock`` untouched (zero overhead,
  same pattern as ``LOCALAI_TRANSFER_GUARD``); when set, the lock comes
  back wrapped in a :class:`LockdepLock` that records per-thread held-sets
  and the global observed acquisition-order graph.

- the tripwire itself: on every acquire the wrapper checks whether the
  lock being taken can already *reach* any currently-held lock in the
  observed order graph (transitive — A→B→C recorded, now C is taken while
  A... wait, while holding C someone takes A).  The first inversion — or a
  hold exceeding ``LOCALAI_LOCKDEP_HOLD_MS`` — raises
  :class:`LockdepViolation` carrying BOTH stacks (the current acquire and
  the first observation of the conflicting order), flight-recorded as a
  ``lockdep_inversion`` event.  ``LOCALAI_LOCKDEP=record`` flight-records
  and accumulates in :func:`violations` instead of raising, so a whole
  chaos suite can run as one lockdep probe.

- ``perturb_schedule(seed)``: a seeded scheduling fuzzer for the ``races``
  pytest lane — shrinks ``sys.setswitchinterval`` and injects randomized
  pre-acquire yields/sleeps through the same wrappers, so latent orderings
  that only appear under unlucky preemption get flushed out in-test
  instead of in production.

Edge identity is by lock *name* (lockdep's "lock class" semantics): two
engines' ``engine.submit`` locks share one node, so an ordering proven bad
on any instance pair trips on every instance pair.  Same-instance
re-acquire on a non-reentrant lock is a certain deadlock and raises even
in record mode (proceeding would hang the probe).

Stdlib-only, imports nothing from the package at module level — telemetry
is reached lazily on the violation path only (telemetry modules create
their own locks through here).
"""
from __future__ import annotations

import contextlib
import os
import random
import sys
import threading
import time
import traceback

__all__ = [
    "LockdepLock", "LockdepViolation", "lockdep_lock", "lockdep_mode",
    "set_lockdep_mode", "hold_threshold_ms", "set_hold_threshold_ms",
    "perturb_schedule", "violations", "reset_lockdep", "held_names",
    "order_graph",
]


class LockdepViolation(AssertionError):
    """A lock-order inversion, same-lock re-acquire, or hold-time trip.

    ``kind`` is one of ``"inversion"``, ``"self-deadlock"``, ``"hold"``;
    ``report`` is the full two-stack human-readable report.
    """

    def __init__(self, kind: str, report: str):
        super().__init__(report)
        self.kind = kind
        self.report = report


# ---------------------------------------------------------------- mode gate

_MODE: str | None = None       # None = read env; set_lockdep_mode overrides
_HOLD_MS: float | None = None  # None = read env


def lockdep_mode() -> str:
    """"" (disabled), "raise", or "record" — from LOCALAI_LOCKDEP ("1" is
    shorthand for "raise"), overridable via set_lockdep_mode for tests."""
    if _MODE is not None:
        return _MODE
    val = os.environ.get("LOCALAI_LOCKDEP", "").strip().lower()
    if val in ("", "0"):
        return ""
    if val in ("1", "raise"):
        return "raise"
    if val == "record":
        return "record"
    return "raise"     # any other truthy value errs on the loud side


def set_lockdep_mode(mode: str | None) -> None:
    """Test hook: "" / "raise" / "record", or None to fall back to the
    environment.  Locks created while disabled stay raw — enable BEFORE
    constructing the objects under test."""
    global _MODE
    _MODE = mode


def hold_threshold_ms() -> float:
    """Hold-time trip threshold (0 = hold checking off)."""
    if _HOLD_MS is not None:
        return _HOLD_MS
    try:
        return float(os.environ.get("LOCALAI_LOCKDEP_HOLD_MS", "0") or 0)
    except ValueError:
        return 0.0


def set_hold_threshold_ms(ms: float | None) -> None:
    global _HOLD_MS
    _HOLD_MS = ms


# ------------------------------------------------------------- global state

# The tripwire's own bookkeeping runs under ONE raw (uninstrumented) lock;
# everything inside it is dict/list work — never a blocking call, never a
# wrapped lock.
_graph_lock = threading.Lock()
# (held_name, acquired_name) -> formatted stack of the FIRST observation
_edges: dict[tuple[str, str], str] = {}
_violations: list[dict] = []
_tls = threading.local()
# perturbation state: (random.Random, max_delay_us) or None
_PERTURB: tuple | None = None


def _held_stack() -> list:
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


def held_names() -> list:
    """Names of the locks the CURRENT thread holds (outermost first)."""
    return [h[0].name for h in _held_stack()]


def order_graph() -> dict:
    """Snapshot of the observed acquisition-order edges
    {(held, acquired): first-observation stack}."""
    with _graph_lock:
        return dict(_edges)


def violations() -> list:
    """Violations accumulated in record mode (each a dict with kind/
    names/report)."""
    with _graph_lock:
        return list(_violations)


def reset_lockdep() -> None:
    """Drop the observed order graph and recorded violations (held-sets of
    live threads are untouched)."""
    global _PERTURB
    with _graph_lock:
        _edges.clear()
        _violations.clear()
    _PERTURB = None


def _reaches(src: str, dst: str) -> bool:
    """Is there a path src -> ... -> dst in the observed edge graph?
    Caller holds _graph_lock."""
    seen = {src}
    stack = [src]
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        for (a, b) in _edges:
            if a == cur and b not in seen:
                seen.add(b)
                stack.append(b)
    return False


def _first_stack(src: str, dst: str) -> str:
    """The stored stack proving some path src -> dst (direct edge when
    present, else the first hop of a path).  Caller holds _graph_lock."""
    direct = _edges.get((src, dst))
    if direct is not None:
        return direct
    for (a, b), stk in _edges.items():
        if a == src and _reaches(b, dst):
            return stk
    return "(stack of the prior ordering was not retained)"


def _report(kind: str, title: str, prior_stack: str | None) -> None:
    """Build the two-stack report, flight-record it, then raise or
    accumulate per mode.  Never called with _graph_lock held."""
    here = "".join(traceback.format_stack(sys._getframe(2)))
    lines = [f"lockdep {kind}: {title}",
             "", "--- this acquisition ---", here]
    if prior_stack is not None:
        lines += ["--- first observation of the conflicting order ---",
                  prior_stack]
    report = "\n".join(lines)
    entry = {"kind": kind, "title": title, "report": report}
    try:
        from localai_tpu import telemetry

        telemetry.flightrec().record_event(
            "lockdep_inversion", lockdep_kind=kind, title=title)
    except Exception:
        pass   # the tripwire must work in processes without telemetry wiring
    mode = lockdep_mode()
    if mode == "record" and kind != "self-deadlock":
        with _graph_lock:
            _violations.append(entry)
        return
    raise LockdepViolation(kind, report)


# ------------------------------------------------------------- the wrapper

class LockdepLock:
    """A named, order-checked wrapper around a real threading lock.

    Delegates acquire/release; before each acquire it (a) applies the
    active schedule perturbation, (b) checks the acquisition against the
    per-thread held-set and the global observed-order graph; after each
    release it checks the hold time.  Supports the full context-manager
    and acquire/release surface the wrapped lock exposes.
    """

    __slots__ = ("name", "_lock", "_reentrant", "_per_key")

    def __init__(self, name: str, lock=None, reentrant: bool = False,
                 per_key: bool = False):
        self.name = name
        self._lock = lock if lock is not None else threading.Lock()
        self._reentrant = reentrant
        self._per_key = per_key

    # -- checks ------------------------------------------------------------

    def _pre_acquire(self) -> None:
        p = _PERTURB
        if p is not None:
            rng, max_us = p
            r = rng.random()
            if r < 0.5:
                time.sleep(0.0)                  # bare yield
            else:
                time.sleep(r * max_us / 1e6)
        held = _held_stack()
        if not held:
            return
        for hlock, _t0, _stk in held:
            if hlock is self._lock or hlock is self:
                if self._reentrant:
                    return      # RLock re-entry: no new ordering information
                _report("self-deadlock",
                        f"re-acquiring non-reentrant lock {self.name!r} "
                        f"already held by this thread — certain deadlock",
                        None)
                return
        prior = None
        conflict = None
        with _graph_lock:
            for hlock, _t0, _stk in held:
                hname = hlock.name if isinstance(hlock, LockdepLock) \
                    else str(hlock)
                if hname == self.name:
                    conflict = (hname, "same-class")
                    prior = None
                    break
                if _reaches(self.name, hname):
                    conflict = (hname, "inversion")
                    prior = _first_stack(self.name, hname)
                    break
        if conflict is None:
            return
        hname, why = conflict
        if why == "same-class":
            _report("inversion",
                    f"acquiring {self.name!r} while already holding another "
                    f"lock of the same class {hname!r} — per-key/instance "
                    f"locks of one class must never nest (ABBA between "
                    f"threads)", None)
        else:
            _report("inversion",
                    f"acquiring {self.name!r} while holding {hname!r}, but "
                    f"the reverse order {self.name!r} -> ... -> {hname!r} "
                    f"was already observed — lock-order inversion "
                    f"(potential deadlock)", prior)

    def _post_acquire(self) -> None:
        held = _held_stack()
        need_stack = hold_threshold_ms() > 0
        my_stack = ("".join(traceback.format_stack(sys._getframe(2)))
                    if need_stack else "")
        new_edges = []
        with _graph_lock:
            for hlock, _t0, _stk in held:
                if not isinstance(hlock, LockdepLock):
                    continue
                key = (hlock.name, self.name)
                if key not in _edges and hlock.name != self.name:
                    new_edges.append(key)
            for key in new_edges:
                # capture the stack proving this order, once per edge
                _edges[key] = "".join(
                    traceback.format_stack(sys._getframe(1)))
        held.append((self, time.perf_counter(), my_stack))

    def _pop_held(self):
        """Drop this lock from the thread's held stack; return the hold-time
        trip (title string) if the hold exceeded the threshold, else None.
        Never raises — the caller must release the real lock FIRST, then
        report, or a raise-mode trip would leave it held forever."""
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                _lock, t0, stk = held.pop(i)
                thr = hold_threshold_ms()
                if thr > 0:
                    dt_ms = (time.perf_counter() - t0) * 1e3
                    if dt_ms > thr:
                        return (f"lock {self.name!r} held for "
                                f"{dt_ms:.1f} ms (threshold {thr:.1f} ms)"
                                + (f"\n--- acquired at ---\n{stk}"
                                   if stk else ""))
                return None
        return None

    # -- lock surface ------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._pre_acquire()
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._post_acquire()
        return got

    def release(self):
        trip = self._pop_held()
        self._lock.release()
        if trip is not None:
            _report("hold", trip, None)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<LockdepLock {self.name!r} wrapping {self._lock!r}>"


def lockdep_lock(name: str, lock=None, per_key: bool = False):
    """Create (or wrap) a lock registered under a hierarchy ``name``.

    Disabled (the default): returns ``lock`` — or a fresh
    ``threading.Lock()`` when none is given — completely untouched.
    Enabled (``LOCALAI_LOCKDEP`` / :func:`set_lockdep_mode`): returns a
    :class:`LockdepLock` enforcing the observed acquisition order.

    ``name`` should match an entry in ``tools/lockdep/hierarchy.py`` so
    the static and runtime layers talk about the same lock classes.
    """
    if lock is None:
        lock = threading.Lock()
    if not lockdep_mode():
        return lock
    reentrant = type(lock).__name__ in ("RLock", "_RLock")
    return LockdepLock(name, lock, reentrant=reentrant, per_key=per_key)


# ------------------------------------------------------ schedule perturber

@contextlib.contextmanager
def perturb_schedule(seed: int = 0, max_delay_us: float = 200.0,
                     switch_interval: float = 1e-5):
    """Seeded schedule fuzzer for the ``races`` pytest lane.

    Shrinks the interpreter's thread switch interval (more preemption
    points) and arms randomized pre-acquire delays inside every
    :class:`LockdepLock` — half the injections are bare yields, half are
    sleeps up to ``max_delay_us``.  Deterministic per seed at the decision
    level (the OS still owns true interleaving).  Restores both on exit.

    Only instrumented locks perturb, so enable lockdep (and construct the
    objects under test) before entering.
    """
    global _PERTURB
    rng = random.Random(seed)
    old_interval = sys.getswitchinterval()
    sys.setswitchinterval(switch_interval * (0.5 + rng.random()))
    prev = _PERTURB
    _PERTURB = (rng, float(max_delay_us))
    try:
        yield rng
    finally:
        _PERTURB = prev
        sys.setswitchinterval(old_interval)
