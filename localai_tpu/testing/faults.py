"""Deterministic fault injection for the chaos harness (ISSUE 4).

Activated by the `LOCALAI_FAULT` environment variable — a comma-separated
list of fault specs, each `kind[:arg[:limit[:target]]]`:

- `kind`: injection point name. Wired points:
    `spawn_crash`   backend process exits immediately at startup (the
                    free_port TOCTOU / dead-child shape; arg = exit code)
    `slow_start`    backend sleeps `arg` seconds before serving health
    `unavailable`   Predict/PredictStream aborts with gRPC UNAVAILABLE
    `deadline`      Predict/PredictStream aborts with DEADLINE_EXCEEDED
    `stall_stream`  PredictStream sleeps `arg` seconds after its first chunk
    `preempt`       backend raises SIGTERM against itself after the first
                    emitted token of a stream — the preemption-notice
                    fast-path (ISSUE 19): the engine spill-drains live slots
                    into ResumeTokens before the process stops (arg = grace
                    seconds the drain lets slots keep running)
    `kill9_middecode`  backend SIGKILLs itself at the `arg`-th emitted token
                    of a stream (default 1) — ungraceful death mid-decode:
                    no drain, no checkpoint; the HTTP bridge must resume
                    from its own accumulated stream state
- `arg`: float parameter (seconds / exit code); default 0.
- `limit`: inject at most N times; empty = unlimited. Counting is shared
  across processes when `LOCALAI_FAULT_DIR` points at a directory (one
  marker file per injection, O_EXCL-raced so concurrent processes never
  double-count a slot); otherwise per-process.
- `target`: only inject in processes whose `LOCALAI_FAULT_MODEL` matches
  (the ModelManager stamps each backend spawn with its model name); empty
  = every process. This is what lets one chaos test crash model A's
  backend while model B serves normally.

Example: `LOCALAI_FAULT=slow_start:3::slowpoke,unavailable:0:1:tiny`
injects a 3 s startup stall into every `slowpoke` backend and exactly one
UNAVAILABLE abort into `tiny`'s generation path.

The whole module is read-only over os.environ at call time — no setup, no
registration; a subprocess inherits the spec through its environment.
"""
from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_local_counts: dict[str, int] = {}


def _specs() -> list[tuple[str, float, int | None, str]]:
    raw = os.environ.get("LOCALAI_FAULT", "")
    if not raw:
        return []
    out = []
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = (entry.split(":") + ["", "", ""])[:4]
        kind, arg, limit, target = parts
        try:
            farg = float(arg) if arg else 0.0
        except ValueError:
            farg = 0.0
        try:
            nlimit = int(limit) if limit else None
        except ValueError:
            nlimit = None
        out.append((kind, farg, nlimit, target))
    return out


def _take_slot(kind: str, target: str, limit: int | None) -> bool:
    """Claim one injection slot for a (kind, target) entry; False once
    `limit` is spent. Each spec entry counts independently — two models'
    stall_stream faults never steal each other's slots. Shared-count mode
    (LOCALAI_FAULT_DIR) survives process boundaries."""
    if limit is None:
        return True
    key = f"{kind}@{target}" if target else kind
    fault_dir = os.environ.get("LOCALAI_FAULT_DIR", "")
    if fault_dir and os.path.isdir(fault_dir):
        n = 0
        while n < limit:
            try:
                fd = os.open(os.path.join(fault_dir, f"{key}.{n}"),
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                return True
            except FileExistsError:
                n += 1
        return False
    with _lock:
        used = _local_counts.get(key, 0)
        if used >= limit:
            return False
        _local_counts[key] = used + 1
        return True


def fire(kind: str) -> float | None:
    """Should fault `kind` inject right now? Returns its arg (consuming one
    count) when yes, None when no. Fast path: env unset → one dict miss."""
    if not os.environ.get("LOCALAI_FAULT"):
        return None
    me = os.environ.get("LOCALAI_FAULT_MODEL", "")
    for k, arg, limit, target in _specs():
        if k != kind:
            continue
        if target and target != me:
            continue
        if not _take_slot(kind, target, limit):
            continue
        import sys

        print(f"[fault] {kind} arg={arg} target={target or '*'} "
              f"pid={os.getpid()} firing", file=sys.stderr, flush=True)
        return arg
    return None
