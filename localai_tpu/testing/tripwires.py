"""Runtime tripwires — the dynamic half of tools/lint.

The AST pass (tools/lint) catches the host syncs and recompile hazards it can
see; these two guards catch what it can't:

- transfer guard: `LOCALAI_TRANSFER_GUARD=disallow` makes the engine wrap
  every fused decode dispatch in `jax.transfer_guard("disallow")` — any
  implicit host↔device transfer inside the dispatch (an un-wrapped numpy
  arg, a stray `.item()` on a donated buffer) raises instead of silently
  stalling the pipeline. Explicit transfers (jnp.asarray / device_put /
  device_get) stay legal: the contract is "syncs are spelled out", not
  "no transfers".

- compile-count guard: `decode_compile_count(engine)` sums the jit cache
  sizes of the decode-step programs, and `CompileCounter` counts live XLA
  compilations via jax.log_compiles. A perf PR that makes `decode_step`
  retrace per request (tracer branch, data-dependent shape, unhashed jit
  arg) fails the guard long before anyone reads a profile.

- dispatch-count guard: `dispatch_budget(engine, ...)` asserts the enclosed
  stream keeps the decode-dispatch count within a budget per 128 generated
  tokens. The single-dispatch while-loop makes a 128-token single-slot
  stream ~2 dispatches; a regression back to the scan ladder (8-16) or to
  per-step dispatches (128) trips the guard in a tier-1 test instead of a
  chip profile. Ragged dispatches count too, credited with the tokens they
  actually packed (generated + prefill-chunk) — only spec-as-ragged verify
  windows are exempt.

The concurrency sibling lives in `localai_tpu.testing.lockdep`: the same
env-gate pattern (`LOCALAI_LOCKDEP=1` / `record`, raw locks when unset)
arms an acquisition-order tripwire over every lock registered through
`lockdep_lock()` — the dynamic half of `tools/lockdep`, the way these
guards are the dynamic half of `tools/lint`.
"""
from __future__ import annotations

import contextlib
import logging
import math
import os


def decode_guard_level() -> str:
    """The engine's transfer-guard level from LOCALAI_TRANSFER_GUARD
    ("" = disabled; "1" is shorthand for "disallow")."""
    val = os.environ.get("LOCALAI_TRANSFER_GUARD", "").strip()
    if val == "1":
        return "disallow"
    if val in ("", "0"):
        return ""
    return val


def transfer_guard(level: str = "disallow"):
    """Context manager guarding implicit transfers (both directions) —
    nullcontext when level is empty."""
    if not level:
        return contextlib.nullcontext()
    import jax

    return jax.transfer_guard(level)


# the engine attributes holding decode-step jit programs; everything the
# per-token serving path can dispatch (admission/prefill compile per bucket
# by design and are not covered by the exactly-once contract)
DECODE_FN_ATTRS = (
    "_decode_fn", "_decode_nomask_fn", "_decode_fast_fn",
    "_decode_block_fn", "_decode_block_mask_fn", "_decode_loop_fn",
    "_spec_fn", "_ragged_fn", "_spec_ragged_fn", "_ragged_loop_fn",
)


def jit_cache_size(fn) -> int:
    """Compiled-variant count of a jax.jit callable (-1 when the runtime
    doesn't expose it — the guard then degrades to the CompileCounter)."""
    try:
        return int(fn._cache_size())
    except Exception:
        return -1


def decode_cache_sizes(engine) -> dict[str, int]:
    out = {}
    for attr in DECODE_FN_ATTRS:
        fn = getattr(engine, attr, None)
        if fn is not None:
            out[attr] = jit_cache_size(fn)
    return out


def decode_compile_count(engine) -> int:
    """Total decode-step programs compiled by this engine. The regression
    contract (ROADMAP #1): a mixed-length request stream with uniform
    sampling knobs compiles the decode step EXACTLY ONCE — prefill buckets
    absorb length variance; per-knob static variants (fast_width tiers,
    decode_block ladder steps) are deliberate and each counts once."""
    sizes = decode_cache_sizes(engine)
    known = [v for v in sizes.values() if v >= 0]
    return sum(known)


@contextlib.contextmanager
def dispatch_budget(engine, max_per_128_tokens: float = 3.0):
    """Decode-dispatch counter guard: assert the enclosed stream spends no
    more than `max_per_128_tokens` decode dispatches per 128 generated
    tokens (pro-rated, floor 1). Reads the engine's own decode_dispatches /
    tokens_generated counters, so it works across loop, block, ragged, and
    spec paths without instrumentation.

    Ragged mode counts for real (ISSUE 16): a ragged dispatch earns budget
    from the tokens it actually packed — generated tokens through
    `tokens_generated`, prefill chunk tokens through `ragged_prefill_tokens`
    — so a decode-heavy single-step ragged stream (~1 dispatch per token,
    ~4 prefill-credit tokens per dispatch) TRIPS a 3/128 budget unless the
    fused multi-step loop engages. Only spec-as-ragged dispatches stay
    exempt (`spec_ragged_dispatches` is subtracted): a verify window is
    gamma-fused by construction and its efficiency is gated by acceptance
    telemetry, not dispatch counting."""
    m = engine.metrics
    d0, t0 = m["decode_dispatches"], m["tokens_generated"]
    s0 = m.get("spec_ragged_dispatches", 0)
    p0 = m.get("ragged_prefill_tokens", 0)
    yield
    dispatches = (m["decode_dispatches"] - d0) \
        - (m.get("spec_ragged_dispatches", 0) - s0)
    tokens = (m["tokens_generated"] - t0) \
        + (m.get("ragged_prefill_tokens", 0) - p0)
    allowed = max(1, math.ceil(tokens / 128.0 * max_per_128_tokens))
    if dispatches > allowed:
        # flight-recorder post-mortem (ISSUE 11): the request timelines in
        # the ring at trip time show WHICH stream regressed to the ladder
        from localai_tpu import telemetry

        rec = telemetry.flightrec()
        rec.record_event("tripwire", guard="dispatch_budget",
                         dispatches=dispatches, tokens=tokens,
                         allowed=allowed)
        rec.auto_dump("tripwire:dispatch_budget")
        raise AssertionError(
            f"decode dispatch budget exceeded: {dispatches} dispatches for "
            f"{tokens} credited tokens (allowed {allowed} at "
            f"{max_per_128_tokens}/128-token) — a fused loop (decode or "
            f"ragged) is not engaging or has regressed to per-step "
            f"dispatch")


class CompileCounter:
    """Count XLA compilations by function name while the context is open.

    Rides `jax.log_compiles`: the pxla layer logs one
    "Compiling <name> ..." record per backend compile, which a handler on
    the "jax" logger tree tallies. Zero new compilations across a repeat
    stream is the strongest no-retrace assertion available at runtime.
    """

    def __init__(self):
        self.counts: dict[str, int] = {}
        self._handler: logging.Handler | None = None
        self._ctx = None

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def __enter__(self):
        import jax

        counter = self

        class _H(logging.Handler):
            def emit(self, record):
                msg = record.getMessage()
                if msg.startswith("Compiling "):
                    name = msg.split()[1]
                    counter.counts[name] = counter.counts.get(name, 0) + 1

        self._handler = _H(level=logging.DEBUG)
        logging.getLogger("jax").addHandler(self._handler)
        self._ctx = jax.log_compiles(True)
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            self._ctx.__exit__(*exc)
            self._ctx = None
        if self._handler is not None:
            logging.getLogger("jax").removeHandler(self._handler)
            self._handler = None
        return False
