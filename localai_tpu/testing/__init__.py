"""Test-support package: deterministic fault injection (`testing.faults`)
and the runtime perf tripwires (`testing.tripwires`).

Shipped inside the package (not under tests/) because the injection points
live in production modules — the backend entrypoint and the LLM servicer
call `faults.fire(...)` at their hazard points, the engine reads
`tripwires.decode_guard_level()` at construction — and those hooks must
resolve in spawned subprocesses too. With `LOCALAI_FAULT` /
`LOCALAI_TRANSFER_GUARD` unset every hook is a dict/env lookup returning
None-or-empty.
"""
from localai_tpu.testing import faults, tripwires  # noqa: F401
