"""Test-support package: deterministic fault injection (`testing.faults`).

Shipped inside the package (not under tests/) because the injection points
live in production modules — the backend entrypoint and the LLM servicer
call `faults.fire(...)` at their hazard points, and those calls must resolve
in spawned subprocesses too. With `LOCALAI_FAULT` unset every hook is a
single dict lookup returning None.
"""
from localai_tpu.testing import faults  # noqa: F401
