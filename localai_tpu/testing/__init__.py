"""Test-support package: deterministic fault injection (`testing.faults`),
the runtime perf tripwires (`testing.tripwires`), and the lock-dependency
tripwire + schedule perturber (`testing.lockdep`).

Shipped inside the package (not under tests/) because the injection points
live in production modules — the backend entrypoint and the LLM servicer
call `faults.fire(...)` at their hazard points, the engine reads
`tripwires.decode_guard_level()` at construction, every serving-critical
lock is created through `lockdep.lockdep_lock(name)` — and those hooks
must resolve in spawned subprocesses too. With `LOCALAI_FAULT` /
`LOCALAI_TRANSFER_GUARD` / `LOCALAI_LOCKDEP` unset every hook is a
dict/env lookup returning None-or-empty (lockdep_lock hands back the raw
threading.Lock untouched).
"""
from localai_tpu.testing import faults, lockdep, tripwires  # noqa: F401
