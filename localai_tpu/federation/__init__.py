"""Federated load balancing: route requests across whole-model replica
workers.

Reference: /root/reference/core/p2p/federated_server.go:15-103 — a proxy in
front of libp2p-tunneled workers with least-used/random selection (sync.go),
worker registry (node.go). The libp2p/edgevpn overlay itself is a deliberate
exclusion (no such runtime in this image; the LB is transport-agnostic and
works over any reachable worker URL — plain TCP, VPN, or tunnel).

Here the federated server is an aiohttp reverse proxy: workers are full
localai-tpu HTTP servers (= replica groups on separate TPU slices); selection
strategies match the reference (least_used | random | round_robin), dead
workers are skipped and retried.
"""
from __future__ import annotations

import asyncio
import itertools
import random
import time

import aiohttp
from aiohttp import web

from localai_tpu.core.resilience import CircuitBreaker


class Worker:
    """One upstream replica. The circuit breaker (core/resilience — the same
    class guarding backend subprocesses) stops the LB from re-probing a
    flapping worker on every request: after `threshold` failures it is
    skipped outright until the cooldown elapses."""

    def __init__(self, url: str, breaker_threshold: int = 3,
                 breaker_cooldown: float = 10.0):
        self.url = url.rstrip("/")
        self.in_flight = 0
        self.total = 0
        self.healthy = True
        self.last_check = 0.0
        # KV-affinity gossip (ISSUE 17): the worker's served-prefix digest
        # — text-chunk chain ids (engine/kvhost.text_chain_ids) it reported
        # on its last /healthz poll. pick(prompt_hint=) scores the leading
        # run of a request's ids against this set so a conversation's
        # follow-up turn lands where its KV (device or host tier) lives.
        self.kv_digest: frozenset = frozenset()
        self.breaker = CircuitBreaker(threshold=breaker_threshold,
                                      cooldown=breaker_cooldown,
                                      name=f"worker:{self.url}")


class FederatedServer:
    """`token` enables the shared-token HMAC scheme (federation/auth.py —
    the reference's p2p token+OTP role, p2p.go:31-66): incoming requests
    must carry a valid X-LocalAI-Federation signature, and proxied requests
    are re-signed so token-configured workers accept them."""

    def __init__(self, workers: list[str], strategy: str = "least_used",
                 health_interval: float = 10.0, token: str = ""):
        if strategy not in ("least_used", "random", "round_robin"):
            raise ValueError(f"unknown strategy {strategy!r}")
        self.workers = [Worker(w) for w in workers]
        self.strategy = strategy
        self.health_interval = health_interval
        self.token = token
        self._rr = itertools.count()
        self.app = web.Application()
        self.app.router.add_get("/healthz", self._health)
        self.app.router.add_get("/federation/workers", self._workers_info)
        self.app.router.add_route("*", "/{tail:.*}", self._proxy)
        self._session: aiohttp.ClientSession | None = None

    # ------------------------------------------------------------ selection

    def pick(self, prompt_hint=None) -> Worker | None:
        live = [w for w in self.workers
                if w.healthy and w.breaker.allow()]
        # all breakers open / all unhealthy: half-open probes re-admit
        # workers after their cooldown; until then, any worker beats none.
        # KV affinity never applies on this degraded path — a worker whose
        # breaker is open doesn't get requests for holding the right KV
        degraded = not live
        live = live or [w for w in self.workers if w.breaker.allow()] \
            or self.workers
        if not live:
            return None
        if prompt_hint and not degraded:
            # KV affinity (ISSUE 17): prefer the worker whose gossiped
            # digest covers the longest leading run of the request's
            # text-chain ids — turn 2 lands where turn 1's KV lives.
            # Ties (including the no-coverage case) fall through to the
            # configured strategy below over the tied workers.
            from localai_tpu.engine.kvhost import coverage

            scored = [(coverage(w.kv_digest, prompt_hint), w) for w in live]
            best = max(c for c, _ in scored)
            if best > 0:
                tied = [w for c, w in scored if c == best]
                if len(tied) == 1:
                    return tied[0]
                live = tied
        if self.strategy == "random":
            return random.choice(live)
        if self.strategy == "round_robin":
            return live[next(self._rr) % len(live)]
        return min(live, key=lambda w: w.in_flight)

    async def _check_health(self, w: Worker):
        now = time.monotonic()
        if now - w.last_check < self.health_interval:
            return
        w.last_check = now
        try:
            async with self._session.get(w.url + "/healthz",
                                         timeout=aiohttp.ClientTimeout(total=3)) as r:
                w.healthy = r.status == 200
                if w.healthy:
                    # KV-affinity gossip rides the existing poll: workers
                    # report their served-prefix digest in the healthz
                    # body (server/http.py). Non-JSON bodies (older
                    # workers) just leave the digest empty.
                    try:
                        info = await r.json()
                        w.kv_digest = frozenset(info.get("kv_digest") or ())
                    except Exception:
                        pass
        except Exception:
            w.healthy = False

    # ------------------------------------------------------------ handlers

    async def _health(self, request):
        return web.json_response({"status": "ok",
                                  "workers": len(self.workers)})

    def _authorized(self, request: web.Request, body: bytes) -> bool:
        if not self.token:
            return True
        from localai_tpu.federation.auth import HEADER, verify

        return verify(self.token, request.headers.get(HEADER),
                      request.method, request.path_qs, body)

    async def _workers_info(self, request):
        if not self._authorized(request, b""):
            raise web.HTTPUnauthorized(text="federation token required")
        return web.json_response([{
            "url": w.url, "healthy": w.healthy, "in_flight": w.in_flight,
            "total": w.total, "breaker": w.breaker.state,
            "kv_digest_size": len(w.kv_digest),
        } for w in self.workers])

    async def _proxy(self, request: web.Request):
        if self._session is None:
            self._session = aiohttp.ClientSession()
        body = await request.read()
        if not self._authorized(request, body):
            raise web.HTTPUnauthorized(text="federation token required")
        last_error = None
        # KV-affinity hint (ISSUE 17): text-chain ids of the request's
        # conversation, computed from the SAME body bytes the worker will
        # hash on its side — their digests agree by construction. Non-chat
        # paths and unparseable bodies yield [] (plain load balancing).
        hint: list = []
        tail = request.match_info["tail"]
        if request.method == "POST" and (
                "chat/completions" in tail or "completions" in tail):
            from localai_tpu.engine.kvhost import request_hint

            hint = request_hint(body)
        # try up to len(workers) distinct workers (federated_server.go:66-99
        # skip-to-next-replica behavior)
        tried: set[str] = set()
        for _ in range(len(self.workers)):
            w = self.pick(prompt_hint=hint)
            if w is None or w.url in tried:
                break
            tried.add(w.url)
            await self._check_health(w)
            if not w.healthy:
                continue
            w.in_flight += 1
            w.total += 1
            try:
                url = w.url + "/" + request.match_info["tail"]
                if request.query_string:
                    url += "?" + request.query_string
                headers = {k: v for k, v in request.headers.items()
                           if k.lower() not in ("host", "content-length")}
                if self.token:
                    from localai_tpu.federation.auth import HEADER, sign

                    upstream_path = "/" + request.match_info["tail"]
                    if request.query_string:
                        upstream_path += "?" + request.query_string
                    headers[HEADER] = sign(self.token, request.method,
                                           upstream_path, body or b"")
                async with self._session.request(
                        request.method, url, data=body or None,
                        headers=headers,
                        timeout=aiohttp.ClientTimeout(total=600)) as r:
                    resp = web.StreamResponse(status=r.status)
                    for k, v in r.headers.items():
                        if k.lower() not in ("transfer-encoding",
                                             "content-length", "connection"):
                            resp.headers[k] = v
                    await resp.prepare(request)
                    async for chunk in r.content.iter_chunked(16384):
                        await resp.write(chunk)
                    await resp.write_eof()
                    w.breaker.record_success()
                    return resp
            except Exception as e:
                w.healthy = False
                w.breaker.record_failure()
                last_error = e
            finally:
                w.in_flight -= 1
        raise web.HTTPBadGateway(
            text=f"no healthy federation worker ({last_error})")

    async def close(self):
        if self._session is not None:
            await self._session.close()


def run_federated(args) -> int:
    """CLI `federated` entrypoint (reference core/cli federated cmd)."""
    import os

    workers = [w.strip() for w in (args.workers or "").split(",") if w.strip()]
    if not workers:
        print("no --workers given")
        return 1
    token = (getattr(args, "token", "") or
             os.environ.get("LOCALAI_FEDERATION_TOKEN", ""))
    srv = FederatedServer(workers, strategy=args.strategy, token=token)
    host, _, port = args.address.rpartition(":")
    web.run_app(srv.app, host=host or "127.0.0.1", port=int(port),
                print=lambda *a: print(f"federated LB on {args.address} → "
                                       f"{len(workers)} workers", flush=True))
    return 0
