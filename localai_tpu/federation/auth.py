"""Federation request authentication — shared-token HMAC with a rolling
time window.

Reference role: the cluster layer's shared token + OTP rendezvous
(/root/reference/core/p2p/p2p.go:31-66: the token seeds an OTP that rotates
on an interval and gates who may join/talk). Without a libp2p overlay the
TPU framework's federation is plain HTTP, so the same trust model becomes a
signed header:

    X-LocalAI-Federation: <unix_ts>.<hex hmac_sha256(token,
                              "{ts}:{METHOD}:{path_qs}:{sha256(body)}")>

- the token never travels on the wire (only MACs of it),
- the timestamp bounds replay to ±`skew` seconds (the OTP-interval role),
- method/path+query/body binding stops a captured signature being replayed
  against a different endpoint, parameters, or payload. Callers MUST pass
  the path WITH its query string (aiohttp `request.path_qs`).
"""
from __future__ import annotations

import hashlib
import hmac
import time

HEADER = "X-LocalAI-Federation"
DEFAULT_SKEW = 90.0


def _mac(token: str, ts: int, method: str, path: str, body: bytes) -> str:
    msg = f"{ts}:{method.upper()}:{path}:{hashlib.sha256(body).hexdigest()}"
    return hmac.new(token.encode(), msg.encode(), hashlib.sha256).hexdigest()


def sign(token: str, method: str, path: str, body: bytes = b"",
         ts: int | None = None) -> str:
    """Header value authenticating one request."""
    ts = int(time.time()) if ts is None else int(ts)
    return f"{ts}.{_mac(token, ts, method, path, body)}"


def verify(token: str, header: str | None, method: str, path: str,
           body: bytes = b"", skew: float = DEFAULT_SKEW,
           now: float | None = None) -> bool:
    """Constant-time verification of a signed header; False on anything
    malformed, stale, or forged."""
    if not token or not header or "." not in header:
        return False
    ts_s, _, mac = header.partition(".")
    try:
        ts = int(ts_s)
    except ValueError:
        return False
    now = time.time() if now is None else now
    if abs(now - ts) > skew:
        return False
    return hmac.compare_digest(mac, _mac(token, ts, method, path, body))
