"""Hardware capability detection (reference: /root/reference/pkg/system/
capabilities.go:28-99 — GPU vendor → capability string used to pick concrete
backends, force-file override :49-64; sysinfo pkg/xsysinfo).

TPU build: capability keys are `tpu-v4|tpu-v5e|tpu-v5p|tpu-v6e|cpu`, detected
from the attached JAX device (lazily — detection must not initialize a TPU
client at import time)."""
from __future__ import annotations

import functools
import os


CAPABILITY_FORCE_FILE = "/run/localai/capability"


@functools.lru_cache(maxsize=1)
def detect_capability() -> str:
    # force-file override wins (capabilities.go:49-64)
    if os.path.exists(CAPABILITY_FORCE_FILE):
        with open(CAPABILITY_FORCE_FILE) as f:
            forced = f.read().strip()
        if forced:
            return forced
    if os.environ.get("LOCALAI_FORCE_CAPABILITY"):
        return os.environ["LOCALAI_FORCE_CAPABILITY"]
    try:
        import jax

        d = jax.devices()[0]
        kind = getattr(d, "device_kind", "").lower()
        if d.platform == "cpu":
            return "cpu"
        for tag in ("v6e", "v5p", "v5e", "v5", "v4"):
            if tag in kind:
                return f"tpu-{'v5e' if tag == 'v5' else tag}"
        return "tpu"
    except Exception:
        return "cpu"


def system_info() -> dict:
    """CPU/memory/accelerator summary (xsysinfo role)."""
    info: dict = {"capability": detect_capability()}
    try:
        from localai_tpu.system.memory import hbm_table_bytes

        hbm = hbm_table_bytes(info["capability"])
        if hbm:
            info["hbm_bytes"] = hbm
    except Exception:
        pass
    try:
        info["cpu_count"] = os.cpu_count()
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal"):
                    info["mem_total_kb"] = int(line.split()[1])
                    break
    except OSError:
        pass
    try:
        import jax

        info["devices"] = [
            {"id": d.id, "platform": d.platform,
             "kind": getattr(d, "device_kind", "")}
            for d in jax.devices()
        ]
    except Exception:
        info["devices"] = []
    return info
