"""HBM fit estimation — the gguf-parser VRAM-estimate role.

Reference: /root/reference/pkg/xsysinfo/gguf.go estimates whether a GGUF fits
VRAM before loading. Here the estimate is computed from the HF config
geometry (the same numbers the loader uses), covering weights, the KV cache
(dense or int8), and a working-set allowance — and compared against the
attached accelerator's memory (memory_stats when the runtime exposes it,
a per-generation table otherwise).
"""
from __future__ import annotations

import dataclasses
from typing import Any

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2,
                "int8": 1, "q8": 1, "int4": 0.5, "q4": 0.5}

# per-chip HBM for the TPU generations the capability detector reports
_HBM_TABLE = {"tpu-v4": 32 << 30, "tpu-v5e": 16 << 30,
              "tpu-v5p": 95 << 30, "tpu-v6e": 32 << 30}


@dataclasses.dataclass
class MemoryEstimate:
    weights_bytes: int
    kv_cache_bytes: int
    working_bytes: int
    total_bytes: int
    hbm_bytes: int | None

    @property
    def fits(self) -> bool | None:
        if self.hbm_bytes is None:
            return None
        return self.total_bytes <= self.hbm_bytes

    def to_dict(self) -> dict[str, Any]:
        return {
            "weights_bytes": self.weights_bytes,
            "kv_cache_bytes": self.kv_cache_bytes,
            "working_bytes": self.working_bytes,
            "total_bytes": self.total_bytes,
            "hbm_bytes": self.hbm_bytes,
            "fits": self.fits,
        }


def param_count(cfg) -> int:
    """LlamaConfig → parameter count (dense or MoE)."""
    h, hd = cfg.hidden_size, cfg.head_dim
    qk = cfg.num_heads * hd
    kv = cfg.num_kv_heads * hd
    attn = h * qk + 2 * h * kv + qk * h
    if cfg.num_experts:
        mlp = cfg.num_experts * 3 * h * cfg.intermediate_size \
            + h * cfg.num_experts
    else:
        mlp = 3 * h * cfg.intermediate_size
    per_layer = attn + mlp + 2 * h
    embed = cfg.vocab_size * h * (1 if cfg.tie_embeddings else 2)
    return embed + cfg.num_layers * per_layer + h


def hbm_table_bytes(capability: str) -> int | None:
    """Per-generation HBM lookup (no accelerator runtime touched — safe for
    the control-plane process, which must never init jax)."""
    return _HBM_TABLE.get(capability)


def detect_hbm_bytes() -> int | None:
    """Attached accelerator memory: memory_stats()['bytes_limit'] when the
    runtime exposes it, else the generation table, else None (CPU)."""
    try:
        import jax

        dev = jax.devices()[0]
        if dev.platform == "cpu":
            return None
        stats = getattr(dev, "memory_stats", lambda: None)()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        return None
    from localai_tpu.system.capabilities import detect_capability

    return hbm_table_bytes(detect_capability())


def estimate(cfg, *, slots: int, context: int, dtype: str = "bfloat16",
             cache_type: str = "", hbm_bytes: int | None = None,
             draft_cfg=None, shards: int = 1,
             kv_shards: int | None = None,
             kv_pages: int = 0,
             detect_hbm: bool = True) -> MemoryEstimate:
    """PER-CHIP serving-memory estimate for a Llama-family config at the
    given engine shape (reference role: initializers' VRAM guesser guarding
    LoadModel). `shards` divides the weights (the TP 'model' axis — data
    replicas hold full copies); `kv_shards` divides the KV cache (sharded
    over BOTH axes: slots on 'data', kv heads on 'model'; defaults to
    `shards`). `kv_pages` > 0 sizes a PAGED cache (ops/paged.py): the pool is
    kv_pages 128-token blocks shared across slots, so slots × context stops
    being the dense product."""
    wbytes = int(param_count(cfg) * _DTYPE_BYTES.get(dtype, 2))
    if _DTYPE_BYTES.get(dtype, 2) < 2:
        # quantized weights carry f32 per-channel scales (~1/in_dim overhead)
        wbytes = int(wbytes * 1.02)

    kv_elem = 1 if cache_type in ("int8", "q8_0", "q8") else 2
    kv_tokens = kv_pages * 128 if kv_pages > 0 else slots * context
    kv = (2 * cfg.num_layers * kv_tokens * cfg.num_kv_heads
          * cfg.head_dim * kv_elem)
    if cache_type in ("int8", "q8_0", "q8"):
        kv += 2 * cfg.num_layers * kv_tokens * cfg.num_kv_heads * 4

    if draft_cfg is not None:
        wbytes += int(param_count(draft_cfg) * _DTYPE_BYTES.get(dtype, 2))
        kv += (2 * draft_cfg.num_layers * slots * draft_cfg.num_kv_heads
               * context * draft_cfg.head_dim * 2)

    wbytes = wbytes // max(shards, 1)
    kv = kv // max(kv_shards if kv_shards is not None else shards, 1)

    # working set: logits [slots, V] f32 ×2 (last + sampled), sampler state,
    # transient fusion buffers — a conservative 512MB + logits
    working = 2 * slots * cfg.vocab_size * 4 + (512 << 20)

    hbm = hbm_bytes
    if hbm is None and detect_hbm:
        hbm = detect_hbm_bytes()
    total = wbytes + kv + working
    return MemoryEstimate(wbytes, kv, working, total, hbm)
