from localai_tpu.system.capabilities import (  # noqa: F401
    detect_capability,
    system_info,
)
