"""localai_tpu — a TPU-native, OpenAI-API-compatible inference framework.

A ground-up re-design of the capability surface of LocalAI
(reference: skyscope-sentinel/LocalAI) for TPU hardware:

- control plane: asyncio HTTP server (OpenAI + LocalAI-native routes),
  YAML model configs, templating, grammar-constrained function calling,
  model galleries, backend process lifecycle   (reference: Go core, L3-L7)
- process boundary: one gRPC contract, many backend processes
  (reference: backend/backend.proto)
- compute plane: a first-class JAX/XLA engine — safetensors → sharded
  jax.Array over an ICI Mesh, continuous-batching decode as a jitted
  slot-array step, Pallas kernels for the hot ops
  (reference role: backend/cpp/llama-cpp grpc-server + vLLM)
"""

__version__ = "0.1.0"
