"""Attention reference implementations (pure XLA).

Layouts (chosen so the MXU sees large [tokens, head_dim] matmuls and the
sharding layer can shard the head axis over the `model` mesh axis):

  q:        [B, S, H, D]
  k/v:      [B, S, KVH, D]      (GQA: H % KVH == 0)
  kv cache: [B, KVH, T, D]      (slot-contiguous, head-major, T = max context —
                                 head-major keeps the Pallas decode kernel's
                                 trailing block dims at (seq, head_dim), the
                                 Mosaic-legal tiling)

Softmax is computed in float32; matmuls stay in the input dtype (bf16).
These XLA versions are the semantic reference and the CPU-mesh test path;
Pallas TPU kernels (when present under localai_tpu/ops/pallas/) are selected
by the engine on TPU and validated against these in tests.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _group_query_heads(q, num_kv_heads):
    b, s, h, d = q.shape
    return q.reshape(b, s, num_kv_heads, h // num_kv_heads, d)


def _softcap(logits, cap):
    if cap is None or cap <= 0:
        return logits
    return jnp.tanh(logits / cap) * cap


def mha_prefill(q, k, v, lengths, *, scale=None, softcap=None, sliding_window=None):
    """Causal self-attention over padded sequences.

    lengths: [B] int32 — valid token count per sequence; padded tail is masked.
    sliding_window: optional int — Mistral-style local attention window.
    Returns [B, S, H, D].
    """
    b, s, h, d = q.shape
    kvh = k.shape[2]
    scale = scale if scale is not None else d ** -0.5

    qg = _group_query_heads(q, kvh)  # [B,S,KVH,G,D]
    logits = jnp.einsum("bskgd,btkd->bkgst", qg, k).astype(jnp.float32) * scale
    logits = _softcap(logits, softcap)

    pos = jnp.arange(s)
    causal = pos[:, None] >= pos[None, :]                      # [S,T]
    valid = pos[None, :] < lengths[:, None]                    # [B,T]
    mask = causal[None, :, :] & valid[:, None, :]              # [B,S,T]
    if sliding_window is not None and sliding_window > 0:
        mask = mask & (pos[:, None] - pos[None, :] < sliding_window)[None]
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, d)


def mha_extend(q, k_cache, v_cache, q_positions, *, scale=None,
               sliding_window=None):
    """Window attention against the cache: scores S new tokens whose K/V are
    already written at `q_positions` (speculative-verification forward).

    q: [B, S, H, D]; caches: [B, KVH, T, D]; q_positions: [B, S] global
    positions of the window tokens. Each query attends to every cache entry
    at position <= its own. Returns [B, S, H, D].
    """
    b, s, h, d = q.shape
    kvh = k_cache.shape[1]
    t = k_cache.shape[2]
    scale = scale if scale is not None else d ** -0.5

    qg = _group_query_heads(q, kvh)                             # [B,S,KVH,G,D]
    logits = jnp.einsum("bskgd,bktd->bkgst", qg, k_cache).astype(jnp.float32) * scale

    pos = jnp.arange(t)
    mask = pos[None, None, :] <= q_positions[:, :, None]        # [B,S,T]
    if sliding_window is not None and sliding_window > 0:
        mask = mask & (pos[None, None, :]
                       > q_positions[:, :, None] - sliding_window)
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgst,bktd->bskgd", probs, v_cache)
    return out.reshape(b, s, h, d)


def mha_prefill_tiered(q, k, v, lengths, sinks, window, *, scale=None,
                       softcap=None):
    """mha_prefill with a PER-SLOT attention-sink + sliding-window mask
    (KV lifecycle tier, engine/kvtier.py): query at position p attends key
    at position t iff t <= p and (t > p - window[b] or t < sinks[b]).
    Full-policy slots ship sentinel window/sinks >= S and reduce to the
    plain causal mask. sinks/window: [B] int32."""
    b, s, h, d = q.shape
    kvh = k.shape[2]
    scale = scale if scale is not None else d ** -0.5

    qg = _group_query_heads(q, kvh)
    logits = jnp.einsum("bskgd,btkd->bkgst", qg, k).astype(jnp.float32) * scale
    logits = _softcap(logits, softcap)

    pos = jnp.arange(s)
    causal = pos[:, None] >= pos[None, :]                      # [S,T]
    valid = pos[None, :] < lengths[:, None]                    # [B,T]
    mask = causal[None, :, :] & valid[:, None, :]              # [B,S,T]
    keep = (pos[None, None, :] > pos[None, :, None]
            - window[:, None, None]) \
        | (pos[None, None, :] < sinks[:, None, None])
    logits = jnp.where((mask & keep)[:, None, None, :, :], logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, d)


def mha_extend_tiered(q, k_cache, v_cache, q_positions, kv_positions, kv_ok,
                      sinks, window, *, scale=None, drop_window=True):
    """mha_extend against a RESIDENT (ring-mapped) cache view whose rows
    carry explicit true positions (kv_positions [B, T]) and validity
    (kv_ok [B, T] — residency + freshness, ops/paged.resident_row_positions
    plus any cold-tier extension the caller concatenated).

    drop_window=True applies the sink_window retention mask per query
    (dropped-block semantics); False keeps every valid row <= the query —
    the quantize_cold case, where exited-window content is still readable
    (at int8) rather than evicted. sinks/window: [B] int32."""
    b, s, h, d = q.shape
    kvh = k_cache.shape[1]
    scale = scale if scale is not None else d ** -0.5

    qg = _group_query_heads(q, kvh)                             # [B,S,KVH,G,D]
    logits = jnp.einsum("bskgd,bktd->bkgst", qg,
                        k_cache).astype(jnp.float32) * scale

    mask = kv_ok[:, None, :] & (kv_positions[:, None, :]
                                <= q_positions[:, :, None])     # [B,S,T]
    if drop_window:
        mask = mask & (
            (kv_positions[:, None, :] > q_positions[:, :, None]
             - window[:, None, None])
            | (kv_positions[:, None, :] < sinks[:, None, None]))
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgst,bktd->bskgd", probs, v_cache)
    return out.reshape(b, s, h, d)


def mha_decode_masked(q, k_cache, v_cache, kv_mask, *, scale=None,
                      softcap=None):
    """Single-token decode attention with a caller-built per-row mask
    [B, T] instead of the implicit arange(T) < lengths — the KV-lifecycle
    read path, where the cache view is ring-mapped (+ optionally
    concatenated with the cold tier) and row validity is a function of
    residency, true position, window membership, and demotion state."""
    b, _, h, d = q.shape
    kvh = k_cache.shape[1]
    scale = scale if scale is not None else d ** -0.5

    qg = _group_query_heads(q, kvh)[:, 0]                       # [B,KVH,G,D]
    logits = jnp.einsum("bkgd,bktd->bkgt", qg,
                        k_cache).astype(jnp.float32) * scale
    logits = _softcap(logits, softcap)
    logits = jnp.where(kv_mask[:, None, None, :], logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgt,bktd->bkgd", probs, v_cache)
    return out.reshape(b, 1, h, d)


def mha_decode(q, k_cache, v_cache, lengths, *, scale=None, softcap=None,
               sliding_window=None):
    """Single-token decode attention against a slot-contiguous KV cache.

    q: [B, 1, H, D]; k_cache/v_cache: [B, KVH, T, D]; lengths: [B] — number of
    valid cache entries per slot INCLUDING the token being decoded.
    Returns [B, 1, H, D].
    """
    b, _, h, d = q.shape
    kvh = k_cache.shape[1]
    t = k_cache.shape[2]
    scale = scale if scale is not None else d ** -0.5

    qg = _group_query_heads(q, kvh)[:, 0]                       # [B,KVH,G,D]
    logits = jnp.einsum("bkgd,bktd->bkgt", qg, k_cache).astype(jnp.float32) * scale
    logits = _softcap(logits, softcap)

    pos = jnp.arange(t)
    mask = pos[None, :] < lengths[:, None]                      # [B,T]
    if sliding_window is not None and sliding_window > 0:
        mask = mask & (pos[None, :] >= lengths[:, None] - sliding_window)
    logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgt,bktd->bkgd", probs, v_cache)
    return out.reshape(b, 1, h, d)
