"""Normalization ops.

Computed in float32 regardless of input dtype (bf16 accumulation loses too
much precision for variance), cast back to the input dtype so the surrounding
matmuls stay on the MXU in bf16.
"""
import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6, *, offset: float = 0.0):
    """RMSNorm. `offset=1.0` gives the Gemma convention (weight stored as w-1)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * (1.0 / jnp.sqrt(var + eps))
    w = weight.astype(jnp.float32) + offset
    return (y * w).astype(dtype)


def layer_norm(x, weight, bias, eps: float = 1e-5):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) / jnp.sqrt(var + eps)
    y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)
