"""Ragged paged attention — one kernel for mixed prefill + decode tokens.

The serving phase split (`prefill` buckets / `extend` chunks / `decode_step`)
makes admission wait on dispatch boundaries and pads every prompt to a bucket.
This kernel serves a FLAT token stream instead (arXiv:2604.15464): the engine
packs this tick's tokens — one row per live decode slot, plus as many
chunked-prefill rows as the token budget fits — into `q [T, H, D]`, and every
row attends to its own sequence's paged KV through the block table. No bucket
padding, no per-phase dispatch: a 10-token admission rides the same program
as its 8k-token neighbor's chunk and the whole batch's decode step.

Packing contract (the scheduler's side of the deal):
- rows are grouped by sequence, and every sequence's rows start at a
  `QBLK`-aligned row (its tail rows up to the next boundary are padding) —
  so each fixed QBLK-row q block belongs to exactly ONE sequence and the
  grid can gather that block's K/V through one table row;
- `block_seq [T/QBLK]` maps each q block to its sequence (−1 = dead block);
- `qstart/qlen [S]` give each sequence's first row and row count;
- `kvlen [S]` is the attended KV length INCLUDING this tick's new tokens
  (write-then-attend, the `decode_step` convention: the row at position p
  attends to positions 0..p);
- `tables [S, MAXB]` are the per-sequence block-table rows.

A decode sequence is simply qlen=1 (7 padding rows); a prefill chunk spans
`ceil(chunk/QBLK)` blocks. Padding rows produce finite garbage (their whole
score row is masked; the 1e-30 floor keeps the division defined) and callers
ignore them.

Traffic stays O(valid tokens) through the same table-clamp trick as
`ragged_decode` (flash_attention.py): beyond-length kv blocks repeat the last
valid physical index and Mosaic skips the duplicate DMA. Blocks of the SAME
sequence share each fetched kv block across QBLK rows — the reason rows pack
to QBLK granularity instead of fully dense.

Tiers match the rest of ops/pallas:
- `ragged_paged_attention`: bf16/f32 pools [NB, KVH, BS, D];
- `ragged_paged_attention_q8`: int8 pools + [NB, KVH, 1, BS] scales;
- `ragged_attention_xla` / `ragged_attention_xla_q8`: pure-XLA twins — the
  CPU-tier forward path AND the parity reference for the kernels (they
  gather only the table-mapped blocks, never the whole pool);
- `*_sharded`: shard_map wrappers over the pool's KV-head axis
  (models/llama.paged_pool_spec), same scheme as paged_scatter.py;
- `ragged_scatter_append[_q8]`: flat-stream KV writes — the paged_scatter
  row-DMA kernel driven by host-precomputed (physical block, row) targets,
  one DMA per token, O(tokens) traffic.

Loop-carried metadata (ISSUE 16): every metadata input — block_seq,
qstart/qlen/kvlen, tables — is an ordinary traced array, never a static
argument, so the fused multi-step ragged tick (models/llama.build_ragged_loop)
can carry re-derived metadata through `lax.while_loop` iterations WITHOUT
re-tracing this kernel: one trace serves iteration 0's mixed pack and every
shape-identical dispatch after it. The only static inputs are the shapes
themselves (T, pool dims, MAXB) and `sliding_window`; keep it that way —
promoting any metadata value to Python int would re-specialize the program
per tick and break the zero-recompile invariant the compile-count tripwire
enforces.

On CPU everything runs in interpreter mode (LOCALAI_FORCE_PALLAS=1 in
tests); real-TPU lowering rides the same `pallas_works` probe gate.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from localai_tpu.ops.pallas.flash_attention import (
    NEG_INF,
    CompilerParams as _CompilerParams,
    _interpret,
)
from localai_tpu.ops.pallas.paged_scatter import (
    _append_kernel,
    _append_q8_kernel,
)

try:                                  # jax >= 0.5 top-level export
    from jax import shard_map as _shard_map
except ImportError:                   # 0.4.x spelling
    from jax.experimental.shard_map import shard_map as _shard_map

QBLK = 8   # q rows per grid block; every sequence's rows start on a boundary


def _q_blocked(q, kvh):
    """[T, H, D] → [NQB, KVH, QBLK*G, D] (kv-head-major rows, token-major
    within a block: row r of a block is token r//G, q-head-in-group r%G)."""
    t, h, d = q.shape
    g = h // kvh
    qb = q.reshape(t // QBLK, QBLK, kvh, g, d)
    return qb.transpose(0, 2, 1, 3, 4).reshape(t // QBLK, kvh, QBLK * g, d)


def _q_unblocked(o, t, h, d, kvh):
    g = h // kvh
    o = o.reshape(t // QBLK, kvh, QBLK, g, d).transpose(0, 2, 1, 3, 4)
    return o.reshape(t, h, d)


def _row_mask(i, group, shape, klen, qs, ql, start, sliding_window):
    """[R, BS] attention mask for q block i: row validity + causality
    (kv_pos <= q_pos, where q_pos = kvlen - qlen + row's offset into the
    sequence) + the optional sliding window."""
    rr = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    kv_pos = start + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    grow = i * QBLK + rr // group
    q_pos = klen - ql + (grow - qs)
    mask = (grow >= qs) & (grow < qs + ql)
    mask &= (kv_pos <= q_pos) & (kv_pos < klen)
    if sliding_window is not None:
        mask &= kv_pos > q_pos - sliding_window
    return mask


def _ragged_kernel(bseq_ref, qs_ref, ql_ref, kl_ref, tab_ref,
                   q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                   bs: int, num_kb: int, group: int, scale: float,
                   sliding_window: int | None):
    i = pl.program_id(0)
    kb = pl.program_id(2)
    s_raw = bseq_ref[i]
    s = jnp.maximum(s_raw, 0)
    klen, qs, ql = kl_ref[s], qs_ref[s], ql_ref[s]

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    start = kb * bs
    live = (s_raw >= 0) & (start < klen)
    if sliding_window is not None:
        # lowest q_pos any row of this block holds — blocks entirely below
        # its window are dead (the per-row mask stays exact)
        live &= (start + bs) > (klen - ql + i * QBLK - qs) - sliding_window

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale            # [R, D]
        k_blk = k_ref[0, 0].astype(jnp.float32)                # [BS, D]
        v_blk = v_ref[0, 0].astype(jnp.float32)
        sc = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        mask = _row_mask(i, group, sc.shape, klen, qs, ql, start,
                         sliding_window)
        # a physical block's rows past klen hold other tenants' (finite)
        # data, never undefined memory — masking to NEG_INF underflows their
        # p to exactly 0, so no v zeroing is needed (cf. _decode_kernel's
        # contiguous-case t_total guard)
        sc = jnp.where(mask, sc, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
        p = jnp.exp(sc - m_new[:, :1])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(kb == num_kb - 1)
    def _finish():
        out = acc_ref[...] / jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0, 0] = out.astype(o_ref.dtype)


def _meta_i32(block_seq, qstart, qlen, kvlen, tables):
    return (block_seq.astype(jnp.int32), qstart.astype(jnp.int32),
            qlen.astype(jnp.int32), kvlen.astype(jnp.int32),
            tables.astype(jnp.int32))


def _kv_map(bs):
    def kv_map(i, h, kb, bseq, qs, ql, kl, tab):
        s = jnp.maximum(bseq[i], 0)
        last = jnp.maximum(pl.cdiv(kl[s], bs) - 1, 0)
        return (tab[s, jnp.minimum(kb, last)], h, 0, 0)
    return kv_map


@functools.partial(jax.jit, static_argnames=("sliding_window",))
def ragged_paged_attention(q, k_pool, v_pool, block_seq, qstart, qlen,
                           kvlen, tables, sliding_window=None):
    """Flat-stream GQA attention over paged KV. q: [T, H, D] with T a
    multiple of QBLK; pools [NB, KVH, BS, D]; metadata per the module
    docstring. Returns [T, H, D] in q.dtype (padding rows garbage)."""
    t, h, d = q.shape
    if t % QBLK != 0:
        raise ValueError(
            f"ragged stream rows T={t} must be a multiple of QBLK={QBLK} "
            "(the engine's token budget is QBLK-aligned by construction)")
    kvh = k_pool.shape[1]
    bs = k_pool.shape[2]
    group = h // kvh
    num_kb = tables.shape[1]
    qg = _q_blocked(q, kvh)
    r = QBLK * group
    kernel = functools.partial(
        _ragged_kernel, bs=bs, num_kb=num_kb, group=group,
        scale=d ** -0.5, sliding_window=sliding_window)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(t // QBLK, kvh, num_kb),
            in_specs=[
                pl.BlockSpec((1, 1, r, d),
                             lambda i, h, kb, *s: (i, h, 0, 0)),
                pl.BlockSpec((1, 1, bs, d), _kv_map(bs)),
                pl.BlockSpec((1, 1, bs, d), _kv_map(bs)),
            ],
            out_specs=pl.BlockSpec((1, 1, r, d),
                                   lambda i, h, kb, *s: (i, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((r, 128), jnp.float32),   # m (lane-replicated)
                pltpu.VMEM((r, 128), jnp.float32),   # l
                pltpu.VMEM((r, d), jnp.float32),     # acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(qg.shape, q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*_meta_i32(block_seq, qstart, qlen, kvlen, tables), qg,
      k_pool, v_pool)
    return _q_unblocked(out, t, h, d, kvh)


def _ragged_q8_kernel(bseq_ref, qs_ref, ql_ref, kl_ref, tab_ref,
                      q_ref, kq_ref, ks_ref, vq_ref, vs_ref,
                      o_ref, m_ref, l_ref, acc_ref, *,
                      bs: int, num_kb: int, group: int, scale: float,
                      sliding_window: int | None):
    i = pl.program_id(0)
    kb = pl.program_id(2)
    s_raw = bseq_ref[i]
    s = jnp.maximum(s_raw, 0)
    klen, qs, ql = kl_ref[s], qs_ref[s], ql_ref[s]

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    start = kb * bs
    live = (s_raw >= 0) & (start < klen)
    if sliding_window is not None:
        live &= (start + bs) > (klen - ql + i * QBLK - qs) - sliding_window

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale            # [R, D]
        k_blk = kq_ref[0, 0].astype(jnp.float32)               # [BS, D]
        v_blk = vq_ref[0, 0].astype(jnp.float32)
        k_s = ks_ref[0, 0]                                     # [1, BS]
        v_s = vs_ref[0, 0]
        sc = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        sc = sc * k_s                                          # dequant K
        mask = _row_mask(i, group, sc.shape, klen, qs, ql, start,
                         sliding_window)
        sc = jnp.where(mask, sc, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
        p = jnp.exp(sc - m_new[:, :1])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + jnp.dot(
            p * v_s, v_blk, preferred_element_type=jnp.float32)  # dequant V
        m_ref[...] = m_new

    @pl.when(kb == num_kb - 1)
    def _finish():
        out = acc_ref[...] / jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sliding_window",))
def ragged_paged_attention_q8(q, k_q, k_s, v_q, v_s, block_seq, qstart,
                              qlen, kvlen, tables, sliding_window=None):
    """int8 twin: pools k_q/v_q [NB, KVH, BS, D] int8 with per-token scales
    k_s/v_s [NB, KVH, 1, BS] f32 (ops/paged.py layout, BS == 128)."""
    t, h, d = q.shape
    kvh = k_q.shape[1]
    bs = k_q.shape[2]
    if bs != 128:
        raise ValueError("paged int8 KV blocks must be 128 tokens")
    group = h // kvh
    num_kb = tables.shape[1]
    qg = _q_blocked(q, kvh)
    r = QBLK * group
    kernel = functools.partial(
        _ragged_q8_kernel, bs=bs, num_kb=num_kb, group=group,
        scale=d ** -0.5, sliding_window=sliding_window)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(t // QBLK, kvh, num_kb),
            in_specs=[
                pl.BlockSpec((1, 1, r, d),
                             lambda i, h, kb, *s: (i, h, 0, 0)),
                pl.BlockSpec((1, 1, bs, d), _kv_map(bs)),
                pl.BlockSpec((1, 1, 1, 128), _kv_map(bs)),
                pl.BlockSpec((1, 1, bs, d), _kv_map(bs)),
                pl.BlockSpec((1, 1, 1, 128), _kv_map(bs)),
            ],
            out_specs=pl.BlockSpec((1, 1, r, d),
                                   lambda i, h, kb, *s: (i, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((r, 128), jnp.float32),
                pltpu.VMEM((r, 128), jnp.float32),
                pltpu.VMEM((r, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(qg.shape, q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*_meta_i32(block_seq, qstart, qlen, kvlen, tables), qg,
      k_q, k_s.astype(jnp.float32), v_q, v_s.astype(jnp.float32))
    return _q_unblocked(out, t, h, d, kvh)


# ------------------------------------------------------------ XLA twins
# The pure-XLA formulation: gather each q block's table-mapped kv blocks
# (never the whole pool — [NQB, MAXB] indices, O(stream · context) output)
# and run one masked attention einsum. This is BOTH the non-Pallas serving
# tier (CPU, data-only meshes) and the parity reference the kernel tests
# compare against.

def _xla_core(q, kg, vg, block_seq, qstart, qlen, kvlen, sliding_window,
              scale, tier=None):
    """q: [T, H, D]; kg/vg: [NQB, KVH, C, D] f32 per-q-block gathered KV.

    tier (KV lifecycle, engine/kvtier.py): (pos [NQB, C], ok [NQB, C],
    sinks [NQB], window [NQB]) — the gathered view is ring-mapped, so kv row
    positions come from ops/paged.resident_row_positions instead of
    arange(C), and the retention mask (sink ∪ window) replaces the plain
    length mask. ok already folds residency + pos < kvlen."""
    t, h, d = q.shape
    nqb, kvh, c, _ = kg.shape
    g = h // kvh
    qb = q.reshape(nqb, QBLK, kvh, g, d).astype(jnp.float32) * scale
    sc = jnp.einsum("nqhgd,nhcd->nhqgc", qb, kg)
    s_b = jnp.maximum(block_seq, 0)
    klen = kvlen[s_b][:, None]                                 # [NQB, 1]
    qs, ql = qstart[s_b][:, None], qlen[s_b][:, None]
    grow = jnp.arange(t, dtype=jnp.int32).reshape(nqb, QBLK)
    q_pos = klen - ql + (grow - qs)                            # [NQB, QBLK]
    valid = (grow >= qs) & (grow < qs + ql) & (block_seq[:, None] >= 0)
    if tier is None:
        kv_pos = jnp.arange(c, dtype=jnp.int32)[None, None, :]
        mask = (valid[:, :, None] & (kv_pos <= q_pos[:, :, None])
                & (kv_pos < klen[:, :, None]))
        if sliding_window is not None:
            mask &= kv_pos > (q_pos[:, :, None] - sliding_window)
    else:
        pos, ok, sinks, window = tier
        kv_pos = pos[:, None, :]                               # [NQB, 1, C]
        mask = (valid[:, :, None] & ok[:, None, :]
                & (kv_pos <= q_pos[:, :, None]))
        mask &= ((kv_pos > q_pos[:, :, None] - window[:, None, None])
                 | (kv_pos < sinks[:, None, None]))
    sc = jnp.where(mask[:, None, :, None, :], sc, NEG_INF)
    p = jax.nn.softmax(sc, axis=-1)
    out = jnp.einsum("nhqgc,nhcd->nqhgd", p, vg)
    return out.reshape(t, h, d).astype(q.dtype)


def _tier_blocks(block_seq, kvlen, tables, kvt):
    """Per-q-block tier metadata for _xla_core: true row positions +
    residency of the ring-mapped gathered view. kvt holds per-SEQUENCE
    [NSEQ] geometry arrays (engine ships them like tables)."""
    if kvt is None:
        return None
    from localai_tpu.ops.paged import resident_row_positions

    s_b = jnp.maximum(block_seq, 0).astype(jnp.int32)
    pos, ok = resident_row_positions(
        tables.shape[1], kvt["sb"].astype(jnp.int32)[s_b],
        kvt["rw"].astype(jnp.int32)[s_b], kvlen.astype(jnp.int32)[s_b])
    return (pos, ok, kvt["sinks"].astype(jnp.int32)[s_b],
            kvt["window"].astype(jnp.int32)[s_b])


def _gather_blocks(pool, block_seq, tables):
    """[NQB, KVH, MAXB*BS, D] per-q-block KV view through the table."""
    tab = tables[jnp.maximum(block_seq, 0)]                    # [NQB, MAXB]
    g = pool[tab]                                              # [NQB, MAXB, KVH, BS, D]
    nqb, maxb, kvh, bs, d = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(nqb, kvh, maxb * bs, d)


def ragged_attention_xla(q, k_pool, v_pool, block_seq, qstart, qlen, kvlen,
                         tables, sliding_window=None, kvt=None):
    kg = _gather_blocks(k_pool, block_seq, tables).astype(jnp.float32)
    vg = _gather_blocks(v_pool, block_seq, tables).astype(jnp.float32)
    return _xla_core(q, kg, vg, block_seq.astype(jnp.int32),
                     qstart.astype(jnp.int32), qlen.astype(jnp.int32),
                     kvlen.astype(jnp.int32), sliding_window,
                     q.shape[-1] ** -0.5,
                     tier=_tier_blocks(block_seq, kvlen, tables, kvt))


def _gather_scales(s_pool, block_seq, tables):
    """[NQB, KVH, MAXB*BS] dequant scales through the table
    (pool layout [NB, KVH, 1, BS])."""
    tab = tables[jnp.maximum(block_seq, 0)]
    g = s_pool[tab][:, :, :, 0, :]                             # [NQB, MAXB, KVH, BS]
    nqb, maxb, kvh, bs = g.shape
    return g.transpose(0, 2, 1, 3).reshape(nqb, kvh, maxb * bs)


def ragged_attention_xla_q8(q, k_q, k_s, v_q, v_s, block_seq, qstart, qlen,
                            kvlen, tables, sliding_window=None, kvt=None):
    kg = (_gather_blocks(k_q, block_seq, tables).astype(jnp.float32)
          * _gather_scales(k_s, block_seq, tables)[..., None])
    vg = (_gather_blocks(v_q, block_seq, tables).astype(jnp.float32)
          * _gather_scales(v_s, block_seq, tables)[..., None])
    return _xla_core(q, kg, vg, block_seq.astype(jnp.int32),
                     qstart.astype(jnp.int32), qlen.astype(jnp.int32),
                     kvlen.astype(jnp.int32), sliding_window,
                     q.shape[-1] ** -0.5,
                     tier=_tier_blocks(block_seq, kvlen, tables, kvt))


# -------------------------------------------------------- shard_map (TP)

def _head_axis(mesh):
    return "model" if "model" in mesh.axis_names else None


def ragged_paged_attention_sharded(mesh, q, k_pool, v_pool, block_seq,
                                   qstart, qlen, kvlen, tables,
                                   sliding_window=None):
    """TP wrapper: per-shard ragged kernel over the pool's KV-head axis
    (paged_pool_spec). q's head axis is kv-head-major, so an even KV-head
    split keeps whole GQA groups on one shard (the cfg.num_kv_heads % tp
    gate in models/llama). Metadata replicates; check_rep=False because the
    kernel body is opaque to the replication checker."""
    from jax.sharding import PartitionSpec as P

    ax = _head_axis(mesh)
    pool, qs_, rep = P(None, ax, None, None), P(None, ax, None), P()
    return _shard_map(
        lambda qq, kp, vp, bs_, q0, q1, kl, tb: ragged_paged_attention(
            qq, kp, vp, bs_, q0, q1, kl, tb,
            sliding_window=sliding_window),
        mesh=mesh,
        in_specs=(qs_, pool, pool, rep, rep, rep, rep, rep),
        out_specs=qs_, check_rep=False,
    )(q, k_pool, v_pool, block_seq, qstart, qlen, kvlen, tables)


def ragged_paged_attention_q8_sharded(mesh, q, k_q, k_s, v_q, v_s,
                                      block_seq, qstart, qlen, kvlen,
                                      tables, sliding_window=None):
    from jax.sharding import PartitionSpec as P

    ax = _head_axis(mesh)
    pool, qs_, rep = P(None, ax, None, None), P(None, ax, None), P()
    return _shard_map(
        lambda qq, a, b, c, d, bs_, q0, q1, kl, tb:
        ragged_paged_attention_q8(
            qq, a, b, c, d, bs_, q0, q1, kl, tb,
            sliding_window=sliding_window),
        mesh=mesh,
        in_specs=(qs_, pool, pool, pool, pool, rep, rep, rep, rep, rep),
        out_specs=qs_, check_rep=False,
    )(q, k_q, k_s, v_q, v_s, block_seq, qstart, qlen, kvlen, tables)


# ------------------------------------------------- flat-stream KV writes
# The scatter-append kernels from paged_scatter.py, driven by
# host-precomputed (physical block, in-block row) targets — the host knows
# every write position at pack time (decode rows write at the slot's
# current length, prefill rows at their absolute prompt position), so no
# table math runs on device. Padding rows target the trash block (physical
# 0) at caller-chosen rows.

def ragged_scatter_append(k_pool, v_pool, k_new, v_new, pb, off):
    """DMA each flat row into its pool slot, in place. k_new/v_new:
    [T, KVH, D]; pb/off: [T] i32. Returns the aliased (k_pool, v_pool)."""
    t, kvh, d = k_new.shape
    kn = k_new.reshape(t, kvh, 1, d).astype(k_pool.dtype)
    vn = v_new.reshape(t, kvh, 1, d).astype(v_pool.dtype)
    return pl.pallas_call(
        _append_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(t,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 4,
            out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 2,
            scratch_shapes=[pltpu.SemaphoreType.DMA((2,))],
        ),
        out_shape=[jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
                   jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype)],
        input_output_aliases={4: 0, 5: 1},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(pb.astype(jnp.int32), off.astype(jnp.int32), kn, vn, k_pool, v_pool)


def ragged_scatter_append_q8(kq, ks, vq, vs, k_new, v_new, pb, off):
    """int8 twin: quantize the flat rows (plain XLA) and DMA int8 bodies +
    scale elements into the [NB, KVH, BS, D] / [NB, KVH, 1, BS] pools."""
    from localai_tpu.ops.kvcache import quantize_tokens

    t, kvh, d = k_new.shape
    kq_n, ks_n = quantize_tokens(k_new)          # [T, KVH, D], [T, KVH]
    vq_n, vs_n = quantize_tokens(v_new)
    kq_n = kq_n.reshape(t, kvh, 1, d)
    vq_n = vq_n.reshape(t, kvh, 1, d)
    ks_n = ks_n.reshape(t, kvh, 1, 1).astype(ks.dtype)
    vs_n = vs_n.reshape(t, kvh, 1, 1).astype(vs.dtype)
    return pl.pallas_call(
        _append_q8_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(t,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 8,
            out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 4,
            scratch_shapes=[pltpu.SemaphoreType.DMA((4,))],
        ),
        out_shape=[jax.ShapeDtypeStruct(kq.shape, kq.dtype),
                   jax.ShapeDtypeStruct(ks.shape, ks.dtype),
                   jax.ShapeDtypeStruct(vq.shape, vq.dtype),
                   jax.ShapeDtypeStruct(vs.shape, vs.dtype)],
        input_output_aliases={6: 0, 7: 1, 8: 2, 9: 3},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(pb.astype(jnp.int32), off.astype(jnp.int32), kq_n, ks_n, vq_n, vs_n,
      kq, ks, vq, vs)


def ragged_scatter_append_sharded(mesh, k_pool, v_pool, k_new, v_new,
                                  pb, off):
    from jax.sharding import PartitionSpec as P

    ax = _head_axis(mesh)
    pool, new, rep = P(None, ax, None, None), P(None, ax, None), P()
    return _shard_map(
        lambda kp, vp, kn, vn, p, o: ragged_scatter_append(
            kp, vp, kn, vn, p, o),
        mesh=mesh, in_specs=(pool, pool, new, new, rep, rep),
        out_specs=(pool, pool), check_rep=False,
    )(k_pool, v_pool, k_new, v_new, pb, off)


def ragged_scatter_append_q8_sharded(mesh, kq, ks, vq, vs, k_new, v_new,
                                     pb, off):
    from jax.sharding import PartitionSpec as P

    ax = _head_axis(mesh)
    pool = P(None, ax, None, None)
    new, rep = P(None, ax, None), P()
    return _shard_map(
        lambda a, b, c, d, kn, vn, p, o: ragged_scatter_append_q8(
            a, b, c, d, kn, vn, p, o),
        mesh=mesh, in_specs=(pool,) * 4 + (new, new, rep, rep),
        out_specs=(pool,) * 4, check_rep=False,
    )(kq, ks, vq, vs, k_new, v_new, pb, off)


def ragged_scatter_xla(k_pool, v_pool, k_new, v_new, pb, off):
    """XLA-tier flat-row scatter (the non-Pallas twin of
    ragged_scatter_append). Duplicate targets exist only among padding rows
    aimed at the trash block, whose content is dead — last-write-wins is
    fine there, so the scatter stays on the default (non-unique) path."""
    kvh = k_new.shape[1]
    hh = jnp.arange(kvh, dtype=jnp.int32)[None, :]
    k_pool = k_pool.at[pb[:, None], hh, off[:, None]].set(
        k_new.astype(k_pool.dtype))
    v_pool = v_pool.at[pb[:, None], hh, off[:, None]].set(
        v_new.astype(v_pool.dtype))
    return k_pool, v_pool


def ragged_scatter_xla_q8(kq, ks, vq, vs, k_new, v_new, pb, off):
    from localai_tpu.ops.kvcache import quantize_tokens

    kvh = k_new.shape[1]
    hh = jnp.arange(kvh, dtype=jnp.int32)[None, :]
    kq_n, ks_n = quantize_tokens(k_new)
    vq_n, vs_n = quantize_tokens(v_new)
    kq = kq.at[pb[:, None], hh, off[:, None]].set(kq_n.astype(kq.dtype))
    vq = vq.at[pb[:, None], hh, off[:, None]].set(vq_n.astype(vq.dtype))
    ks = ks.at[pb[:, None], hh, 0, off[:, None]].set(ks_n.astype(ks.dtype))
    vs = vs.at[pb[:, None], hh, 0, off[:, None]].set(vs_n.astype(vs.dtype))
    return kq, ks, vq, vs
