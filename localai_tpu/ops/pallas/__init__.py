from localai_tpu.ops.pallas.flash_attention import (  # noqa: F401
    flash_prefill,
    ragged_decode,
    ragged_decode_q8,
    pallas_available,
    pallas_works,
)
from localai_tpu.ops.pallas.paged_scatter import (  # noqa: F401
    paged_scatter_append,
    paged_scatter_append_q8,
    paged_scatter_append_q8_sharded,
    paged_scatter_append_sharded,
)
from localai_tpu.ops.pallas.ragged_attention import (  # noqa: F401
    QBLK,
    ragged_attention_xla,
    ragged_attention_xla_q8,
    ragged_paged_attention,
    ragged_paged_attention_q8,
    ragged_paged_attention_q8_sharded,
    ragged_paged_attention_sharded,
    ragged_scatter_append,
    ragged_scatter_append_q8,
    ragged_scatter_append_q8_sharded,
    ragged_scatter_append_sharded,
    ragged_scatter_xla,
    ragged_scatter_xla_q8,
)
