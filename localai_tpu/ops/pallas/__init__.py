from localai_tpu.ops.pallas.flash_attention import (  # noqa: F401
    flash_prefill,
    ragged_decode,
    ragged_decode_q8,
    pallas_available,
    pallas_works,
)
