from localai_tpu.ops.pallas.flash_attention import (  # noqa: F401
    flash_prefill,
    ragged_decode,
    ragged_decode_q8,
    pallas_available,
    pallas_works,
)
from localai_tpu.ops.pallas.paged_scatter import (  # noqa: F401
    paged_scatter_append,
    paged_scatter_append_q8,
    paged_scatter_append_q8_sharded,
    paged_scatter_append_sharded,
)
