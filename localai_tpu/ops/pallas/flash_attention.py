"""Pallas TPU attention kernels — the native compute tier.

Reference parity note: llama.cpp's flash-attention toggle
(/root/reference/backend/backend.proto:247) enables fused CUDA attention; here
the fused kernels are Mosaic/Pallas, written block-wise for the MXU with
online softmax so the [S, S] score matrix never hits HBM (memory O(block²)
instead of O(S²)).

Two kernels:
- flash_prefill: causal GQA attention over padded prompt batches
  [B, S, H, D]; per-row validity from `lengths`; optional sliding window.
- ragged_decode: one-token-per-slot decode attention against the slot KV
  cache [B, KVH, T, D]; the KV-block axis lives in the GRID with a
  scalar-prefetched index map that clamps out-of-range blocks to the last
  valid one — Mosaic skips the DMA when consecutive grid steps map to the
  same block, so each slot streams only ceil(length/BLOCK) KV blocks from
  HBM. That is the "ragged" part: long-context decode is O(valid tokens) in
  both compute AND memory traffic, not O(max context).

Mosaic tiling rule (the round-3 lesson): the LAST TWO dims of every block
shape must be (divisible by 8, divisible by 128) or equal to the array dims.
Heads therefore live in the grid, never in a trailing block dim; every block
is [..., seq_block, head_dim] over head-major [B, H, S, D] layouts.

On CPU (tests) both run in interpreter mode; the math is identical. Real-TPU
lowering is validated by tests/test_tpu_real.py (TPU-gated) and by the
pallas_works() probe the model uses before selecting this path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# large-but-finite so exp(NEG_INF - NEG_INF) stays 0/1 instead of NaN when a
# row's first blocks are fully masked (sliding window, ragged tails)
NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)

# jax < 0.5 spells it TPUCompilerParams; 0.5+ renamed it CompilerParams
CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


def pallas_available() -> bool:
    return jax.default_backend() == "tpu"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------- prefill

def _prefill_kernel(lengths_ref, q_ref, k_ref, v_ref, o_ref, *,
                    block_q: int, block_k: int, scale: float,
                    sliding_window: int | None):
    b = pl.program_id(0)
    qb = pl.program_id(2)
    length = lengths_ref[b]
    q = q_ref[0, 0].astype(jnp.float32) * scale                # [BQ, D]
    S = k_ref.shape[2]

    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # [BQ, BK]
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = (k_pos <= q_pos) & (k_pos < length)
        if sliding_window is not None:
            mask &= k_pos > q_pos - sliding_window
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))   # [BQ,1]
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    num_kb = pl.cdiv(S, block_k)
    # causal: only KV blocks up to (and including) this query block
    last_kb = jnp.minimum(
        (qb + 1) * block_q + block_k - 1, S + block_k - 1) // block_k
    last_kb = jnp.minimum(last_kb, num_kb)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)
    o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sliding_window", "block_q",
                                             "block_k"))
def flash_prefill(q, k, v, lengths, sliding_window=None,
                  block_q: int = 128, block_k: int = 128):
    """Causal GQA flash attention. q: [B, S, H, D]; k/v: [B, S, KVH, D];
    lengths: [B]. Returns [B, S, H, D] in q.dtype."""
    B, S, H, D = q.shape
    KVH = k.shape[2]
    group = H // KVH
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    scale = D ** -0.5

    # pad K/V so block_k divides the KV length: pl.ds CLAMPS an out-of-range
    # start (it does not pad), which would silently misattribute key positions
    # in the final partial block. Zero padding is masked out by k_pos<length.
    Sk = pl.cdiv(S, block_k) * block_k
    if Sk != S:
        pad = [(0, 0), (0, Sk - S), (0, 0), (0, 0)]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    # head-major layouts so trailing block dims are (seq, head_dim)
    qt = q.transpose(0, 2, 1, 3)                               # [B, H, S, D]
    kt = k.transpose(0, 2, 1, 3)                               # [B, KVH, Sk, D]
    vt = v.transpose(0, 2, 1, 3)

    grid = (B, H, pl.cdiv(S, block_q))
    kernel = functools.partial(
        _prefill_kernel, block_q=block_q, block_k=block_k, scale=scale,
        sliding_window=sliding_window)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, block_q, D),
                             lambda b, h, qb, lens: (b, h, qb, 0)),
                pl.BlockSpec((1, 1, Sk, D),
                             lambda b, h, qb, lens: (b, h // group, 0, 0)),
                pl.BlockSpec((1, 1, Sk, D),
                             lambda b, h, qb, lens: (b, h // group, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, D),
                                   lambda b, h, qb, lens: (b, h, qb, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(lengths.astype(jnp.int32), qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


# --------------------------------------------------------------- decode

def _decode_kernel(lengths_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *,
                   block_k: int, num_kb: int, t_total: int, scale: float,
                   sliding_window: int | None):
    b = pl.program_id(0)
    kb = pl.program_id(2)
    length = lengths_ref[b]

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    start = kb * block_k
    live = start < length
    if sliding_window is not None:
        live &= (start + block_k) > (length - sliding_window)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale            # [G, D]
        k_blk = k_ref[0, 0].astype(jnp.float32)                # [BK, D]
        v_blk = v_ref[0, 0].astype(jnp.float32)
        if t_total % block_k:
            # final partial block: rows past the array end hold UNDEFINED
            # values (NaN in interpret mode) — zero them so 0·undef can't
            # poison the accumulator through the p@v matmul
            row_pos = start + jax.lax.broadcasted_iota(
                jnp.int32, (k_blk.shape[0], 1), 0)
            v_blk = jnp.where(row_pos < t_total, v_blk, 0.0)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # [G, BK]
        k_pos = start + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        mask = k_pos < jnp.minimum(length, t_total)
        if sliding_window is not None:
            mask &= k_pos >= length - sliding_window
        s = jnp.where(mask, s, NEG_INF)
        # m/l live lane-replicated in [G, 128] scratch
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new[:, :1])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(kb == num_kb - 1)
    def _finish():
        out = acc_ref[...] / jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0, 0] = out.astype(o_ref.dtype)


def _decode_kernel_paged(lengths_ref, table_ref, *refs, **kw):
    # table is consumed by the index maps only; the body math is identical
    _decode_kernel(lengths_ref, *refs, **kw)


@functools.partial(jax.jit, static_argnames=("sliding_window", "block_k"))
def ragged_decode(q, k_cache, v_cache, lengths, sliding_window=None,
                  block_k: int = 256, table=None):
    """Decode-step GQA attention. q: [B, 1, H, D]; caches [B, KVH, T, D];
    lengths: [B] valid entries incl. the newly-written token.
    Returns [B, 1, H, D].

    Paged mode (`table` [B, MAXB] i32, ops/paged.py): caches are a block
    pool [NB, KVH, BS, D]; virtual KV block kb of slot b streams from
    physical block table[b, kb]. Same O(valid tokens) traffic — the clamp
    repeats the physical index past the valid length and Mosaic skips the
    duplicate DMA."""
    B, _, H, D = q.shape
    KVH = k_cache.shape[1]   # axis 1 in both layouts ([B,KVH,T,D] / pool)
    group = H // KVH
    scale = D ** -0.5
    qg = q.reshape(B, KVH, group, D)

    if table is not None:
        BS = k_cache.shape[2]            # pool [NB, KVH, BS, D]
        num_kb = table.shape[1]
        T = num_kb * BS

        def kv_map(b, h, kb, lens, tab):
            last = jnp.maximum(pl.cdiv(lens[b], BS) - 1, 0)
            return (tab[b, jnp.minimum(kb, last)], h, 0, 0)

        kernel = functools.partial(_decode_kernel_paged, block_k=BS,
                                   num_kb=num_kb, t_total=T, scale=scale,
                                   sliding_window=sliding_window)
        out = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B, KVH, num_kb),
                in_specs=[
                    pl.BlockSpec((1, 1, group, D),
                                 lambda b, h, kb, lens, tab: (b, h, 0, 0)),
                    pl.BlockSpec((1, 1, BS, D), kv_map),
                    pl.BlockSpec((1, 1, BS, D), kv_map),
                ],
                out_specs=pl.BlockSpec((1, 1, group, D),
                                       lambda b, h, kb, lens, tab:
                                       (b, h, 0, 0)),
                scratch_shapes=[
                    pltpu.VMEM((group, 128), jnp.float32),
                    pltpu.VMEM((group, 128), jnp.float32),
                    pltpu.VMEM((group, D), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct(qg.shape, q.dtype),
            compiler_params=CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=_interpret(),
        )(lengths.astype(jnp.int32), table.astype(jnp.int32), qg,
          k_cache, v_cache)
        return out.reshape(B, 1, H, D)

    T = k_cache.shape[2]
    block_k = min(block_k, T)
    num_kb = pl.cdiv(T, block_k)

    def kv_map(b, h, kb, lens):
        # clamp beyond-length blocks to the last valid one: Mosaic skips the
        # DMA when the block index repeats, making traffic O(length)
        last = jnp.maximum(pl.cdiv(lens[b], block_k) - 1, 0)
        return (b, h, jnp.minimum(kb, last), 0)

    kernel = functools.partial(_decode_kernel, block_k=block_k,
                               num_kb=num_kb, t_total=T, scale=scale,
                               sliding_window=sliding_window)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, KVH, num_kb),
            in_specs=[
                pl.BlockSpec((1, 1, group, D),
                             lambda b, h, kb, lens: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, block_k, D), kv_map),
                pl.BlockSpec((1, 1, block_k, D), kv_map),
            ],
            out_specs=pl.BlockSpec((1, 1, group, D),
                                   lambda b, h, kb, lens: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((group, 128), jnp.float32),   # m (lane-replicated)
                pltpu.VMEM((group, 128), jnp.float32),   # l
                pltpu.VMEM((group, D), jnp.float32),     # acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(qg.shape, q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(lengths.astype(jnp.int32), qg, k_cache, v_cache)
    return out.reshape(B, 1, H, D)


# ----------------------------------------------------- int8 KV decode

def _decode_q8_kernel(lengths_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref,
                      o_ref, m_ref, l_ref, acc_ref, *,
                      num_kb: int, t_total: int, scale: float,
                      sliding_window: int | None, paged: bool = False):
    """ragged_decode against an int8 cache: K/V stream from HBM as int8 (half
    the decode bandwidth — the resource decode is bound by); scales are one
    aligned [1, 128] row per 128-token block, applied to score columns (K) and
    to p's columns before the p@v matmul (V) so the matmuls stay dense.
    paged=True: the scale ref is the single [1, 128] row of this physical
    block (table-mapped) instead of the slot's whole scale strip."""
    b = pl.program_id(0)
    kb = pl.program_id(2)
    length = lengths_ref[b]
    block_k = 128

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    start = kb * block_k
    live = start < length
    if sliding_window is not None:
        live &= (start + block_k) > (length - sliding_window)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale            # [G, D]
        k_blk = kq_ref[0, 0].astype(jnp.float32)               # [BK, D]
        v_blk = vq_ref[0, 0].astype(jnp.float32)
        if paged:
            k_s = ks_ref[0, 0]                                 # [1, BK]
            v_s = vs_ref[0, 0]
        else:
            k_s = ks_ref[0, 0, pl.ds(kb, 1), :]                # [1, BK]
            v_s = vs_ref[0, 0, pl.ds(kb, 1), :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        s = s * k_s                                            # dequant K
        k_pos = start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = k_pos < jnp.minimum(length, t_total)
        if sliding_window is not None:
            mask &= k_pos >= length - sliding_window
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new[:, :1])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + jnp.dot(
            p * v_s, v_blk, preferred_element_type=jnp.float32)  # dequant V
        m_ref[...] = m_new

    @pl.when(kb == num_kb - 1)
    def _finish():
        out = acc_ref[...] / jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0, 0] = out.astype(o_ref.dtype)


def _decode_q8_kernel_paged(lengths_ref, table_ref, *refs, **kw):
    _decode_q8_kernel(lengths_ref, *refs, paged=True, **kw)


@functools.partial(jax.jit, static_argnames=("sliding_window",))
def ragged_decode_q8(q, k_q, k_s, v_q, v_s, lengths, sliding_window=None,
                     table=None):
    """Decode-step GQA attention over an int8 KV cache (ops/kvcache.py
    layout). q: [B, 1, H, D]; k_q/v_q: [B, KVH, T, D] int8;
    k_s/v_s: [B, KVH, T//128, 128] f32 (token t's scale at [t//128, t%128]);
    lengths: [B]. T must be a multiple of 128. Returns [B, 1, H, D].

    Paged mode (`table` [B, MAXB] i32): k_q/v_q are a block pool
    [NB, KVH, 128, D] with scales [NB, KVH, 1, 128] (ops/paged.py)."""
    B, _, H, D = q.shape
    KVH = k_q.shape[1]
    group = H // KVH
    scale = D ** -0.5
    qg = q.reshape(B, KVH, group, D)

    if table is not None:
        BS = k_q.shape[2]
        if BS != 128:
            raise ValueError("paged int8 KV blocks must be 128 tokens")
        num_kb = table.shape[1]
        T = num_kb * BS

        def kv_map(b, h, kb, lens, tab):
            last = jnp.maximum(pl.cdiv(lens[b], BS) - 1, 0)
            return (tab[b, jnp.minimum(kb, last)], h, 0, 0)

        kernel = functools.partial(_decode_q8_kernel_paged, num_kb=num_kb,
                                   t_total=T, scale=scale,
                                   sliding_window=sliding_window)
        out = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B, KVH, num_kb),
                in_specs=[
                    pl.BlockSpec((1, 1, group, D),
                                 lambda b, h, kb, lens, tab: (b, h, 0, 0)),
                    pl.BlockSpec((1, 1, BS, D), kv_map),
                    pl.BlockSpec((1, 1, 1, 128), kv_map),
                    pl.BlockSpec((1, 1, BS, D), kv_map),
                    pl.BlockSpec((1, 1, 1, 128), kv_map),
                ],
                out_specs=pl.BlockSpec((1, 1, group, D),
                                       lambda b, h, kb, lens, tab:
                                       (b, h, 0, 0)),
                scratch_shapes=[
                    pltpu.VMEM((group, 128), jnp.float32),
                    pltpu.VMEM((group, 128), jnp.float32),
                    pltpu.VMEM((group, D), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct(qg.shape, q.dtype),
            compiler_params=CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=_interpret(),
        )(lengths.astype(jnp.int32), table.astype(jnp.int32), qg,
          k_q, k_s.astype(jnp.float32), v_q, v_s.astype(jnp.float32))
        return out.reshape(B, 1, H, D)

    T = k_q.shape[2]
    if T % 128:
        raise ValueError("int8 KV cache length must be a multiple of 128")
    num_kb = T // 128
    n_tiles = k_s.shape[2]

    def kv_map(b, h, kb, lens):
        last = jnp.maximum(pl.cdiv(lens[b], 128) - 1, 0)
        return (b, h, jnp.minimum(kb, last), 0)

    kernel = functools.partial(_decode_q8_kernel, num_kb=num_kb, t_total=T,
                               scale=scale, sliding_window=sliding_window)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, KVH, num_kb),
            in_specs=[
                pl.BlockSpec((1, 1, group, D),
                             lambda b, h, kb, lens: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, 128, D), kv_map),
                # scales ride whole per (slot, head): one small DMA, reused
                # across every KV block of the row
                pl.BlockSpec((1, 1, n_tiles, 128),
                             lambda b, h, kb, lens: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, 128, D), kv_map),
                pl.BlockSpec((1, 1, n_tiles, 128),
                             lambda b, h, kb, lens: (b, h, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, group, D),
                                   lambda b, h, kb, lens: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((group, 128), jnp.float32),   # m (lane-replicated)
                pltpu.VMEM((group, 128), jnp.float32),   # l
                pltpu.VMEM((group, D), jnp.float32),     # acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(qg.shape, q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(lengths.astype(jnp.int32), qg, k_q, k_s.astype(jnp.float32),
      v_q, v_s.astype(jnp.float32))
    return out.reshape(B, 1, H, D)


# --------------------------------------------------------------- probe

_PROBE_CACHE: dict[tuple, bool] = {}


def pallas_works(num_heads: int = 4, num_kv_heads: int = 2,
                 head_dim: int = 128, sliding_window: int | None = None,
                 dtype=jnp.bfloat16, kv_quant: bool = False) -> bool:
    """Compile-probe the kernels once per (shape, dtype) on this backend.

    Round-3 failure mode: the kernels lowered fine in interpreter mode but
    Mosaic rejected them on the real chip — killing the serving engine from
    inside the jitted step. Mosaic's tiling legality is SHAPE-dependent, so
    the probe uses the caller's head geometry (the model passes its config),
    letting the attention selector fall back to the XLA path instead of dying.
    """
    key = (num_heads, num_kv_heads, head_dim, sliding_window,
           jnp.dtype(dtype).name, kv_quant)
    if key in _PROBE_CACHE:
        return _PROBE_CACHE[key]
    if jax.default_backend() != "tpu":
        _PROBE_CACHE[key] = True        # interpreter mode: always lowers
        return True

    def _probe():
        # load-time tier probe: the block_until_ready fences ARE the point
        # (prove each kernel lowers+runs on this chip before serving
        # starts); never on a request path
        B, S, T = 1, 256, 512
        q = jnp.zeros((B, S, num_heads, head_dim), dtype)
        kv = jnp.zeros((B, S, num_kv_heads, head_dim), dtype)
        lengths = jnp.array([S], jnp.int32)
        # lint: allow(sync-block-until-ready)
        flash_prefill(q, kv, kv, lengths,
                      sliding_window=sliding_window).block_until_ready()
        qd = jnp.zeros((B, 1, num_heads, head_dim), dtype)
        # paged pool shapes for the scatter-append probe (ops/pallas/
        # paged_scatter.py) — the decode hot path's write kernel must lower
        # on this chip too, or the whole paged tier falls back to XLA
        from localai_tpu.ops.pallas.paged_scatter import (
            paged_scatter_append, paged_scatter_append_q8,
        )

        table = jnp.zeros((B, 2), jnp.int32)
        pos = jnp.zeros((B,), jnp.int32)
        knew = jnp.zeros((B, num_kv_heads, head_dim), dtype)
        if kv_quant:
            cq = jnp.zeros((B, num_kv_heads, T, head_dim), jnp.int8)
            cs = jnp.zeros((B, num_kv_heads, T // 128, 128), jnp.float32)
            # lint: allow(sync-block-until-ready)
            ragged_decode_q8(
                qd, cq, cs, cq, cs, lengths,
                sliding_window=sliding_window).block_until_ready()
            pq = jnp.zeros((2, num_kv_heads, 128, head_dim), jnp.int8)
            ps = jnp.zeros((2, num_kv_heads, 1, 128), jnp.float32)
            # lint: allow(sync-block-until-ready)
            jax.block_until_ready(paged_scatter_append_q8(
                pq, ps, pq, ps, knew, knew, pos, table))
        else:
            cache = jnp.zeros((B, num_kv_heads, T, head_dim), dtype)
            # lint: allow(sync-block-until-ready)
            ragged_decode(qd, cache, cache, lengths,
                          sliding_window=sliding_window).block_until_ready()
            pool = jnp.zeros((2, num_kv_heads, 128, head_dim), dtype)
            # lint: allow(sync-block-until-ready)
            jax.block_until_ready(paged_scatter_append(
                pool, pool, knew, knew, pos, table))

    # _attn_impls consults this probe at TRACE time (inside jit). JAX's trace
    # stack is thread-local, so a worker thread compiles + runs the probe
    # eagerly even mid-trace — jnp.zeros above must produce real arrays, not
    # tracers (round-4 bench silently fell back to XLA attention exactly
    # here), and pallas_call cannot run under ensure_compile_time_eval.
    import threading

    box: dict = {}

    def _runner():
        try:
            _probe()
            box["ok"] = True
        except Exception as e:          # pragma: no cover - TPU-only branch
            box["ok"] = False
            box["err"] = e

    t = threading.Thread(target=_runner, daemon=True)
    t.start()
    t.join()
    ok = box.get("ok", False)
    if not ok:
        import logging

        logging.getLogger("localai_tpu").warning(
            "Pallas attention failed to lower on %s for heads=%d kv=%d d=%d "
            "— falling back to XLA attention: %s",
            jax.devices()[0].device_kind, num_heads, num_kv_heads, head_dim,
            box.get("err"))
    _PROBE_CACHE[key] = ok
    return ok
