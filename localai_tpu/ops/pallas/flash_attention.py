"""Pallas TPU attention kernels — the native compute tier.

Reference parity note: llama.cpp's flash-attention toggle
(/root/reference/backend/backend.proto:247) enables fused CUDA attention; here
the fused kernels are Mosaic/Pallas, written block-wise for the MXU with
online softmax so the [S, S] score matrix never hits HBM (memory O(block²)
instead of O(S²)).

Two kernels:
- flash_prefill: causal GQA attention over padded prompt batches
  [B, S, H, D]; per-row validity from `lengths`; optional sliding window.
- ragged_decode: one-token-per-slot decode attention against the slot KV
  cache [B, T, KVH, D]; each (slot, head) program scans only
  ceil(length/BLOCK) KV blocks — the "ragged" part that makes long-context
  decode O(valid tokens), not O(max context).

On CPU (tests) both run in interpreter mode; the math is identical.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# large-but-finite so exp(NEG_INF - NEG_INF) stays 0/1 instead of NaN when a
# row's first blocks are fully masked (sliding window, ragged tails)
NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def pallas_available() -> bool:
    return jax.default_backend() == "tpu"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------- prefill

def _prefill_kernel(lengths_ref, q_ref, k_ref, v_ref, o_ref, *,
                    block_q: int, block_k: int, scale: float,
                    sliding_window: int | None):
    qb = pl.program_id(2)
    length = lengths_ref[0]
    q = q_ref[0, :, 0, :].astype(jnp.float32) * scale          # [BQ, D]
    S = k_ref.shape[1]
    num_kb = pl.cdiv(S, block_k)

    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), 0, :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), 0, :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # [BQ, BK]
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = (k_pos <= q_pos) & (k_pos < length)
        if sliding_window is not None:
            mask &= k_pos > q_pos - sliding_window
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    # causal: only KV blocks up to (and including) this query block
    last_kb = jnp.minimum(
        (qb + 1) * block_q + block_k - 1, S + block_k - 1) // block_k
    last_kb = jnp.minimum(last_kb, num_kb)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0, :, 0, :] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sliding_window", "block_q",
                                             "block_k"))
def flash_prefill(q, k, v, lengths, sliding_window=None,
                  block_q: int = 128, block_k: int = 128):
    """Causal GQA flash attention. q: [B, S, H, D]; k/v: [B, S, KVH, D];
    lengths: [B]. Returns [B, S, H, D] in q.dtype."""
    B, S, H, D = q.shape
    KVH = k.shape[2]
    group = H // KVH
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    scale = D ** -0.5

    grid = (B, H, pl.cdiv(S, block_q))
    kernel = functools.partial(
        _prefill_kernel, block_q=block_q, block_k=block_k, scale=scale,
        sliding_window=sliding_window)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda b, h, qb: (b,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, 1, D),
                         lambda b, h, qb: (b, qb, h, 0)),
            pl.BlockSpec((1, S, 1, D),
                         lambda b, h, qb: (b, 0, h // group, 0)),
            pl.BlockSpec((1, S, 1, D),
                         lambda b, h, qb: (b, 0, h // group, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, 1, D),
                               lambda b, h, qb: (b, qb, h, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(lengths.astype(jnp.int32), q, k, v)


# --------------------------------------------------------------- decode

def _decode_kernel(lengths_ref, q_ref, k_ref, v_ref, o_ref, *,
                   block_k: int, scale: float, sliding_window: int | None):
    length = lengths_ref[0]
    q = q_ref[0, 0, 0, :, :].astype(jnp.float32) * scale        # [G, D]
    T = k_ref.shape[1]

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), 0, :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), 0, :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # [G, BK]
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[0], block_k), 1)
        mask = k_pos < length
        if sliding_window is not None:
            mask &= k_pos > length - 1 - sliding_window
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    # ragged: scan only the blocks holding valid cache entries
    num_kb = jnp.minimum(pl.cdiv(length, block_k), pl.cdiv(T, block_k))
    G = q.shape[0]
    m0 = jnp.full((G,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((G,), jnp.float32)
    acc0 = jnp.zeros((G, q.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0, 0, 0, :, :] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sliding_window", "block_k"))
def ragged_decode(q, k_cache, v_cache, lengths, sliding_window=None,
                  block_k: int = 256):
    """Decode-step GQA attention. q: [B, 1, H, D]; caches [B, T, KVH, D];
    lengths: [B] valid entries incl. the newly-written token.
    Returns [B, 1, H, D]."""
    B, _, H, D = q.shape
    T, KVH = k_cache.shape[1], k_cache.shape[2]
    group = H // KVH
    block_k = min(block_k, T)
    scale = D ** -0.5

    # one program per (slot, kv head): its q block is the GQA group
    qg = q.reshape(B, 1, KVH, group, D)
    kernel = functools.partial(_decode_kernel, block_k=block_k, scale=scale,
                               sliding_window=sliding_window)
    out = pl.pallas_call(
        kernel,
        grid=(B, KVH),
        in_specs=[
            pl.BlockSpec((1,), lambda b, h: (b,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, 1, group, D), lambda b, h: (b, 0, h, 0, 0)),
            pl.BlockSpec((1, T, 1, D), lambda b, h: (b, 0, h, 0)),
            pl.BlockSpec((1, T, 1, D), lambda b, h: (b, 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 1, group, D),
                               lambda b, h: (b, 0, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(qg.shape, q.dtype),
        interpret=_interpret(),
    )(lengths.astype(jnp.int32), qg, k_cache, v_cache)
    return out.reshape(B, 1, H, D)
