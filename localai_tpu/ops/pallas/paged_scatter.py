"""Pallas scatter-append — the paged-KV decode write path.

The XLA formulation of the per-step cache write (`models/llama._cache_write`
with a `table`) scatters through GATHERED physical indices
(`pool.at[table[b, pos // BS], :, pos % BS].set(row)`). Inside the fused
multi-step decode block the scatter rides the layer scan's donated carry, and
whenever XLA cannot keep it on the in-place path (index uniqueness is only
host-knowledge; the compiler sees arbitrary computed indices) it falls back
to copying the ENTIRE block pool per layer per step — the paged-vs-dense
regression VERDICT.md Weak #2 measured at 8x on chip (CPU repro 42 ms →
6.6 s).

This kernel removes the question from the compiler entirely: the physical
destination of each slot's new token — block `table[b, len // BS]`, row
`len % BS` — is computed at trace time, shipped as scalar-prefetch operands,
and each grid step DMAs exactly one [KVH, 1, D] row into the pool, which is
aliased in place via `input_output_aliases` (the Pallas analog of donation).
Traffic is O(slots), not O(pool); nothing else in the pool is touched.

Inactive slots (admission racing a decode dispatch) redirect to the TRASH
block (physical 0, ops/paged.py) at a distinct per-slot row, mirroring the
XLA path's redirect semantics.

Two variants, matching the ragged decode kernels:
- `paged_scatter_append`: bf16/f32 pools [NB, KVH, BS, D].
- `paged_scatter_append_q8`: int8 pools + per-token scales
  [NB, KVH, 1, BS] (ops/kvcache layout with BS == SCALE_TILE); the new row
  is quantized in the wrapper (plain XLA — one token) and the kernel DMAs
  the int8 row and its scale element.

On CPU both run in interpreter mode (tests force LOCALAI_FORCE_PALLAS=1);
real-TPU lowering is gated by the same `pallas_works` probe as the attention
kernels (ops/pallas/flash_attention.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from localai_tpu.ops.pallas.flash_attention import (
    CompilerParams as _CompilerParams,
    _interpret,
)

try:                                  # jax >= 0.5 top-level export
    from jax import shard_map as _shard_map
except ImportError:                   # 0.4.x spelling
    from jax.experimental.shard_map import shard_map as _shard_map


def _targets(positions, table, active, sb=None, rw=None):
    """(physical block [B], in-block row [B]) for each slot's new token.

    Computed at trace time from the scalar-prefetched table — the kernel
    never sees an index it could fail to prove unique. Inactive rows route
    to the trash block at row `b % BS` (distinct while B <= BS, the same
    bound the XLA redirect asserts — models/llama._cache_write).

    sb/rw ([B] i32, optional): KV-lifecycle ring geometry
    (ops/paged.ring_block_map) — windowed slots' raw block indices fold into
    their O(window) ring columns before the table lookup, so the DMA kernel
    itself needs no ring knowledge. Full-policy slots ship the identity
    sentinel (sb >= table width)."""
    b = positions.shape[0]
    block = jnp.int32(_POOL_BS)
    raw = positions // block
    if sb is not None:
        from localai_tpu.ops.paged import ring_block_map

        raw = ring_block_map(raw, sb, rw)
    pb = table[jnp.arange(b), raw]
    off = positions % block
    if active is not None:
        pb = jnp.where(active, pb, 0)
        off = jnp.where(active, off, jnp.arange(b, dtype=jnp.int32) % block)
    return pb.astype(jnp.int32), off.astype(jnp.int32)


_POOL_BS = 128  # == ops.paged.BLOCK == kvcache.SCALE_TILE


def _append_kernel(pb_ref, off_ref, knew_ref, vnew_ref, kin_ref, vin_ref,
                   kout_ref, vout_ref, sem):
    b = pl.program_id(0)
    pb, off = pb_ref[b], off_ref[b]
    # kin/vin are the aliased pools themselves (input_output_aliases): the
    # only writes are the two row DMAs below — O(slots) traffic per step
    del kin_ref, vin_ref
    ck = pltpu.make_async_copy(
        knew_ref.at[b], kout_ref.at[pb, :, pl.ds(off, 1), :], sem.at[0])
    cv = pltpu.make_async_copy(
        vnew_ref.at[b], vout_ref.at[pb, :, pl.ds(off, 1), :], sem.at[1])
    ck.start()
    cv.start()
    ck.wait()
    cv.wait()


def paged_scatter_append(k_pool, v_pool, k_new, v_new, positions, table,
                         active=None, sb=None, rw=None):
    """Append one K/V token per slot into the paged pools, in place.

    k_pool/v_pool: [NB, KVH, BS, D]; k_new/v_new: [B, KVH, D] (this step's
    rope-applied K and raw V rows); positions: [B] write position (= the
    slot's current length); table: [B, MAXB] i32; active: [B] bool or None;
    sb/rw: [B] i32 or None — KV-lifecycle ring geometry (see _targets).
    Returns the updated (k_pool, v_pool) — aliased, not copies.
    """
    b, kvh, d = k_new.shape
    pb, off = _targets(positions, table, active, sb=sb, rw=rw)
    kn = k_new.reshape(b, kvh, 1, d).astype(k_pool.dtype)
    vn = v_new.reshape(b, kvh, 1, d).astype(v_pool.dtype)
    return pl.pallas_call(
        _append_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 4,
            out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 2,
            scratch_shapes=[pltpu.SemaphoreType.DMA((2,))],
        ),
        out_shape=[jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
                   jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype)],
        # flat operand indices include the 2 scalar-prefetch args:
        # (pb, off, kn, vn, k_pool, v_pool) -> pools at 4 and 5
        input_output_aliases={4: 0, 5: 1},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(pb, off, kn, vn, k_pool, v_pool)


def _head_axis(mesh):
    """Mesh axis the pool's KV-head dim shards on (None on a data-only
    mesh — every shard then holds the full head set)."""
    return "model" if "model" in mesh.axis_names else None


def paged_scatter_append_sharded(mesh, k_pool, v_pool, k_new, v_new,
                                 positions, table, active=None,
                                 sb=None, rw=None):
    """TP wrapper: run the scatter-append kernel per-shard via shard_map
    over the pool's KV-head axis (models/llama.py paged_pool_spec).

    pallas_call has no GSPMD partitioning rule, so calling the kernel
    directly under a mesh would make the partitioner all-gather the whole
    pool — exactly the traffic the kernel exists to avoid. Inside shard_map
    each model-shard DMAs its local [KVH/tp, 1, D] rows; positions/table/
    active are replicated scalars-per-slot, so every shard computes the same
    block targets. check_rep=False: the kernel body is opaque to the
    replication checker."""
    from jax.sharding import PartitionSpec as P

    ax = _head_axis(mesh)
    pool, new, rep = P(None, ax, None, None), P(None, ax, None), P()
    # ring-map the write targets OUTSIDE shard_map (positions/table are
    # replicated anyway) so the inner body stays one shape for every
    # active/tier combination
    if sb is not None:
        from localai_tpu.ops.paged import ring_block_map

        b = positions.shape[0]
        raw = ring_block_map(positions // _POOL_BS, sb, rw)
        table = table[jnp.arange(b), raw][:, None]       # [B, 1] direct map
        positions = positions % _POOL_BS
    if active is None:
        return _shard_map(
            lambda kp, vp, kn, vn, pos, tab: paged_scatter_append(
                kp, vp, kn, vn, pos, tab),
            mesh=mesh, in_specs=(pool, pool, new, new, rep, rep),
            out_specs=(pool, pool), check_rep=False,
        )(k_pool, v_pool, k_new, v_new, positions, table)
    return _shard_map(
        lambda kp, vp, kn, vn, pos, tab, act: paged_scatter_append(
            kp, vp, kn, vn, pos, tab, act),
        mesh=mesh, in_specs=(pool, pool, new, new, rep, rep, rep),
        out_specs=(pool, pool), check_rep=False,
    )(k_pool, v_pool, k_new, v_new, positions, table, active)


def paged_scatter_append_q8_sharded(mesh, kq, ks, vq, vs, k_new, v_new,
                                    positions, table, active=None,
                                    sb=None, rw=None):
    """int8 twin of paged_scatter_append_sharded: the scale pools
    [NB, KVH, 1, BS] shard their KV-head axis alongside the int8 bodies."""
    from jax.sharding import PartitionSpec as P

    ax = _head_axis(mesh)
    pool = P(None, ax, None, None)
    new, rep = P(None, ax, None), P()
    if sb is not None:
        from localai_tpu.ops.paged import ring_block_map

        b = positions.shape[0]
        raw = ring_block_map(positions // _POOL_BS, sb, rw)
        table = table[jnp.arange(b), raw][:, None]       # [B, 1] direct map
        positions = positions % _POOL_BS
    specs4 = (pool, pool, pool, pool, new, new, rep, rep)
    if active is None:
        return _shard_map(
            lambda a, b, c, d, kn, vn, pos, tab: paged_scatter_append_q8(
                a, b, c, d, kn, vn, pos, tab),
            mesh=mesh, in_specs=specs4, out_specs=(pool,) * 4,
            check_rep=False,
        )(kq, ks, vq, vs, k_new, v_new, positions, table)
    return _shard_map(
        lambda a, b, c, d, kn, vn, pos, tab, act: paged_scatter_append_q8(
            a, b, c, d, kn, vn, pos, tab, act),
        mesh=mesh, in_specs=specs4 + (rep,), out_specs=(pool,) * 4,
        check_rep=False,
    )(kq, ks, vq, vs, k_new, v_new, positions, table, active)


def _append_q8_kernel(pb_ref, off_ref, kq_new_ref, ks_new_ref, vq_new_ref,
                      vs_new_ref, kq_in, ks_in, vq_in, vs_in,
                      kq_ref, ks_ref, vq_ref, vs_ref, sem):
    b = pl.program_id(0)
    pb, off = pb_ref[b], off_ref[b]
    del kq_in, ks_in, vq_in, vs_in
    copies = (
        pltpu.make_async_copy(
            kq_new_ref.at[b], kq_ref.at[pb, :, pl.ds(off, 1), :], sem.at[0]),
        pltpu.make_async_copy(
            ks_new_ref.at[b], ks_ref.at[pb, :, :, pl.ds(off, 1)], sem.at[1]),
        pltpu.make_async_copy(
            vq_new_ref.at[b], vq_ref.at[pb, :, pl.ds(off, 1), :], sem.at[2]),
        pltpu.make_async_copy(
            vs_new_ref.at[b], vs_ref.at[pb, :, :, pl.ds(off, 1)], sem.at[3]),
    )
    for c in copies:
        c.start()
    for c in copies:
        c.wait()


def paged_scatter_append_q8(kq, ks, vq, vs, k_new, v_new, positions, table,
                            active=None, sb=None, rw=None):
    """int8 variant: pools kq/vq [NB, KVH, BS, D] int8 with scales ks/vs
    [NB, KVH, 1, BS] f32 (one aligned scale row per block — ops/paged.py).
    k_new/v_new arrive dense [B, KVH, D]; quantization happens here (one
    token per slot — negligible next to the attention it feeds)."""
    from localai_tpu.ops.kvcache import quantize_tokens

    b, kvh, d = k_new.shape
    pb, off = _targets(positions, table, active, sb=sb, rw=rw)
    kq_n, ks_n = quantize_tokens(k_new)          # [B, KVH, D], [B, KVH]
    vq_n, vs_n = quantize_tokens(v_new)
    kq_n = kq_n.reshape(b, kvh, 1, d)
    vq_n = vq_n.reshape(b, kvh, 1, d)
    ks_n = ks_n.reshape(b, kvh, 1, 1).astype(ks.dtype)
    vs_n = vs_n.reshape(b, kvh, 1, 1).astype(vs.dtype)
    return pl.pallas_call(
        _append_q8_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 8,
            out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 4,
            scratch_shapes=[pltpu.SemaphoreType.DMA((4,))],
        ),
        out_shape=[jax.ShapeDtypeStruct(kq.shape, kq.dtype),
                   jax.ShapeDtypeStruct(ks.shape, ks.dtype),
                   jax.ShapeDtypeStruct(vq.shape, vq.dtype),
                   jax.ShapeDtypeStruct(vs.shape, vs.dtype)],
        # (pb, off, kq_n, ks_n, vq_n, vs_n, kq, ks, vq, vs)
        input_output_aliases={6: 0, 7: 1, 8: 2, 9: 3},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(pb, off, kq_n, ks_n, vq_n, vs_n, kq, ks, vq, vs)
