"""Paged KV cache — block-paged storage + per-slot block tables.

The round-4 limit (VERDICT missing #1): the dense cache [L, B, KVH, T, D]
makes slots × context a hard HBM product, so 64-slot/8k-ctx configs cannot
exist even though a typical request touches a small fraction of its context.
The reference's llama.cpp serving core runs a unified cell pool across slots
(/root/reference/backend/cpp/llama-cpp/grpc-server.cpp:311-318 manages a
shared n_ctx with per-slot cells); the TPU shape of that idea (PAPERS.md
ragged-paged-attention) is:

  storage:  [L, NBLOCKS, KVH, BS, D]   BS = 128 tokens (the int8 scale tile)
  table:    [B, MAXB] int32            virtual block v of slot b lives in
                                       physical block table[b, v]

Physical block 0 is the TRASH block: unallocated table entries point at it,
so redirected writes (inactive slots) land somewhere harmless and reads are
impossible (every read is masked by `lengths`, and a slot's lengths never
exceed its allocation — the engine reserves blocks for prompt + max_tokens
at admission, which is also why generation can never run out mid-flight).

The Pallas decode kernels stream KV blocks through the table with a
scalar-prefetched index map (ops/pallas/flash_attention.py), and the decode
WRITE is a scatter-append DMA kernel (ops/pallas/paged_scatter.py) — traffic
stays O(valid tokens)/O(slots). The XLA reference paths below materialize
the virtual view with a gather; that is the CPU-test / fallback tier, not
the TPU hot path (asserted by tests/test_paged_fast_path.py).

int8 storage reuses ops/kvcache.QuantKV verbatim: with BS == SCALE_TILE the
per-block scale row is [1, 128] and `cache_scatter`'s tok//128, tok%128
arithmetic is the identity on in-block rows.
"""
from __future__ import annotations

import jax.numpy as jnp

from localai_tpu.ops.kvcache import QuantKV, init_quant

BLOCK = 128  # tokens per physical block == kvcache.SCALE_TILE


def init_paged(num_layers: int, nblocks: int, kv_heads: int, head_dim: int,
               dtype=jnp.bfloat16, cache_type: str = ""):
    """Block pool [L, NB, KVH, BS, D] (+1 trash block is the CALLER's count:
    pass nblocks already including physical block 0)."""
    from localai_tpu.ops.kvcache import is_quant_kind

    shape = (num_layers, nblocks, kv_heads, BLOCK, head_dim)
    if is_quant_kind(cache_type):
        return init_quant(shape), init_quant(shape)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def paged_view(cache, table):
    """Materialize the virtual per-slot cache [B, KVH, MAXB*BS, D] from the
    block pool [NB, KVH, BS, D] (single layer — call inside the layer scan).
    XLA reference path only; the Pallas kernels never materialize this."""
    maxb = table.shape[1]
    if isinstance(cache, QuantKV):
        q = paged_view(cache.q, table)
        s = cache.s[table]                       # [B, MAXB, KVH, 1, 128]
        b = s.shape[0]
        s = s.transpose(0, 2, 1, 3, 4).reshape(b, s.shape[2], maxb, BLOCK)
        return QuantKV(q, s)                     # s: [B, KVH, T//128, 128]
    g = cache[table]                             # [B, MAXB, KVH, BS, D]
    b, _, kvh, _, d = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(b, kvh, maxb * BLOCK, d)


def blocks_needed(tokens: int) -> int:
    """Virtual blocks required to hold `tokens` cache rows."""
    return -(-tokens // BLOCK)


# --------------------------------------------------------------- KV lifecycle
# Ring-mapped compact residency (engine/kvtier.py): under a
# sink_window(sinks, window) retention policy a slot keeps only
# sink_blocks identity-mapped table columns plus a ring of ring_blocks
# columns that the write path reuses in place — O(sinks + window) resident
# blocks for any context length. Every function below is pure device
# arithmetic over per-slot runtime arrays (sink_blocks `sb`, ring width
# `rw`), so ONE compiled program serves any mix of full and windowed slots:
# full-policy slots ship the sentinel sb >= table width, which makes the
# mapping the identity and every block valid.


def ring_block_map(raw_block, sb, rw):
    """Raw (virtual) block index -> resident table column.

    raw_block: int32 array of position//BLOCK values; sb/rw broadcastable
    against it. Identity for raw_block < sb (sinks, and everything under the
    full-policy sentinel); blocks at/after the sinks land in the ring."""
    rw = jnp.maximum(rw, 1)
    return jnp.where(raw_block < sb, raw_block, sb + (raw_block - sb) % rw)


def resident_block_positions(maxb: int, sb, rw, length):
    """Which raw block each table column currently holds, and whether it is
    a live resident — the read-side inverse of ring_block_map.

    sb/rw/length: [B] int32. Returns (raw [B, maxb] int32, ok [B, maxb]
    bool). Ring column j >= sb holds the LARGEST raw block <= cur (the block
    `length-1` lives in) mapping to it; columns the ring has not reached yet
    (raw would precede the sinks) and columns past sb+rw are masked. Rows
    with positions >= length inside a live block are the previous ring
    generation's leftovers — callers mask them with `pos < length`."""
    j = jnp.arange(maxb, dtype=jnp.int32)[None, :]
    sb = sb[:, None].astype(jnp.int32)
    rw = jnp.maximum(rw[:, None].astype(jnp.int32), 1)
    cur = jnp.maximum(length[:, None].astype(jnp.int32) - 1, 0) // BLOCK
    # ring offset of the current block, and of column j
    m = (cur - sb) % rw
    o = j - sb
    raw_ring = cur - ((m - o) % rw)
    raw = jnp.where(j < sb, j, raw_ring)
    ok = (j < sb) | ((j < sb + rw) & (raw_ring >= sb))
    return raw, ok


def resident_row_positions(maxb: int, sb, rw, length):
    """Per-row true positions + validity of the gathered resident view
    ([B, maxb*BLOCK], matching paged_view's token axis). Validity here is
    residency + `pos < length`; retention-policy masking (window/sinks,
    demotion state) is layered on top by the attention caller."""
    raw, okb = resident_block_positions(maxb, sb, rw, length)
    b = raw.shape[0]
    pos = (raw[:, :, None] * BLOCK
           + jnp.arange(BLOCK, dtype=jnp.int32)[None, None, :])
    pos = pos.reshape(b, maxb * BLOCK)
    ok = jnp.broadcast_to(okb[:, :, None], (b, maxb, BLOCK))
    ok = ok.reshape(b, maxb * BLOCK) & (pos < length[:, None])
    return pos, ok
