"""Weight quantization: per-channel symmetric int8 — the TPU answer to
llama.cpp's GGUF quants (reference ModelOptions dtype/quant surface,
/root/reference/backend/backend.proto:175-265; F16Memory/LowVRAM knobs).

A quantized tensor is {"q": int8 [.., in, out], "s": f32 [.., 1, out]}
(per-output-channel scales). `qmatmul` computes x @ (q * s) with the scale
folded AFTER the int8→bf16 cast so XLA fuses dequant into the matmul epilogue;
HBM traffic halves vs bf16, which is what decode throughput is bound by.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def quantize(w, bits: int = 8):
    """f32/bf16 weight [..., in, out] → {"q": int8|int4, "s": f32 [..., 1, out]}.

    Scales reduce over the INPUT axis only: leading dims (the stacked layer
    axis of the scan layout) keep their own scales — reducing them away
    would give every layer one shared scale AND break lax.scan's leading-axis
    agreement between q [L, in, out] and s.

    bits=4 stores jnp.int4 (the exllama2/GGUF-Q4 role — half the HBM traffic
    of int8 again; XLA packs two nibbles per byte)."""
    if bits not in (4, 8):
        raise ValueError(f"unsupported quantization width {bits}")
    qmax = 7 if bits == 4 else 127
    qdtype = jnp.int4 if bits == 4 else jnp.int8
    w32 = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / qmax
    q = jnp.clip(jnp.round(w32 / scale), -qmax, qmax).astype(qdtype)
    return {"q": q, "s": scale.astype(jnp.float32)}


def quantize_np(w, bits: int = 8):
    """Host-side (numpy) mirror of `quantize`, for the mesh-sharded loader:
    each safetensors shard quantizes right after its host read, so only the
    int8 payload + f32 scales ever cross `device_put` — the full bf16 stack
    is never materialized on host or chip. Bit-identical to the device path
    (IEEE max/div/mul, round-half-even). int4 keeps an int8 container; the
    loader casts to jnp.int4 AFTER the sharded placement (numpy has no int4).
    """
    import numpy as np

    if bits not in (4, 8):
        raise ValueError(f"unsupported quantization width {bits}")
    qmax = 7 if bits == 4 else 127
    w32 = np.asarray(w, np.float32)
    amax = np.max(np.abs(w32), axis=-2, keepdims=True)
    scale = np.maximum(amax, 1e-8) / qmax
    q = np.clip(np.rint(w32 / scale), -qmax, qmax).astype(np.int8)
    return {"q": q, "s": scale.astype(np.float32)}


def is_quantized(p) -> bool:
    return isinstance(p, dict) and set(p.keys()) == {"q", "s"}


def dequantize(p, dtype=jnp.bfloat16):
    return (p["q"].astype(jnp.float32) * p["s"]).astype(dtype)


def qmatmul(x, p, spec=None):
    """x @ W for a (possibly) quantized W; activations keep their dtype.

    `spec` (optional PartitionSpec) is an output-activation sharding hint:
    under an active mesh it is applied as a hard constraint so GSPMD keeps
    the (possibly int8) weight resident-sharded and computes the local
    partial product instead of all-gathering W — the TP decode contract.
    Callers inside shard_map must leave it None (constraints are illegal
    under manual axes)."""
    if not is_quantized(p):
        y = x @ p
    else:
        # int8 → activation dtype, scale folded per output channel
        w = p["q"].astype(x.dtype)
        y = x @ w
        y = y * p["s"].reshape((1,) * (y.ndim - 1) + (-1,)).astype(y.dtype)
    if spec is not None:
        from localai_tpu.parallel.mesh import constrain

        y = constrain(y, spec)
    return y


def quantize_params(params, *, bits: int = 8, skip=("embed", "final_norm")):
    """Quantize every projection matrix in a llama param tree (norms, biases
    and embeddings stay high-precision, like llama.cpp's mixed layouts)."""
    out = {}
    for k, v in params.items():
        if k == "layers":
            out[k] = {
                lk: (quantize(lv, bits)
                     if lk.startswith("w") or lk.startswith("moe_w") else lv)
                for lk, lv in v.items()
            }
        elif k == "lm_head":
            out[k] = quantize(v, bits)
        else:
            out[k] = v
    return out
