"""Batched, jit-safe token sampling — the PredictOptions knob surface.

The reference's sampling knobs live in PredictOptions
(/root/reference/backend/backend.proto:110-159) and are enforced inside
llama.cpp's sampler chain. Here the whole chain is a single vectorized
function over the slot batch, applied on-device every decode step:

  penalties (repeat/presence/frequency over a per-slot token-count table)
  → logit bias → temperature → top-k → top-p → min-p → typical-p → sample

All per-slot knobs are device arrays [B] so slots with different settings
share one jitted step (no recompilation per request mix). top_k/top_p/min_p
use one shared descending sort of the logits — O(B·V·logV) but a single fused
XLA op, MXU-free and bandwidth-bound, which is the right trade on TPU.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


@dataclasses.dataclass
class SamplingParams:
    """Host-side per-request sampling configuration (proto PredictOptions names)."""
    temperature: float = 0.8
    top_k: int = 40            # <=0 disables
    top_p: float = 0.95        # >=1 disables
    min_p: float = 0.0         # <=0 disables
    typical_p: float = 1.0     # >=1 disables
    repeat_penalty: float = 1.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    seed: int = -1             # <0 → draw from entropy
    logit_bias: dict[int, float] | None = None
    greedy: bool = False       # temperature<=0 → greedy

    def normalized(self) -> "SamplingParams":
        p = dataclasses.replace(self)
        if p.temperature is None or p.temperature <= 0:
            p.greedy = True
            p.temperature = 1.0
        if not p.top_k or p.top_k <= 0:
            p.top_k = 0
        if p.top_p is None or p.top_p <= 0:
            p.top_p = 1.0
        return p


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SamplerState:
    """Device-side batched sampler state, one row per engine slot (a pytree —
    flows through jit with buffer donation)."""
    temperature: jax.Array   # [B] f32
    top_k: jax.Array         # [B] i32 (0 = off)
    top_p: jax.Array         # [B] f32
    min_p: jax.Array         # [B] f32
    typical_p: jax.Array     # [B] f32
    repeat_penalty: jax.Array    # [B] f32
    presence_penalty: jax.Array  # [B] f32
    frequency_penalty: jax.Array # [B] f32
    greedy: jax.Array        # [B] bool
    key: jax.Array           # [B, 2] u32 PRNG keys
    token_counts: jax.Array  # [B, V] i32 — occurrences in prompt+generation
    logit_bias: jax.Array    # [B, V] f32

    @staticmethod
    def init(batch: int, vocab: int) -> "SamplerState":
        z = lambda d: jnp.zeros((batch,), d)
        return SamplerState(
            temperature=jnp.ones((batch,), jnp.float32),
            top_k=z(jnp.int32),
            top_p=jnp.ones((batch,), jnp.float32),
            min_p=z(jnp.float32),
            typical_p=jnp.ones((batch,), jnp.float32),
            repeat_penalty=jnp.ones((batch,), jnp.float32),
            presence_penalty=z(jnp.float32),
            frequency_penalty=z(jnp.float32),
            greedy=jnp.zeros((batch,), jnp.bool_),
            key=jnp.zeros((batch, 2), jnp.uint32),
            token_counts=jnp.zeros((batch, vocab), jnp.int32),
            logit_bias=jnp.zeros((batch, vocab), jnp.float32),
        )


def sampler_row(params: SamplingParams, vocab: int, fallback_seed: int,
                include_bias: bool = True) -> dict:
    """Host-side: build the per-slot row values (everything except
    token_counts, which the engine fills with prompt occurrence counts).
    `fallback_seed` is used when the request doesn't pin a seed.
    include_bias=False omits the [V]-sized logit_bias entirely (the engine's
    light-row path — building it here would already device-transfer it)."""
    import numpy as np

    p = params.normalized()
    bias = None
    if include_bias:
        bias = np.zeros((vocab,), np.float32)
        if p.logit_bias:
            for k, v in p.logit_bias.items():
                if 0 <= int(k) < vocab:
                    bias[int(k)] = v
    seed = p.seed if (p.seed is not None and p.seed >= 0) else fallback_seed
    row = dict(
        temperature=jnp.float32(p.temperature),
        top_k=jnp.int32(min(p.top_k, vocab)),
        top_p=jnp.float32(p.top_p),
        min_p=jnp.float32(p.min_p),
        typical_p=jnp.float32(p.typical_p),
        repeat_penalty=jnp.float32(p.repeat_penalty),
        presence_penalty=jnp.float32(p.presence_penalty),
        frequency_penalty=jnp.float32(p.frequency_penalty),
        greedy=jnp.bool_(p.greedy),
        key=jax.random.key_data(jax.random.PRNGKey(seed)).astype(jnp.uint32),
    )
    if bias is not None:
        row["logit_bias"] = jnp.asarray(bias)
    return row


def apply_penalties(logits, state: SamplerState):
    """llama.cpp-semantics penalties: repeat penalty divides positive logits /
    multiplies negative ones for seen tokens; presence/frequency subtract."""
    counts = state.token_counts
    seen = counts > 0
    rp = state.repeat_penalty[:, None]
    penalized = jnp.where(logits > 0, logits / rp, logits * rp)
    logits = jnp.where(seen, penalized, logits)
    logits = logits - seen.astype(jnp.float32) * state.presence_penalty[:, None]
    logits = logits - counts.astype(jnp.float32) * state.frequency_penalty[:, None]
    return logits


def pipeline_logits(logits, state: SamplerState, mask_bits=None):
    """Penalties → bias → temperature (the pre-truncation transform). The
    log_softmax of this is sample()'s logprob contract — OpenAI-style
    logprobs are NOT inflated by top-k/top-p renormalization."""
    b, v = logits.shape
    logits = logits.astype(jnp.float32)
    if mask_bits is not None:
        # two wire formats, one semantic: u8 rows are the host matcher's
        # per-step upload (LSB-first bytes); u32 rows are gathered from the
        # device-resident grammar table (LSB-first words) — identical bit
        # order, so either unpack yields the same allowed set
        if mask_bits.dtype == jnp.uint32:
            bits = (mask_bits[:, :, None]
                    >> jnp.arange(32, dtype=jnp.uint32)) & 1
        else:
            bits = (mask_bits[:, :, None]
                    >> jnp.arange(8, dtype=jnp.uint8)) & 1
        allowed = bits.reshape(b, -1)[:, :v].astype(bool)
        logits = jnp.where(allowed, logits, NEG_INF)
    logits = apply_penalties(logits, state)
    logits = logits + state.logit_bias
    return logits / jnp.maximum(state.temperature[:, None], 1e-6)


def _filtered_sorted(logits, state: SamplerState, mask_bits=None):
    """Shared pipeline: penalties → bias → temperature → truncation chain.
    Returns (masked_sorted_logits [B,V] desc with dropped entries at NEG_INF,
    order [B,V] mapping sorted rank → token id)."""
    b, v = logits.shape
    logits = pipeline_logits(logits, state, mask_bits)

    # shared descending sort powers top-k / top-p / min-p / typical-p
    sorted_logits = -jnp.sort(-logits, axis=-1)                 # [B,V] desc
    order = jnp.argsort(-logits, axis=-1)                       # [B,V]

    rank = jnp.arange(v)[None, :]
    # top-k first, then renormalize over the survivors: llama.cpp chains its
    # samplers sequentially, and the sort-free fast path (_sample_topk) can
    # only see the survivors — sequential semantics keep both paths equal in
    # distribution
    k = jnp.where(state.top_k > 0, state.top_k, v)[:, None]
    keep = rank < k
    probs = jax.nn.softmax(
        jnp.where(keep, sorted_logits, NEG_INF), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # top-p: keep smallest prefix with cum >= p (always keep rank 0)
    keep &= (cum - probs) < state.top_p[:, None]
    # min-p: prob >= min_p * max_prob
    keep &= probs >= state.min_p[:, None] * probs[:, :1]
    # typical-p: keep tokens closest to expected entropy until mass >= typ_p
    ent = -jnp.sum(probs * jnp.log(probs + 1e-10), axis=-1, keepdims=True)
    dev = jnp.abs(-jnp.log(probs + 1e-10) - ent)
    dev_order = jnp.argsort(dev, axis=-1)
    typ_cum = jnp.cumsum(jnp.take_along_axis(probs, dev_order, axis=-1), axis=-1)
    typ_keep_sorted_by_dev = (typ_cum - jnp.take_along_axis(probs, dev_order, axis=-1)) < state.typical_p[:, None]
    typ_keep = jnp.zeros((b, v), bool).at[
        jnp.arange(b)[:, None], dev_order
    ].set(typ_keep_sorted_by_dev)
    keep &= jnp.where(state.typical_p[:, None] >= 1.0, True, typ_keep)
    keep = keep.at[:, 0].set(True)

    masked = jnp.where(keep, sorted_logits, NEG_INF)
    return masked, sorted_logits, order


def sampling_probs(logits, state: SamplerState, mask_bits=None):
    """Full post-pipeline categorical distribution [B, V] in TOKEN order —
    exactly what sample() draws from (greedy rows → one-hot argmax). The
    speculative verifier needs this as an explicit density (Leviathan accept
    ratio + residual distribution)."""
    b, v = logits.shape
    masked, _, order = _filtered_sorted(logits, state, mask_bits)
    p_sorted = jax.nn.softmax(masked, axis=-1)
    rank0 = (jnp.arange(v)[None, :] == 0).astype(jnp.float32)
    p_sorted = jnp.where(state.greedy[:, None], rank0, p_sorted)
    return jnp.zeros((b, v), jnp.float32).at[
        jnp.arange(b)[:, None], order
    ].set(p_sorted)


def sample(logits, state: SamplerState, mask_bits=None, topk_width=None):
    """One sampling step. logits: [B, V] (any float dtype).

    mask_bits: optional [B, ceil(V/8)] u8 allowed-token bitmask (LSB-first)
    from the grammar matcher — disallowed tokens are hard-masked before the
    truncation chain (the llama.cpp grammar-sampler role, applied on-device).

    topk_width (static): decode fast path. A full [B, 128k] descending sort
    is the dominant non-matmul cost of a decode step on TPU; when every
    active slot has 0 < top_k <= width (the engine checks), lax.top_k over
    `width` lanes replaces the two full sorts and top-p/min-p apply WITHIN
    the top-k survivors — llama.cpp's sequential sampler-chain semantics.
    Chosen-token logprobs stay exact (full-vocab logsumexp, no sort needed).

    Returns (tokens [B] i32, new_keys [B,2], logprobs [B] f32 of chosen token).
    """
    if topk_width is not None:
        if mask_bits is not None:
            raise ValueError("grammar masks require the full sampling path "
                             "(topk_width must be None)")
        return _sample_topk(logits, state, topk_width)
    b, v = logits.shape
    masked, sorted_logits, order = _filtered_sorted(logits, state, mask_bits)
    sampled_rank, carry_keys = _draw(state, masked)
    tokens = jnp.take_along_axis(order, sampled_rank[:, None], axis=-1)[:, 0]

    # logprob of the chosen token under the PRE-truncation distribution
    # (post penalties/bias/temperature) — OpenAI-style logprobs must not be
    # inflated by top-k/top-p renormalization.
    logprobs_sorted = jax.nn.log_softmax(sorted_logits, axis=-1)
    tok_logprob = jnp.take_along_axis(logprobs_sorted, sampled_rank[:, None], axis=-1)[:, 0]
    return tokens.astype(jnp.int32), carry_keys, tok_logprob


def _draw(state: SamplerState, masked):
    """Shared PRNG step: split per-slot keys, invert the masked categorical's
    CDF at ONE scalar uniform per slot, greedy rows take rank 0.

    jax.random.categorical would be the obvious draw, but its Gumbel-max
    trick consumes randomness per LANE: the same key over a [B, V] full-sort
    row and a [B, W] top-k window yields different tokens even when the
    survivor distributions are identical, so escalating a slot onto the
    sort-free fast path silently changed its sampled stream. A scalar
    uniform + inverse CDF is width-independent by construction — dropped
    lanes sit at NEG_INF, carry exactly zero probability mass, and cannot
    move the threshold count.
    Returns (sampled_rank [B], carry_keys [B,2] u32)."""
    new_keys = jax.vmap(lambda kk: jax.random.split(
        jax.random.wrap_key_data(kk), 2))(state.key)
    step_keys = jax.vmap(jax.random.wrap_key_data)(
        jax.vmap(jax.random.key_data)(new_keys[:, 1]))
    u = jax.vmap(lambda kk: jax.random.uniform(kk, ()))(step_keys)
    # unnormalized weights: exp(NEG_INF - max) underflows to exactly 0, so
    # the cumsum prefix over the survivors is identical across widths
    w = jnp.exp(masked - masked[:, :1])      # rank 0 always survives
    cum = jnp.cumsum(w, axis=-1)
    r = u[:, None] * cum[:, -1:]
    # smallest rank with cum >= r; the constant tail (cum == total >= r)
    # never counts, so the rank stays within the survivor prefix
    sampled_rank = jnp.sum((cum < r).astype(jnp.int32), axis=-1)
    sampled_rank = jnp.where(state.greedy, 0, sampled_rank)
    carry_keys = jax.vmap(jax.random.key_data)(new_keys[:, 0]).astype(
        jnp.uint32)
    return sampled_rank, carry_keys


def _sample_topk(logits, state: SamplerState, width: int):
    """Sort-free decode sampling over the top-`width` logits (see sample).
    Sequential-chain semantics identical to _filtered_sorted for any slot
    with 0 < top_k <= width and typical_p disabled."""
    b, v = logits.shape
    logits = pipeline_logits(logits, state, None)
    vals, order = jax.lax.top_k(logits, width)                 # [B, W] desc
    rank = jnp.arange(width)[None, :]
    k = jnp.where(state.top_k > 0, state.top_k, width)[:, None]
    keep = rank < k
    # renormalize over the top-k survivors, THEN apply top-p/min-p — the
    # same sequential chain as the full path
    probs = jax.nn.softmax(jnp.where(keep, vals, NEG_INF), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < state.top_p[:, None]
    keep &= probs >= state.min_p[:, None] * probs[:, :1]
    keep = keep.at[:, 0].set(True)
    masked = jnp.where(keep, vals, NEG_INF)

    sampled_rank, carry_keys = _draw(state, masked)
    tokens = jnp.take_along_axis(order, sampled_rank[:, None], axis=-1)[:, 0]

    # exact full-vocab logprob without a sort: val - logsumexp(all logits)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tok_logprob = jnp.take_along_axis(
        vals, sampled_rank[:, None], axis=-1)[:, 0] - lse
    return tokens.astype(jnp.int32), carry_keys, tok_logprob
