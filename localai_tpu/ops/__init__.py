from localai_tpu.ops.norms import rms_norm, layer_norm
from localai_tpu.ops.rope import RopeConfig, rope_freqs, apply_rope
from localai_tpu.ops.attention import mha_prefill, mha_decode
from localai_tpu.ops import sampling

__all__ = [
    "rms_norm",
    "layer_norm",
    "RopeConfig",
    "rope_freqs",
    "apply_rope",
    "mha_prefill",
    "mha_decode",
    "sampling",
]
