"""Rotary position embeddings with the long-context scaling family.

The reference exposes RoPE knobs per model YAML (rope_freq_base, rope_freq_scale,
YaRN ext/attn/beta — /root/reference/backend/backend.proto:191-192,240-243 and
core/config/model_config.go:232-236); we keep that exact knob surface but
compute everything as precomputed cos/sin tables applied on-device.

Scaling modes: none | linear | yarn | llama3 (HF rope_scaling parity).
"""
from __future__ import annotations

import dataclasses
import math

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RopeConfig:
    head_dim: int = 128
    base: float = 10000.0           # rope_freq_base
    scaling: str = "none"           # none | linear | yarn | llama3
    scale_factor: float = 1.0       # 1/rope_freq_scale (HF "factor")
    original_max_position: int = 4096
    # yarn
    beta_fast: float = 32.0
    beta_slow: float = 1.0
    attn_factor: float | None = None   # HF attention_factor; None → computed
    # llama3
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0


def _yarn_find_dim(num_rot: float, dim: int, base: float, max_pos: int) -> float:
    return (dim * math.log(max_pos / (num_rot * 2 * math.pi))) / (2 * math.log(base))


def rope_freqs(cfg: RopeConfig):
    """Returns per-channel inverse frequencies [head_dim//2] (float32) and the
    attention magnitude scale (mscale, used by yarn)."""
    half = cfg.head_dim // 2
    # HF/Llama convention: base ** (-2i/dim) == base ** (-i/half)
    inv_freq = 1.0 / (cfg.base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    mscale = 1.0

    if cfg.scaling == "linear":
        inv_freq = inv_freq / cfg.scale_factor
    elif cfg.scaling == "llama3":
        # per-channel: high-freq dims untouched, low-freq dims scaled, smooth ramp between
        low_wavelen = cfg.original_max_position / cfg.low_freq_factor
        high_wavelen = cfg.original_max_position / cfg.high_freq_factor
        wavelen = 2 * math.pi / inv_freq
        smooth = (cfg.original_max_position / wavelen - cfg.low_freq_factor) / (
            cfg.high_freq_factor - cfg.low_freq_factor
        )
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = inv_freq / cfg.scale_factor
        blended = (1 - smooth) * scaled + smooth * inv_freq
        inv_freq = jnp.where(
            wavelen > low_wavelen, scaled,
            jnp.where(wavelen < high_wavelen, inv_freq, blended),
        )
    elif cfg.scaling == "yarn":
        lo = max(math.floor(_yarn_find_dim(cfg.beta_fast, cfg.head_dim, cfg.base,
                                           cfg.original_max_position)), 0)
        # HF clamps the upper correction bound to head_dim-1 (NOT half-1), and
        # guards a collapsed range with +0.001 — mirror both exactly.
        hi = min(math.ceil(_yarn_find_dim(cfg.beta_slow, cfg.head_dim, cfg.base,
                                          cfg.original_max_position)),
                 cfg.head_dim - 1)
        if hi == lo:
            hi += 0.001
        ramp = jnp.clip((jnp.arange(half, dtype=jnp.float32) - lo) / (hi - lo), 0.0, 1.0)
        # extrapolate (keep original freq) below lo, interpolate (1/scale) above
        # hi, blend in between — matches HF _compute_yarn_parameters where
        # extrapolation_factor = 1 - ramp.
        inv_freq = inv_freq / cfg.scale_factor * ramp + inv_freq * (1.0 - ramp)
        # HF: a provided attention_factor is used VERBATIM; otherwise computed
        if cfg.attn_factor is not None:
            mscale = cfg.attn_factor
        elif cfg.scale_factor > 1:
            mscale = 0.1 * math.log(cfg.scale_factor) + 1.0
    elif cfg.scaling != "none":
        raise ValueError(f"unknown rope scaling mode {cfg.scaling!r}")

    return inv_freq, mscale


def rope_table(cfg: RopeConfig, max_len: int):
    """Precompute (cos, sin) tables of shape [max_len, head_dim//2] (float32)."""
    inv_freq, mscale = rope_freqs(cfg)
    t = jnp.arange(max_len, dtype=jnp.float32)
    angles = t[:, None] * inv_freq[None, :]
    return jnp.cos(angles) * mscale, jnp.sin(angles) * mscale


def apply_rope(x, cos, sin, positions):
    """Apply rotary embedding.

    x: [..., seq, heads, head_dim]; positions: [..., seq] int32 indices into the
    tables; cos/sin: [max_len, head_dim//2]. Uses the "split halves" (GPT-NeoX /
    HF Llama) layout: channel i rotates with channel i + head_dim//2.
    """
    dtype = x.dtype
    c = cos[positions][..., None, :]  # [..., seq, 1, half]
    s = sin[positions][..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)
