"""Quantized KV cache — int8 storage with per-token scales.

Reference parity: llama.cpp exposes KV-cache quantization via
`CacheTypeKey`/`CacheTypeValue` (/root/reference/backend/backend.proto:257-258,
mapped at backend/cpp/llama-cpp/grpc-server.cpp:236-251). Here the same knob
halves the decode working set on TPU: K/V live in HBM as int8 with one f32
scale per (token, kv-head), computed symmetrically over the head_dim axis —
the same granularity as llama.cpp's q8_0 blocks (32 elems there, head_dim
here; head_dim is the natural TPU tile).

Layout is chosen for Mosaic, not for numpy: the scales of cache
[..., T, D] are stored as [..., T // 128, 128] (token t ↦ element
[t // 128, t % 128]) so the trailing two dims of any Pallas block over them
are (rows, 128) — tile-legal — and a 128-token KV block's scales are exactly
one aligned scale row. `T` must therefore be a multiple of 128; callers round
up (extra rows are inert — every read is masked by `lengths`).

The XLA (non-Pallas) attention paths read the cache through `dequant`, which
XLA fuses into the consuming dot where it can; HBM *capacity* is halved
either way, and the int8 Pallas decode kernel
(ops/pallas/flash_attention.py:ragged_decode_q8) also halves decode HBM
*traffic* — the thing decode is actually bound by. On the paged Pallas tier
the per-step cache WRITE quantizes through `quantize_tokens` and lands via
the scatter-append DMA kernel (ops/pallas/paged_scatter.py) instead of
`cache_scatter`'s XLA scatter.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

SCALE_TILE = 128
# int8 symmetric range; 1/127 floor keeps zero vectors exactly zero
_QMAX = 127.0
_EPS = 1e-8

KV_KINDS = ("", "bf16", "f16", "f32", "int8", "q8_0")


def is_quant_kind(kind: str | None) -> bool:
    """True for the cache-type strings that select int8 storage (accepts the
    reference's llama.cpp spelling `q8_0` as well as plain `int8`)."""
    return (kind or "").lower() in ("int8", "q8_0", "q8")


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantKV:
    """One int8 cache tensor: `q` [..., T, D] int8, `s` [..., T//128, 128] f32.

    Behaves enough like the dense array it replaces that the model code's
    `cache.shape[3]`, `cache[rows]`, and lax.scan-over-layers all work
    unchanged.
    """
    q: jax.Array
    s: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def __getitem__(self, idx):
        # leading-axis indexing only (layer scan / slot gather); token and
        # head_dim axes must stay whole because `s` mirrors only the lead dims
        return QuantKV(self.q[idx], self.s[idx])


def padded_len(t: int) -> int:
    """Round a cache length up to the scale-tile multiple the layout needs."""
    return -(-t // SCALE_TILE) * SCALE_TILE


def init_quant(shape, *, scale_dtype=jnp.float32) -> QuantKV:
    """Zero cache of logical shape [..., T, D] (T already tile-padded)."""
    *lead, t, d = shape
    if t % SCALE_TILE:
        raise ValueError(f"quantized cache length {t} not a multiple of "
                         f"{SCALE_TILE} (use padded_len)")
    return QuantKV(
        jnp.zeros(shape, jnp.int8),
        jnp.zeros((*lead, t // SCALE_TILE, SCALE_TILE), scale_dtype),
    )


def quantize_tokens(x):
    """Per-token symmetric int8 over the trailing head_dim axis.

    x: [..., D] (any lead shape) → (q int8 same shape, scale f32 lead shape).
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, _EPS) / _QMAX
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def token_scales(cache: QuantKV):
    """Scales as [..., T] (flattening the tile layout back to token order)."""
    *lead, rows, tile = cache.s.shape
    return cache.s.reshape(*lead, rows * tile)


def dequant(cache, dtype=jnp.bfloat16):
    """QuantKV → dense [..., T, D]; dense arrays pass through untouched."""
    if not isinstance(cache, QuantKV):
        return cache
    s = token_scales(cache)[..., None]
    return (cache.q.astype(jnp.float32) * s).astype(dtype)


def cache_scatter(cache: QuantKV, idx, values, unique: bool = True) -> QuantKV:
    """Scatter dense token vectors into the quantized cache.

    idx: advanced-index tuple addressing [..., T] positions of the cache's
    lead+token axes (the same tuple the dense path hands to `.at[idx].set`);
    values: matching [..., D] dense rows. `unique` asserts non-colliding
    rows (see models/llama.py _cache_write for when that holds) — the
    assertion keeps XLA on the in-place scatter path inside the layer scan.
    """
    q, scale = quantize_tokens(values)
    *lead_idx, tok_idx = idx
    s_idx = (*lead_idx, tok_idx // SCALE_TILE, tok_idx % SCALE_TILE)
    return QuantKV(cache.q.at[idx].set(q, unique_indices=unique),
                   cache.s.at[s_idx].set(scale, unique_indices=unique))


def requantize(cache: QuantKV, dense) -> QuantKV:
    """Dense [..., T, D] → fresh QuantKV with cache's layout (context-shift
    rewrites go through here after operating in f32)."""
    q, scale = quantize_tokens(dense)
    *lead, t = scale.shape
    return QuantKV(q, scale.reshape(*lead, t // SCALE_TILE, SCALE_TILE)
                   .astype(cache.s.dtype))
