"""Minimal MCP (Model Context Protocol) client: stdio + HTTP transports.

Reference: /root/reference/core/http/endpoints/openai/mcp.go:1-142 exposes
`/mcp/v1/chat/completions` — the model config lists MCP servers, their tools
are fetched once per model, and an agentic loop lets the LLM call them. This
module is the protocol side: JSON-RPC 2.0 `initialize` / `tools/list` /
`tools/call` over newline-delimited stdio (spawned command) or HTTP POST
(streamable-http transport; single SSE-framed responses are unwrapped).
"""
from __future__ import annotations

import json
import subprocess
import threading
from typing import Any

PROTOCOL_VERSION = "2024-11-05"


class MCPError(RuntimeError):
    pass


class _StdioTransport:
    """Newline-delimited JSON-RPC over a spawned server process.

    Reads are done at the fd level (os.read after select) with our own line
    buffer: select() on a buffered TextIO misses lines already pulled into
    the userspace buffer, which would stall a reply that arrived in the same
    chunk as a server notification."""

    def __init__(self, command: str, env: dict | None = None):
        import os
        import shlex

        full_env = dict(os.environ)
        full_env.update(env or {})
        self.proc = subprocess.Popen(
            shlex.split(command), stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=full_env)
        self._lock = threading.Lock()
        self._buf = bytearray()

    def _readline(self, deadline: float) -> bytes:
        import os
        import select
        import time

        while b"\n" not in self._buf:
            remain = deadline - time.monotonic()
            if remain <= 0:
                raise MCPError("MCP server timed out")
            ready, _, _ = select.select([self.proc.stdout], [], [],
                                        min(remain, 1.0))
            if not ready:
                if self.proc.poll() is not None:
                    raise MCPError("MCP server process exited")
                continue
            chunk = os.read(self.proc.stdout.fileno(), 1 << 16)
            if not chunk:
                raise MCPError("MCP server closed the pipe")
            self._buf.extend(chunk)
        line, _, rest = bytes(self._buf).partition(b"\n")
        self._buf = bytearray(rest)
        return line

    def request(self, payload: dict, timeout: float = 30.0) -> dict | None:
        import time

        with self._lock:
            if self.proc.poll() is not None:
                raise MCPError("MCP server process exited")
            self.proc.stdin.write((json.dumps(payload) + "\n").encode())
            self.proc.stdin.flush()
            if "id" not in payload:      # notification: no response expected
                return None
            deadline = time.monotonic() + timeout
            while True:
                line = self._readline(deadline)
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue             # non-protocol stdout noise
                # skip server-initiated notifications / mismatched replies
                # (real servers log via notifications/message on stdout)
                if msg.get("id") == payload["id"]:
                    return msg

    def close(self):
        try:
            self.proc.terminate()
            self.proc.wait(timeout=3)
        except Exception:
            self.proc.kill()


class _HttpTransport:
    """JSON-RPC over HTTP POST (MCP streamable-http). A text/event-stream
    reply containing one data: frame is unwrapped."""

    def __init__(self, url: str, headers: dict | None = None):
        self.url = url
        self.headers = {"Content-Type": "application/json",
                        "Accept": "application/json, text/event-stream"}
        self.headers.update(headers or {})

    def request(self, payload: dict, timeout: float = 30.0) -> dict | None:
        import urllib.request

        req = urllib.request.Request(
            self.url, data=json.dumps(payload).encode(), headers=self.headers)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            body = r.read().decode()
            ctype = r.headers.get("Content-Type", "")
        if "id" not in payload:
            return None
        if "text/event-stream" in ctype:
            for line in body.splitlines():
                if line.startswith("data:"):
                    return json.loads(line[5:].strip())
            raise MCPError("SSE response without a data frame")
        return json.loads(body) if body else None

    def close(self):
        pass


class MCPSession:
    """One initialized MCP server connection with its tool list."""

    def __init__(self, name: str, transport):
        self.name = name
        self.transport = transport
        self._next_id = 0
        self.tools: list[dict] = []
        self._initialize()

    def _rpc(self, method: str, params: dict | None = None,
             notify: bool = False):
        payload: dict[str, Any] = {"jsonrpc": "2.0", "method": method}
        if params is not None:
            payload["params"] = params
        if not notify:
            self._next_id += 1
            payload["id"] = self._next_id
        resp = self.transport.request(payload)
        if notify:
            return None
        if resp is None:
            raise MCPError(f"{method}: no response")
        if "error" in resp:
            raise MCPError(f"{method}: {resp['error']}")
        return resp.get("result", {})

    def _initialize(self):
        self._rpc("initialize", {
            "protocolVersion": PROTOCOL_VERSION,
            "capabilities": {},
            "clientInfo": {"name": "localai-tpu", "version": "1"},
        })
        self._rpc("notifications/initialized", {}, notify=True)
        self.tools = self._rpc("tools/list", {}).get("tools", [])

    def call_tool(self, name: str, arguments: dict) -> str:
        result = self._rpc("tools/call", {"name": name,
                                          "arguments": arguments})
        parts = []
        for item in result.get("content", []):
            if item.get("type") == "text":
                parts.append(item.get("text", ""))
            else:
                parts.append(json.dumps(item))
        if result.get("isError"):
            raise MCPError("; ".join(parts) or "tool error")
        return "\n".join(parts)

    def close(self):
        self.transport.close()


def sessions_from_config(mcp_cfg: dict) -> list[MCPSession]:
    """Model-config MCP block → initialized sessions.

    Shape (reference config.MCP, remote+stdio YAML blocks):
      mcp:
        servers:            # remote
          - name: search
            url: http://host/mcp
            headers: {Authorization: ...}
        stdio:              # local commands
          - name: calc
            command: python /path/server.py
            env: {KEY: VAL}
    """
    sessions = []
    for entry in mcp_cfg.get("servers") or []:
        sessions.append(MCPSession(
            entry.get("name", entry.get("url", "remote")),
            _HttpTransport(entry["url"], entry.get("headers"))))
    for entry in mcp_cfg.get("stdio") or []:
        sessions.append(MCPSession(
            entry.get("name", "stdio"),
            _StdioTransport(entry["command"], entry.get("env"))))
    return sessions


def tools_as_openai(sessions: list[MCPSession]) -> tuple[list[dict], dict]:
    """Sessions' tools → OpenAI `tools` array + {tool_name: session} map."""
    tools, owner = [], {}
    for s in sessions:
        for t in s.tools:
            tools.append({"type": "function", "function": {
                "name": t["name"],
                "description": t.get("description", ""),
                "parameters": t.get("inputSchema", {"type": "object"}),
            }})
            owner[t["name"]] = s
    return tools, owner
