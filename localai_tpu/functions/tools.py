"""OpenAI tools / response_format → grammar, and output → tool_calls parsing
(reference: /root/reference/pkg/functions/functions.go ToJSONStructure +
parse.go result parsing; wiring in core/http/endpoints/openai/chat.go:224-312).
"""
from __future__ import annotations

import json
import uuid
from typing import Any

from localai_tpu.functions.grammars import JSON_GRAMMAR, json_schema_grammar


def tools_schema(tools: list[dict]) -> dict:
    """Schema matching {"name": <one of the tools>, "arguments": {...}} —
    the reference's ToJSONStructure shape (functions.go)."""
    alts = []
    for t in tools:
        fn = t.get("function", t)
        alts.append({
            "type": "object",
            "properties": {
                "name": {"const": fn.get("name", "")},
                "arguments": fn.get("parameters", {"type": "object"}),
            },
            "required": ["name", "arguments"],
        })
    if len(alts) == 1:
        return alts[0]
    return {"oneOf": alts}


def grammar_for_request(body: dict) -> str:
    """response_format / tools → GBNF (chat.go:224-312 semantics):
    json_object → generic JSON; json_schema → compiled schema; tools (unless
    tool_choice=none) → tool-call schema."""
    rf = body.get("response_format") or {}
    if isinstance(rf, str):
        rf = {"type": rf}
    if rf.get("type") == "json_object":
        return JSON_GRAMMAR
    if rf.get("type") == "json_schema":
        schema = (rf.get("json_schema") or {}).get("schema") or {}
        return json_schema_grammar(schema)
    tools = body.get("tools") or []
    if tools and body.get("tool_choice") != "none":
        choice = body.get("tool_choice")
        if isinstance(choice, dict):
            want = choice.get("function", {}).get("name")
            tools = [t for t in tools
                     if t.get("function", t).get("name") == want] or tools
        return json_schema_grammar(tools_schema(tools))
    return ""


def parse_tool_calls(text: str) -> list[dict[str, Any]] | None:
    """Parse model output into OpenAI tool_calls (parse.go role). Returns
    None when the output isn't a tool-call JSON object."""
    text = text.strip()
    if not text.startswith(("{", "[")):
        return None
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        return None
    objs = obj if isinstance(obj, list) else [obj]
    calls = []
    for o in objs:
        if not isinstance(o, dict) or "name" not in o:
            return None
        args = o.get("arguments", o.get("parameters", {}))
        calls.append({
            "id": f"call_{uuid.uuid4().hex[:12]}",
            "type": "function",
            "function": {
                "name": o["name"],
                "arguments": json.dumps(args) if not isinstance(args, str)
                else args,
            },
        })
    return calls or None
