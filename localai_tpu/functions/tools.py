"""OpenAI tools / response_format → grammar, and output → tool_calls parsing
(reference: /root/reference/pkg/functions/functions.go ToJSONStructure +
parse.go result parsing; wiring in core/http/endpoints/openai/chat.go:224-312).
"""
from __future__ import annotations

import json
import uuid
from typing import Any

from localai_tpu.functions.grammars import JSON_GRAMMAR, json_schema_grammar


# the reference's no-action function (functions.go GrammarConfig: a grammar
# that ONLY matches tool calls forces a call even when none applies — the
# "answer" alternative lets tool_choice:"auto" produce prose instead)
NO_ACTION_NAME = "answer"
_NO_ACTION_SCHEMA = {
    "type": "object",
    "properties": {
        "name": {"const": NO_ACTION_NAME},
        "arguments": {
            "type": "object",
            "properties": {"message": {"type": "string"}},
            "required": ["message"],
        },
    },
    "required": ["name", "arguments"],
}


def tools_schema(tools: list[dict], allow_answer: bool = False) -> dict:
    """Schema matching {"name": <one of the tools>, "arguments": {...}} —
    the reference's ToJSONStructure shape (functions.go). With
    `allow_answer` the no-action {"name": "answer", "arguments":
    {"message": ...}} alternative joins the oneOf (tool_choice "auto")."""
    alts = []
    for t in tools:
        fn = t.get("function", t)
        alts.append({
            "type": "object",
            "properties": {
                "name": {"const": fn.get("name", "")},
                "arguments": fn.get("parameters", {"type": "object"}),
            },
            "required": ["name", "arguments"],
        })
    if allow_answer and not any(
            t.get("function", t).get("name") == NO_ACTION_NAME
            for t in tools):
        alts.append(_NO_ACTION_SCHEMA)
    if len(alts) == 1:
        return alts[0]
    return {"oneOf": alts}


def grammar_for_request(body: dict) -> str:
    """response_format / tools → GBNF (chat.go:224-312 semantics):
    json_object → generic JSON; json_schema → compiled schema; tools (unless
    tool_choice=none) → tool-call schema."""
    rf = body.get("response_format") or {}
    if isinstance(rf, str):
        rf = {"type": rf}
    if rf.get("type") == "json_object":
        return JSON_GRAMMAR
    if rf.get("type") == "json_schema":
        schema = (rf.get("json_schema") or {}).get("schema") or {}
        return json_schema_grammar(schema)
    tools = body.get("tools") or []
    if tools and body.get("tool_choice") != "none":
        choice = body.get("tool_choice")
        if isinstance(choice, dict):
            want = choice.get("function", {}).get("name")
            tools = [t for t in tools
                     if t.get("function", t).get("name") == want] or tools
        # OpenAI semantics: absent tool_choice means "auto" — only
        # "required" (or pinning a specific function) forces a call, so
        # auto gets the no-action "answer" escape hatch
        auto = choice in (None, "auto")
        return json_schema_grammar(tools_schema(tools, allow_answer=auto))
    return ""


def parse_tool_response(text: str) -> tuple[list[dict] | None, str | None]:
    """Grammar output → (tool_calls, answer_text): a no-action "answer"
    object becomes prose content (its `message`), anything else parses like
    parse_tool_calls. (None, None) = not a tool JSON at all — callers pass
    the raw text through (reference parse.go + functions.go no-action)."""
    calls = parse_tool_calls(text)
    if calls and len(calls) == 1 \
            and calls[0]["function"]["name"] == NO_ACTION_NAME:
        raw = calls[0]["function"]["arguments"]
        try:
            args = json.loads(raw) if isinstance(raw, str) else raw
        except ValueError:
            args = {}
        msg = args.get("message", "") if isinstance(args, dict) else ""
        return None, str(msg)
    return calls, None


def parse_tool_calls(text: str) -> list[dict[str, Any]] | None:
    """Parse model output into OpenAI tool_calls (parse.go role). Returns
    None when the output isn't a tool-call JSON object."""
    text = text.strip()
    if not text.startswith(("{", "[")):
        return None
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        return None
    objs = obj if isinstance(obj, list) else [obj]
    calls = []
    for o in objs:
        if not isinstance(o, dict) or "name" not in o:
            return None
        args = o.get("arguments", o.get("parameters", {}))
        calls.append({
            "id": f"call_{uuid.uuid4().hex[:12]}",
            "type": "function",
            "function": {
                "name": o["name"],
                "arguments": json.dumps(args) if not isinstance(args, str)
                else args,
            },
        })
    return calls or None
