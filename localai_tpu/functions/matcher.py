"""Grammar matcher binding: GBNF → native PDA → per-step token bitmasks.

Host/device split (the TPU answer to llama.cpp's sampler-integrated grammar):
the native lib (localai_tpu/native/grammar.cpp) tracks the parse state and
produces a [ceil(V/8)]-byte allowed-token bitmask; the engine uploads masks
for constrained slots each step and the jitted sampler applies them before
top-k/top-p (ops/sampling.sample).
"""
from __future__ import annotations

import ctypes
import dataclasses
import functools
import json
import threading

import numpy as np

from localai_tpu.native import build_and_load
from localai_tpu.testing.lockdep import lockdep_lock


@functools.lru_cache(maxsize=8)
def _lib():
    lib = build_and_load("grammar")
    lib.gm_compile.restype = ctypes.c_void_p
    lib.gm_compile.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.gm_set_vocab.restype = ctypes.c_int
    lib.gm_set_vocab.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    lib.gm_state_new.restype = ctypes.c_void_p
    lib.gm_state_new.argtypes = [ctypes.c_void_p]
    lib.gm_state_accept_token.restype = ctypes.c_int
    lib.gm_state_accept_token.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.gm_state_mask.restype = ctypes.c_int
    lib.gm_state_mask.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
    lib.gm_state_done.restype = ctypes.c_int
    lib.gm_state_done.argtypes = [ctypes.c_void_p]
    lib.gm_state_can_continue.restype = ctypes.c_int
    lib.gm_state_can_continue.argtypes = [ctypes.c_void_p]
    lib.gm_state_free.argtypes = [ctypes.c_void_p]
    lib.gm_free.argtypes = [ctypes.c_void_p]
    lib.gm_table_build.restype = ctypes.c_int
    lib.gm_table_build.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8)]
    return lib


# ------------------------------------------------------------ token texts

_BYTELEVEL_DECODER: dict[str, int] | None = None


def _bytelevel_table() -> dict[str, int]:
    """GPT-2 bytes↔unicode mapping (chars used by ByteLevel tokenizers)."""
    global _BYTELEVEL_DECODER
    if _BYTELEVEL_DECODER is None:
        bs = (list(range(ord("!"), ord("~") + 1))
              + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
        cs = bs[:]
        n = 0
        for b in range(256):
            if b not in bs:
                bs.append(b)
                cs.append(256 + n)
                n += 1
        _BYTELEVEL_DECODER = {chr(c): b for b, c in zip(bs, cs)}
    return _BYTELEVEL_DECODER


def token_texts(tok) -> list[str]:
    """Raw text each vocab id contributes mid-sequence. Handles ByteLevel
    (byte-alphabet remap; tokens with partial UTF-8 → ''), Metaspace (▁→space)
    and WordPiece (## continuation)."""
    hf = tok._tok
    try:
        spec = json.loads(hf.to_str())
        dec = (spec.get("decoder") or {})
        dtypes = [dec.get("type")] + [
            d.get("type") for d in dec.get("decoders", []) or []
        ]
    except Exception:
        dtypes = [None]

    vocab_size = hf.get_vocab_size()
    out = [""] * vocab_size
    table = _bytelevel_table()
    for i in range(vocab_size):
        t = hf.id_to_token(i)
        if t is None:
            continue
        if "ByteLevel" in dtypes:
            try:
                raw = bytes(table[c] for c in t)
            except KeyError:
                out[i] = ""  # special token — never allowed by a grammar
                continue
            try:
                out[i] = raw.decode("utf-8")
            except UnicodeDecodeError:
                out[i] = ""  # partial multi-byte sequence
        elif "Metaspace" in dtypes:
            out[i] = t.replace("▁", " ")
        elif "WordPiece" in dtypes:
            out[i] = t[2:] if t.startswith("##") else t
        else:
            out[i] = t
    return out


@dataclasses.dataclass(frozen=True)
class GrammarTable:
    """Dense automaton tables for device-side constrained decoding: the
    whole token-reachable state set of one grammar, enumerated once off the
    hot path (gm_table_build). State 0 is the initial state.

    masks     [n_states, (V+31)//32] u32 — LSB-first allowed-token bitmask,
              bit-compatible with MatcherState.mask_bits(()) (no EOS bits:
              EOS policy is the engine's, injected per-tokenizer at install)
    trans     [n_states, V] i32 — next state per token, -1 where masked off
    accepting [n_states] u8 — a completed parse exists in this state
    """
    n_states: int
    masks: np.ndarray
    trans: np.ndarray
    accepting: np.ndarray


class CompiledGrammar:
    """A grammar compiled against a tokenizer's vocabulary."""

    def __init__(self, gbnf: str, token_strings: list[str]):
        lib = _lib()
        err = ctypes.create_string_buffer(256)
        self._g = lib.gm_compile(gbnf.encode(), err, 256)
        if not self._g:
            raise ValueError(f"grammar parse error: {err.value.decode()}")
        self.vocab_size = len(token_strings)
        self.nbytes = (self.vocab_size + 7) // 8
        self.nwords = (self.vocab_size + 31) // 32
        blob = b"".join(s.encode() for s in token_strings)
        offsets = np.zeros(self.vocab_size + 1, np.int64)
        o = 0
        for i, s in enumerate(token_strings):
            offsets[i] = o
            o += len(s.encode())
        offsets[self.vocab_size] = o
        lib.gm_set_vocab(
            self._g, blob,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            self.vocab_size)
        self._lib = lib
        self._tables: dict[int, GrammarTable | None] = {}
        self._tables_lock = lockdep_lock("matcher.tables")

    def state(self) -> "MatcherState":
        return MatcherState(self)

    def table(self, cap: int) -> GrammarTable | None:
        """The grammar's dense device tables, or None when the reachable
        state set exceeds `cap` (unbounded-nesting grammars never close —
        those keep the per-token host matcher path). Memoized per cap; the
        BFS enumeration runs OUTSIDE the lock (it trials every vocab token
        from every state — slow is fine off the hot path, holding a lock
        across it is not) with a double-checked insert."""
        with self._tables_lock:
            if cap in self._tables:
                return self._tables[cap]
        tbl = self._build_table(cap)
        with self._tables_lock:
            return self._tables.setdefault(cap, tbl)

    def _build_table(self, cap: int) -> GrammarTable | None:
        if cap <= 0:
            return None
        masks = np.zeros((cap, self.nwords), np.uint32)
        trans = np.full((cap, self.vocab_size), -1, np.int32)
        accepting = np.zeros(cap, np.uint8)
        n = self._lib.gm_table_build(
            self._g, cap,
            masks.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            self.nwords,
            trans.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            accepting.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if n < 0:
            return None
        return GrammarTable(n, masks[:n].copy(), trans[:n].copy(),
                            accepting[:n].copy())

    def __del__(self):
        if getattr(self, "_g", None):
            self._lib.gm_free(self._g)
            self._g = None


class GrammarCache:
    """Per-tokenizer cache of compiled grammars (token_texts is computed
    once; grammar compiles are memoized by text). Thread-safe: request
    handler threads and the engine loop both call get(); the compile runs
    outside the lock with a double-checked insert, so a slow grammar
    compile (or table precompilation behind it) never blocks other
    threads' cache hits."""

    def __init__(self, tok):
        self._texts = token_texts(tok)
        self._cache: dict[str, CompiledGrammar] = {}
        self._lock = lockdep_lock("matcher.cache")

    def get(self, gbnf: str) -> CompiledGrammar:
        with self._lock:
            g = self._cache.get(gbnf)
        if g is not None:
            return g
        g = CompiledGrammar(gbnf, self._texts)   # slow: outside the lock
        with self._lock:
            if len(self._cache) > 32:
                self._cache.clear()
            return self._cache.setdefault(gbnf, g)


class MatcherState:
    def __init__(self, grammar: CompiledGrammar):
        self.g = grammar
        self._s = grammar._lib.gm_state_new(grammar._g)

    def accept(self, token_id: int) -> bool:
        return bool(self.g._lib.gm_state_accept_token(self._s, token_id))

    def mask_bits(self, eos_ids=()) -> np.ndarray:
        """Allowed-token bitmask [nbytes] u8; EOS bits set iff the grammar
        can complete here."""
        bits = np.zeros(self.g.nbytes, np.uint8)
        self.g._lib.gm_state_mask(
            self._s, bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            self.g.nbytes)
        if self.done:
            for e in eos_ids:
                if 0 <= e < self.g.vocab_size:
                    bits[e >> 3] |= 1 << (e & 7)
        return bits

    @property
    def done(self) -> bool:
        return bool(self.g._lib.gm_state_done(self._s))

    @property
    def can_continue(self) -> bool:
        return bool(self.g._lib.gm_state_can_continue(self._s))

    def __del__(self):
        if getattr(self, "_s", None):
            self.g._lib.gm_state_free(self._s)
            self._s = None
