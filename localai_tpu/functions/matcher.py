"""Grammar matcher binding: GBNF → native PDA → per-step token bitmasks.

Host/device split (the TPU answer to llama.cpp's sampler-integrated grammar):
the native lib (localai_tpu/native/grammar.cpp) tracks the parse state and
produces a [ceil(V/8)]-byte allowed-token bitmask; the engine uploads masks
for constrained slots each step and the jitted sampler applies them before
top-k/top-p (ops/sampling.sample).
"""
from __future__ import annotations

import ctypes
import functools
import json

import numpy as np

from localai_tpu.native import build_and_load


@functools.lru_cache(maxsize=8)
def _lib():
    lib = build_and_load("grammar")
    lib.gm_compile.restype = ctypes.c_void_p
    lib.gm_compile.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.gm_set_vocab.restype = ctypes.c_int
    lib.gm_set_vocab.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    lib.gm_state_new.restype = ctypes.c_void_p
    lib.gm_state_new.argtypes = [ctypes.c_void_p]
    lib.gm_state_accept_token.restype = ctypes.c_int
    lib.gm_state_accept_token.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.gm_state_mask.restype = ctypes.c_int
    lib.gm_state_mask.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
    lib.gm_state_done.restype = ctypes.c_int
    lib.gm_state_done.argtypes = [ctypes.c_void_p]
    lib.gm_state_can_continue.restype = ctypes.c_int
    lib.gm_state_can_continue.argtypes = [ctypes.c_void_p]
    lib.gm_state_free.argtypes = [ctypes.c_void_p]
    lib.gm_free.argtypes = [ctypes.c_void_p]
    return lib


# ------------------------------------------------------------ token texts

_BYTELEVEL_DECODER: dict[str, int] | None = None


def _bytelevel_table() -> dict[str, int]:
    """GPT-2 bytes↔unicode mapping (chars used by ByteLevel tokenizers)."""
    global _BYTELEVEL_DECODER
    if _BYTELEVEL_DECODER is None:
        bs = (list(range(ord("!"), ord("~") + 1))
              + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
        cs = bs[:]
        n = 0
        for b in range(256):
            if b not in bs:
                bs.append(b)
                cs.append(256 + n)
                n += 1
        _BYTELEVEL_DECODER = {chr(c): b for b, c in zip(bs, cs)}
    return _BYTELEVEL_DECODER


def token_texts(tok) -> list[str]:
    """Raw text each vocab id contributes mid-sequence. Handles ByteLevel
    (byte-alphabet remap; tokens with partial UTF-8 → ''), Metaspace (▁→space)
    and WordPiece (## continuation)."""
    hf = tok._tok
    try:
        spec = json.loads(hf.to_str())
        dec = (spec.get("decoder") or {})
        dtypes = [dec.get("type")] + [
            d.get("type") for d in dec.get("decoders", []) or []
        ]
    except Exception:
        dtypes = [None]

    vocab_size = hf.get_vocab_size()
    out = [""] * vocab_size
    table = _bytelevel_table()
    for i in range(vocab_size):
        t = hf.id_to_token(i)
        if t is None:
            continue
        if "ByteLevel" in dtypes:
            try:
                raw = bytes(table[c] for c in t)
            except KeyError:
                out[i] = ""  # special token — never allowed by a grammar
                continue
            try:
                out[i] = raw.decode("utf-8")
            except UnicodeDecodeError:
                out[i] = ""  # partial multi-byte sequence
        elif "Metaspace" in dtypes:
            out[i] = t.replace("▁", " ")
        elif "WordPiece" in dtypes:
            out[i] = t[2:] if t.startswith("##") else t
        else:
            out[i] = t
    return out


class CompiledGrammar:
    """A grammar compiled against a tokenizer's vocabulary."""

    def __init__(self, gbnf: str, token_strings: list[str]):
        lib = _lib()
        err = ctypes.create_string_buffer(256)
        self._g = lib.gm_compile(gbnf.encode(), err, 256)
        if not self._g:
            raise ValueError(f"grammar parse error: {err.value.decode()}")
        self.vocab_size = len(token_strings)
        self.nbytes = (self.vocab_size + 7) // 8
        blob = b"".join(s.encode() for s in token_strings)
        offsets = np.zeros(self.vocab_size + 1, np.int64)
        o = 0
        for i, s in enumerate(token_strings):
            offsets[i] = o
            o += len(s.encode())
        offsets[self.vocab_size] = o
        lib.gm_set_vocab(
            self._g, blob,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            self.vocab_size)
        self._lib = lib

    def state(self) -> "MatcherState":
        return MatcherState(self)

    def __del__(self):
        if getattr(self, "_g", None):
            self._lib.gm_free(self._g)
            self._g = None


class GrammarCache:
    """Per-tokenizer cache of compiled grammars (token_texts is computed
    once; grammar compiles are memoized by text)."""

    def __init__(self, tok):
        self._texts = token_texts(tok)
        self._cache: dict[str, CompiledGrammar] = {}

    def get(self, gbnf: str) -> CompiledGrammar:
        g = self._cache.get(gbnf)
        if g is None:
            g = CompiledGrammar(gbnf, self._texts)
            if len(self._cache) > 32:
                self._cache.clear()
            self._cache[gbnf] = g
        return g


class MatcherState:
    def __init__(self, grammar: CompiledGrammar):
        self.g = grammar
        self._s = grammar._lib.gm_state_new(grammar._g)

    def accept(self, token_id: int) -> bool:
        return bool(self.g._lib.gm_state_accept_token(self._s, token_id))

    def mask_bits(self, eos_ids=()) -> np.ndarray:
        """Allowed-token bitmask [nbytes] u8; EOS bits set iff the grammar
        can complete here."""
        bits = np.zeros(self.g.nbytes, np.uint8)
        self.g._lib.gm_state_mask(
            self._s, bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            self.g.nbytes)
        if self.done:
            for e in eos_ids:
                if 0 <= e < self.g.vocab_size:
                    bits[e >> 3] |= 1 << (e & 7)
        return bits

    @property
    def done(self) -> bool:
        return bool(self.g._lib.gm_state_done(self._s))

    @property
    def can_continue(self) -> bool:
        return bool(self.g._lib.gm_state_can_continue(self._s))

    def __del__(self):
        if getattr(self, "_s", None):
            self.g._lib.gm_state_free(self._s)
            self._s = None
