"""JSON-schema → GBNF grammar generation.

Role of /root/reference/pkg/functions/grammars/json_schema.go:1-258 (schema
converter) + json_mode.go (generic-JSON grammar), re-written for this
framework: the output GBNF is consumed by our own matcher
(localai_tpu/functions/matcher.py + native lib) to build per-step token masks
on the host, the TPU answer to llama.cpp's in-sampler grammar enforcement.

GBNF subset emitted: `rule ::= production`, literals "...", char classes
[a-z0-9], ( ) grouping, | alternation, * + ? repetition.
"""
from __future__ import annotations

import json
import re
from typing import Any

_SPACE = 'space ::= " "?'

# primitive rules shared by every grammar
_PRIMITIVES = {
    "boolean": 'boolean ::= ("true" | "false") space',
    "null": 'null ::= "null" space',
    # raw control chars < 0x20 are NOT legal inside a JSON string — they
    # must ride the escape branch (RFC 8259; json.loads rejects them)
    "string": r'''string ::= "\"" (
  [^"\\\x00-\x1f] |
  "\\" (["\\/bfnrt] | "u" [0-9a-fA-F] [0-9a-fA-F] [0-9a-fA-F] [0-9a-fA-F])
)* "\"" space''',
    "number": 'number ::= ("-"? ([0-9] | [1-9] [0-9]*)) ("." [0-9]+)? '
              '([eE] [-+]? [0-9]+)? space',
    "integer": 'integer ::= ("-"? ([0-9] | [1-9] [0-9]*)) space',
    "value": 'value ::= object | array | string | number | boolean | null',
    "object": 'object ::= "{" space (string ":" space value ("," space string '
              '":" space value)*)? "}" space',
    "array": 'array ::= "[" space (value ("," space value)*)? "]" space',
}

# grammar accepting any JSON object — the `json_object` response_format
# (reference json_mode.go JSONBNF)
JSON_GRAMMAR = "\n".join(
    ["root ::= object", _SPACE] + [
        _PRIMITIVES[k]
        for k in ("object", "array", "string", "number", "boolean", "null",
                  "value")
    ]
)


def _literal(s: str) -> str:
    return json.dumps(s)


def _name_ok(s: str) -> str:
    return re.sub(r"[^a-zA-Z0-9-]", "-", s) or "r"


class _Converter:
    def __init__(self):
        self.rules: dict[str, str] = {"space": _SPACE.split("::= ", 1)[1]}
        self._used_prims: set[str] = set()
        self.defs: dict[str, Any] = {}

    def _add(self, name: str, production: str) -> str:
        base = _name_ok(name)
        key = base
        i = 0
        while key in self.rules and self.rules[key] != production:
            i += 1
            key = f"{base}{i}"
        self.rules[key] = production
        return key

    def _prim(self, name: str) -> str:
        if name not in self.rules:
            self.rules[name] = _PRIMITIVES[name].split("::= ", 1)[1]
            if name in ("value", "object", "array"):
                # the freeform trio is mutually recursive
                for dep in ("object", "array", "string", "number", "boolean",
                            "null", "value"):
                    if dep not in self.rules:
                        self.rules[dep] = _PRIMITIVES[dep].split("::= ", 1)[1]
        return name

    def visit(self, schema: Any, name: str) -> str:
        if schema is True or schema in ({}, None):
            return self._prim("value")
        if "$defs" in schema:
            self.defs.update(schema["$defs"])
        if "$ref" in schema:
            ref = schema["$ref"].split("/")[-1]
            if ref in self.defs:
                return self.visit(self.defs[ref], ref)
            return self._prim("value")
        if "const" in schema:
            return self._add(name, f"{_literal(json.dumps(schema['const']))} space")
        if "enum" in schema:
            alts = " | ".join(_literal(json.dumps(v)) for v in schema["enum"])
            return self._add(name, f"({alts}) space")
        for comb in ("oneOf", "anyOf"):
            if comb in schema:
                subs = [self.visit(s, f"{name}-{i}")
                        for i, s in enumerate(schema[comb])]
                return self._add(name, "(" + " | ".join(subs) + ")")

        t = schema.get("type")
        if isinstance(t, list):
            subs = [self.visit({**schema, "type": ti}, f"{name}-{ti}")
                    for ti in t]
            return self._add(name, "(" + " | ".join(subs) + ")")
        if t == "object" or (t is None and "properties" in schema):
            return self._object(schema, name)
        if t == "array":
            item = self.visit(schema.get("items", True), f"{name}-item")
            prod = f'"[" space ({item} ("," space {item})*)? "]" space'
            return self._add(name, prod)
        if t in ("string",):
            return self._prim("string")
        if t in ("number",):
            return self._prim("number")
        if t in ("integer",):
            return self._prim("integer")
        if t in ("boolean",):
            return self._prim("boolean")
        if t in ("null",):
            return self._prim("null")
        return self._prim("value")

    def _object(self, schema: dict, name: str) -> str:
        props = schema.get("properties", {})
        required = set(schema.get("required", list(props)))
        if not props:
            return self._prim("object")
        # fixed property order (sorted required-first) keeps the grammar
        # regular — same simplification the reference makes
        ordered = [k for k in props if k in required] + [
            k for k in props if k not in required
        ]
        kvs = {}
        for k in ordered:
            sub = self.visit(props[k], f"{name}-{k}")
            kvs[k] = f'{_literal(json.dumps(k))} space ":" space {sub}'

        req = [k for k in ordered if k in required]
        opt = [k for k in ordered if k not in required]
        parts = []
        for i, k in enumerate(req):
            sep = "" if i == 0 else '"," space '
            parts.append(f"{sep}{kvs[k]}")
        if req:
            # a required property always precedes, so every optional is an
            # independent comma-prefixed group
            parts.extend(f'("," space {kvs[k]})?' for k in opt)
        elif opt:
            # all-optional object: alternate on which property appears first
            # (cf. reference json_schema.go) — the first emitted property has
            # no comma, each later one keeps its own
            alts = []
            for i, k in enumerate(opt):
                tail = "".join(f' ("," space {kvs[j]})?' for j in opt[i + 1:])
                alts.append(f"{kvs[k]}{tail}")
            parts.append("(" + " | ".join(alts) + ")?")
        prod = '"{" space ' + " ".join(parts) + ' "}" space'
        return self._add(name, prod)


def json_schema_grammar(schema: dict | str) -> str:
    """Compile a JSON schema into a GBNF grammar with root rule `root`."""
    if isinstance(schema, str):
        schema = json.loads(schema)
    c = _Converter()
    root = c.visit(schema, "root-v")
    lines = [f"root ::= {root} space" if root != "root" else ""]
    for k, v in c.rules.items():
        lines.append(f"{k} ::= {v}")
    return "\n".join(l for l in lines if l)
