"""Function calling + constrained decoding (reference: /root/reference/pkg/
functions — tools → BNF grammar via grammars/json_schema.go:1-258, result
parsing in parse.go)."""
from localai_tpu.functions.grammars import (  # noqa: F401
    json_schema_grammar,
    JSON_GRAMMAR,
)
from localai_tpu.functions.tools import (  # noqa: F401
    NO_ACTION_NAME,
    grammar_for_request,
    parse_tool_calls,
    parse_tool_response,
    tools_schema,
)
