"""Terminal launcher — the GUI-launcher role.

Reference: /root/reference/cmd/launcher (a Fyne systray app wrapping the
server: start/stop, log tail, open the WebUI). A TPU pod has no desktop, so
the launcher here is a small interactive terminal controller around the same
operations: spawn/stop `localai-tpu run`, watch health, tail the server log,
and print the WebUI address.

Programmatic surface (`Launcher`) is separated from the REPL so the control
operations are testable headless.
"""
from __future__ import annotations

import collections
import os
import subprocess
import sys
import threading
import time
import urllib.request


class Launcher:
    def __init__(self, address: str = "127.0.0.1:8080",
                 models_path: str = "models", extra_args: list[str] | None
                 = None, log_lines: int = 400):
        self.address = address
        self.models_path = models_path
        self.extra_args = extra_args or []
        self.proc: subprocess.Popen | None = None
        self.log: collections.deque[str] = collections.deque(
            maxlen=log_lines)
        self._tail_thread: threading.Thread | None = None

    # ------------------------------------------------------------ control

    def start(self) -> bool:
        if self.running:
            return True
        argv = [sys.executable, "-m", "localai_tpu.cli", "run",
                "--address", self.address,
                "--models-path", self.models_path] + self.extra_args
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        parts = [pkg_root] + [p for p in env.get("PYTHONPATH", "").split(
            os.pathsep) if p]
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
        self.proc = subprocess.Popen(
            argv, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        self._tail_thread = threading.Thread(target=self._tail, daemon=True)
        self._tail_thread.start()
        return True

    def _tail(self):
        proc = self.proc
        for line in proc.stdout or []:
            self.log.append(line.rstrip())

    def stop(self, timeout: float = 10.0):
        if self.proc is None:
            return
        self.proc.terminate()
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
        self.proc = None

    @property
    def running(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def healthy(self, timeout: float = 2.0) -> bool:
        try:
            with urllib.request.urlopen(
                    f"http://{self.address}/healthz", timeout=timeout) as r:
                return r.status == 200
        except Exception:
            return False

    def wait_healthy(self, attempts: int = 60, sleep: float = 0.5) -> bool:
        for _ in range(attempts):
            if self.healthy():
                return True
            if not self.running:
                return False
            time.sleep(sleep)
        return False

    def tail(self, n: int = 20) -> list[str]:
        return list(self.log)[-n:]

    @property
    def webui_url(self) -> str:
        return f"http://{self.address}/"


def run_launcher(args) -> int:
    """CLI `launcher`: interactive controller (reference cmd/launcher role)."""
    l = Launcher(address=args.address, models_path=args.models_path)
    print("localai-tpu launcher — commands: "
          "[s]tart [x]stop [l]ogs [h]ealth [w]ebui [q]uit", flush=True)
    if args.autostart:
        print("starting server...", flush=True)
        l.start()
        print("healthy" if l.wait_healthy() else "NOT healthy", flush=True)
    try:
        while True:
            try:
                cmd = input("> ").strip().lower()
            except EOFError:
                break
            if cmd in ("q", "quit", "exit"):
                break
            elif cmd in ("s", "start"):
                l.start()
                print("healthy" if l.wait_healthy() else "NOT healthy",
                      flush=True)
            elif cmd in ("x", "stop"):
                l.stop()
                print("stopped", flush=True)
            elif cmd in ("l", "logs"):
                for line in l.tail(20):
                    print(line, flush=True)
            elif cmd in ("h", "health"):
                print("running" if l.running else "not running",
                      "| healthy" if l.healthy() else "| unhealthy",
                      flush=True)
            elif cmd in ("w", "webui"):
                print(l.webui_url, flush=True)
            elif cmd:
                print("unknown command", flush=True)
    finally:
        l.stop()
    return 0
