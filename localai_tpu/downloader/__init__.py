from localai_tpu.downloader.uri import download_file, resolve_uri  # noqa: F401
