"""Artifact download with URI schemes + sha256 verification.

Reference: /root/reference/pkg/downloader/uri.go:26-163 — schemes
`huggingface://`, `github:`, http(s), with progress callbacks and checksum
verify. TPU build adds `file://` (local/offline galleries, also the test
fixture path; this container has zero egress, so network schemes are code
paths verified by unit tests against local servers/files).
"""
from __future__ import annotations

import hashlib
import os
import shutil
import urllib.parse


def resolve_uri(uri: str) -> str:
    """Normalize gallery URI schemes to a fetchable URL/path."""
    if uri.startswith("huggingface://") or uri.startswith("hf://"):
        # huggingface://owner/repo/file/path → resolve URL (uri.go:52-90)
        rest = uri.split("://", 1)[1]
        parts = rest.split("/")
        if len(parts) < 3:
            raise ValueError(f"bad huggingface uri {uri!r}")
        repo = "/".join(parts[:2])
        fname = "/".join(parts[2:])
        return f"https://huggingface.co/{repo}/resolve/main/{fname}"
    if uri.startswith("github:"):
        # github:owner/repo/path[@branch]
        rest = uri.split(":", 1)[1].lstrip("/")
        branch = "main"
        if "@" in rest:
            rest, branch = rest.rsplit("@", 1)
        parts = rest.split("/")
        owner, repo, path = parts[0], parts[1], "/".join(parts[2:])
        return (f"https://raw.githubusercontent.com/{owner}/{repo}/"
                f"{branch}/{path}")
    return uri


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download_file(uri: str, dest: str, *, sha256: str | None = None,
                  progress=None, timeout: float = 600.0) -> str:
    """Fetch `uri` to `dest` (skips when already present with matching
    sha256 — uri.go's cache behavior). Returns dest."""
    uri = resolve_uri(uri)
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)

    if os.path.exists(dest) and sha256 and _sha256(dest) == sha256:
        return dest

    parsed = urllib.parse.urlparse(uri)
    if parsed.scheme in ("", "file"):
        src = parsed.path if parsed.scheme == "file" else uri
        shutil.copyfile(src, dest)
    elif parsed.scheme == "oci":
        # oci://host/repo:tag → unpack the image INTO dest (a directory);
        # layers are digest-verified in transit, but a tree has no single
        # sha256 — honor the caller's pin by refusing, not skipping
        if sha256:
            raise ValueError("sha256 pinning is not supported for oci:// "
                             "(layer digests are verified instead)")
        from localai_tpu.oci import pull_image

        return pull_image(uri, dest, progress=progress)
    elif parsed.scheme == "ollama":
        # ollama://model:tag → the model blob becomes the dest file
        from localai_tpu.oci import pull_ollama_model

        pull_ollama_model(uri, dest, progress=progress)
        if sha256:
            actual = _sha256(dest)
            if actual != sha256:
                os.unlink(dest)
                raise ValueError(f"sha256 mismatch for {uri}: want {sha256}, "
                                 f"got {actual}")
        return dest
    elif parsed.scheme == "ocifile":
        if sha256:
            raise ValueError("sha256 pinning is not supported for ocifile://")
        from localai_tpu.oci import unpack_oci_file

        return unpack_oci_file(parsed.netloc + parsed.path, dest)
    elif parsed.scheme in ("http", "https"):
        import requests

        with requests.get(uri, stream=True, timeout=timeout) as r:
            r.raise_for_status()
            total = int(r.headers.get("content-length") or 0)
            done = 0
            with open(dest + ".part", "wb") as f:
                for chunk in r.iter_content(1 << 20):
                    f.write(chunk)
                    done += len(chunk)
                    if progress:
                        progress(done, total)
        os.replace(dest + ".part", dest)
    else:
        raise ValueError(f"unsupported scheme {parsed.scheme!r} in {uri!r}")

    if sha256:
        actual = _sha256(dest)
        if actual != sha256:
            os.unlink(dest)
            raise ValueError(
                f"sha256 mismatch for {uri}: want {sha256}, got {actual}")
    return dest
