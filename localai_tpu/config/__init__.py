from localai_tpu.config.app_config import AppConfig  # noqa: F401
from localai_tpu.config.model_config import (  # noqa: F401
    ModelConfig,
    ModelConfigLoader,
    PredictionParams,
)
