"""Application-wide configuration (reference ApplicationConfig,
/root/reference/core/config/application_config.go:14 + CLI flag surface
core/cli/run.go:24-77). Layering: CLI flags > env (LOCALAI_*) > defaults."""
from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass
class AppConfig:
    address: str = "127.0.0.1:8080"
    models_path: str = "models"
    backends_path: str = ""          # installed external backends dir
                                     # (also spawn cwd for backend procs)
    backend_galleries: list[str] = dataclasses.field(default_factory=list)
                                     # backend registry index URIs
    context_size: int = 0
    parallel_requests: int = 4       # default engine slots per model
    tensor_parallel: int = 0         # default TP degree ('model' mesh axis)
                                     # for models without their own mesh:
                                     # block; 0 = backend auto-TP
    api_keys: list[str] = dataclasses.field(default_factory=list)
    federation_token: str = ""       # shared-token HMAC (federation/auth.py);
                                     # a valid X-LocalAI-Federation signature
                                     # authorizes like an API key
    cors: bool = False
    single_active_backend: bool = False
    watchdog_idle_timeout: float = 0.0   # seconds; 0 = disabled
    watchdog_busy_timeout: float = 0.0
    # --- resilience knobs (ISSUE 4) ---
    request_timeout: float = 600.0   # per-request deadline budget (s); the
                                     # X-Request-Timeout header can lower it
    retry_budget: int = 1            # supervised retries after the first
                                     # attempt (dead/UNAVAILABLE backends)
    breaker_threshold: int = 3       # consecutive failures → breaker opens
    breaker_cooldown: float = 15.0   # seconds open before a half-open probe
    queue_depth: int = 8             # per-model bounded wait queue; beyond
                                     # in-flight+queue → 429 + Retry-After
    drain_timeout: float = 30.0      # graceful-shutdown hard deadline (s)
    preempt_grace: float = 0.0       # spill-drain grace (s): how long a
                                     # preempted backend lets live slots run
                                     # before force-freezing them into
                                     # ResumeTokens (ISSUE 19)
    spawn_retries: int = 2           # fresh-port respawns when the child
                                     # dies before health (port TOCTOU)
    spawn_timeout: float = 120.0     # health budget per spawn attempt (s)
    kv_window: int = 0               # app-default KV retention window in
                                     # tokens (engine/kvtier.py); 0 = full
                                     # KV. A per-model YAML kv_policy wins.
    kv_sinks: int = 0                # attention-sink tokens kept alongside
                                     # the window (only with kv_window > 0)
    kv_host_bytes: int = 0           # app-default host-RAM KV spill tier
                                     # budget (engine/kvhost.py); evicted
                                     # device blocks are kept in host RAM
                                     # and re-admitted on prefix hits.
                                     # 0 disables; per-model YAML wins.
    preload_models: list[str] = dataclasses.field(default_factory=list)
    log_level: str = "info"
    machine_tag: str = ""
    max_request_bytes: int = 256 * 1024 * 1024   # body limit (app.go:45 role)

    @classmethod
    def from_env(cls, **overrides) -> "AppConfig":
        def env(name, cast=str, default=None):
            v = os.environ.get(f"LOCALAI_{name}")
            return cast(v) if v is not None else default

        cfg = cls()
        for field, cast in [("address", str), ("models_path", str),
                            ("context_size", int), ("parallel_requests", int),
                            ("tensor_parallel", int), ("machine_tag", str),
                            ("request_timeout", float), ("retry_budget", int),
                            ("breaker_threshold", int),
                            ("breaker_cooldown", float),
                            ("queue_depth", int), ("drain_timeout", float),
                            ("preempt_grace", float),
                            ("spawn_retries", int), ("spawn_timeout", float),
                            ("kv_window", int), ("kv_sinks", int),
                            ("kv_host_bytes", int)]:
            v = env(field.upper(), cast)
            if v is not None:
                setattr(cfg, field, v)
        keys = env("API_KEY", str)
        if keys:
            cfg.api_keys = [k.strip() for k in keys.split(",") if k.strip()]
        tok = env("FEDERATION_TOKEN", str)
        if tok:
            cfg.federation_token = tok
        for k, v in overrides.items():
            if v is not None and hasattr(cfg, k):
                setattr(cfg, k, v)
        return cfg
