"""Per-model YAML configuration — the ModelConfig schema.

Mirrors the reference's YAML surface (field names included) so existing model
YAMLs translate directly: /root/reference/core/config/model_config.go:30-83
(ModelConfig), :178-240 (LLMConfig knobs), with prediction defaults nested
under `parameters:` exactly like the reference. Multi-model single files
(YAML list) are supported (model_config_loader.go:163).
"""
from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Any

import yaml

log = logging.getLogger("localai_tpu")


@dataclasses.dataclass
class PredictionParams:
    """Request-level defaults a model YAML can pin (reference
    `parameters:` block + OpenAIRequest merge, schema/prediction.go:4-29)."""
    model: str = ""                  # checkpoint dir (relative to models path)
    temperature: float | None = None
    top_k: int | None = None
    top_p: float | None = None
    min_p: float | None = None
    typical_p: float | None = None
    repeat_penalty: float | None = None
    presence_penalty: float | None = None
    frequency_penalty: float | None = None
    seed: int | None = None
    max_tokens: int | None = None
    ignore_eos: bool | None = None
    logit_bias: dict[int, float] | None = None
    language: str | None = None      # transcription default

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PredictionParams":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class TemplateConfig:
    """Prompt template names/inline bodies (reference TemplateConfig,
    model_config.go:249-283). `use_tokenizer_template` routes chat through
    the HF tokenizer's chat template instead."""
    chat: str = ""
    chat_message: str = ""
    completion: str = ""
    edit: str = ""
    use_tokenizer_template: bool = True


# Reference template fields this port intentionally does NOT render: tool
# schemas and multimodal markers go through the tokenizer chat template
# instead, and reply_prefix is never applied. A YAML using them must say so
# out loud (VERDICT Weak #8) — silent dropping made ported configs
# misbehave invisibly. key → what actually happens here.
_UNSUPPORTED_TEMPLATE_FIELDS = {
    "function": "tool schemas render via the tokenizer chat template's "
                "`tools` variable, not a Go template",
    "functions": "tool schemas render via the tokenizer chat template's "
                 "`tools` variable, not a Go template",
    "multimodal": "image placeholders expand engine-side "
                  "(<image> markers), not via a template",
    "reply_prefix": "reply prefixes are not applied",
    "join_chat_messages_by_character": "message joining is fixed to newline",
    "jinja_template": "the HF tokenizer's own chat template is used; "
                      "set use_tokenizer_template instead",
}


@dataclasses.dataclass
class MeshShape:
    data: int = 0    # 0 = auto
    model: int = 0


@dataclasses.dataclass
class Pipeline:
    """Model composition for /v1/realtime voice sessions (reference
    ModelConfig.Pipeline, model_config.go:135-140)."""
    vad: str = ""
    transcription: str = ""
    llm: str = ""
    tts: str = ""


@dataclasses.dataclass
class ModelConfig:
    name: str = ""
    backend: str = "llm"             # backend role (llm|whisper|store|...)
    description: str = ""
    usage: str = ""
    parameters: PredictionParams = dataclasses.field(default_factory=PredictionParams)
    template: TemplateConfig = dataclasses.field(default_factory=TemplateConfig)
    context_size: int = 0            # 0 = model default (capped 2048)
    parallel: int = 0                # engine slots; 0 = app default
    embeddings: bool = False
    rerank: bool = False
    dtype: str = ""                  # bfloat16|float32 (engine compute dtype)
    stopwords: list[str] = dataclasses.field(default_factory=list)
    prefill_buckets: list[int] = dataclasses.field(default_factory=list)
    mesh: MeshShape = dataclasses.field(default_factory=MeshShape)
    grammar: str = ""
    draft_model: str = ""            # speculative decoding draft checkpoint
    n_draft: int = 0                 # draft tokens per step (0 = default 4)
    cache_type_k: str = ""           # KV cache storage: ""|bf16|int8|q8_0
    cache_type_v: str = ""           # (reference cache_type_k/v YAML keys)
    kv_pages: int = 0                # paged KV pool size in 128-token blocks
                                     # (0 = dense per-slot cache)
    kv_policy: str = ""              # KV lifecycle tier (engine/kvtier.py):
                                     # ""|"full"|"sink_window(sinks=N,
                                     # window=W[, quantize_cold=true])"
    kv_cold_pages: int = 0           # int8 cold pool size in 128-token
                                     # blocks (quantize_cold policies)
    kv_host_bytes: int = 0           # host-RAM KV spill tier budget in
                                     # bytes (engine/kvhost.py); 0 = app
                                     # default (--kv-host-bytes)
    mcp: dict = dataclasses.field(default_factory=dict)
                                     # MCP servers {servers: [...], stdio:
                                     # [...]} (reference config.MCP block)
    agent: dict = dataclasses.field(default_factory=dict)
                                     # agent loop knobs {max_iterations: N}
    pipeline: Pipeline = dataclasses.field(default_factory=Pipeline)
    known_usecases: list[str] = dataclasses.field(default_factory=list)
    # reference template fields the YAML used but this port ignores
    # (populated by from_dict; the loader logs one structured warning)
    unsupported_template_fields: list[str] = dataclasses.field(
        default_factory=list)
    # file this config came from (set by the loader)
    config_file: str = ""

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ModelConfig":
        d = dict(d)
        params = d.pop("parameters", {}) or {}
        tmpl = d.pop("template", {}) or {}
        mesh = d.pop("mesh", {}) or {}
        pipe = d.pop("pipeline", {}) or {}
        known = {f.name for f in dataclasses.fields(cls)}
        cfg = cls(**{k: v for k, v in d.items() if k in known})
        cfg.parameters = PredictionParams.from_dict(params)
        cfg.pipeline = Pipeline(**{
            k: v for k, v in pipe.items()
            if k in {f.name for f in dataclasses.fields(Pipeline)}
        })
        cfg.template = TemplateConfig(**{
            k: v for k, v in tmpl.items()
            if k in {f.name for f in dataclasses.fields(TemplateConfig)}
        })
        cfg.unsupported_template_fields = sorted(
            k for k, v in tmpl.items()
            if k in _UNSUPPORTED_TEMPLATE_FIELDS and v not in (None, "", {}))
        if cfg.unsupported_template_fields:
            log.warning(
                "model %r: unsupported template field(s) ignored: %s",
                cfg.name or "<unnamed>",
                "; ".join(f"{k} ({_UNSUPPORTED_TEMPLATE_FIELDS[k]})"
                          for k in cfg.unsupported_template_fields))
        cfg.mesh = MeshShape(**{k: v for k, v in mesh.items()
                                if k in ("data", "model")})
        return cfg

    def model_dir(self, models_path: str) -> str:
        m = self.parameters.model or self.name
        return m if os.path.isabs(m) else os.path.join(models_path, m)

    def validate(self) -> list[str]:
        errs = []
        if not self.name:
            errs.append("missing name")
        if self.context_size < 0:
            errs.append("context_size < 0")
        if any(b <= 0 for b in self.prefill_buckets):
            errs.append("non-positive prefill bucket")
        return errs


class ModelConfigLoader:
    """Scans a models directory for YAML configs; hot-rescans on demand
    (reference model_config_loader.go:118-373 + per-request rescan
    middleware/request.go:87-117). Bare checkpoint dirs (config.json present)
    are auto-registered so `models_path/<name>` works without YAML."""

    def __init__(self, models_path: str):
        self.models_path = models_path
        self._configs: dict[str, ModelConfig] = {}
        self._lock = threading.Lock()
        self.reload()

    def reload(self):
        configs: dict[str, ModelConfig] = {}
        if os.path.isdir(self.models_path):
            for fname in sorted(os.listdir(self.models_path)):
                path = os.path.join(self.models_path, fname)
                if fname.endswith((".yaml", ".yml")) and os.path.isfile(path):
                    for cfg in self._load_file(path):
                        configs[cfg.name] = cfg
                elif os.path.isdir(path) and os.path.exists(
                        os.path.join(path, "config.json")):
                    if fname not in configs:
                        c = ModelConfig(name=fname)
                        c.parameters.model = fname
                        configs.setdefault(fname, c)
        with self._lock:
            self._configs = configs

    @staticmethod
    def _load_file(path: str) -> list[ModelConfig]:
        with open(path) as f:
            doc = yaml.safe_load(f)
        docs = doc if isinstance(doc, list) else [doc]
        out = []
        for d in docs:
            if not isinstance(d, dict):
                continue
            cfg = ModelConfig.from_dict(d)
            cfg.config_file = path
            if not cfg.validate():
                out.append(cfg)
        return out

    def get(self, name: str) -> ModelConfig | None:
        with self._lock:
            cfg = self._configs.get(name)
        if cfg is None:
            self.reload()  # hot-pickup of newly dropped YAMLs/dirs
            with self._lock:
                cfg = self._configs.get(name)
        return cfg

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._configs)

    def all(self) -> list[ModelConfig]:
        with self._lock:
            return [self._configs[k] for k in sorted(self._configs)]

    def first(self) -> ModelConfig | None:
        names = self.names()
        return self.get(names[0]) if names else None
