"""Minimal OCI distribution client: pull + unpack images and ollama blobs.

Reference: /root/reference/pkg/oci/{image.go,ollama.go,blob.go,tarball.go}
(go-containerregistry) — backends ship as OCI artifacts
(`oci://quay.io/...`), models can come from ollama's registry
(`ollama://gemma:2b`), and `ocifile://` unpacks a local OCI-layout tarball.

This is a dependency-free implementation of the distribution spec's pull
side: token-auth handshake (WWW-Authenticate Bearer), manifest negotiation
(OCI index / docker manifest-list → platform manifest → layers), blob fetch
with sha256 verification, and path-confined tar extraction (symlink/.. tar
members are rejected — the same traversal class the model gallery guards).

Zero-egress note: this container cannot reach real registries; every code
path here is exercised by tests against a local in-process registry
(tests/test_oci.py).
"""
from __future__ import annotations

import gzip
import hashlib
import io
import json
import os
import tarfile
import urllib.parse
import urllib.request

MT_OCI_INDEX = "application/vnd.oci.image.index.v1+json"
MT_OCI_MANIFEST = "application/vnd.oci.image.manifest.v1+json"
MT_DOCKER_LIST = "application/vnd.docker.distribution.manifest.list.v2+json"
MT_DOCKER_MANIFEST = "application/vnd.docker.distribution.manifest.v2+json"
_ACCEPT = ", ".join((MT_OCI_MANIFEST, MT_OCI_INDEX, MT_DOCKER_MANIFEST,
                     MT_DOCKER_LIST))

OLLAMA_REGISTRY = "registry.ollama.ai"
_OLLAMA_MODEL_MT = "application/vnd.ollama.image.model"


class OCIError(RuntimeError):
    pass


def parse_ref(ref: str):
    """'oci://host/repo:tag' → (host, repo, tag). Default tag 'latest';
    bare repos ('oci://host/name') keep registry semantics."""
    body = ref.split("://", 1)[1] if "://" in ref else ref
    host, _, rest = body.partition("/")
    if not rest:
        raise OCIError(f"bad OCI reference {ref!r} (no repository)")
    if "@" in rest:                       # digest pin
        repo, tag = rest.split("@", 1)
    elif ":" in rest.rsplit("/", 1)[-1]:
        repo, tag = rest.rsplit(":", 1)
    else:
        repo, tag = rest, "latest"
    return host, repo, tag


def parse_ollama_ref(ref: str):
    """'ollama://gemma:2b' → (registry.ollama.ai, library/gemma, 2b)."""
    body = ref.split("://", 1)[1]
    if ":" in body:
        repo, tag = body.rsplit(":", 1)
    else:
        repo, tag = body, "latest"
    if "/" not in repo:
        repo = f"library/{repo}"
    return OLLAMA_REGISTRY, repo, tag


class Registry:
    """One registry endpoint with lazy bearer-token auth."""

    def __init__(self, host: str, *, insecure: bool | None = None,
                 timeout: float = 600.0):
        if insecure is None:
            # localhost registries (tests, sidecars) default to plain http
            insecure = host.startswith(("localhost", "127.0.0.1"))
        self.base = f"{'http' if insecure else 'https'}://{host}"
        self.timeout = timeout
        self._token: str | None = None

    def _request(self, url: str, headers: dict) -> "urllib.request.addinfourl":
        req = urllib.request.Request(url, headers=headers)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        try:
            return urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            if e.code == 401 and self._token is None:
                self._authenticate(e.headers.get("WWW-Authenticate", ""))
                return self._request(url, headers)
            raise

    def _authenticate(self, challenge: str):
        """Bearer realm="...",service="...",scope="..." token dance."""
        if not challenge.lower().startswith("bearer "):
            raise OCIError(f"unsupported auth challenge {challenge!r}")
        fields = dict(
            kv.split("=", 1) for kv in challenge[7:].split(",") if "=" in kv)
        fields = {k.strip(): v.strip().strip('"') for k, v in fields.items()}
        realm = fields.pop("realm", None)
        if not realm:
            raise OCIError("auth challenge without realm")
        q = urllib.parse.urlencode(
            {k: v for k, v in fields.items() if k in ("service", "scope")})
        with urllib.request.urlopen(f"{realm}?{q}",
                                    timeout=self.timeout) as r:
            tok = json.load(r)
        self._token = tok.get("token") or tok.get("access_token")
        if not self._token:
            raise OCIError("token endpoint returned no token")

    def manifest(self, repo: str, tag: str) -> dict:
        url = f"{self.base}/v2/{repo}/manifests/{tag}"
        with self._request(url, {"Accept": _ACCEPT}) as r:
            m = json.load(r)
        mt = m.get("mediaType", "")
        if mt in (MT_OCI_INDEX, MT_DOCKER_LIST) or "manifests" in m:
            digest = _pick_platform(m["manifests"])
            with self._request(f"{self.base}/v2/{repo}/manifests/{digest}",
                               {"Accept": _ACCEPT}) as r:
                m = json.load(r)
        return m

    def blob(self, repo: str, digest: str) -> bytes:
        url = f"{self.base}/v2/{repo}/blobs/{digest}"
        with self._request(url, {}) as r:
            data = r.read()
        algo, _, want = digest.partition(":")
        got = hashlib.new(algo, data).hexdigest()
        if got != want:
            raise OCIError(f"blob digest mismatch: want {want}, got {got}")
        return data

    def blob_to_file(self, repo: str, digest: str, dest: str,
                     progress=None) -> str:
        url = f"{self.base}/v2/{repo}/blobs/{digest}"
        algo, _, want = digest.partition(":")
        h = hashlib.new(algo)
        done = 0
        with self._request(url, {}) as r, open(dest, "wb") as out:
            total = int(r.headers.get("Content-Length") or 0)
            for chunk in iter(lambda: r.read(1 << 20), b""):
                h.update(chunk)
                out.write(chunk)
                done += len(chunk)
                if progress:
                    progress(done, total)
        if h.hexdigest() != want:
            os.unlink(dest)
            raise OCIError(f"blob digest mismatch for {digest}")
        return dest


def _pick_platform(manifests: list[dict]) -> str:
    want_arch = {"x86_64": "amd64", "aarch64": "arm64"}.get(
        os.uname().machine, os.uname().machine)
    for m in manifests:
        plat = m.get("platform") or {}
        if plat.get("os", "linux") == "linux" and \
                plat.get("architecture") == want_arch:
            return m["digest"]
    return manifests[0]["digest"]


def _safe_extract(tf: tarfile.TarFile, dest: str):
    """Path-confined extraction; strips docker whiteout files."""
    root = os.path.realpath(dest)
    for member in tf.getmembers():
        name = member.name
        while name.startswith("./"):
            name = name[2:]
        name = name.lstrip("/")
        base = os.path.basename(name)
        if base.startswith(".wh."):      # overlayfs whiteout: delete target
            victim = os.path.join(dest, os.path.dirname(name),
                                  base[len(".wh."):])
            if os.path.realpath(victim).startswith(root + os.sep):
                if os.path.isdir(victim):
                    import shutil

                    shutil.rmtree(victim, ignore_errors=True)
                elif os.path.exists(victim):
                    os.unlink(victim)
            continue
        target = os.path.realpath(os.path.join(dest, name))
        if not (target == root or target.startswith(root + os.sep)):
            raise OCIError(f"tar member escapes destination: {member.name!r}")
        if member.issym() or member.islnk():
            link_target = os.path.realpath(
                os.path.join(dest, os.path.dirname(name), member.linkname))
            if not link_target.startswith(root + os.sep):
                raise OCIError(f"tar link escapes destination: {member.name!r}")
        member.name = name
        tf.extract(member, dest, filter="data")


def _extract_layer(data: bytes, mt: str, dest: str):
    if "gzip" in mt or data[:2] == b"\x1f\x8b":
        data = gzip.decompress(data)
    with tarfile.open(fileobj=io.BytesIO(data)) as tf:
        _safe_extract(tf, dest)


def _extract_layer_file(path: str, dest: str):
    # 'r:*' sniffs gzip/plain and decompresses as a stream — no in-memory copy
    with tarfile.open(path, "r:*") as tf:
        _safe_extract(tf, dest)


def pull_image(ref: str, dest: str, *, progress=None,
               insecure: bool | None = None) -> str:
    """Pull `oci://host/repo:tag` and unpack all layers into `dest`. Layers
    stream to a temp file (digest-verified incrementally) so a multi-GB
    backend image never lives in RAM."""
    import tempfile

    host, repo, tag = parse_ref(ref)
    reg = Registry(host, insecure=insecure)
    manifest = reg.manifest(repo, tag)
    os.makedirs(dest, exist_ok=True)
    layers = manifest.get("layers") or []
    for i, layer in enumerate(layers):
        tmp = tempfile.NamedTemporaryFile(dir=dest, suffix=".layer",
                                          delete=False)
        tmp.close()
        try:
            reg.blob_to_file(repo, layer["digest"], tmp.name)
            _extract_layer_file(tmp.name, dest)
        finally:
            if os.path.exists(tmp.name):
                os.unlink(tmp.name)
        if progress:
            progress(i + 1, len(layers))
    return dest


def pull_ollama_model(ref: str, dest_file: str, *, progress=None,
                      insecure: bool | None = None) -> str:
    """Pull `ollama://model:tag`'s GGUF model blob to `dest_file`
    (reference pkg/oci/ollama.go — the model layer is the payload)."""
    host, repo, tag = parse_ollama_ref(ref)
    reg = Registry(host, insecure=insecure)
    manifest = reg.manifest(repo, tag)
    model = next((l for l in manifest.get("layers", [])
                  if l.get("mediaType") == _OLLAMA_MODEL_MT), None)
    if model is None:
        raise OCIError(f"{ref}: manifest has no model layer")
    return reg.blob_to_file(repo, model["digest"], dest_file,
                            progress=progress)


def unpack_oci_file(tar_path: str, dest: str) -> str:
    """`ocifile://` — unpack a local OCI-layout tarball's first manifest's
    layers into dest (reference pkg/oci/tarball.go)."""
    os.makedirs(dest, exist_ok=True)
    with tarfile.open(tar_path) as tf:
        def read(name):
            f = tf.extractfile(name)
            if f is None:
                raise OCIError(f"{tar_path}: missing {name}")
            return f.read()

        index = json.loads(read("index.json"))
        mdig = index["manifests"][0]["digest"].replace(":", "/")
        manifest = json.loads(read(f"blobs/{mdig}"))
        for layer in manifest.get("layers", []):
            data = read("blobs/" + layer["digest"].replace(":", "/"))
            _extract_layer(data, layer.get("mediaType", ""), dest)
    return dest
