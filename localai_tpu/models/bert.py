"""BERT-family encoder — the universal embeddings role.

Reference analog: the transformers backend's SentenceTransformer /
AutoModel embeddings path (/root/reference/backend/python/transformers/
backend.py:37,179-221,323): any BERT-class HF checkpoint serves
`/v1/embeddings`. Here the encoder is JAX: layers stacked on a leading axis
and run with lax.scan (one compiled layer body), bidirectional attention with
a padding mask, masked-mean pooling + L2 norm (the sentence-transformers
default recipe).

Covers BertModel / RobertaModel / XLMRobertaModel checkpoints (Roberta's only
structural deltas: position ids start at pad+1=2 and token_type collapses to
a single row).
"""
from __future__ import annotations

import dataclasses
import json
import os
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.ops.norms import layer_norm

BERT_FAMILY = {
    "BertModel": {},
    "BertForMaskedLM": {},
    "RobertaModel": {"position_offset": 2},
    "XLMRobertaModel": {"position_offset": 2},
    "CamembertModel": {"position_offset": 2},
}


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_position: int = 512
    type_vocab_size: int = 2
    ln_eps: float = 1e-12
    position_offset: int = 0      # Roberta: padding_idx+1
    dtype: str = "float32"        # embeddings are accuracy-sensitive; f32
                                  # default, bf16 opt-in

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


def load_bert_config(model_dir: str, dtype: str | None = None) -> BertConfig:
    with open(os.path.join(model_dir, "config.json")) as f:
        hf: dict[str, Any] = json.load(f)
    arch = (hf.get("architectures") or ["BertModel"])[0]
    if arch not in BERT_FAMILY:
        raise ValueError(f"unsupported encoder architecture {arch!r}")
    extra = BERT_FAMILY[arch]
    return BertConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        max_position=hf.get("max_position_embeddings", 512),
        type_vocab_size=hf.get("type_vocab_size", 2),
        ln_eps=hf.get("layer_norm_eps", 1e-12),
        position_offset=extra.get("position_offset", 0),
        dtype=dtype or "float32",
    )


def is_bert_dir(model_dir: str) -> bool:
    """Peek config.json: does this checkpoint want the encoder path?"""
    try:
        with open(os.path.join(model_dir, "config.json")) as f:
            arch = (json.load(f).get("architectures") or [""])[0]
        return arch in BERT_FAMILY
    except (OSError, ValueError):
        return False


# ---------------------------------------------------------------- params

def init_bert_params(cfg: BertConfig, key, dtype=None):
    """Random init mirroring load_bert_params' layout (tests, synthetic)."""
    dtype = dtype or cfg.jdtype
    h, L, I = cfg.hidden_size, cfg.num_layers, cfg.intermediate_size
    ks = jax.random.split(key, 8)

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    layers = {
        "wqkv": w(ks[0], (L, h, 3 * h), h),
        "bqkv": jnp.zeros((L, 3 * h), dtype),
        "wo": w(ks[1], (L, h, h), h),
        "bo": jnp.zeros((L, h), dtype),
        "ln1_w": jnp.ones((L, h), dtype), "ln1_b": jnp.zeros((L, h), dtype),
        "w_in": w(ks[2], (L, h, I), h), "b_in": jnp.zeros((L, I), dtype),
        "w_out": w(ks[3], (L, I, h), I), "b_out": jnp.zeros((L, h), dtype),
        "ln2_w": jnp.ones((L, h), dtype), "ln2_b": jnp.zeros((L, h), dtype),
    }
    return {
        "word_emb": w(ks[4], (cfg.vocab_size, h), h),
        "pos_emb": w(ks[5], (cfg.max_position, h), h),
        "type_emb": w(ks[6], (cfg.type_vocab_size, h), h),
        "emb_ln_w": jnp.ones((h,), dtype), "emb_ln_b": jnp.zeros((h,), dtype),
        "layers": layers,
    }


def load_bert_params(model_dir: str, cfg: BertConfig, dtype=None):
    """HF safetensors → stacked pytree ([out,in] torch weights transposed to
    the [in,out] matmul layout; q/k/v fused into one wqkv)."""
    from localai_tpu.engine.loader import _TensorReader, _is_synthetic

    if _is_synthetic(model_dir):
        return init_bert_params(cfg, jax.random.PRNGKey(0), dtype)
    dtype = dtype or cfg.jdtype
    r = _TensorReader(model_dir)
    names = set(r.index.keys())
    pre = "bert." if any(n.startswith("bert.") for n in names) else ""

    def t(name):
        return np.asarray(r.get(pre + name), np.float32)

    def lin(name):  # torch Linear → ([in, out] weight, bias)
        return t(name + ".weight").T, t(name + ".bias")

    L = cfg.num_layers
    stk: dict[str, list] = {k: [] for k in (
        "wqkv", "bqkv", "wo", "bo", "ln1_w", "ln1_b",
        "w_in", "b_in", "w_out", "b_out", "ln2_w", "ln2_b")}
    for i in range(L):
        p = f"encoder.layer.{i}."
        qw, qb = lin(p + "attention.self.query")
        kw, kb = lin(p + "attention.self.key")
        vw, vb = lin(p + "attention.self.value")
        stk["wqkv"].append(np.concatenate([qw, kw, vw], axis=1))
        stk["bqkv"].append(np.concatenate([qb, kb, vb]))
        ow, ob = lin(p + "attention.output.dense")
        stk["wo"].append(ow)
        stk["bo"].append(ob)
        stk["ln1_w"].append(t(p + "attention.output.LayerNorm.weight"))
        stk["ln1_b"].append(t(p + "attention.output.LayerNorm.bias"))
        iw, ib = lin(p + "intermediate.dense")
        stk["w_in"].append(iw)
        stk["b_in"].append(ib)
        dw, db = lin(p + "output.dense")
        stk["w_out"].append(dw)
        stk["b_out"].append(db)
        stk["ln2_w"].append(t(p + "output.LayerNorm.weight"))
        stk["ln2_b"].append(t(p + "output.LayerNorm.bias"))
    params = {
        "word_emb": t("embeddings.word_embeddings.weight"),
        "pos_emb": t("embeddings.position_embeddings.weight"),
        "type_emb": t("embeddings.token_type_embeddings.weight"),
        "emb_ln_w": t("embeddings.LayerNorm.weight"),
        "emb_ln_b": t("embeddings.LayerNorm.bias"),
        "layers": {k: np.stack(v) for k, v in stk.items()},
    }
    r.close() if hasattr(r, "close") else None
    return jax.tree_util.tree_map(lambda a: jnp.asarray(a, dtype), params)


# ---------------------------------------------------------------- forward

def bert_encode(params, cfg: BertConfig, tokens, lengths):
    """tokens [B, S] i32, lengths [B] → final hidden states [B, S, H]."""
    b, s = tokens.shape
    h, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim
    pos = jnp.arange(s) + cfg.position_offset
    x = (params["word_emb"][tokens] + params["pos_emb"][pos][None]
         + params["type_emb"][0][None, None])
    x = layer_norm(x.astype(jnp.float32), params["emb_ln_w"],
                   params["emb_ln_b"], cfg.ln_eps).astype(cfg.jdtype)
    # bidirectional padding mask: [B, 1, 1, S]
    valid = (jnp.arange(s)[None, :] < lengths[:, None])
    bias = jnp.where(valid, 0.0, -1e9)[:, None, None, :].astype(jnp.float32)
    scale = hd ** -0.5

    def layer(x, lp):
        qkv = x @ lp["wqkv"] + lp["bqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
        att = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
        att = jax.nn.softmax(att * scale + bias, axis=-1).astype(x.dtype)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", att, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h)
        x = layer_norm((x + ctx @ lp["wo"] + lp["bo"]).astype(jnp.float32),
                       lp["ln1_w"], lp["ln1_b"], cfg.ln_eps).astype(x.dtype)
        y = jax.nn.gelu(x @ lp["w_in"] + lp["b_in"], approximate=False)
        x = layer_norm((x + y @ lp["w_out"] + lp["b_out"]).astype(jnp.float32),
                       lp["ln2_w"], lp["ln2_b"], cfg.ln_eps).astype(x.dtype)
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    return x


def bert_pooled(params, cfg: BertConfig, tokens, lengths, normalize=True):
    """Masked-mean pooled sentence embeddings [B, H] f32 (the
    sentence-transformers mean-pooling recipe the reference applies,
    transformers/backend.py:37)."""
    b, s = tokens.shape
    x = bert_encode(params, cfg, tokens, lengths).astype(jnp.float32)
    mask = (jnp.arange(s)[None, :] < lengths[:, None]).astype(jnp.float32)
    pooled = (x * mask[..., None]).sum(1) / jnp.maximum(
        mask.sum(1)[:, None], 1.0)
    if normalize:
        pooled = pooled / jnp.maximum(
            jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)
    return pooled


from localai_tpu.engine.embedder import Embedder as _Embedder


class BertEmbedder(_Embedder):
    """Bucketed, jitted embeddings runner — engine.Embedder with the encoder
    swapped for bert_pooled (_bucket/embed inherited)."""

    def __init__(self, cfg: BertConfig, params, *,
                 buckets: tuple[int, ...] = (64, 256, 512), mesh=None):
        self.cfg = cfg
        self.params = params
        # position indices shift by position_offset (Roberta), so the usable
        # sequence length is max_position - offset
        top = cfg.max_position - cfg.position_offset
        self.buckets = tuple(sorted(b for b in buckets if b <= top)) or (
            min(64, top),)
        self.mesh = mesh
        # normalize is branched on in Python inside the trace — static, so a
        # caller passing it as a live bool can't hit a TracerBoolConversion
        self._fn = jax.jit(partial(bert_pooled, cfg=cfg),
                           static_argnames=("normalize",))
