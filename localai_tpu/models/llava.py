"""LLaVA-style vision-language chat — images in /v1/chat/completions.

Reference parity: LocalAI's multimodal chat rides llama.cpp's mmproj path
(/root/reference/backend/cpp/llama-cpp/grpc-server.cpp:285-289) and the
vLLM/mlx-vlm backends' image inputs
(/root/reference/backend/python/vllm/backend.py:232-252); the proto carries
images as base64 strings (PredictOptions.images,
/root/reference/backend/backend.proto:131). The TPU shape of the same idea:

  CLIP ViT tower (models/clip_vit.py, one lax.scan block)
    → hidden_states[vision_feature_layer], CLS dropped
    → 2-layer gelu projector into the text hidden size
    → spliced into the prompt as injected embeddings; the engine's
      admission/extend programs take an (extra, is_embed) inject pair so
      image features flow through the SAME continuous-batching slots as
      text tokens (engine/engine.py) — no separate vision serving path.

Supports both HF LLaVA save layouts: the classic
`language_model.model.* / vision_tower.* / multi_modal_projector.*` and the
4.52+ `model.language_model.* / model.vision_tower.* /
model.multi_modal_projector.* / lm_head.*`.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.models.clip_vit import (
    ClipVisionConfig, load_vision_params, preprocess_image, vision_forward,
)


@dataclasses.dataclass(frozen=True)
class LlavaMeta:
    image_token_index: int
    vision_feature_layer: int = -2
    select_strategy: str = "default"   # "default" drops CLS, "full" keeps


def is_llava(model_dir: str) -> bool:
    path = os.path.join(model_dir, "config.json")
    if not os.path.exists(path):
        return False
    with open(path) as f:
        hf = json.load(f)
    arch = (hf.get("architectures") or [""])[0]
    return hf.get("model_type") == "llava" or arch.startswith("Llava")


def load_vision(model_dir: str, dtype: str | None = None):
    """Load the vision side of a LLaVA checkpoint:
    (vision_cfg, {"tower": ..., "proj_w1", "proj_b1", "proj_w2", "proj_b2"},
    LlavaMeta)."""
    from localai_tpu.engine.loader import _TensorReader

    with open(os.path.join(model_dir, "config.json")) as f:
        hf: dict[str, Any] = json.load(f)
    vcfg = ClipVisionConfig.from_hf(hf.get("vision_config") or {},
                                    dtype=dtype or "float32")
    meta = LlavaMeta(
        image_token_index=hf.get("image_token_index", 32000),
        vision_feature_layer=hf.get("vision_feature_layer", -2),
        select_strategy=hf.get("vision_feature_select_strategy", "default"),
    )
    r = _TensorReader(model_dir)
    try:
        tower_prefix = next(
            p for p in ("vision_tower.", "model.vision_tower.")
            if p + "vision_model.pre_layrnorm.weight" in r)
        proj_prefix = next(
            p for p in ("multi_modal_projector.", "model.multi_modal_projector.")
            if p + "linear_1.weight" in r)
        tower = load_vision_params(r, vcfg, prefix=tower_prefix)
        jdt = vcfg.jdtype
        params = {
            "tower": tower,
            "proj_w1": jnp.asarray(
                np.asarray(r.get(proj_prefix + "linear_1.weight"),
                           np.float32).T, jdt),
            "proj_b1": jnp.asarray(
                np.asarray(r.get(proj_prefix + "linear_1.bias"), np.float32),
                jdt),
            "proj_w2": jnp.asarray(
                np.asarray(r.get(proj_prefix + "linear_2.weight"),
                           np.float32).T, jdt),
            "proj_b2": jnp.asarray(
                np.asarray(r.get(proj_prefix + "linear_2.bias"), np.float32),
                jdt),
        }
    finally:
        r.close()
    return vcfg, params, meta


def encode_images(params, vcfg: ClipVisionConfig, meta: LlavaMeta,
                  pixel_values) -> jax.Array:
    """pixel_values [N, 3, S, S] → projected image features [N, n_tok, H_text]
    (n_tok = n_patches for the CLS-dropping "default" strategy)."""
    feats = vision_forward(params["tower"], vcfg, pixel_values,
                           feature_layer=meta.vision_feature_layer)
    if meta.select_strategy != "full":
        feats = feats[:, 1:]                                   # drop CLS
    h = feats @ params["proj_w1"] + params["proj_b1"]
    h = jax.nn.gelu(h, approximate=False)
    return h @ params["proj_w2"] + params["proj_b2"]


def expand_image_tokens(prompt_ids: list[int], n_images: int, n_tok: int,
                        image_token: int) -> tuple[list[int], np.ndarray]:
    """HF LlavaProcessor's expansion: each single image token in the prompt
    becomes n_tok copies. Returns (expanded_ids, positions [n_images*n_tok]
    of the expanded image slots, in image order)."""
    occurrences = [i for i, t in enumerate(prompt_ids) if t == image_token]
    if len(occurrences) != n_images:
        raise ValueError(
            f"prompt has {len(occurrences)} image placeholder(s) but "
            f"{n_images} image(s) were attached")
    out: list[int] = []
    positions: list[int] = []
    for i, t in enumerate(prompt_ids):
        if t == image_token:
            positions.extend(range(len(out), len(out) + n_tok))
            out.extend([image_token] * n_tok)
        else:
            out.append(t)
    return out, np.asarray(positions, np.int64)


def decode_image_b64(data: str) -> bytes:
    """Proto images entries: raw base64, or a data: URL."""
    import base64

    if data.startswith("data:"):
        data = data.split(",", 1)[1]
    return base64.b64decode(data)


__all__ = [
    "LlavaMeta", "is_llava", "load_vision", "encode_images",
    "expand_image_tokens", "decode_image_b64", "preprocess_image",
]
