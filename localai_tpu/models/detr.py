"""DETR-family object detection — the Detect RPC's model family.

Reference analog: the rfdetr backend (/root/reference/backend/python/rfdetr/
backend.py — RF-DETR is a DETR descendant) serving `Detect(src)` →
boxes/confidence/class_name. Here the detector is JAX end-to-end: ResNet
backbone (frozen batchnorm, as DETR trains it), sine 2-D position embeddings,
post-LN transformer encoder/decoder over the flattened feature map, learned
object queries, class + box-MLP heads. Loads HF `DetrForObjectDetection`
checkpoints in both weight namings (transformers-native ResNet and the timm
naming the facebook/detr-resnet-* checkpoints ship).

TPU notes: convs are XLA convolutions (MXU-eligible), the transformer stacks
layers for lax.scan, shapes are static per image-size bucket so each bucket
compiles once.
"""
from __future__ import annotations

import dataclasses
import json
import os
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.ops.norms import layer_norm

DETR_FAMILY = ("DetrForObjectDetection", "DetrModel",
               "ConditionalDetrForObjectDetection")


@dataclasses.dataclass(frozen=True)
class DetrConfig:
    d_model: int = 256
    encoder_layers: int = 6
    decoder_layers: int = 6
    num_heads: int = 8
    ffn_dim: int = 2048
    num_queries: int = 100
    num_labels: int = 91
    ln_eps: float = 1e-5
    # backbone (transformers ResNetConfig subset)
    embedding_size: int = 64
    hidden_sizes: tuple[int, ...] = (256, 512, 1024, 2048)
    depths: tuple[int, ...] = (3, 4, 6, 3)
    layer_type: str = "bottleneck"          # bottleneck | basic
    downsample_in_first_stage: bool = False
    downsample_in_bottleneck: bool = False
    id2label: tuple[str, ...] = ()

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads


def is_detr_dir(model_dir: str) -> bool:
    try:
        with open(os.path.join(model_dir, "config.json")) as f:
            arch = (json.load(f).get("architectures") or [""])[0]
        return arch in DETR_FAMILY
    except (OSError, ValueError):
        return False


def load_detr_config(model_dir: str) -> DetrConfig:
    with open(os.path.join(model_dir, "config.json")) as f:
        hf: dict[str, Any] = json.load(f)
    bb = hf.get("backbone_config") or {}
    id2label = hf.get("id2label") or {}
    labels = tuple(id2label[k] for k in sorted(id2label, key=int)) \
        if id2label else ()
    return DetrConfig(
        d_model=hf.get("d_model", 256),
        encoder_layers=hf.get("encoder_layers", 6),
        decoder_layers=hf.get("decoder_layers", 6),
        num_heads=hf.get("encoder_attention_heads", 8),
        ffn_dim=hf.get("encoder_ffn_dim", 2048),
        num_queries=hf.get("num_queries", 100),
        num_labels=len(labels) or hf.get("num_labels", 91),
        embedding_size=bb.get("embedding_size", 64),
        hidden_sizes=tuple(bb.get("hidden_sizes", (256, 512, 1024, 2048))),
        depths=tuple(bb.get("depths", (3, 4, 6, 3))),
        layer_type=bb.get("layer_type", "bottleneck"),
        downsample_in_first_stage=bb.get("downsample_in_first_stage", False),
        downsample_in_bottleneck=bb.get("downsample_in_bottleneck", False),
        id2label=labels,
    )


# ---------------------------------------------------------------- loading

def _frozen_bn(t, prefix):
    """Fold a (frozen) batchnorm into (scale, shift): y = x*scale + shift."""
    w = t(prefix + ".weight")
    b = t(prefix + ".bias")
    mean = t(prefix + ".running_mean")
    var = t(prefix + ".running_var")
    inv = w / np.sqrt(var + 1e-5)
    return np.stack([inv, b - mean * inv])    # [2, C]


def load_detr_params(model_dir: str, cfg: DetrConfig):
    """HF safetensors → pytree. Backbone convs keep NCHW torch layout ([O, I,
    kh, kw] → HWIO for lax.conv); BN folded to affine; transformer weights
    transposed to [in, out]; q/k/v stay separate (HF scales q only)."""
    from localai_tpu.engine.loader import _TensorReader, _is_synthetic

    if _is_synthetic(model_dir):
        return init_detr_params(cfg, jax.random.PRNGKey(0))
    r = _TensorReader(model_dir)
    names = set(r.index.keys())

    def raw(name):
        return np.asarray(r.get(name), np.float32)

    timm = any(".conv_encoder.model.conv1." in n for n in names)

    def t(name):
        return raw(name)

    def conv(name):                       # [O,I,kh,kw] → [kh,kw,I,O]
        return t(name).transpose(2, 3, 1, 0)

    def lin(name):
        return t(name + ".weight").T, t(name + ".bias")

    bb = "model.backbone.conv_encoder.model."
    p: dict[str, Any] = {}
    if timm:
        # timm resnet naming (facebook/detr-resnet-50): conv1/bn1,
        # layer{1..4}.{i}.conv{1..3}/bn{1..3} + downsample.{0,1}
        p["stem_conv"] = conv(bb + "conv1.weight")
        p["stem_bn"] = _frozen_bn(t, bb + "bn1")
        stages = []
        for si in range(len(cfg.hidden_sizes)):
            blocks = []
            for li in range(cfg.depths[si]):
                blk = {}
                base = f"{bb}layer{si + 1}.{li}."
                ncv = 3 if cfg.layer_type == "bottleneck" else 2
                for ci in range(ncv):
                    blk[f"conv{ci}"] = conv(base + f"conv{ci + 1}.weight")
                    blk[f"bn{ci}"] = _frozen_bn(t, base + f"bn{ci + 1}")
                if (base + "downsample.0.weight") in names:
                    blk["short_conv"] = conv(base + "downsample.0.weight")
                    blk["short_bn"] = _frozen_bn(t, base + "downsample.1")
                blocks.append(blk)
            stages.append(blocks)
        p["stages"] = stages
    else:
        # transformers-native ResNet naming
        p["stem_conv"] = conv(bb + "embedder.embedder.convolution.weight")
        p["stem_bn"] = _frozen_bn(t, bb + "embedder.embedder.normalization")
        stages = []
        for si in range(len(cfg.hidden_sizes)):
            blocks = []
            for li in range(cfg.depths[si]):
                blk = {}
                base = f"{bb}encoder.stages.{si}.layers.{li}."
                ncv = 3 if cfg.layer_type == "bottleneck" else 2
                for ci in range(ncv):
                    blk[f"conv{ci}"] = conv(
                        base + f"layer.{ci}.convolution.weight")
                    blk[f"bn{ci}"] = _frozen_bn(
                        t, base + f"layer.{ci}.normalization")
                if (base + "shortcut.convolution.weight") in names:
                    blk["short_conv"] = conv(
                        base + "shortcut.convolution.weight")
                    blk["short_bn"] = _frozen_bn(
                        t, base + "shortcut.normalization")
                blocks.append(blk)
            stages.append(blocks)
        p["stages"] = stages

    pw, pb = t("model.input_projection.weight"), t("model.input_projection.bias")
    p["input_proj"] = pw.transpose(2, 3, 1, 0)
    p["input_proj_b"] = pb
    p["query_emb"] = t("model.query_position_embeddings.weight")

    def xf_layer(base, cross: bool):
        lp = {}
        for nm, key in (("self_attn", "sa"),) + (
                (("encoder_attn", "ca"),) if cross else ()):
            for proj in ("q", "k", "v", "out"):
                w, b = lin(f"{base}{nm}.{proj}_proj")
                lp[f"{key}_{proj}w"], lp[f"{key}_{proj}b"] = w, b
            ln = ("self_attn_layer_norm" if nm == "self_attn"
                  else "encoder_attn_layer_norm")
            lp[f"{key}_ln_w"] = t(f"{base}{ln}.weight")
            lp[f"{key}_ln_b"] = t(f"{base}{ln}.bias")
        lp["fc1_w"], lp["fc1_b"] = lin(base + "fc1")
        lp["fc2_w"], lp["fc2_b"] = lin(base + "fc2")
        lp["ln_f_w"] = t(base + "final_layer_norm.weight")
        lp["ln_f_b"] = t(base + "final_layer_norm.bias")
        return lp

    def stack(layers):
        return {k: np.stack([lp[k] for lp in layers])
                for k in layers[0]}

    p["encoder"] = stack([xf_layer(f"model.encoder.layers.{i}.", False)
                          for i in range(cfg.encoder_layers)])
    p["decoder"] = stack([xf_layer(f"model.decoder.layers.{i}.", True)
                          for i in range(cfg.decoder_layers)])
    p["dec_ln_w"] = t("model.decoder.layernorm.weight")
    p["dec_ln_b"] = t("model.decoder.layernorm.bias")
    p["cls_w"], p["cls_b"] = lin("class_labels_classifier")
    p["box"] = [lin(f"bbox_predictor.layers.{i}") for i in range(3)]
    return jax.tree_util.tree_map(jnp.asarray, p)


def init_detr_params(cfg: DetrConfig, key):
    """Random init with load_detr_params' layout (synthetic checkpoints)."""
    ks = iter(jax.random.split(key, 64))

    def w(shape, fan_in):
        return jax.random.normal(next(ks), shape, jnp.float32) * fan_in ** -0.5

    def convw(kh, kw, i, o):
        return w((kh, kw, i, o), kh * kw * i)

    def bn(c):
        return jnp.stack([jnp.ones((c,)), jnp.zeros((c,))])

    d, H = cfg.d_model, cfg.ffn_dim
    p: dict[str, Any] = {
        "stem_conv": convw(7, 7, 3, cfg.embedding_size),
        "stem_bn": bn(cfg.embedding_size),
    }
    stages = []
    cin = cfg.embedding_size
    for si, cout in enumerate(cfg.hidden_sizes):
        blocks = []
        for li in range(cfg.depths[si]):
            i = cin if li == 0 else cout
            blk = {}
            if cfg.layer_type == "bottleneck":
                red = cout // 4
                blk["conv0"] = convw(1, 1, i, red)
                blk["conv1"] = convw(3, 3, red, red)
                blk["conv2"] = convw(1, 1, red, cout)
                for ci in range(3):
                    blk[f"bn{ci}"] = bn(blk[f"conv{ci}"].shape[-1])
            else:
                blk["conv0"] = convw(3, 3, i, cout)
                blk["conv1"] = convw(3, 3, cout, cout)
                blk["bn0"], blk["bn1"] = bn(cout), bn(cout)
            if li == 0 and (i != cout or si > 0
                            or cfg.downsample_in_first_stage):
                blk["short_conv"] = convw(1, 1, i, cout)
                blk["short_bn"] = bn(cout)
            blocks.append(blk)
        stages.append(blocks)
        cin = cout
    p["stages"] = stages
    p["input_proj"] = convw(1, 1, cfg.hidden_sizes[-1], d)
    p["input_proj_b"] = jnp.zeros((d,))
    p["query_emb"] = w((cfg.num_queries, d), d)

    def xf(cross):
        lp = {}
        keys = ("sa", "ca") if cross else ("sa",)
        for k in keys:
            for proj in ("q", "k", "v", "out"):
                lp[f"{k}_{proj}w"] = w((d, d), d)
                lp[f"{k}_{proj}b"] = jnp.zeros((d,))
            lp[f"{k}_ln_w"], lp[f"{k}_ln_b"] = jnp.ones((d,)), jnp.zeros((d,))
        lp["fc1_w"], lp["fc1_b"] = w((d, H), d), jnp.zeros((H,))
        lp["fc2_w"], lp["fc2_b"] = w((H, d), H), jnp.zeros((d,))
        lp["ln_f_w"], lp["ln_f_b"] = jnp.ones((d,)), jnp.zeros((d,))
        return lp

    def stackn(n, cross):
        layers = [xf(cross) for _ in range(n)]
        return {k: jnp.stack([lp[k] for lp in layers]) for k in layers[0]}

    p["encoder"] = stackn(cfg.encoder_layers, False)
    p["decoder"] = stackn(cfg.decoder_layers, True)
    p["dec_ln_w"], p["dec_ln_b"] = jnp.ones((d,)), jnp.zeros((d,))
    p["cls_w"], p["cls_b"] = w((d, cfg.num_labels + 1), d), jnp.zeros(
        (cfg.num_labels + 1,))
    p["box"] = [(w((d, d), d), jnp.zeros((d,))),
                (w((d, d), d), jnp.zeros((d,))),
                (w((d, 4), d), jnp.zeros((4,)))]
    return p


# ---------------------------------------------------------------- forward

def _conv(x, w, stride=1):
    # torch Conv2d pads k//2 on BOTH sides; XLA "SAME" pads asymmetrically
    # under stride 2, which would shift every strided feature map half a pixel
    pad = w.shape[0] // 2
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), ((pad, pad), (pad, pad)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(x, bn):
    return x * bn[0] + bn[1]


def _backbone(p, cfg: DetrConfig, x):
    """x: [B, H, W, 3] → last-stage feature map [B, H/32, W/32, C]."""
    x = jax.nn.relu(_bn(_conv(x, p["stem_conv"], 2), p["stem_bn"]))
    # maxpool 3x3 stride 2 pad 1 (torch-symmetric)
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1),
                              ((0, 0), (1, 1), (1, 1), (0, 0)))
    for si, blocks in enumerate(p["stages"]):
        stride0 = 1 if (si == 0 and not cfg.downsample_in_first_stage) else 2
        for li, blk in enumerate(blocks):
            stride = stride0 if li == 0 else 1
            res = x
            if "short_conv" in blk:
                res = _bn(_conv(x, blk["short_conv"], stride),
                          blk["short_bn"])
            if cfg.layer_type == "bottleneck":
                s_first = stride if cfg.downsample_in_bottleneck else 1
                s_mid = 1 if cfg.downsample_in_bottleneck else stride
                y = jax.nn.relu(_bn(_conv(x, blk["conv0"], s_first),
                                    blk["bn0"]))
                y = jax.nn.relu(_bn(_conv(y, blk["conv1"], s_mid),
                                    blk["bn1"]))
                y = _bn(_conv(y, blk["conv2"], 1), blk["bn2"])
            else:
                y = jax.nn.relu(_bn(_conv(x, blk["conv0"], stride),
                                    blk["bn0"]))
                y = _bn(_conv(y, blk["conv1"], 1), blk["bn1"])
            x = jax.nn.relu(res + y)
    return x


def _sine_pos(h, w, d_model):
    """DETR 2-D sine position embedding (normalized, scale 2π) → [h*w, D]."""
    half = d_model // 2
    scale = 2 * np.pi
    y = (jnp.arange(h, dtype=jnp.float32) + 1) / (h + 1e-6) * scale
    x = (jnp.arange(w, dtype=jnp.float32) + 1) / (w + 1e-6) * scale
    dim_t = 10000.0 ** (2 * (jnp.arange(half) // 2) / half)
    py = y[:, None] / dim_t                      # [h, half]
    px = x[:, None] / dim_t
    def interleave(p):
        return jnp.stack([jnp.sin(p[:, 0::2]), jnp.cos(p[:, 1::2])],
                         axis=2).reshape(p.shape[0], -1)
    py, px = interleave(py), interleave(px)
    pos = jnp.concatenate([
        jnp.broadcast_to(py[:, None, :], (h, w, half)),
        jnp.broadcast_to(px[None, :, :], (h, w, half)),
    ], axis=-1)
    return pos.reshape(h * w, d_model)


def _attn(q, k, v, nh, scale):
    b, sq, d = q.shape
    hd = d // nh
    qh = q.reshape(b, sq, nh, hd).transpose(0, 2, 1, 3) * scale
    kh = k.reshape(b, k.shape[1], nh, hd).transpose(0, 2, 1, 3)
    vh = v.reshape(b, v.shape[1], nh, hd).transpose(0, 2, 1, 3)
    a = jax.nn.softmax(jnp.einsum("bhqd,bhkd->bhqk", qh, kh), axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", a, vh)
    return o.transpose(0, 2, 1, 3).reshape(b, sq, d)


def detr_forward(p, cfg: DetrConfig, pixels):
    """pixels: [B, H, W, 3] (ImageNet-normalized) →
    (logits [B, Q, labels+1], boxes [B, Q, 4] cxcywh in [0,1])."""
    nh = cfg.num_heads
    scale = cfg.head_dim ** -0.5
    feat = _backbone(p, cfg, pixels)
    b, fh, fw, _ = feat.shape
    src = _conv(feat, p["input_proj"]) + p["input_proj_b"]
    src = src.reshape(b, fh * fw, cfg.d_model)
    pos = _sine_pos(fh, fw, cfg.d_model)[None]

    def enc_layer(x, lp):
        q = (x + pos) @ lp["sa_qw"] + lp["sa_qb"]
        k = (x + pos) @ lp["sa_kw"] + lp["sa_kb"]
        v = x @ lp["sa_vw"] + lp["sa_vb"]
        y = _attn(q, k, v, nh, scale) @ lp["sa_outw"] + lp["sa_outb"]
        x = layer_norm(x + y, lp["sa_ln_w"], lp["sa_ln_b"], cfg.ln_eps)
        y = jax.nn.relu(x @ lp["fc1_w"] + lp["fc1_b"]) @ lp["fc2_w"] \
            + lp["fc2_b"]
        x = layer_norm(x + y, lp["ln_f_w"], lp["ln_f_b"], cfg.ln_eps)
        return x, None

    mem, _ = jax.lax.scan(enc_layer, src, p["encoder"])

    qpos = p["query_emb"][None]                    # [1, Q, D]
    tgt = jnp.zeros((b, cfg.num_queries, cfg.d_model))

    def dec_layer(x, lp):
        q = (x + qpos) @ lp["sa_qw"] + lp["sa_qb"]
        k = (x + qpos) @ lp["sa_kw"] + lp["sa_kb"]
        v = x @ lp["sa_vw"] + lp["sa_vb"]
        y = _attn(q, k, v, nh, scale) @ lp["sa_outw"] + lp["sa_outb"]
        x = layer_norm(x + y, lp["sa_ln_w"], lp["sa_ln_b"], cfg.ln_eps)
        q = (x + qpos) @ lp["ca_qw"] + lp["ca_qb"]
        k = (mem + pos) @ lp["ca_kw"] + lp["ca_kb"]
        v = mem @ lp["ca_vw"] + lp["ca_vb"]
        y = _attn(q, k, v, nh, scale) @ lp["ca_outw"] + lp["ca_outb"]
        x = layer_norm(x + y, lp["ca_ln_w"], lp["ca_ln_b"], cfg.ln_eps)
        y = jax.nn.relu(x @ lp["fc1_w"] + lp["fc1_b"]) @ lp["fc2_w"] \
            + lp["fc2_b"]
        x = layer_norm(x + y, lp["ln_f_w"], lp["ln_f_b"], cfg.ln_eps)
        return x, None

    out, _ = jax.lax.scan(dec_layer, tgt, p["decoder"])
    out = layer_norm(out, p["dec_ln_w"], p["dec_ln_b"], cfg.ln_eps)
    logits = out @ p["cls_w"] + p["cls_b"]
    h = out
    for i, (w, bb_) in enumerate(p["box"]):
        h = h @ w + bb_
        if i < 2:
            h = jax.nn.relu(h)
    boxes = jax.nn.sigmoid(h)
    return logits, boxes


# ---------------------------------------------------------------- detector

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)

# COCO-91 labels (the DETR checkpoints' label space) as fallback when the
# config carries no id2label
_FALLBACK_LABEL = "object"


@dataclasses.dataclass
class Detection:
    x: float
    y: float
    width: float
    height: float
    confidence: float
    class_name: str


class Detector:
    """Bucketed jitted DETR inference: image file → [Detection]."""

    def __init__(self, cfg: DetrConfig, params, *,
                 sizes: tuple[int, ...] = (480, 640, 800),
                 threshold: float = 0.5):
        self.cfg = cfg
        self.params = params
        self.sizes = tuple(sorted(sizes))
        self.threshold = threshold
        self._fn = jax.jit(partial(detr_forward, cfg=cfg))

    def _preprocess(self, img) -> tuple[np.ndarray, float, float]:
        """PIL image → normalized [1, S, S, 3] square resize (static shapes →
        one compile per bucket; boxes are normalized so the mild aspect
        distortion maps back exactly through the per-axis scales)."""
        w0, h0 = img.size
        side = self.sizes[-1]
        for s in self.sizes:
            if max(w0, h0) <= s:
                side = s
                break
        img = img.convert("RGB").resize((side, side))
        arr = np.asarray(img, np.float32) / 255.0
        arr = (arr - IMAGENET_MEAN) / IMAGENET_STD
        return arr[None], float(w0), float(h0)

    def detect(self, src: str) -> list[Detection]:
        from PIL import Image

        img = Image.open(src)
        pixels, sx, sy = self._preprocess(img)
        logits, boxes = self._fn(self.params, pixels=jnp.asarray(pixels))
        probs = jax.device_get(jax.nn.softmax(logits, axis=-1))[0, :, :-1]
        boxes = jax.device_get(boxes)[0]
        out = []
        for qi in range(probs.shape[0]):
            ci = int(np.argmax(probs[qi]))
            conf = float(probs[qi, ci])
            if conf < self.threshold:
                continue
            cx, cy, bw, bh = boxes[qi]
            name = (self.cfg.id2label[ci] if ci < len(self.cfg.id2label)
                    else _FALLBACK_LABEL)
            out.append(Detection(
                x=float((cx - bw / 2) * sx), y=float((cy - bh / 2) * sy),
                width=float(bw * sx), height=float(bh * sy),
                confidence=conf, class_name=name))
        out.sort(key=lambda d: -d.confidence)
        return out
