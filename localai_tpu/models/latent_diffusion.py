"""Stable-Diffusion-class latent diffusion in JAX — txt2img from REAL
checkpoints in the standard diffusers directory layout.

Reference role: the diffusers backend's GenerateImage
(/root/reference/backend/python/diffusers/backend.py) and the
stablediffusion-ggml backend (/root/reference/backend/go/
stablediffusion-ggml/gosd.cpp). TPU-first rebuild: the CLIP text encoder,
UNet2DCondition (down/mid/up ResNet + cross-attention transformer blocks)
and VAE decoder are pure JAX functions over a flat {diffusers key: array}
weight dict loaded straight from `unet/`, `vae/`, `text_encoder/`
safetensors; the DDIM denoise loop is a lax.scan, so one jitted XLA program
runs the whole trajectory on the MXU (bf16 matmuls/convs, f32 norms).

Supported layouts (config-driven so tiny test checkpoints load the same
way): model_index.json at the root plus unet/config.json +
unet/diffusion_pytorch_model.safetensors, same for vae/, text_encoder/
(+ tokenizer/tokenizer.json). SD 1.x/2.x geometry, and SDXL geometry —
text_encoder_2 (CLIP-with-projection) conditioning concat, per-block
transformer depth (`transformer_layers_per_block`), and the `text_time`
addition embedding (pooled embeds + size/crop micro-conditioning).
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


# ------------------------------------------------------------ weight loading

def _read_safetensors(path: str) -> dict[str, np.ndarray]:
    from localai_tpu.engine.loader import _SafetensorsFile

    f = _SafetensorsFile(path)
    try:
        return {k: np.array(f.get(k)) for k in f.keys()}
    finally:
        f.close()


def _component_weights(model_dir: str, sub: str) -> dict[str, np.ndarray]:
    d = os.path.join(model_dir, sub)
    for name in ("diffusion_pytorch_model.safetensors", "model.safetensors"):
        p = os.path.join(d, name)
        if os.path.exists(p):
            return _read_safetensors(p)
    raise FileNotFoundError(f"no safetensors for component {sub!r} in {d}")


def _component_config(model_dir: str, sub: str) -> dict:
    with open(os.path.join(model_dir, sub, "config.json")) as fh:
        return json.load(fh)


def is_diffusers_checkpoint(model_dir: str) -> bool:
    return os.path.exists(os.path.join(model_dir, "model_index.json"))


# ------------------------------------------------------------ primitives

def conv2d(x, w, b, stride=1, padding=1):
    """x NHWC, torch OIHW kernel (transposed to HWIO at load)."""
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    return (out + b).astype(x.dtype)


def group_norm(x, gamma, beta, groups, eps=1e-5):
    """NHWC group norm in f32."""
    n, h, w, c = x.shape
    xf = x.astype(jnp.float32).reshape(n, h, w, groups, c // groups)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (xf.reshape(n, h, w, c) * gamma + beta).astype(x.dtype)


def layer_norm(x, gamma, beta, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    return (((xf - mean) * jax.lax.rsqrt(var + eps)) * gamma + beta).astype(
        x.dtype)


def linear(x, w, b=None):
    """torch [out, in] weight."""
    y = x @ w.T
    return y if b is None else y + b


def attention(q, k, v, heads: int):
    """[B, Nq, C] x [B, Nk, C] multi-head attention."""
    b, nq, c = q.shape
    nk = k.shape[1]
    d = c // heads
    q = q.reshape(b, nq, heads, d).transpose(0, 2, 1, 3)
    k = k.reshape(b, nk, heads, d).transpose(0, 2, 1, 3)
    v = v.reshape(b, nk, heads, d).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * (d ** -0.5)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o.transpose(0, 2, 1, 3).reshape(b, nq, c)


def timestep_embedding(t, dim: int):
    """diffusers get_timestep_embedding (flip_sin_to_cos=True, shift=0)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0) * jnp.arange(half) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


# ------------------------------------------------------------ CLIP text

def clip_encode(w: dict, cfg: dict, tokens, *, penultimate=False,
                with_pooled=False):
    """CLIP text encoder → last hidden state [B, S, H] (pre-LN, causal).

    `penultimate=True` returns hidden_states[-2] (the input to the final
    encoder layer, no final LN) — what SDXL conditions on from both of its
    encoders. `with_pooled=True` additionally returns the pooled embedding:
    final-LN hidden at the EOT position, through `text_projection` when the
    checkpoint has one (CLIPTextModelWithProjection, SDXL's second encoder)
    — then the return is (hidden, pooled)."""
    p = "text_model."
    x = w[p + "embeddings.token_embedding.weight"][tokens]
    x = x + w[p + "embeddings.position_embedding.weight"][: tokens.shape[1]]
    heads = cfg["num_attention_heads"]
    s = tokens.shape[1]
    causal = jnp.tril(jnp.ones((s, s), bool))
    penult = None
    for i in range(cfg["num_hidden_layers"]):
        if i == cfg["num_hidden_layers"] - 1:
            penult = x
        lp = f"{p}encoder.layers.{i}."
        h = layer_norm(x, w[lp + "layer_norm1.weight"],
                       w[lp + "layer_norm1.bias"])
        q = linear(h, w[lp + "self_attn.q_proj.weight"],
                   w[lp + "self_attn.q_proj.bias"])
        k = linear(h, w[lp + "self_attn.k_proj.weight"],
                   w[lp + "self_attn.k_proj.bias"])
        v = linear(h, w[lp + "self_attn.v_proj.weight"],
                   w[lp + "self_attn.v_proj.bias"])
        b, _, c = q.shape
        d = c // heads
        qh = q.reshape(b, s, heads, d).transpose(0, 2, 1, 3)
        kh = k.reshape(b, s, heads, d).transpose(0, 2, 1, 3)
        vh = v.reshape(b, s, heads, d).transpose(0, 2, 1, 3)
        sc = jnp.einsum("bhqd,bhkd->bhqk", qh, kh).astype(jnp.float32)
        sc = jnp.where(causal, sc * (d ** -0.5), -1e30)
        pr = jax.nn.softmax(sc, axis=-1).astype(vh.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", pr, vh)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, c)
        x = x + linear(o, w[lp + "self_attn.out_proj.weight"],
                       w[lp + "self_attn.out_proj.bias"])
        h = layer_norm(x, w[lp + "layer_norm2.weight"],
                       w[lp + "layer_norm2.bias"])
        h = linear(h, w[lp + "mlp.fc1.weight"], w[lp + "mlp.fc1.bias"])
        h = h * jax.nn.sigmoid(1.702 * h)          # quick_gelu
        x = x + linear(h, w[lp + "mlp.fc2.weight"], w[lp + "mlp.fc2.bias"])
    final = layer_norm(x, w[p + "final_layer_norm.weight"],
                       w[p + "final_layer_norm.bias"])
    hidden = penult if penultimate else final
    if not with_pooled:
        return hidden
    # HF CLIP pooler: first EOS position; legacy configs (eos_token_id=2)
    # keep the original argmax-of-ids behavior (EOT is the largest CLIP id
    # and SD pipelines pad with it)
    eos_id = cfg.get("eos_token_id", 49407)
    if eos_id == 2:
        eot = jnp.argmax(tokens, axis=-1)
    else:
        eot = jnp.argmax((tokens == eos_id).astype(jnp.int32), axis=-1)
    pooled = final[jnp.arange(tokens.shape[0]), eot]
    if "text_projection.weight" in w:
        pooled = linear(pooled, w["text_projection.weight"])
    return hidden, pooled


# ------------------------------------------------------------ UNet blocks

def _resnet(w, pfx, x, temb, groups):
    h = group_norm(x, w[pfx + "norm1.weight"], w[pfx + "norm1.bias"], groups)
    h = conv2d(jax.nn.silu(h), w[pfx + "conv1.weight"],
               w[pfx + "conv1.bias"])
    if pfx + "time_emb_proj.weight" in w:
        t = linear(jax.nn.silu(temb), w[pfx + "time_emb_proj.weight"],
                   w[pfx + "time_emb_proj.bias"])
        h = h + t[:, None, None, :]
    h = group_norm(h, w[pfx + "norm2.weight"], w[pfx + "norm2.bias"], groups)
    h = conv2d(jax.nn.silu(h), w[pfx + "conv2.weight"],
               w[pfx + "conv2.bias"])
    if pfx + "conv_shortcut.weight" in w:
        x = conv2d(x, w[pfx + "conv_shortcut.weight"],
                   w[pfx + "conv_shortcut.bias"], padding=0)
    return x + h


def _tblock(w, pfx, x, ctx, heads):
    """BasicTransformerBlock: self-attn, cross-attn, GEGLU ff."""
    h = layer_norm(x, w[pfx + "norm1.weight"], w[pfx + "norm1.bias"])
    a = attention(linear(h, w[pfx + "attn1.to_q.weight"]),
                  linear(h, w[pfx + "attn1.to_k.weight"]),
                  linear(h, w[pfx + "attn1.to_v.weight"]), heads)
    x = x + linear(a, w[pfx + "attn1.to_out.0.weight"],
                   w[pfx + "attn1.to_out.0.bias"])
    h = layer_norm(x, w[pfx + "norm2.weight"], w[pfx + "norm2.bias"])
    a = attention(linear(h, w[pfx + "attn2.to_q.weight"]),
                  linear(ctx, w[pfx + "attn2.to_k.weight"]),
                  linear(ctx, w[pfx + "attn2.to_v.weight"]), heads)
    x = x + linear(a, w[pfx + "attn2.to_out.0.weight"],
                   w[pfx + "attn2.to_out.0.bias"])
    h = layer_norm(x, w[pfx + "norm3.weight"], w[pfx + "norm3.bias"])
    h = linear(h, w[pfx + "ff.net.0.proj.weight"],
               w[pfx + "ff.net.0.proj.bias"])
    a, gate = jnp.split(h, 2, axis=-1)
    h = a * jax.nn.gelu(gate)
    return x + linear(h, w[pfx + "ff.net.2.weight"], w[pfx + "ff.net.2.bias"])


def _spatial_transformer(w, pfx, x, ctx, heads, groups, depth=1):
    """Transformer2DModel over NHWC features (conv proj, SD1 layout)."""
    n, h_, w_, c = x.shape
    res = x
    x = group_norm(x, w[pfx + "norm.weight"], w[pfx + "norm.bias"], groups)
    if w[pfx + "proj_in.weight"].ndim == 4:
        x = conv2d(x, w[pfx + "proj_in.weight"], w[pfx + "proj_in.bias"],
                   padding=0)
        x = x.reshape(n, h_ * w_, c)
    else:   # use_linear_projection (SD2)
        x = x.reshape(n, h_ * w_, c)
        x = linear(x, w[pfx + "proj_in.weight"], w[pfx + "proj_in.bias"])
    for d in range(depth):
        x = _tblock(w, f"{pfx}transformer_blocks.{d}.", x, ctx, heads)
    if w[pfx + "proj_out.weight"].ndim == 4:
        x = x.reshape(n, h_, w_, c)
        x = conv2d(x, w[pfx + "proj_out.weight"], w[pfx + "proj_out.bias"],
                   padding=0)
    else:
        x = linear(x, w[pfx + "proj_out.weight"], w[pfx + "proj_out.bias"])
        x = x.reshape(n, h_, w_, c)
    return x + res


def unet_apply(w: dict, cfg: dict, latents, t, ctx,
               add_text_embeds=None, add_time_ids=None):
    """UNet2DCondition forward: latents [B,H,W,4], t [B], ctx [B,S,D].

    SDXL geometry (gosd.cpp / diffusers SDXL role): per-block transformer
    depth via `transformer_layers_per_block`, and the `text_time` addition
    embedding — pooled text embeds [B, P] + Fourier-embedded micro-cond
    time_ids [B, 6] through add_embedding, summed into the time embedding."""
    groups = cfg.get("norm_num_groups", 32)
    chans = cfg["block_out_channels"]
    lpb = cfg.get("layers_per_block", 2)
    head_dim = cfg.get("attention_head_dim", 8)
    head_dims = (head_dim if isinstance(head_dim, list)
                 else [head_dim] * len(chans))
    tlpb = cfg.get("transformer_layers_per_block", 1)
    depths = (list(tlpb) if isinstance(tlpb, (list, tuple))
              else [tlpb] * len(chans))
    down_types = cfg["down_block_types"]
    up_types = cfg["up_block_types"]

    temb = timestep_embedding(t, chans[0])
    temb = linear(temb, w["time_embedding.linear_1.weight"],
                  w["time_embedding.linear_1.bias"])
    temb = linear(jax.nn.silu(temb), w["time_embedding.linear_2.weight"],
                  w["time_embedding.linear_2.bias"])

    if cfg.get("addition_embed_type") == "text_time":
        atd = cfg.get("addition_time_embed_dim", 256)
        b = add_time_ids.shape[0]
        tid = timestep_embedding(add_time_ids.reshape(-1), atd)
        aug = jnp.concatenate(
            [add_text_embeds, tid.reshape(b, -1).astype(add_text_embeds.dtype)],
            axis=-1)
        aug = linear(aug, w["add_embedding.linear_1.weight"],
                     w["add_embedding.linear_1.bias"])
        aug = linear(jax.nn.silu(aug), w["add_embedding.linear_2.weight"],
                     w["add_embedding.linear_2.bias"])
        temb = temb + aug

    x = conv2d(latents, w["conv_in.weight"], w["conv_in.bias"])
    skips = [x]
    for i, btype in enumerate(down_types):
        heads = max(1, chans[i] // head_dims[i])
        for j in range(lpb):
            x = _resnet(w, f"down_blocks.{i}.resnets.{j}.", x, temb, groups)
            if "CrossAttn" in btype:
                x = _spatial_transformer(
                    w, f"down_blocks.{i}.attentions.{j}.", x, ctx, heads,
                    groups, depth=depths[i])
            skips.append(x)
        if f"down_blocks.{i}.downsamplers.0.conv.weight" in w:
            x = conv2d(x, w[f"down_blocks.{i}.downsamplers.0.conv.weight"],
                       w[f"down_blocks.{i}.downsamplers.0.conv.bias"],
                       stride=2)
            skips.append(x)

    heads_mid = max(1, chans[-1] // head_dims[-1])
    x = _resnet(w, "mid_block.resnets.0.", x, temb, groups)
    x = _spatial_transformer(w, "mid_block.attentions.0.", x, ctx,
                             heads_mid, groups, depth=depths[-1])
    x = _resnet(w, "mid_block.resnets.1.", x, temb, groups)

    for i, btype in enumerate(up_types):
        ch_i = len(chans) - 1 - i
        heads = max(1, chans[ch_i] // head_dims[ch_i])
        for j in range(lpb + 1):
            x = jnp.concatenate([x, skips.pop()], axis=-1)
            x = _resnet(w, f"up_blocks.{i}.resnets.{j}.", x, temb, groups)
            if "CrossAttn" in btype:
                x = _spatial_transformer(
                    w, f"up_blocks.{i}.attentions.{j}.", x, ctx, heads,
                    groups, depth=depths[ch_i])
        if f"up_blocks.{i}.upsamplers.0.conv.weight" in w:
            n, h_, w_, c = x.shape
            x = jax.image.resize(x, (n, h_ * 2, w_ * 2, c), "nearest")
            x = conv2d(x, w[f"up_blocks.{i}.upsamplers.0.conv.weight"],
                       w[f"up_blocks.{i}.upsamplers.0.conv.bias"])

    x = group_norm(x, w["conv_norm_out.weight"], w["conv_norm_out.bias"],
                   groups)
    return conv2d(jax.nn.silu(x), w["conv_out.weight"], w["conv_out.bias"])


# ------------------------------------------------------------ VAE decoder

def _vae_attn(w, pfx, x, groups):
    n, h_, w_, c = x.shape
    res = x
    x = group_norm(x, w[pfx + "group_norm.weight"],
                   w[pfx + "group_norm.bias"], groups)
    x = x.reshape(n, h_ * w_, c)
    o = attention(linear(x, w[pfx + "to_q.weight"], w[pfx + "to_q.bias"]),
                  linear(x, w[pfx + "to_k.weight"], w[pfx + "to_k.bias"]),
                  linear(x, w[pfx + "to_v.weight"], w[pfx + "to_v.bias"]), 1)
    o = linear(o, w[pfx + "to_out.0.weight"], w[pfx + "to_out.0.bias"])
    return o.reshape(n, h_, w_, c) + res


def vae_decode(w: dict, cfg: dict, latents):
    """AutoencoderKL decoder: latents [B,h,w,4] → images [B,H,W,3] in [0,1]."""
    groups = cfg.get("norm_num_groups", 32)
    scale = cfg.get("scaling_factor", 0.18215)
    x = latents / scale
    x = conv2d(x, w["post_quant_conv.weight"], w["post_quant_conv.bias"],
               padding=0)
    x = conv2d(x, w["decoder.conv_in.weight"], w["decoder.conv_in.bias"])
    x = _resnet(w, "decoder.mid_block.resnets.0.", x, None, groups)
    x = _vae_attn(w, "decoder.mid_block.attentions.0.", x, groups)
    x = _resnet(w, "decoder.mid_block.resnets.1.", x, None, groups)
    n_up = len(cfg["block_out_channels"])
    for i in range(n_up):
        for j in range(3):
            x = _resnet(w, f"decoder.up_blocks.{i}.resnets.{j}.", x, None,
                        groups)
        if f"decoder.up_blocks.{i}.upsamplers.0.conv.weight" in w:
            n, h_, w_, c = x.shape
            x = jax.image.resize(x, (n, h_ * 2, w_ * 2, c), "nearest")
            x = conv2d(x, w[f"decoder.up_blocks.{i}.upsamplers.0.conv.weight"],
                       w[f"decoder.up_blocks.{i}.upsamplers.0.conv.bias"])
    x = group_norm(x, w["decoder.conv_norm_out.weight"],
                   w["decoder.conv_norm_out.bias"], groups)
    x = conv2d(jax.nn.silu(x), w["decoder.conv_out.weight"],
               w["decoder.conv_out.bias"])
    return jnp.clip(x.astype(jnp.float32) / 2 + 0.5, 0.0, 1.0)


# ------------------------------------------------------------ pipeline

@dataclasses.dataclass
class LatentDiffusion:
    """txt2img pipeline over a diffusers-layout checkpoint directory."""

    model_dir: str
    dtype: str = "float32"

    def __post_init__(self):
        dt = jnp.dtype(self.dtype)

        def to_jax(d):
            out = {}
            for k, v in d.items():
                if v.ndim == 4:           # torch OIHW conv → HWIO
                    v = v.transpose(2, 3, 1, 0)
                a = jnp.asarray(v)
                out[k] = a.astype(dt) if a.dtype in (jnp.float32,
                                                     jnp.float16,
                                                     jnp.bfloat16) else a
            return out

        self.unet_cfg = _component_config(self.model_dir, "unet")
        self.vae_cfg = _component_config(self.model_dir, "vae")
        self.text_cfg = _component_config(self.model_dir, "text_encoder")
        self.unet_w = to_jax(_component_weights(self.model_dir, "unet"))
        self.vae_w = to_jax(_component_weights(self.model_dir, "vae"))
        self.text_w = to_jax(_component_weights(self.model_dir,
                                                "text_encoder"))
        # SDXL: a second (projection) text encoder conditions the UNet
        # jointly with the first and supplies the pooled `text_embeds`
        self.is_xl = os.path.isdir(
            os.path.join(self.model_dir, "text_encoder_2"))
        if self.is_xl:
            self.text2_cfg = _component_config(self.model_dir,
                                               "text_encoder_2")
            self.text2_w = to_jax(_component_weights(self.model_dir,
                                                     "text_encoder_2"))

        def load_tok(sub):
            p = os.path.join(self.model_dir, sub, "tokenizer.json")
            if os.path.exists(p):
                from tokenizers import Tokenizer as HFTok

                return HFTok.from_file(p)
            return None

        self.tokenizer = load_tok("tokenizer")
        self.tokenizer_2 = load_tok("tokenizer_2") or self.tokenizer

        # latent downscale = one halving per VAE block transition (8 for SD)
        self.vae_scale = 2 ** (len(self.vae_cfg["block_out_channels"]) - 1)
        # scaled-linear (sqrt-space) beta schedule — SD's PNDM/DDIM default
        n_train = 1000
        betas = jnp.linspace(0.00085 ** 0.5, 0.012 ** 0.5, n_train) ** 2
        self.alphas_bar = jnp.cumprod(1.0 - betas)
        self.n_train = n_train
        self._sample = jax.jit(
            partial(self._sample_impl), static_argnames=("steps", "h", "w"))

    def _encode_text(self, prompt: str, tokenizer=None, cfg=None):
        tokenizer = tokenizer if tokenizer is not None else self.tokenizer
        cfg = cfg or self.text_cfg
        s = min(cfg.get("max_position_embeddings", 77), 77)
        if tokenizer is not None:
            eos = tokenizer.token_to_id("<|endoftext|>")
            ids = tokenizer.encode(prompt).ids
            if eos is not None:
                # diffusers pads to 77 with EOS and never truncates it away
                ids = ids[: s - 1] + [eos]
                ids = ids + [eos] * (s - len(ids))
            else:
                ids = ids[:s] + [0] * max(0, s - len(ids))
        else:   # stable-hash fallback for tokenizer-less tiny checkpoints
            import zlib

            v = cfg["vocab_size"]
            ids = [zlib.crc32(tk.encode()) % v
                   for tk in prompt.lower().split()][:s]
            ids = ids + [0] * (s - len(ids))
        return jnp.asarray([ids], jnp.int32)

    def _sample_impl(self, cond, uncond, key, *, steps, h, w,
                     guidance_scale):
        pooled = time_ids = None
        if isinstance(cond, tuple):   # SDXL: (ctx, pooled) per side
            ctx = jnp.concatenate([uncond[0], cond[0]], axis=0)
            pooled = jnp.concatenate([uncond[1], cond[1]], axis=0)
            # micro-conditioning: original size, crop origin, target size
            time_ids = jnp.tile(
                jnp.asarray([[h, w, 0, 0, h, w]], jnp.float32), (2, 1))
        else:
            ctx = jnp.concatenate([uncond, cond], axis=0)
        lc = self.vae_cfg.get("latent_channels", 4)
        latents = jax.random.normal(
            key, (1, h // self.vae_scale, w // self.vae_scale, lc),
            jnp.float32)
        ts = jnp.linspace(self.n_train - 1, 0, steps).astype(jnp.int32)

        def body(lat, i):
            t = ts[i]
            t_prev = jnp.where(i + 1 < steps, ts[jnp.minimum(i + 1,
                                                             steps - 1)], -1)
            lat2 = jnp.concatenate([lat, lat], axis=0).astype(ctx.dtype)
            eps = unet_apply(self.unet_w, self.unet_cfg, lat2,
                             jnp.full((2,), t, jnp.int32), ctx,
                             add_text_embeds=pooled, add_time_ids=time_ids)
            eps = eps.astype(jnp.float32)
            eps_u, eps_c = eps[:1], eps[1:]
            e = eps_u + guidance_scale * (eps_c - eps_u)
            a_t = self.alphas_bar[t]
            a_prev = jnp.where(t_prev >= 0, self.alphas_bar[t_prev], 1.0)
            x0 = (lat - jnp.sqrt(1 - a_t) * e) / jnp.sqrt(a_t)
            lat = jnp.sqrt(a_prev) * x0 + jnp.sqrt(1 - a_prev) * e  # DDIM η=0
            return lat, None

        latents, _ = jax.lax.scan(body, latents, jnp.arange(steps))
        return vae_decode(self.vae_w, self.vae_cfg,
                          latents.astype(ctx.dtype))

    def encode_prompts(self, prompt: str, negative_prompt: str = ""):
        """(cond, uncond) CLIP embeddings — reusable across frames/seeds.

        SD 1.x/2.x: each side is the final-LN hidden state [1, 77, D].
        SDXL: each side is (ctx, pooled) — ctx the channel-concat of both
        encoders' penultimate hidden states [1, 77, D1+D2], pooled the
        projected EOT embedding of encoder 2 [1, P]."""
        if not self.is_xl:
            return (clip_encode(self.text_w, self.text_cfg,
                                self._encode_text(prompt)),
                    clip_encode(self.text_w, self.text_cfg,
                                self._encode_text(negative_prompt)))

        def enc(text):
            h1 = clip_encode(self.text_w, self.text_cfg,
                             self._encode_text(text), penultimate=True)
            h2, pooled = clip_encode(
                self.text2_w, self.text2_cfg,
                self._encode_text(text, self.tokenizer_2, self.text2_cfg),
                penultimate=True, with_pooled=True)
            return jnp.concatenate([h1, h2], axis=-1), pooled

        return enc(prompt), enc(negative_prompt)

    def sample(self, cond, uncond, *, width: int, height: int,
               steps: int = 20, guidance_scale: float = 7.5,
               seed: int = 0) -> np.ndarray:
        """Precomputed embeddings → uint8 HWC image."""
        if (width % self.vae_scale or height % self.vae_scale
                or width < self.vae_scale or height < self.vae_scale):
            raise ValueError(
                f"width/height must be positive multiples of "
                f"{self.vae_scale} (got {width}x{height})")
        img = self._sample(cond, uncond, jax.random.PRNGKey(seed),
                           steps=steps, h=height, w=width,
                           guidance_scale=guidance_scale)
        return np.asarray(jax.device_get(
            jnp.round(img[0] * 255))).astype(np.uint8)

    def txt2img(self, prompt: str, negative_prompt: str = "",
                width: int = 512, height: int = 512, steps: int = 20,
                guidance_scale: float = 7.5, seed: int = 0) -> np.ndarray:
        """→ uint8 HWC image."""
        cond, uncond = self.encode_prompts(prompt, negative_prompt)
        return self.sample(cond, uncond, width=width, height=height,
                           steps=steps, guidance_scale=guidance_scale,
                           seed=seed)
