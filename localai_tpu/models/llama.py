"""Llama-family decoder (Llama 2/3, Mistral, Qwen2/2.5, TinyLlama, ...).

Role in the framework: the flagship text engine — what llama.cpp's GGUF
decoder is to the reference (/root/reference/backend/cpp/llama-cpp/
grpc-server.cpp drives llama.cpp's model; here the model IS JAX code).

Design (TPU-first, not a torch translation):
- pure functions over a param pytree; layers STACKED on a leading axis and
  executed with lax.scan → one compiled layer body, low compile time, and
  XLA pipelines the weight prefetch (HBM→VMEM) across layers.
- bf16 weights/activations, f32 norms/softmax/logits head.
- GQA with a slot-contiguous, head-major KV cache [L, B, KVH, T, D] carried
  through scan (trailing (T, D) dims = the Mosaic-legal Pallas tiling).
- tensor parallelism by GSPMD: param PartitionSpecs (see param_specs) put
  heads/ffn on the `model` mesh axis; activations get with_sharding_constraint
  hints; XLA inserts the all-reduces (the NCCL-free answer to vLLM's
  tensor_parallel_size — /root/reference/backend/python/vllm/backend.py:106).
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from localai_tpu.ops.norms import rms_norm
from localai_tpu.ops.rope import RopeConfig, rope_table, apply_rope
from localai_tpu.ops.attention import mha_prefill, mha_decode
from localai_tpu.ops.kvcache import (
    QuantKV, cache_scatter, dequant, init_quant, is_quant_kind, padded_len,
    requantize,
)
from localai_tpu.ops.quant import qmatmul
from localai_tpu.parallel.mesh import constrain


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int = 128
    max_position: int = 8192
    rms_eps: float = 1e-5
    rope_base: float = 10000.0
    rope_scaling: str = "none"          # none|linear|yarn|llama3
    rope_scale_factor: float = 1.0
    rope_original_max_position: int = 8192
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_beta_fast: float = 32.0
    rope_beta_slow: float = 1.0
    rope_attn_factor: float | None = None
    qkv_bias: bool = False              # Qwen2
    tie_embeddings: bool = False
    sliding_window: int | None = None   # Mistral
    num_experts: int = 0                # Mixtral MoE (0 = dense MLP)
    experts_per_tok: int = 2
    dtype: str = "bfloat16"

    @property
    def rope(self) -> RopeConfig:
        return RopeConfig(
            head_dim=self.head_dim,
            base=self.rope_base,
            scaling=self.rope_scaling,
            scale_factor=self.rope_scale_factor,
            original_max_position=self.rope_original_max_position,
            low_freq_factor=self.rope_low_freq_factor,
            high_freq_factor=self.rope_high_freq_factor,
            beta_fast=self.rope_beta_fast,
            beta_slow=self.rope_beta_slow,
            attn_factor=self.rope_attn_factor,
        )

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


# ---------------------------------------------------------------- params

def init_params(cfg: LlamaConfig, key, dtype=None):
    """Random init (tests + training). Layout matches load_safetensors output."""
    dtype = dtype or cfg.jdtype
    h, hd = cfg.hidden_size, cfg.head_dim
    nh, nkv, L, I = cfg.num_heads, cfg.num_kv_heads, cfg.num_layers, cfg.intermediate_size
    ks = jax.random.split(key, 10)

    def norm(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) * (fan_in ** -0.5)).astype(dtype)

    layers = {
        "attn_norm": jnp.ones((L, h), dtype),
        "wq": norm(ks[0], (L, h, nh * hd), h),
        "wk": norm(ks[1], (L, h, nkv * hd), h),
        "wv": norm(ks[2], (L, h, nkv * hd), h),
        "wo": norm(ks[3], (L, nh * hd, h), nh * hd),
        "mlp_norm": jnp.ones((L, h), dtype),
    }
    if cfg.num_experts:
        E = cfg.num_experts
        layers["moe_gate"] = norm(ks[4], (L, h, E), h).astype(jnp.float32)
        layers["moe_w1"] = norm(ks[5], (L, E, h, I), h)
        layers["moe_w2"] = norm(ks[6], (L, E, I, h), I)
        layers["moe_w3"] = norm(ks[9], (L, E, h, I), h)
    else:
        layers.update({
            "w_gate": norm(ks[4], (L, h, I), h),
            "w_up": norm(ks[5], (L, h, I), h),
            "w_down": norm(ks[6], (L, I, h), I),
        })
    if cfg.qkv_bias:
        layers["bq"] = jnp.zeros((L, nh * hd), dtype)
        layers["bk"] = jnp.zeros((L, nkv * hd), dtype)
        layers["bv"] = jnp.zeros((L, nkv * hd), dtype)
    params = {
        "embed": norm(ks[7], (cfg.vocab_size, h), h),
        "layers": layers,
        "final_norm": jnp.ones((h,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(ks[8], (h, cfg.vocab_size), h)
    return params


def param_specs(cfg: LlamaConfig, qbits: int | None = None):
    """PartitionSpecs over mesh axes ('data','model'): Megatron-style TP.

    qkv/gate/up column-parallel, wo/down row-parallel, lm_head vocab-parallel,
    embed replicated. XLA GSPMD inserts the psum after wo/w_down.

    With `qbits` the projection leaves become {"q", "s"} spec dicts matching
    ops/quant.quantize's layout (the flagship int8-W recipe under a mesh):
    `q` shards exactly like the bf16 weight it replaces; the per-output-
    channel scale [..., 1, out] keeps the output-axis sharding and replicates
    the reduced-away input axis — so a row-parallel wo keeps its scales
    whole on every chip while its int8 body shards on the input axis.
    """
    layers = {
        "attn_norm": P(None, None),
        "wq": P(None, None, "model"),
        "wk": P(None, None, "model"),
        "wv": P(None, None, "model"),
        "wo": P(None, "model", None),
        "mlp_norm": P(None, None),
    }
    if cfg.num_experts:
        # expert parallelism: experts sharded over the `model` axis (the
        # GSPMD answer to EP — XLA reduces the masked combine across shards)
        layers["moe_gate"] = P(None, None, None)
        layers["moe_w1"] = P(None, "model", None, None)
        layers["moe_w2"] = P(None, "model", None, None)
        layers["moe_w3"] = P(None, "model", None, None)
    else:
        layers.update({
            "w_gate": P(None, None, "model"),
            "w_up": P(None, None, "model"),
            "w_down": P(None, "model", None),
        })
    if cfg.qkv_bias:
        layers["bq"] = P(None, "model")
        layers["bk"] = P(None, "model")
        layers["bv"] = P(None, "model")
    specs = {
        "embed": P(None, None),
        "layers": layers,
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "model")
    if qbits:
        # mirror ops/quant.quantize_params' selection: every projection
        # matrix becomes {q, s}; norms/biases/embed/moe_gate stay dense
        def qspec(spec):
            body = tuple(spec)
            return {"q": spec, "s": P(*body[:-2], None, body[-1])}

        for k in list(layers):
            if k.startswith("w") or k.startswith("moe_w"):
                layers[k] = qspec(layers[k])
        if not cfg.tie_embeddings:
            specs["lm_head"] = qspec(specs["lm_head"])
    return specs


def replicated_specs(cfg: LlamaConfig, qbits: int | None = None):
    """Fully-replicated PartitionSpecs (same tree as param_specs, incl. the
    quantized {q, s} leaves when qbits is given). The right placement for a
    draft model whose dims don't divide the TP axis: drafts are small by
    design, so every chip holds a full copy."""
    import jax

    return jax.tree_util.tree_map(lambda _: P(), param_specs(cfg, qbits))


def max_model_axis(cfg: LlamaConfig, n_devices: int) -> int:
    """Largest divisor of n_devices usable as the TP ('model') mesh axis: it
    must divide every dimension param_specs/kv_cache_spec shard on it."""
    dims = [
        cfg.num_heads * cfg.head_dim,
        cfg.num_kv_heads * cfg.head_dim,
        cfg.intermediate_size,
        cfg.num_kv_heads,  # kv cache shards the head axis
    ]
    if cfg.num_experts:
        dims.append(cfg.num_experts)  # expert parallelism
    if not cfg.tie_embeddings:
        dims.append(cfg.vocab_size)  # vocab-parallel lm_head
    for d in range(n_devices, 0, -1):
        if n_devices % d == 0 and all(dim % d == 0 for dim in dims):
            return d
    return 1


def kv_cache_spec(cache_type: str = ""):
    """KV cache [L, B, KVH, T, D]: slots on `data`, kv heads on `model`."""
    spec = P(None, "data", "model", None, None)
    if is_quant_kind(cache_type):
        return QuantKV(q=spec, s=spec)
    return spec


def paged_pool_spec():
    """Paged block pool [L, NB, KVH, BS, D] (and its QuantKV scale twin):
    the physical-block axis stays replicated — the host allocator hands out
    block ids with no notion of placement — and KV heads shard on `model`,
    the same head-parallelism the dense cache uses. Holds for both the q and
    s leaves of a QuantKV pool (same leading dims)."""
    return P(None, None, "model", None, None)


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int, dtype=None,
                  cache_type: str = ""):
    """Head-major cache [L, B, KVH, T, D] — trailing (T, D) dims are the
    Mosaic-legal tiling for the Pallas decode kernel, and the decode hot path
    reads it with zero transposes.

    cache_type "int8"/"q8_0" (reference CacheTypeKey/Value,
    /root/reference/backend/backend.proto:257-258) stores int8 + per-token
    scales (ops/kvcache.py) at half the HBM; the token axis is then padded to
    the 128 scale tile (extra rows are never read — lengths mask them).
    """
    if is_quant_kind(cache_type):
        shape = (cfg.num_layers, batch, cfg.num_kv_heads,
                 padded_len(max_len), cfg.head_dim)
        return init_quant(shape), init_quant(shape)
    dtype = dtype or cfg.jdtype
    shape = (cfg.num_layers, batch, cfg.num_kv_heads, max_len, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def _cache_write(kc, vc, k, v, rows, positions, table=None, unique=True,
                 redirect=None, kvt=None):
    """Scatter window K/V [B, S, KVH, D] into head-major caches [B', KVH, T, D]
    at (rows[b], :, positions[b, s]). With a paged `table` [B, MAXB] the cache
    is a block pool [NB, KVH, BS, D] and (slot, position) resolves to
    (table[slot, pos // BS], :, pos % BS) — ops/paged.py layout.

    redirect [B] bool (paged only): rows flagged True write to the TRASH
    block (physical 0, ops/paged.py) at offset (row*S + s) % BLOCK instead
    of through their table — the inactive-slot redirect for decode (S=1)
    and the spec-verify window (S=gamma+1). Routing by PHYSICAL block keeps
    the garbage out of every real block (a slot's own table can map its
    last virtual block to a RETAINED warm-prefix block); the per-(row, s)
    offsets keep the scatter collision-free only while B*S <= BLOCK —
    callers must drop the uniqueness assertion beyond that.

    unique=True asserts the scatter rows never collide: decode rows target
    distinct slots (one row per slot; redirected rows get distinct trash
    offsets), so the assertion holds and keeps XLA on the in-place scatter
    path — without it the table-gathered indices are unprovably unique and
    the layer scan re-materializes the whole pool every decode step
    (O(pool) per token). Callers pass unique=False when collisions are
    REAL: batched admission pads groups by repeating a plan
    (engine._flush_admits), and a final prefill chunk's padded tail
    positions resolve to shared trash offsets — don't lie to the compiler
    on those paths (both are per-request, not per-token).

    kvt (paged only, KV lifecycle tier — engine/kvtier.py): per-slot
    residency arrays {"sb": [B], "rw": [B], ...}; raw block indices are
    ring-mapped (ops/paged.ring_block_map) before the table lookup, so a
    windowed slot's writes reuse its O(window) ring columns in place.
    Full-policy slots carry the identity sentinel — same program, no
    recompile across policy mixes. Uniqueness survives the mapping: the
    ring's wrap period (rw*BLOCK tokens) exceeds any single write window
    by construction (kvtier.ring_blocks margins)."""
    kvh = kc.shape[1]
    if table is None:
        idx = (rows[:, None, None], jnp.arange(kvh)[None, :, None],
               positions[:, None, :])
    else:
        from localai_tpu.ops.paged import BLOCK

        raw = positions // BLOCK
        if kvt is not None:
            from localai_tpu.ops.paged import ring_block_map

            raw = ring_block_map(raw, kvt["sb"][rows][:, None],
                                 kvt["rw"][rows][:, None])
        pb = table[rows[:, None], raw]                     # [B, S] physical
        off = positions % BLOCK
        if redirect is not None:
            # distinct per-(row, window-pos) trash offsets: collision-free
            # (and so assertable-unique) as long as B*S <= BLOCK
            s = positions.shape[1]
            tr_off = (rows[:, None] * s
                      + jnp.arange(s)[None, :]) % BLOCK
            pb = jnp.where(redirect[:, None], 0, pb)
            off = jnp.where(redirect[:, None], tr_off, off)
        idx = (pb[:, None, :], jnp.arange(kvh)[None, :, None],
               off[:, None, :])
    if isinstance(kc, QuantKV):
        return (cache_scatter(kc, idx, k.transpose(0, 2, 1, 3), unique),
                cache_scatter(vc, idx, v.transpose(0, 2, 1, 3), unique))
    kc = kc.at[idx].set(k.transpose(0, 2, 1, 3), unique_indices=unique)
    vc = vc.at[idx].set(v.transpose(0, 2, 1, 3), unique_indices=unique)
    return kc, vc


# ---------------------------------------------------------------- forward

def _qkv(x, lp, cfg: LlamaConfig, spec=None):
    """QKV projections. `spec` (optional) is the head-parallel output
    constraint (P(batch_ax, seq_ax, 'model')) threaded into qmatmul so TP
    keeps the (possibly int8) projection weights resident-sharded. Callers
    under shard_map (parallel/pipeline.py) leave it None."""
    b, s, _ = x.shape
    q = qmatmul(x, lp["wq"], spec)
    k = qmatmul(x, lp["wk"], spec)
    v = qmatmul(x, lp["wv"], spec)
    if cfg.qkv_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = k.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    return q, k, v


def _lm_head(x32, params):
    """Vocabulary projection in f32 (tied embeddings or separate, possibly
    int8-quantized, lm_head)."""
    from localai_tpu.ops.quant import is_quantized

    head = params.get("lm_head", None)
    if head is None:
        return x32 @ params["embed"].astype(jnp.float32).T
    if is_quantized(head):
        # int8 values are exact in bf16, so a bf16×bf16 dot with f32
        # accumulation loses only the f32→bf16 rounding of the activations —
        # noise next to the int8 weight quantization — while halving the
        # projection's HBM traffic vs dequant-to-f32 (2.2 ms → ~1 ms/step
        # on v5e at the 128k vocab)
        y = jnp.dot(x32.astype(jnp.bfloat16), head["q"].astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32)
        return y * head["s"].astype(jnp.float32)
    return qmatmul(x32, head)


def _mlp(x, lp, cfg=None, spec_prefix=None):
    """Gated MLP. `spec_prefix` (optional tuple, e.g. ('data', None)) is the
    leading batch/seq sharding of the activation: when given, gate/up outputs
    are constrained ffn-parallel (…, 'model') and the down projection back to
    (…, None) — the hints that keep TP weights sharded through the scan."""
    if "moe_gate" in lp:
        return _moe_mlp(x, lp, cfg.experts_per_tok if cfg else 2)
    up_spec = down_spec = None
    if spec_prefix is not None:
        up_spec = P(*spec_prefix, "model")
        down_spec = P(*spec_prefix, None)
    return qmatmul(jax.nn.silu(qmatmul(x, lp["w_gate"], up_spec))
                   * qmatmul(x, lp["w_up"], up_spec),
                   lp["w_down"], down_spec)


def _moe_mlp(x, lp, k: int):
    """Mixtral top-k routed experts (reference: the MoE GGUFs llama.cpp
    serves within ggml — SURVEY §2.4 expert-parallel row; HF semantics:
    softmax router → top-k → renormalize → weighted expert sum).

    Dense dispatch: every expert runs on every token and the top-k mask
    zeroes the rest — einsum-shaped for the MXU and for GSPMD expert
    parallelism (experts sharded on the `model` mesh axis; XLA turns the
    masked combine into an all-reduce). Top-k gather/scatter dispatch is a
    later optimization for large-E prefill."""
    from localai_tpu.ops.quant import dequantize, is_quantized

    def dq(p):
        return dequantize(p, x.dtype) if is_quantized(p) else p

    gate = lp["moe_gate"].astype(jnp.float32)
    logits = x.astype(jnp.float32) @ gate                      # [B, S, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_w, top_i = jax.lax.top_k(probs, k)
    top_w = top_w / jnp.maximum(top_w.sum(-1, keepdims=True), 1e-9)
    E = gate.shape[-1]
    combine = jnp.einsum("bske,bsk->bse",
                         jax.nn.one_hot(top_i, E, dtype=jnp.float32), top_w)
    w1, w2, w3 = dq(lp["moe_w1"]), dq(lp["moe_w2"]), dq(lp["moe_w3"])
    h1 = jnp.einsum("bsh,ehi->bsei", x, w1)
    h3 = jnp.einsum("bsh,ehi->bsei", x, w3)
    y = jnp.einsum("bsei,eih->bseh", jax.nn.silu(h1) * h3, w2)
    return jnp.einsum("bseh,bse->bsh", y, combine.astype(x.dtype))


# Activation sharding hints: hard constraints when a mesh is active (raises on
# a wrong spec), identity otherwise. See localai_tpu/parallel/mesh.py.
_shard_act = constrain


def _seq_ax():
    """'seq' when the ambient mesh carries the ring-attention axis, else None
    (specs naming absent axes would raise)."""
    from localai_tpu.parallel.mesh import current_mesh, seq_axis_size

    return "seq" if seq_axis_size(current_mesh()) > 1 else None


def _tiered_kv(kc, vc, table_rows, sb, rw, length, ctab=None, ck=None,
               cv=None):
    """Materialize the RESIDENT (ring-mapped) cache view for the KV
    lifecycle tier (engine/kvtier.py): the per-slot table gather
    [B, MAXB*BS] plus explicit true positions and row validity, optionally
    concatenated with the dequantized int8 cold tier.

    table_rows [B, MAXB]; sb/rw/length [B] (already row-indexed by the
    caller). ctab [B, MAXB_FULL] (quantize_cold): cold block per raw
    virtual block, 0 = not demoted; ck/cv are the cold QuantKV pools for
    this layer. Demoted blocks drop out of the hot view (their ring column
    may already hold a newer generation's rows) and are read from the cold
    pool at their true positions instead. Returns
    (k [B, KVH, T, D], v, pos [B, T], ok [B, T]) — `ok` covers residency +
    freshness (+ demotion state); retention masking (window/sinks) is the
    attention caller's layer."""
    from localai_tpu.ops.paged import (
        BLOCK, paged_view, resident_block_positions, resident_row_positions,
    )

    maxb = table_rows.shape[1]
    kr, vr = paged_view(kc, table_rows), paged_view(vc, table_rows)
    pos, ok = resident_row_positions(maxb, sb, rw, length)
    k, v = dequant(kr), dequant(vr)
    if ctab is not None:
        b = pos.shape[0]
        mb_full = ctab.shape[1]
        raw, _ = resident_block_positions(maxb, sb, rw, length)
        demoted = ctab != 0                                # [B, MAXB_FULL]
        hot_dem = jnp.take_along_axis(
            demoted, jnp.clip(raw, 0, mb_full - 1), axis=1)
        hot_dem = hot_dem & (raw >= 0) & (raw < mb_full)   # [B, MAXB]
        keep = jnp.broadcast_to(~hot_dem[:, :, None],
                                (b, maxb, BLOCK)).reshape(b, maxb * BLOCK)
        ok = ok & keep
        ckr = paged_view(ck, ctab)
        cvr = paged_view(cv, ctab)
        posc = jnp.arange(mb_full * BLOCK, dtype=jnp.int32)[None, :]
        okc = jnp.broadcast_to(demoted[:, :, None],
                               (b, mb_full, BLOCK)).reshape(b,
                                                            mb_full * BLOCK)
        okc = okc & (posc < length[:, None])
        k = jnp.concatenate([k, dequant(ckr).astype(k.dtype)], axis=2)
        v = jnp.concatenate([v, dequant(cvr).astype(v.dtype)], axis=2)
        pos = jnp.concatenate(
            [pos, jnp.broadcast_to(posc, (b, mb_full * BLOCK))], axis=1)
        ok = jnp.concatenate([ok, okc], axis=1)
    return k, v, pos, ok


def _decode_dq(q, kc, vc, lengths, sliding_window=None, table=None,
               kvt=None, ck=None, cv=None):
    """XLA decode attention over a (possibly quantized) cache: dequant is
    fused into the consuming dots by XLA; quantized caches still halve HBM
    capacity on this path. A paged cache is materialized per layer via
    gather (reference tier — the Pallas kernels stream through the table).

    kvt (KV lifecycle tier, engine/kvtier.py): per-slot residency arrays —
    the gather covers only the RESIDENT ring view (O(sinks+window) rows for
    windowed slots, identity for full-policy slots in the same program) and
    the mask derives from true ring positions; with quantize_cold (ck/cv —
    this layer's cold pools) the exited-window blocks attend from the int8
    cold tier instead of being dropped."""
    if kvt is not None:
        from localai_tpu.ops.attention import mha_decode_masked

        cold = "cold_tab" in kvt
        k, v, pos, ok = _tiered_kv(
            kc, vc, table, kvt["sb"], kvt["rw"], lengths,
            ctab=kvt["cold_tab"] if cold else None, ck=ck, cv=cv)
        if cold:
            mask = ok  # demotion state decides hot vs cold; nothing evicted
        else:
            mask = ok & ((pos >= (lengths - kvt["window"])[:, None])
                         | (pos < kvt["sinks"][:, None]))
        return mha_decode_masked(q, k, v, mask)
    if table is not None:
        from localai_tpu.ops.paged import paged_view

        kc, vc = paged_view(kc, table), paged_view(vc, table)
    return mha_decode(q, dequant(kc), dequant(vc), lengths,
                      sliding_window=sliding_window)


def _pallas_paged_scatter(cfg: LlamaConfig | None, kv_quant: bool) -> bool:
    """Whether the paged decode write should use the Pallas scatter-append
    kernel (ops/pallas/paged_scatter.py) instead of the XLA scatter. Same
    tier selection as _attn_impls' decode branch: Pallas on TPU (probe-gated)
    or under LOCALAI_FORCE_PALLAS; XLA on CPU and under LOCALAI_NO_PALLAS.

    Under a mesh the pool shards its KV-head axis on 'model' and the kernel
    runs per-shard via shard_map (paged_scatter_append_sharded) — usable iff
    the KV-head count divides the TP axis; otherwise the XLA scatter tier
    handles the (unevenly shardable) pool."""
    import os

    from localai_tpu.parallel.mesh import current_mesh

    mesh = current_mesh()
    if mesh is not None:
        if cfg is None:
            return False
        tp = dict(zip(mesh.axis_names, mesh.devices.shape)).get("model", 1)
        if cfg.num_kv_heads % int(tp):
            return False
    if os.environ.get("LOCALAI_FORCE_PALLAS") == "1":
        return True
    if (os.environ.get("LOCALAI_NO_PALLAS") == "1"
            or jax.default_backend() != "tpu"):
        return False
    from localai_tpu.ops.pallas import pallas_works

    if cfg is not None:
        return pallas_works(cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
                            cfg.sliding_window, cfg.jdtype, kv_quant=kv_quant)
    return pallas_works(kv_quant=kv_quant)


def _attn_impls(cfg: LlamaConfig | None = None, kv_quant: bool = False):
    """Select attention kernels at trace time: Pallas (fused, online-softmax)
    on single-chip TPU; XLA reference under a mesh (GSPMD shards the einsums)
    or on CPU. LOCALAI_FORCE_PALLAS=1 forces Pallas (interpreter on CPU —
    used by tests); LOCALAI_NO_PALLAS=1 forces the XLA path."""
    import os

    from localai_tpu.parallel.mesh import current_mesh

    force = os.environ.get("LOCALAI_FORCE_PALLAS") == "1"
    block = os.environ.get("LOCALAI_NO_PALLAS") == "1"
    mesh = current_mesh()
    if mesh is not None and not force:
        from localai_tpu.parallel.mesh import seq_axis_size

        if seq_axis_size(mesh) > 1:
            # sequence parallelism: prefill rides the ppermute ring over the
            # 'seq' axis (parallel/ring_attention.py); decode (S=1) stays on
            # the XLA path with GSPMD sharding
            from localai_tpu.parallel.ring_attention import ring_prefill

            return (lambda q, k, v, lengths, sliding_window=None:
                    ring_prefill(q, k, v, lengths, mesh=mesh,
                                 sliding_window=sliding_window),
                    _decode_dq)
        return mha_prefill, _decode_dq
    use = force or (not block and jax.default_backend() == "tpu"
                    and current_mesh() is None)
    if use and not force:
        # compile-probe this model's head geometry once: if Mosaic rejects
        # the kernels on this chip, serve on the XLA path instead of dying
        # inside the jitted step
        from localai_tpu.ops.pallas import pallas_works

        if cfg is not None:
            use = pallas_works(cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
                               cfg.sliding_window, cfg.jdtype,
                               kv_quant=kv_quant)
        else:
            use = pallas_works(kv_quant=kv_quant)
    if use:
        from localai_tpu.ops.pallas import (
            flash_prefill, ragged_decode, ragged_decode_q8,
        )

        def attn_decode(q, kc, vc, lengths, sliding_window=None, table=None,
                        kvt=None, ck=None, cv=None):
            if kvt is not None:
                # KV lifecycle tier: the ring-position/tier-map read rides
                # the XLA reference path for now — the Pallas decode kernel
                # has no per-slot ring-geometry scalar prefetch yet (the
                # WRITE side is kernel-native: paged_scatter's targets are
                # ring-mapped before the DMA kernel). TODO(kvtier): teach
                # _decode_kernel the ring map + per-block dtype tier.
                return _decode_dq(q, kc, vc, lengths,
                                  sliding_window=sliding_window, table=table,
                                  kvt=kvt, ck=ck, cv=cv)
            if isinstance(kc, QuantKV):
                return ragged_decode_q8(q, kc.q, kc.s, vc.q, vc.s, lengths,
                                        sliding_window=sliding_window,
                                        table=table)
            return ragged_decode(q, kc, vc, lengths,
                                 sliding_window=sliding_window, table=table)

        return (lambda q, k, v, lengths, sliding_window=None:
                flash_prefill(q, k, v, lengths, sliding_window=sliding_window),
                attn_decode)
    return mha_prefill, _decode_dq


def prefill(params, cfg: LlamaConfig, tokens, lengths, cos, sin,
            k_cache, v_cache, slot_map, table=None, inject=None, kvt=None):
    """Process padded prompt batch, writing K/V into slot rows of the cache.

    tokens: [B, S] i32 (padded); lengths: [B]; slot_map: [B] i32 — which cache
    slot each batch row writes into; cos/sin: rope tables; table: optional
    paged block table (ops/paged.py). inject (extra [B, S, H], is_embed
    [B, S] bool), optional: positions with is_embed take `extra` rows instead
    of the token embedding — the multimodal path (models/llava.py) splices
    projected image features into the prompt here.
    Returns (last_token_logits [B, V] f32, k_cache, v_cache).
    """
    b, s = tokens.shape
    attn_prefill, _ = _attn_impls(cfg)
    if kvt is not None:
        # KV lifecycle tier: first-chunk self-attention under the per-slot
        # sink+window retention mask (engine/kvtier.py). quantize_cold slots
        # keep full causal coverage (exited content is demoted, not
        # dropped), so the window term is lifted to a sentinel there.
        from localai_tpu.ops.attention import mha_prefill_tiered

        _sinks = kvt["sinks"][slot_map]
        _window = kvt["window"][slot_map]
        if "cold_tab" in kvt:
            _window = jnp.full_like(_window, jnp.int32(1 << 30))

        def attn_prefill(q, k, v, lengths, sliding_window=None):  # noqa: F811
            return mha_prefill_tiered(q, k, v, lengths, _sinks, _window)
    positions = jnp.arange(s)[None, :].repeat(b, 0)
    sax = _seq_ax()
    x = params["embed"].astype(cfg.jdtype)[tokens]
    if inject is not None:
        extra, is_embed = inject
        x = jnp.where(is_embed[..., None], extra.astype(x.dtype), x)
    x = _shard_act(x, P("data", sax, None))

    def layer(x, xs):
        lp, kc, vc = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(h, lp, cfg, spec=P("data", sax, "model"))
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        q = _shard_act(q, P("data", sax, "model", None))
        attn = attn_prefill(q, k, v, lengths, sliding_window=cfg.sliding_window)
        x = x + qmatmul(attn.reshape(b, s, -1), lp["wo"],
                        spec=P("data", sax, None))
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        x = x + _mlp(h, lp, cfg, spec_prefix=("data", sax))
        x = _shard_act(x, P("data", sax, None))
        # unique=False: batched admission pads groups by repeating a real
        # request's plan (engine _flush_admits), so slot_map can repeat
        kc, vc = _cache_write(kc, vc, k, v, slot_map, positions, table,
                              unique=False, kvt=kvt)
        return x, (kc, vc)

    x, (k_cache, v_cache) = jax.lax.scan(
        layer, x, (params["layers"], k_cache, v_cache)
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    last = jnp.take_along_axis(
        x, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1
    )[:, 0]
    logits = _lm_head(last.astype(jnp.float32), params)
    return logits, k_cache, v_cache


def decode_step(params, cfg: LlamaConfig, tokens, lengths, cos, sin,
                k_cache, v_cache, active=None, table=None, kvt=None):
    """One continuous-batching decode step over ALL slots.

    tokens: [B] i32 — last sampled token per slot; lengths: [B] — cache entries
    valid per slot BEFORE this token (the new token is written at index
    lengths). Inactive slots just compute garbage that is masked host-side.
    `active` [B] bool (optional): inactive slots redirect their cache write to
    the last cache row (never a readable position — the engine terminates at
    max_context-1) so a decode step can run concurrently with a chunked
    prefill into an inactive slot without corrupting it.
    `table` [B, MAXB] i32 (optional): block-paged cache (ops/paged.py) — the
    redirect row then resolves through the table's last virtual block, which
    is the trash block for any slot not allocated to full context.
    Returns (logits [B, V] f32, k_cache, v_cache).
    """
    b = tokens.shape[0]
    kv_quant = isinstance(k_cache, QuantKV)
    T = k_cache.shape[3] if table is None else table.shape[1] * 128
    _, attn_decode = _attn_impls(cfg, kv_quant=kv_quant)
    positions = lengths[:, None]  # [B,1]
    if active is None:
        wpos, redirect = positions, None
    elif table is None:
        # dense: each row owns its slot row, so T-1 (never readable — the
        # engine terminates at max_context-2) is a safe per-row target
        wpos, redirect = jnp.where(active[:, None], positions, T - 1), None
    else:
        # paged: inactive rows write to the trash block at distinct per-row
        # offsets (_cache_write redirect) — never through their own table,
        # whose last virtual block can be a RETAINED warm-prefix block
        wpos, redirect = positions, ~active
    unique = table is None or b <= 128
    # paged Pallas tier: the per-step write is a scatter-append DMA kernel
    # (O(slots) traffic, provably in place) instead of an XLA scatter
    # through gathered physical indices — the scatter XLA de-optimizes into
    # a full-pool copy inside the fused decode block (VERDICT Weak #2)
    kernel_write = table is not None and _pallas_paged_scatter(cfg, kv_quant)
    # under a mesh the pool shards its KV-head axis: the kernel runs
    # per-shard via shard_map (pallas_call has no GSPMD partitioning rule —
    # without this the partitioner would all-gather the whole pool)
    write_mesh = None
    if kernel_write:
        from localai_tpu.parallel.mesh import current_mesh

        write_mesh = current_mesh()
    x = params["embed"].astype(cfg.jdtype)[tokens][:, None, :]  # [B,1,H]
    x = _shard_act(x, P("data", None, None))
    # KV lifecycle tier: the cold pools (per-layer, like kc/vc) ride the scan
    # as extra READ-ONLY xs — the demote copy is a separate host-driven jit
    # (engine._demote_fn), so ys stays (kc, vc)
    cold = kvt is not None and "cold_tab" in kvt
    sb = rw = None
    if kvt is not None:
        sb, rw = kvt["sb"], kvt["rw"]

    def layer(x, xs):
        if cold:
            lp, kc, vc, ck, cv = xs
        else:
            (lp, kc, vc), ck, cv = xs, None, None
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(h, lp, cfg, spec=P("data", None, "model"))
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        q = _shard_act(q, P("data", None, "model", None))
        if kernel_write:
            from localai_tpu.ops.pallas import (
                paged_scatter_append, paged_scatter_append_q8,
                paged_scatter_append_q8_sharded, paged_scatter_append_sharded,
            )

            if kv_quant:
                if write_mesh is not None:
                    kq, ks, vq, vs = paged_scatter_append_q8_sharded(
                        write_mesh, kc.q, kc.s, vc.q, vc.s, k[:, 0], v[:, 0],
                        lengths, table, active, sb=sb, rw=rw)
                else:
                    kq, ks, vq, vs = paged_scatter_append_q8(
                        kc.q, kc.s, vc.q, vc.s, k[:, 0], v[:, 0], lengths,
                        table, active, sb=sb, rw=rw)
                kc, vc = QuantKV(kq, ks), QuantKV(vq, vs)
            elif write_mesh is not None:
                kc, vc = paged_scatter_append_sharded(
                    write_mesh, kc, vc, k[:, 0], v[:, 0], lengths, table,
                    active, sb=sb, rw=rw)
            else:
                kc, vc = paged_scatter_append(kc, vc, k[:, 0], v[:, 0],
                                              lengths, table, active,
                                              sb=sb, rw=rw)
        else:
            kc, vc = _cache_write(kc, vc, k, v, jnp.arange(b), wpos, table,
                                  unique=unique, redirect=redirect, kvt=kvt)
        attn = attn_decode(q, kc, vc, lengths + 1,
                           sliding_window=cfg.sliding_window, table=table,
                           kvt=kvt, ck=ck, cv=cv)
        x = x + qmatmul(attn.reshape(b, 1, -1), lp["wo"],
                        spec=P("data", None, None))
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        x = x + _mlp(h, lp, cfg, spec_prefix=("data", None))
        return x, (kc, vc)

    xs = (params["layers"], k_cache, v_cache)
    if cold:
        xs = xs + (kvt["cold_k"], kvt["cold_v"])
    x, (k_cache, v_cache) = jax.lax.scan(layer, x, xs)
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _lm_head(x[:, 0].astype(jnp.float32), params)
    return logits, k_cache, v_cache


def ragged_forward(params, cfg: LlamaConfig, tokens, cos, sin,
                   k_cache, v_cache, block_seq, qstart, qlen, kvlen,
                   tables, logit_rows, kvt=None, inject=None):
    """Mixed prefill+decode forward over ONE flat token stream (ragged
    continuous batching, arXiv:2604.15464): decode tokens and chunked-prefill
    windows from different requests pack into a single [T] stream and run as
    one dispatch on the paged tier — no per-bucket padding, no separate
    prefill and decode programs on mixed ticks.

    tokens: [T] i32, T a multiple of ops.pallas.QBLK (8); every sequence's
    rows start on a QBLK boundary (the engine packs this way) so each 8-row
    kernel block belongs to exactly one sequence. Per-sequence metadata
    ([NSEQ], padded with dead entries):
      qstart[s]/qlen[s] — the sequence's row span in the stream (row units);
      kvlen[s] — cache length INCLUDING this chunk (decode: old length + 1);
      tables [NSEQ, MAXB] — block table into the paged pool;
      block_seq [NQB=T/QBLK] — sequence id per q block, -1 for padding
      blocks. logit_rows [NSEQ] — flat row of each sequence's last token
      (decode rows and final prefill chunks; mid-prefill chunks may point
      anywhere — their logits are ignored host-side). A 2-D logit_rows
      [NSEQ, R] gathers R rows per sequence instead (logits [NSEQ, R, V]) —
      the spec-as-ragged verify pass needs the distribution at every row of
      its draft window, not just the last.

    inject: optional (extra [T, H] float, is_embed [T] bool) — rows with
    is_embed take `extra` directly instead of the token-id embedding lookup
    (multimodal prefill chunks pack their projected image/audio embeddings
    into the same flat stream; reference: LLaVA-style mm prompt splicing).

    Everything per-ROW (rope positions, scatter targets) derives on device
    from that per-sequence metadata, so the host ships O(NSEQ) scalars, not
    O(T). Padding rows write to the trash block (physical 0) and produce
    garbage attention output that never reaches a logit row.

    k_cache/v_cache: paged pools [L, NB, KVH, BS, D] (QuantKV int8 twin
    supported). Returns (logits [NSEQ, V] f32, k_cache, v_cache). Tier
    selection matches the decode path: Pallas ragged kernels on TPU (or
    LOCALAI_FORCE_PALLAS), sharded per KV-head shard under a TP mesh, XLA
    gather/scatter twins otherwise."""
    from localai_tpu.ops.pallas import (
        QBLK, ragged_attention_xla, ragged_attention_xla_q8,
        ragged_paged_attention, ragged_paged_attention_q8,
        ragged_paged_attention_q8_sharded, ragged_paged_attention_sharded,
        ragged_scatter_append, ragged_scatter_append_q8,
        ragged_scatter_append_q8_sharded, ragged_scatter_append_sharded,
        ragged_scatter_xla, ragged_scatter_xla_q8,
    )

    t = tokens.shape[0]
    kv_quant = isinstance(k_cache, QuantKV)
    blk = (k_cache.q if kv_quant else k_cache).shape[3]        # pool BS
    use_kernel = _pallas_paged_scatter(cfg, kv_quant)
    mesh = None
    if use_kernel:
        from localai_tpu.parallel.mesh import current_mesh

        mesh = current_mesh()
    block_seq = block_seq.astype(jnp.int32)
    qstart, qlen = qstart.astype(jnp.int32), qlen.astype(jnp.int32)
    kvlen = kvlen.astype(jnp.int32)

    # per-row derivations (device-side, from per-seq metadata): sequence id,
    # liveness, absolute position, and the (physical block, in-block row)
    # scatter target. Dead rows target trash (block 0) at per-row offsets —
    # collisions there only overwrite other dead rows.
    rows = jnp.arange(t, dtype=jnp.int32)
    sid = block_seq[rows // QBLK]
    s = jnp.maximum(sid, 0)
    live = (sid >= 0) & (rows >= qstart[s]) & (rows < qstart[s] + qlen[s])
    pos = kvlen[s] - qlen[s] + (rows - qstart[s])
    pos = jnp.where(live, jnp.clip(pos, 0, cos.shape[0] - 1), 0)
    raw = pos // blk
    if kvt is not None:
        # KV lifecycle tier: fold raw blocks into the per-sequence ring
        # before the table lookup (kvt ships [NSEQ] geometry, like tables)
        from localai_tpu.ops.paged import ring_block_map

        raw = ring_block_map(raw, kvt["sb"][s], kvt["rw"][s])
    pb = jnp.where(live, tables[s, raw], 0)
    off = jnp.where(live, pos % blk, rows % blk)

    def write(kc, vc, kn, vn):
        if use_kernel and kv_quant:
            if mesh is not None:
                kq, ks, vq, vs = ragged_scatter_append_q8_sharded(
                    mesh, kc.q, kc.s, vc.q, vc.s, kn, vn, pb, off)
            else:
                kq, ks, vq, vs = ragged_scatter_append_q8(
                    kc.q, kc.s, vc.q, vc.s, kn, vn, pb, off)
            return QuantKV(kq, ks), QuantKV(vq, vs)
        if use_kernel:
            if mesh is not None:
                return ragged_scatter_append_sharded(mesh, kc, vc, kn, vn,
                                                     pb, off)
            return ragged_scatter_append(kc, vc, kn, vn, pb, off)
        if kv_quant:
            kq, ks, vq, vs = ragged_scatter_xla_q8(
                kc.q, kc.s, vc.q, vc.s, kn, vn, pb, off)
            return QuantKV(kq, ks), QuantKV(vq, vs)
        return ragged_scatter_xla(kc, vc, kn, vn, pb, off)

    def attend(qf, kc, vc):
        sw = cfg.sliding_window
        if kvt is not None:
            # tiered reads ride the XLA twins (ring positions + retention
            # masking); the ragged kernel's table streaming has no ring
            # inverse yet. TODO(kvtier): _kv_map + _row_mask ring support.
            if kv_quant:
                return ragged_attention_xla_q8(
                    qf, kc.q, kc.s, vc.q, vc.s, block_seq, qstart, qlen,
                    kvlen, tables, sliding_window=sw, kvt=kvt)
            return ragged_attention_xla(qf, kc, vc, block_seq, qstart,
                                        qlen, kvlen, tables,
                                        sliding_window=sw, kvt=kvt)
        if use_kernel and kv_quant:
            if mesh is not None:
                return ragged_paged_attention_q8_sharded(
                    mesh, qf, kc.q, kc.s, vc.q, vc.s, block_seq, qstart,
                    qlen, kvlen, tables, sliding_window=sw)
            return ragged_paged_attention_q8(
                qf, kc.q, kc.s, vc.q, vc.s, block_seq, qstart, qlen, kvlen,
                tables, sliding_window=sw)
        if use_kernel:
            if mesh is not None:
                return ragged_paged_attention_sharded(
                    mesh, qf, kc, vc, block_seq, qstart, qlen, kvlen,
                    tables, sliding_window=sw)
            return ragged_paged_attention(qf, kc, vc, block_seq, qstart,
                                          qlen, kvlen, tables,
                                          sliding_window=sw)
        if kv_quant:
            return ragged_attention_xla_q8(
                qf, kc.q, kc.s, vc.q, vc.s, block_seq, qstart, qlen, kvlen,
                tables, sliding_window=sw)
        return ragged_attention_xla(qf, kc, vc, block_seq, qstart, qlen,
                                    kvlen, tables, sliding_window=sw)

    emb = params["embed"].astype(cfg.jdtype)[tokens]           # [T, H]
    if inject is not None:
        extra, is_embed = inject
        emb = jnp.where(is_embed[:, None], extra.astype(cfg.jdtype), emb)
    x = emb[None]                                              # [1, T, H]

    def layer(x, xs):
        lp, kc, vc = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(h, lp, cfg, spec=P(None, None, "model"))
        q = apply_rope(q, cos, sin, pos[None])
        k = apply_rope(k, cos, sin, pos[None])
        q = _shard_act(q, P(None, None, "model", None))
        # current chunk lands in the pool FIRST (decode_step convention:
        # attention then reads it back through the table — kvlen already
        # counts it), so prefill chunks attend to themselves paged
        kc, vc = write(kc, vc, k[0], v[0])
        attn = attend(q[0], kc, vc)
        x = x + qmatmul(attn.reshape(1, t, -1), lp["wo"],
                        spec=P(None, None, None))
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        x = x + _mlp(h, lp, cfg, spec_prefix=(None, None))
        return x, (kc, vc)

    x, (k_cache, v_cache) = jax.lax.scan(
        layer, x, (params["layers"], k_cache, v_cache)
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    # [NSEQ, H] for 1-D logit_rows, [NSEQ, R, H] for the 2-D spec windows
    last = x[0][logit_rows.astype(jnp.int32)]
    logits = _lm_head(last.astype(jnp.float32), params)
    return logits, k_cache, v_cache


def build_decode_loop(step_fn, *, max_steps: int, limit: int):
    """While-loop variant of the fused decode block (Kernel Looping,
    arXiv:2410.23668): up to `max_steps` sample→decode iterations run as ONE
    on-device `lax.while_loop` dispatch, with per-slot stop conditions
    evaluated from device-resident state — no host round trip per block, no
    host-side power-of-two step ladder.

    `step_fn` is the engine's fused sample→decode body
    (params, cos, sin, kc, vc, sampler, last_logits, lengths, active,
    mask_bits, fast_width, table) → (tokens, logprobs, kc, vc, sampler,
    logits, lengths) — the SAME body the scan block and the single-step
    dispatch run, so per-slot RNG streams are identical across paths.

    Per-iteration stop conditions (computed on device, per slot):
    - EOS-set membership: sampled token ∈ `eos_ids` for slots with
      `check_eos` (host clears it for ignore_eos requests);
    - token budget: the slot produced `remaining` tokens this dispatch
      (max_tokens net of in-flight reservations, shipped per dispatch);
    - context margin: the slot's cache length reached `limit` (static,
      max_context minus the decode margin) — the host then finishes the
      request or context-shifts it and the loop resumes next dispatch.

    A finished slot is frozen: its sampler key and last_logits stop
    advancing (so a context-shifted slot resumes the exact RNG stream the
    single-step path would have used), its length stops, and its cache
    writes redirect to the trash row/block via `step_fn`'s active mask.
    The loop EARLY-EXITS once every live slot froze — a dispatch costs only
    the steps it actually ran (`steps_run` proves it).

    Grammar-constrained slots ride the same loop via the optional device
    automaton tables (gstate [B] i32 per-slot state, gmasks [S, ceil(V/32)]
    u32 packed allowed-token rows, gtrans [S, V] i32): each iteration
    gathers the slot's mask row, hard-masks sampling with it (the fused
    sample body's grammar path), and advances the state through gtrans on
    the emitted token — no host resync inside the loop. State row 0 is the
    all-ones/self-loop identity, so unconstrained slots stay bit-identical
    to the maskless variant (an all-true jnp.where is the logits exactly,
    and _draw is width-independent).

    Tokens land in an on-device ring buffer [max_steps, B]; the engine
    streams them out via async device→host copies (engine._AsyncFetch).
    Returns (tokens [max_steps, B], logprobs [max_steps, B], n_out [B],
    steps_run, kc, vc, sampler, last_logits, lengths) — slot b's valid
    tokens are rows 0..n_out[b)-1.
    """

    def decode_loop(params, cos, sin, kc, vc, sampler, last_logits, lengths,
                    active, remaining, check_eos, eos_ids, table=None,
                    fast_width=None, kvt=None, gstate=None, gmasks=None,
                    gtrans=None):
        B = lengths.shape[0]
        grammar = gmasks is not None
        if gstate is None:
            gstate = jnp.zeros((B,), jnp.int32)
        init = (
            jnp.int32(0),                            # steps run
            ~active,                                 # done (per slot)
            jnp.zeros((B,), jnp.int32),              # n_out
            jnp.zeros((max_steps, B), jnp.int32),    # token ring buffer
            jnp.zeros((max_steps, B), jnp.float32),  # logprob ring buffer
            gstate,                                  # grammar automaton state
            kc, vc, sampler, last_logits, lengths,
        )

        def cond(carry):
            i, done = carry[0], carry[1]
            return (i < max_steps) & jnp.any(~done)

        def body(carry):
            (i, done, n_out, toks, lps, gstate, kc, vc, sampler,
             last_logits, lengths) = carry
            live = ~done
            prev_key = sampler.key
            mask = gmasks[gstate] if grammar else None
            tokens, lp, kc, vc, sampler, logits, lengths = step_fn(
                params, cos, sin, kc, vc, sampler, last_logits, lengths,
                live, mask, fast_width, table, kvt)
            # freeze finished slots: their key stream and last_logits hold
            # at the finishing token (step_fn already gates lengths and
            # token_counts on the active mask)
            sampler = dataclasses.replace(
                sampler,
                key=jnp.where(live[:, None], sampler.key, prev_key))
            last_logits = jnp.where(live[:, None], logits, last_logits)
            toks = toks.at[i].set(tokens)
            lps = lps.at[i].set(lp)
            n_out = n_out + live.astype(jnp.int32)
            is_eos = check_eos & jnp.any(
                tokens[:, None] == eos_ids[None, :], axis=1)
            if grammar:
                # advance the automaton on the emitted token; only a live
                # slot's state moves. gtrans rows self-loop on EOS in
                # accepting states and send masked-off tokens to the
                # identity row 0 — neither is ever taken: sampling already
                # excluded them.
                gstate = jnp.where(live, gtrans[gstate, tokens], gstate)
            done = done | (live & (is_eos
                                   | (n_out >= remaining)
                                   | (lengths >= limit)))
            return (i + 1, done, n_out, toks, lps, gstate, kc, vc, sampler,
                    last_logits, lengths)

        (steps, _, n_out, toks, lps, _, kc, vc, sampler, last_logits,
         lengths) = jax.lax.while_loop(cond, body, init)
        return (toks, lps, n_out, steps, kc, vc, sampler, last_logits,
                lengths)

    return decode_loop


# fused ragged-loop exit codes (device → host; engine maps them onto the
# telemetry.sched pack reason codes at consume time)
RLOOP_EXIT_STEPS_CAP = 0   # ran the full max_steps budget
RLOOP_EXIT_FINISH = 1      # a decode slot finished (EOS/max_tokens/context)
RLOOP_EXIT_PREFILL = 2     # host-set prefill/admission-pending flag


def build_ragged_loop(ragged_step, decode_step, *, max_steps: int,
                      limit: int):
    """Fused multi-step ragged tick (Kernel Looping over the ragged pack):
    the mixed ragged dispatch plus up to `max_steps - 1` follow-on decode
    iterations run as ONE device program, so every live decode slot keeps
    advancing without a host round trip per token.

    The re-pack between iterations degenerates to pure data movement on
    device: iteration 0 runs `ragged_step` (the engine's single-step mixed
    body — sample, splice into the flat stream, one ragged_forward over
    decode rows + prefill chunks, set_len/logit_set commits), after which
    every datum the next decode step needs (lengths, last_logits, sampler
    state, block tables, grammar `gstate`) is already device-resident.
    Iterations >= 1 therefore run `decode_step` (the SAME fused
    sample→decode body the dense while loop uses) over the decode-live
    slots — a [B]-row step, not a re-run of the [T]-row ragged forward, so
    a multi-step dispatch costs ragged + (steps-1) x dense instead of
    steps x ragged. Slots mid-prefill (or whose final chunk just packed,
    sampler row pending host install) sit the continuation out frozen.

    With `has_pack=False` the ragged iteration is skipped entirely and the
    program is the pure-decode loop for ragged engines: `build_decode_loop`
    semantics plus the early-exit conditions below. Per-slot RNG streams are
    bit-identical to the single-step paths either way (`_draw` is width-
    independent and finished slots freeze key/last_logits exactly as the
    dense loop does).

    The loop EARLY-EXITS (cond, evaluated per iteration) when:
    - any decode slot finishes (EOS set / `remaining` budget / `limit`
      context margin — the PR 6 stop conditions): the host can admit into
      the freed slot immediately instead of waiting out the step cap;
    - `prefill_pending` (a traced bool shipped per dispatch) says the host
      has prefill chunks or admissible queue work: the dispatch collapses
      to a single iteration so TTFT stays at ragged levels;
    - the `max_steps` budget is spent.
    Host-arbitration cases (host-only grammar masks, stop strings) never
    reach this program — the engine falls back to the single-step ragged
    dispatch and records `loop_early_exit_host_arbitration`.

    Returns (toks [max_steps, B], lps [max_steps, B], n_out [B], steps_run,
    exit_code, kc, vc, sampler, last_logits, lengths); slot b's valid
    tokens are ring rows 0..n_out[b)-1 and exit_code is one of the
    RLOOP_EXIT_* constants (finish wins over prefill wins over steps_cap).
    """

    def ragged_loop(params, cos, sin, kc, vc, sampler, last_logits, lengths,
                    is_decode, remaining, check_eos, eos_ids,
                    prefill_pending, pack=None, table=None, kvt=None,
                    fast_width=None, gstate=None, gmasks=None, gtrans=None,
                    *, has_pack: bool):
        B = lengths.shape[0]
        grammar = gmasks is not None
        if gstate is None:
            gstate = jnp.zeros((B,), jnp.int32)
        done = ~is_decode
        n_out = jnp.zeros((B,), jnp.int32)
        toks = jnp.zeros((max_steps, B), jnp.int32)
        lps = jnp.zeros((max_steps, B), jnp.float32)

        def stops(tokens, n_out, lengths, live):
            is_eos = check_eos & jnp.any(
                tokens[:, None] == eos_ids[None, :], axis=1)
            return live & (is_eos | (n_out >= remaining)
                           | (lengths >= limit))

        i0 = jnp.int32(0)
        if has_pack:
            # iteration 0, unrolled: the exact single-step mixed ragged
            # body. Every packed decode row samples and advances (the
            # device cannot unpack a row), so the host only routes packs
            # here when each decode entry has remaining budget >= 1.
            mask0 = gmasks[gstate] if grammar else None
            (tokens, lp, kc, vc, sampler, last_logits, lengths) = \
                ragged_step(params, cos, sin, kc, vc, sampler, last_logits,
                            lengths, pack["tokens"], pack["decode_slot"],
                            is_decode, pack["set_len"], pack["logit_set"],
                            pack["logit_rows"], pack["block_seq"],
                            pack["qstart"], pack["qlen"], pack["kvlen"],
                            table, kvt, mask0, pack.get("inject"))
            toks = toks.at[0].set(tokens)
            lps = lps.at[0].set(lp)
            n_out = n_out + is_decode.astype(jnp.int32)
            if grammar:
                gstate = jnp.where(is_decode, gtrans[gstate, tokens], gstate)
            done = done | stops(tokens, n_out, lengths, is_decode)
            i0 = jnp.int32(1)

        init = (i0, done, n_out, toks, lps, gstate, kc, vc, sampler,
                last_logits, lengths)

        def cond(carry):
            i, done = carry[0], carry[1]
            # first-finish exit: unlike build_decode_loop (which keeps
            # looping until EVERY slot froze), one finished decode slot
            # ends the dispatch — early-exit admission
            return ((i < max_steps) & jnp.any(~done)
                    & ~jnp.any(is_decode & done) & ~prefill_pending)

        def body(carry):
            (i, done, n_out, toks, lps, gstate, kc, vc, sampler,
             last_logits, lengths) = carry
            live = ~done
            prev_key = sampler.key
            mask = gmasks[gstate] if grammar else None
            tokens, lp, kc, vc, sampler, logits, lengths = decode_step(
                params, cos, sin, kc, vc, sampler, last_logits, lengths,
                live, mask, fast_width, table, kvt)
            sampler = dataclasses.replace(
                sampler,
                key=jnp.where(live[:, None], sampler.key, prev_key))
            last_logits = jnp.where(live[:, None], logits, last_logits)
            toks = toks.at[i].set(tokens)
            lps = lps.at[i].set(lp)
            n_out = n_out + live.astype(jnp.int32)
            if grammar:
                gstate = jnp.where(live, gtrans[gstate, tokens], gstate)
            done = done | stops(tokens, n_out, lengths, live)
            return (i + 1, done, n_out, toks, lps, gstate, kc, vc, sampler,
                    last_logits, lengths)

        (steps, done, n_out, toks, lps, _, kc, vc, sampler, last_logits,
         lengths) = jax.lax.while_loop(cond, body, init)
        exit_code = jnp.where(
            jnp.any(is_decode & done), jnp.int32(RLOOP_EXIT_FINISH),
            jnp.where(prefill_pending & jnp.any(~done),
                      jnp.int32(RLOOP_EXIT_PREFILL),
                      jnp.int32(RLOOP_EXIT_STEPS_CAP)))
        return (toks, lps, n_out, steps, exit_code, kc, vc, sampler,
                last_logits, lengths)

    return ragged_loop


def hidden_states(params, cfg: LlamaConfig, tokens, lengths=None):
    """Full-sequence causal forward → final-norm hidden states [B, S, H].
    `lengths` masks padded positions out of attention (defaults to full)."""
    b, s = tokens.shape
    cos, sin = rope_table(cfg.rope, s)
    positions = jnp.arange(s)[None, :].repeat(b, 0)
    if lengths is None:
        lengths = jnp.full((b,), s, jnp.int32)
    attn_prefill, _ = _attn_impls(cfg)
    sax = _seq_ax()
    x = params["embed"].astype(cfg.jdtype)[tokens]
    x = _shard_act(x, P("data", sax, None))

    def layer(x, lp):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(h, lp, cfg, spec=P("data", sax, "model"))
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        q = _shard_act(q, P("data", sax, "model", None))
        attn = attn_prefill(q, k, v, lengths, sliding_window=cfg.sliding_window)
        x = x + qmatmul(attn.reshape(b, s, -1), lp["wo"],
                        spec=P("data", sax, None))
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        x = x + _mlp(h, lp, cfg, spec_prefix=("data", sax))
        x = _shard_act(x, P("data", sax, None))
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    return rms_norm(x, params["final_norm"], cfg.rms_eps)


def extend(params, cfg: LlamaConfig, tokens, start, cos, sin,
           k_cache, v_cache, slot_map=None, with_logits=True, last_pos=None,
           table=None, inject=None, full_window=False, redirect=None,
           kvt=None):
    """Forward a window of S tokens per slot starting at cache offset
    `start` [B] — the speculative-decoding verification pass (reference knob:
    DraftModel/NDraft, /root/reference/backend/backend.proto:218,150) and the
    chunked-prefill workhorse. Writes window K/V into the cache and returns
    logits for EVERY window position [B, S, V] plus the updated caches.

    slot_map [B] (optional): which cache slot each batch row reads/writes
    (defaults to row i ↔ slot i). with_logits=False skips the vocabulary
    projection (non-final prefill chunks need only the KV writes) and
    returns (None, k_cache, v_cache). last_pos [B] (optional): project only
    the hidden state at that window position → logits [B, V], avoiding the
    [B, S, V] buffer when a single row is wanted (final prefill chunk).
    """
    from localai_tpu.ops.attention import mha_extend, mha_extend_tiered

    b, s = tokens.shape
    rows = jnp.arange(b) if slot_map is None else slot_map
    positions = start[:, None] + jnp.arange(s)[None, :]
    x = params["embed"].astype(cfg.jdtype)[tokens]
    if inject is not None:
        # multimodal chunk: image-feature rows replace token embeddings
        # (see prefill's inject)
        extra, is_embed = inject
        x = jnp.where(is_embed[..., None], extra.astype(x.dtype), x)
    # KV lifecycle tier (engine/kvtier.py): chunk windows write through the
    # ring map and attend against the resident view at true positions.
    # Padded final-chunk tails land in ring margin columns (never the live
    # window — kvtier.ring_blocks reserves a full prefill chunk of margin)
    # at positions > every real query, so the kv_pos <= q_pos mask hides
    # them until real tokens overwrite those rows.
    cold = kvt is not None and "cold_tab" in kvt

    def layer(x, xs):
        if cold:
            lp, kc, vc, ck, cv = xs
        else:
            (lp, kc, vc), ck, cv = xs, None, None
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(h, lp, cfg, spec=P("data", None, "model"))
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        # paged uniqueness: a window whose positions all sit inside the
        # slot's allocation (mid prefill chunks — callers pass
        # full_window=True) never collides; a FINAL chunk's padded tail
        # resolves to shared TRASH offsets with different values — a
        # genuine collision, so the assertion would be a lie there. A
        # redirect (paged spec verify: inactive rows' windows route to the
        # trash block) gets distinct per-(row, pos) offsets, so it stays
        # unique while B*S fits one block (beyond that the engine warns at
        # init — engine._build_jit).
        from localai_tpu.ops.paged import BLOCK as _PB

        red_ok = redirect is None or b * s <= _PB
        kc, vc = _cache_write(
            kc, vc, k, v, rows, positions, table,
            unique=(table is None or full_window or redirect is not None)
            and red_ok,
            redirect=redirect, kvt=kvt)
        if kvt is not None:
            kr, vr, kv_pos, kv_ok = _tiered_kv(
                kc, vc, table[rows], kvt["sb"][rows], kvt["rw"][rows],
                start + s,
                ctab=kvt["cold_tab"][rows] if cold else None, ck=ck, cv=cv)
            attn = mha_extend_tiered(
                q, kr, vr, positions, kv_pos, kv_ok,
                kvt["sinks"][rows], kvt["window"][rows],
                drop_window=not cold)
        else:
            if table is not None:
                from localai_tpu.ops.paged import paged_view

                kr = paged_view(kc, table[rows])
                vr = paged_view(vc, table[rows])
            else:
                kr = kc if slot_map is None else kc[rows]
                vr = vc if slot_map is None else vc[rows]
            attn = mha_extend(q, dequant(kr), dequant(vr), positions,
                              sliding_window=cfg.sliding_window)
        x = x + qmatmul(attn.reshape(b, s, -1), lp["wo"],
                        spec=P("data", None, None))
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        x = x + _mlp(h, lp, cfg, spec_prefix=("data", None))
        return x, (kc, vc)

    xs = (params["layers"], k_cache, v_cache)
    if cold:
        xs = xs + (kvt["cold_k"], kvt["cold_v"])
    x, (k_cache, v_cache) = jax.lax.scan(layer, x, xs)
    if not with_logits:
        return None, k_cache, v_cache
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    if last_pos is not None:
        x = jnp.take_along_axis(x, last_pos[:, None, None], axis=1)[:, 0]
        return _lm_head(x.astype(jnp.float32), params), k_cache, v_cache
    logits = _lm_head(x.astype(jnp.float32), params)
    return logits, k_cache, v_cache


def cache_shift(cfg: LlamaConfig, k_cache, v_cache, lengths, slot, *,
                keep: int, discard: int):
    """llama.cpp-style context shift for one slot (reference ctx_shift,
    /root/reference/backend/cpp/llama-cpp/grpc-server.cpp:311): keep the
    first `keep` sink tokens, evict the next `discard`, slide the rest left.

    Cached K is stored post-RoPE, so the moved entries are re-rotated by
    -discard positions (a pure rotation by angle -discard·inv_freq — the
    YaRN/llama3 attention mscale is a uniform factor and commutes with it).
    `keep`/`discard` are static → one compiled program per engine.
    Returns (k_cache, v_cache, lengths) with lengths[slot] -= discard.
    """
    from localai_tpu.ops.rope import rope_freqs

    inv_freq, _ = rope_freqs(cfg.rope)
    ang = discard * inv_freq                     # [D/2]
    c, s = jnp.cos(ang), jnp.sin(ang)

    T = k_cache.shape[3]
    quant = isinstance(k_cache, QuantKV)
    # quantized caches shift in f32 and requantize the slot (fresh scales);
    # only the shifted slot pays the dequant→requant round trip
    ks = dequant(k_cache[:, slot], jnp.float32) if quant else k_cache[:, slot]
    vs = dequant(v_cache[:, slot], jnp.float32) if quant else v_cache[:, slot]
    ks_m = jnp.roll(ks, -discard, axis=2)
    vs_m = jnp.roll(vs, -discard, axis=2)
    # R(-d): x1' = x1·cos + x2·sin ; x2' = x2·cos - x1·sin
    x1, x2 = jnp.split(ks_m.astype(jnp.float32), 2, axis=-1)
    ks_rot = jnp.concatenate([x1 * c + x2 * s, x2 * c - x1 * s],
                             axis=-1).astype(ks.dtype)
    idx = jnp.arange(T)[None, None, :, None]
    length = lengths[slot]
    move = (idx >= keep) & (idx < length - discard)
    k_new = jnp.where(move, ks_rot, ks)
    v_new = jnp.where(move, vs_m, vs)
    if quant:
        kq = requantize(k_cache[:, slot], k_new)
        vq = requantize(v_cache[:, slot], v_new)
        k_cache = QuantKV(k_cache.q.at[:, slot].set(kq.q),
                          k_cache.s.at[:, slot].set(kq.s))
        v_cache = QuantKV(v_cache.q.at[:, slot].set(vq.q),
                          v_cache.s.at[:, slot].set(vq.s))
    else:
        k_cache = k_cache.at[:, slot].set(k_new)
        v_cache = v_cache.at[:, slot].set(v_new)
    lengths = lengths.at[slot].add(-discard)
    return k_cache, v_cache, lengths


def cache_shift_paged(cfg: LlamaConfig, k_pool, row_table, *,
                      keep_blocks: int, discard_blocks: int):
    """Block-granular context shift for ONE paged slot (reference ctx_shift
    against a unified cache, grpc-server.cpp:311; dense analog: cache_shift).

    With paged storage the SLIDE is free — the host permutes the slot's
    table row (keep the first `keep_blocks` sink blocks, drop the next
    `discard_blocks`, tail moves left; freed blocks re-append as fresh tail
    capacity). The only physical work is K's RoPE correction: every kept
    tail block re-rotates by -discard_blocks*BLOCK positions, IN PLACE in
    the pool. V blocks never move or change.

    row_table [MAXB] i32 is the PRE-permutation map; tail blocks (virtual
    index >= keep_blocks+discard_blocks, physical != 0) are rotated;
    everything else scatters to the trash block (unique=False — those rows
    collide there by design). Returns the updated k_pool."""
    from localai_tpu.ops.paged import BLOCK
    from localai_tpu.ops.rope import rope_freqs

    inv_freq, _ = rope_freqs(cfg.rope)
    ang = (discard_blocks * BLOCK) * inv_freq
    c, s = jnp.cos(ang), jnp.sin(ang)

    # only the tail blocks move — gather/rotate/scatter just those
    # (keep_blocks + discard_blocks is static under jit, so this is a
    # plain slice, not a dynamic gather)
    tail = row_table[keep_blocks + discard_blocks:]
    quant = isinstance(k_pool, QuantKV)
    kb = k_pool[:, tail]                         # [L, TAIL, KVH, BS, D]
    kf = dequant(kb, jnp.float32) if quant else kb.astype(jnp.float32)
    x1, x2 = jnp.split(kf, 2, axis=-1)
    rot = jnp.concatenate([x1 * c + x2 * s, x2 * c - x1 * s], axis=-1)

    target = jnp.where(tail != 0, tail, 0)       # unallocated entries → trash
    if quant:
        rq = requantize(kb, rot)
        k_pool = QuantKV(
            k_pool.q.at[:, target].set(rq.q, unique_indices=False),
            k_pool.s.at[:, target].set(rq.s, unique_indices=False))
        return k_pool
    return k_pool.at[:, target].set(rot.astype(k_pool.dtype),
                                    unique_indices=False)


def forward_train(params, cfg: LlamaConfig, tokens):
    """Full-sequence causal forward → logits [B, S, V] (training / eval path)."""
    x = hidden_states(params, cfg, tokens)
    return _lm_head(x.astype(jnp.float32), params)


def encode_pooled(params, cfg: LlamaConfig, tokens, lengths, normalize=True):
    """Masked-mean-pooled embeddings [B, H] f32 — the embeddings path
    (reference: mean_pooling + Embedding RPC,
    /root/reference/backend/python/transformers/backend.py:37,323)."""
    b, s = tokens.shape
    x = hidden_states(params, cfg, tokens, lengths).astype(jnp.float32)
    mask = (jnp.arange(s)[None, :] < lengths[:, None]).astype(jnp.float32)
    pooled = (x * mask[..., None]).sum(1) / jnp.maximum(
        mask.sum(1)[:, None], 1.0
    )
    if normalize:
        pooled = pooled / jnp.maximum(
            jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9
        )
    return pooled
