"""Text-conditioned diffusion image generator in JAX — the image-gen engine.

Reference role: stablediffusion-ggml backend (/root/reference/backend/go/
stablediffusion-ggml/gosd.cpp — txt2img with scheduler/sampler options) and
the diffusers Python backend (GenerateImage/GenerateVideo,
/root/reference/backend/python/diffusers/backend.py). TPU-first rebuild: a
pixel-space UNet (resblocks + self/cross-attention) with a DDIM sampler, all
jitted — the denoise loop is a lax.scan so the whole sampling trajectory is
one XLA program on the MXU. Text conditioning comes from the model's own
token-embedding transformer encoder.

The architecture is checkpoint-loadable (its own safetensors format via
orbax/np); without trained weights it runs end-to-end producing
deterministic-noise images, which keeps the full contract (RPC → PNG/GIF)
testable and lets trained weights drop in.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DiffusionConfig:
    channels: int = 64            # base UNet width
    channel_mults: tuple = (1, 2, 4)
    image_size: int = 64          # native resolution (resized on output)
    text_dim: int = 128
    text_layers: int = 2
    text_heads: int = 4
    vocab_size: int = 1024
    max_text_len: int = 64
    steps_train: int = 1000
    dtype: str = "float32"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


# ----------------------------------------------------------------- params

def _dense(key, din, dout, dtype):
    w = jax.random.normal(key, (din, dout), jnp.float32) * (din ** -0.5)
    return {"w": w.astype(dtype), "b": jnp.zeros((dout,), dtype)}


def _conv(key, cin, cout, k, dtype):
    w = jax.random.normal(key, (k, k, cin, cout), jnp.float32) * ((k * k * cin) ** -0.5)
    return {"w": w.astype(dtype), "b": jnp.zeros((cout,), dtype)}


def init_params(cfg: DiffusionConfig, key):
    dtype = cfg.jdtype
    ks = iter(jax.random.split(key, 200))
    C = cfg.channels

    def resblock(cin, cout):
        return {
            "conv1": _conv(next(ks), cin, cout, 3, dtype),
            "conv2": _conv(next(ks), cout, cout, 3, dtype),
            "temb": _dense(next(ks), C * 4, cout, dtype),
            "skip": _conv(next(ks), cin, cout, 1, dtype) if cin != cout else None,
        }

    def attnblock(c):
        return {
            "qkv": _dense(next(ks), c, 3 * c, dtype),
            "out": _dense(next(ks), c, c, dtype),
            "cross_q": _dense(next(ks), c, c, dtype),
            "cross_kv": _dense(next(ks), cfg.text_dim, 2 * c, dtype),
            "cross_out": _dense(next(ks), c, c, dtype),
        }

    chans = [C * m for m in cfg.channel_mults]
    down, up = [], []
    cin = C
    for c in chans:
        down.append({"res": resblock(cin, c), "attn": attnblock(c)})
        cin = c
    mid = {"res1": resblock(cin, cin), "attn": attnblock(cin),
           "res2": resblock(cin, cin)}
    for c in reversed(chans):
        up.append({"res": resblock(cin + c, c), "attn": attnblock(c)})
        cin = c

    text_layers = []
    for _ in range(cfg.text_layers):
        text_layers.append({
            "qkv": _dense(next(ks), cfg.text_dim, 3 * cfg.text_dim, dtype),
            "out": _dense(next(ks), cfg.text_dim, cfg.text_dim, dtype),
            "fc1": _dense(next(ks), cfg.text_dim, 4 * cfg.text_dim, dtype),
            "fc2": _dense(next(ks), 4 * cfg.text_dim, cfg.text_dim, dtype),
        })
    return {
        "conv_in": _conv(next(ks), 3, C, 3, dtype),
        "temb1": _dense(next(ks), C, C * 4, dtype),
        "temb2": _dense(next(ks), C * 4, C * 4, dtype),
        "down": down,
        "mid": mid,
        "up": up,
        "conv_out": _conv(next(ks), C, 3, 3, dtype),
        "text_embed": (jax.random.normal(next(ks), (cfg.vocab_size, cfg.text_dim),
                                         jnp.float32) * 0.02).astype(dtype),
        "text_pos": jnp.zeros((cfg.max_text_len, cfg.text_dim), dtype),
        "text_layers": text_layers,
    }


# ----------------------------------------------------------------- forward

def _apply_dense(p, x):
    return x @ p["w"] + p["b"]


def _apply_conv(p, x, stride=1):
    return jax.lax.conv_general_dilated(
        x, p["w"], (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + p["b"]


def _groupnorm(x, groups=8):
    b, h, w, c = x.shape
    g = min(groups, c)
    x32 = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
    mu = x32.mean((1, 2, 4), keepdims=True)
    var = x32.var((1, 2, 4), keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-5)).reshape(b, h, w, c).astype(x.dtype)


def _resblock(p, x, temb):
    h = _apply_conv(p["conv1"], jax.nn.silu(_groupnorm(x)))
    h = h + _apply_dense(p["temb"], jax.nn.silu(temb))[:, None, None, :]
    h = _apply_conv(p["conv2"], jax.nn.silu(_groupnorm(h)))
    skip = x if p["skip"] is None else _apply_conv(p["skip"], x)
    return skip + h


def _attnblock(p, x, text):
    b, hh, ww, c = x.shape
    flat = _groupnorm(x).reshape(b, hh * ww, c)
    qkv = _apply_dense(p["qkv"], flat)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    att = jax.nn.softmax(
        (q @ k.transpose(0, 2, 1)).astype(jnp.float32) * (c ** -0.5), -1
    ).astype(x.dtype)
    flat = flat + _apply_dense(p["out"], att @ v)
    # cross-attention on text states
    qc = _apply_dense(p["cross_q"], flat)
    kv = _apply_dense(p["cross_kv"], text)
    kc, vc = jnp.split(kv, 2, axis=-1)
    att = jax.nn.softmax(
        (qc @ kc.transpose(0, 2, 1)).astype(jnp.float32) * (c ** -0.5), -1
    ).astype(x.dtype)
    flat = flat + _apply_dense(p["cross_out"], att @ vc)
    return flat.reshape(b, hh, ww, c)


def _timestep_embedding(t, dim):
    half = dim // 2
    freqs = jnp.exp(-jnp.log(10000.0) * jnp.arange(half) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], -1)


def encode_text(params, cfg: DiffusionConfig, tokens):
    """[B, Lt] ids → [B, Lt, text_dim] transformer states."""
    x = params["text_embed"][tokens] + params["text_pos"][: tokens.shape[1]]
    d = cfg.text_dim
    for lp in params["text_layers"]:
        qkv = _apply_dense(lp["qkv"], x)
        q, k, v = jnp.split(qkv, 3, -1)
        att = jax.nn.softmax(
            (q @ k.transpose(0, 2, 1)).astype(jnp.float32) * (d ** -0.5), -1
        ).astype(x.dtype)
        x = x + _apply_dense(lp["out"], att @ v)
        x = x + _apply_dense(lp["fc2"], jax.nn.gelu(_apply_dense(lp["fc1"], x)))
    return x


def unet(params, cfg: DiffusionConfig, x, t, text):
    """Predict noise eps for x_t. x: [B, H, W, 3]; t: [B]; text states."""
    temb = _apply_dense(params["temb1"], _timestep_embedding(t, cfg.channels)
                        .astype(cfg.jdtype))
    temb = _apply_dense(params["temb2"], jax.nn.silu(temb))
    h = _apply_conv(params["conv_in"], x)
    skips = []
    for blk in params["down"]:
        h = _resblock(blk["res"], h, temb)
        h = _attnblock(blk["attn"], h, text)
        skips.append(h)
        h = jax.lax.reduce_window(h, -jnp.inf, jax.lax.max,
                                  (1, 2, 2, 1), (1, 2, 2, 1), "SAME")
    h = _resblock(params["mid"]["res1"], h, temb)
    h = _attnblock(params["mid"]["attn"], h, text)
    h = _resblock(params["mid"]["res2"], h, temb)
    for blk, skip in zip(params["up"], reversed(skips)):
        b, hh, ww, c = skip.shape
        h = jax.image.resize(h, (b, hh, ww, h.shape[-1]), "nearest")
        h = jnp.concatenate([h, skip], -1)
        h = _resblock(blk["res"], h, temb)
        h = _attnblock(blk["attn"], h, text)
    return _apply_conv(params["conv_out"], jax.nn.silu(_groupnorm(h)))


# ----------------------------------------------------------------- sampling

def ddim_sample(params, cfg: DiffusionConfig, tokens, *, steps: int = 20,
                seed: int = 0, guidance: float = 3.0):
    """DDIM sampler, full trajectory as one lax.scan → [B, H, W, 3] in [0,1].
    Classifier-free guidance runs cond/uncond batched together."""
    B = tokens.shape[0]
    size = cfg.image_size
    betas = jnp.linspace(1e-4, 0.02, cfg.steps_train)
    alphas = jnp.cumprod(1.0 - betas)
    ts = jnp.linspace(cfg.steps_train - 1, 0, steps).astype(jnp.int32)

    text = encode_text(params, cfg, tokens)
    text_uncond = encode_text(params, cfg, jnp.zeros_like(tokens))
    text_both = jnp.concatenate([text, text_uncond], 0)

    key = jax.random.PRNGKey(seed)
    x = jax.random.normal(key, (B, size, size, 3), cfg.jdtype)

    def step(x, i):
        t = ts[i]
        t_next = jnp.where(i + 1 < steps, ts[jnp.minimum(i + 1, steps - 1)], 0)
        a_t = alphas[t]
        a_next = jnp.where(i + 1 < steps, alphas[t_next], 1.0)
        eps_both = unet(params, cfg, jnp.concatenate([x, x], 0),
                        jnp.full((2 * B,), t), text_both)
        eps_c, eps_u = jnp.split(eps_both, 2, 0)
        eps = eps_u + guidance * (eps_c - eps_u)
        x0 = (x - jnp.sqrt(1 - a_t) * eps) / jnp.sqrt(a_t)
        x0 = jnp.clip(x0, -1.5, 1.5)
        x = jnp.sqrt(a_next) * x0 + jnp.sqrt(1 - a_next) * eps
        return x, None

    x, _ = jax.lax.scan(step, x, jnp.arange(steps))
    return jnp.clip((x + 1.0) / 2.0, 0.0, 1.0)


class DiffusionModel:
    """Engine wrapper: prompt → PNG/GIF bytes on disk."""

    def __init__(self, cfg: DiffusionConfig | None = None, params=None,
                 seed: int = 0):
        self.cfg = cfg or DiffusionConfig()
        self.params = params if params is not None else init_params(
            self.cfg, jax.random.PRNGKey(seed))
        self._sample = jax.jit(partial(ddim_sample, cfg=self.cfg),
                               static_argnames=("steps",))

    def _tokens(self, prompt: str) -> jnp.ndarray:
        ids = [1] + [2 + (b % (self.cfg.vocab_size - 2))
                     for b in prompt.encode()][: self.cfg.max_text_len - 1]
        ids += [0] * (self.cfg.max_text_len - len(ids))
        return jnp.asarray([ids], jnp.int32)

    def generate_image(self, prompt: str, dst: str, *,
                       negative_prompt: str = "", width: int = 256,
                       height: int = 256, steps: int = 12, seed: int = 0):
        from PIL import Image

        img = self._sample(self.params, tokens=self._tokens(prompt),
                           steps=steps, seed=seed)
        arr = jax.device_get(img[0] * 255.0).astype(np.uint8)
        Image.fromarray(arr).resize((width, height),
                                    Image.BILINEAR).save(dst)
        return dst

    def generate_video(self, prompt: str, dst: str, *, num_frames: int = 8,
                       fps: int = 4, width: int = 128, height: int = 128,
                       steps: int = 8, seed: int = 0):
        """Frame sequence (per-frame seeds) → animated GIF (no ffmpeg in
        image; reference shells out to ffmpeg, pkg/utils/ffmpeg.go)."""
        from PIL import Image

        frames = []
        for f in range(num_frames):
            img = self._sample(self.params, tokens=self._tokens(prompt),
                               steps=steps, seed=seed + f)
            arr = jax.device_get(img[0] * 255.0).astype(np.uint8)
            frames.append(Image.fromarray(arr).resize((width, height),
                                                      Image.BILINEAR))
        frames[0].save(dst, save_all=True, append_images=frames[1:],
                       duration=int(1000 / fps), loop=0)
        return dst
