"""Flux-geometry rectified-flow transformer (MMDiT) in JAX — txt2img from
REAL checkpoints in the diffusers FluxPipeline directory layout.

Reference role: the diffusers backend serves Flux
(/root/reference/backend/python/diffusers/backend.py, FluxPipeline branch)
and so does stablediffusion-ggml (/root/reference/backend/go/
stablediffusion-ggml/gosd.cpp). TPU-first rebuild: CLIP (pooled vector) +
T5 (sequence conditioning) encoders, the double-stream/single-stream MMDiT
with 3-axis rotary position embeddings and adaLN modulation, and a
flow-matching Euler sampler as a lax.scan — one jitted XLA program per
trajectory, all matmuls MXU-shaped.

Layout: model_index.json (_class_name FluxPipeline) + transformer/ +
text_encoder/ (CLIP) + text_encoder_2/ (T5) + vae/ (16-channel latents,
decoded by latent_diffusion.vae_decode, which is config-driven).
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.models.latent_diffusion import (
    _component_config, _component_weights, clip_encode, layer_norm, linear,
    timestep_embedding, vae_decode,
)


def is_flux_checkpoint(model_dir: str) -> bool:
    p = os.path.join(model_dir, "model_index.json")
    if not os.path.exists(p):
        return False
    try:
        with open(p) as f:
            return "Flux" in json.load(f).get("_class_name", "")
    except Exception:
        return False


# ------------------------------------------------------------ T5 encoder

def _rms(x, w, eps=1e-6):
    xf = x.astype(jnp.float32)
    xf = xf * jax.lax.rsqrt((xf * xf).mean(-1, keepdims=True) + eps)
    return (xf * w).astype(x.dtype)


def _t5_rel_bucket(rel, num_buckets=32, max_distance=128):
    """T5 bidirectional relative-position bucket (HF t5 implementation)."""
    n = num_buckets // 2
    out = jnp.where(rel > 0, n, 0)
    rel = jnp.abs(rel)
    max_exact = n // 2
    large = max_exact + (
        jnp.log(rel.astype(jnp.float32) / max_exact + 1e-6)
        / math.log(max_distance / max_exact) * (n - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, n - 1)
    return out + jnp.where(rel < max_exact, rel, large)


def t5_encode(w: dict, cfg: dict, tokens):
    """T5 encoder (v1.1 gated-gelu) → last hidden state [B, S, D]."""
    d_model = cfg["d_model"]
    heads = cfg["num_heads"]
    kv = cfg.get("d_kv", d_model // heads)
    s = tokens.shape[1]
    x = w["shared.weight"][tokens]

    pos = jnp.arange(s)
    rel = pos[None, :] - pos[:, None]                  # memory - query
    bucket = _t5_rel_bucket(rel, cfg.get("relative_attention_num_buckets", 32),
                            cfg.get("relative_attention_max_distance", 128))
    bias = w["encoder.block.0.layer.0.SelfAttention."
             "relative_attention_bias.weight"][bucket]  # [S, S, H]
    bias = bias.transpose(2, 0, 1)[None]               # [1, H, S, S]

    for i in range(cfg["num_layers"]):
        p = f"encoder.block.{i}.layer."
        h = _rms(x, w[p + "0.layer_norm.weight"])
        q = linear(h, w[p + "0.SelfAttention.q.weight"])
        k = linear(h, w[p + "0.SelfAttention.k.weight"])
        v = linear(h, w[p + "0.SelfAttention.v.weight"])
        b = x.shape[0]
        qh = q.reshape(b, s, heads, kv).transpose(0, 2, 1, 3)
        kh = k.reshape(b, s, heads, kv).transpose(0, 2, 1, 3)
        vh = v.reshape(b, s, heads, kv).transpose(0, 2, 1, 3)
        # T5 attention is unscaled; the bias carries relative positions
        sc = jnp.einsum("bhqd,bhkd->bhqk", qh, kh).astype(jnp.float32) + bias
        pr = jax.nn.softmax(sc, axis=-1).astype(vh.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", pr, vh)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, heads * kv)
        x = x + linear(o, w[p + "0.SelfAttention.o.weight"])
        h = _rms(x, w[p + "1.layer_norm.weight"])
        g = jax.nn.gelu(linear(h, w[p + "1.DenseReluDense.wi_0.weight"]),
                        approximate=True)
        u = linear(h, w[p + "1.DenseReluDense.wi_1.weight"])
        x = x + linear(g * u, w[p + "1.DenseReluDense.wo.weight"])
    return _rms(x, w["encoder.final_layer_norm.weight"])


# ------------------------------------------------------------ MMDiT core

def _rope_3axis(ids, axes_dims, theta=10000.0):
    """Flux rotary embedding: per-axis rotary tables concatenated over the
    head dim. ids [N, 3] → (cos, sin) [N, sum(axes_dims)//2]."""
    cos_parts, sin_parts = [], []
    for a, dim in enumerate(axes_dims):
        freqs = 1.0 / (theta ** (jnp.arange(0, dim, 2,
                                            dtype=jnp.float32) / dim))
        ang = ids[:, a].astype(jnp.float32)[:, None] * freqs[None]
        cos_parts.append(jnp.cos(ang))
        sin_parts.append(jnp.sin(ang))
    return (jnp.concatenate(cos_parts, -1), jnp.concatenate(sin_parts, -1))


def _apply_rope(x, cos, sin):
    """x [B, H, N, D] with interleaved pairs; cos/sin [N, D/2]."""
    x1, x2 = x[..., 0::2], x[..., 1::2]
    c, s = cos[None, None], sin[None, None]
    o1 = x1 * c - x2 * s
    o2 = x1 * s + x2 * c
    return jnp.stack([o1, o2], axis=-1).reshape(x.shape)


def _to_heads(x, heads):
    b, n, c = x.shape
    return x.reshape(b, n, heads, c // heads).transpose(0, 2, 1, 3)


def _attn_heads(qh, kh, vh, cos, sin):
    """Rotary attention over already-headed (and QK-normed) streams."""
    b, heads, n, d = qh.shape
    qh = _apply_rope(qh, cos, sin)
    kh = _apply_rope(kh, cos, sin)
    sc = jnp.einsum("bhqd,bhkd->bhqk", qh, kh).astype(jnp.float32)
    pr = jax.nn.softmax(sc * (d ** -0.5), axis=-1).astype(vh.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", pr, vh)
    return o.transpose(0, 2, 1, 3).reshape(b, n, heads * d)


def _mod(vec, w, pfx, n_chunks):
    m = linear(jax.nn.silu(vec), w[pfx + ".weight"], w[pfx + ".bias"])
    return jnp.split(m[:, None, :], n_chunks, axis=-1)


def flux_apply(w: dict, cfg: dict, img, txt, vec, t, guidance=None,
               grid_hw=None):
    """Flux transformer forward.

    img [B, Nimg, 64] packed 2x2 latent patches, txt [B, Ntxt, joint_dim]
    T5 states, vec [B, pooled_dim] CLIP pooled, t [B] in [0, 1] flow time,
    guidance [B] (dev-variant distilled guidance scale), grid_hw the packed
    latent grid (gh, gw) — defaults to square. → velocity [B, Nimg, 64]."""
    heads = cfg.get("num_attention_heads", 24)
    axes = cfg.get("axes_dims_rope", (16, 56, 56))
    ntxt = txt.shape[1]
    b, nimg, _ = img.shape
    gh, gw = grid_hw if grid_hw is not None else (
        int(math.isqrt(nimg)), int(math.isqrt(nimg)))
    if gh * gw != nimg:
        raise ValueError(f"grid {gh}x{gw} != {nimg} image tokens")

    x = linear(img, w["x_embedder.weight"], w["x_embedder.bias"])
    c = linear(txt, w["context_embedder.weight"], w["context_embedder.bias"])

    temb = timestep_embedding(t * 1000.0, 256)
    e = linear(temb, w["time_text_embed.timestep_embedder.linear_1.weight"],
               w["time_text_embed.timestep_embedder.linear_1.bias"])
    e = linear(jax.nn.silu(e),
               w["time_text_embed.timestep_embedder.linear_2.weight"],
               w["time_text_embed.timestep_embedder.linear_2.bias"])
    if cfg.get("guidance_embeds") and guidance is not None:
        g = timestep_embedding(guidance * 1000.0, 256)
        g = linear(g, w["time_text_embed.guidance_embedder.linear_1.weight"],
                   w["time_text_embed.guidance_embedder.linear_1.bias"])
        g = linear(jax.nn.silu(g),
                   w["time_text_embed.guidance_embedder.linear_2.weight"],
                   w["time_text_embed.guidance_embedder.linear_2.bias"])
        e = e + g
    p = linear(vec, w["time_text_embed.text_embedder.linear_1.weight"],
               w["time_text_embed.text_embedder.linear_1.bias"])
    p = linear(jax.nn.silu(p),
               w["time_text_embed.text_embedder.linear_2.weight"],
               w["time_text_embed.text_embedder.linear_2.bias"])
    vec_e = e + p

    # rotary ids: text tokens at the origin, image tokens on the (y, x) grid
    txt_ids = jnp.zeros((ntxt, 3), jnp.int32)
    ys, xs = jnp.meshgrid(jnp.arange(gh), jnp.arange(gw), indexing="ij")
    img_ids = jnp.stack(
        [jnp.zeros_like(ys), ys, xs], axis=-1).reshape(-1, 3)
    cos, sin = _rope_3axis(jnp.concatenate([txt_ids, img_ids], 0), axes)

    for i in range(cfg.get("num_layers", 19)):
        pfx = f"transformer_blocks.{i}."
        sh_m, sc_m, g_m, sh_f, sc_f, g_f = _mod(
            vec_e, w, pfx + "norm1.linear", 6)
        csh_m, csc_m, cg_m, csh_f, csc_f, cg_f = _mod(
            vec_e, w, pfx + "norm1_context.linear", 6)
        xn = _ln_mod(x, sc_m, sh_m)
        cn = _ln_mod(c, csc_m, csh_m)
        # per-stream projections + per-stream QK RMS norms (norm_added_*
        # for the context stream), then joint attention over [txt; img]
        qx = _rms(_to_heads(linear(xn, w[pfx + "attn.to_q.weight"],
                                   w[pfx + "attn.to_q.bias"]), heads),
                  w[pfx + "attn.norm_q.weight"])
        kx = _rms(_to_heads(linear(xn, w[pfx + "attn.to_k.weight"],
                                   w[pfx + "attn.to_k.bias"]), heads),
                  w[pfx + "attn.norm_k.weight"])
        vx = _to_heads(linear(xn, w[pfx + "attn.to_v.weight"],
                              w[pfx + "attn.to_v.bias"]), heads)
        qc = _rms(_to_heads(linear(cn, w[pfx + "attn.add_q_proj.weight"],
                                   w[pfx + "attn.add_q_proj.bias"]), heads),
                  w[pfx + "attn.norm_added_q.weight"])
        kc = _rms(_to_heads(linear(cn, w[pfx + "attn.add_k_proj.weight"],
                                   w[pfx + "attn.add_k_proj.bias"]), heads),
                  w[pfx + "attn.norm_added_k.weight"])
        vc = _to_heads(linear(cn, w[pfx + "attn.add_v_proj.weight"],
                              w[pfx + "attn.add_v_proj.bias"]), heads)
        o = _attn_heads(jnp.concatenate([qc, qx], axis=2),
                        jnp.concatenate([kc, kx], axis=2),
                        jnp.concatenate([vc, vx], axis=2), cos, sin)
        oc, ox = o[:, :ntxt], o[:, ntxt:]
        x = x + g_m * linear(ox, w[pfx + "attn.to_out.0.weight"],
                             w[pfx + "attn.to_out.0.bias"])
        c = c + cg_m * linear(oc, w[pfx + "attn.to_add_out.weight"],
                              w[pfx + "attn.to_add_out.bias"])
        xn = _ln_mod(x, sc_f, sh_f)
        h = linear(xn, w[pfx + "ff.net.0.proj.weight"],
                   w[pfx + "ff.net.0.proj.bias"])
        x = x + g_f * linear(jax.nn.gelu(h, approximate=True),
                             w[pfx + "ff.net.2.weight"],
                             w[pfx + "ff.net.2.bias"])
        cn = _ln_mod(c, csc_f, csh_f)
        h = linear(cn, w[pfx + "ff_context.net.0.proj.weight"],
                   w[pfx + "ff_context.net.0.proj.bias"])
        c = c + cg_f * linear(jax.nn.gelu(h, approximate=True),
                              w[pfx + "ff_context.net.2.weight"],
                              w[pfx + "ff_context.net.2.bias"])

    z = jnp.concatenate([c, x], axis=1)
    for i in range(cfg.get("num_single_layers", 38)):
        pfx = f"single_transformer_blocks.{i}."
        sh, sc, gate = _mod(vec_e, w, pfx + "norm.linear", 3)
        zn = _ln_mod(z, sc, sh)
        q = _rms(_to_heads(linear(zn, w[pfx + "attn.to_q.weight"],
                                  w[pfx + "attn.to_q.bias"]), heads),
                 w[pfx + "attn.norm_q.weight"])
        k = _rms(_to_heads(linear(zn, w[pfx + "attn.to_k.weight"],
                                  w[pfx + "attn.to_k.bias"]), heads),
                 w[pfx + "attn.norm_k.weight"])
        v = _to_heads(linear(zn, w[pfx + "attn.to_v.weight"],
                             w[pfx + "attn.to_v.bias"]), heads)
        o = _attn_heads(q, k, v, cos, sin)
        mlp = jax.nn.gelu(linear(zn, w[pfx + "proj_mlp.weight"],
                                 w[pfx + "proj_mlp.bias"]), approximate=True)
        z = z + gate * linear(jnp.concatenate([o, mlp], axis=-1),
                              w[pfx + "proj_out.weight"],
                              w[pfx + "proj_out.bias"])

    x = z[:, ntxt:]
    shift, scale = _mod(vec_e, w, "norm_out.linear", 2)
    x = _ln_mod(x, scale, shift)
    return linear(x, w["proj_out.weight"], w["proj_out.bias"])


def _ln_mod(x, scale, shift):
    """adaLN: parameter-free LN then learned scale/shift from the vec."""
    xf = x.astype(jnp.float32)
    xf = (xf - xf.mean(-1, keepdims=True)) * jax.lax.rsqrt(
        xf.var(-1, keepdims=True) + 1e-6)
    return (xf * (1 + scale) + shift).astype(x.dtype)


# ------------------------------------------------------------ pipeline

@dataclasses.dataclass
class FluxPipeline:
    """txt2img over a diffusers FluxPipeline checkpoint directory."""

    model_dir: str
    dtype: str = "float32"

    def __post_init__(self):
        dt = jnp.dtype(self.dtype)

        def to_jax(d):
            out = {}
            for k, v in d.items():
                if v.ndim == 4:
                    v = v.transpose(2, 3, 1, 0)
                a = jnp.asarray(v)
                out[k] = a.astype(dt) if a.dtype in (
                    jnp.float32, jnp.float16, jnp.bfloat16) else a
            return out

        self.tf_cfg = _component_config(self.model_dir, "transformer")
        self.vae_cfg = _component_config(self.model_dir, "vae")
        self.clip_cfg = _component_config(self.model_dir, "text_encoder")
        self.t5_cfg = _component_config(self.model_dir, "text_encoder_2")
        self.tf_w = to_jax(_component_weights(self.model_dir, "transformer"))
        self.vae_w = to_jax(_component_weights(self.model_dir, "vae"))
        self.clip_w = to_jax(_component_weights(self.model_dir,
                                                "text_encoder"))
        self.t5_w = to_jax(_component_weights(self.model_dir,
                                              "text_encoder_2"))

        def load_tok(sub):
            p = os.path.join(self.model_dir, sub, "tokenizer.json")
            if os.path.exists(p):
                from tokenizers import Tokenizer as HFTok

                return HFTok.from_file(p)
            return None

        self.tokenizer = load_tok("tokenizer")
        self.tokenizer_2 = load_tok("tokenizer_2")
        self.vae_scale = 2 ** (len(self.vae_cfg["block_out_channels"]) - 1)
        self._sample = jax.jit(self._sample_impl,
                               static_argnames=("steps", "h", "w"))

    def _ids(self, prompt, tokenizer, cfg, s, eos_pad=False):
        if tokenizer is not None:
            ids = tokenizer.encode(prompt).ids
            eos = tokenizer.token_to_id("<|endoftext|>") if eos_pad else None
            if eos is not None:
                # CLIP: never truncate the EOT away — the pooled embedding
                # is read at its position — and pad with it, as SD does
                ids = ids[: s - 1] + [eos]
                ids = ids + [eos] * (s - len(ids))
            else:
                ids = ids[:s] + [0] * max(0, s - len(ids))
        else:
            import zlib

            v = cfg["vocab_size"]
            ids = [zlib.crc32(tk.encode()) % v
                   for tk in prompt.lower().split()][:s]
            ids = ids + [0] * (s - len(ids))
        return jnp.asarray([ids], jnp.int32)

    def encode_prompt(self, prompt: str, t5_len: int = 64):
        """(txt [1, S, joint_dim], vec [1, pooled_dim])."""
        clip_s = min(self.clip_cfg.get("max_position_embeddings", 77), 77)
        _, pooled = clip_encode(
            self.clip_w, self.clip_cfg,
            self._ids(prompt, self.tokenizer, self.clip_cfg, clip_s,
                      eos_pad=True),
            with_pooled=True)
        txt = t5_encode(self.t5_w, self.t5_cfg,
                        self._ids(prompt, self.tokenizer_2, self.t5_cfg,
                                  t5_len))
        return txt, pooled

    def _sample_impl(self, txt, vec, key, *, steps, h, w, guidance):
        lc = self.vae_cfg.get("latent_channels", 16)
        lh, lw = h // self.vae_scale, w // self.vae_scale
        # packed 2x2 patches: [1, (lh/2)*(lw/2), lc*4]
        lat = jax.random.normal(key, (1, (lh // 2) * (lw // 2), lc * 4),
                                jnp.float32)
        sigmas = jnp.linspace(1.0, 1.0 / steps, steps)
        sigmas = jnp.concatenate([sigmas, jnp.zeros((1,))])
        g = jnp.full((1,), guidance, jnp.float32)

        def body(z, i):
            t = jnp.full((1,), sigmas[i], jnp.float32)
            vel = flux_apply(self.tf_w, self.tf_cfg, z.astype(txt.dtype),
                             txt, vec, t, guidance=g,
                             grid_hw=(lh // 2, lw // 2))
            return z + (sigmas[i + 1] - sigmas[i]) * vel.astype(jnp.float32), None

        lat, _ = jax.lax.scan(body, lat, jnp.arange(steps))
        # unpack 2x2 patches back to [1, lh, lw, lc]
        lat = lat.reshape(1, lh // 2, lw // 2, 2, 2, lc)
        lat = lat.transpose(0, 1, 3, 2, 4, 5).reshape(1, lh, lw, lc)
        sf = self.vae_cfg.get("scaling_factor", 0.3611)
        shift = self.vae_cfg.get("shift_factor", 0.1159)
        lat = lat + sf * shift      # vae_decode divides by scaling_factor;
                                    # flux latents also carry a shift
        return vae_decode(self.vae_w, self.vae_cfg, lat.astype(txt.dtype))

    def txt2img(self, prompt: str, width: int = 256, height: int = 256,
                steps: int = 4, guidance: float = 3.5,
                seed: int = 0) -> np.ndarray:
        m = 2 * self.vae_scale
        if width % m or height % m or width < m or height < m:
            raise ValueError(f"width/height must be multiples of {m}")
        txt, vec = self.encode_prompt(prompt)
        img = self._sample(txt, vec, jax.random.PRNGKey(seed),
                           steps=steps, h=height, w=width, guidance=guidance)
        return np.asarray(jax.device_get(
            jnp.round(img[0] * 255))).astype(np.uint8)
