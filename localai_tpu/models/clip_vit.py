"""CLIP ViT vision tower in JAX — the eyes of the multimodal chat path.

Reference parity: LocalAI serves vision-language chat through llama.cpp's
mmproj CLIP encoder (/root/reference/backend/cpp/llama-cpp/grpc-server.cpp:285-289
loads the mmproj GGUF) and vLLM/mlx-vlm multimodal inputs
(/root/reference/backend/python/vllm/backend.py:232-252). Here the tower is
the HF `CLIPVisionModel` layout run as a stacked-layer lax.scan — one
compiled block, MXU-shaped matmuls — feeding the LLaVA projector
(models/llava.py).

Layout notes (HF transformers):
- patch conv [H, 3, P, P], stride P, no bias → as a matmul over flattened
  patches (a P×P conv with stride P IS a linear map per patch — matmul is
  the MXU-native spelling).
- class embedding prepended, learned position embeddings added.
- "pre_layrnorm" (sic — HF's historical typo) before the encoder.
- pre-LN transformer blocks, quick_gelu (x·σ(1.702x)) MLP.
- LLaVA reads hidden_states[-2] (vision_feature_layer) and drops the CLS
  row (vision_feature_select_strategy="default"), so the final
  post_layernorm is NOT applied to the features we return.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.ops.norms import layer_norm


@dataclasses.dataclass(frozen=True)
class ClipVisionConfig:
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    image_size: int = 336
    patch_size: int = 14
    layer_norm_eps: float = 1e-5
    dtype: str = "float32"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @staticmethod
    def from_hf(hf: dict[str, Any], dtype: str | None = None):
        return ClipVisionConfig(
            hidden_size=hf.get("hidden_size", 1024),
            intermediate_size=hf.get("intermediate_size", 4096),
            num_layers=hf.get("num_hidden_layers", 24),
            num_heads=hf.get("num_attention_heads", 16),
            image_size=hf.get("image_size", 336),
            patch_size=hf.get("patch_size", 14),
            layer_norm_eps=hf.get("layer_norm_eps", 1e-5),
            dtype=dtype or "float32",
        )


# CLIP pixel normalization (OpenAI checkpoints; HF CLIPImageProcessor)
IMAGE_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
IMAGE_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)


def preprocess_image(data: bytes, cfg: ClipVisionConfig) -> np.ndarray:
    """Image bytes → pixel_values [1, 3, S, S] f32 (resize + CLIP normalize).
    Matches CLIPImageProcessor's square resize (llava's processor does a
    bicubic resize to image_size on both axes)."""
    import io

    from PIL import Image

    img = Image.open(io.BytesIO(data)).convert("RGB")
    img = img.resize((cfg.image_size, cfg.image_size), Image.BICUBIC)
    x = np.asarray(img, np.float32) / 255.0                    # [S, S, 3]
    x = (x - IMAGE_MEAN) / IMAGE_STD
    return x.transpose(2, 0, 1)[None]                          # [1, 3, S, S]


def vision_forward(params, cfg: ClipVisionConfig, pixel_values,
                   feature_layer: int = -2):
    """pixel_values [B, 3, S, S] → hidden states [B, 1 + N, H] at
    `feature_layer` (counted like HF hidden_states: -1 = after the last
    block, -2 = after the second-to-last). CLS row included; callers slice.
    """
    x = jnp.asarray(pixel_values, cfg.jdtype)
    b = x.shape[0]
    p = cfg.patch_size
    g = cfg.image_size // p
    # [B, 3, G, p, G, p] → [B, G*G, 3*p*p]: each patch flattened exactly in
    # the conv-kernel element order (channel-major), so the matmul below is
    # bit-equivalent to HF's stride-P conv
    x = x.reshape(b, 3, g, p, g, p).transpose(0, 2, 4, 1, 3, 5)
    x = x.reshape(b, g * g, 3 * p * p)
    x = x @ params["patch_embed"]                              # [B, N, H]
    cls = jnp.broadcast_to(params["class_embed"], (b, 1, cfg.hidden_size))
    x = jnp.concatenate([cls.astype(x.dtype), x], axis=1)      # [B, 1+N, H]
    x = x + params["pos_embed"]
    x = layer_norm(x, params["pre_ln_w"], params["pre_ln_b"],
                   cfg.layer_norm_eps)

    n_run = cfg.num_layers + 1 + feature_layer if feature_layer < 0 \
        else feature_layer
    nh = cfg.num_heads
    hd = cfg.hidden_size // nh
    scale = hd ** -0.5

    def block(x, lp):
        h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], cfg.layer_norm_eps)
        q = (h @ lp["wq"] + lp["bq"]).reshape(b, -1, nh, hd)
        k = (h @ lp["wk"] + lp["bk"]).reshape(b, -1, nh, hd)
        v = (h @ lp["wv"] + lp["bv"]).reshape(b, -1, nh, hd)
        s = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
        a = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", a, v).reshape(b, -1, nh * hd)
        x = x + (o @ lp["wo"] + lp["bo"])
        h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], cfg.layer_norm_eps)
        h = h @ lp["fc1"] + lp["b1"]
        h = h * jax.nn.sigmoid(1.702 * h)                      # quick_gelu
        x = x + (h @ lp["fc2"] + lp["b2"])
        return x, None

    sliced = jax.tree_util.tree_map(lambda t: t[:n_run], params["layers"])
    x, _ = jax.lax.scan(block, x, sliced)
    return x


def init_vision_params(cfg: ClipVisionConfig, key):
    """Random init with the load_vision_params layout (tests)."""
    ks = jax.random.split(key, 4)
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    pdim = 3 * cfg.patch_size ** 2
    dt = cfg.jdtype

    def norm(k, shape, fan):
        return (jax.random.normal(k, shape, jnp.float32) * fan ** -0.5
                ).astype(dt)

    layers = {
        "ln1_w": jnp.ones((L, H), dt), "ln1_b": jnp.zeros((L, H), dt),
        "wq": norm(ks[0], (L, H, H), H), "bq": jnp.zeros((L, H), dt),
        "wk": norm(ks[1], (L, H, H), H), "bk": jnp.zeros((L, H), dt),
        "wv": norm(ks[2], (L, H, H), H), "bv": jnp.zeros((L, H), dt),
        "wo": norm(ks[3], (L, H, H), H), "bo": jnp.zeros((L, H), dt),
        "ln2_w": jnp.ones((L, H), dt), "ln2_b": jnp.zeros((L, H), dt),
        "fc1": norm(ks[0], (L, H, I), H), "b1": jnp.zeros((L, I), dt),
        "fc2": norm(ks[1], (L, I, H), I), "b2": jnp.zeros((L, H), dt),
    }
    return {
        "patch_embed": norm(ks[2], (pdim, H), pdim),
        "class_embed": norm(ks[3], (H,), H),
        "pos_embed": norm(ks[0], (1 + cfg.n_patches, H), H),
        "pre_ln_w": jnp.ones((H,), dt), "pre_ln_b": jnp.zeros((H,), dt),
        "layers": layers,
    }


def load_vision_params(reader, cfg: ClipVisionConfig, *, prefix: str,
                       dtype=None):
    """HF CLIPVisionModel weights → our layout. `reader` is an
    engine.loader._TensorReader; `prefix` is e.g. "vision_tower." or
    "model.vision_tower." (both LLaVA save layouts)."""
    def get(name):
        t = reader.get(prefix + "vision_model." + name)
        return np.asarray(t, np.float32)

    L = cfg.num_layers
    lay = "encoder.layers.{i}."

    def stack(fmt, transpose):
        ts = [get(fmt.format(i=i)) for i in range(L)]
        return np.stack([t.T if transpose else t for t in ts])

    layers = {
        "ln1_w": stack(lay + "layer_norm1.weight", False),
        "ln1_b": stack(lay + "layer_norm1.bias", False),
        "wq": stack(lay + "self_attn.q_proj.weight", True),
        "bq": stack(lay + "self_attn.q_proj.bias", False),
        "wk": stack(lay + "self_attn.k_proj.weight", True),
        "bk": stack(lay + "self_attn.k_proj.bias", False),
        "wv": stack(lay + "self_attn.v_proj.weight", True),
        "bv": stack(lay + "self_attn.v_proj.bias", False),
        "wo": stack(lay + "self_attn.out_proj.weight", True),
        "bo": stack(lay + "self_attn.out_proj.bias", False),
        "ln2_w": stack(lay + "layer_norm2.weight", False),
        "ln2_b": stack(lay + "layer_norm2.bias", False),
        "fc1": stack(lay + "mlp.fc1.weight", True),
        "b1": stack(lay + "mlp.fc1.bias", False),
        "fc2": stack(lay + "mlp.fc2.weight", True),
        "b2": stack(lay + "mlp.fc2.bias", False),
    }
    conv = get("embeddings.patch_embedding.weight")  # [H, 3, P, P]
    patch = conv.reshape(conv.shape[0], -1).T        # [3*P*P, H]
    params = {
        "patch_embed": patch,
        "class_embed": get("embeddings.class_embedding"),
        "pos_embed": get("embeddings.position_embedding.weight"),
        "pre_ln_w": get("pre_layrnorm.weight"),
        "pre_ln_b": get("pre_layrnorm.bias"),
        "layers": layers,
    }
    jdt = jnp.dtype(cfg.dtype)
    return jax.tree_util.tree_map(lambda t: jnp.asarray(t, jdt), params)
