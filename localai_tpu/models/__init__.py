from localai_tpu.models.llama import LlamaConfig, init_params, param_specs
