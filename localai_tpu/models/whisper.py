"""Whisper encoder-decoder in JAX — the transcription engine.

Reference role: whisper.cpp backend (/root/reference/backend/go/whisper/
gowhisper.go + gowhisper.cpp) serving the AudioTranscription RPC. Rebuilt
TPU-first: mel features on host (audio/mel.py), encoder+decoder as jitted
scan-stacked transformer layers (bf16-ready, MXU-shaped matmuls), greedy
decode with a self-attn KV cache and precomputed cross-attention K/V.

Checkpoint layout follows HF WhisperForConditionalGeneration safetensors
(q/k/v/out per attention, k_proj biasless; decoder positions learned; output
projection tied to token embeddings). Parity-tested against the torch model.
"""
from __future__ import annotations

import dataclasses
import json
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    vocab_size: int = 51865
    d_model: int = 384
    encoder_layers: int = 4
    decoder_layers: int = 4
    heads: int = 6
    ffn_dim: int = 1536
    num_mel_bins: int = 80
    max_source_positions: int = 1500
    max_target_positions: int = 448
    dtype: str = "float32"
    # generation specials (from generation_config.json)
    decoder_start_token_id: int = 50258
    eos_token_id: int = 50257
    suppress_tokens: tuple = ()
    forced_ids: tuple = ()     # ((position, token), ...) language/task tokens

    @property
    def head_dim(self) -> int:
        return self.d_model // self.heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


def load_config(model_dir: str, dtype: str | None = None) -> WhisperConfig:
    with open(os.path.join(model_dir, "config.json")) as f:
        hf = json.load(f)
    kw = dict(
        vocab_size=hf["vocab_size"],
        d_model=hf["d_model"],
        encoder_layers=hf["encoder_layers"],
        decoder_layers=hf["decoder_layers"],
        heads=hf["encoder_attention_heads"],
        ffn_dim=hf["encoder_ffn_dim"],
        num_mel_bins=hf["num_mel_bins"],
        max_source_positions=hf["max_source_positions"],
        max_target_positions=hf["max_target_positions"],
    )
    if dtype:
        kw["dtype"] = dtype
    gen_path = os.path.join(model_dir, "generation_config.json")
    gen = {}
    if os.path.exists(gen_path):
        with open(gen_path) as f:
            gen = json.load(f)
    kw["decoder_start_token_id"] = gen.get(
        "decoder_start_token_id", hf.get("decoder_start_token_id", 50258))
    eos = gen.get("eos_token_id", hf.get("eos_token_id", 50257))
    kw["eos_token_id"] = eos if isinstance(eos, int) else eos[0]
    kw["suppress_tokens"] = tuple(gen.get("suppress_tokens") or [])
    forced = gen.get("forced_decoder_ids") or []
    kw["forced_ids"] = tuple((int(p), int(t)) for p, t in forced)
    return WhisperConfig(**kw)


# ------------------------------------------------------------------ params

def _attn_names(prefix, bias_k=False):
    names = {
        "qw": f"{prefix}.q_proj.weight", "qb": f"{prefix}.q_proj.bias",
        "kw": f"{prefix}.k_proj.weight",
        "vw": f"{prefix}.v_proj.weight", "vb": f"{prefix}.v_proj.bias",
        "ow": f"{prefix}.out_proj.weight", "ob": f"{prefix}.out_proj.bias",
    }
    if bias_k:
        names["kb"] = f"{prefix}.k_proj.bias"
    return names


def load_params(model_dir: str, cfg: WhisperConfig, dtype=None):
    """HF safetensors → stacked pytree ([L, ...] per side, x @ W layout)."""
    from localai_tpu.engine.loader import _TensorReader

    dtype = jnp.dtype(dtype) if dtype else cfg.jdtype
    r = _TensorReader(model_dir)

    def get(name, transpose=False):
        t = r.get("model." + name) if ("model." + name) in r else r.get(name)
        t = t.astype(dtype) if t.dtype != dtype else t
        return jnp.asarray(t.T if transpose else t)

    def stack_side(side: str, n_layers: int, cross: bool):
        rows = []
        for i in range(n_layers):
            L = f"{side}.layers.{i}"
            row = {}
            for key, name in _attn_names(f"{L}.self_attn").items():
                row["self_" + key] = get(name, transpose=key.endswith("w")
                                         and key != "ln")
            if cross:
                for key, name in _attn_names(f"{L}.encoder_attn").items():
                    row["cross_" + key] = get(name, transpose=key.endswith("w"))
                row["ln_cross_w"] = get(f"{L}.encoder_attn_layer_norm.weight")
                row["ln_cross_b"] = get(f"{L}.encoder_attn_layer_norm.bias")
            row["ln_self_w"] = get(f"{L}.self_attn_layer_norm.weight")
            row["ln_self_b"] = get(f"{L}.self_attn_layer_norm.bias")
            row["fc1_w"] = get(f"{L}.fc1.weight", transpose=True)
            row["fc1_b"] = get(f"{L}.fc1.bias")
            row["fc2_w"] = get(f"{L}.fc2.weight", transpose=True)
            row["fc2_b"] = get(f"{L}.fc2.bias")
            row["ln_mlp_w"] = get(f"{L}.final_layer_norm.weight")
            row["ln_mlp_b"] = get(f"{L}.final_layer_norm.bias")
            rows.append(row)
        return {k: jnp.stack([row[k] for row in rows]) for k in rows[0]}

    params = {
        "encoder": {
            "conv1_w": get("encoder.conv1.weight"),    # [D, mel, 3]
            "conv1_b": get("encoder.conv1.bias"),
            "conv2_w": get("encoder.conv2.weight"),
            "conv2_b": get("encoder.conv2.bias"),
            "pos": get("encoder.embed_positions.weight"),
            "layers": stack_side("encoder", cfg.encoder_layers, cross=False),
            "ln_w": get("encoder.layer_norm.weight"),
            "ln_b": get("encoder.layer_norm.bias"),
        },
        "decoder": {
            "embed": get("decoder.embed_tokens.weight"),
            "pos": get("decoder.embed_positions.weight"),
            "layers": stack_side("decoder", cfg.decoder_layers, cross=True),
            "ln_w": get("decoder.layer_norm.weight"),
            "ln_b": get("decoder.layer_norm.bias"),
        },
    }
    r.close()
    return params


# ------------------------------------------------------------------ ops

def _ln(x, w, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * w + b).astype(x.dtype)


def _heads(x, h):
    b, s, d = x.shape
    return x.reshape(b, s, h, d // h)


def _attend(q, k, v, mask=None):
    """q [B,S,H,D] vs k/v [B,T,H,D] → [B,S,H*D]; softmax in f32."""
    b, s, h, d = q.shape
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32)
    logits = logits * (d ** -0.5)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhst,bthd->bshd", p, v)
    return out.reshape(b, s, h * d)


def encode(params, cfg: WhisperConfig, mel):
    """mel [B, n_mels, frames] → encoder states [B, S, D]."""
    enc = params["encoder"]
    x = jax.lax.conv_general_dilated(
        mel.astype(cfg.jdtype), enc["conv1_w"].astype(cfg.jdtype),
        window_strides=(1,), padding=((1, 1),),
        dimension_numbers=("NCH", "OIH", "NCH"))
    x = jax.nn.gelu(x + enc["conv1_b"][None, :, None], approximate=False)
    x = jax.lax.conv_general_dilated(
        x, enc["conv2_w"].astype(cfg.jdtype),
        window_strides=(2,), padding=((1, 1),),
        dimension_numbers=("NCH", "OIH", "NCH"))
    x = jax.nn.gelu(x + enc["conv2_b"][None, :, None], approximate=False)
    x = x.transpose(0, 2, 1)                                # [B, S, D]
    x = x + enc["pos"][: x.shape[1]].astype(x.dtype)

    h = cfg.heads

    def layer(x, lp):
        y = _ln(x, lp["ln_self_w"], lp["ln_self_b"])
        q = _heads(y @ lp["self_qw"] + lp["self_qb"], h)
        k = _heads(y @ lp["self_kw"], h)
        v = _heads(y @ lp["self_vw"] + lp["self_vb"], h)
        x = x + _attend(q, k, v) @ lp["self_ow"] + lp["self_ob"]
        y = _ln(x, lp["ln_mlp_w"], lp["ln_mlp_b"])
        y = jax.nn.gelu(y @ lp["fc1_w"] + lp["fc1_b"], approximate=False)
        x = x + y @ lp["fc2_w"] + lp["fc2_b"]
        return x, None

    x, _ = jax.lax.scan(layer, x, enc["layers"])
    return _ln(x, enc["ln_w"], enc["ln_b"])


def cross_kv(params, cfg: WhisperConfig, enc_out):
    """Precompute per-layer cross-attention K/V → [L, B, S, H, D] each."""
    h = cfg.heads
    lp = params["decoder"]["layers"]

    def one(carry, row):
        k = _heads(enc_out @ row["cross_kw"], h)
        v = _heads(enc_out @ row["cross_vw"] + row["cross_vb"], h)
        return carry, (k, v)

    _, (ks, vs) = jax.lax.scan(one, None, lp)
    return ks, vs


def init_self_cache(cfg: WhisperConfig, batch: int, max_len: int | None = None):
    T = max_len or cfg.max_target_positions
    shape = (cfg.decoder_layers, batch, T, cfg.heads, cfg.head_dim)
    return (jnp.zeros(shape, cfg.jdtype), jnp.zeros(shape, cfg.jdtype))


def decode_step(params, cfg: WhisperConfig, tokens, lengths, cross_k, cross_v,
                kc, vc):
    """One decoder step. tokens [B]; lengths [B] = tokens already in cache.
    Returns (logits [B, V] f32, kc, vc)."""
    dec = params["decoder"]
    b = tokens.shape[0]
    h = cfg.heads
    T = kc.shape[2]
    x = dec["embed"].astype(cfg.jdtype)[tokens][:, None, :]  # [B,1,D]
    x = x + jnp.take(dec["pos"], lengths, axis=0)[:, None, :].astype(x.dtype)

    pos = jnp.arange(T)
    self_mask = (pos[None, :] <= lengths[:, None])[:, None, None, :]  # [B,1,1,T]

    def layer(x, xs):
        lp, ck, cv, kcl, vcl = xs
        y = _ln(x, lp["ln_self_w"], lp["ln_self_b"])
        q = _heads(y @ lp["self_qw"] + lp["self_qb"], h)
        k = _heads(y @ lp["self_kw"], h)
        v = _heads(y @ lp["self_vw"] + lp["self_vb"], h)
        kcl = kcl.at[jnp.arange(b)[:, None], lengths[:, None]].set(k)
        vcl = vcl.at[jnp.arange(b)[:, None], lengths[:, None]].set(v)
        x = x + _attend(q, kcl, vcl, self_mask) @ lp["self_ow"] + lp["self_ob"]
        y = _ln(x, lp["ln_cross_w"], lp["ln_cross_b"])
        q = _heads(y @ lp["cross_qw"] + lp["cross_qb"], h)
        x = x + _attend(q, ck, cv) @ lp["cross_ow"] + lp["cross_ob"]
        y = _ln(x, lp["ln_mlp_w"], lp["ln_mlp_b"])
        y = jax.nn.gelu(y @ lp["fc1_w"] + lp["fc1_b"], approximate=False)
        x = x + y @ lp["fc2_w"] + lp["fc2_b"]
        return x, (kcl, vcl)

    x, (kc, vc) = jax.lax.scan(layer, x, (dec["layers"], cross_k, cross_v,
                                          kc, vc))
    x = _ln(x, dec["ln_w"], dec["ln_b"])
    logits = x[:, 0].astype(jnp.float32) @ dec["embed"].astype(jnp.float32).T
    return logits, kc, vc


# ------------------------------------------------------------------ generate

class WhisperModel:
    """Host-driven greedy transcription over the jitted encoder/decoder."""

    def __init__(self, model_dir: str, dtype: str | None = None):
        self.cfg = load_config(model_dir, dtype)
        self.params = load_params(model_dir, self.cfg)
        self._encode = jax.jit(partial(encode, cfg=self.cfg))
        self._cross = jax.jit(partial(cross_kv, cfg=self.cfg))
        self._step = jax.jit(partial(decode_step, cfg=self.cfg))
        self.tokenizer = None
        tok_path = os.path.join(model_dir, "tokenizer.json")
        if os.path.exists(tok_path):
            from tokenizers import Tokenizer as HFTok

            self.tokenizer = HFTok.from_file(tok_path)

    def transcribe_tokens(self, audio: np.ndarray, max_tokens: int = 224,
                          beam_size: int = 5,
                          temperatures=(0.0, 0.2, 0.4, 0.6, 0.8, 1.0),
                          logprob_threshold: float = -1.0,
                          compression_threshold: float = 2.4,
                          seed: int = 0) -> list[int]:
        """16 kHz mono f32 → decoded token ids, one 30 s chunk.

        Decode strategy mirrors whisper.cpp / faster-whisper (the reference's
        transcription engines, backend/go/whisper + faster-whisper
        backend.py): beam search at temperature 0, then temperature-fallback
        resampling whenever the result looks degenerate (average logprob
        below `logprob_threshold` or zlib compression ratio above
        `compression_threshold` — the repetition-loop detector)."""
        from localai_tpu.audio.mel import log_mel_spectrogram

        cfg = self.cfg
        mel = log_mel_spectrogram(audio, n_mels=cfg.num_mel_bins)[None]
        enc = self._encode(self.params, mel=jnp.asarray(mel))
        ck, cv = self._cross(self.params, enc_out=enc)
        max_tokens = min(max_tokens, cfg.max_target_positions - 1)

        best: list[int] = []
        for ti, temp in enumerate(temperatures):
            if temp == 0.0 and beam_size > 1:
                ids, avg_lp = self._beam_decode(ck, cv, beam_size, max_tokens)
            else:
                ids, avg_lp = self._sample_decode(ck, cv, temp, max_tokens,
                                                  seed + ti)
            best = ids
            if avg_lp < logprob_threshold:
                continue
            if self.tokenizer is not None and len(ids) >= 8:
                import zlib

                text = self.tokenizer.decode(ids, skip_special_tokens=True)
                raw = text.encode()
                if raw and len(raw) / len(zlib.compress(raw)) > \
                        compression_threshold:
                    continue
            break
        return best

    def _logprobs_host(self, logits) -> np.ndarray:
        """[B, V] logits → suppress-masked log-softmax on host."""
        lg = np.asarray(logits, np.float64)
        suppress = np.array(list(self.cfg.suppress_tokens), np.int64)
        if suppress.size:
            lg[:, suppress] = -np.inf
        lg = lg - lg.max(axis=-1, keepdims=True)
        lse = np.log(np.exp(lg).sum(axis=-1, keepdims=True))
        return lg - lse

    def _sample_decode(self, ck, cv, temp: float, max_tokens: int, seed: int
                       ) -> tuple[list[int], float]:
        """Single-stream decode: argmax at temp 0, multinomial otherwise.
        Returns (ids, avg logprob incl. the end token)."""
        cfg = self.cfg
        rng = np.random.default_rng(seed)
        kc, vc = init_self_cache(cfg, 1)
        forced = dict(cfg.forced_ids)
        ids = [cfg.decoder_start_token_id]
        sum_lp, n_lp = 0.0, 0
        for i in range(max_tokens):
            logits, kc, vc = self._step(
                self.params, tokens=jnp.array([ids[-1]], jnp.int32),
                lengths=jnp.array([i], jnp.int32),
                cross_k=ck, cross_v=cv, kc=kc, vc=vc)
            lp = self._logprobs_host(logits)[0]
            if i + 1 in forced:
                nxt = forced[i + 1]
            elif temp > 0:
                p = np.exp((lp - lp.max()) / temp)
                p = p / p.sum()
                nxt = int(rng.choice(len(p), p=p))
            else:
                nxt = int(lp.argmax())
            sum_lp += float(lp[nxt]) if np.isfinite(lp[nxt]) else 0.0
            n_lp += 1
            if nxt == cfg.eos_token_id:
                break
            ids.append(nxt)
        return ids[1:], (sum_lp / max(n_lp, 1))

    def _beam_decode(self, ck, cv, beam_size: int, max_tokens: int
                     ) -> tuple[list[int], float]:
        """Batched beam search over the jitted decode step: the whole beam
        is ONE device batch; beams reorder by gathering the self-attn cache
        on the parent index. Finished hypotheses leave the beam; selection is
        by length-normalized logprob (the whisper.cpp/HF default)."""
        cfg = self.cfg
        B = beam_size
        kc, vc = init_self_cache(cfg, B)
        ckb = jnp.repeat(ck, B, axis=1)
        cvb = jnp.repeat(cv, B, axis=1)
        forced = dict(cfg.forced_ids)

        seqs = [[cfg.decoder_start_token_id] for _ in range(B)]
        # only beam 0 is live at step 0 (all beams start identical)
        cum = np.full(B, -np.inf)
        cum[0] = 0.0
        finished: list[tuple[list[int], float]] = []

        for i in range(max_tokens):
            logits, kc, vc = self._step(
                self.params,
                tokens=jnp.asarray([s[-1] for s in seqs], jnp.int32),
                lengths=jnp.full((B,), i, jnp.int32),
                cross_k=ckb, cross_v=cvb, kc=kc, vc=vc)
            lp = self._logprobs_host(logits)            # [B, V]
            if i + 1 in forced:
                # forced tokens may themselves be suppressed (whisper's
                # standard lists overlap) — a -inf here would collapse every
                # beam; count them as free, like _sample_decode does
                tok = forced[i + 1]
                step = lp[:, tok]
                cum = cum + np.where(np.isfinite(step), step, 0.0)
                for s in seqs:
                    s.append(tok)
                continue
            total = cum[:, None] + lp                   # [B, V]
            flat = total.ravel()
            order = np.argsort(flat)[::-1][: 2 * B]
            new_seqs, new_cum, parents = [], [], []
            for fi in order:
                parent, tok = divmod(int(fi), lp.shape[1])
                score = float(flat[fi])
                if not np.isfinite(score):
                    continue
                if tok == cfg.eos_token_id:
                    finished.append((seqs[parent][1:], score / (i + 2)))
                    continue
                new_seqs.append(seqs[parent] + [tok])
                new_cum.append(score)
                parents.append(parent)
                if len(new_seqs) == B:
                    break
            if not new_seqs or len(finished) >= B:
                break
            while len(new_seqs) < B:                    # pad dead beams
                new_seqs.append(list(new_seqs[0]))
                new_cum.append(-np.inf)
                parents.append(parents[0])
            idx = jnp.asarray(parents)
            kc = kc[:, idx]
            vc = vc[:, idx]
            seqs, cum = new_seqs, np.asarray(new_cum)

        if not finished:
            j = int(np.argmax(cum))
            finished.append((seqs[j][1:], float(cum[j]) / (len(seqs[j]) + 1)))
        finished.sort(key=lambda t: -t[1])
        return finished[0]

    def transcribe(self, audio: np.ndarray, rate: int = 16000) -> str:
        if rate != 16000:
            from localai_tpu.audio.pcm import read_wav  # noqa: F401  (resample path)

            from scipy.signal import resample_poly
            from math import gcd

            g = gcd(16000, rate)
            audio = resample_poly(audio, 16000 // g, rate // g)
        toks = self.transcribe_tokens(np.asarray(audio, np.float32))
        if self.tokenizer is None:
            return " ".join(map(str, toks))
        return self.tokenizer.decode(toks, skip_special_tokens=True)
