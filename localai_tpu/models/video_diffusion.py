"""Text-to-video latent diffusion — AnimateDiff-style motion modules over the
SD UNet (replaces the round-4 GIF-of-independent-frames stand-in).

Reference role: the diffusers backend's GenerateVideo
(/root/reference/backend/python/diffusers/backend.py) serves video pipelines;
the dominant open recipe is a frozen SD 1.x UNet + a motion adapter whose
temporal transformers attend ACROSS the frame axis after each spatial block
(diffusers `MotionAdapter` layout: `down_blocks.{i}.motion_modules.{j}.*`,
`mid_block.motion_modules.0.*`, `up_blocks.{i}.motion_modules.{j}.*`).

TPU shape: frames ride the batch axis for every spatial op (conv/attention
stay large MXU matmuls), and each motion module is one reshape to
[(B·H·W), F, C] + self-attention over F — small, fused, no host round trips;
the whole denoise loop is a single lax.scan like the image path.

Checkpoint layout: a diffusers SD directory plus a `motion_adapter/`
subdirectory (config.json + *.safetensors with the MotionAdapter names).
"""
from __future__ import annotations

import dataclasses
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.models.latent_diffusion import (
    LatentDiffusion,
    _component_config,
    _component_weights,
    _resnet,
    _spatial_transformer,
    attention,
    conv2d,
    group_norm,
    layer_norm,
    linear,
    timestep_embedding,
    vae_decode,
)


def is_video_checkpoint(model_dir: str) -> bool:
    return os.path.isdir(os.path.join(model_dir, "motion_adapter"))


def _sin_pos(f: int, c: int):
    """Sinusoidal positions [F, C] (AnimateDiff's fixed PositionalEncoding)."""
    pos = np.arange(f)[:, None]
    div = np.exp(np.arange(0, c, 2) * (-np.log(10000.0) / c))
    pe = np.zeros((f, c), np.float32)
    pe[:, 0::2] = np.sin(pos * div)
    pe[:, 1::2] = np.cos(pos * div[: pe[:, 1::2].shape[1]])
    return jnp.asarray(pe)


def motion_module(mm, pfx, x, num_frames: int, heads: int):
    """One temporal transformer: x [B*F, H, W, C] → same, mixing information
    across the F axis at every spatial location. Layout-flexible like the
    spatial blocks: cross-attn (attn2) and the third norm are optional."""
    bf, h_, w_, c = x.shape
    b = bf // num_frames
    res = x
    x = group_norm(x, mm[pfx + "norm.weight"], mm[pfx + "norm.bias"],
                   min(32, c))
    # [(B·H·W), F, C]: frames become the sequence axis
    x = (x.reshape(b, num_frames, h_, w_, c)
          .transpose(0, 2, 3, 1, 4)
          .reshape(b * h_ * w_, num_frames, c))
    x = linear(x, mm[pfx + "proj_in.weight"], mm[pfx + "proj_in.bias"])
    x = x + _sin_pos(num_frames, c)[None]
    d = 0
    while pfx + f"transformer_blocks.{d}.attn1.to_q.weight" in mm:
        t = f"{pfx}transformer_blocks.{d}."
        hh = layer_norm(x, mm[t + "norm1.weight"], mm[t + "norm1.bias"])
        a = attention(linear(hh, mm[t + "attn1.to_q.weight"]),
                      linear(hh, mm[t + "attn1.to_k.weight"]),
                      linear(hh, mm[t + "attn1.to_v.weight"]), heads)
        x = x + linear(a, mm[t + "attn1.to_out.0.weight"],
                       mm[t + "attn1.to_out.0.bias"])
        if t + "attn2.to_q.weight" in mm:
            hh = layer_norm(x, mm[t + "norm2.weight"], mm[t + "norm2.bias"])
            a = attention(linear(hh, mm[t + "attn2.to_q.weight"]),
                          linear(hh, mm[t + "attn2.to_k.weight"]),
                          linear(hh, mm[t + "attn2.to_v.weight"]), heads)
            x = x + linear(a, mm[t + "attn2.to_out.0.weight"],
                           mm[t + "attn2.to_out.0.bias"])
        nf = ("norm3" if t + "norm3.weight" in mm else "norm2")
        hh = layer_norm(x, mm[t + nf + ".weight"], mm[t + nf + ".bias"])
        hh = linear(hh, mm[t + "ff.net.0.proj.weight"],
                    mm[t + "ff.net.0.proj.bias"])
        if hh.shape[-1] == 8 * c:      # GEGLU (diffusers): value · gelu(gate)
            val, gate = jnp.split(hh, 2, axis=-1)
            hh = val * jax.nn.gelu(gate)
        else:                          # plain GELU mlp
            hh = jax.nn.gelu(hh)
        x = x + linear(hh, mm[t + "ff.net.2.weight"], mm[t + "ff.net.2.bias"])
        d += 1
    x = linear(x, mm[pfx + "proj_out.weight"], mm[pfx + "proj_out.bias"])
    x = (x.reshape(b, h_, w_, num_frames, c)
          .transpose(0, 3, 1, 2, 4)
          .reshape(bf, h_, w_, c))
    return res + x


def unet3d_apply(w, mm, cfg, latents, t, ctx, num_frames: int):
    """UNet2DCondition + motion modules. latents [B*F, H, W, 4]; t [B*F];
    ctx [B*F, S, D] (prompt embedding repeated per frame). Mirrors
    latent_diffusion.unet_apply's loop with a temporal transformer after
    every (resnet, attention) pair — the AnimateDiff insertion points."""
    groups = cfg.get("norm_num_groups", 32)
    chans = cfg["block_out_channels"]
    lpb = cfg.get("layers_per_block", 2)
    head_dim = cfg.get("attention_head_dim", 8)
    head_dims = (head_dim if isinstance(head_dim, list)
                 else [head_dim] * len(chans))
    down_types = cfg["down_block_types"]
    up_types = cfg["up_block_types"]
    mm_heads = 8

    def motion(x, pfx):
        if pfx + "proj_in.weight" in mm:
            return motion_module(mm, pfx, x, num_frames,
                                 min(mm_heads, max(1, x.shape[-1] // 32)))
        return x

    temb = timestep_embedding(t, chans[0])
    temb = linear(temb, w["time_embedding.linear_1.weight"],
                  w["time_embedding.linear_1.bias"])
    temb = linear(jax.nn.silu(temb), w["time_embedding.linear_2.weight"],
                  w["time_embedding.linear_2.bias"])

    x = conv2d(latents, w["conv_in.weight"], w["conv_in.bias"])
    skips = [x]
    for i, btype in enumerate(down_types):
        heads = max(1, chans[i] // head_dims[i])
        for j in range(lpb):
            x = _resnet(w, f"down_blocks.{i}.resnets.{j}.", x, temb, groups)
            if "CrossAttn" in btype:
                x = _spatial_transformer(
                    w, f"down_blocks.{i}.attentions.{j}.", x, ctx, heads,
                    groups)
            x = motion(x, f"down_blocks.{i}.motion_modules.{j}.")
            skips.append(x)
        if f"down_blocks.{i}.downsamplers.0.conv.weight" in w:
            x = conv2d(x, w[f"down_blocks.{i}.downsamplers.0.conv.weight"],
                       w[f"down_blocks.{i}.downsamplers.0.conv.bias"],
                       stride=2)
            skips.append(x)

    heads_mid = max(1, chans[-1] // head_dims[-1])
    x = _resnet(w, "mid_block.resnets.0.", x, temb, groups)
    x = _spatial_transformer(w, "mid_block.attentions.0.", x, ctx,
                             heads_mid, groups)
    x = motion(x, "mid_block.motion_modules.0.")
    x = _resnet(w, "mid_block.resnets.1.", x, temb, groups)

    for i, btype in enumerate(up_types):
        ch_i = len(chans) - 1 - i
        heads = max(1, chans[ch_i] // head_dims[ch_i])
        for j in range(lpb + 1):
            x = jnp.concatenate([x, skips.pop()], axis=-1)
            x = _resnet(w, f"up_blocks.{i}.resnets.{j}.", x, temb, groups)
            if "CrossAttn" in btype:
                x = _spatial_transformer(
                    w, f"up_blocks.{i}.attentions.{j}.", x, ctx, heads,
                    groups)
            x = motion(x, f"up_blocks.{i}.motion_modules.{j}.")
        if f"up_blocks.{i}.upsamplers.0.conv.weight" in w:
            n, h_, w_, c = x.shape
            x = jax.image.resize(x, (n, h_ * 2, w_ * 2, c), "nearest")
            x = conv2d(x, w[f"up_blocks.{i}.upsamplers.0.conv.weight"],
                       w[f"up_blocks.{i}.upsamplers.0.conv.bias"])

    x = group_norm(x, w["conv_norm_out.weight"], w["conv_norm_out.bias"],
                   groups)
    return conv2d(jax.nn.silu(x), w["conv_out.weight"], w["conv_out.bias"])


@dataclasses.dataclass
class VideoDiffusion:
    """txt2video pipeline: base SD checkpoint + motion_adapter/ subdir."""

    model_dir: str
    dtype: str = "float32"

    def __post_init__(self):
        self.base = LatentDiffusion(self.model_dir, self.dtype)
        dt = jnp.dtype(self.dtype)
        raw = _component_weights(self.model_dir, "motion_adapter")
        self.mm = {k: jnp.asarray(v).astype(dt)
                   if np.issubdtype(v.dtype, np.floating) else jnp.asarray(v)
                   for k, v in raw.items()}
        self._sample_v = jax.jit(
            partial(self._sample_impl),
            static_argnames=("steps", "h", "w", "frames"))

    def _sample_impl(self, cond, uncond, key, *, steps, h, w, frames,
                     guidance_scale):
        base = self.base
        lc = base.vae_cfg.get("latent_channels", 4)
        latents = jax.random.normal(
            key, (frames, h // base.vae_scale, w // base.vae_scale, lc),
            jnp.float32)
        ts = jnp.linspace(base.n_train - 1, 0, steps).astype(jnp.int32)
        ctx = jnp.concatenate([jnp.repeat(uncond, frames, 0),
                               jnp.repeat(cond, frames, 0)], axis=0)

        def body(lat, i):
            t = ts[i]
            t_prev = jnp.where(i + 1 < steps,
                               ts[jnp.minimum(i + 1, steps - 1)], -1)
            lat2 = jnp.concatenate([lat, lat], axis=0).astype(ctx.dtype)
            eps = unet3d_apply(base.unet_w, self.mm, base.unet_cfg, lat2,
                               jnp.full((2 * frames,), t, jnp.int32), ctx,
                               num_frames=frames)
            eps = eps.astype(jnp.float32)
            eps_u, eps_c = eps[:frames], eps[frames:]
            e = eps_u + guidance_scale * (eps_c - eps_u)
            a_t = base.alphas_bar[t]
            a_prev = jnp.where(t_prev >= 0, base.alphas_bar[t_prev], 1.0)
            x0 = (lat - jnp.sqrt(1 - a_t) * e) / jnp.sqrt(a_t)
            lat = jnp.sqrt(a_prev) * x0 + jnp.sqrt(1 - a_prev) * e
            return lat, None

        latents, _ = jax.lax.scan(body, latents, jnp.arange(steps))
        return vae_decode(base.vae_w, base.vae_cfg, latents.astype(ctx.dtype))

    def encode_prompts(self, prompt: str, negative_prompt: str = ""):
        return self.base.encode_prompts(prompt, negative_prompt)

    def txt2video(self, prompt: str, negative_prompt: str = "",
                  width: int = 128, height: int = 128, num_frames: int = 8,
                  steps: int = 8, guidance_scale: float = 7.5,
                  seed: int = 0) -> np.ndarray:
        """→ uint8 [F, H, W, 3] frames with temporally-coherent content."""
        vs = self.base.vae_scale
        if width % vs or height % vs or width < vs or height < vs:
            raise ValueError(f"width/height must be multiples of {vs}")
        cond, uncond = self.encode_prompts(prompt, negative_prompt)
        vid = self._sample_v(cond, uncond, jax.random.PRNGKey(seed),
                             steps=steps, h=height, w=width,
                             frames=num_frames,
                             guidance_scale=guidance_scale)
        return np.asarray(jax.device_get(
            jnp.round(vid * 255))).astype(np.uint8)
