"""VITS text-to-speech (MMS-TTS family) — the neural TTS role.

Reference: the piper / bark TTS backends (/root/reference/backend/go/piper/
piper.go:1-49, backend/go/bark-cpp) serve the TTS RPC with neural voices;
this is the JAX equivalent, loading HF `VitsModel` checkpoints
(facebook/mms-tts-* — 1100+ languages) end-to-end:

  char ids → relative-window transformer text encoder → (stochastic or
  deterministic) duration predictor → length regulator → inverse residual
  coupling flow → HiFi-GAN decoder → waveform.

Everything runs in JAX, including the rational-quadratic spline flows of the
stochastic duration predictor (masked select instead of boolean indexing so
the math stays vectorized). Weight-norm parametrizations are folded into
plain conv weights at load. Sampling noise scales are honored
(noise_scale=0 → deterministic output, which is how the torch-parity test
pins both implementations).
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class VitsConfig:
    vocab_size: int = 38
    hidden_size: int = 192
    num_layers: int = 6
    num_heads: int = 2
    window_size: int = 4
    ffn_dim: int = 768
    ffn_kernel_size: int = 3
    flow_size: int = 192
    ln_eps: float = 1e-5
    # duration predictor
    use_stochastic_dp: bool = True
    dp_kernel_size: int = 3
    dp_filter_channels: int = 256
    dp_flow_bins: int = 10
    dp_num_flows: int = 4
    dp_tail_bound: float = 5.0
    depth_separable_channels: int = 2
    depth_separable_num_layers: int = 3
    # prior flow
    prior_num_flows: int = 4
    prior_wavenet_layers: int = 4
    wavenet_kernel_size: int = 5
    wavenet_dilation_rate: int = 1
    # decoder (HiFi-GAN)
    upsample_initial_channel: int = 512
    upsample_rates: tuple[int, ...] = (8, 8, 2, 2)
    upsample_kernel_sizes: tuple[int, ...] = (16, 16, 4, 4)
    resblock_kernel_sizes: tuple[int, ...] = (3, 7, 11)
    resblock_dilation_sizes: tuple[tuple[int, ...], ...] = (
        (1, 3, 5), (1, 3, 5), (1, 3, 5))
    leaky_relu_slope: float = 0.1
    # inference
    noise_scale: float = 0.667
    noise_scale_duration: float = 0.8
    speaking_rate: float = 1.0
    sampling_rate: int = 16000


VITS_FAMILY = ("VitsModel",)


def is_vits_dir(model_dir: str) -> bool:
    try:
        with open(os.path.join(model_dir, "config.json")) as f:
            arch = (json.load(f).get("architectures") or [""])[0]
        return arch in VITS_FAMILY
    except (OSError, ValueError):
        return False


def load_vits_config(model_dir: str) -> VitsConfig:
    with open(os.path.join(model_dir, "config.json")) as f:
        hf: dict[str, Any] = json.load(f)
    return VitsConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf.get("hidden_size", 192),
        num_layers=hf.get("num_hidden_layers", 6),
        num_heads=hf.get("num_attention_heads", 2),
        window_size=hf.get("window_size", 4),
        ffn_dim=hf.get("ffn_dim", 768),
        ffn_kernel_size=hf.get("ffn_kernel_size", 3),
        flow_size=hf.get("flow_size", 192),
        ln_eps=hf.get("layer_norm_eps", 1e-5),
        use_stochastic_dp=hf.get("use_stochastic_duration_prediction", True),
        dp_kernel_size=hf.get("duration_predictor_kernel_size", 3),
        dp_filter_channels=hf.get("duration_predictor_filter_channels", 256),
        dp_flow_bins=hf.get("duration_predictor_flow_bins", 10),
        dp_num_flows=hf.get("duration_predictor_num_flows", 4),
        dp_tail_bound=hf.get("duration_predictor_tail_bound", 5.0),
        depth_separable_channels=hf.get("depth_separable_channels", 2),
        depth_separable_num_layers=hf.get("depth_separable_num_layers", 3),
        prior_num_flows=hf.get("prior_encoder_num_flows", 4),
        prior_wavenet_layers=hf.get("prior_encoder_num_wavenet_layers", 4),
        wavenet_kernel_size=hf.get("wavenet_kernel_size", 5),
        wavenet_dilation_rate=hf.get("wavenet_dilation_rate", 1),
        upsample_initial_channel=hf.get("upsample_initial_channel", 512),
        upsample_rates=tuple(hf.get("upsample_rates", (8, 8, 2, 2))),
        upsample_kernel_sizes=tuple(
            hf.get("upsample_kernel_sizes", (16, 16, 4, 4))),
        resblock_kernel_sizes=tuple(
            hf.get("resblock_kernel_sizes", (3, 7, 11))),
        resblock_dilation_sizes=tuple(
            tuple(d) for d in hf.get("resblock_dilation_sizes",
                                     ((1, 3, 5),) * 3)),
        leaky_relu_slope=hf.get("leaky_relu_slope", 0.1),
        noise_scale=hf.get("noise_scale", 0.667),
        noise_scale_duration=hf.get("noise_scale_duration", 0.8),
        speaking_rate=hf.get("speaking_rate", 1.0),
        sampling_rate=hf.get("sampling_rate", 16000),
    )


# ---------------------------------------------------------------- loading

def _fold_weight_norm(t, prefix):
    """weight_norm(v, g): w = g * v / ||v||  (norm over in+kernel dims)."""
    g = t(prefix + ".parametrizations.weight.original0")      # [O,1,1]
    v = t(prefix + ".parametrizations.weight.original1")      # [O,I,K]
    norm = np.sqrt((v * v).sum(axis=(1, 2), keepdims=True))
    return g * v / np.maximum(norm, 1e-12)


def load_vits_params(model_dir: str, cfg: VitsConfig):
    from localai_tpu.engine.loader import _TensorReader, _is_synthetic

    if _is_synthetic(model_dir):
        raise ValueError("VITS synthetic checkpoints are not supported; "
                         "save real (random-initialized is fine) weights")
    r = _TensorReader(model_dir)
    names = set(r.index.keys())

    def t(name):
        return np.asarray(r.get(name), np.float32)

    def conv(prefix):
        if (prefix + ".parametrizations.weight.original0") in names:
            w = _fold_weight_norm(t, prefix)
        else:
            w = t(prefix + ".weight")
        b = t(prefix + ".bias") if (prefix + ".bias") in names else None
        return {"w": w, "b": b}

    def lin(prefix):
        return {"w": t(prefix + ".weight").T, "b": t(prefix + ".bias")}

    def dds(prefix, n):
        return {
            "dil": [conv(f"{prefix}.convs_dilated.{i}") for i in range(n)],
            "pw": [conv(f"{prefix}.convs_pointwise.{i}") for i in range(n)],
            "n1": [(t(f"{prefix}.norms_1.{i}.weight"),
                    t(f"{prefix}.norms_1.{i}.bias")) for i in range(n)],
            "n2": [(t(f"{prefix}.norms_2.{i}.weight"),
                    t(f"{prefix}.norms_2.{i}.bias")) for i in range(n)],
        }

    def wavenet(prefix, n):
        return {
            "in": [conv(f"{prefix}.in_layers.{i}") for i in range(n)],
            "rs": [conv(f"{prefix}.res_skip_layers.{i}") for i in range(n)],
        }

    def conv_flow(prefix):
        return {
            "pre": conv(prefix + ".conv_pre"),
            "dds": dds(prefix + ".conv_dds", cfg.depth_separable_num_layers),
            "proj": conv(prefix + ".conv_proj"),
        }

    def sdp_flows(prefix, n):
        flows = [{"translate": t(f"{prefix}.0.translate"),
                  "log_scale": t(f"{prefix}.0.log_scale")}]
        flows += [conv_flow(f"{prefix}.{i}") for i in range(1, n + 1)]
        return flows

    p: dict[str, Any] = {
        "embed": t("text_encoder.embed_tokens.weight"),
        "project": conv("text_encoder.project"),
    }
    layers = []
    for i in range(cfg.num_layers):
        base = f"text_encoder.encoder.layers.{i}."
        layers.append({
            "q": lin(base + "attention.q_proj"),
            "k": lin(base + "attention.k_proj"),
            "v": lin(base + "attention.v_proj"),
            "out": lin(base + "attention.out_proj"),
            "rel_k": t(base + "attention.emb_rel_k"),
            "rel_v": t(base + "attention.emb_rel_v"),
            "ln1": (t(base + "layer_norm.weight"), t(base + "layer_norm.bias")),
            "ff1": conv(base + "feed_forward.conv_1"),
            "ff2": conv(base + "feed_forward.conv_2"),
            "ln2": (t(base + "final_layer_norm.weight"),
                    t(base + "final_layer_norm.bias")),
        })
    p["layers"] = layers

    if cfg.use_stochastic_dp:
        dpp = "duration_predictor"
        p["dp"] = {
            "pre": conv(dpp + ".conv_pre"),
            "proj": conv(dpp + ".conv_proj"),
            "dds": dds(dpp + ".conv_dds", cfg.depth_separable_num_layers),
            "flows": sdp_flows(dpp + ".flows", cfg.dp_num_flows),
        }
    else:
        dpp = "duration_predictor"
        p["dp"] = {
            "conv1": conv(dpp + ".conv_1"),
            "n1": (t(dpp + ".norm_1.weight"), t(dpp + ".norm_1.bias")),
            "conv2": conv(dpp + ".conv_2"),
            "n2": (t(dpp + ".norm_2.weight"), t(dpp + ".norm_2.bias")),
            "proj": conv(dpp + ".proj"),
        }

    p["flow"] = [{
        "pre": conv(f"flow.flows.{i}.conv_pre"),
        "wn": wavenet(f"flow.flows.{i}.wavenet", cfg.prior_wavenet_layers),
        "post": conv(f"flow.flows.{i}.conv_post"),
    } for i in range(cfg.prior_num_flows)]

    dec = {
        "pre": conv("decoder.conv_pre"),
        "up": [conv(f"decoder.upsampler.{i}")
               for i in range(len(cfg.upsample_rates))],
        "post": conv("decoder.conv_post"),
    }
    nk = len(cfg.resblock_kernel_sizes)
    blocks = []
    for i in range(len(cfg.upsample_rates) * nk):
        nd = len(cfg.resblock_dilation_sizes[i % nk])
        blocks.append({
            "c1": [conv(f"decoder.resblocks.{i}.convs1.{j}")
                   for j in range(nd)],
            "c2": [conv(f"decoder.resblocks.{i}.convs2.{j}")
                   for j in range(nd)],
        })
    dec["resblocks"] = blocks
    p["decoder"] = dec
    return jax.tree_util.tree_map(
        lambda a: jnp.asarray(a) if a is not None else None, p,
        is_leaf=lambda x: x is None or isinstance(x, np.ndarray))


# ---------------------------------------------------------------- primitives
# [B, C, T] layout throughout (mirrors the checkpoint's conv orientation)

def _conv1d(x, p, *, stride=1, dilation=1, padding=None, groups=1):
    w = p["w"]
    k = w.shape[-1]
    if padding is None:
        padding = (k * dilation - dilation) // 2
    out = jax.lax.conv_general_dilated(
        x, w, (stride,), [(padding, padding)],
        rhs_dilation=(dilation,), feature_group_count=groups,
        dimension_numbers=("NCH", "OIH", "NCH"))
    if p["b"] is not None:
        out = out + p["b"][None, :, None]
    return out


def _conv_transpose1d(x, p, *, stride, padding):
    # torch ConvTranspose1d(weight [in, out, k]) == dilated conv with the
    # kernel flipped and in/out transposed
    w = jnp.flip(p["w"].transpose(1, 0, 2), -1)     # [out, in, k]
    k = w.shape[-1]
    out = jax.lax.conv_general_dilated(
        x, w, (1,), [(k - 1 - padding, k - 1 - padding)],
        lhs_dilation=(stride,),
        dimension_numbers=("NCH", "OIH", "NCH"))
    if p["b"] is not None:
        out = out + p["b"][None, :, None]
    return out


def _layer_norm_c(x, w, b, eps):
    """LayerNorm over the channel axis of [B, C, T]."""
    xt = x.transpose(0, 2, 1)
    mu = xt.mean(-1, keepdims=True)
    var = ((xt - mu) ** 2).mean(-1, keepdims=True)
    xt = (xt - mu) / jnp.sqrt(var + eps) * w + b
    return xt.transpose(0, 2, 1)


def _dds_forward(x, p, cfg: VitsConfig, mask, cond=None):
    """VitsDilatedDepthSeparableConv (modeling_vits.py role)."""
    if cond is not None:
        x = x + cond
    k = cfg.dp_kernel_size
    ch = x.shape[1]
    for i in range(len(p["dil"])):
        dilation = k ** i
        h = _conv1d(x * mask, p["dil"][i], dilation=dilation, groups=ch)
        h = _layer_norm_c(h, p["n1"][i][0], p["n1"][i][1], cfg.ln_eps)
        h = jax.nn.gelu(h, approximate=False)
        h = _conv1d(h, p["pw"][i])
        h = _layer_norm_c(h, p["n2"][i][0], p["n2"][i][1], cfg.ln_eps)
        h = jax.nn.gelu(h, approximate=False)
        x = x + h
    return x * mask


def _wavenet_forward(x, p, cfg: VitsConfig, mask):
    h_size = cfg.hidden_size
    outputs = jnp.zeros_like(x)
    n = len(p["in"])
    for i in range(n):
        dilation = cfg.wavenet_dilation_rate ** i
        h = _conv1d(x, p["in"][i], dilation=dilation)
        acts = jnp.tanh(h[:, :h_size]) * jax.nn.sigmoid(h[:, h_size:])
        rs = _conv1d(acts, p["rs"][i])
        if i < n - 1:
            x = (x + rs[:, :h_size]) * mask
            outputs = outputs + rs[:, h_size:]
        else:
            outputs = outputs + rs
    return outputs * mask


# ------------------------------------------------------------- text encoder

def _rel_embeddings(rel, length, window):
    pad = max(length - (window + 1), 0)
    if pad > 0:
        rel = jnp.pad(rel, ((0, 0), (pad, pad), (0, 0)))
    start = max((window + 1) - length, 0)
    return rel[:, start:start + 2 * length - 1]


def _rel_to_abs(x):
    """[BH, L, 2L-1] relative scores → [BH, L, L] absolute."""
    bh, length, _ = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (0, 1)))
    x = x.reshape(bh, length * 2 * length)
    x = jnp.pad(x, ((0, 0), (0, length - 1)))
    x = x.reshape(bh, length + 1, 2 * length - 1)
    return x[:, :length, length - 1:]


def _abs_to_rel(x):
    """[BH, L, L] absolute probs → [BH, L, 2L-1] relative."""
    bh, length, _ = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (0, length - 1)))
    x = x.reshape(bh, length * (2 * length - 1))
    x = jnp.pad(x, ((0, 0), (length, 0)))
    return x.reshape(bh, length, 2 * length)[:, :, 1:]


def text_encoder(p, cfg: VitsConfig, ids, mask_t):
    """ids [B, L]; mask_t [B, L] → (hidden [B, H, L], m_p, logs_p [B,L,F])."""
    b, length = ids.shape
    nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    scale = hd ** -0.5
    x = p["embed"][ids] * (cfg.hidden_size ** 0.5)          # [B, L, H]
    pad = mask_t[:, :, None]
    attn_bias = jnp.where(mask_t[:, None, None, :] > 0, 0.0, -3.4e38)
    x = x * pad

    for lp in p["layers"]:
        q = (x @ lp["q"]["w"] + lp["q"]["b"]) * scale
        kk = x @ lp["k"]["w"] + lp["k"]["b"]
        vv = x @ lp["v"]["w"] + lp["v"]["b"]

        def heads(t):
            return t.reshape(b, length, nh, hd).transpose(0, 2, 1, 3).reshape(
                b * nh, length, hd)
        qh, kh, vh = heads(q), heads(kk), heads(vv)
        logits = qh @ kh.transpose(0, 2, 1)                 # [BH, L, L]
        rel_k = _rel_embeddings(lp["rel_k"], length, cfg.window_size)
        logits = logits + _rel_to_abs(qh @ rel_k[0].T[None])
        logits = (logits.reshape(b, nh, length, length) + attn_bias
                  ).reshape(b * nh, length, length)
        probs = jax.nn.softmax(logits, axis=-1)
        out = probs @ vh
        rel_v = _rel_embeddings(lp["rel_v"], length, cfg.window_size)
        out = out + _abs_to_rel(probs) @ rel_v[0][None]
        out = out.reshape(b, nh, length, hd).transpose(0, 2, 1, 3).reshape(
            b, length, cfg.hidden_size)
        out = out @ lp["out"]["w"] + lp["out"]["b"]
        x = _ln(x + out, lp["ln1"], cfg.ln_eps)

        # FFN: conv over time with asymmetric same-padding, masked
        h = (x * pad).transpose(0, 2, 1)                    # [B, H, L]
        kf = cfg.ffn_kernel_size
        pl_, pr = (kf - 1) // 2, kf // 2
        h = jnp.pad(h, ((0, 0), (0, 0), (pl_, pr)))
        h = _conv1d(h, lp["ff1"], padding=0)
        h = jax.nn.relu(h)
        h = h * pad.transpose(0, 2, 1)
        h = jnp.pad(h, ((0, 0), (0, 0), (pl_, pr)))
        h = _conv1d(h, lp["ff2"], padding=0)
        h = (h * pad.transpose(0, 2, 1)).transpose(0, 2, 1)
        x = _ln(x + h, lp["ln2"], cfg.ln_eps)
    x = x * pad

    stats = _conv1d(x.transpose(0, 2, 1), p["project"]).transpose(0, 2, 1)
    stats = stats * pad
    m_p, logs_p = jnp.split(stats, 2, axis=-1)
    return x.transpose(0, 2, 1), m_p, logs_p


def _ln(x, wb, eps):
    w, b = wb
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


# ------------------------------------------------------- spline + SDP flows

def _rq_spline(inputs, uw, uh, ud, *, reverse, tail_bound,
               min_bin_width=1e-3, min_bin_height=1e-3, min_derivative=1e-3):
    """Unconstrained rational-quadratic spline (identity outside tail_bound),
    vectorized with where-selects (no boolean indexing)."""
    num_bins = uw.shape[-1]
    inside = (inputs >= -tail_bound) & (inputs <= tail_bound)
    x = jnp.clip(inputs, -tail_bound, tail_bound)

    constant = np.log(np.exp(1 - min_derivative) - 1)
    ud = jnp.pad(ud, [(0, 0)] * (ud.ndim - 1) + [(1, 1)],
                 constant_values=constant)

    widths = jax.nn.softmax(uw, axis=-1)
    widths = min_bin_width + (1 - min_bin_width * num_bins) * widths
    cumw = jnp.cumsum(widths, -1)
    cumw = jnp.pad(cumw, [(0, 0)] * (cumw.ndim - 1) + [(1, 0)])
    cumw = 2 * tail_bound * cumw - tail_bound
    cumw = cumw.at[..., 0].set(-tail_bound)
    cumw = cumw.at[..., -1].set(tail_bound)
    widths = cumw[..., 1:] - cumw[..., :-1]

    derivs = min_derivative + jax.nn.softplus(ud)

    heights = jax.nn.softmax(uh, axis=-1)
    heights = min_bin_height + (1 - min_bin_height * num_bins) * heights
    cumh = jnp.cumsum(heights, -1)
    cumh = jnp.pad(cumh, [(0, 0)] * (cumh.ndim - 1) + [(1, 0)])
    cumh = 2 * tail_bound * cumh - tail_bound
    cumh = cumh.at[..., 0].set(-tail_bound)
    cumh = cumh.at[..., -1].set(tail_bound)
    heights = cumh[..., 1:] - cumh[..., :-1]

    locations = cumh if reverse else cumw
    locations = locations.at[..., -1].add(1e-6)
    bin_idx = jnp.sum((x[..., None] >= locations).astype(jnp.int32),
                      axis=-1) - 1
    bin_idx = jnp.clip(bin_idx, 0, num_bins - 1)[..., None]

    def pick(arr):
        return jnp.take_along_axis(arr, bin_idx, axis=-1)[..., 0]

    in_cumw = pick(cumw[..., :-1])
    in_w = pick(widths)
    in_cumh = pick(cumh[..., :-1])
    delta = heights / widths
    in_delta = pick(delta)
    in_d = pick(derivs[..., :-1])
    in_d1 = pick(derivs[..., 1:])
    in_h = pick(heights)

    inter1 = in_d + in_d1 - 2 * in_delta
    if not reverse:
        theta = (x - in_cumw) / in_w
        tmt = theta * (1 - theta)
        num = in_h * (in_delta * theta ** 2 + in_d * tmt)
        den = in_delta + inter1 * tmt
        out = in_cumh + num / den
    else:
        inter2 = x - in_cumh
        inter3 = inter2 * inter1
        a = in_h * (in_delta - in_d) + inter3
        bq = in_h * in_d - inter3
        c = -in_delta * inter2
        disc = bq ** 2 - 4 * a * c
        root = (2 * c) / (-bq - jnp.sqrt(jnp.maximum(disc, 0.0)))
        out = root * in_w + in_cumw
    return jnp.where(inside, out, inputs)


def _conv_flow(x, p, cfg: VitsConfig, mask, cond, *, reverse):
    half = cfg.depth_separable_channels // 2
    first, second = x[:, :half], x[:, half:]
    h = _conv1d(first, p["pre"])
    h = _dds_forward(h, p["dds"], cfg, mask, cond)
    h = _conv1d(h, p["proj"]) * mask
    b, ch, length = first.shape
    h = h.reshape(b, ch, -1, length).transpose(0, 1, 3, 2)
    nb = cfg.dp_flow_bins
    scale = cfg.hidden_size ** 0.5
    second = _rq_spline(second, h[..., :nb] / scale,
                        h[..., nb:2 * nb] / scale, h[..., 2 * nb:],
                        reverse=reverse, tail_bound=cfg.dp_tail_bound)
    return jnp.concatenate([first, second], axis=1) * mask


def _elementwise_affine(x, p, mask, *, reverse):
    if not reverse:
        return (p["translate"] + jnp.exp(p["log_scale"]) * x) * mask
    return (x - p["translate"]) * jnp.exp(-p["log_scale"]) * mask


def stochastic_log_duration(p, cfg: VitsConfig, hidden, mask, noise,
                            noise_scale):
    """Inverse SDP: noise [B, 2, L] → log durations [B, 1, L]
    (VitsStochasticDurationPredictor.forward reverse branch)."""
    x = _conv1d(hidden, p["pre"])
    x = _dds_forward(x, p["dds"], cfg, mask)
    x = _conv1d(x, p["proj"]) * mask

    # reversed flow list with the reference's "remove a useless vflow" quirk
    flows = list(reversed(p["flows"]))
    flows = flows[:-2] + [flows[-1]]
    latents = noise * noise_scale
    for fp in flows:
        latents = jnp.flip(latents, 1)
        if "translate" in fp:
            latents = _elementwise_affine(latents, fp, mask, reverse=True)
        else:
            latents = _conv_flow(latents, fp, cfg, mask, x, reverse=True)
    return latents[:, :1]


def plain_log_duration(p, cfg: VitsConfig, hidden, mask):
    x = _conv1d(hidden * mask, p["conv1"])
    x = jax.nn.relu(x)
    x = _layer_norm_c(x, p["n1"][0], p["n1"][1], cfg.ln_eps)
    x = _conv1d(x * mask, p["conv2"])
    x = jax.nn.relu(x)
    x = _layer_norm_c(x, p["n2"][0], p["n2"][1], cfg.ln_eps)
    return _conv1d(x * mask, p["proj"]) * mask


# ----------------------------------------------------------- flow + decoder

def flow_inverse(p, cfg: VitsConfig, z, mask):
    half = cfg.flow_size // 2
    for fp in reversed(p):
        z = jnp.flip(z, 1)
        first, second = z[:, :half], z[:, half:]
        h = _conv1d(first, fp["pre"]) * mask
        h = _wavenet_forward(h, fp["wn"], cfg, mask)
        mean = _conv1d(h, fp["post"]) * mask
        second = (second - mean) * mask
        z = jnp.concatenate([first, second], axis=1)
    return z


def hifigan(p, cfg: VitsConfig, spec):
    x = _conv1d(spec, p["pre"], padding=3)
    nk = len(cfg.resblock_kernel_sizes)
    slope = cfg.leaky_relu_slope
    for i, (rate, k) in enumerate(zip(cfg.upsample_rates,
                                      cfg.upsample_kernel_sizes)):
        x = jax.nn.leaky_relu(x, slope)
        x = _conv_transpose1d(x, p["up"][i], stride=rate,
                              padding=(k - rate) // 2)
        acc = None
        for j in range(nk):
            bp = p["resblocks"][i * nk + j]
            h = x
            for c1, c2, dil in zip(bp["c1"], bp["c2"],
                                   cfg.resblock_dilation_sizes[j]):
                r = h
                h = jax.nn.leaky_relu(h, slope)
                h = _conv1d(h, c1, dilation=dil)
                h = jax.nn.leaky_relu(h, slope)
                h = _conv1d(h, c2)
                h = h + r
            acc = h if acc is None else acc + h
        x = acc / nk
    x = jax.nn.leaky_relu(x)  # default slope 0.01 (the reference's final act)
    x = _conv1d(x, p["post"], padding=3)
    return jnp.tanh(x)


# ---------------------------------------------------------------- inference

def synthesize_ids(p, cfg: VitsConfig, ids: np.ndarray, *,
                   seed: int = 0, noise_scale: float | None = None,
                   noise_scale_duration: float | None = None,
                   speaking_rate: float | None = None) -> np.ndarray:
    """Token ids [L] → waveform float32 [T]. The full VitsModel.forward
    inference path (duration → length-regulate → inverse flow → HiFi-GAN)."""
    ns = cfg.noise_scale if noise_scale is None else noise_scale
    nsd = (cfg.noise_scale_duration if noise_scale_duration is None
           else noise_scale_duration)
    rate = cfg.speaking_rate if speaking_rate is None else speaking_rate
    ids = jnp.asarray(ids, jnp.int32)[None]
    b, length = ids.shape
    mask_t = jnp.ones((b, length), jnp.float32)
    mask = mask_t[:, None, :]                        # [B,1,L]

    hidden, m_p, logs_p = text_encoder(p, cfg, ids, mask_t)

    key = jax.random.PRNGKey(seed)
    kd, kp = jax.random.split(key)
    if cfg.use_stochastic_dp:
        noise = jax.random.normal(kd, (b, 2, length))
        log_dur = stochastic_log_duration(p["dp"], cfg, hidden, mask,
                                          noise, nsd)
    else:
        log_dur = plain_log_duration(p["dp"], cfg, hidden, mask)

    dur = jax.device_get(jnp.ceil(jnp.exp(log_dur) * mask / rate))[0, 0]

    # length regulator: repeat each input index dur[i] times
    reps = dur.astype(np.int64)
    idx = np.repeat(np.arange(length), reps)
    if idx.size == 0:
        idx = np.zeros((1,), np.int64)
    m_exp = np.asarray(m_p)[0][idx]                  # [T, F]
    logs_exp = np.asarray(logs_p)[0][idx]

    z_p = jnp.asarray(m_exp.T)[None]                 # [1, F, T]
    if ns > 0:
        z_p = z_p + jax.random.normal(kp, z_p.shape) * jnp.exp(
            jnp.asarray(logs_exp.T)[None]) * ns
    out_mask = jnp.ones((1, 1, z_p.shape[-1]), jnp.float32)
    latents = flow_inverse(p["flow"], cfg, z_p, out_mask)
    wav = hifigan(p["decoder"], cfg, latents)
    return np.asarray(wav)[0, 0]


# ---------------------------------------------------------------- tokenizer

class VitsCharTokenizer:
    """MMS-TTS character tokenizer: vocab.json chars, lowercase + filter,
    blank (pad) interleaving (VitsTokenizer semantics)."""

    def __init__(self, model_dir: str):
        with open(os.path.join(model_dir, "vocab.json")) as f:
            self.vocab: dict[str, int] = json.load(f)
        tc = {}
        tcp = os.path.join(model_dir, "tokenizer_config.json")
        if os.path.exists(tcp):
            with open(tcp) as f:
                tc = json.load(f)
        self.do_lower = tc.get("do_lower_case", True)
        self.add_blank = tc.get("add_blank", True)
        self.pad_id = self.vocab.get(tc.get("pad_token", "<pad>"),
                                     self.vocab.get(" ", 0))

    def encode(self, text: str) -> np.ndarray:
        if self.do_lower:
            text = text.lower()
        ids = [self.vocab[ch] for ch in text if ch in self.vocab]
        if not ids:
            ids = [self.pad_id]
        if self.add_blank:
            out = [self.pad_id]
            for t in ids:
                out += [t, self.pad_id]
            ids = out
        return np.asarray(ids, np.int64)


class VitsTTS:
    """Loaded VITS voice: text → waveform (the TTS servicer's neural path)."""

    def __init__(self, model_dir: str):
        self.cfg = load_vits_config(model_dir)
        self.params = load_vits_params(model_dir, self.cfg)
        self.tokenizer = VitsCharTokenizer(model_dir)

    @property
    def rate(self) -> int:
        return self.cfg.sampling_rate

    def synthesize(self, text: str, *, seed: int = 0) -> np.ndarray:
        ids = self.tokenizer.encode(text)
        return synthesize_ids(self.params, self.cfg, ids, seed=seed)
