"""Pipeline parallelism — GPipe-style microbatched stages over a 'pipe' mesh axis.

The reference has no pipeline parallelism (SURVEY §2.4 lists it as the one
optional strategy; llama.cpp splits layers across GPUs but runs them
sequentially per token, and vllm's PP is torch-rpc based). The TPU-native
answer is the scaling-book recipe: shard the STACKED layer params
[L, ...] over a 'pipe' mesh axis (each stage holds L/S contiguous layers),
run the stage body under `jax.shard_map`, and rotate activations
stage-to-stage with `lax.ppermute` while microbatches stream in a GPipe
schedule. The whole loop is one `lax.scan` → one compiled program, fully
differentiable (ppermute's transpose is the reverse rotation), so the same
code serves forward and backward — no hand-written 1F1B scheduling, XLA
overlaps the ppermute with the next microbatch's compute.

Composes with data parallelism: tokens sharded on 'data', pipeline on
'pipe' ('model' must be 1 in this entry path — TP happens via GSPMD outside
shard_map and is a separate deployment shape; see parallel/mesh.py).

Schedule (S stages, M microbatches, T = M + S - 1 ticks):

    tick t:   stage s computes microbatch (t - s)   [valid when 0 <= t-s < M]
              then sends its output to stage s+1 via ppermute.

The bubble fraction is (S-1)/(M+S-1) — pick M >= 4*S for >80% utilization.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:                                  # jax >= 0.5 top-level export
    _shard_map = jax.shard_map
except AttributeError:                # 0.4.x spelling
    from jax.experimental.shard_map import shard_map as _shard_map

from localai_tpu.models.llama import (
    LlamaConfig, _attn_impls, _lm_head, _mlp, _qkv, param_specs, rms_norm,
)
from localai_tpu.ops.rope import apply_rope, rope_table


def pipeline_specs(cfg: LlamaConfig):
    """PartitionSpecs for pipeline parallelism: stacked layer params sharded
    on dim 0 (the layer axis) over 'pipe'; everything else replicated.
    Same tree shape as param_specs, so shard_params works unchanged."""
    def _strip(spec):
        return P(*[None if a == "model" else a for a in spec])

    specs = jax.tree_util.tree_map(_strip, param_specs(cfg))
    specs["layers"] = {
        k: P(*(("pipe",) + tuple(v)[1:])) for k, v in specs["layers"].items()
    }
    return specs


def _stage_layers(layers_local, x, cfg: LlamaConfig, cos, sin, positions,
                  lengths, attn):
    """Run this stage's L/S layers over one microbatch [mb, T, D].

    Same math as models/llama.py hidden_states' layer body, minus the
    activation-sharding hints (with_sharding_constraint is illegal inside
    shard_map — the manual axes already fix the layout)."""
    b, s, _ = x.shape

    def layer(x, lp):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(h, lp, cfg)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        a = attn(q, k, v, lengths, sliding_window=cfg.sliding_window)
        from localai_tpu.ops.quant import qmatmul

        x = x + qmatmul(a.reshape(b, s, -1), lp["wo"])
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        x = x + _mlp(h, lp, cfg)
        return x, None

    x, _ = jax.lax.scan(layer, x, layers_local)
    return x


def pipeline_hidden(params, cfg: LlamaConfig, tokens, *, mesh: Mesh,
                    n_micro: int, lengths=None):
    """Full-sequence causal forward → final hidden states [B, T, D], with the
    decoder layers executed as a pipeline over the mesh's 'pipe' axis.

    tokens [B, T] (B sharded on 'data' if present); n_micro microbatches per
    data shard. Output is replicated over 'pipe' (psum-broadcast from the
    last stage) and stays sharded on 'data'."""
    if "pipe" not in mesh.axis_names:
        raise ValueError(f"mesh {mesh.axis_names} has no 'pipe' axis")
    S = mesh.shape["pipe"]
    if mesh.shape.get("model", 1) != 1:
        raise ValueError("pipeline entry path needs model=1 (TP is a "
                         "separate GSPMD deployment shape)")
    L = cfg.num_layers
    if L % S != 0:
        raise ValueError(f"num_layers {L} not divisible by {S} stages")
    B, T = tokens.shape
    dsize = mesh.shape.get("data", 1)
    if B % (dsize * n_micro) != 0:
        raise ValueError(f"batch {B} not divisible by data {dsize} x "
                         f"n_micro {n_micro}")
    cos, sin = rope_table(cfg.rope, T)
    if lengths is None:
        lengths = jnp.full((B,), T, jnp.int32)
    attn, _ = _attn_impls(cfg)
    emb = params["embed"].astype(cfg.jdtype)[tokens]          # [B, T, D]
    D = emb.shape[-1]
    positions = jnp.arange(T)[None, :]

    lspec = {k: P(*(("pipe",) + (None,) * (v.ndim - 1)))
             for k, v in params["layers"].items()}

    def body(layers_local, emb_local, len_local):
        stage = jax.lax.axis_index("pipe")
        mb = emb_local.shape[0] // n_micro
        mbs = emb_local.reshape(n_micro, mb, T, D)
        mlens = len_local.reshape(n_micro, mb)
        pos = jnp.broadcast_to(positions, (mb, T))
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            recv, out = carry
            feed = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            x = jnp.where(stage == 0, feed, recv)
            lens = jax.lax.dynamic_index_in_dim(
                mlens, jnp.clip(t - stage, 0, n_micro - 1), 0, keepdims=False)
            y = _stage_layers(layers_local, x, cfg, cos, sin, pos, lens, attn)
            widx = t - (S - 1)
            updated = jax.lax.dynamic_update_index_in_dim(
                out, y, jnp.clip(widx, 0, n_micro - 1), 0)
            out = jnp.where((stage == S - 1) & (widx >= 0), updated, out)
            recv = jax.lax.ppermute(y, "pipe", perm)
            return (recv, out), None

        # the carry is stage-varying (and data-varying): mark the zeros init
        # accordingly or jax 0.9's vma check rejects the scan (0.4.x has no
        # varying-axes tracking — pcast is absent and unnecessary there)
        init = (jnp.zeros((mb, T, D), emb_local.dtype),
                jnp.zeros((n_micro, mb, T, D), emb_local.dtype))
        if hasattr(jax.lax, "pcast"):
            init = jax.lax.pcast(init, ("data", "pipe"), to="varying")
        (_, out), _ = jax.lax.scan(tick, init, jnp.arange(n_micro + S - 1))
        # broadcast the last stage's collected outputs to every pipe rank
        out = jax.lax.psum(
            jnp.where(stage == S - 1, out, jnp.zeros_like(out)), "pipe")
        return out.reshape(-1, T, D)

    dax = "data" if "data" in mesh.axis_names else None
    x = _shard_map(
        body, mesh=mesh,
        in_specs=(lspec, P(dax, None, None), P(dax)),
        out_specs=P(dax, None, None),
    )(params["layers"], emb, lengths)
    return rms_norm(x, params["final_norm"], cfg.rms_eps)


def pipeline_forward_train(params, cfg: LlamaConfig, tokens, *, mesh: Mesh,
                           n_micro: int):
    """forward_train twin on the pipeline path → logits [B, T, V] f32."""
    x = pipeline_hidden(params, cfg, tokens, mesh=mesh, n_micro=n_micro)
    return _lm_head(x.astype(jnp.float32), params)


def pipeline_loss(params, cfg: LlamaConfig, tokens, *, mesh: Mesh,
                  n_micro: int):
    """Next-token cross-entropy, numerically matching train.causal_lm_loss."""
    logits = pipeline_forward_train(params, cfg, tokens[:, :-1], mesh=mesh,
                                    n_micro=n_micro)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def make_pipeline_train_step(cfg: LlamaConfig, optimizer, mesh: Mesh,
                             n_micro: int):
    """train_step(params, opt_state, tokens) -> (params, opt_state, loss)
    with the forward+backward pipelined over 'pipe'. jit under the mesh with
    params sharded per pipeline_specs."""
    loss_fn = partial(pipeline_loss, mesh=mesh, n_micro=n_micro)

    def train_step(params, opt_state, tokens):
        import optax

        loss, grads = jax.value_and_grad(loss_fn)(params, cfg, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step
