"""Device-mesh management — the TPU answer to the reference's parallelism menu.

The reference scales by NCCL tensor-parallel (vllm/backend.py:106-107), ggml
tensor_split (backend.proto:189) and cross-host ggml-RPC workers
(grpc-server.cpp:256-278). Here all of that is ONE mechanism: a
`jax.sharding.Mesh` over ('data','model') [+ optional 'seq' for ring
attention], PartitionSpecs on params/activations, and XLA-inserted collectives
riding ICI (intra-slice) / DCN (inter-slice via jax.distributed).

`constrain` is the activation-sharding hint used inside model code. It is a
no-op when no mesh has been activated (single-chip / plain CPU tests) and a
HARD sharding constraint when one has — a wrong spec under a mesh raises
instead of degrading to a silent no-op.
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_state = threading.local()


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Mesh shape knobs (YAML `tensor_parallel` etc. map here).

    data × model × seq × pipe must equal the device count; axes of size 1 are
    fine. seq > 1 adds a 'seq' axis for ring-attention sequence parallelism
    (parallel/ring_attention.py) — long-prompt prefill shards the sequence
    over it. pipe > 1 adds a 'pipe' axis for GPipe-style pipeline
    parallelism (parallel/pipeline.py) — stacked layer params shard over it.
    """
    data: int = 1
    model: int = 1
    seq: int = 1
    pipe: int = 1

    def axis_sizes(self) -> tuple[int, int]:
        return self.data, self.model


def build_mesh(cfg: MeshConfig | None = None, devices=None) -> Mesh:
    """Build a ('data','model'[,'seq']) mesh. Defaults to all devices on the
    model axis (tensor parallelism), the common single-host serving layout.
    The 'seq' axis only exists when seq > 1, so existing 2-axis PartitionSpecs
    stay valid."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if cfg is None:
        cfg = MeshConfig(data=1, model=n)
    d, m = cfg.axis_sizes()
    s = getattr(cfg, "seq", 1) or 1
    p = getattr(cfg, "pipe", 1) or 1
    if d * m * s * p != n:
        raise ValueError(f"mesh {d}x{m}" + (f"x{s}" if s > 1 else "")
                         + (f"x{p}" if p > 1 else "") + f" != {n} devices")
    sizes, names = [d, m], ["data", "model"]
    if s > 1:
        sizes.append(s)
        names.append("seq")
    if p > 1:
        sizes.append(p)
        names.append("pipe")
    return Mesh(np.array(devices).reshape(*sizes), tuple(names))


def seq_axis_size(mesh: Mesh | None) -> int:
    """Size of the ring-attention 'seq' axis (1 when absent/no mesh)."""
    if mesh is None or "seq" not in mesh.axis_names:
        return 1
    return mesh.shape["seq"]


def current_mesh() -> Mesh | None:
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def activate_mesh(mesh: Mesh | None):
    """Make `mesh` the ambient mesh for `constrain` within the block."""
    prev = current_mesh()
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev


def constrain(x, spec: PartitionSpec):
    """Apply a sharding constraint iff a mesh is active. NOTE: the ambient mesh
    is captured at TRACE time — jit the model functions inside `activate_mesh`."""
    mesh = current_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def mesh_shape(mesh: Mesh | None) -> dict[str, int] | None:
    """Mesh axes as a plain {'data': d, 'model': m, ...} dict (None without a
    mesh) — the serializable shape telemetry/bench artifacts record."""
    if mesh is None:
        return None
    return {name: int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)}


def validate_specs(params, specs) -> None:
    """Every param leaf must carry a PartitionSpec of the leaf's rank (or the
    empty P(), explicit full replication). A missing leaf or wrong-rank spec
    raises naming the offender — under GSPMD a short spec would otherwise
    silently replicate the trailing axes, which for a TP'd weight means a
    full copy per chip and no error anywhere."""
    def check(path, p, s):
        name = jax.tree_util.keystr(path)
        if not isinstance(s, PartitionSpec):
            raise ValueError(
                f"param {name}: spec is {type(s).__name__}, not a "
                f"PartitionSpec")
        ndim = getattr(p, "ndim", np.ndim(p))
        if len(s) not in (0, ndim):
            raise ValueError(
                f"param {name} has rank {ndim} but spec {s} has rank "
                f"{len(s)} — a wrong-rank spec would silently replicate")

    try:
        jax.tree_util.tree_map_with_path(check, params, specs)
    except (KeyError, TypeError) as e:
        # tree-structure mismatch (missing/extra spec leaf)
        raise ValueError(f"param/spec tree mismatch: {e}") from e


def shard_params(params, specs, mesh: Mesh):
    """device_put every leaf with its PartitionSpec → sharded jax.Arrays.
    Validates spec coverage/rank first (see validate_specs)."""
    validate_specs(params, specs)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )


def safe_sharding(mesh: Mesh, spec: PartitionSpec, shape) -> NamedSharding:
    """NamedSharding for `spec` with any axis whose mesh size does not divide
    the corresponding dim dropped to replicated — the pre-placement helper
    for serving state (KV caches/pools), where an odd slot or head count
    should degrade to replication, not refuse to serve. Params go through
    shard_params, which refuses instead."""
    axes = []
    entries = tuple(spec) + (None,) * (len(shape) - len(spec))
    for dim, ax in zip(shape, entries):
        if ax is None:
            axes.append(None)
            continue
        names = ax if isinstance(ax, (tuple, list)) else (ax,)
        size = 1
        for n in names:
            size *= mesh.shape[n]
        axes.append(ax if size and dim % size == 0 else None)
    return NamedSharding(mesh, PartitionSpec(*axes))
