from localai_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    activate_mesh,
    build_mesh,
    constrain,
    current_mesh,
    mesh_shape,
    safe_sharding,
    shard_params,
    validate_specs,
)
