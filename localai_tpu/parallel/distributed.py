"""Multi-host serving: jax.distributed bring-up + the lockstep follower
protocol that lets ONE engine host loop drive a model sharded across hosts.

Reference parity: the llama.cpp RPC worker path — a master registers remote
device workers and streams tensor work to them
(/root/reference/backend/cpp/llama-cpp/grpc-server.cpp:256-278, worker CLI
/root/reference/core/cli/worker/worker_llamacpp.go:66-92). The TPU-native
answer is multi-controller SPMD: every process runs the SAME jitted
computations on its local shard of a global mesh and XLA's collectives ride
ICI/DCN. What llama.cpp ships as tensors over TCP, we ship as a few hundred
BYTES of host args per step (token ids, slot indices, masks) — the device
data never leaves the chips.

Mechanics: rank 0 runs the real Engine (admission, sampling bookkeeping,
streams). Every device dispatch is prefixed by a broadcast of (op, host args)
over a TCP side channel; follower ranks replay the identical call sequence
into their own engine state, which holds the locally-addressable shards of
the same global arrays. Host args are bit-identical → traces are identical →
SPMD stays in lockstep.
"""
from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import socket
import struct
import threading

_LEN = struct.Struct(">I")


def _token_digest(token: str | None) -> bytes:
    """32-byte handshake proof. LOCALAI_REPLICATE_TOKEN overrides the default
    (the coordinator address) for deployments that want a real shared secret."""
    secret = os.environ.get("LOCALAI_REPLICATE_TOKEN") or token or "localai"
    return hashlib.sha256(secret.encode()).digest()


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> int:
    """jax.distributed.initialize from args or LOCALAI_* env vars. Returns
    this process's rank. No-op (rank 0) when unconfigured."""
    import jax

    coordinator = coordinator or os.environ.get("LOCALAI_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("LOCALAI_NUM_PROCESSES", "0") or 0)
    if process_id is None:
        pid = os.environ.get("LOCALAI_PROCESS_ID")
        process_id = int(pid) if pid is not None else None
    if not coordinator or not num_processes or num_processes <= 1:
        return 0
    try:
        # multiprocess CPU meshes need a cross-host collectives backend —
        # without this, sharded device_put and any cross-process psum fail
        # with "Multiprocess computations aren't implemented on the CPU
        # backend". Must be set before the CPU client is created; a no-op
        # for TPU backends.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass   # older jaxlibs without gloo keep the previous behavior
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return jax.process_index()


def _send_msg(sock: socket.socket, payload: bytes):
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> bytes:
    hdr = b""
    while len(hdr) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(hdr))
        if not chunk:
            raise ConnectionError("follower channel closed")
        hdr += chunk
    (n,) = _LEN.unpack(hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            raise ConnectionError("follower channel closed mid-message")
        buf += chunk
    return bytes(buf)


class Replicator:
    """Rank-0 side: accepts `num_followers` connections, then broadcast()
    ships each (op, kwargs) to every follower before the local dispatch.

    A connection only counts as a follower after it presents the shared-token
    digest — a stray connection can neither occupy a follower slot nor
    receive the dispatch stream."""

    def __init__(self, port: int, num_followers: int, host: str = "0.0.0.0",
                 accept_timeout: float = 300.0, token: str | None = None):
        self.num_followers = num_followers
        self._expect = _token_digest(token)
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(accept_timeout)
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()

    @property
    def port(self) -> int:
        return self._srv.getsockname()[1]

    def wait_for_followers(self):
        while len(self._conns) < self.num_followers:
            conn, peer = self._srv.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                conn.settimeout(10.0)
                proof = _recv_msg(conn)
                conn.settimeout(None)
            except (ConnectionError, OSError):
                conn.close()
                continue
            if not hmac.compare_digest(proof, self._expect):
                import logging

                logging.getLogger("localai_tpu").warning(
                    "replicator: rejected connection from %s (bad token)",
                    peer)
                conn.close()
                continue
            self._conns.append(conn)

    def broadcast(self, op: str, kwargs: dict):
        payload = pickle.dumps((op, kwargs), protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            for c in self._conns:
                # lockdep: allow(lock-blocking) — sendall under the lock is
                # the broadcast ordering guarantee: every follower sees ops
                # in one global order; the leaf lock acquires nothing else
                _send_msg(c, payload)

    def close(self):
        try:
            self.broadcast("stop", {})
        except OSError:
            pass
        for c in self._conns:
            c.close()
        self._srv.close()


class Follower:
    """Rank>0 side: connect to rank 0's Replicator and iterate messages."""

    def __init__(self, addr: str, connect_timeout: float = 300.0,
                 token: str | None = None):
        host, _, port = addr.rpartition(":")
        self._sock = socket.create_connection((host or "127.0.0.1",
                                               int(port)),
                                              timeout=connect_timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _send_msg(self._sock, _token_digest(token))

    def recv(self) -> tuple[str, dict]:
        return pickle.loads(_recv_msg(self._sock))

    def close(self):
        self._sock.close()
