"""Ring attention: sequence-parallel causal attention over a mesh axis.

The reference has NO sequence/context parallelism (SURVEY §2.4 — long context
is handled per-device with RoPE scaling + context shift); this is the
framework's beyond-parity capability: contexts larger than one chip's HBM are
sharded over the `seq` mesh axis, and K/V chunks rotate around the ring via
`ppermute` (ICI neighbor exchange) while each device accumulates its local
queries' online-softmax state — compute and communication fully overlapped by
XLA, memory per chip O(S/n).

Layout: q/k/v sharded on the sequence axis [B, S/n, H, D]; output identical
sharding. Works on any mesh axis name; tested on the virtual CPU mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:                                  # jax >= 0.5 top-level export
    from jax import shard_map
except ImportError:                   # 0.4.x spelling
    from jax.experimental.shard_map import shard_map

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _local_block(q, k, v, lengths, q_pos, k_pos, scale, sliding_window,
                 m, l, acc):
    """Online-softmax accumulation of one K/V chunk into (m, l, acc)."""
    b, sq, kvh, g, d = q.shape
    logits = jnp.einsum("bskgd,btkd->bkgst", q, k).astype(jnp.float32) * scale
    mask = (k_pos[None, :] <= q_pos[:, None])[None]          # [1,Sq,Sk] causal
    mask = mask & (k_pos[None, None, :] < lengths[:, None, None])
    if sliding_window is not None and sliding_window > 0:
        mask = mask & ((q_pos[:, None] - k_pos[None, :])
                       < sliding_window)[None]
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)

    m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
    p = jnp.exp(logits - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bkgst,btkd->bkgsd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def _ring_attn_shard(q, k, v, lengths, *, axis_name, scale, sliding_window):
    """Per-device body under shard_map. q/k/v: local [B, Sl, H|KVH, D]."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sl, h, d = q.shape
    kvh = k.shape[2]
    qg = q.reshape(b, sl, kvh, h // kvh, d)

    q_pos = idx * sl + jnp.arange(sl)
    m = jnp.full((b, kvh, h // kvh, sl), NEG_INF, jnp.float32)
    l = jnp.zeros((b, kvh, h // kvh, sl), jnp.float32)
    acc = jnp.zeros((b, kvh, h // kvh, sl, d), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = k, v
    for t in range(n):  # static unroll: n is the mesh axis size
        src = (idx - t) % n                      # owner of the current chunk
        k_pos = src * sl + jnp.arange(sl)
        m, l, acc = _local_block(qg, k_cur, v_cur, lengths, q_pos, k_pos,
                                 scale, sliding_window, m, l, acc)
        if t != n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    out = acc / jnp.maximum(l, 1e-30)[..., None]             # [B,KVH,G,Sl,D]
    out = jnp.moveaxis(out, 3, 1).reshape(b, sl, h, d)
    return out.astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "sliding_window"))
def ring_prefill(q, k, v, lengths, mesh: Mesh, axis: str = "seq",
                 sliding_window: int | None = None):
    """Sequence-parallel causal GQA attention.

    q: [B, S, H, D]; k/v: [B, S, KVH, D]; lengths: [B]. S must divide by the
    `axis` mesh size. Returns [B, S, H, D] sharded like q. On a combined
    serving mesh ('data','model','seq') the batch/head axes keep their TP/DP
    sharding — the ring runs over `axis` only, with data/model as ordinary
    shard_map axes (the per-device body sees local B/H/KVH sizes).
    """
    d = q.shape[-1]
    scale = d ** -0.5
    data_ax = "data" if "data" in mesh.axis_names else None
    model_ax = "model" if "model" in mesh.axis_names else None
    qkv_spec = P(data_ax, axis, model_ax, None)
    fn = shard_map(
        functools.partial(_ring_attn_shard, axis_name=axis, scale=scale,
                          sliding_window=sliding_window),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, P(data_ax)),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, lengths)


def build_seq_mesh(n: int | None = None, devices=None) -> Mesh:
    """1-D ('seq',) mesh for sequence parallelism."""
    import numpy as np

    devices = list(devices if devices is not None else jax.devices())
    n = n or len(devices)
    return Mesh(np.array(devices[:n]), ("seq",))
