"""CLI entrypoint — the `local-ai` role (reference: core/cli/cli.go:8-21).

Subcommands mirror the reference surface: `run` (serve HTTP), `backend` (run
one gRPC backend process), `models` (list/install), `version`; invoking with
no subcommand prints help. Implemented with argparse; flags use the same names
as the reference's kong flags (core/cli/run.go:24-77) where they map 1:1.
"""
from __future__ import annotations

import argparse
import sys


def _add_run(sub):
    p = sub.add_parser("run", help="start the OpenAI-compatible HTTP server")
    p.add_argument("models", nargs="*", help="model names/URIs to preload")
    p.add_argument("--address", default="127.0.0.1:8080", help="bind address")
    p.add_argument("--models-path", default="models", help="model YAML/weights dir")
    p.add_argument("--context-size", type=int, default=None)
    p.add_argument("--threads", type=int, default=None)
    p.add_argument("--api-keys", nargs="*", default=None)
    p.add_argument("--cors", action="store_true")
    p.add_argument("--watchdog-idle-timeout", default=None)
    p.add_argument("--watchdog-busy-timeout", default=None)
    p.add_argument("--single-active-backend", action="store_true")
    p.add_argument("--parallel-requests", type=int, default=8)
    p.add_argument("--tensor-parallel", type=int, default=None,
                   help="shard each model over N chips (Megatron-style TP "
                        "on the 'model' mesh axis; int8 weights shard too). "
                        "A per-model YAML `mesh:` block overrides this; "
                        "default: auto-TP over every divisible device")
    p.add_argument("--backends-path", default=None,
                   help="installed external backends dir")
    p.add_argument("--backend-galleries", default=None,
                   help="comma-separated backend registry index URIs")
    p.add_argument("--galleries", default=None,
                   help="comma-separated gallery index YAMLs (path or URL)")
    p.add_argument("--env-file", default=None,
                   help=".env file to load (default: ./.env, ./.env.local)")
    p.add_argument("--disable-config-watcher", action="store_true",
                   help="do not hot-reload model YAMLs on change")
    # resilience knobs (ISSUE 4) — AppConfig fields, env LOCALAI_<NAME>
    p.add_argument("--request-timeout", type=float, default=None,
                   help="per-request deadline budget in seconds; propagated "
                        "through gRPC into the engine so expired slots are "
                        "evicted (default 600)")
    p.add_argument("--retry-budget", type=int, default=None,
                   help="transparent retries against a respawned backend "
                        "when a request fails before any bytes streamed "
                        "(default 1)")
    p.add_argument("--breaker-threshold", type=int, default=None,
                   help="consecutive backend failures before the circuit "
                        "breaker opens and loads fail fast (default 3)")
    p.add_argument("--breaker-cooldown", type=float, default=None,
                   help="seconds a tripped breaker stays open before a "
                        "half-open probe (default 15)")
    p.add_argument("--queue-depth", type=int, default=None,
                   help="per-model bounded wait queue beyond the in-flight "
                        "limit; excess requests get 429 + Retry-After "
                        "(default 8)")
    p.add_argument("--drain-timeout", type=float, default=None,
                   help="graceful-shutdown hard deadline: SIGTERM and "
                        "/backend/shutdown let in-flight requests finish "
                        "this long while new work gets 503 (default 30)")
    p.add_argument("--preempt-grace", type=float, default=None,
                   help="preemption spill-drain grace in seconds: on a "
                        "preemption notice (backend SIGTERM or "
                        "/backend/preempt) live slots run this long before "
                        "being frozen into resume checkpoints (default 0)")
    # KV lifecycle tier (engine/kvtier.py) — app-wide default; a per-model
    # YAML kv_policy wins
    p.add_argument("--kv-window", type=int, default=None,
                   help="retain only the last N tokens of KV per request "
                        "(attention-sink + sliding-window tier for 32k-128k "
                        "serving); 0/unset = full KV")
    p.add_argument("--kv-sinks", type=int, default=None,
                   help="keep the first N tokens (attention sinks) resident "
                        "alongside --kv-window")
    p.add_argument("--kv-host-bytes", type=int, default=None,
                   help="host-RAM KV spill tier budget in bytes (engine/"
                        "kvhost.py): device blocks evicted by slot reclaim "
                        "or the KV lifecycle tier are kept in host RAM "
                        "(int8 sub-channel) and re-admitted on prefix-cache "
                        "hits instead of re-prefilling; 0/unset disables. "
                        "Per-model YAML kv_host_bytes wins")
    p.add_argument("--trace", action="store_true",
                   help="record request/engine spans (LOCALAI_TRACE=1); "
                        "export via /debug/trace or `util trace`")
    p.add_argument("--profile", action="store_true",
                   help="fenced device-step stage timing (LOCALAI_PROFILE=1;"
                        " measurement mode — serializes the decode pipeline)")
    p.add_argument("--log-level", default="info")
    return p


def _add_backend(sub):
    p = sub.add_parser("backend", help="run a single gRPC backend process")
    p.add_argument("--addr", default="127.0.0.1:50051")
    p.add_argument("--backend", default="jax-tpu")
    return p


def _add_federated(sub):
    p = sub.add_parser("federated",
                       help="run a federated load balancer over workers")
    p.add_argument("--address", default="127.0.0.1:9090")
    p.add_argument("--token", default="",
                   help="shared federation token (HMAC-signed requests; "
                        "default $LOCALAI_FEDERATION_TOKEN)")
    p.add_argument("--workers", default="",
                   help="comma-separated worker base URLs")
    p.add_argument("--strategy", default="least_used",
                   choices=["least_used", "random", "round_robin"])
    return p


def _add_tts(sub):
    p = sub.add_parser("tts", help="synthesize speech to a WAV file "
                                   "(reference core/cli/tts.go)")
    p.add_argument("text", help="text to speak")
    p.add_argument("--model", default="default-tts")
    p.add_argument("--voice", default="")
    p.add_argument("--language", default="")
    p.add_argument("--output-file", default="output.wav")
    p.add_argument("--models-path", default="models")
    return p


def _add_soundgeneration(sub):
    p = sub.add_parser("soundgeneration",
                       help="generate audio from a text description "
                            "(reference core/cli/soundgeneration.go)")
    p.add_argument("text", help="description of the sound to generate")
    p.add_argument("--model", default="default-tts")
    p.add_argument("--duration", type=float, default=2.0,
                   help="clip length in seconds")
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--output-file", default="output.wav")
    p.add_argument("--models-path", default="models")
    return p


def cli_soundgeneration(args) -> int:
    manager, handle = _one_shot_handle(args.model, args.models_path, "tts")
    try:
        import os

        dst = os.path.abspath(args.output_file)
        r = handle.client.sound_generation(
            text=args.text, duration=args.duration,
            temperature=args.temperature, dst=dst)
        if not r.success:
            print(f"sound generation failed: {r.message}")
            return 1
        print(dst)
        return 0
    finally:
        manager.stop_all()


def _add_transcript(sub):
    p = sub.add_parser("transcript",
                       help="transcribe an audio file "
                            "(reference core/cli/transcript.go)")
    p.add_argument("filename", help="audio file (16kHz WAV)")
    p.add_argument("--model", default="default-whisper")
    p.add_argument("--language", default="")
    p.add_argument("--translate", action="store_true")
    p.add_argument("--output-format", default="text",
                   choices=["text", "json", "srt"])
    p.add_argument("--models-path", default="models")
    return p


def _one_shot_handle(model: str, models_path: str, default_backend: str):
    """Spawn the backend for a one-shot CLI inference command."""
    from localai_tpu.config import AppConfig, ModelConfig, ModelConfigLoader
    from localai_tpu.core.manager import ModelManager

    import dataclasses

    app = AppConfig(models_path=models_path)
    cfg = ModelConfigLoader(models_path).get(model) if model else None
    if cfg is None:
        cfg = ModelConfig(name=model, backend=default_backend)
    elif not cfg.config_file and cfg.backend == "llm":
        # bare checkpoint dir auto-registered with the generic default —
        # this one-shot command knows the right backend role
        cfg = dataclasses.replace(cfg, backend=default_backend)
    manager = ModelManager(app)
    return manager, manager.load(cfg)


def cli_tts(args) -> int:
    manager, handle = _one_shot_handle(args.model, args.models_path, "tts")
    try:
        import os

        dst = os.path.abspath(args.output_file)
        r = handle.client.tts(text=args.text, voice=args.voice, dst=dst,
                              language=args.language)
        if not r.success:
            print(f"tts failed: {r.message}")
            return 1
        print(dst)
        return 0
    finally:
        manager.stop_all()


def cli_transcript(args) -> int:
    import json as _json
    import os

    manager, handle = _one_shot_handle(args.model, args.models_path,
                                       "whisper")
    try:
        r = handle.client.transcribe(dst=os.path.abspath(args.filename),
                                     language=args.language,
                                     translate=args.translate)
        if args.output_format == "json":
            print(_json.dumps({"text": r.text, "segments": [
                {"id": s.id, "start": s.start / 1e9, "end": s.end / 1e9,
                 "text": s.text} for s in r.segments]}))
        elif args.output_format == "srt":
            def ts(ns):
                s, ms = divmod(int(ns // 1e6), 1000)
                h, rem = divmod(s, 3600)
                m, s = divmod(rem, 60)
                return f"{h:02}:{m:02}:{s:02},{ms:03}"

            for i, seg in enumerate(r.segments, 1):
                print(f"{i}\n{ts(seg.start)} --> {ts(seg.end)}\n{seg.text}\n")
        else:
            print(r.text)
        return 0
    finally:
        manager.stop_all()


def _add_worker(sub):
    p = sub.add_parser(
        "worker",
        help="join a multi-host serving job (reference: worker_llamacpp.go)")
    p.add_argument("--coordinator", default=None,
                   help="jax.distributed coordinator host:port (rank 0's host)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--model", required=True, help="model directory (all ranks)")
    p.add_argument("--dtype", default=None)
    p.add_argument("--context-size", type=int, default=None)
    p.add_argument("--parallel", type=int, default=4)
    p.add_argument("--mesh-data", type=int, default=None)
    p.add_argument("--mesh-model", type=int, default=None)
    p.add_argument("--replicate-port", type=int, default=39219,
                   help="rank 0's dispatch-broadcast port")
    p.add_argument("--addr", default="127.0.0.1:50051",
                   help="rank 0's gRPC backend bind address")
    return p


def _add_util(sub):
    p = sub.add_parser("util",
                       help="model utilities (reference: core/cli util cmd)")
    p.add_argument("action", choices=["hf-info", "fits", "trace",
                                      "flightrec", "sched"],
                   help="hf-info: checkpoint geometry + params; "
                            "fits: HBM fit estimate; "
                            "trace: pull a Chrome-trace + stage profile "
                            "from a running server's /debug endpoints; "
                            "flightrec: dump the server's flight recorder "
                            "(recent request timelines + SLO percentiles); "
                            "sched: scheduler X-ray (reason-code counters, "
                            "pack composition, per-variant rooflines)")
    p.add_argument("model", help="checkpoint directory (hf-info/fits) or "
                                 "server address (trace/flightrec/sched)")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--context", type=int, default=2048)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--cache-type", default="")
    p.add_argument("--hbm-gb", type=float, default=None)
    p.add_argument("--out", default="",
                   help="trace: output Chrome-trace file "
                        "(default trace.json); "
                        "flightrec: output dump file (default stdout)")
    p.add_argument("--api-key", default="",
                   help="trace: bearer token for a key-protected server")
    return p


def cli_util_trace(args) -> int:
    """`local-ai util trace <addr>` — fetch /debug/trace into a Chrome-trace
    file (open at chrome://tracing) and print the /debug/profile stage
    breakdown. The server must run with --trace (and --profile for stages)."""
    import json as _json
    import urllib.request

    base = args.model if args.model.startswith("http") \
        else f"http://{args.model}"

    def fetch(path):
        req = urllib.request.Request(base + path)
        if args.api_key:
            req.add_header("Authorization", f"Bearer {args.api_key}")
        with urllib.request.urlopen(req, timeout=30) as r:
            return _json.loads(r.read().decode())

    trace = fetch("/debug/trace")
    out = args.out or "trace.json"
    with open(out, "w") as fh:
        _json.dump(trace, fh)
    n = len(trace.get("traceEvents", []))
    print(f"{out}: {n} events")
    profile = fetch("/debug/profile")
    for model, prof in (profile.get("models") or {}).items():
        stages = (prof or {}).get("stages") or {}
        if not stages:
            continue
        print(f"\n{model}: coverage {prof.get('coverage', 0):.0%} of "
              f"{prof.get('wall_ms', 0):.0f} ms busy window")
        width = max(len(s) for s in stages)
        for name, st in sorted(stages.items(),
                               key=lambda kv: -kv[1]["total_ms"]):
            mfu = f" mfu {st['mfu']:.1%}" if st.get("mfu") else ""
            print(f"  {name:<{width}}  {st['share']:>5.1%}  "
                  f"{st['total_ms']:>9.1f} ms  x{st['count']:<6d} "
                  f"p50 {st['p50_ms']:.2f} ms  "
                  f"{st['tok_s']:.0f} tok/s{mfu}")
    if not any((p or {}).get("stages")
               for p in (profile.get("models") or {}).values()):
        print("no stage profile (run the server with --profile / "
              "LOCALAI_PROFILE=1)")
    return 0


def cli_util_flightrec(args) -> int:
    """`local-ai util flightrec <addr>` — pull /debug/flightrec +
    /debug/slo from a running server: recent request timelines, engine
    ticks, tripwire/breaker/supervision events, and the current latency
    percentiles. JSON goes to --out (or stdout); a summary to stderr."""
    import json as _json
    import sys as _sys
    import urllib.request

    base = args.model if args.model.startswith("http") \
        else f"http://{args.model}"

    def fetch(path):
        req = urllib.request.Request(base + path)
        if args.api_key:
            req.add_header("Authorization", f"Bearer {args.api_key}")
        with urllib.request.urlopen(req, timeout=30) as r:
            return _json.loads(r.read().decode())

    dump = fetch("/debug/flightrec")
    slo = fetch("/debug/slo")
    payload = {"flightrec": dump, "slo": slo}
    if args.out:
        with open(args.out, "w") as fh:
            _json.dump(payload, fh, indent=1)
        print(f"wrote {args.out}")
    else:
        print(_json.dumps(payload, indent=1))
    for model, rec in (dump.get("models") or {}).items():
        reqs = (rec or {}).get("requests") or []
        events = (rec or {}).get("events") or []
        print(f"{model}: {len(reqs)} recent requests, "
              f"{len(events)} events in the ring", file=_sys.stderr)
    for model, snap in (slo.get("models") or {}).items():
        e2e = (snap or {}).get("e2e") or {}
        if e2e.get("count"):
            print(f"{model}: e2e p50 {e2e.get('p50_ms', 0):.0f} ms  "
                  f"p95 {e2e.get('p95_ms', 0):.0f} ms  "
                  f"p99 {e2e.get('p99_ms', 0):.0f} ms  "
                  f"({e2e['count']} requests)", file=_sys.stderr)
    return 0


def cli_util_sched(args) -> int:
    """`local-ai util sched <addr>` — pull /debug/sched from a running
    server and print the scheduler X-ray: reason-code counters grouped by
    category, pack-composition totals (budget utilization, pad-row
    fraction), per-variant dispatch counts with their cost-analysis
    rooflines, and the most recent ticks. Raw JSON to --out when given."""
    import json as _json
    import sys as _sys
    import urllib.request

    base = args.model if args.model.startswith("http") \
        else f"http://{args.model}"

    req = urllib.request.Request(base + "/debug/sched")
    if args.api_key:
        req.add_header("Authorization", f"Bearer {args.api_key}")
    with urllib.request.urlopen(req, timeout=30) as r:
        payload = _json.loads(r.read().decode())
    if args.out:
        with open(args.out, "w") as fh:
            _json.dump(payload, fh, indent=1)
        print(f"wrote {args.out}")
    registry = payload.get("reason_codes") or {}
    saw_any = False
    for model, snap in (payload.get("models") or {}).items():
        if not snap:
            continue
        saw_any = True
        print(f"{model}: {snap.get('ticks_total', 0)} ticks, "
              f"{snap.get('dispatches_total', 0)} dispatches")
        util = snap.get("budget_utilization")
        if util is not None:
            print(f"  budget utilization {util:.1%}  "
                  f"pad rows {snap.get('pad_rows_frac', 0):.1%}")
        reasons = snap.get("reason_counters") or {}
        if reasons:
            width = max(len(c) for c in reasons)
            print("  reason codes:")
            for code, n in sorted(reasons.items(), key=lambda kv: -kv[1]):
                cat = (registry.get(code) or {}).get("category", "?")
                print(f"    {code:<{width}}  x{n:<8d} [{cat}]")
        variants = snap.get("variants") or {}
        roofs = snap.get("rooflines") or {}
        if variants:
            width = max(len(v) for v in variants)
            print("  variants:")
            for name, n in sorted(variants.items(), key=lambda kv: -kv[1]):
                roof = roofs.get(name) or {}
                extra = ""
                if roof:
                    extra = (f"  {roof.get('cost_flops', 0):.3g} flops  "
                             f"{roof.get('cost_bytes', 0):.3g} B  "
                             f"{roof.get('bound', '?')}-bound  "
                             f"mfu≤{roof.get('mfu', 0):.1%}")
                print(f"    {name:<{width}}  x{n:<8d}{extra}")
        kvh = snap.get("kv_host") or {}
        if kvh:
            print(f"  kv host tier: {kvh.get('blocks', 0)} blocks "
                  f"({kvh.get('bytes', 0) / 1e6:.1f} MB, peak "
                  f"{kvh.get('peak_bytes', 0) / 1e6:.1f} MB of "
                  f"{kvh.get('budget_bytes', 0) / 1e6:.1f} MB)  "
                  f"hits {kvh.get('hits', 0)}  "
                  f"spills {kvh.get('spills', 0)}  "
                  f"evictions {kvh.get('evictions', 0)}")
        ticks = snap.get("recent_ticks") or []
        if ticks:
            print(f"  last tick: {_json.dumps(ticks[-1])}", file=_sys.stderr)
    if not saw_any:
        print("no scheduler ledger (run the backend with LOCALAI_SCHED=1)")
    return 0


def cli_util(args) -> int:
    import json as _json

    if args.action == "trace":
        return cli_util_trace(args)
    if args.action == "flightrec":
        return cli_util_flightrec(args)
    if args.action == "sched":
        return cli_util_sched(args)

    from localai_tpu.engine.loader import load_config
    from localai_tpu.system.memory import estimate, param_count

    cfg = load_config(args.model)
    if args.action == "hf-info":
        print(_json.dumps({
            "architecture": "llama-family",
            "hidden_size": cfg.hidden_size,
            "layers": cfg.num_layers,
            "heads": cfg.num_heads,
            "kv_heads": cfg.num_kv_heads,
            "head_dim": cfg.head_dim,
            "intermediate_size": cfg.intermediate_size,
            "vocab_size": cfg.vocab_size,
            "max_position": cfg.max_position,
            "num_experts": cfg.num_experts,
            "rope_scaling": cfg.rope_scaling,
            "parameters": param_count(cfg),
        }, indent=1))
        return 0
    if args.hbm_gb:
        hbm = int(args.hbm_gb * 2**30)
    else:
        # table lookup only — a pre-flight CLI must never init a PJRT
        # client (it would contend for the chip with a running server)
        from localai_tpu.system.capabilities import detect_capability
        from localai_tpu.system.memory import hbm_table_bytes

        hbm = hbm_table_bytes(detect_capability())
    est = estimate(cfg, slots=args.slots, context=args.context,
                   dtype=args.dtype, cache_type=args.cache_type,
                   hbm_bytes=hbm, detect_hbm=False)
    print(_json.dumps(est.to_dict(), indent=1))
    return 0


def _add_launcher(sub):
    p = sub.add_parser("launcher",
                       help="interactive server controller "
                            "(reference: cmd/launcher GUI role)")
    p.add_argument("--address", default="127.0.0.1:8080")
    p.add_argument("--models-path", default="models")
    p.add_argument("--autostart", action="store_true")
    return p


def _add_explorer(sub):
    p = sub.add_parser("explorer",
                       help="federation dashboard + network discovery "
                            "(reference: core/cli/explorer.go)")
    p.add_argument("--address", default="127.0.0.1:8509")
    p.add_argument("--pool-database", default="explorer.json")
    p.add_argument("--with-sync", action="store_true",
                   help="poll registered networks in the background")
    p.add_argument("--only-sync", action="store_true",
                   help="run the discovery crawler without the dashboard")
    p.add_argument("--interval", type=float, default=50.0)
    p.add_argument("--threshold", type=int, default=3)
    return p


def _add_models(sub):
    p = sub.add_parser("models", help="list or install models")
    p.add_argument("action", choices=["list", "install"], nargs="?", default="list")
    p.add_argument("name", nargs="?", default=None)
    p.add_argument("--models-path", default="models")
    p.add_argument("--galleries", default=None)
    return p


def _add_backends(sub):
    p = sub.add_parser("backends",
                       help="list, install, or uninstall serving backends "
                            "(reference: core/cli backends cmd)")
    p.add_argument("action", choices=["list", "install", "uninstall"],
                   nargs="?", default="list")
    p.add_argument("name", nargs="?", default=None)
    p.add_argument("--backends-path", default="backends")
    p.add_argument("--backend-galleries", default=None,
                   help="comma-separated backend registry index URIs")
    p.add_argument("--capability", default=None,
                   help="override detected capability for meta resolution")
    return p


def cli_backends(args) -> int:
    from localai_tpu.services.backend_gallery import (
        BackendGallery, delete_backend, install_backend,
        list_system_backends,
    )

    if args.action == "list":
        for b in list_system_backends(args.backends_path):
            kind = "system" if b.get("system") else "installed"
            extra = (f" -> {b['meta_backend_for']}"
                     if b.get("meta_backend_for") else "")
            print(f"{b['name']}\t{kind}{extra}")
        return 0
    if not args.name:
        print("backend name required", file=sys.stderr)
        return 2
    if args.action == "uninstall":
        delete_backend(args.backends_path, args.name)
        print(f"uninstalled {args.name}")
        return 0
    sources = [s.strip() for s in (args.backend_galleries or "").split(",")
               if s.strip()]
    if not sources:
        print("--backend-galleries required for install", file=sys.stderr)
        return 2
    path = install_backend(BackendGallery(sources), args.name,
                           args.backends_path, capability=args.capability)
    print(f"installed {args.name} -> {path}")
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="localai-tpu",
        description="TPU-native OpenAI-compatible inference server",
    )
    sub = parser.add_subparsers(dest="cmd")
    _add_run(sub)
    _add_backend(sub)
    _add_models(sub)
    _add_backends(sub)
    _add_explorer(sub)
    _add_launcher(sub)
    _add_util(sub)
    _add_federated(sub)
    _add_worker(sub)
    _add_tts(sub)
    _add_soundgeneration(sub)
    _add_transcript(sub)
    sub.add_parser("version", help="print version")

    args = parser.parse_args(argv)
    cmd = args.cmd
    if cmd is None:
        parser.print_help()
        return 1

    if cmd == "version":
        from localai_tpu.version import __version__

        print(__version__)
        return 0
    if cmd == "backend":
        from localai_tpu.backend.server import serve_blocking

        return serve_blocking(addr=args.addr, backend=args.backend)
    if cmd == "models":
        from localai_tpu.services.gallery import cli_models

        return cli_models(args)
    if cmd == "backends":
        return cli_backends(args)
    if cmd == "explorer":
        from localai_tpu.explorer import run_explorer

        return run_explorer(args)
    if cmd == "launcher":
        from localai_tpu.launcher import run_launcher

        return run_launcher(args)
    if cmd == "util":
        return cli_util(args)
    if cmd == "federated":
        from localai_tpu.federation import run_federated

        return run_federated(args)
    if cmd == "worker":
        from localai_tpu.core.worker import run_worker

        return run_worker(args)
    if cmd == "tts":
        return cli_tts(args)
    if cmd == "soundgeneration":
        return cli_soundgeneration(args)
    if cmd == "transcript":
        return cli_transcript(args)
    if cmd == "run":
        from localai_tpu.server.http import run_server

        return run_server(args)
    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
