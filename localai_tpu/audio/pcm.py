"""PCM / WAV utilities (reference: /root/reference/pkg/sound — float/int16
conversion — and the ffmpeg shell-outs in pkg/utils). Stdlib `wave` + numpy;
resampling via scipy polyphase."""
from __future__ import annotations

import wave

import numpy as np


def i16_to_f32(x: np.ndarray) -> np.ndarray:
    return (x.astype(np.float32) / 32768.0).clip(-1.0, 1.0)


def f32_to_i16(x: np.ndarray) -> np.ndarray:
    return (np.asarray(x, np.float32).clip(-1.0, 1.0) * 32767.0).astype(np.int16)


def read_wav(path: str, target_rate: int | None = None) -> tuple[np.ndarray, int]:
    """→ (mono float32 [-1, 1], sample_rate); resamples when target_rate set."""
    with wave.open(path, "rb") as w:
        rate = w.getframerate()
        n = w.getnframes()
        width = w.getsampwidth()
        channels = w.getnchannels()
        raw = w.readframes(n)
    if width == 2:
        audio = i16_to_f32(np.frombuffer(raw, np.int16))
    elif width == 4:
        audio = np.frombuffer(raw, np.int32).astype(np.float32) / 2**31
    elif width == 1:
        audio = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    else:
        raise ValueError(f"unsupported sample width {width}")
    if channels > 1:
        audio = audio.reshape(-1, channels).mean(axis=1)
    if target_rate and target_rate != rate:
        from scipy.signal import resample_poly
        from math import gcd

        g = gcd(target_rate, rate)
        audio = resample_poly(audio, target_rate // g, rate // g).astype(np.float32)
        rate = target_rate
    return audio.astype(np.float32), rate


def write_wav(path: str, audio: np.ndarray, rate: int = 16000):
    with wave.open(path, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(f32_to_i16(audio).tobytes())
