"""Voice-activity detection: adaptive-threshold energy VAD.

Reference uses silero-vad via ONNX runtime
(/root/reference/backend/go/silero-vad/vad.go) — not available in this image,
so the VAD capability ships as a dependency-free spectral-energy detector with
the same RPC/HTTP contract (segments of {start, end} seconds). Model-based VAD
can drop in behind the same interface.
"""
from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class VADConfig:
    rate: int = 16000
    frame_ms: float = 30.0
    # threshold = noise_floor * ratio (adaptive), floored at min_energy
    energy_ratio: float = 4.0
    min_energy: float = 1e-4
    min_speech_ms: float = 90.0
    hangover_ms: float = 150.0        # keep speech alive over short dips


def frames_to_segments(active, hang: int, min_frames: int
                       ) -> list[tuple[int, int]]:
    """Active-frame mask → merged (start, end) frame spans with `hang`
    frames of hangover and a minimum span length (shared by the energy and
    model detectors)."""
    segments = []
    start, gap = None, 0
    for i, a in enumerate(active):
        if a:
            if start is None:
                start = i
            gap = 0
        elif start is not None:
            gap += 1
            if gap > hang:
                end = i - gap + 1
                if end - start >= min_frames:
                    segments.append((start, end))
                start, gap = None, 0
    if start is not None:
        end = len(active)
        if end - start >= min_frames:
            segments.append((start, end))
    return segments


def detect_segments(audio: np.ndarray, cfg: VADConfig | None = None
                    ) -> list[tuple[float, float]]:
    """mono f32 → [(start_s, end_s), ...] speech segments."""
    cfg = cfg or VADConfig()
    frame = max(1, int(cfg.rate * cfg.frame_ms / 1000.0))
    n = len(audio) // frame
    if n == 0:
        return []
    x = np.asarray(audio[: n * frame], np.float32).reshape(n, frame)
    energy = np.sqrt((x ** 2).mean(axis=1))                 # per-frame RMS

    # adaptive noise floor: median of the quietest half
    quiet = np.sort(energy)[: max(1, n // 2)]
    floor = float(np.median(quiet))
    thresh = max(floor * cfg.energy_ratio, cfg.min_energy)
    active = energy > thresh

    hang = max(1, int(cfg.hangover_ms / cfg.frame_ms))
    min_frames = max(1, int(cfg.min_speech_ms / cfg.frame_ms))
    segments = frames_to_segments(active, hang, min_frames)
    sec = cfg.frame_ms / 1000.0
    return [(round(s * sec, 3), round(e * sec, 3)) for s, e in segments]


_model_params = None
_model_params_loaded = False


def detect_segments_auto(audio: np.ndarray) -> list[tuple[float, float]]:
    """Model-based VAD (audio/nvad.py — the silero role) when the shipped
    weights are present, adaptive-energy fallback otherwise. This is what
    the VAD RPC serves. Weights are loaded once; a broken weight file logs a
    warning instead of silently degrading on every call."""
    global _model_params, _model_params_loaded
    if not _model_params_loaded:
        from localai_tpu.audio.nvad import load_params

        _model_params = load_params()
        _model_params_loaded = True
    if _model_params is not None:
        try:
            from localai_tpu.audio.nvad import detect_segments_model

            return [(round(s, 3), round(e, 3))
                    for s, e in detect_segments_model(
                        audio, params=_model_params)]
        except Exception:
            import logging

            logging.getLogger("localai_tpu").warning(
                "model VAD failed; falling back to energy VAD",
                exc_info=True)
            _model_params = None        # don't retry per call
    return detect_segments(audio)
