"""Voice-activity detection: adaptive-threshold energy VAD.

Reference uses silero-vad via ONNX runtime
(/root/reference/backend/go/silero-vad/vad.go) — not available in this image,
so the VAD capability ships as a dependency-free spectral-energy detector with
the same RPC/HTTP contract (segments of {start, end} seconds). Model-based VAD
can drop in behind the same interface.
"""
from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class VADConfig:
    rate: int = 16000
    frame_ms: float = 30.0
    # threshold = noise_floor * ratio (adaptive), floored at min_energy
    energy_ratio: float = 4.0
    min_energy: float = 1e-4
    min_speech_ms: float = 90.0
    hangover_ms: float = 150.0        # keep speech alive over short dips


def detect_segments(audio: np.ndarray, cfg: VADConfig | None = None
                    ) -> list[tuple[float, float]]:
    """mono f32 → [(start_s, end_s), ...] speech segments."""
    cfg = cfg or VADConfig()
    frame = max(1, int(cfg.rate * cfg.frame_ms / 1000.0))
    n = len(audio) // frame
    if n == 0:
        return []
    x = np.asarray(audio[: n * frame], np.float32).reshape(n, frame)
    energy = np.sqrt((x ** 2).mean(axis=1))                 # per-frame RMS

    # adaptive noise floor: median of the quietest half
    quiet = np.sort(energy)[: max(1, n // 2)]
    floor = float(np.median(quiet))
    thresh = max(floor * cfg.energy_ratio, cfg.min_energy)
    active = energy > thresh

    hang = max(1, int(cfg.hangover_ms / cfg.frame_ms))
    min_frames = max(1, int(cfg.min_speech_ms / cfg.frame_ms))

    segments = []
    start = None
    gap = 0
    for i, a in enumerate(active):
        if a:
            if start is None:
                start = i
            gap = 0
        elif start is not None:
            gap += 1
            if gap > hang:
                end = i - gap + 1
                if end - start >= min_frames:
                    segments.append((start, end))
                start, gap = None, 0
    if start is not None:
        end = n
        if end - start >= min_frames:
            segments.append((start, end))

    sec = cfg.frame_ms / 1000.0
    return [(round(s * sec, 3), round(e * sec, 3)) for s, e in segments]
