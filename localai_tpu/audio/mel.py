"""Whisper log-mel spectrogram, numerically matching HF's
WhisperFeatureExtractor (parity-tested in tests/test_whisper.py): hann 400,
hop 160, slaney-scale/slaney-norm 80/128-bin mel filterbank, log10 with 8 dB
dynamic-range floor, (x+4)/4 normalization. Pure numpy (host-side feature
extraction feeding the TPU encoder)."""
from __future__ import annotations

import numpy as np

SAMPLE_RATE = 16000
N_FFT = 400
HOP = 160
CHUNK_SECONDS = 30
N_SAMPLES = SAMPLE_RATE * CHUNK_SECONDS


def _hertz_to_mel(f):
    # slaney scale: linear below 1 kHz, log above
    f = np.asarray(f, np.float64)
    mel = 3.0 * f / 200.0
    log_region = f >= 1000.0
    mel = np.where(log_region,
                   15.0 + np.log(np.maximum(f, 1e-10) / 1000.0) * (27.0 / np.log(6.4)),
                   mel)
    return mel


def _mel_to_hertz(m):
    m = np.asarray(m, np.float64)
    f = 200.0 * m / 3.0
    log_region = m >= 15.0
    f = np.where(log_region, 1000.0 * np.exp(np.log(6.4) / 27.0 * (m - 15.0)), f)
    return f


def mel_filters(n_mels: int = 80, n_fft: int = N_FFT,
                rate: int = SAMPLE_RATE) -> np.ndarray:
    """[n_freq, n_mels] slaney-normalized triangular filterbank."""
    n_freq = n_fft // 2 + 1
    fft_freqs = np.linspace(0, rate / 2, n_freq)
    mel_pts = np.linspace(_hertz_to_mel(0.0), _hertz_to_mel(8000.0), n_mels + 2)
    hz_pts = _mel_to_hertz(mel_pts)

    fdiff = np.diff(hz_pts)
    slopes = hz_pts[None, :] - fft_freqs[:, None]          # [n_freq, n_mels+2]
    down = -slopes[:, :-2] / fdiff[:-1]
    up = slopes[:, 2:] / fdiff[1:]
    fb = np.maximum(0.0, np.minimum(down, up))
    enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])               # slaney norm
    return (fb * enorm[None, :]).astype(np.float32)


def log_mel_spectrogram(audio: np.ndarray, n_mels: int = 80,
                        pad_to_chunk: bool = True) -> np.ndarray:
    """mono f32 audio @16 kHz → [n_mels, frames] f32 (HF-compatible)."""
    audio = np.asarray(audio, np.float32)
    if pad_to_chunk:
        audio = audio[:N_SAMPLES]
        audio = np.pad(audio, (0, N_SAMPLES - len(audio)))
    # center-padded reflective framing (np.fft STFT)
    pad = N_FFT // 2
    x = np.pad(audio.astype(np.float64), (pad, pad), mode="reflect")
    window = np.hanning(N_FFT + 1)[:-1]
    n_frames = 1 + (len(x) - N_FFT) // HOP
    idx = np.arange(N_FFT)[None, :] + HOP * np.arange(n_frames)[:, None]
    frames = x[idx] * window[None, :]
    spec = np.abs(np.fft.rfft(frames, axis=1)) ** 2         # [frames, n_freq]
    spec = spec[:-1]                                        # drop last (HF)
    mel = spec @ mel_filters(n_mels)                        # [frames, n_mels]
    log_spec = np.log10(np.maximum(mel, 1e-10))
    log_spec = np.maximum(log_spec, log_spec.max() - 8.0)
    log_spec = (log_spec + 4.0) / 4.0
    return log_spec.T.astype(np.float32)                    # [n_mels, frames]
