"""Neural voice-activity detection — the silero-vad role, as a JAX model.

Reference: /root/reference/backend/go/silero-vad/vad.go:1-58 serves the VAD
RPC with silero's learned model (ONNX runtime). That runtime isn't in this
image, so the learned detector here is a compact spectral conv net *trained
in-repo* (train.py in this module): log-mel frames → 3 dilated conv layers
(receptive field ~11 frames) → per-frame speech probability. Training data
is generated on the fly — positives from the formant speech synthesizer
(audio/tts.py), negatives from silence / white & pink noise / pure tones /
clicks — so, unlike the adaptive-energy fallback (audio/vad.py), the model
rejects stationary tones and hum that carry plenty of energy but no speech
structure.

The shipped weights (vad_model.npz, a few KB) are committed; retrain with
`python -m localai_tpu.audio.nvad` (~1 min on CPU).
"""
from __future__ import annotations

import dataclasses
import os

import numpy as np

_WEIGHTS = os.path.join(os.path.dirname(__file__), "vad_model.npz")
RATE = 16000
N_MELS = 40
HOP = 160                       # 10 ms frames


@dataclasses.dataclass
class NVADConfig:
    threshold: float = 0.5
    hangover_ms: float = 240.0
    min_speech_ms: float = 90.0
    frame_ms: float = 10.0      # = HOP / RATE


def _features(audio: np.ndarray) -> np.ndarray:
    """mono f32 → [T, N_MELS] log-mel frames (10 ms hop)."""
    from localai_tpu.audio.mel import log_mel_spectrogram

    mel = log_mel_spectrogram(audio, n_mels=N_MELS, pad_to_chunk=False)
    return np.asarray(mel, np.float32).T


# ---------------------------------------------------------------- model

def init_params(key=0):
    rng = np.random.default_rng(key)

    def w(shape, fan_in):
        return (rng.standard_normal(shape) * fan_in ** -0.5).astype(
            np.float32)

    # conv kernels [k, in, out]; dilations 1,2,4 → receptive field 11 frames
    return {
        "c1": w((3, N_MELS, 32), 3 * N_MELS), "b1": np.zeros(32, np.float32),
        "c2": w((3, 32, 32), 96), "b2": np.zeros(32, np.float32),
        "c3": w((3, 32, 32), 96), "b3": np.zeros(32, np.float32),
        "out": w((32, 1), 32), "bout": np.zeros(1, np.float32),
    }


def apply(params, feats):
    """[T, N_MELS] → per-frame speech logits [T] (pure JAX)."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(feats)[None]                    # [1, T, F]
    # per-utterance mean/var norm: robust to recording gain
    x = (x - x.mean(axis=(1, 2), keepdims=True)) / (
        x.std(axis=(1, 2), keepdims=True) + 1e-5)

    def conv(x, w, b, dilation):
        out = jax.lax.conv_general_dilated(
            x, jnp.asarray(w), (1,), [(dilation, dilation)],
            rhs_dilation=(dilation,),
            dimension_numbers=("NHC", "HIO", "NHC"))
        return jax.nn.relu(out + jnp.asarray(b))

    x = conv(x, params["c1"], params["b1"], 1)
    x = conv(x, params["c2"], params["b2"], 2)
    x = conv(x, params["c3"], params["b3"], 4)
    logits = x @ jnp.asarray(params["out"]) + jnp.asarray(params["bout"])
    return logits[0, :, 0]


def load_params(path: str | None = None):
    path = path or _WEIGHTS
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def speech_probs(audio: np.ndarray, params=None) -> np.ndarray:
    """mono f32 @16k → per-10ms-frame speech probability."""
    import jax.nn

    params = params if params is not None else load_params()
    if params is None:
        raise FileNotFoundError("no VAD weights (run python -m "
                                "localai_tpu.audio.nvad to train)")
    feats = _features(audio)
    if feats.shape[0] == 0:
        return np.zeros((0,), np.float32)
    return np.asarray(jax.nn.sigmoid(apply(params, feats)))


def detect_segments_model(audio: np.ndarray, cfg: NVADConfig | None = None,
                          params=None) -> list[tuple[float, float]]:
    """Segment extraction with hangover merging (same output contract as the
    energy fallback, audio/vad.py)."""
    from localai_tpu.audio.vad import frames_to_segments

    cfg = cfg or NVADConfig()
    probs = speech_probs(audio, params)
    active = probs > cfg.threshold
    hang = max(1, int(cfg.hangover_ms / cfg.frame_ms))
    min_frames = max(1, int(cfg.min_speech_ms / cfg.frame_ms))
    segments = frames_to_segments(active, hang, min_frames)
    sec = cfg.frame_ms / 1000.0
    return [(s * sec, e * sec) for s, e in segments]


# ---------------------------------------------------------------- training

def _rand_text(rng, n=24):
    chars = "aeiouy bcdfgklmnprst "
    return "".join(chars[rng.integers(0, len(chars))] for _ in range(n))


def _frame_labels_from_energy(clean: np.ndarray, frames: int) -> np.ndarray:
    """Per-frame speech labels from the CLEAN speech signal's energy: padded
    or inter-word silence inside a speech clip trains as 0, not 1 (labeling
    whole clips would teach the model to hold 'speech' through silence)."""
    n_frames = min(frames, len(clean) // HOP)
    lab = np.zeros(frames, np.float32)
    if n_frames <= 0:
        return lab
    x = clean[: n_frames * HOP].reshape(n_frames, HOP)
    rms = np.sqrt((x ** 2).mean(axis=1))
    lab[:n_frames] = (rms > 0.01).astype(np.float32)
    return lab


def _make_clip(rng) -> tuple[np.ndarray, np.ndarray]:
    """(audio ~1.5s, per-frame labels) — positives: synthesized speech
    (optionally in noise); negatives: non-speech that fools energy VADs
    (tones, hum, clicks)."""
    from localai_tpu.audio.tts import synthesize

    kind = rng.integers(0, 6)
    n = int(1.5 * RATE)
    frames = n // HOP
    t = np.arange(n) / RATE
    if kind in (0, 1):                              # speech (+ noise)
        a = synthesize(_rand_text(rng), voice="default", language="en")
        a = a[:n] if len(a) >= n else np.pad(a, (0, n - len(a)))
        labels = _frame_labels_from_energy(a, frames)
        if kind == 1:
            a = a + 0.02 * rng.standard_normal(n)
        return a.astype(np.float32), labels
    zeros = np.zeros(frames, np.float32)
    if kind == 2:                                   # silence / hiss
        return (0.01 * rng.standard_normal(n)).astype(np.float32), zeros
    if kind == 3:                                   # pure tone(s) — loud!
        f = rng.uniform(80, 3000)
        a = 0.4 * np.sin(2 * np.pi * f * t)
        if rng.random() < 0.5:
            a += 0.2 * np.sin(2 * np.pi * rng.uniform(80, 3000) * t)
        return a.astype(np.float32), zeros
    if kind == 4:                                   # mains hum + noise
        a = 0.3 * np.sin(2 * np.pi * 50 * t) + 0.05 * rng.standard_normal(n)
        return a.astype(np.float32), zeros
    # clicks / impulses
    a = np.zeros(n, np.float32)
    for _ in range(rng.integers(2, 8)):
        i = rng.integers(0, n - 100)
        a[i:i + 100] = rng.uniform(-0.8, 0.8)
    return a, zeros


def train(steps: int = 250, seed: int = 0, save: str | None = _WEIGHTS,
          frames: int = 151):
    """Train the detector on generated clips; returns params. Clips are
    padded/cropped to a fixed frame count so the jitted update compiles
    once."""
    import jax
    import jax.numpy as jnp
    import optax

    rng = np.random.default_rng(seed)
    params = jax.tree_util.tree_map(jnp.asarray, init_params(seed))
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    def loss_fn(params, feats, labels):
        logits = apply(params, feats)
        return optax.sigmoid_binary_cross_entropy(logits, labels).mean()

    @jax.jit
    def step_fn(params, opt_state, feats, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, feats, labels)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    for step in range(steps):
        audio, labels = _make_clip(rng)
        feats = _features(audio)[:frames]
        if feats.shape[0] < frames:
            feats = np.pad(feats, ((0, frames - feats.shape[0]), (0, 0)))
        labels = labels[:feats.shape[0]]
        if labels.shape[0] < frames:
            labels = np.pad(labels, (0, frames - labels.shape[0]))
        params, opt_state, loss = step_fn(params, opt_state,
                                          jnp.asarray(feats),
                                          jnp.asarray(labels))
        if step % 50 == 0:
            print(f"step {step}: loss {float(loss):.4f}", flush=True)
    out = {k: np.asarray(v) for k, v in params.items()}
    if save:
        np.savez(save, **out)
        print(f"saved {save}", flush=True)
    return out


if __name__ == "__main__":
    import jax

    # the model is tiny — train on host CPU even when an accelerator (or a
    # half-dead accelerator tunnel) is attached
    jax.config.update("jax_platforms", "cpu")
    train()
