"""Parametric DSP speech synthesizer — the TTS capability's built-in voice.

Reference ships neural TTS backends (piper ONNX voices, bark.cpp —
/root/reference/backend/go/piper, backend/go/bark-cpp); neither runtime exists
in this image, so the TTS contract (RPC + endpoints + WAV output) is served by
a dependency-free formant synthesizer: each phoneme-ish character class maps
to a short formant-filtered excitation. A neural JAX voice can drop in behind
`synthesize()` without touching the contract.
"""
from __future__ import annotations

import numpy as np

RATE = 16000

# (f1, f2) rough vowel formants; consonants → noise bursts
_VOWELS = {
    "a": (730, 1090), "e": (530, 1840), "i": (270, 2290),
    "o": (570, 840), "u": (300, 870), "y": (270, 2100),
}
_PAUSE = set(" \t\n.,;:!?-")


def _formant_tone(f1, f2, dur, pitch=120.0):
    t = np.arange(int(dur * RATE)) / RATE
    # glottal-ish source: pitch + harmonics, shaped by two formant resonances
    src = (np.sin(2 * np.pi * pitch * t)
           + 0.5 * np.sin(2 * np.pi * 2 * pitch * t)
           + 0.25 * np.sin(2 * np.pi * 3 * pitch * t))
    form = (0.6 * np.sin(2 * np.pi * f1 * t)
            + 0.4 * np.sin(2 * np.pi * f2 * t))
    sig = src * (0.5 + 0.5 * form)
    env = np.minimum(1.0, np.minimum(t / 0.02, (dur - t) / 0.04).clip(0))
    return (sig * env).astype(np.float32)


def _noise_burst(dur, color=0.5, seed=0):
    rng = np.random.default_rng(seed)
    n = int(dur * RATE)
    x = rng.normal(size=n).astype(np.float32)
    # crude one-pole lowpass for "color"
    y = np.empty_like(x)
    acc = 0.0
    for i in range(n):
        acc = color * acc + (1 - color) * x[i]
        y[i] = acc
    env = np.minimum(1.0, np.arange(n) / (0.004 * RATE))
    return (0.6 * y * env * env[::-1]).astype(np.float32)


def synthesize(text: str, voice: str = "default", language: str = "en"
               ) -> np.ndarray:
    """text → mono f32 waveform @16 kHz."""
    pitch = {"default": 120.0, "low": 90.0, "high": 170.0}.get(voice, 120.0)
    parts = [np.zeros(int(0.05 * RATE), np.float32)]
    for i, ch in enumerate(text.lower()):
        if ch in _PAUSE:
            parts.append(np.zeros(int(0.12 * RATE), np.float32))
        elif ch in _VOWELS:
            f1, f2 = _VOWELS[ch]
            parts.append(_formant_tone(f1, f2, 0.11, pitch))
        elif ch.isalpha():
            parts.append(_noise_burst(0.06, color=0.3 + 0.02 * (ord(ch) % 20),
                                      seed=ord(ch)))
        elif ch.isdigit():
            parts.append(_formant_tone(400 + 40 * int(ch), 1200, 0.1, pitch))
    audio = np.concatenate(parts) if parts else np.zeros(RATE, np.float32)
    peak = np.abs(audio).max()
    return (0.8 * audio / peak).astype(np.float32) if peak > 0 else audio


def generate_sound(text: str, duration: float = 2.0, seed: int = 0
                   ) -> np.ndarray:
    """SoundGeneration role (reference musicgen path): deterministic
    text-seeded ambient tone mixture."""
    rng = np.random.default_rng(abs(hash(text)) % (2 ** 31) + seed)
    t = np.arange(int(duration * RATE)) / RATE
    audio = np.zeros_like(t, dtype=np.float32)
    for _ in range(5):
        f = float(rng.uniform(80, 1200))
        a = float(rng.uniform(0.05, 0.25))
        ph = float(rng.uniform(0, 2 * np.pi))
        audio += (a * np.sin(2 * np.pi * f * t + ph)).astype(np.float32)
    env = np.minimum(1.0, np.minimum(t / 0.1, (duration - t) / 0.2).clip(0))
    return (audio * env / max(np.abs(audio).max(), 1e-6) * 0.7).astype(np.float32)
