from localai_tpu.audio.pcm import read_wav, write_wav, f32_to_i16, i16_to_f32  # noqa: F401
from localai_tpu.audio.vad import detect_segments  # noqa: F401
