"""Audio transcode helper — the ffmpeg shell-out role.

Reference: /root/reference/pkg/utils/ffmpeg.go converts arbitrary uploads to
16 kHz mono WAV by shelling out to ffmpeg. Same strategy here: WAV handled
natively (wave + polyphase resample), anything else delegated to an ffmpeg
binary when one is on PATH; otherwise a clear error names the missing
dependency instead of mis-decoding.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import tempfile

import numpy as np


def ffmpeg_available() -> bool:
    return shutil.which("ffmpeg") is not None


def to_pcm16k(path: str) -> np.ndarray:
    """Any audio file → mono float32 @16 kHz."""
    if path.lower().endswith(".wav"):
        from localai_tpu.audio.pcm import read_wav

        audio, _ = read_wav(path, target_rate=16000)
        return audio
    if not ffmpeg_available():
        raise RuntimeError(
            f"cannot decode {os.path.basename(path)!r}: non-WAV input needs "
            f"an ffmpeg binary on PATH (reference pkg/utils/ffmpeg.go role)")
    with tempfile.NamedTemporaryFile(suffix=".wav", delete=False) as tmp:
        out = tmp.name
    try:
        subprocess.run(
            ["ffmpeg", "-y", "-i", path, "-ar", "16000", "-ac", "1",
             "-f", "wav", out],
            check=True, capture_output=True, timeout=600)
        from localai_tpu.audio.pcm import read_wav

        audio, _ = read_wav(out)
        return audio
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"ffmpeg failed: {e.stderr.decode(errors='replace')[-400:]}")
    finally:
        if os.path.exists(out):
            os.unlink(out)
