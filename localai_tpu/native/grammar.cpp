// GBNF grammar matcher: parse → pushdown automaton over Unicode codepoints →
// per-state allowed-token bitmasks.
//
// This is the native tier of grammar-constrained decoding: the role llama.cpp's
// in-sampler grammar engine plays in the reference
// (/root/reference/backend/cpp/llama-cpp/grpc-server.cpp:534-559 wires grammar
// triggers into the sampler). TPU split: this library runs HOST-side, emitting
// a vocab bitmask per decode step; the mask is applied on-device inside the
// jitted sampling step (localai_tpu/ops/sampling.py), so the TPU never waits
// on anything but a [V/8]-byte upload.
//
// Build: g++ -O2 -shared -fPIC -o libgrammar.so grammar.cpp
//
// GBNF subset (matches localai_tpu/functions/grammars.py output):
//   rule ::= production        # alternation |, groups (), postfix * + ?
//   literals "..." (with \" \\ \n \r \t \xHH \uHHHH escapes)
//   char classes [a-z0-9] / negated [^"\\] (same escapes)
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <vector>

namespace {

struct CharRange { uint32_t lo, hi; };

struct Element {
  enum Type : uint8_t { CHAR, CHAR_NOT, RULE, END } type;
  std::vector<CharRange> ranges;  // CHAR / CHAR_NOT
  int rule = -1;                  // RULE
};

using Seq = std::vector<Element>;  // END-terminated

struct Rule { std::vector<Seq> alts; };

// ----------------------------------------------------------------- utf8

// decode next codepoint from s[i..]; returns false on invalid/truncated
bool utf8_next(const std::string& s, size_t& i, uint32_t& cp) {
  if (i >= s.size()) return false;
  uint8_t c = s[i];
  int extra;
  if (c < 0x80) { cp = c; extra = 0; }
  else if ((c >> 5) == 0x6) { cp = c & 0x1f; extra = 1; }
  else if ((c >> 4) == 0xe) { cp = c & 0x0f; extra = 2; }
  else if ((c >> 3) == 0x1e) { cp = c & 0x07; extra = 3; }
  else return false;
  if (i + extra >= s.size()) return false;
  for (int k = 1; k <= extra; k++) {
    uint8_t cc = s[i + k];
    if ((cc >> 6) != 0x2) return false;
    cp = (cp << 6) | (cc & 0x3f);
  }
  i += extra + 1;
  return true;
}

// ----------------------------------------------------------------- parser

struct Parser {
  std::string src;
  size_t pos = 0;
  std::map<std::string, int> rule_ids;
  std::vector<Rule> rules;
  std::string err;

  int rule_id(const std::string& name) {
    auto it = rule_ids.find(name);
    if (it != rule_ids.end()) return it->second;
    int id = (int)rules.size();
    rule_ids[name] = id;
    rules.emplace_back();
    return id;
  }

  void ws() {
    while (pos < src.size()) {
      char c = src[pos];
      if (c == '#') { while (pos < src.size() && src[pos] != '\n') pos++; }
      else if (c == ' ' || c == '\t' || c == '\r' || c == '\n') pos++;
      else break;
    }
  }
  // whitespace that does NOT cross into the next rule definition
  void ws_inline() {
    while (pos < src.size()) {
      char c = src[pos];
      if (c == ' ' || c == '\t') { pos++; continue; }
      if (c == '\r' || c == '\n') {
        // lookahead: next non-space line starting with name ::= ends the rule
        size_t save = pos;
        while (pos < src.size() && (src[pos] == '\n' || src[pos] == '\r' ||
                                    src[pos] == ' ' || src[pos] == '\t'))
          pos++;
        size_t name_end = pos;
        while (name_end < src.size() &&
               (isalnum((uint8_t)src[name_end]) || src[name_end] == '-' ||
                src[name_end] == '_'))
          name_end++;
        size_t j = name_end;
        while (j < src.size() && (src[j] == ' ' || src[j] == '\t')) j++;
        if (name_end > pos && j + 2 < src.size() && src[j] == ':' &&
            src[j + 1] == ':' && src[j + 2] == '=') {
          pos = save;  // next rule definition: stop
          return;
        }
        continue;  // wrapped production line
      }
      break;
    }
  }

  bool name(std::string& out) {
    size_t start = pos;
    while (pos < src.size() && (isalnum((uint8_t)src[pos]) ||
                                src[pos] == '-' || src[pos] == '_'))
      pos++;
    if (pos == start) return false;
    out = src.substr(start, pos - start);
    return true;
  }

  bool escape(uint32_t& cp) {
    if (pos >= src.size()) return false;
    char c = src[pos++];
    switch (c) {
      case 'n': cp = '\n'; return true;
      case 'r': cp = '\r'; return true;
      case 't': cp = '\t'; return true;
      case '"': case '\\': case '/': case '[': case ']': case '^': case '-':
        cp = (uint32_t)(uint8_t)c; return true;
      case 'x': case 'u': case 'U': {
        int n = c == 'x' ? 2 : (c == 'u' ? 4 : 8);
        cp = 0;
        for (int k = 0; k < n && pos < src.size(); k++) {
          char h = src[pos];
          int v = (h >= '0' && h <= '9') ? h - '0'
                : (h >= 'a' && h <= 'f') ? h - 'a' + 10
                : (h >= 'A' && h <= 'F') ? h - 'A' + 10 : -1;
          if (v < 0) break;
          cp = cp * 16 + v;
          pos++;
        }
        return true;
      }
      default: cp = (uint32_t)(uint8_t)c; return true;
    }
  }

  bool literal(Seq& seq) {  // after opening "
    while (pos < src.size() && src[pos] != '"') {
      uint32_t cp;
      if (src[pos] == '\\') { pos++; if (!escape(cp)) return false; }
      else { size_t p = pos; if (!utf8_next(src, p, cp)) return false; pos = p; }
      Element e; e.type = Element::CHAR; e.ranges.push_back({cp, cp});
      seq.push_back(std::move(e));
    }
    if (pos >= src.size()) return false;
    pos++;  // closing "
    return true;
  }

  bool char_class(Element& e) {  // after opening [
    e.type = Element::CHAR;
    if (pos < src.size() && src[pos] == '^') { e.type = Element::CHAR_NOT; pos++; }
    while (pos < src.size() && src[pos] != ']') {
      uint32_t lo;
      if (src[pos] == '\\') { pos++; if (!escape(lo)) return false; }
      else { size_t p = pos; if (!utf8_next(src, p, lo)) return false; pos = p; }
      uint32_t hi = lo;
      if (pos + 1 < src.size() && src[pos] == '-' && src[pos + 1] != ']') {
        pos++;
        if (src[pos] == '\\') { pos++; if (!escape(hi)) return false; }
        else { size_t p = pos; if (!utf8_next(src, p, hi)) return false; pos = p; }
      }
      e.ranges.push_back({lo, hi});
    }
    if (pos >= src.size()) return false;
    pos++;  // closing ]
    return true;
  }

  // wrap element(s) for postfix operator via an auxiliary rule
  int aux_rule(Rule&& r) {
    int id = (int)rules.size();
    rules.push_back(std::move(r));
    return id;
  }

  void apply_postfix(Seq& seq, char op) {
    // take last element E of seq
    Element e = seq.back();
    seq.pop_back();
    Seq unit{e};
    unit.push_back({Element::END, {}, -1});
    if (op == '?') {
      Rule r;
      Seq a{e}; a.push_back({Element::END, {}, -1});
      r.alts.push_back(std::move(a));
      r.alts.push_back({{Element::END, {}, -1}});
      int id = aux_rule(std::move(r));
      Element ref; ref.type = Element::RULE; ref.rule = id;
      seq.push_back(ref);
      return;
    }
    // star: S ::= E S | ε ; plus: E S
    Rule r;
    int id = (int)rules.size();
    Seq a{e};
    Element self; self.type = Element::RULE; self.rule = id;
    a.push_back(self);
    a.push_back({Element::END, {}, -1});
    r.alts.push_back(std::move(a));
    r.alts.push_back({{Element::END, {}, -1}});
    aux_rule(std::move(r));
    if (op == '+') seq.push_back(e);
    Element ref; ref.type = Element::RULE; ref.rule = id;
    seq.push_back(ref);
  }

  // parse a sequence of items until | ) or end-of-production
  bool sequence(Seq& seq);

  bool group(int& out_rule) {  // after ( : alternation until )
    Rule r;
    for (;;) {
      Seq s;
      if (!sequence(s)) return false;
      s.push_back({Element::END, {}, -1});
      r.alts.push_back(std::move(s));
      ws_inline();
      if (pos < src.size() && src[pos] == '|') { pos++; continue; }
      break;
    }
    if (pos >= src.size() || src[pos] != ')') return false;
    pos++;
    out_rule = aux_rule(std::move(r));
    return true;
  }

  bool production(int rid) {
    // NOTE: sequence() may push auxiliary rules (reallocating `rules`), so
    // never hold a Rule& across it — collect alts locally, assign by index.
    std::vector<Seq> alts;
    for (;;) {
      Seq s;
      if (!sequence(s)) return false;
      s.push_back({Element::END, {}, -1});
      alts.push_back(std::move(s));
      ws_inline();
      if (pos < src.size() && src[pos] == '|') { pos++; continue; }
      break;
    }
    for (auto& a : alts) rules[rid].alts.push_back(std::move(a));
    return true;
  }

  bool parse() {
    ws();
    while (pos < src.size()) {
      std::string n;
      if (!name(n)) { err = "expected rule name @" + std::to_string(pos); return false; }
      ws_inline();
      if (pos + 2 >= src.size() || src.compare(pos, 3, "::=") != 0) {
        err = "expected ::= after " + n;
        return false;
      }
      pos += 3;
      if (!production(rule_id(n))) {
        err = "bad production for " + n + (err.empty() ? "" : (": " + err));
        return false;
      }
      ws();
    }
    return true;
  }
};

bool Parser::sequence(Seq& seq) {
  for (;;) {
    ws_inline();
    if (pos >= src.size()) break;
    char c = src[pos];
    if (c == '|' || c == ')') break;
    if (c == '"') {
      pos++;
      if (!literal(seq)) { err = "bad literal"; return false; }
    } else if (c == '[') {
      pos++;
      Element e;
      if (!char_class(e)) { err = "bad char class"; return false; }
      if (e.ranges.empty() && e.type == Element::CHAR) { err = "empty class"; return false; }
      seq.push_back(std::move(e));
    } else if (c == '(') {
      pos++;
      int gid;
      if (!group(gid)) { err = "bad group"; return false; }
      Element ref; ref.type = Element::RULE; ref.rule = gid;
      seq.push_back(ref);
    } else if (isalnum((uint8_t)c) || c == '-' || c == '_') {
      std::string n;
      name(n);
      Element ref; ref.type = Element::RULE; ref.rule = rule_id(n);
      seq.push_back(ref);
    } else {
      break;
    }
    // postfix operators
    if (pos < src.size() && (src[pos] == '*' || src[pos] == '+' || src[pos] == '?')) {
      if (seq.empty()) { err = "postfix without operand"; return false; }
      char op = src[pos++];
      apply_postfix(seq, op);
    }
  }
  return true;
}

// ----------------------------------------------------------------- PDA

struct Grammar {
  std::vector<Rule> rules;
  int root = -1;
  std::vector<std::vector<uint32_t>> tok_cps;  // codepoints per vocab token
  std::vector<uint8_t> tok_valid;
};

using Stack = std::vector<const Element*>;  // top = back()

bool char_matches(const Element& e, uint32_t cp) {
  bool in = false;
  for (const auto& r : e.ranges)
    if (cp >= r.lo && cp <= r.hi) { in = true; break; }
  return e.type == Element::CHAR ? in : !in;
}

// Stack-entry convention (llama.cpp grammar style): an entry is a pointer to
// an element WITHIN an END-terminated sequence; matching it continues with
// pos+1 at consumption time. expand() rewrites stacks until every top is a
// terminal char element (or the stack is empty = completed parse).
void expand(const Grammar& g, Stack stack, std::set<Stack>& out, int depth = 0) {
  if (depth > 512) return;  // runaway-recursion guard
  if (stack.empty()) { out.insert(stack); return; }
  const Element* top = stack.back();
  if (top->type == Element::CHAR || top->type == Element::CHAR_NOT) {
    out.insert(stack);
    return;
  }
  if (top->type == Element::RULE) {
    stack.pop_back();
    Stack base = std::move(stack);
    if ((top + 1)->type != Element::END) base.push_back(top + 1);
    for (const auto& alt : g.rules[top->rule].alts) {
      Stack s = base;
      if (alt[0].type != Element::END) s.push_back(&alt[0]);
      expand(g, std::move(s), out, depth + 1);
    }
    return;
  }
  // END shouldn't appear on stacks
}

// after consuming the terminal at `pos`, continue with pos+1 then expand
void advance_past(const Grammar& g, Stack stack, const Element* pos,
                  std::set<Stack>& out) {
  if ((pos + 1)->type != Element::END) stack.push_back(pos + 1);
  expand(g, std::move(stack), out);
}

struct State {
  const Grammar* g;
  std::set<Stack> stacks;

  bool accept_cp(uint32_t cp) {
    std::set<Stack> next;
    for (const auto& st : stacks) {
      if (st.empty()) continue;  // completed parse can't consume more
      const Element* top = st.back();
      if (!char_matches(*top, cp)) continue;
      Stack s = st;
      s.pop_back();
      advance_past(*g, std::move(s), top, next);
    }
    if (next.empty()) return false;
    stacks.swap(next);
    return true;
  }

  bool accept_token(const std::vector<uint32_t>& cps) {
    // trial on a copy
    State trial = *this;
    for (uint32_t cp : cps)
      if (!trial.accept_cp(cp)) return false;
    return true;
  }

  bool done() const {
    for (const auto& st : stacks)
      if (st.empty()) return true;
    return false;
  }
  bool can_continue() const {
    for (const auto& st : stacks)
      if (!st.empty()) return true;
    return false;
  }
};

}  // namespace

// ----------------------------------------------------------------- C API

extern "C" {

Grammar* gm_compile(const char* text, char* errbuf, int errlen) {
  Parser p;
  p.src = text;
  if (!p.parse()) {
    if (errbuf && errlen > 0) {
      strncpy(errbuf, p.err.c_str(), errlen - 1);
      errbuf[errlen - 1] = 0;
    }
    return nullptr;
  }
  auto it = p.rule_ids.find("root");
  if (it == p.rule_ids.end()) {
    if (errbuf) strncpy(errbuf, "no root rule", errlen - 1);
    return nullptr;
  }
  auto* g = new Grammar();
  g->rules = std::move(p.rules);
  g->root = it->second;
  return g;
}

// vocab: concatenated UTF-8 token texts + offsets[n+1]
int gm_set_vocab(Grammar* g, const char* blob, const int64_t* offsets, int n) {
  g->tok_cps.assign(n, {});
  g->tok_valid.assign(n, 0);
  for (int i = 0; i < n; i++) {
    std::string t(blob + offsets[i], blob + offsets[i + 1]);
    if (t.empty()) continue;
    std::vector<uint32_t> cps;
    size_t j = 0;
    bool ok = true;
    while (j < t.size()) {
      uint32_t cp;
      if (!utf8_next(t, j, cp)) { ok = false; break; }
      cps.push_back(cp);
    }
    if (ok && !cps.empty()) {
      g->tok_cps[i] = std::move(cps);
      g->tok_valid[i] = 1;
    }
  }
  return 0;
}

State* gm_state_new(Grammar* g) {
  auto* s = new State();
  s->g = g;
  std::set<Stack> out;
  for (const auto& alt : g->rules[g->root].alts) {
    Stack st;
    if (alt[0].type != Element::END) st.push_back(&alt[0]);
    expand(*g, std::move(st), out);
  }
  s->stacks = std::move(out);
  return s;
}

State* gm_state_clone(State* s) { return new State(*s); }

// advance with a token's codepoints; 1 on success, 0 reject
int gm_state_accept_token(State* s, int token_id) {
  if (token_id < 0 || token_id >= (int)s->g->tok_cps.size() ||
      !s->g->tok_valid[token_id])
    return 0;
  const auto& cps = s->g->tok_cps[token_id];
  State trial = *s;
  for (uint32_t cp : cps)
    if (!trial.accept_cp(cp)) return 0;
  *s = std::move(trial);
  return 1;
}

// fill bitmask (LSB-first per byte) of tokens acceptable from this state
int gm_state_mask(State* s, uint8_t* bits, int nbytes) {
  memset(bits, 0, nbytes);
  int n = (int)s->g->tok_cps.size();
  for (int i = 0; i < n && i / 8 < nbytes; i++) {
    if (!s->g->tok_valid[i]) continue;
    if (s->accept_token(s->g->tok_cps[i]))
      bits[i >> 3] |= (uint8_t)(1u << (i & 7));
  }
  return 0;
}

int gm_state_done(State* s) { return s->done() ? 1 : 0; }
int gm_state_can_continue(State* s) { return s->can_continue() ? 1 : 0; }
int gm_state_stack_count(State* s) { return (int)s->stacks.size(); }

// Enumerate every automaton state reachable from the initial state by
// whole-token transitions (BFS with exact dedup on the stack-set identity)
// and emit the dense device tables:
//   masks     [cap, words] u32  LSB-first bit t = token t acceptable
//   trans     [cap, n]     i32  next state index, -1 where the mask is 0
//   accepting [cap]        u8   done() — a completed parse exists here
// State 0 is the initial state. Returns the state count, or -1 when the
// reachable set exceeds `cap` (recursive grammars with unbounded nesting
// never close; callers fall back to the per-token host matcher).
int gm_table_build(Grammar* g, int cap, uint32_t* masks, int words,
                   int32_t* trans, uint8_t* accepting) {
  int n = (int)g->tok_cps.size();
  if (cap <= 0 || n <= 0) return -1;
  std::map<std::set<Stack>, int> index;
  std::vector<State> states;
  {
    State* init = gm_state_new(g);
    states.push_back(*init);
    delete init;
  }
  index[states[0].stacks] = 0;
  for (size_t i = 0; i < states.size(); i++) {
    State cur = states[i];  // copy: states reallocs under push_back below
    uint32_t* mrow = masks + i * (size_t)words;
    int32_t* trow = trans + i * (size_t)n;
    memset(mrow, 0, (size_t)words * sizeof(uint32_t));
    for (int t = 0; t < n; t++) trow[t] = -1;
    accepting[i] = cur.done() ? 1 : 0;
    for (int t = 0; t < n; t++) {
      if (!g->tok_valid[t]) continue;
      State trial = cur;
      bool ok = true;
      for (uint32_t cp : g->tok_cps[t])
        if (!trial.accept_cp(cp)) { ok = false; break; }
      if (!ok) continue;
      mrow[t >> 5] |= (1u << (t & 31));
      auto it = index.find(trial.stacks);
      int nxt;
      if (it != index.end()) {
        nxt = it->second;
      } else {
        nxt = (int)states.size();
        if (nxt >= cap) return -1;
        index[trial.stacks] = nxt;
        states.push_back(trial);
      }
      trow[t] = nxt;
    }
  }
  return (int)states.size();
}

void gm_state_free(State* s) { delete s; }
void gm_free(Grammar* g) { delete g; }

}  // extern "C"
