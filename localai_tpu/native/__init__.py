"""Native (C++) components, built on demand with the in-image toolchain.

The reference ships native code for its hot host-side paths (llama.cpp server,
grammar sampler, local-store); here the native tier is compiled lazily at
first use (g++ -O2 -shared) and cached next to the source. ctypes bindings —
no pybind11 in the image.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from localai_tpu.testing.lockdep import lockdep_lock

_HERE = os.path.dirname(__file__)
_LOCK = lockdep_lock("native.build")
_LIBS: dict[str, ctypes.CDLL] = {}


def build_and_load(name: str) -> ctypes.CDLL:
    """Compile native/<name>.cpp → lib<name>.so (if stale) and dlopen it."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        src = os.path.join(_HERE, f"{name}.cpp")
        lib = os.path.join(_HERE, f"lib{name}.so")
        if (not os.path.exists(lib)
                or os.path.getmtime(lib) < os.path.getmtime(src)):
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                   "-o", lib + ".tmp", src]
            # lint: allow(lock-across-blocking) — one-time lazy build: the
            # lock MUST cover the compile so concurrent importers don't race
            # the .so; no request path runs before load
            r = subprocess.run(cmd, capture_output=True, text=True)
            if r.returncode != 0:
                raise RuntimeError(f"native build of {name} failed:\n{r.stderr}")
            os.replace(lib + ".tmp", lib)
        try:
            _LIBS[name] = ctypes.CDLL(lib)
        except OSError:
            # a stale/foreign-arch .so (copied tree, cross-platform rsync):
            # rebuild from source for THIS platform and retry once
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                   "-o", lib + ".tmp", src]
            # lint: allow(lock-across-blocking) — same one-time build lock
            # as above (stale/foreign-arch rebuild retry)
            r = subprocess.run(cmd, capture_output=True, text=True)
            if r.returncode != 0:
                raise RuntimeError(
                    f"native rebuild of {name} failed:\n{r.stderr}")
            os.replace(lib + ".tmp", lib)
            _LIBS[name] = ctypes.CDLL(lib)
        return _LIBS[name]
