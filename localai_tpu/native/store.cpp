// In-memory vector store with cosine top-k — the native tier of the Stores
// backend (role of /root/reference/backend/go/local-store/store.go:110-515:
// sorted keys, normalized fast path, priority-queue top-k).
//
// Design: flat row-major float matrix + byte values; exact-key lookup via a
// hash of the raw float bits; all vectors stored L2-normalized alongside the
// originals so Find is one GEMV + partial_sort. ctypes C API.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -o libstore.so store.cpp
#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct KeyHash {
  size_t operator()(const std::string& s) const {
    return std::hash<std::string>()(s);
  }
};

struct Store {
  int dim;
  std::vector<float> keys;        // [n, dim] originals
  std::vector<float> unit;        // [n, dim] L2-normalized
  std::vector<std::string> values;
  std::unordered_map<std::string, int> index;  // raw key bytes → row
  std::vector<int> free_rows;

  std::string key_bytes(const float* k) const {
    return std::string(reinterpret_cast<const char*>(k), dim * sizeof(float));
  }

  void write_row(int row, const float* k, const uint8_t* v, int64_t vlen) {
    std::memcpy(&keys[(size_t)row * dim], k, dim * sizeof(float));
    double norm = 0;
    for (int i = 0; i < dim; i++) norm += (double)k[i] * k[i];
    float inv = norm > 0 ? (float)(1.0 / std::sqrt(norm)) : 0.f;
    for (int i = 0; i < dim; i++) unit[(size_t)row * dim + i] = k[i] * inv;
    values[row].assign(reinterpret_cast<const char*>(v), vlen);
  }

  int upsert(const float* k, const uint8_t* v, int64_t vlen) {
    auto kb = key_bytes(k);
    auto it = index.find(kb);
    if (it != index.end()) {
      write_row(it->second, k, v, vlen);
      return it->second;
    }
    int row;
    if (!free_rows.empty()) {
      row = free_rows.back();
      free_rows.pop_back();
    } else {
      row = (int)(keys.size() / dim);
      keys.resize(keys.size() + dim);
      unit.resize(unit.size() + dim);
      values.emplace_back();
    }
    write_row(row, k, v, vlen);
    index[kb] = row;
    return row;
  }
};

}  // namespace

extern "C" {

Store* st_new(int dim) {
  auto* s = new Store();
  s->dim = dim;
  return s;
}

void st_free(Store* s) { delete s; }

int st_count(Store* s) { return (int)s->index.size(); }
int st_dim(Store* s) { return s->dim; }

int st_set(Store* s, int n, const float* keys, const uint8_t* blob,
           const int64_t* offsets) {
  for (int i = 0; i < n; i++)
    s->upsert(keys + (size_t)i * s->dim, blob + offsets[i],
              offsets[i + 1] - offsets[i]);
  return n;
}

int st_delete(Store* s, int n, const float* keys) {
  int deleted = 0;
  for (int i = 0; i < n; i++) {
    auto it = s->index.find(s->key_bytes(keys + (size_t)i * s->dim));
    if (it == s->index.end()) continue;
    s->free_rows.push_back(it->second);
    s->values[it->second].clear();
    s->index.erase(it);
    deleted++;
  }
  return deleted;
}

// returns row id or -1
int st_lookup(Store* s, const float* key) {
  auto it = s->index.find(s->key_bytes(key));
  return it == s->index.end() ? -1 : it->second;
}

int64_t st_value_len(Store* s, int row) {
  return (int64_t)s->values[row].size();
}

void st_value_copy(Store* s, int row, uint8_t* out) {
  std::memcpy(out, s->values[row].data(), s->values[row].size());
}

void st_key_copy(Store* s, int row, float* out) {
  std::memcpy(out, &s->keys[(size_t)row * s->dim], s->dim * sizeof(float));
}

// cosine top-k over live rows; returns m <= k, fills rows + similarities
int st_find(Store* s, const float* key, int k, int* out_rows,
            float* out_sims) {
  double norm = 0;
  for (int i = 0; i < s->dim; i++) norm += (double)key[i] * key[i];
  float inv = norm > 0 ? (float)(1.0 / std::sqrt(norm)) : 0.f;
  std::vector<float> q(s->dim);
  for (int i = 0; i < s->dim; i++) q[i] = key[i] * inv;

  std::vector<std::pair<float, int>> scored;
  scored.reserve(s->index.size());
  for (const auto& [kb, row] : s->index) {
    const float* u = &s->unit[(size_t)row * s->dim];
    float dot = 0;
    for (int i = 0; i < s->dim; i++) dot += q[i] * u[i];
    scored.emplace_back(dot, row);
  }
  int m = std::min<int>(k, (int)scored.size());
  std::partial_sort(scored.begin(), scored.begin() + m, scored.end(),
                    [](auto& a, auto& b) { return a.first > b.first; });
  for (int i = 0; i < m; i++) {
    out_rows[i] = scored[i].second;
    out_sims[i] = scored[i].first;
  }
  return m;
}

}  // extern "C"
