"""/v1/realtime — WebSocket voice sessions composing VAD → transcription →
LLM → TTS from the model's `pipeline:` config.

Reference: /root/reference/core/http/endpoints/openai/realtime.go:179-1301
(session state machine :130/:605, audio ring buffer + VAD goroutine :644-858,
utterance commit → pipeline models, events back over WS :542) and
routes/openai.go:20-22 (GET /v1/realtime + POST session-factory routes).

Two session intents, as in the reference (realtime.go:67
"realtime.transcription_session"):
  conversation   — audio/text in → transcription → LLM → TTS out
  transcription  — audio in → interim transcription deltas + completed only

Event surface (OpenAI-realtime-shaped):
  client → server: session.update, transcription_session.update,
                   conversation.item.create,
                   input_audio_buffer.append (b64 pcm16 @16 kHz),
                   input_audio_buffer.commit, input_audio_buffer.clear,
                   response.create, response.cancel
  server → client: session.created | transcription_session.created,
                   session.updated, conversation.item.created,
                   input_audio_buffer.committed / .cleared,
                   input_audio_buffer.speech_started / .speech_stopped,
                   conversation.item.input_audio_transcription.delta,
                   conversation.item.input_audio_transcription.completed,
                   response.created, response.text.delta,
                   response.audio.delta (b64 wav pcm16), response.done
                   (status completed|cancelled), error

`response.cancel` genuinely interrupts an in-flight response mid-stream
(the reference stubs it with NotImplemented, realtime.go:522): the LLM is
consumed token-by-token via PredictStream and the asyncio task carrying it
is cancelled, so generation stops being delivered at the next delta.
"""
from __future__ import annotations

import asyncio
import base64
import json
import secrets
import tempfile
import time
import uuid

import numpy as np
from aiohttp import WSMsgType, web


class RealtimeSession:
    def __init__(self, api, cfg, intent: str = "conversation"):
        self.api = api
        self.cfg = cfg                      # ModelConfig with .pipeline
        self.intent = intent                # "conversation" | "transcription"
        self.messages: list[dict] = []
        self.audio = bytearray()            # pcm16 @16 kHz
        self.session_id = f"sess_{uuid.uuid4().hex[:16]}"
        self.voice = "default"
        self.server_vad = False
        self.in_speech = False              # VAD state for started/stopped
        self.response_task: asyncio.Task | None = None
        self.response_id: str | None = None
        self.response_done_sent = False

    # ---------------------------------------------------------- pipeline ops

    async def _handle_for(self, name: str):
        mcfg = self.api.configs.get(name)
        if mcfg is None:
            if not name.startswith("default-"):
                raise ValueError(f"pipeline model {name!r} not found")
            from localai_tpu.config import ModelConfig

            mcfg = ModelConfig(name=name, backend=name.split("-", 1)[1])
        return await self.api._handle(mcfg)

    async def transcribe_buffer(self) -> str:
        name = self.cfg.pipeline.transcription
        if not name:
            return ""
        from localai_tpu.audio.pcm import i16_to_f32, write_wav

        pcm = np.frombuffer(bytes(self.audio), np.int16)
        handle = await self._handle_for(name)
        with tempfile.NamedTemporaryFile(suffix=".wav", delete=False) as t:
            path = t.name
        import os

        try:
            write_wav(path, i16_to_f32(pcm), 16000)
            r = await asyncio.to_thread(
                lambda: handle.client.transcribe(dst=path))
            return r.text
        finally:
            os.unlink(path)

    async def run_llm_stream(self):
        """Async-iterate LLM reply chunks via the backend's PredictStream.

        A worker thread drains the gRPC stream into an asyncio queue; the
        consumer (respond task) may be cancelled between deltas, which stops
        delivery immediately and abandons the worker to finish into a dead
        queue.
        """
        name = self.cfg.pipeline.llm or self.cfg.name
        handle = await self._handle_for(name)
        mcfg = self.api.configs.get(name) or self.cfg
        opts = self.api._merged_options(mcfg, {})
        opts["messages_json"] = json.dumps(self.messages)
        opts["use_tokenizer_template"] = True

        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        DONE = object()
        call = handle.client.predict_stream(**opts)   # gRPC stream handle

        def worker():
            try:
                for reply in call:
                    loop.call_soon_threadsafe(
                        q.put_nowait, reply.message.decode("utf-8", "replace"))
                loop.call_soon_threadsafe(q.put_nowait, DONE)
            except Exception as e:  # surfaced as an error event by respond()
                loop.call_soon_threadsafe(q.put_nowait, e)

        threading_task = asyncio.create_task(asyncio.to_thread(worker))
        try:
            while True:
                item = await q.get()
                if item is DONE:
                    break
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            # cancel the gRPC stream so the BACKEND stops generating — a
            # thread cancel alone would let the engine run to max_tokens
            # into a dead queue
            call.cancel()
            threading_task.cancel()

    async def run_tts(self, text: str) -> bytes:
        name = self.cfg.pipeline.tts
        if not name:
            return b""
        handle = await self._handle_for(name)
        with tempfile.NamedTemporaryFile(suffix=".wav", delete=False) as t:
            path = t.name
        import os

        try:
            await asyncio.to_thread(lambda: handle.client.tts(
                text=text, voice=self.voice, dst=path))
            with open(path, "rb") as f:
                return f.read()
        finally:
            os.unlink(path)

    def vad_state(self) -> tuple[bool, bool]:
        """One detect_segments pass over the buffer → (speech_present,
        utterance_complete: speech followed by >=300 ms of silence)."""
        from localai_tpu.audio.pcm import i16_to_f32
        from localai_tpu.audio.vad import detect_segments

        pcm = i16_to_f32(np.frombuffer(bytes(self.audio), np.int16))
        if len(pcm) < 16000 // 4:
            return False, False
        segs = detect_segments(pcm)
        if not segs:
            return False, False
        done = (len(pcm) >= 16000 // 2
                and (len(pcm) / 16000.0 - segs[-1][1]) >= 0.3)
        return True, done


def _session_payload(sess: RealtimeSession, model: str) -> dict:
    """Session object shape shared by WS created events and the POST
    session-factory routes (reference: RealtimeTranscriptionSession,
    routes/openai.go:21-22). client_secret is the ephemeral-key surface."""
    return {
        "id": sess.session_id,
        "object": ("realtime.transcription_session"
                   if sess.intent == "transcription" else "realtime.session"),
        "model": model,
        "intent": sess.intent,
        "voice": sess.voice,
        "client_secret": {
            "value": f"ek_{secrets.token_hex(16)}",
            "expires_at": int(time.time()) + 600,
        },
    }


async def session_factory_handler(api, request: web.Request,
                                  intent: str = "conversation"):
    """POST /v1/realtime/sessions and /v1/realtime/transcription_session —
    mint an ephemeral session descriptor (reference routes/openai.go:21-22)."""
    try:
        body = await request.json()
    except Exception:
        body = {}
    name = body.get("model", "")
    cfg = api.configs.get(name) if name else api.configs.first()
    if cfg is None:
        raise web.HTTPNotFound(text="no model for realtime session")
    sess = RealtimeSession(api, cfg, intent=intent)
    if isinstance(body.get("voice"), str):
        sess.voice = body["voice"]
    return web.json_response(_session_payload(sess, cfg.name))


async def realtime_handler(api, request: web.Request):
    name = request.query.get("model", "")
    intent = request.query.get("intent", "conversation")
    if intent not in ("conversation", "transcription"):
        raise web.HTTPBadRequest(text=f"unknown intent {intent!r}")
    cfg = api.configs.get(name) if name else api.configs.first()
    if cfg is None:
        raise web.HTTPNotFound(text="no model for realtime session")

    ws = web.WebSocketResponse()
    await ws.prepare(request)
    sess = RealtimeSession(api, cfg, intent=intent)

    send_lock = asyncio.Lock()

    async def send(obj):
        # the respond() task and the message loop both write to the socket
        async with send_lock:
            await ws.send_json(obj)

    created = ("transcription_session.created"
               if intent == "transcription" else "session.created")
    await send({"type": created,
                "session": _session_payload(sess, cfg.name)})

    async def transcribe_committed():
        """Shared commit path: emit committed + transcription events, append
        the user message (conversation intent only). Returns the text."""
        await send({"type": "input_audio_buffer.committed"})
        text = await sess.transcribe_buffer()
        sess.audio.clear()
        sess.in_speech = False
        if text:
            # interim delta(s) then completed — the reference's Python
            # transcription backends emit segment deltas the same way
            for word in _delta_chunks(text):
                await send({
                    "type": "conversation.item.input_audio_transcription.delta",
                    "delta": word})
            await send({
                "type":
                    "conversation.item.input_audio_transcription.completed",
                "transcript": text})
            if sess.intent == "conversation":
                sess.messages.append({"role": "user", "content": text})
        return text

    async def commit_and_respond():
        if sess.audio:
            await transcribe_committed()
        if sess.intent == "conversation":
            start_response()

    def start_response():
        if sess.response_task is not None and not sess.response_task.done():
            return  # one active response at a time, as in the reference
        sess.response_id = f"resp_{uuid.uuid4().hex[:12]}"
        sess.response_done_sent = False
        sess.response_task = asyncio.create_task(respond(sess.response_id))

    async def respond(rid: str):
        if not sess.messages:
            await send({"type": "error",
                        "error": {"message": "no conversation items"}})
            return
        await send({"type": "response.created", "response_id": rid})
        parts: list[str] = []
        appended = False
        try:
            async for delta in sess.run_llm_stream():
                parts.append(delta)
                await send({"type": "response.text.delta",
                            "response_id": rid, "delta": delta})
            text = "".join(parts)
            sess.messages.append({"role": "assistant", "content": text})
            appended = True
            audio = await sess.run_tts(text)
            if audio:
                await send({"type": "response.audio.delta",
                            "response_id": rid,
                            "delta": base64.b64encode(audio).decode()})
            sess.response_done_sent = True
            await send({"type": "response.done", "response_id": rid,
                        "status": "completed"})
        except asyncio.CancelledError:
            # partial text is still conversation state, as with a user
            # interrupting a voice assistant mid-sentence (unless the full
            # reply was already appended and the cancel landed in TTS)
            if parts and not appended:
                sess.messages.append(
                    {"role": "assistant", "content": "".join(parts)})
            sess.response_done_sent = True
            await send({"type": "response.done", "response_id": rid,
                        "status": "cancelled"})
            raise
        except Exception as e:
            await send({"type": "error",
                        "error": {"message": f"{type(e).__name__}: {e}"}})

    async for msg in ws:
        if msg.type != WSMsgType.TEXT:
            continue
        try:
            ev = json.loads(msg.data)
        except json.JSONDecodeError:
            await send({"type": "error",
                        "error": {"message": "invalid JSON"}})
            continue
        t = ev.get("type")
        try:
            if t in ("session.update", "transcription_session.update"):
                s = ev.get("session", {})
                sess.voice = s.get("voice", sess.voice)
                td = s.get("turn_detection")
                sess.server_vad = bool(td and td.get("type") == "server_vad")
                await send({"type": "session.updated", "session": s})
            elif t == "conversation.item.create":
                item = ev.get("item", {})
                content = item.get("content", "")
                if isinstance(content, list):
                    content = "".join(p.get("text", "") for p in content)
                sess.messages.append({
                    "role": item.get("role", "user"), "content": content})
                await send({"type": "conversation.item.created"})
            elif t == "input_audio_buffer.append":
                sess.audio.extend(base64.b64decode(ev.get("audio", "")))
                if sess.server_vad:
                    present, done = sess.vad_state()
                    if not sess.in_speech and present:
                        sess.in_speech = True
                        await send(
                            {"type": "input_audio_buffer.speech_started"})
                    if done:
                        if sess.in_speech:
                            await send(
                                {"type":
                                 "input_audio_buffer.speech_stopped"})
                        await commit_and_respond()
            elif t == "input_audio_buffer.commit":
                await commit_and_respond()
            elif t == "input_audio_buffer.clear":
                sess.audio.clear()
                sess.in_speech = False
                await send({"type": "input_audio_buffer.cleared"})
            elif t == "response.create":
                if sess.intent == "transcription":
                    await send({"type": "error", "error": {
                        "message": "transcription session has no responses"}})
                else:
                    start_response()
            elif t == "response.cancel":
                task = sess.response_task
                if task is not None and not task.done():
                    task.cancel()
                    try:
                        await task
                    except asyncio.CancelledError:
                        pass
                    if not sess.response_done_sent:
                        # cancel landed before the task's coroutine ever
                        # ran — its own cancelled-handler never fired
                        sess.response_done_sent = True
                        await send({"type": "response.done",
                                    "response_id": sess.response_id,
                                    "status": "cancelled"})
                else:
                    await send({"type": "error", "error": {
                        "message": "no active response to cancel"}})
            else:
                await send({"type": "error",
                            "error": {"message": f"unknown event {t!r}"}})
        except Exception as e:
            await send({"type": "error",
                        "error": {"message": f"{type(e).__name__}: {e}"}})
    if sess.response_task is not None and not sess.response_task.done():
        sess.response_task.cancel()
    return ws


def _delta_chunks(text: str, n: int = 4) -> list[str]:
    """Split a transcript into word-group deltas for interim events."""
    words = text.split(" ")
    return [" ".join(words[i:i + n]) + (" " if i + n < len(words) else "")
            for i in range(0, len(words), n)]
