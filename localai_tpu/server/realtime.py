"""/v1/realtime — WebSocket voice sessions composing VAD → transcription →
LLM → TTS from the model's `pipeline:` config.

Reference: /root/reference/core/http/endpoints/openai/realtime.go:179-1301
(session state machine :130/:605, audio ring buffer + VAD goroutine :644-858,
utterance commit → pipeline models, events back over WS :542). This is the
commit-driven subset of that machine: explicit input_audio_buffer.commit (or
text conversation items) triggers the pipeline; server-VAD auto-commit mode
triggers on trailing silence after speech.

Event surface (OpenAI-realtime-shaped):
  client → server: session.update, conversation.item.create,
                   input_audio_buffer.append (b64 pcm16 @16 kHz),
                   input_audio_buffer.commit, response.create
  server → client: session.created, conversation.item.created,
                   input_audio_buffer.committed,
                   conversation.item.input_audio_transcription.completed,
                   response.text.delta, response.audio.delta (b64 wav pcm16),
                   response.done, error
"""
from __future__ import annotations

import asyncio
import base64
import json
import tempfile
import uuid

import numpy as np
from aiohttp import WSMsgType, web


class RealtimeSession:
    def __init__(self, api, cfg):
        self.api = api
        self.cfg = cfg                      # ModelConfig with .pipeline
        self.messages: list[dict] = []
        self.audio = bytearray()            # pcm16 @16 kHz
        self.session_id = f"sess_{uuid.uuid4().hex[:16]}"
        self.voice = "default"
        self.server_vad = False

    # ---------------------------------------------------------- pipeline ops

    async def _handle_for(self, name: str):
        mcfg = self.api.configs.get(name)
        if mcfg is None:
            if not name.startswith("default-"):
                raise ValueError(f"pipeline model {name!r} not found")
            from localai_tpu.config import ModelConfig

            mcfg = ModelConfig(name=name, backend=name.split("-", 1)[1])
        return await self.api._handle(mcfg)

    async def transcribe_buffer(self) -> str:
        name = self.cfg.pipeline.transcription
        if not name:
            return ""
        from localai_tpu.audio.pcm import i16_to_f32, write_wav

        pcm = np.frombuffer(bytes(self.audio), np.int16)
        handle = await self._handle_for(name)
        with tempfile.NamedTemporaryFile(suffix=".wav", delete=False) as t:
            path = t.name
        import os

        try:
            write_wav(path, i16_to_f32(pcm), 16000)
            r = await asyncio.to_thread(
                lambda: handle.client.transcribe(dst=path))
            return r.text
        finally:
            os.unlink(path)

    async def run_llm(self) -> str:
        name = self.cfg.pipeline.llm or self.cfg.name
        handle = await self._handle_for(name)
        mcfg = self.api.configs.get(name) or self.cfg
        opts = self.api._merged_options(mcfg, {})
        opts["messages_json"] = json.dumps(self.messages)
        opts["use_tokenizer_template"] = True
        reply = await asyncio.to_thread(
            lambda: handle.client.predict(**opts))
        return reply.message.decode("utf-8", "replace")

    async def run_tts(self, text: str) -> bytes:
        name = self.cfg.pipeline.tts
        if not name:
            return b""
        handle = await self._handle_for(name)
        with tempfile.NamedTemporaryFile(suffix=".wav", delete=False) as t:
            path = t.name
        import os

        try:
            await asyncio.to_thread(lambda: handle.client.tts(
                text=text, voice=self.voice, dst=path))
            with open(path, "rb") as f:
                return f.read()
        finally:
            os.unlink(path)

    def vad_has_utterance(self) -> bool:
        """Server-VAD: speech followed by >=300 ms of silence."""
        from localai_tpu.audio.pcm import i16_to_f32
        from localai_tpu.audio.vad import detect_segments

        pcm = i16_to_f32(np.frombuffer(bytes(self.audio), np.int16))
        if len(pcm) < 16000 // 2:
            return False
        segs = detect_segments(pcm)
        if not segs:
            return False
        return (len(pcm) / 16000.0 - segs[-1][1]) >= 0.3


async def realtime_handler(api, request: web.Request):
    name = request.query.get("model", "")
    cfg = api.configs.get(name) if name else api.configs.first()
    if cfg is None:
        raise web.HTTPNotFound(text="no model for realtime session")

    ws = web.WebSocketResponse()
    await ws.prepare(request)
    sess = RealtimeSession(api, cfg)

    async def send(obj):
        await ws.send_json(obj)

    await send({"type": "session.created",
                "session": {"id": sess.session_id, "model": cfg.name}})

    async def commit_and_respond():
        if sess.audio:
            await send({"type": "input_audio_buffer.committed"})
            text = await sess.transcribe_buffer()
            sess.audio.clear()
            if text:
                await send({
                    "type": "conversation.item.input_audio_transcription.completed",
                    "transcript": text})
                sess.messages.append({"role": "user", "content": text})
        await respond()

    async def respond():
        if not sess.messages:
            await send({"type": "error",
                        "error": {"message": "no conversation items"}})
            return
        text = await sess.run_llm()
        rid = f"resp_{uuid.uuid4().hex[:12]}"
        await send({"type": "response.text.delta", "response_id": rid,
                    "delta": text})
        sess.messages.append({"role": "assistant", "content": text})
        audio = await sess.run_tts(text)
        if audio:
            await send({"type": "response.audio.delta", "response_id": rid,
                        "delta": base64.b64encode(audio).decode()})
        await send({"type": "response.done", "response_id": rid})

    async for msg in ws:
        if msg.type != WSMsgType.TEXT:
            continue
        try:
            ev = json.loads(msg.data)
        except json.JSONDecodeError:
            await send({"type": "error",
                        "error": {"message": "invalid JSON"}})
            continue
        t = ev.get("type")
        try:
            if t == "session.update":
                s = ev.get("session", {})
                sess.voice = s.get("voice", sess.voice)
                td = s.get("turn_detection")
                sess.server_vad = bool(td and td.get("type") == "server_vad")
                await send({"type": "session.updated", "session": s})
            elif t == "conversation.item.create":
                item = ev.get("item", {})
                content = item.get("content", "")
                if isinstance(content, list):
                    content = "".join(p.get("text", "") for p in content)
                sess.messages.append({
                    "role": item.get("role", "user"), "content": content})
                await send({"type": "conversation.item.created"})
            elif t == "input_audio_buffer.append":
                sess.audio.extend(base64.b64decode(ev.get("audio", "")))
                if sess.server_vad and sess.vad_has_utterance():
                    await commit_and_respond()
            elif t == "input_audio_buffer.commit":
                await commit_and_respond()
            elif t == "response.create":
                await respond()
            else:
                await send({"type": "error",
                            "error": {"message": f"unknown event {t!r}"}})
        except Exception as e:
            await send({"type": "error",
                        "error": {"message": f"{type(e).__name__}: {e}"}})
    return ws
