"""OpenAI-compatible wire schema builders (reference structs:
/root/reference/core/schema/openai.go:40-133). Plain dicts — the contract is
JSON shape, not types."""
from __future__ import annotations

import time
import uuid


def _id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex[:24]}"


def merge_extra_usage(out: dict, enabled: bool, t_prompt_s: float,
                      t_gen_s: float) -> dict:
    """Reference Extra-Usage opt-in (chat.go:47-50,191; completion.go:74;
    edit.go:35): merge the in-band timings into `usage`, llama.cpp field
    names in milliseconds. The header predicate (non-empty `Extra-Usage`)
    lives at the endpoint layer — this is a pure body builder."""
    if enabled:
        out.setdefault("usage", {}).update({
            "timing_prompt_processing": (t_prompt_s or 0.0) * 1e3,
            "timing_token_generation": (t_gen_s or 0.0) * 1e3,
        })
    return out


def usage(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def chat_completion(model: str, text: str, finish_reason: str,
                    prompt_tokens: int, completion_tokens: int,
                    timings: dict | None = None,
                    tool_calls: list | None = None) -> dict:
    """OpenAI chat.completion body; with tool_calls the message carries the
    parsed calls and finish_reason becomes "tool_calls"
    (reference: core/http/endpoints/openai/chat.go:266-312)."""
    if tool_calls:
        message: dict = {"role": "assistant", "content": None,
                         "tool_calls": tool_calls}
        finish_reason = "tool_calls"
    else:
        message = {"role": "assistant", "content": text}
    out = {
        "id": _id("chatcmpl"),
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "message": message,
            "finish_reason": finish_reason or "stop",
        }],
        "usage": usage(prompt_tokens, completion_tokens),
    }
    if timings:
        out["timings"] = timings
    return out


def chat_chunk(rid: str, model: str, delta_text: str | None,
               finish_reason: str | None = None, role: bool = False,
               tool_calls: list | None = None) -> dict:
    delta: dict = {}
    if role:
        delta["role"] = "assistant"
    if delta_text:
        delta["content"] = delta_text
    if tool_calls:
        delta["tool_calls"] = [
            {**c, "index": i} for i, c in enumerate(tool_calls)
        ]
    return {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "delta": delta,
            "finish_reason": finish_reason,
        }],
    }


def chat_usage_chunk(rid: str, model: str, prompt_tokens: int,
                     completion_tokens: int) -> dict:
    return {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [],
        "usage": usage(prompt_tokens, completion_tokens),
    }


def text_completion(model: str, text: str, finish_reason: str,
                    prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "id": _id("cmpl"),
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "text": text,
            "finish_reason": finish_reason or "stop",
        }],
        "usage": usage(prompt_tokens, completion_tokens),
    }


def text_completion_chunk(rid: str, model: str, text: str,
                          finish_reason: str | None = None) -> dict:
    return {
        "id": rid,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{"index": 0, "text": text,
                     "finish_reason": finish_reason}],
    }


def embeddings_response(model: str, vectors: list[list[float]],
                        prompt_tokens: int) -> dict:
    return {
        "object": "list",
        "model": model,
        "data": [{"object": "embedding", "index": i, "embedding": v}
                 for i, v in enumerate(vectors)],
        "usage": usage(prompt_tokens, 0),
    }


def models_list(names: list[str]) -> dict:
    return {
        "object": "list",
        "data": [{"id": n, "object": "model", "owned_by": "localai-tpu"}
                 for n in names],
    }


def error_body(message: str, kind: str = "invalid_request_error",
               code: int = 400) -> dict:
    return {"error": {"message": message, "type": kind, "code": code}}
